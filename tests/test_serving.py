"""`det serve` — continuous batching, KV accounting, drain (docs/serving.md).

Fast tier-1 tests pin the batcher core contracts: admission/backpressure,
join-at-step-boundary + retire-without-drain ordering, KV block
reuse/free accounting, decode-vs-full-forward equivalence (the KV cache
produces bit-identical greedy generations), the ISSUE-6 acceptance burst
(>= 32 concurrent requests, batch occupancy > 1), drain semantics
(stop-admitting → finish in-flight, zero dropped), integrity-verified
checkpoint loading with lineage fallback, and the HTTP front-end's
status-code contract. The `-m slow` e2e drives a real devcluster:
submit → serve through the master proxy → spot-notice drain → replica
reschedule onto the survivor.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from determined_tpu import core
from determined_tpu.common import faultpoint
from determined_tpu.models import gpt2
from determined_tpu.serve import (
    AdmissionQueue,
    BlockManager,
    ContinuousBatcher,
    Draining,
    KVBlockError,
    QueueFull,
    Request,
    ServingEngine,
    load_checkpoint_params,
)
from determined_tpu.serve.scheduler import FAULT_POINT_DROP

# Tiny f32 config: CPU-fast, and float32 keeps the cached-decode vs
# full-forward argmax comparison exact (bf16 rounding could flip ties).
TINY = gpt2.Config(
    vocab_size=128, n_positions=64, d_model=32, n_layer=2, n_head=2,
    dtype=jnp.float32, remat=False, attention_impl="dot",
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faultpoint.disarm_all()
    yield
    faultpoint.disarm_all()


@pytest.fixture(scope="module")
def tiny_params():
    return gpt2.init(jax.random.PRNGKey(0), TINY)


def make_engine(params, slots=4, max_seq=32, buckets=(8, 16, 32)):
    return ServingEngine(params, TINY, slots=slots, max_seq_len=max_seq,
                         prefill_buckets=list(buckets))


def reference_greedy(params, prompt, n):
    """Full-forward greedy generation — the ground truth the KV-cached
    path must reproduce exactly."""
    ctx = [int(t) for t in prompt]
    out = []
    for _ in range(n):
        logits = gpt2.apply(params, jnp.asarray([ctx], jnp.int32), TINY)
        tok = int(jnp.argmax(logits[0, -1]))
        out.append(tok)
        ctx.append(tok)
    return out


def make_batcher(engine, queue_size=64, num_blocks=None, block_size=8):
    blocks = BlockManager(
        num_blocks=num_blocks if num_blocks is not None
        else engine.slots * (engine.max_seq_len // block_size),
        block_size=block_size)
    return ContinuousBatcher(
        engine, queue=AdmissionQueue(queue_size), block_manager=blocks,
        idle_wait_s=0.005)


# ---------------------------------------------------------------------------
# KV block manager: allocation, reuse/free accounting, failure modes.
# ---------------------------------------------------------------------------


def test_block_manager_allocate_free_roundtrip():
    bm = BlockManager(num_blocks=8, block_size=4)
    assert bm.blocks_for_tokens(1) == 1
    assert bm.blocks_for_tokens(4) == 1
    assert bm.blocks_for_tokens(5) == 2
    blocks = bm.allocate("a", 10)  # 3 blocks
    assert len(blocks) == 3 and bm.free_blocks == 5 and bm.used_blocks == 3
    assert bm.free("a") == 3
    assert bm.free_blocks == 8
    assert bm.stats()["total_allocated"] == 3
    assert bm.stats()["total_freed"] == 3


def test_block_manager_exhaustion_is_backpressure_not_error():
    bm = BlockManager(num_blocks=4, block_size=4)
    assert bm.allocate("a", 16) is not None  # all 4 blocks
    assert not bm.can_allocate(1)
    assert bm.allocate("b", 1) is None       # exhausted: None, no raise
    bm.free("a")
    assert bm.allocate("b", 1) is not None   # freed capacity admits it


def test_block_manager_reuse_accounting():
    bm = BlockManager(num_blocks=4, block_size=4)
    bm.allocate("a", 8)
    bm.free("a")
    bm.allocate("b", 8)  # reuses a's two blocks
    assert bm.stats()["total_reused"] == 2


def test_block_manager_extend():
    bm = BlockManager(num_blocks=3, block_size=4)
    bm.allocate("a", 4)
    assert bm.extend("a", 8) is True    # +1 block
    assert bm.extend("a", 8) is True    # already covered: no-op
    assert bm.extend("a", 100) is False  # pool can't cover
    assert bm.free("a") == 2


def test_block_manager_misuse_raises():
    bm = BlockManager(num_blocks=4, block_size=4)
    bm.allocate("a", 4)
    with pytest.raises(KVBlockError):
        bm.allocate("a", 4)  # double allocate
    bm.free("a")
    with pytest.raises(KVBlockError):
        bm.free("a")         # double free
    with pytest.raises(KVBlockError):
        bm.extend("ghost", 4)


# ---------------------------------------------------------------------------
# Admission queue: bounded backpressure, drain, chaos.
# ---------------------------------------------------------------------------


def _req(n_prompt=4, max_new=4, **kw):
    return Request(np.arange(1, 1 + n_prompt, dtype=np.int32),
                   max_new_tokens=max_new, **kw)


def test_queue_backpressure():
    q = AdmissionQueue(maxsize=2)
    q.submit(_req())
    q.submit(_req())
    with pytest.raises(QueueFull):
        q.submit(_req())
    assert q.rejected_full == 1 and q.depth() == 2
    q.pop()
    q.submit(_req())  # capacity freed → admits again


def test_queue_drain_stops_admissions():
    q = AdmissionQueue(maxsize=4)
    q.submit(_req())
    q.drain()
    with pytest.raises(Draining):
        q.submit(_req())
    assert q.rejected_draining == 1
    assert q.depth() == 1  # accepted work stays queued
    q.undrain()
    q.submit(_req())


def test_queue_fault_point_drop_and_error():
    q = AdmissionQueue(maxsize=4)
    faultpoint.arm(FAULT_POINT_DROP, "drop", count=1)
    with pytest.raises(QueueFull, match="shed"):
        q.submit(_req())
    assert q.dropped == 1
    faultpoint.arm(FAULT_POINT_DROP, "error", count=1)
    with pytest.raises(faultpoint.FaultInjected):
        q.submit(_req())
    q.submit(_req())  # disarmed again: admits


# ---------------------------------------------------------------------------
# Engine: KV-cached decode == full forward; buckets.
# ---------------------------------------------------------------------------


def test_cached_decode_matches_full_forward(tiny_params):
    eng = make_engine(tiny_params, slots=2)
    eng.compile()
    prompt = np.array([5, 9, 17, 3], np.int32)
    first = eng.prefill_request(0, prompt)
    out = [first]
    tokens = np.zeros(2, np.int32)
    positions = np.zeros(2, np.int32)
    temps = np.zeros(2, np.float32)
    pos, last = len(prompt), first
    for _ in range(7):
        tokens[0], positions[0] = last, pos
        last = int(eng.decode(tokens, positions, temps)[0])
        out.append(last)
        pos += 1
    assert out == reference_greedy(tiny_params, prompt, 8)


def test_engine_warm_aot_deserializes_on_second_boot(tiny_params, tmp_path):
    """The scale-to-zero cold-start contract (docs/serving.md "Scale to
    zero"): the FIRST engine for a serving signature traces and saves its
    executables into the node-local AOT dir; the SECOND engine with the
    same signature deserializes every piece (aot_source "deserialize",
    never a re-trace) and generates identically."""
    from determined_tpu.compile.runtime import FarmClient

    sig = "serve-warmaot-test"
    aot_dir = str(tmp_path / "aot")

    import os as _os

    def boot():
        """One replica boot: engine + farm + batcher (the batcher syncs
        block geometry, then compiles through the farm — exactly the
        serve task's startup order)."""
        eng = make_engine(tiny_params, slots=2, max_seq=16,
                          buckets=(8, 16))
        eng.farm = FarmClient(session=None, signature=sig,
                              aot_dir=aot_dir)
        b = make_batcher(eng, block_size=8)
        b.start()
        return eng, b

    cold, b1 = boot()
    try:
        assert cold.aot_source == "trace"
        assert cold.compile_stats["aot_misses"] > 0
        # Artifacts landed locally (decode, prefill buckets, sampler,
        # CoW block copy).
        saved = _os.listdir(_os.path.join(aot_dir, sig))
        assert any(n.startswith("aot-decode") for n in saved), saved

        req = b1.submit(Request(np.asarray([5, 9, 17], np.int32),
                                max_new_tokens=4))
        req.result(timeout=60)
        want = reference_greedy(tiny_params, [5, 9, 17], 4)
        assert list(req.out_tokens) == want
    finally:
        b1.stop()

    warm, b2 = boot()
    try:
        assert warm.aot_source == "deserialize", warm.compile_stats
        assert warm.compile_stats["aot_misses"] == 0
        assert warm.compile_stats["decode_source"] == "deserialize"
        # Warm executables behave identically.
        req = b2.submit(Request(np.asarray([5, 9, 17], np.int32),
                                max_new_tokens=4))
        req.result(timeout=60)
        assert list(req.out_tokens) == want
    finally:
        b2.stop()


def test_serving_signature_stable_and_shape_sensitive():
    """Same serving config -> same signature (replicas share artifacts);
    any shape-affecting knob change -> a different signature (a respawn
    can never load a stale executable)."""
    from determined_tpu.serve.task import serving_signature

    base = {"model": "gpt2", "model_config": {"model_size": "tiny"},
            "max_batch_size": 4, "max_seq_len": 64, "kv_block_size": 16}
    assert serving_signature(dict(base)) == serving_signature(dict(base))
    changed = dict(base, max_seq_len=128)
    assert serving_signature(changed) != serving_signature(base)
    # Non-shape knobs (ports, sampling) don't fragment the cache.
    assert serving_signature(dict(base, port=9999)) == \
        serving_signature(base)


def test_bucket_selection(tiny_params):
    eng = make_engine(tiny_params, buckets=(8, 16, 32))
    assert eng.bucket_for(1) == 8
    assert eng.bucket_for(8) == 8
    assert eng.bucket_for(9) == 16
    assert eng.bucket_for(32) == 32
    assert eng.bucket_for(33) is None


def test_engine_compiles_every_bucket_aot(tiny_params):
    eng = make_engine(tiny_params, buckets=(8, 16))
    stats = eng.compile()
    assert set(eng._compiled_prefill) == {8, 16}
    assert stats["decode_s"] > 0 and "total_s" in stats


# ---------------------------------------------------------------------------
# Continuous batcher: the ISSUE-6 acceptance contracts.
# ---------------------------------------------------------------------------


def test_burst_completes_with_occupancy_above_one(tiny_params):
    """Acceptance: a burst of >= 32 concurrent requests completes with
    batch occupancy > 1, and every result is the exact full-forward
    greedy generation (continuous batching changes scheduling, never
    content)."""
    eng = make_engine(tiny_params, slots=4)
    b = make_batcher(eng).start()
    try:
        rng = np.random.default_rng(0)
        reqs = [
            b.submit(Request(
                rng.integers(1, 100, size=int(rng.integers(2, 7))),
                max_new_tokens=int(rng.integers(3, 10))))
            for _ in range(32)
        ]
        results = [r.result(timeout=120) for r in reqs]
        stats = b.stats()
        assert stats["completed"] == 32
        assert stats["mean_occupancy"] > 1.0, stats
        assert stats["max_occupancy"] > 1
        # Spot-check content against the reference (first + last).
        for req, res in [(reqs[0], results[0]), (reqs[-1], results[-1])]:
            assert res["tokens"] == reference_greedy(
                tiny_params, req.tokens, req.max_new_tokens)
    finally:
        b.stop()


def test_join_at_boundary_retire_without_drain(tiny_params):
    """With 2 slots and 3 requests, the 3rd joins at the step boundary
    where the 1st retires, while the 2nd keeps decoding — the batch
    NEVER drains to refill."""
    eng = make_engine(tiny_params, slots=2)
    b = make_batcher(eng).start()
    try:
        r1 = b.submit(_req(n_prompt=3, max_new=2))
        r2 = b.submit(_req(n_prompt=3, max_new=12))
        r3 = b.submit(_req(n_prompt=3, max_new=2))
        for r in (r1, r2, r3):
            r.result(timeout=60)
        ev = {(kind, rid): step for kind, rid, step in b.events}
        # r1 and r2 joined before r3 (only 2 slots).
        assert ev[("admit", r3.id)] >= ev[("retire", r1.id)]
        # retire-without-drain: r2 was still mid-decode when r3 joined —
        # its retirement happened strictly after r3's admission.
        assert ev[("retire", r2.id)] > ev[("admit", r3.id)]
    finally:
        b.stop()


def test_kv_blocks_gate_admission(tiny_params):
    """Block exhaustion keeps requests queued (occupancy 1) until a
    retire frees capacity — backpressure, not failure."""
    eng = make_engine(tiny_params, slots=4)
    # Pool covers exactly one worst-case sequence at a time.
    b = make_batcher(eng, num_blocks=1, block_size=16)
    b.start()
    try:
        reqs = [b.submit(_req(n_prompt=4, max_new=6)) for _ in range(3)]
        for r in reqs:
            r.result(timeout=60)
        stats = b.stats()
        assert stats["completed"] == 3
        assert stats["max_occupancy"] == 1, (
            "block pool for one sequence must serialize the batch")
    finally:
        b.stop()


def test_kv_accounting_balances_after_load(tiny_params):
    eng = make_engine(tiny_params, slots=4)
    b = make_batcher(eng).start()
    try:
        reqs = [b.submit(_req(n_prompt=5, max_new=5)) for _ in range(12)]
        for r in reqs:
            r.result(timeout=60)
        kv = b.stats()["kv_blocks"]
        assert kv["used_blocks"] == 0
        assert kv["free_blocks"] == kv["num_blocks"]
        assert kv["total_freed"] == kv["total_allocated"] > 0
        assert kv["total_reused"] > 0  # retired blocks cycled back in
    finally:
        b.stop()


def test_drain_finishes_accepted_work_zero_dropped(tiny_params):
    """Drain contract: stop admitting (Draining), but every accepted
    request — queued or in-flight — completes successfully."""
    eng = make_engine(tiny_params, slots=2)
    b = make_batcher(eng).start()
    try:
        reqs = [b.submit(_req(n_prompt=4, max_new=10)) for _ in range(6)]
        assert b.drain(timeout=None) in (True, False)  # signal only
        with pytest.raises(Draining):
            b.submit(_req())
        assert b.drain(timeout=60) is True
        results = [r.result(timeout=5) for r in reqs]  # none dropped
        assert all(len(res["tokens"]) == 10 for res in results)
        stats = b.stats()
        assert stats["completed"] == 6 and stats["failed"] == 0
        assert stats["rejected_draining"] == 1
    finally:
        b.stop()


def test_submit_validates_against_engine_limits(tiny_params):
    eng = make_engine(tiny_params, slots=2, max_seq=32, buckets=(8, 16))
    b = make_batcher(eng)
    with pytest.raises(ValueError, match="prefill bucket"):
        b.submit(_req(n_prompt=20))  # no bucket covers 20
    with pytest.raises(ValueError, match="max_seq_len"):
        b.submit(_req(n_prompt=16, max_new=20))  # 36 > 32 budget


def test_batcher_stop_fails_outstanding(tiny_params):
    eng = make_engine(tiny_params, slots=2)
    b = make_batcher(eng).start()
    r = b.submit(_req(n_prompt=4, max_new=28))
    b.stop()
    with pytest.raises((RuntimeError, TimeoutError)):
        r.result(timeout=5)


# ---------------------------------------------------------------------------
# Paged KV: attention-impl equivalence (ISSUE-11 acceptance) — greedy decode
# through the paged path (Pallas kernel in interpret mode AND the jnp
# reference gather) must match dense-cache decode and full-forward
# gpt2.apply exactly, in f32.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl", ["reference", "pallas", "dense"])
def test_attention_impl_greedy_equivalence(tiny_params, impl):
    eng = ServingEngine(tiny_params, TINY, slots=2, max_seq_len=32,
                        prefill_buckets=[8, 16, 32], attention_impl=impl)
    eng.compile()
    prompt = np.array([5, 9, 17, 3], np.int32)
    first = eng.prefill_request(0, prompt)
    out = [first]
    tokens = np.zeros(2, np.int32)
    positions = np.zeros(2, np.int32)
    temps = np.zeros(2, np.float32)
    pos, last = len(prompt), first
    for _ in range(7):
        tokens[0], positions[0] = last, pos
        last = int(eng.decode(tokens, positions, temps)[0])
        out.append(last)
        pos += 1
    assert out == reference_greedy(tiny_params, prompt, 8)


def test_paged_reference_bitwise_matches_dense_decode(tiny_params):
    """The jnp gather path does the *same arithmetic* as the dense lane:
    with block_size dividing max_seq the gathered lane has identical shape
    and element order, so the decode logits are bit-identical, not merely
    argmax-identical."""
    import jax.numpy as jnp

    from determined_tpu.serve import model as smodel

    prompt = np.array([5, 9, 17, 3], np.int32)
    # Dense: prefill + one decode, capture logits.
    dcache = smodel.init_cache(TINY, 1, 32)
    dcache, dlog = smodel.prefill(
        tiny_params, dcache, jnp.asarray(prompt), jnp.int32(4),
        jnp.int32(0), TINY)
    tok = jnp.argmax(dlog).astype(jnp.int32)
    dcache, dstep = smodel.decode_step(
        tiny_params, dcache, tok[None], jnp.asarray([4], jnp.int32), TINY)
    # Paged reference: same prompt through the paged layout (bs=8 -> 4
    # blocks tile max_seq 32 exactly).
    pcache = smodel.init_paged_cache(TINY, 5, 8)  # 4 blocks + trash
    table = jnp.asarray([0, 1, 2, 3], jnp.int32)
    pcache, plog = smodel.paged_prefill(
        tiny_params, pcache, jnp.asarray(prompt), jnp.int32(4),
        jnp.int32(0), table, TINY)
    pcache, pstep = smodel.paged_decode_step(
        tiny_params, pcache, tok[None], jnp.asarray([4], jnp.int32),
        table[None], TINY, attention_impl="reference")
    assert np.array_equal(np.asarray(dstep), np.asarray(pstep))


def test_paged_attention_pallas_matches_reference(tiny_params):
    """Unit-level: the Pallas kernel (interpret mode on CPU) and the jnp
    gather agree numerically on a random paged pool, including partially
    filled blocks and an inactive (trash-table) slot."""
    import jax.numpy as jnp

    from determined_tpu.ops.paged_attention import (
        paged_attention_pallas, paged_attention_reference)

    rng = np.random.default_rng(7)
    slots, mb, bs, nh, dh = 3, 4, 8, 2, 16
    pool_blocks = slots * mb + 1
    q = jnp.asarray(rng.normal(size=(slots, nh, dh)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(pool_blocks, bs, nh, dh)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(pool_blocks, bs, nh, dh)), jnp.float32)
    tbl = np.arange(slots * mb).reshape(slots, mb).astype(np.int32)
    tbl[2] = slots * mb  # inactive slot: all-trash table
    tbl = jnp.asarray(tbl)
    pos = jnp.asarray([5, 17, 0], jnp.int32)
    ref = paged_attention_reference(q, kp, vp, tbl, pos)
    out = paged_attention_pallas(q, kp, vp, tbl, pos, interpret=True)
    np.testing.assert_allclose(np.asarray(out[:2]), np.asarray(ref[:2]),
                               atol=1e-5)


# ---------------------------------------------------------------------------
# BlockManager sharing semantics: refcounts, prefix reuse, CoW, eviction.
# ---------------------------------------------------------------------------


def test_prefix_blocks_shared_and_survive_one_sharer(tiny_params):
    bm = BlockManager(num_blocks=16, block_size=4)
    prompt = list(range(1, 9))  # 2 full blocks
    ta, ca, cowa = bm.admit("a", prompt, 12)
    assert ca == 0 and cowa == [] and len(ta) == 3
    tb, cb, cowb = bm.admit("b", prompt + [99], 12)  # same 8-token prefix
    assert cb == 8  # both full blocks reused
    assert tb[:2] == ta[:2] and cowb == []
    assert bm.ref_count(ta[0]) == 2
    # a retires: the shared blocks survive for b.
    bm.free("a")
    assert bm.ref_count(ta[0]) == 1
    # b retires: prompt blocks park in the prefix cache, still reusable.
    bm.free("b")
    assert bm.ref_count(ta[0]) == 0
    assert bm.cached_blocks >= 2
    tc, cc, cowc = bm.admit("c", prompt + [7], 12)
    assert cc == 8 and tc[:2] == ta[:2]
    bm.free("c")


def test_full_prompt_hit_copies_on_write_while_shared(tiny_params):
    bm = BlockManager(num_blocks=16, block_size=4)
    prompt = list(range(1, 9))  # exactly 2 full blocks
    ta, _, _ = bm.admit("a", prompt, 10)
    # b's prompt IS the cached prefix: the last token must be recomputed,
    # which writes into a's still-referenced final block -> private copy.
    tb, cb, cowb = bm.admit("b", prompt, 10)
    assert cb == 7  # len(prompt) - 1: one novel query for the logits
    assert cowb == [(ta[1], tb[1])]
    assert tb[0] == ta[0] and tb[1] != ta[1]
    assert bm.ref_count(ta[0]) == 2 and bm.ref_count(ta[1]) == 1
    bm.free("a")
    bm.free("b")
    # With no live sharer the parked copy is exclusively pinned: no CoW.
    tc, cc, cowc = bm.admit("c", prompt, 10)
    assert cc == 7 and cowc == []
    bm.free("c")
    assert bm.stats()["cow_copies"] == 1


def test_block_accounting_exact_under_interleaved_admit_retire():
    bm = BlockManager(num_blocks=12, block_size=4)
    prompt = list(range(1, 9))  # 2 full blocks

    def invariant():
        s = bm.stats()
        assert s["free_blocks"] + s["used_blocks"] == s["num_blocks"]
        return s

    ta, _, _ = bm.admit("a", prompt, 16)           # 4 blocks, 0 shared
    assert invariant()["used_blocks"] == 4
    tb, cb, _ = bm.admit("b", prompt + [9], 16)    # shares 2, charges 2
    assert cb == 8
    assert invariant()["used_blocks"] == 6     # 4 + 2 novel
    bm.free("a")
    # b still references the 2 shared blocks; only a's 2 private freed.
    assert invariant()["used_blocks"] == 4
    tc, cc, _ = bm.admit("c", [1, 2, 3], 4)    # 1 block, no full-block hit
    assert cc == 0
    assert invariant()["used_blocks"] == 5
    bm.free("b")
    bm.free("c")
    s = invariant()
    assert s["used_blocks"] == 0
    assert s["free_blocks"] == s["num_blocks"]
    assert s["total_freed"] == s["total_allocated"] > 0


def test_prefix_cache_eviction_under_pressure():
    bm = BlockManager(num_blocks=4, block_size=4)
    bm.admit("a", list(range(1, 9)), 8)   # 2 hashed blocks
    bm.free("a")                          # -> cached (evictable)
    assert bm.cached_blocks == 2
    # A non-matching allocation needs the space: cached LRU is evicted.
    tb = bm.allocate("b", 16)             # all 4 blocks
    assert tb is not None and bm.cached_blocks == 0
    assert bm.stats()["cached_evictions"] == 2
    bm.free("b")
    # The evicted prefix no longer matches.
    _, cached_len, _ = bm.admit("c", list(range(1, 9)), 8)
    assert cached_len == 0


def test_admit_misuse_raises():
    bm = BlockManager(num_blocks=8, block_size=4)
    bm.admit("a", [1, 2, 3], 4)
    with pytest.raises(KVBlockError):
        bm.admit("a", [1, 2, 3], 4)       # double admit
    with pytest.raises(KVBlockError):
        bm.admit("x", [], 4)              # empty prompt
    with pytest.raises(KVBlockError):
        bm.admit("y", [1, 2, 3], 2)       # budget below prompt
    bm.free("a")
    with pytest.raises(KVBlockError):
        bm.free("a")                      # double free


def test_prefix_cache_disabled_never_shares():
    bm = BlockManager(num_blocks=8, block_size=4, prefix_cache=False)
    ta, ca, _ = bm.admit("a", list(range(1, 9)), 8)
    tb, cb, _ = bm.admit("b", list(range(1, 9)), 8)
    assert ca == cb == 0
    assert not set(ta) & set(tb)
    bm.free("a")
    bm.free("b")
    assert bm.cached_blocks == 0


# ---------------------------------------------------------------------------
# Engine-level prefix caching: shared prompts admit at suffix-only cost
# and still generate exactly the reference tokens.
# ---------------------------------------------------------------------------


def test_shared_prefix_admits_at_suffix_cost(tiny_params):
    """Two requests sharing a 75% prefix: after the first, the second is
    charged only its novel suffix's prompt blocks (~25%) — and both
    generate exactly the full-forward reference tokens."""
    eng = make_engine(tiny_params, slots=4, max_seq=32, buckets=(8, 16, 32))
    b = make_batcher(eng, block_size=8)  # 32/8 = 4 blocks per sequence
    b.start()
    try:
        shared = list(np.arange(1, 25))          # 24 tokens = 3 full blocks
        p1 = np.asarray(shared + [30, 31], np.int32)      # 26-token prompt
        p2 = np.asarray(shared + [40, 41], np.int32)      # same 24 prefix
        r1 = b.submit(Request(p1, max_new_tokens=4))
        r1.result(timeout=60)
        alloc_after_r1 = b.blocks.total_allocated
        r2 = b.submit(Request(p2, max_new_tokens=4))
        r2.result(timeout=60)
        charged = b.blocks.total_allocated - alloc_after_r1
        # r2's budget is 30 tokens = 4 blocks; 3 were served from cache.
        assert charged == 1, b.blocks.stats()
        kv = b.blocks.stats()
        assert kv["prefix_hit_tokens"] == 24
        assert kv["prefix_hits"] == 1
        assert 0 < kv["prefix_cache_hit_rate"] < 1
        # Prefix reuse changes cost, never content.
        assert r1.out_tokens == reference_greedy(tiny_params, p1, 4)
        assert r2.out_tokens == reference_greedy(tiny_params, p2, 4)
    finally:
        b.stop()


def test_identical_prompt_full_hit_still_exact(tiny_params):
    """A 100% prompt hit (the CoW path end to end, device copy included)
    still produces the exact reference generation."""
    eng = make_engine(tiny_params, slots=2, max_seq=32, buckets=(8, 16, 32))
    b = make_batcher(eng, block_size=8)
    try:
        prompt = np.asarray(np.arange(1, 17), np.int32)  # 2 full blocks
        # Submit BOTH before starting: they admit at the same boundary,
        # so r2's full-prompt hit lands while r1 still references its
        # final block — the deterministic CoW case.
        r1 = b.submit(Request(prompt, max_new_tokens=5))
        r2 = b.submit(Request(prompt, max_new_tokens=5))
        b.start()
        r1.result(timeout=60)
        r2.result(timeout=60)
        ref = reference_greedy(tiny_params, prompt, 5)
        assert r1.out_tokens == ref and r2.out_tokens == ref
        assert b.blocks.stats()["cow_copies"] == 1
        assert eng.block_copies == 1
    finally:
        b.stop()


def test_heartbeat_and_stats_carry_paging_fields(tiny_params):
    eng = make_engine(tiny_params, slots=2)
    b = make_batcher(eng)
    hb = b.heartbeat_stats()
    for key in ("kv_blocks_used", "kv_blocks_free", "kv_blocks_total",
                "prefix_cache_hit_rate"):
        assert key in hb, hb
    from determined_tpu.serve.http import prometheus_exposition

    text = prometheus_exposition(b.stats())
    assert "det_serve_kv_blocks_used" in text
    assert "det_serve_prefix_cache_hit_rate" in text
    est = eng.stats()
    assert est["kv_layout"] == "paged"
    assert est["cache_hbm_bytes"] > 0


# ---------------------------------------------------------------------------
# Checkpoint loading: COMPLETED-verified, lineage fallback.
# ---------------------------------------------------------------------------


def _save_checkpoint(tmp_path, params, steps, extra_poison=None):
    ctx = core.init(max_length=steps,
                    checkpoint_dir=str(tmp_path / "ckpts"))
    state = {"step": jnp.asarray(steps, jnp.int32), "params": params,
             "opt_state": {"count": jnp.zeros((), jnp.int32)}}
    sid = ctx.checkpoint.save_state(state, steps)
    ctx.checkpoint.wait()
    ctx.close()
    return ctx, sid


def test_load_checkpoint_params_roundtrip(tmp_path, tiny_params):
    ctx, sid = _save_checkpoint(tmp_path, tiny_params, 2)
    loaded = load_checkpoint_params(ctx.checkpoint, sid)
    flat_a = jax.tree_util.tree_leaves(tiny_params)
    flat_b = jax.tree_util.tree_leaves(loaded)
    assert len(flat_a) == len(flat_b)
    assert all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(flat_a, flat_b))


def test_load_checkpoint_latest_resolves_lineage(tmp_path, tiny_params):
    _save_checkpoint(tmp_path, tiny_params, 2)
    ctx, _ = _save_checkpoint(tmp_path, tiny_params, 4)
    loaded = load_checkpoint_params(ctx.checkpoint, "latest")
    assert loaded is not None


def test_load_checkpoint_corrupt_falls_back_through_lineage(
        tmp_path, tiny_params):
    """A torn latest checkpoint must never be served: verification fails
    and the previous COMPLETED checkpoint loads instead."""
    _save_checkpoint(tmp_path, tiny_params, 2)
    ctx, sid4 = _save_checkpoint(tmp_path, tiny_params, 4)
    path4 = ctx.checkpoint._storage.path_for(sid4)
    victim = None
    for root, _, files in os.walk(os.path.join(path4, "state")):
        for f in files:
            victim = os.path.join(root, f)
    with open(victim, "r+b") as f:
        f.truncate(max(0, os.path.getsize(victim) // 2))
    loaded = load_checkpoint_params(ctx.checkpoint, sid4)
    assert loaded is not None  # fell back to trial0-step2


def test_load_checkpoint_nothing_completed_raises(tmp_path):
    ctx = core.init(max_length=2, checkpoint_dir=str(tmp_path / "ckpts"))
    with pytest.raises(FileNotFoundError):
        load_checkpoint_params(ctx.checkpoint, "latest")
    ctx.close()


# ---------------------------------------------------------------------------
# HTTP front-end: the status-code contract load balancers act on.
# ---------------------------------------------------------------------------


def _http(method, url, body=None, timeout=30.0):
    req = urllib.request.Request(
        url, method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


@pytest.fixture()
def http_replica(tiny_params):
    from determined_tpu.serve.http import ServingServer

    eng = make_engine(tiny_params, slots=2)
    b = make_batcher(eng).start()
    server = ServingServer(b, host="127.0.0.1", port=0).start()
    yield f"http://127.0.0.1:{server.port}", b
    server.stop()
    b.stop()


def test_http_generate_stats_health(http_replica, tiny_params):
    url, _ = http_replica
    status, body = _http("POST", url + "/v1/generate",
                         {"tokens": [5, 9, 17, 3], "max_new_tokens": 6})
    assert status == 200
    assert body["tokens"] == reference_greedy(
        tiny_params, [5, 9, 17, 3], 6)
    assert body["latency_ms"] >= body["queue_ms"] >= 0
    status, stats = _http("GET", url + "/v1/stats")
    assert status == 200 and stats["completed"] >= 1
    assert stats["engine"]["prefill_buckets"]
    status, health = _http("GET", url + "/healthz")
    assert (status, health["status"]) == (200, "ok")


def test_http_error_codes(http_replica):
    url, batcher = http_replica
    status, body = _http("POST", url + "/v1/generate", {"tokens": []})
    assert status == 400
    status, body = _http("POST", url + "/v1/generate",
                         {"tokens": list(range(1, 30))})  # no bucket
    assert status == 400
    batcher.queue.drain()
    status, body = _http("POST", url + "/v1/generate",
                         {"tokens": [1, 2], "max_new_tokens": 2})
    assert status == 503
    status, health = _http("GET", url + "/healthz")
    assert health["status"] == "draining"
    batcher.queue.undrain()
    status, _ = _http("POST", url + "/v1/generate",
                      {"tokens": [1, 2], "max_new_tokens": 2})
    assert status == 200


def test_429_carries_computed_retry_after(tiny_params):
    """A QueueFull 429 carries a Retry-After computed from queue depth ×
    the smoothed service time — a hint the harness Session (and the
    deployment router, which propagates the header) can act on."""
    import urllib.error
    import urllib.request

    from determined_tpu.serve.http import ServingServer

    eng = make_engine(tiny_params, slots=2)
    b = make_batcher(eng, queue_size=1)
    # No start(): with the batcher thread parked, the queue fills and the
    # second submit 429s deterministically.
    eng.compile()
    server = ServingServer(b, host="127.0.0.1", port=0).start()
    try:
        url = f"http://127.0.0.1:{server.port}"
        body = {"tokens": [1, 2], "max_new_tokens": 2, "timeout_s": 0.1}
        req = urllib.request.Request(
            url + "/v1/generate", method="POST",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=10)  # fills the queue(504)
        except urllib.error.HTTPError:
            pass
        try:
            urllib.request.urlopen(req, timeout=10)
            raise AssertionError("expected 429")
        except urllib.error.HTTPError as e:
            assert e.code == 429
            assert int(e.headers["Retry-After"]) >= 1
    finally:
        server.stop()
        b.stop()


def test_retry_after_hint_scales_with_backlog(tiny_params):
    eng = make_engine(tiny_params, slots=2)
    b = make_batcher(eng, queue_size=64)
    assert b.retry_after_hint() == 1  # no history, empty queue
    # Synthetic history: 4s per request over 2 slots; 8 queued → ~16s.
    b._service_s_ewma = 4.0
    for _ in range(8):
        b.queue.submit(_req())
    assert b.retry_after_hint() == 16
    assert b.heartbeat_stats()["retry_after_hint_s"] == 16
    hb = b.heartbeat_stats()
    assert hb["queue_depth"] == 8 and hb["queue_capacity"] == 64
    assert hb["slots"] == 2 and hb["draining"] is False
    # The hint is clamped: a pathological backlog still answers <= 60.
    b._service_s_ewma = 1000.0
    assert b.retry_after_hint() == 60


# ---------------------------------------------------------------------------
# Request-path observability: latency histograms, phase stamps, request
# tracer + the serving.trace.drop contract (ISSUE 12; docs/serving.md
# "Request latency & SLOs", docs/observability.md "Request spans").
# ---------------------------------------------------------------------------


def test_latency_hist_percentiles():
    from determined_tpu.serve.scheduler import LatencyHist

    h = LatencyHist(buckets=(0.01, 0.1, 1.0))
    assert h.percentile(0.5) == 0.0  # empty
    for _ in range(99):
        h.observe(0.05)
    h.observe(0.5)
    assert h.count == 100 and 0.01 < h.percentile(0.5) <= 0.1
    assert 0.1 < h.percentile(0.995) <= 1.0
    # Over the top bucket: the estimate clamps to the last boundary.
    h2 = LatencyHist(buckets=(0.01,))
    h2.observe(5.0)
    assert h2.percentile(0.99) == 0.01
    wire = h.to_wire()
    assert wire["count"] == 100 and len(wire["le"]) == len(wire["counts"])
    # Cumulative counts are monotonic (Prometheus le semantics).
    assert wire["counts"] == sorted(wire["counts"])
    s = h.summary()
    assert s["p99_ms"] >= s["p50_ms"] > 0


def test_request_phase_stamps_and_histograms(tiny_params):
    eng = make_engine(tiny_params, slots=2)
    b = make_batcher(eng).start()
    try:
        reqs = [b.submit(_req(max_new=4, request_id=f"phase-{i}"))
                for i in range(4)]
        results = [r.result(timeout=120) for r in reqs]
        for r, res in zip(reqs, results):
            # submit ≤ admit ≤ prefill end = first token ≤ finish, all on
            # the wall-clock span timeline.
            assert (r.submitted_us <= r.admitted_us <= r.prefill_end_us
                    == r.first_token_us <= r.finished_us)
            assert r.decode_steps == 3  # 4 new tokens = prefill + 3 steps
            assert res["ttft_ms"] >= 0 and res["tpot_ms"] >= 0
            assert res["latency_ms"] >= res["ttft_ms"] >= res["queue_ms"]
        # One observation per retired request in every histogram.
        hb = b.heartbeat_stats()["latency"]
        for key in ("ttft", "tpot", "e2e", "queue_wait"):
            assert hb[key]["count"] == 4, (key, hb[key])
        lat = b.stats()["latency"]
        assert lat["e2e"]["p50_ms"] >= lat["ttft"]["p50_ms"] > 0
    finally:
        b.stop()


def test_request_tracer_span_tree(tiny_params):
    from determined_tpu.serve.tracing import RequestTracer

    eng = make_engine(tiny_params, slots=2)
    b = make_batcher(eng)
    tracer = RequestTracer(None, "", sample=1.0)
    b.tracer = tracer
    b.start()
    try:
        b.submit(_req(n_prompt=4, max_new=4,
                      request_id="tree-1")).result(timeout=120)
        tracer.flush()
        spans = [s for s in tracer.local_spans
                 if s["trace_id"] == "tree-1"]
        by_name = {s["name"]: s for s in spans}
        assert set(by_name) == {"serve.request", "serve.queue_wait",
                                "serve.prefill", "serve.decode"}
        root = by_name["serve.request"]
        assert root["span_id"] == "tree-1" and root["parent"] == ""
        assert root["attrs"] == {"prompt_tokens": 4, "new_tokens": 4}
        for name in ("serve.queue_wait", "serve.prefill", "serve.decode"):
            assert by_name[name]["parent"] == "tree-1"
        pf = by_name["serve.prefill"]["attrs"]
        assert pf["suffix_len"] == 4 and pf["prefix_cache_hit"] is False
        assert pf["bucket"] >= 4 and pf["blocks"] >= 1
        dec = by_name["serve.decode"]["attrs"]
        assert dec["tokens"] == 4 and dec["steps"] == 3
        assert dec["occupancy_at_admit"] >= 1
        # Phases nest inside the root on the timeline.
        for name in ("serve.queue_wait", "serve.prefill", "serve.decode"):
            s = by_name[name]
            assert root["start_us"] <= s["start_us"] <= s["end_us"] \
                <= root["end_us"]
    finally:
        b.stop()


def test_request_tracer_sampling_error_and_slo():
    """sample=0 suppresses healthy traces, but errors and SLO breaches
    are ALWAYS traced — the 'why was THIS request slow' contract."""
    from determined_tpu.serve.scheduler import now_us
    from determined_tpu.serve.tracing import RequestTracer

    def fake_request(rid, error=None, e2e_ms=5.0):
        r = _req(request_id=rid)
        r.admitted_us = r.submitted_us + 100
        r.prefill_start_us = r.admitted_us
        r.prefill_end_us = r.first_token_us = r.admitted_us + 200
        r.out_tokens = [1, 2]
        r.error = error
        r.finished_us = r.submitted_us + int(e2e_ms * 1000)
        return r

    tracer = RequestTracer(None, "", sample=0.0, slo_ms=100.0)
    assert tracer.record(fake_request("healthy")) is False
    assert tracer.sampled_out == 1
    assert tracer.record(fake_request("failed", error="boom")) is True
    assert tracer.record(fake_request("slow", e2e_ms=500.0)) is True
    assert tracer.slo_breaches == 1
    tracer.flush()
    traced = {s["trace_id"] for s in tracer.local_spans}
    assert traced == {"failed", "slow"}
    err_root = [s for s in tracer.local_spans
                if s["trace_id"] == "failed"
                and s["name"] == "serve.request"][0]
    assert err_root["attrs"]["error"] == "boom"
    # Fractional sampling stays within the fraction's ballpark.
    tracer2 = RequestTracer(None, "", sample=0.5)
    hits = sum(tracer2.record(fake_request(f"r{i}")) for i in range(200))
    assert 50 <= hits <= 150


def test_serving_trace_drop_generations_survive_span_sink_loss(tiny_params):
    """The chaos satellite (docs/chaos.md): with `serving.trace.drop`
    armed — and separately with a dead sink session — span batches drop
    and NOT ONE generation blocks or fails (same contract as PR 8's
    trace.span.drop)."""
    from determined_tpu.serve.tracing import FAULT_TRACE_DROP, RequestTracer

    class DeadSink:
        posts = 0

        def post(self, *a, **kw):
            DeadSink.posts += 1
            raise ConnectionError("span sink is gone")

    eng = make_engine(tiny_params, slots=2)
    b = make_batcher(eng)
    tracer = RequestTracer(DeadSink(), "alloc-x", sample=1.0)
    b.tracer = tracer
    b.start()
    try:
        # Leg 1: the fault point eats the batch before it reaches any
        # sink — flush returns 0, nothing raises.
        faultpoint.arm(FAULT_TRACE_DROP, "drop", count=1)
        r = b.submit(_req(max_new=3, request_id="drop-1"))
        assert r.result(timeout=120)["tokens"]
        assert tracer.pending() > 0
        assert tracer.flush() == 0
        assert tracer.dropped == 1 and DeadSink.posts == 0

        # Leg 2: disarmed, the sink itself is dead — the POST raises
        # inside flush, the batch drops, generations keep completing.
        reqs = [b.submit(_req(max_new=3, request_id=f"drop-{i}"))
                for i in range(2, 6)]
        results = [r.result(timeout=120) for r in reqs]
        assert all(res["tokens"] for res in results)
        assert tracer.flush() == 0 and DeadSink.posts == 1
        assert tracer.dropped == 2
        # Zero failed requests — the acceptance gate.
        assert b.failed == 0 and b.stats()["completed"] == 5
    finally:
        b.stop()


def test_http_request_id_and_latency_exposition(http_replica):
    """The replica front-end adopts X-Request-Id, echoes it, and /metrics
    carries the four SLO histograms in exposition form."""
    url, batcher = http_replica
    req = urllib.request.Request(
        url + "/v1/generate", method="POST",
        data=json.dumps({"tokens": [1, 2, 3], "max_new_tokens": 3}).encode(),
        headers={"Content-Type": "application/json",
                 "X-Request-Id": "http-rid-1"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        assert resp.status == 200
        assert resp.headers["X-Request-Id"] == "http-rid-1"
        assert json.loads(resp.read())["id"] == "http-rid-1"
    with urllib.request.urlopen(url + "/metrics", timeout=30) as resp:
        text = resp.read().decode()
    for name in ("det_serve_ttft_seconds", "det_serve_tpot_seconds",
                 "det_serve_e2e_seconds", "det_serve_queue_wait_seconds"):
        assert f"# TYPE {name} histogram" in text
        count = [line for line in text.splitlines()
                 if line.startswith(f"{name}_count")]
        assert count and int(count[0].split()[-1]) >= 1, (name, text)
    # /v1/stats carries the summarized form next to the raw counters.
    status, stats = _http("GET", url + "/v1/stats")
    assert status == 200
    assert stats["latency"]["e2e"]["count"] >= 1
    assert stats["latency"]["e2e"]["p99_ms"] >= stats["latency"]["e2e"]["p50_ms"]


# ---------------------------------------------------------------------------
# Devcluster e2e (slow): submit → serve → drain → replica reschedule.
# ---------------------------------------------------------------------------


def _serving_config(tmp_path, sid="trial0-step2"):
    return {
        "name": "serve-e2e",
        "serving": {
            "checkpoint": sid,
            "model": "gpt2",
            "model_config": {"model_size": "tiny", "seq_len": 64,
                             "dtype": "float32",
                             "vocab_size": TINY.vocab_size,
                             "n_positions": 64,
                             "d_model": TINY.d_model,
                             "n_layer": TINY.n_layer,
                             "n_head": TINY.n_head},
            "max_batch_size": 4,
            "max_seq_len": 32,
            "prefill_buckets": [8, 16],
            "queue_depth": 32,
        },
        "resources": {"slots_per_trial": 1},
        "checkpoint_storage": {
            "type": "shared_fs",
            "host_path": os.path.join(str(tmp_path), "ckpts"),
        },
    }


# ---------------------------------------------------------------------------
# Model lifecycle (docs/serving.md "Model lifecycle"): multi-adapter
# replicas, swap bit-identity, registered-version restore.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def finetuned_params(tiny_params):
    """A head-tuned fine-tune of tiny_params: SAME transformer body,
    retrained (here: perturbed) tied embedding/LM-head table — the
    adapter contract (engine stacks exactly the wte per adapter)."""
    ft = dict(tiny_params)
    ft["wte"] = tiny_params["wte"] + 0.5 * jax.random.normal(
        jax.random.PRNGKey(7), tiny_params["wte"].shape)
    return ft


@pytest.fixture(scope="module")
def finetuned_params_b(tiny_params):
    ft = dict(tiny_params)
    ft["wte"] = tiny_params["wte"] + 0.5 * jax.random.normal(
        jax.random.PRNGKey(11), tiny_params["wte"].shape)
    return ft


class TestAdapters:
    """Multi-adapter replicas: N fine-tunes resident beside one base
    executable, routed per request by `model:` name."""

    def test_adapter_routed_equals_direct_serve(self, tiny_params,
                                                finetuned_params):
        """The acceptance contract: a request routed to adapter `ft`
        produces the SAME generations as a dedicated deployment of the
        fine-tuned checkpoint — many fine-tunes share a fleet without
        changing a single token of anyone's output."""
        eng = ServingEngine(tiny_params, TINY, slots=4, max_seq_len=32,
                            prefill_buckets=[8, 16, 32],
                            adapters={"ft": finetuned_params})
        b = make_batcher(eng)
        b.start()
        prompt = [5, 9, 17, 3]
        try:
            base_out = b.submit(Request(prompt, max_new_tokens=8)
                                ).result(60)["tokens"]
            ft_out = b.submit(Request(prompt, max_new_tokens=8,
                                      model="ft")).result(60)["tokens"]
        finally:
            b.stop()
        assert base_out != ft_out, "fine-tune must change generations"

        # Direct serve of the full fine-tuned checkpoint.
        eng2 = ServingEngine(finetuned_params, TINY, slots=4,
                             max_seq_len=32, prefill_buckets=[8, 16, 32])
        b2 = make_batcher(eng2)
        b2.start()
        try:
            direct = b2.submit(Request(prompt, max_new_tokens=8)
                               ).result(60)["tokens"]
        finally:
            b2.stop()
        assert ft_out == direct

        # And base routing on the adapter engine is bit-equal to a
        # no-adapter engine (index 0 IS the base table).
        eng3 = ServingEngine(tiny_params, TINY, slots=4, max_seq_len=32,
                             prefill_buckets=[8, 16, 32])
        b3 = make_batcher(eng3)
        b3.start()
        try:
            plain = b3.submit(Request(prompt, max_new_tokens=8)
                              ).result(60)["tokens"]
        finally:
            b3.stop()
        assert plain == base_out

    def test_mixed_batch_per_slot_routing(self, tiny_params,
                                          finetuned_params,
                                          finetuned_params_b):
        """Different adapters decode in the SAME continuous batch, each
        lane using its own table — per-slot routing, zero recompiles."""
        eng = ServingEngine(tiny_params, TINY, slots=4, max_seq_len=32,
                            prefill_buckets=[8, 16, 32],
                            adapters={"ft-a": finetuned_params,
                                      "ft-b": finetuned_params_b})
        b = make_batcher(eng)
        b.start()
        prompt = [5, 9, 17, 3]
        try:
            reqs = [
                b.submit(Request(prompt, max_new_tokens=12, model=m))
                for m in (None, "ft-a", "ft-b", None)
            ]
            outs = [r.result(60)["tokens"] for r in reqs]
            # Concurrency really happened (they shared decode steps).
            assert b.max_occupancy >= 2
        finally:
            b.stop()
        assert outs[0] == outs[3]            # same model, same tokens
        # Each matches its solo run (fresh batcher, same engine — the
        # compiled executables and adapter stack are the same objects).
        b2 = make_batcher(eng)
        b2.start()
        try:
            solo = {
                m: b2.submit(Request(prompt, max_new_tokens=12, model=m)
                             ).result(60)["tokens"]
                for m in (None, "ft-a", "ft-b")
            }
        finally:
            b2.stop()
        # The mixed batch reproduced each lane's solo generations: no
        # lane leaked another lane's table (and the fine-tune really
        # moved the base's output).
        assert outs[0] == solo[None]
        assert outs[1] == solo["ft-a"]
        assert outs[2] == solo["ft-b"]
        assert solo["ft-a"] != solo[None]

    def test_unknown_adapter_rejected(self, tiny_params,
                                      finetuned_params):
        eng = ServingEngine(tiny_params, TINY, slots=2, max_seq_len=32,
                            prefill_buckets=[8],
                            adapters={"ft": finetuned_params})
        b = make_batcher(eng)
        with pytest.raises(ValueError, match="unknown adapter"):
            b.submit(Request([1, 2, 3], model="ghost"))
        # No adapters resident at all: any model name is refused.
        eng2 = ServingEngine(tiny_params, TINY, slots=2, max_seq_len=32,
                             prefill_buckets=[8])
        b2 = make_batcher(eng2)
        with pytest.raises(ValueError, match="unknown adapter"):
            b2.submit(Request([1, 2, 3], model="ft"))

    def test_adapter_shape_mismatch_refused(self, tiny_params):
        bad = dict(tiny_params)
        bad["wte"] = jnp.zeros((8, 8), jnp.float32)
        with pytest.raises(ValueError, match="geometry"):
            ServingEngine(tiny_params, TINY, slots=2, max_seq_len=32,
                          adapters={"bad": bad})

    def test_adapter_stats_and_counters(self, tiny_params,
                                        finetuned_params):
        eng = ServingEngine(tiny_params, TINY, slots=2, max_seq_len=32,
                            prefill_buckets=[8],
                            adapters={"ft": finetuned_params})
        b = make_batcher(eng)
        b.start()
        try:
            b.submit(Request([1, 2, 3], max_new_tokens=2)).result(60)
            b.submit(Request([1, 2, 3], max_new_tokens=2,
                             model="ft")).result(60)
            b.submit(Request([1, 2, 3], max_new_tokens=2,
                             model="ft")).result(60)
        finally:
            b.stop()
        stats = b.stats()
        assert stats["adapter_requests"] == {"base": 1, "ft": 2}
        assert eng.stats()["adapters"] == ["ft"]


class TestLifecycleBitIdentity:
    """Swap bit-identity + registered-version restore (acceptance
    criteria of the model-lifecycle PR)."""

    def test_post_swap_replica_matches_fresh_deployment(
            self, tmp_path, tiny_params, finetuned_params):
        """A rolling swap replaces replicas rather than hot-editing
        weights: the replica the reconciler spawns for version B is
        config-identical to a fresh deployment of B — assert the
        generations are bit-identical, with BOTH loads going through the
        manifest+COMMIT verification path."""
        _save_checkpoint(tmp_path, tiny_params, 2)          # version A
        ctx, sid_b = _save_checkpoint(tmp_path, finetuned_params, 4)

        def replica_generations(storage_id):
            params = load_checkpoint_params(ctx.checkpoint, storage_id)
            eng = ServingEngine(params, TINY, slots=2, max_seq_len=32,
                                prefill_buckets=[8, 16])
            b = make_batcher(eng)
            b.start()
            try:
                return b.submit(Request([5, 9, 17, 3], max_new_tokens=8)
                                ).result(60)["tokens"]
            finally:
                b.stop()

        # "Post-swap replica": what spawn_deployment_replica_locked
        # launches after `det serve update` rewrote serving.checkpoint.
        post_swap = replica_generations(sid_b)
        # "Fresh deployment of that version": same checkpoint, new boot.
        fresh = replica_generations(sid_b)
        assert post_swap == fresh

    def test_registered_version_restore_verifies_integrity(
            self, tmp_path, tiny_params):
        """Registered-version restore reuses the PR-6 manifest+COMMIT
        path: a corrupted registered checkpoint REFUSES to serve (falls
        back through the lineage) instead of loading a torso."""
        _save_checkpoint(tmp_path, tiny_params, 2)
        ctx, sid = _save_checkpoint(tmp_path, tiny_params, 4)
        # Corrupt the registered version's payload.
        path = ctx.checkpoint._storage.path_for(sid)
        victim = None
        for root, _, files in os.walk(os.path.join(path, "state")):
            for f in files:
                victim = os.path.join(root, f)
        with open(victim, "r+b") as f:
            f.truncate(max(0, os.path.getsize(victim) // 2))
        # The resolution a deployment performs for "model:N" is exactly
        # load_checkpoint_params on the version's storage id.
        loaded = load_checkpoint_params(ctx.checkpoint, sid)
        assert loaded is not None  # lineage fallback, never the torso


@pytest.mark.slow
def test_serve_drain_reschedule_e2e(tmp_path):
    """Acceptance: a serve replica under load receives a spot notice —
    it stops admitting, finishes every in-flight sequence inside the
    grace window (zero dropped), exits cleanly, and the master
    reschedules it onto the surviving agent (restarts >= 1, fresh proxy
    address, serving again)."""
    from tests.test_platform_e2e import NATIVE_BIN, Devcluster
    import subprocess

    subprocess.run(["make", "-C", os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "native")], check=True, capture_output=True)

    # A checkpoint to serve. The tiny model must match the serve config;
    # TINY here uses n_positions=64 to cover seq_len.
    cfg = gpt2.Config(
        vocab_size=TINY.vocab_size, n_positions=64, d_model=32,
        n_layer=2, n_head=2, dtype=jnp.float32, remat=False,
        attention_impl="dot")
    params = gpt2.init(jax.random.PRNGKey(0), cfg)
    ctx = core.init(max_length=2,
                    checkpoint_dir=os.path.join(str(tmp_path), "ckpts"))
    ctx.checkpoint.save_state(
        {"step": jnp.asarray(2, jnp.int32), "params": params,
         "opt_state": {"count": jnp.zeros((), jnp.int32)}}, 2)
    ctx.checkpoint.wait()
    ctx.close()

    c = Devcluster(str(tmp_path), NATIVE_BIN, slots=1)
    c.start_master()
    notice_files = {}
    for agent_id in ("serve-a", "serve-b"):
        nf = os.path.join(str(tmp_path), f"notice-{agent_id}.json")
        notice_files[agent_id] = nf
        c.start_agent(agent_id, extra_env={"DET_AGENT_NOTICE_FILE": nf})
    try:
        token = c.login()
        resp = c.api("POST", "/api/v1/serving",
                     {"config": _serving_config(tmp_path)}, token=token)
        tid = resp["id"]

        def _task():
            return c.api("GET", f"/api/v1/serving/{tid}",
                         token=token)["task"]

        # Wait for the replica to come up and register its address.
        deadline = time.time() + 180
        task = None
        while time.time() < deadline:
            task = _task()
            if task.get("proxy_address"):
                break
            time.sleep(0.5)
        assert task and task.get("proxy_address"), task

        def generate(max_new=8, timeout=60):
            return c.api(
                "POST", f"/proxy/{tid}/v1/generate",
                {"tokens": [5, 9, 17, 3], "max_new_tokens": max_new,
                 "timeout_s": timeout},
                token=token)

        first = generate(max_new=4)
        assert len(first["tokens"]) == 4

        # Which agent hosts the replica? (serving allocation ids embed
        # the task id: alloc-{task_id}[-rN])
        jobs = c.api("GET", "/api/v1/job-queues", token=token)["jobs"]
        alloc_id = next(j["allocation_id"] for j in jobs
                        if tid in str(j.get("allocation_id", "")))
        alloc = c.api("GET", f"/api/v1/allocations/{alloc_id}",
                      token=token)["allocation"]
        victim = alloc["resources"][0]["agent_id"]
        survivor = "serve-b" if victim == "serve-a" else "serve-a"

        # Load in flight while the notice lands: every accepted request
        # must complete (zero dropped responses).
        results, errors = [], []

        def _loader():
            for _ in range(4):
                try:
                    results.append(generate(max_new=16, timeout=90))
                except Exception as e:  # 503s after drain are expected
                    errors.append(str(e))

        threads = [threading.Thread(target=_loader) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        with open(notice_files[victim], "w") as f:
            json.dump({"deadline_seconds": 30,
                       "reason": "spot_preemption"}, f)
        for t in threads:
            t.join(timeout=180)

        # Every response that came back is complete; HTTP-level
        # rejections (503 while draining) are allowed, dropped/truncated
        # responses are not.
        assert results, "no request completed during the drain window"
        assert all(len(r["tokens"]) == 16 for r in results), results

        # The replica reschedules onto the survivor with restarts >= 1
        # and serves again from its new address.
        deadline = time.time() + 180
        moved = None
        while time.time() < deadline:
            task = _task()
            if int(task.get("restarts") or 0) >= 1 and \
                    task.get("allocation_state") == "RUNNING" and \
                    task.get("proxy_address"):
                jobs = c.api("GET", "/api/v1/job-queues",
                             token=token)["jobs"]
                for j in jobs:
                    a = c.api("GET",
                              f"/api/v1/allocations/{j['allocation_id']}",
                              token=token)["allocation"]
                    if a.get("task_id") == tid and a["state"] == "RUNNING":
                        moved = a["resources"][0]["agent_id"]
                if moved:
                    break
            time.sleep(0.5)
        assert moved == survivor, (
            f"replica did not reschedule onto {survivor}: task={task}")
        again = generate(max_new=4)
        assert len(again["tokens"]) == 4
        c.api("POST", f"/api/v1/serving/{tid}/kill", {}, token=token)
    finally:
        c.stop()
