"""Persistent XLA compilation cache (SURVEY hard part b / VERDICT r4
next #5): core.init enables jax's disk cache from the agent-injected
DET_XLA_CACHE_DIR so identical-shape ASHA rung trials skip compile."""

import json
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PROBE = textwrap.dedent("""
    import json, time
    import jax, jax.numpy as jnp
    from determined_tpu.core._context import _enable_compilation_cache
    _enable_compilation_cache()

    @jax.jit
    def f(x):
        for _ in range(8):
            x = jnp.tanh(x @ x.T) @ x
        return x.sum()

    x = jnp.ones((173, 211))  # odd shapes: this test's cache entry only
    t0 = time.time()
    f(x).block_until_ready()
    print(json.dumps({"compile_s": time.time() - t0}))
""")


def _run_probe(cache_dir, env_extra=None):
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
        DET_XLA_CACHE_DIR=str(cache_dir),
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(env_extra or {})
    r = subprocess.run([sys.executable, "-c", _PROBE],
                       capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_cache_populated_and_reused(tmp_path):
    cache = tmp_path / "xla_cache"
    cold = _run_probe(cache)
    files = sorted(os.listdir(cache))
    assert files, "first run must write cache entries"

    warm = _run_probe(cache)
    files2 = sorted(os.listdir(cache))
    assert files2 == files, "identical program must HIT, not re-write"
    # The warm process loads the compiled executable instead of running
    # XLA optimization; require a real win but keep slack for CI noise.
    assert warm["compile_s"] < cold["compile_s"] * 0.7, (cold, warm)


def test_empty_env_disables_cache(tmp_path):
    """The expconf `DET_XLA_CACHE_DIR=` override must really disable the
    cache: nothing may be written to the dir the env no longer names."""
    cache = tmp_path / "would_be_cache"
    _run_probe(cache, env_extra={"DET_XLA_CACHE_DIR": ""})
    assert not os.path.exists(cache)


def test_core_init_enables_cache(tmp_path, monkeypatch):
    """core.init is the harness-wide hook: after it runs under
    DET_XLA_CACHE_DIR, jax's config points at the dir."""
    import jax

    prev = jax.config.jax_compilation_cache_dir
    try:
        monkeypatch.setenv("DET_XLA_CACHE_DIR", str(tmp_path / "cc"))
        from determined_tpu.core._context import _enable_compilation_cache

        _enable_compilation_cache()
        assert jax.config.jax_compilation_cache_dir == str(tmp_path / "cc")
        assert os.path.isdir(tmp_path / "cc")
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
