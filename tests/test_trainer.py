"""End-to-end Trainer tests in local (masterless) mode on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from determined_tpu import core
from determined_tpu.models import gpt2
from determined_tpu.parallel.mesh import MeshConfig
from determined_tpu.train import JaxTrial, Trainer
from determined_tpu.train.trial import TrialContext


class TinyGPT2Trial(JaxTrial):
    def __init__(self, context):
        super().__init__(context)
        self.cfg = gpt2.Config.tiny()

    def init_params(self, rng):
        return gpt2.init(rng, self.cfg)

    def loss(self, params, batch, rng):
        return gpt2.loss_fn(params, batch, self.cfg)

    def optimizer(self):
        return optax.adam(self.context.get_hparam("learning_rate", 1e-3))

    def param_logical_axes(self):
        return gpt2.param_logical_axes(self.cfg)

    def mesh_config(self):
        return MeshConfig(data=-1, fsdp=2, tensor=2)

    def build_training_data(self):
        rng = np.random.default_rng(0)
        for _ in range(100):
            yield {"tokens": rng.integers(0, 64, size=(8, 17)).astype(np.int32)}

    def build_validation_data(self):
        rng = np.random.default_rng(1)
        for _ in range(2):
            yield {"tokens": rng.integers(0, 64, size=(8, 17)).astype(np.int32)}

    def evaluate(self, params, batch):
        return {"loss": gpt2.loss_fn(params, batch, self.cfg)}


def make_local_core(tmp_path, max_length):
    return core.init(
        max_length=max_length,
        checkpoint_dir=str(tmp_path / "ckpts"),
        async_checkpointing=False,
    )


def test_fit_local(tmp_path):
    ctx = make_local_core(tmp_path, max_length=6)
    trial = TinyGPT2Trial(TrialContext(hparams={"learning_rate": 1e-3}))
    trainer = Trainer(trial, core_context=ctx)
    state = trainer.fit(report_period=2)
    assert int(jax.device_get(state.step)) == 6
    # metrics reported locally
    assert ctx.train.local_training_metrics
    assert ctx.train.local_validation_metrics
    val = ctx.train.local_validation_metrics[-1]
    assert "validation_loss" in val["metrics"]
    # searcher op completed with the validation loss
    assert len(ctx.searcher.completed_metrics) == 1
    # checkpoint written + reported
    assert ctx.checkpoint.local_reported
    ctx.close()


def test_resume_from_checkpoint(tmp_path):
    ctx = make_local_core(tmp_path, max_length=4)
    trial = TinyGPT2Trial(TrialContext())
    trainer = Trainer(trial, core_context=ctx)
    state = trainer.fit(report_period=2)
    ckpt_id = ctx.checkpoint.local_reported[-1]["uuid"]
    ctx.close()

    # fresh trainer resumes *through fit* and continues 4 → 8
    ctx2 = make_local_core(tmp_path, max_length=8)
    trial2 = TinyGPT2Trial(TrialContext())
    trainer2 = Trainer(trial2, core_context=ctx2)
    state2 = trainer2.fit(report_period=2, resume_from=ckpt_id)
    assert int(jax.device_get(state2.step)) == 8
    # resumed run reported steps 6 and 8 only (started at 4, not 0)
    reported_steps = [m["steps_completed"] for m in ctx2.train.local_training_metrics]
    assert min(reported_steps) > 4
    ctx2.close()

    # corrupt checkpoint must not crash-loop: the restore walks the
    # lineage BACKWARD from the requested id (never forward — step 8
    # exists here but is newer), and with no older COMPLETED checkpoint
    # it starts fresh (test_selfheal.py covers the fallback-hit case)
    import shutil

    ckpt_path = ctx2.checkpoint._storage.path_for(ckpt_id)
    shutil.rmtree(ckpt_path + "/state", ignore_errors=True)
    ctx3 = make_local_core(tmp_path, max_length=2)
    trainer3 = Trainer(TinyGPT2Trial(TrialContext()), core_context=ctx3)
    state3 = trainer3.fit(report_period=2, resume_from=ckpt_id)
    assert int(jax.device_get(state3.step)) == 2
    ctx3.close()


class PipelinedTinyGPT2Trial(TinyGPT2Trial):
    """Tiny trial exercising the config→Trainer pipeline path."""

    def mesh_config(self):
        return MeshConfig(data=2, pipeline=2, tensor=2)

    def loss_pipelined(self, params, batch, rng, mesh):
        return gpt2.loss_fn_pipelined(params, batch, self.cfg, mesh,
                                      num_microbatches=4)

    def evaluate_pipelined(self, params, batch, mesh):
        return {"loss": gpt2.loss_fn_pipelined(
            params, batch, self.cfg, mesh, num_microbatches=4)}


def test_pipeline_mesh_selects_pipelined_loss(tmp_path):
    """mesh.pipeline=2 from the trial config runs the GPipe path end-to-end
    through Trainer.fit (train + validate + checkpoint)."""
    ctx = make_local_core(tmp_path, max_length=4)
    trial = PipelinedTinyGPT2Trial(TrialContext(hparams={"learning_rate": 1e-3}))
    trainer = Trainer(trial, core_context=ctx)
    assert trainer.mesh.shape["pipeline"] == 2
    state = trainer.fit(report_period=2)
    assert int(jax.device_get(state.step)) == 4
    val = ctx.train.local_validation_metrics[-1]
    assert np.isfinite(val["metrics"]["validation_loss"])
    ctx.close()


def test_pipeline_mesh_matches_nonpipelined_loss(tmp_path):
    """The pipelined step must train equivalently to the plain path: compare
    the reported loss after identical steps/seed on pipeline vs data mesh."""
    ctx = make_local_core(tmp_path, max_length=3)
    t1 = PipelinedTinyGPT2Trial(TrialContext())
    tr1 = Trainer(t1, core_context=ctx)
    tr1.fit(report_period=1)
    losses_pp = [m["metrics"]["loss"] for m in ctx.train.local_training_metrics]
    ctx.close()

    ctx2 = make_local_core(tmp_path, max_length=3)
    t2 = TinyGPT2Trial(TrialContext())
    tr2 = Trainer(t2, core_context=ctx2)
    tr2.fit(report_period=1)
    losses_plain = [m["metrics"]["loss"] for m in ctx2.train.local_training_metrics]
    ctx2.close()

    np.testing.assert_allclose(losses_pp, losses_plain, rtol=2e-2)


def test_pipeline_mesh_without_hook_rejected(tmp_path):
    """pipeline>1 with a trial lacking loss_pipelined must fail loudly, not
    silently run a gathered non-pipelined step (VERDICT r2 weak #1)."""

    class NoPipelineTrial(TinyGPT2Trial):
        def mesh_config(self):
            return MeshConfig(data=4, pipeline=2)

    ctx = make_local_core(tmp_path, max_length=2)
    trainer = Trainer(NoPipelineTrial(TrialContext()), core_context=ctx)
    with pytest.raises(ValueError, match="loss_pipelined"):
        trainer.fit()
    ctx.close()


def test_preemption_checkpoints_and_stops(tmp_path):
    ctx = make_local_core(tmp_path, max_length=1000)
    trial = TinyGPT2Trial(TrialContext())
    trainer = Trainer(trial, core_context=ctx)
    # preempt immediately: first should_preempt() poll returns True
    ctx.preempt.force()
    state = trainer.fit(report_period=2)
    steps = int(jax.device_get(state.step))
    assert steps < 1000
    assert ctx.checkpoint.local_reported  # checkpointed on preemption
    assert ctx.searcher.completed_metrics == []  # op not completed
    ctx.close()


class _PollCountingTrial(TinyGPT2Trial):
    def mesh_config(self):
        return MeshConfig()  # pure data-parallel: cheapest compile


def test_preempt_poll_cadence_independent_of_report_period(tmp_path):
    """The preemption poll runs every `preempt_period` steps regardless of
    `report_period` — in particular report_period=0 must NOT poll the
    master every step (the old `max(report_period, 1)` coupling)."""
    for report_period, preempt_period, expect in ((0, 4, 4), (3, 2, 2)):
        ctx = make_local_core(tmp_path, max_length=1000)
        polls = []
        orig = ctx.preempt.should_preempt
        ctx.preempt.should_preempt = lambda *a, **k: (polls.append(1), orig())[1]
        ctx.preempt.force()
        trainer = Trainer(_PollCountingTrial(TrialContext()), core_context=ctx)
        state = trainer.fit(report_period=report_period,
                            preempt_period=preempt_period)
        # first poll happens at step == preempt_period and already preempts
        assert int(jax.device_get(state.step)) == expect
        assert len(polls) == 1
        ctx.close()
