"""Drive the native C++ unit tests (plain + sanitizers) from pytest.

Reference discipline: the Go master runs `go test -race -short`
(master/Makefile:187); here `make -C native test / asan / tsan` build and
run the same binary under ThreadSanitizer and AddressSanitizer+UBSan."""

import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make(target: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        ["make", "-C", os.path.join(REPO, "native"), target],
        capture_output=True, text=True, timeout=600,
    )


@pytest.mark.parametrize("target", ["test", "asan", "tsan"])
def test_native_units(target):
    r = _make(target)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    assert "0 failures" in r.stdout
