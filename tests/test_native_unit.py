"""Drive the native C++ unit tests (plain + sanitizers) from pytest.

Reference discipline: the Go master runs `go test -race -short`
(master/Makefile:187). Here the same sources build plain and under
ThreadSanitizer / AddressSanitizer+UBSan:

  - test_native_units:  `make -C native test` — pure-logic units plus the
    threaded master test (real Master hammered through handle() from many
    threads), no sanitizer.
  - test_native_tsan / test_native_asan: the fast pure-logic binary under
    each sanitizer; builds are skipped cleanly when the toolchain cannot
    produce sanitized binaries (no libtsan/libasan).
  - test_master_threads_tsan (slow): the full threaded master under TSan —
    the `go test -race` analogue. Needs tests/tsan_clockwait_shim.cc:
    without it this toolchain's libtsan misses pthread_cond_clockwait
    (libstdc++ steady-clock condition_variable waits) and corrupts its
    lock bookkeeping into bogus "double lock" reports.
"""

import functools
import os
import subprocess
import tempfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")


def _make(target: str, timeout: int = 600) -> subprocess.CompletedProcess:
    return subprocess.run(
        ["make", "-C", NATIVE, target],
        capture_output=True, text=True, timeout=timeout,
    )


def _run(binary: str, env=None) -> subprocess.CompletedProcess:
    e = dict(os.environ)
    e.update(env or {})
    return subprocess.run(
        [os.path.join(NATIVE, "bin", binary)],
        capture_output=True, text=True, timeout=300, env=e,
    )


@functools.lru_cache(maxsize=None)
def _sanitizer_available(flag: str) -> bool:
    """Can the toolchain link a -fsanitize=<flag> binary?"""
    with tempfile.TemporaryDirectory() as d:
        src = os.path.join(d, "probe.cc")
        with open(src, "w") as f:
            f.write("int main() { return 0; }\n")
        r = subprocess.run(
            [os.environ.get("CXX", "g++"), f"-fsanitize={flag}", "-o",
             os.path.join(d, "probe"), src],
            capture_output=True, timeout=120,
        )
        return r.returncode == 0


def test_native_units():
    r = _make("test")
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    assert "0 failures" in r.stdout


def _sanitized_unit(flag: str, binary: str, env=None):
    if not _sanitizer_available(flag):
        pytest.skip(f"toolchain cannot build -fsanitize={flag} binaries")
    r = _make(f"bin/{binary}")
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    out = _run(binary, env=env)
    assert out.returncode == 0, out.stdout[-3000:] + out.stderr[-3000:]
    assert "0 failures" in out.stdout


def test_native_tsan():
    _sanitized_unit("thread", "test_native_tsan")


def test_native_asan():
    _sanitized_unit("address", "test_native_asan")


@pytest.mark.slow
def test_master_threads_tsan():
    """The go-test -race analogue: real master, many concurrent clients,
    under ThreadSanitizer (with the pthread_cond_clockwait shim)."""
    _sanitized_unit("thread", "test_master_threads_tsan",
                    env={"TSAN_OPTIONS": "halt_on_error=1"})
