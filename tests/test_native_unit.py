"""Drive the native C++ unit tests (plain + sanitizers) from pytest.

Reference discipline: the Go master runs `go test -race -short`
(master/Makefile:187). Here the same sources build plain and under
ThreadSanitizer / AddressSanitizer+UBSan:

  - test_native_units:  `make -C native test` — pure-logic units plus the
    threaded master test (real Master hammered through handle() from many
    threads), no sanitizer.
  - test_native_tsan / test_native_asan: the fast pure-logic binary under
    each sanitizer; builds are skipped cleanly when the toolchain cannot
    produce sanitized binaries (no libtsan/libasan).
  - test_master_threads_tsan (slow): the full threaded master under TSan —
    the `go test -race` analogue. Needs tests/tsan_clockwait_shim.cc:
    without it this toolchain's libtsan misses pthread_cond_clockwait
    (libstdc++ steady-clock condition_variable waits) and corrupts its
    lock bookkeeping into bogus "double lock" reports.
"""

import functools
import os
import subprocess
import tempfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")


def _make(target: str, timeout: int = 600) -> subprocess.CompletedProcess:
    return subprocess.run(
        ["make", "-C", NATIVE, target],
        capture_output=True, text=True, timeout=timeout,
    )


def _run(binary: str, env=None) -> subprocess.CompletedProcess:
    e = dict(os.environ)
    e.update(env or {})
    return subprocess.run(
        [os.path.join(NATIVE, "bin", binary)],
        capture_output=True, text=True, timeout=300, env=e,
    )


@functools.lru_cache(maxsize=None)
def _sanitizer_available(flag: str) -> bool:
    """Can the toolchain link a -fsanitize=<flag> binary?"""
    with tempfile.TemporaryDirectory() as d:
        src = os.path.join(d, "probe.cc")
        with open(src, "w") as f:
            f.write("int main() { return 0; }\n")
        r = subprocess.run(
            [os.environ.get("CXX", "g++"), f"-fsanitize={flag}", "-o",
             os.path.join(d, "probe"), src],
            capture_output=True, timeout=120,
        )
        return r.returncode == 0


def test_native_units():
    r = _make("test")
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    assert "0 failures" in r.stdout


def _sanitized_unit(flag: str, binary: str, env=None):
    if not _sanitizer_available(flag):
        pytest.skip(f"toolchain cannot build -fsanitize={flag} binaries")
    r = _make(f"bin/{binary}")
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    out = _run(binary, env=env)
    assert out.returncode == 0, out.stdout[-3000:] + out.stderr[-3000:]
    assert "0 failures" in out.stdout


def test_native_tsan():
    _sanitized_unit("thread", "test_native_tsan")


def test_native_asan():
    _sanitized_unit("address", "test_native_asan")


@pytest.mark.slow
def test_master_threads_tsan():
    """The go-test -race analogue: real master, many concurrent clients,
    under ThreadSanitizer (with the pthread_cond_clockwait shim)."""
    _sanitized_unit("thread", "test_master_threads_tsan",
                    env={"TSAN_OPTIONS": "halt_on_error=1"})


# ---------------------------------------------------------------------------
# compile-time thread-safety gate (`make -C native tsa`,
# docs/static-analysis.md) — mirrors the sanitizer probes: runs for real
# when a thread-safety-capable clang is installed, skips cleanly otherwise.
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _tsa_clang() -> str:
    """Path/name of a clang++ that understands -Wthread-safety, or ''."""
    cxx = os.environ.get("CLANGXX", "clang++")
    try:
        r = subprocess.run(
            [cxx, "-x", "c++", "-fsyntax-only", "-Werror",
             "-Wthread-safety", "-"],
            input="int main() { return 0; }\n",
            capture_output=True, text=True, timeout=120,
        )
    except (OSError, subprocess.TimeoutExpired):
        return ""
    return cxx if r.returncode == 0 else ""


def test_tsa_target_never_breaks_the_build():
    """`make tsa` must exit 0 on toolchains without clang (it prints a
    skip notice) — it is folded into `make lint`, which has to stay
    runnable everywhere."""
    r = _make("tsa")
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    assert ("thread-safety gate skipped" in r.stdout
            or "gate skipped" in r.stdout
            or "-Wthread-safety -Werror over native/" in r.stdout)


def test_tsa_gate_compiles_native_clean():
    """With a capable clang, the whole native layer passes
    -Wthread-safety -Werror (the annotation contract holds)."""
    if not _tsa_clang():
        pytest.skip("no clang++ with -Wthread-safety support installed")
    r = _make("tsa")
    assert r.returncode == 0, r.stdout[-4000:] + r.stderr[-4000:]
    assert "gate skipped" not in r.stdout


_TSA_VIOLATION = """\
#include "common/mutex.h"
#include "common/thread_annotations.h"

class Counter {
 public:
  void bump() { ++n_; }  // BUG: reads/writes n_ without holding mu_

 private:
  det::Mutex mu_;
  int n_ GUARDED_BY(mu_) = 0;
};

int main() {
  Counter c;
  c.bump();
  return 0;
}
"""

_TSA_CORRECT = _TSA_VIOLATION.replace(
    "void bump() { ++n_; }  // BUG: reads/writes n_ without holding mu_",
    "void bump() { det::MutexLock lock(mu_); ++n_; }")


def _tsa_compile(source: str) -> subprocess.CompletedProcess:
    cxx = _tsa_clang()
    with tempfile.TemporaryDirectory() as d:
        src = os.path.join(d, "probe.cc")
        with open(src, "w") as f:
            f.write(source)
        return subprocess.run(
            [cxx, "-std=c++17", "-fsyntax-only", "-Wthread-safety",
             "-Werror", "-I", NATIVE, src],
            capture_output=True, text=True, timeout=120,
        )


def test_tsa_gate_fails_on_seeded_violation():
    """The gate is not vacuous: a TU that touches a GUARDED_BY field
    without the mutex FAILS to compile, and the same TU with a MutexLock
    compiles clean (so the failure is the analysis, not the harness)."""
    if not _tsa_clang():
        pytest.skip("no clang++ with -Wthread-safety support installed")
    bad = _tsa_compile(_TSA_VIOLATION)
    assert bad.returncode != 0, "seeded GUARDED_BY violation compiled clean"
    assert "-Wthread-safety" in bad.stderr or "guarded by" in bad.stderr, \
        bad.stderr[-3000:]
    good = _tsa_compile(_TSA_CORRECT)
    assert good.returncode == 0, good.stderr[-3000:]


# ---------------------------------------------------------------------------
# native_lint (NL001-NL005) — the textual half of the gate; runs on every
# toolchain. Synthetic trees prove each rule is non-vacuous; the real tree
# must be clean (the dogfood assertion `make lint` enforces).
# ---------------------------------------------------------------------------

from determined_tpu.analysis import native_lint  # noqa: E402


def _tree(tmp_path, files):
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    return str(tmp_path)


class TestNativeLint:
    def test_real_tree_is_clean(self):
        assert native_lint.lint_native(REPO) == []

    def test_real_tree_escape_budget(self):
        assert native_lint.tsa_escape_count(REPO) <= \
            native_lint.MAX_TSA_ESCAPES

    def test_nl001_locked_without_requires(self, tmp_path):
        root = _tree(tmp_path, {"native/master/x.h": (
            "class M {\n"
            "  void grow_locked(int n);\n"
            "};\n")})
        probs = native_lint._check_locked_requires(root)
        assert len(probs) == 1 and "NL001" in probs[0] \
            and "grow_locked" in probs[0]

    def test_nl001_negative_with_requires(self, tmp_path):
        root = _tree(tmp_path, {"native/master/x.h": (
            "class M {\n"
            "  void grow_locked(int n) REQUIRES(mu_);\n"
            "};\n")})
        assert native_lint._check_locked_requires(root) == []

    def test_nl001_cc_free_function(self, tmp_path):
        root = _tree(tmp_path, {"native/agent/y.cc": (
            "static void settle_locked() {\n"
            "}\n"
            "void caller() {\n"
            "  settle_locked();\n"  # indented call site: not flagged
            "}\n")})
        probs = native_lint._check_locked_requires(root)
        assert len(probs) == 1 and "settle_locked" in probs[0]

    def test_nl002_unguarded_field(self, tmp_path):
        hdr = (
            "class M {\n"
            "  Mutex mu_;\n"
            "  int counter_;\n"
            "};\n")
        root = _tree(tmp_path, {
            "native/master/master.h": hdr,
            "native/master/rm.h": "// empty\n"})
        probs = native_lint._check_guarded_fields(root)
        assert any("NL002" in p and "counter_" in p for p in probs)

    def test_nl002_negative_guarded_or_justified(self, tmp_path):
        hdr = (
            "class M {\n"
            "  Mutex mu_;\n"
            "  int counter_ GUARDED_BY(mu_);\n"
            "  std::atomic<bool> running_{false};\n"
            "  int cfg_port_;  // not-guarded: set once before start()\n"
            "};\n"
            "class NoLock {\n"
            "  int free_field_;\n"  # class without a Mutex: no discipline
            "};\n")
        root = _tree(tmp_path, {
            "native/master/master.h": hdr,
            "native/master/rm.h": "// empty\n"})
        assert native_lint._check_guarded_fields(root) == []

    def test_nl003_unjustified_escape(self, tmp_path):
        root = _tree(tmp_path, {"native/master/z.cc": (
            "void weird() NO_THREAD_SAFETY_ANALYSIS {\n"
            "}\n")})
        probs, count = native_lint._check_tsa_escapes(root)
        assert count == 1
        assert len(probs) == 1 and "NL003" in probs[0]

    def test_nl003_justified_but_over_budget(self, tmp_path):
        body = ("// tsa: justified for the test\n"
                "void weird() NO_THREAD_SAFETY_ANALYSIS {}\n") * 4
        root = _tree(tmp_path, {"native/master/z.cc": body})
        probs, count = native_lint._check_tsa_escapes(root)
        assert count == 4
        assert len(probs) == 1 and "budget" in probs[0]

    def test_nl004_fault_registry_both_directions(self, tmp_path):
        files = {
            "native/master/m.cc": 'x = FAULT_POINT("a.b");\n',
            "native/common/faultpoint.cc": (
                '    {"a.b", "master", "x"},\n'
                '    {"stale.row", "master", "y"},\n'),
            "docs/chaos.md": "| `a.b` | x |\n| `ghost.point` | y |\n",
        }
        for rel in native_lint.PY_FAULT_SOURCES:
            files[rel] = "# nothing\n"
        probs = native_lint._check_fault_registry(_tree(tmp_path, files))
        assert any("stale.row" in p and "no FAULT_POINT call site" in p
                   for p in probs)
        assert any("ghost.point" in p and "stale row" in p for p in probs)
        assert any("stale.row" in p and "not documented" in p
                   for p in probs)

    def test_nl005_route_drift_both_directions(self, tmp_path):
        spec = {"paths": {"/api/v1/experiments": {},
                          "/api/v1/ghosts/{id}": {}}}
        import json as _json
        root = _tree(tmp_path, {
            "native/master/master.cc": (
                'if (root == "experiments") {}\n'
                'if (root == "agents") {}\n'),
            "proto/openapi.json": _json.dumps(spec),
        })
        probs = native_lint._check_routes(root)
        assert any("'agents'" in p and "absent from the OpenAPI" in p
                   for p in probs)
        assert any("'ghosts'" in p and "not dispatched" in p for p in probs)
