"""Observability: master /metrics endpoint + profiler utilization series.

Reference: internal/prom/det_state_metrics.go (master gauges) and the
profiler-metrics pipeline (SURVEY §5 asks for TPU utilization in it)."""

import urllib.error
import urllib.request

import pytest

from determined_tpu.core._profiler import PEAK_BF16_FLOPS, ProfilerContext
from determined_tpu.core._train import TrainContext
from tests.test_platform_e2e import Devcluster, native_binaries  # noqa: F401


class TestProfilerUtilization:
    def test_device_flops_util_math(self):
        train = TrainContext(None)
        p = ProfilerContext(train)
        p._peak = 197e12  # v5e chip peak (CPU test host detects none)
        p.set_flops_per_step(197e12 * 0.5, n_devices=1)  # half-peak model
        p.observe_steps(20, 10.0)  # 2 steps/sec
        m = p._utilization_window()
        assert m["steps_per_second"] == pytest.approx(2.0)
        assert m["device_flops_util"] == pytest.approx(1.0)  # 2 × half = peak
        # window resets after read
        assert p._utilization_window() == {}

    def test_multi_device_normalization(self):
        p = ProfilerContext(TrainContext(None))
        p._peak = 100.0
        p.set_flops_per_step(400.0, n_devices=8)  # global-step flops
        p.observe_steps(10, 10.0)  # 1 step/sec
        m = p._utilization_window()
        assert m["device_flops_util"] == pytest.approx(0.5)

    def test_no_flops_no_series(self):
        p = ProfilerContext(TrainContext(None))
        p._peak = 100.0
        p.observe_steps(5, 1.0)
        m = p._utilization_window()
        assert "device_flops_util" not in m
        assert m["steps_per_second"] == pytest.approx(5.0)

    def test_peak_table_covers_v5e(self):
        assert PEAK_BF16_FLOPS["TPU v5 lite"] == 197e12

    def test_input_pipeline_gauges(self):
        """DevicePrefetcher window sums flow through observe_input into the
        profiling series as per-batch means."""
        p = ProfilerContext(TrainContext(None))
        p.observe_input(40.0, 8.0, 6.0, 4)   # two flushes accumulate
        p.observe_input(20.0, 4.0, 2.0, 4)
        m = p._utilization_window()
        assert m["input_wait_ms"] == pytest.approx(7.5)   # 60/8
        assert m["h2d_ms"] == pytest.approx(1.5)          # 12/8
        assert m["prefetch_queue_depth"] == pytest.approx(1.0)  # 8/8
        # window resets; zero-batch observations are ignored
        p.observe_input(0.0, 0.0, 0.0, 0)
        assert p._utilization_window() == {}

    def test_trainer_feeds_profiler(self, tmp_path):
        """Trainer.fit(profile=True) reports a profiling metric series."""
        from determined_tpu import core
        from determined_tpu.train import Trainer
        from determined_tpu.train.trial import TrialContext
        from tests.test_trainer import TinyGPT2Trial

        class FlopsTrial(TinyGPT2Trial):
            def flops_per_step(self):
                return 1e9

        ctx = core.init(max_length=4, checkpoint_dir=str(tmp_path),
                        async_checkpointing=False)
        trainer = Trainer(FlopsTrial(TrialContext()), core_context=ctx)
        # make the collector tick fast enough for a short run
        trainer.fit(report_period=1, profile=True)
        ctx.profiler._collector is None or ctx.profiler.off()
        # observe_steps was fed; utilisation window accumulates between
        # collector ticks — read it directly
        assert ctx.profiler._flops_per_step == 1e9
        ctx.close()


def test_master_metrics_endpoint(tmp_path, native_binaries):  # noqa: F811
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    try:
        c.start_agent()
        token = c.login()
        # generate some API traffic
        c.api("GET", "/api/v1/agents", token=token)
        # unauthenticated scrape is rejected like every API route
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(c.master_url + "/metrics", timeout=10)
        assert ei.value.code == 401
        req = urllib.request.Request(
            c.master_url + "/metrics",
            headers={"Authorization": f"Bearer {token}"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            body = r.read().decode()
        assert "det_agents_alive 1" in body
        assert "det_slots_total 2" in body
        assert "det_slots_free 2" in body
        assert "det_scheduler_queue_depth 0" in body
        assert 'det_api_requests_total{code="200"}' in body
        assert "det_api_request_seconds_count" in body
    finally:
        c.stop()
