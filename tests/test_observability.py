"""Observability: trial lifecycle tracing + fleet Prometheus metrics
(docs/observability.md).

Reference: internal/prom/det_state_metrics.go (master gauges) and the
profiler-metrics pipeline (SURVEY §5 asks for TPU utilization in it).
Covers the Tracer span library, the master span ingest/read API, the
expanded master /metrics, the agent's own /metrics, the serve exposition,
the metric/span-name registry lint, and the profiler hardening
satellites."""

import json
import os
import time
import urllib.error
import urllib.request

import pytest

from determined_tpu.common import faultpoint
from determined_tpu.common import metric_names
from determined_tpu.common.trace import Tracer, now_us, render_waterfall
from determined_tpu.core._profiler import PEAK_BF16_FLOPS, ProfilerContext
from determined_tpu.core._train import TrainContext
from tests.test_platform_e2e import (  # noqa: F401
    Devcluster,
    native_binaries,
    _create_experiment,
    _experiment_config,
    _free_port,
    _wait_experiment,
)


class TestProfilerUtilization:
    def test_device_flops_util_math(self):
        train = TrainContext(None)
        p = ProfilerContext(train)
        p._peak = 197e12  # v5e chip peak (CPU test host detects none)
        p.set_flops_per_step(197e12 * 0.5, n_devices=1)  # half-peak model
        p.observe_steps(20, 10.0)  # 2 steps/sec
        m = p._utilization_window()
        assert m["steps_per_second"] == pytest.approx(2.0)
        assert m["device_flops_util"] == pytest.approx(1.0)  # 2 × half = peak
        # window resets after read
        assert p._utilization_window() == {}

    def test_multi_device_normalization(self):
        p = ProfilerContext(TrainContext(None))
        p._peak = 100.0
        p.set_flops_per_step(400.0, n_devices=8)  # global-step flops
        p.observe_steps(10, 10.0)  # 1 step/sec
        m = p._utilization_window()
        assert m["device_flops_util"] == pytest.approx(0.5)

    def test_no_flops_no_series(self):
        p = ProfilerContext(TrainContext(None))
        p._peak = 100.0
        p.observe_steps(5, 1.0)
        m = p._utilization_window()
        assert "device_flops_util" not in m
        assert m["steps_per_second"] == pytest.approx(5.0)

    def test_peak_table_covers_v5e(self):
        assert PEAK_BF16_FLOPS["TPU v5 lite"] == 197e12

    def test_input_pipeline_gauges(self):
        """DevicePrefetcher window sums flow through observe_input into the
        profiling series as per-batch means."""
        p = ProfilerContext(TrainContext(None))
        p.observe_input(40.0, 8.0, 6.0, 4)   # two flushes accumulate
        p.observe_input(20.0, 4.0, 2.0, 4)
        m = p._utilization_window()
        assert m["input_wait_ms"] == pytest.approx(7.5)   # 60/8
        assert m["h2d_ms"] == pytest.approx(1.5)          # 12/8
        assert m["prefetch_queue_depth"] == pytest.approx(1.0)  # 8/8
        # window resets; zero-batch observations are ignored
        p.observe_input(0.0, 0.0, 0.0, 0)
        assert p._utilization_window() == {}

    def test_trainer_feeds_profiler(self, tmp_path):
        """Trainer.fit(profile=True) reports a profiling metric series."""
        from determined_tpu import core
        from determined_tpu.train import Trainer
        from determined_tpu.train.trial import TrialContext
        from tests.test_trainer import TinyGPT2Trial

        class FlopsTrial(TinyGPT2Trial):
            def flops_per_step(self):
                return 1e9

        ctx = core.init(max_length=4, checkpoint_dir=str(tmp_path),
                        async_checkpointing=False)
        trainer = Trainer(FlopsTrial(TrialContext()), core_context=ctx)
        # make the collector tick fast enough for a short run
        trainer.fit(report_period=1, profile=True)
        ctx.profiler._collector is None or ctx.profiler.off()
        # observe_steps was fed; utilisation window accumulates between
        # collector ticks — read it directly
        assert ctx.profiler._flops_per_step == 1e9
        ctx.close()


def test_master_metrics_endpoint(tmp_path, native_binaries):  # noqa: F811
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    try:
        c.start_agent()
        token = c.login()
        # generate some API traffic
        c.api("GET", "/api/v1/agents", token=token)
        # unauthenticated scrape is rejected like every API route
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(c.master_url + "/metrics", timeout=10)
        assert ei.value.code == 401
        req = urllib.request.Request(
            c.master_url + "/metrics",
            headers={"Authorization": f"Bearer {token}"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            body = r.read().decode()
        assert "det_agents_alive 1" in body
        assert "det_slots_total 2" in body
        assert "det_slots_free 2" in body
        assert "det_scheduler_queue_depth 0" in body
        assert 'det_api_requests_total{code="200"}' in body
        assert "det_api_request_seconds_count" in body
    finally:
        c.stop()


# ---------------------------------------------------------------------------
# Tracer span library (determined_tpu/common/trace.py).
# ---------------------------------------------------------------------------


class TestTracer:
    def test_span_nesting_parentage_and_attrs(self):
        t = Tracer(enabled=True)
        with t.span("harness.validate", step=3) as outer:
            with t.span("harness.checkpoint.save") as inner:
                assert inner.parent == outer.span_id
            assert outer.parent == t.root_span_id
        t.flush()
        spans = {s["name"]: s for s in t.local_spans}
        # Children buffer before parents (closed inner-first); parentage
        # is by id, not order.
        assert spans["harness.checkpoint.save"]["parent"] == \
            spans["harness.validate"]["span_id"]
        assert spans["harness.validate"]["attrs"] == {"step": 3}
        for s in spans.values():
            assert s["end_us"] >= s["start_us"] > 0
            assert s["trace_id"] == t.trace_id

    def test_emit_defaults_parent_to_root(self):
        t = Tracer(enabled=True)
        t0 = now_us()
        sp = t.emit("harness.compile", t0, t0 + 5, {"executable": "x"})
        assert sp.parent == t.trace_id  # root span id == trace id
        t.flush()
        assert t.local_spans[0]["start_us"] == t0
        assert t.local_spans[0]["end_us"] == t0 + 5

    def test_exception_records_span_with_error_attr(self):
        t = Tracer(enabled=True)
        with pytest.raises(ValueError):
            with t.span("harness.restore"):
                raise ValueError("boom")
        t.flush()
        assert t.local_spans[0]["attrs"]["error"] == "ValueError"
        # The parent stack unwound: a new span parents to root again.
        with t.span("harness.validate") as sp:
            assert sp.parent == t.root_span_id

    def test_flush_batches_and_empties_buffer(self):
        t = Tracer(enabled=True)
        assert t.flush() == 0  # empty flush is free
        t.emit("a.b", 1, 2)
        t.emit("c.d", 2, 3)
        assert t.pending() == 2
        assert t.flush() == 2
        assert t.pending() == 0
        assert len(t.local_spans) == 2

    def test_trace_off_env_disables_emission(self, monkeypatch):
        monkeypatch.setenv("DET_TRACE_OFF", "1")
        t = Tracer()
        assert not t.enabled
        with t.span("harness.validate") as sp:
            assert sp is None
        assert t.emit("a.b", 1, 2) is None
        assert t.flush() == 0 and t.local_spans == []

    def test_trace_id_from_env(self, monkeypatch):
        monkeypatch.setenv("DET_TRACE_ID", "cafe0123deadbeef")
        t = Tracer()
        assert t.trace_id == "cafe0123deadbeef"
        assert t.root_span_id == "cafe0123deadbeef"

    def test_span_drop_fault_point(self):
        """docs/chaos.md trace.span.drop: the sink eats the batch, the
        caller never sees an error (trials survive span-sink loss)."""
        t = Tracer(enabled=True)
        t.emit("a.b", 1, 2)
        faultpoint.arm("trace.span.drop", "drop", count=1)
        try:
            assert t.flush() == 0
        finally:
            faultpoint.disarm_all()
        assert t.dropped == 1 and t.local_spans == []
        # Next batch flows again.
        t.emit("c.d", 1, 2)
        assert t.flush() == 1

    def test_sink_failure_drops_batch_not_the_trial(self):
        class DeadSession:
            def post(self, *a, **kw):
                raise ConnectionError("sink down")

        t = Tracer(session=DeadSession(), trial_id=7, enabled=True)
        t.emit("a.b", 1, 2)
        assert t.flush() == 0  # logged + dropped, no raise
        assert t.dropped == 1 and t.pending() == 0

    def test_flush_posts_idempotent_batch(self):
        calls = []

        class FakeSession:
            def post(self, path, body=None, idempotent=False, **kw):
                calls.append((path, body, idempotent))

        t = Tracer(session=FakeSession(), trial_id=42, enabled=True)
        t.emit("a.b", 1, 2)
        t.emit("c.d", 3, 4)
        assert t.flush() == 2
        (path, body, idempotent), = calls
        assert path == "/api/v1/trials/42/spans"
        assert idempotent is True
        assert [s["name"] for s in body["spans"]] == ["a.b", "c.d"]

    def test_render_waterfall(self):
        t = Tracer(enabled=True)
        t0 = now_us()
        t.emit("trial.queue_wait", t0, t0 + 100_000)
        t.emit("agent.container_start", t0 + 100_000, t0 + 150_000)
        t.flush()
        out = render_waterfall(t.local_spans)
        assert "trial.queue_wait" in out and "agent.container_start" in out
        assert "100.0" in out  # queue wait duration in ms
        assert render_waterfall([]) == "(no spans)"


# ---------------------------------------------------------------------------
# Metric/span name registry + lint (the make-lint drift gate).
# ---------------------------------------------------------------------------


class TestMetricRegistry:
    def test_registry_self_check_clean(self):
        assert metric_names.check_registry() == []

    def test_repo_emitters_match_registry(self):
        """The actual repo sources and the registry agree in BOTH
        directions — this is the same check `make lint` runs."""
        from determined_tpu.analysis import metric_lint

        assert metric_lint.lint_registry() == []

    def test_naming_rules_catch_violations(self, monkeypatch):
        monkeypatch.setitem(metric_names.MASTER_METRICS,
                            "det_badCounter", ("counter", "x"))
        monkeypatch.setitem(metric_names.MASTER_METRICS,
                            "det_events_lost", ("counter", "x"))
        monkeypatch.setitem(metric_names.MASTER_METRICS,
                            "det_queue_wait", ("gauge", "no unit"))
        problems = "\n".join(metric_names.check_registry())
        assert "det_badCounter" in problems          # not snake_case
        assert "det_events_lost" in problems         # counter w/o _total
        assert "det_queue_wait" in problems          # measured, no unit

    def test_scan_finds_metric_literals_only_in_strings(self):
        from determined_tpu.analysis.metric_lint import _emitted_metrics

        text = '''
        // comment about det_state_metrics.go stays out
        out << "# TYPE det_agents_alive gauge\\n";
        out << "det_api_request_seconds_bucket{route=\\"x\\"} 1\\n";
        f(".det_status");  // filenames stay out
        '''
        assert _emitted_metrics(text) == {"det_agents_alive",
                                          "det_api_request_seconds"}

    def test_scan_finds_span_call_sites(self):
        from determined_tpu.analysis.metric_lint import _emitted_spans

        py = 'with core.tracer.span(\n        "harness.restore", x=1):\n' \
             '    tracer.emit("harness.compile", t0, t1)\n' \
             '    self._span("harness.checkpoint.save", t0)\n'
        assert _emitted_spans("a.py", py) == {
            "harness.restore", "harness.compile", "harness.checkpoint.save"}
        cc = 'trace::make_span(\n    trial->trace_id, "trial.queue_wait",\n'
        assert _emitted_spans("a.cc", cc) == {"trial.queue_wait"}

    def test_unregistered_emission_is_flagged(self, tmp_path):
        """A fresh gauge added to an emitter without a registry row fails
        the lint (the drift this satellite exists to prevent)."""
        from determined_tpu.analysis import metric_lint

        root = tmp_path
        for rel in metric_lint.METRIC_SOURCES + metric_lint.SPAN_SOURCES:
            p = root / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(open(os.path.join(
                metric_lint.REPO_ROOT, rel)).read())
        agent = root / "native/agent/main.cc"
        agent.write_text(agent.read_text() +
                         '\n// new\nconst char* x = "det_agent_new_thing";\n')
        problems = metric_lint.lint_registry(str(root))
        assert any("det_agent_new_thing" in p for p in problems)


# ---------------------------------------------------------------------------
# Serving exposition (determined_tpu/serve/http.py /metrics).
# ---------------------------------------------------------------------------


def _parse_prom(text: str):
    """Tiny Prometheus text-format parser: 'name{labels}' -> float, plus a
    {name -> type} map from # TYPE lines. Raises on malformed lines."""
    values, types = {}, {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split()
            types[name] = mtype
            continue
        if line.startswith("#"):
            continue
        series, value = line.rsplit(" ", 1)
        values[series] = float(value)
    return values, types


def test_serve_prometheus_exposition():
    from determined_tpu.serve.http import prometheus_exposition

    stats = {"queue_depth": 3, "active": 5, "draining": True,
             "completed": 17, "generated_tokens": 123,
             "kv_blocks": {"free_blocks": 9, "num_blocks": 16}}
    values, types = _parse_prom(prometheus_exposition(stats))
    assert values["det_serve_queue_depth"] == 3
    assert values["det_serve_active_requests"] == 5
    assert values["det_serve_draining"] == 1
    assert values["det_serve_kv_blocks_free"] == 9
    assert values["det_serve_kv_blocks_total"] == 16
    assert values["det_serve_requests_total"] == 17
    assert values["det_serve_tokens_total"] == 123
    assert types["det_serve_tokens_total"] == "counter"


# ---------------------------------------------------------------------------
# Profiler hardening satellites (core/_profiler.py).
# ---------------------------------------------------------------------------


class TestProfilerHardening:
    def test_off_joins_collector_bounded(self):
        """The collector's stop event no longer shadows
        threading.Thread._stop (join() used to blow up), and off() joins
        the thread instead of orphaning it."""
        p = ProfilerContext(TrainContext(None))
        p.on(sampling_interval=0.05)
        collector = p._collector
        assert collector.is_alive()
        t0 = time.monotonic()
        p.off()
        assert time.monotonic() - t0 < 5.0
        assert not collector.is_alive()
        assert p._collector is None
        p.off()  # idempotent

    def test_trace_reentry_refused_without_wedging(self, monkeypatch):
        import jax

        calls = {"start": 0, "stop": 0}
        monkeypatch.setattr(
            jax.profiler, "start_trace",
            lambda d: calls.__setitem__("start", calls["start"] + 1))
        monkeypatch.setattr(
            jax.profiler, "stop_trace",
            lambda: calls.__setitem__("stop", calls["stop"] + 1))
        p = ProfilerContext(TrainContext(None), tensorboard_dir="/tmp/tb-t")
        with p.trace():
            with p.trace():  # nested: runs untraced, does NOT re-start
                pass
            assert calls == {"start": 1, "stop": 0}
        assert calls == {"start": 1, "stop": 1}
        # Usable again afterwards.
        with p.trace():
            pass
        assert calls == {"start": 2, "stop": 2}

    def test_trace_start_failure_logs_not_raises(self, monkeypatch):
        import jax

        def boom(d):
            raise RuntimeError("profiler unavailable")

        stopped = []
        monkeypatch.setattr(jax.profiler, "start_trace", boom)
        monkeypatch.setattr(jax.profiler, "stop_trace",
                            lambda: stopped.append(1))
        p = ProfilerContext(TrainContext(None), tensorboard_dir="/tmp/tb-t")
        ran = []
        with p.trace():
            ran.append(1)  # body still runs
        assert ran == [1]
        assert stopped == []  # never started -> never stopped
        assert p._trace_active is False

    def test_trace_stop_failure_clears_active_flag(self, monkeypatch):
        import jax

        monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)

        def boom():
            raise RuntimeError("wedged")

        monkeypatch.setattr(jax.profiler, "stop_trace", boom)
        p = ProfilerContext(TrainContext(None), tensorboard_dir="/tmp/tb-t")
        with p.trace():
            pass  # stop failure is swallowed
        assert p._trace_active is False


# ---------------------------------------------------------------------------
# Trainer span emission (local mode; real jitted steps).
# ---------------------------------------------------------------------------


def test_trainer_emits_lifecycle_spans(tmp_path):
    """A local fit lands compile + checkpoint save/commit + validate spans
    in the tracer buffer, with root parentage and zero per-step residue
    (the compile wrapper uninstalls itself)."""
    from determined_tpu import core
    from determined_tpu.train import Trainer
    from determined_tpu.train.trial import TrialContext
    from tests.test_trainer import TinyGPT2Trial

    ctx = core.init(max_length=6, checkpoint_dir=str(tmp_path),
                    async_checkpointing=False)
    trainer = Trainer(TinyGPT2Trial(TrialContext()), core_context=ctx)
    trainer.fit(report_period=2, checkpoint_period=3, validation_period=3)
    ctx.close()
    names = [s["name"] for s in ctx.tracer.local_spans]
    assert "harness.compile" in names
    assert "harness.checkpoint.save" in names
    assert "harness.checkpoint.commit" in names
    by_name = {s["name"]: s for s in ctx.tracer.local_spans}
    compiles = [s for s in ctx.tracer.local_spans
                if s["name"] == "harness.compile"]
    compile_span = next(s for s in compiles
                        if s["attrs"]["executable"] == "train_step")
    assert compile_span["parent"] == ctx.tracer.root_span_id
    # Exactly one compile span per executable: the wrapper uninstalled.
    assert names.count("harness.compile") == len(
        {s["attrs"]["executable"] for s in ctx.tracer.local_spans
         if s["name"] == "harness.compile"})
    # Non-overlapping phase accounting: the checkpoint save follows the
    # compile (first step) and the commit follows its save.
    save = by_name["harness.checkpoint.save"]
    commit = by_name["harness.checkpoint.commit"]
    assert save["start_us"] >= compile_span["end_us"]
    assert commit["start_us"] >= save["end_us"]
    assert save["attrs"]["storage_id"] == commit["attrs"]["storage_id"]


def test_trainer_fit_unchanged_with_tracing_off(tmp_path, monkeypatch):
    """DET_TRACE_OFF=1: no spans, and fit still runs to completion — the
    bench A/B switch must not change training behavior."""
    monkeypatch.setenv("DET_TRACE_OFF", "1")
    from determined_tpu import core
    from determined_tpu.train import Trainer
    from determined_tpu.train.trial import TrialContext
    from tests.test_trainer import TinyGPT2Trial

    ctx = core.init(max_length=4, checkpoint_dir=str(tmp_path),
                    async_checkpointing=False)
    trainer = Trainer(TinyGPT2Trial(TrialContext()), core_context=ctx)
    state = trainer.fit(report_period=2)
    assert state is not None
    ctx.close()
    assert ctx.tracer.local_spans == []


# ---------------------------------------------------------------------------
# Master span ingest/read API + expanded /metrics (devcluster, master-only).
# ---------------------------------------------------------------------------


@pytest.fixture()
def master_only(tmp_path, native_binaries):  # noqa: F811
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    yield c
    c.stop()


def _unmanaged_trial(cluster, token):
    eid = cluster.api("POST", "/api/v1/experiments",
                      {"unmanaged": True, "config": {"name": "obs"}},
                      token=token)["id"]
    tid = cluster.api("POST", f"/api/v1/experiments/{eid}/trials",
                      {"hparams": {}}, token=token)["id"]
    return eid, tid


def _mk_span(name, start, end, span_id=None, parent=""):
    import uuid

    return {"trace_id": "t1", "span_id": span_id or uuid.uuid4().hex[:16],
            "parent": parent, "name": name, "start_us": start,
            "end_us": end, "attrs": {"k": "v"}}


def test_span_ingest_roundtrip_dedupe_and_validation(master_only):
    c = master_only
    token = c.login()
    _, tid = _unmanaged_trial(c, token)

    s1 = _mk_span("agent.container_start", 1000, 2000)
    s2 = _mk_span("harness.compile", 2000, 5000, parent=s1["span_id"])
    r = c.api("POST", f"/api/v1/trials/{tid}/spans",
              {"spans": [s1, s2]}, token=token)
    assert r["ingested"] == 2

    # Row-level dedupe: replaying the same batch inserts nothing new.
    c.api("POST", f"/api/v1/trials/{tid}/spans", {"spans": [s1, s2]},
          token=token)
    trace = c.api("GET", f"/api/v1/trials/{tid}/trace", token=token)
    assert len(trace["spans"]) == 2
    # Ordered by start time; parentage preserved.
    assert [s["name"] for s in trace["spans"]] == [
        "agent.container_start", "harness.compile"]
    assert trace["spans"][1]["parent"] == s1["span_id"]
    assert trace["spans"][0]["attrs"] == {"k": "v"}

    # Malformed entries are skipped, the batch survives.
    r = c.api("POST", f"/api/v1/trials/{tid}/spans",
              {"spans": [{"name": "", "span_id": "x"},
                         _mk_span("agent.log_drain", 6000, 7000)]},
              token=token)
    assert r["ingested"] == 1

    # Contract errors.
    with pytest.raises(urllib.error.HTTPError) as ei:
        c.api("POST", f"/api/v1/trials/{tid}/spans", {"nope": 1},
              token=token)
    assert ei.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        c.api("GET", "/api/v1/trials/999999/trace", token=token)
    assert ei.value.code == 404


def _scrape(cluster, token):
    req = urllib.request.Request(
        cluster.master_url + "/metrics",
        headers={"Authorization": f"Bearer {token}"})
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.headers.get("Content-Type"), r.read().decode()


def test_master_metrics_exposition_and_counters_increment(master_only):
    """The satellite: exposition content-type, ApiStats counters actually
    move across an API call, and every new gauge parses with a tiny
    text-format parser."""
    c = master_only
    token = c.login()

    ctype, text = _scrape(c, token)
    assert ctype.startswith("text/plain")
    assert "version=0.0.4" in ctype
    values, types = _parse_prom(text)

    # New fleet gauges present and typed.
    for name in ("det_slots_allocated", "det_slots_draining",
                 "det_stream_backlog_events"):
        assert values.get(name) is not None, name
        assert types[name] == "gauge"
    for name in ("det_preemptions_total", "det_resizes_total",
                 "det_trial_requeues_total", "det_idempotency_replays_total",
                 "det_trial_spans_ingested_total"):
        assert name in values and types[name] == "counter"
    assert types["det_scheduler_queue_wait_seconds"] == "histogram"
    assert types["det_api_request_seconds"] == "histogram"

    before = values['det_api_requests_total{code="200"}']
    c.api("GET", "/api/v1/agents", token=token)
    values2, _ = _parse_prom(_scrape(c, token)[1])
    assert values2['det_api_requests_total{code="200"}'] > before
    # Route-family latency histogram saw the agents call; +Inf bucket ==
    # series count (cumulative-bucket invariant).
    inf = values2['det_api_request_seconds_bucket{route="agents",le="+Inf"}']
    cnt = values2['det_api_request_seconds_count{route="agents"}']
    assert inf == cnt >= 1


def test_span_ingest_bumps_counter_and_replay_cache_metric(master_only):
    c = master_only
    token = c.login()
    _, tid = _unmanaged_trial(c, token)

    values0, _ = _parse_prom(_scrape(c, token)[1])

    # Idempotency-keyed batch, sent twice with the SAME key: the second is
    # answered from the replay cache — no double-insert, replay counter up.
    body = json.dumps({"spans": [_mk_span("harness.validate", 1, 2)]}).encode()
    key = "obs-test-key-1"
    for _ in range(2):
        req = urllib.request.Request(
            c.master_url + f"/api/v1/trials/{tid}/spans", data=body,
            headers={"Content-Type": "application/json",
                     "Authorization": f"Bearer {token}",
                     "X-Idempotency-Key": key},
            method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            resp = json.loads(r.read().decode())
            assert resp["ingested"] == 1
            replayed = r.headers.get("x-idempotent-replay")
    assert replayed == "true"

    values1, _ = _parse_prom(_scrape(c, token)[1])
    assert values1["det_trial_spans_ingested_total"] == \
        values0["det_trial_spans_ingested_total"] + 1  # replay not re-applied
    assert values1["det_idempotency_replays_total"] >= \
        values0["det_idempotency_replays_total"] + 1
    trace = c.api("GET", f"/api/v1/trials/{tid}/trace", token=token)
    assert len(trace["spans"]) == 1


def test_agent_metrics_endpoint(tmp_path, native_binaries):  # noqa: F811
    """Every agent serves its own /metrics (docs/observability.md): task
    states, log backlog, drain state — parseable Prometheus text."""
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    port = _free_port()
    try:
        c.start_agent(extra_env={"DET_AGENT_METRICS_PORT": str(port)})
        # The agent binds /metrics just after registering; registration
        # visibility can beat the bind by a moment — retry briefly.
        deadline = time.time() + 15
        while True:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                    assert r.headers.get("Content-Type").startswith(
                        "text/plain")
                    values, types = _parse_prom(r.read().decode())
                break
            except (urllib.error.URLError, ConnectionError):
                if time.time() > deadline:
                    raise
                time.sleep(0.2)
        assert values["det_agent_slots"] == 2
        assert values['det_agent_tasks{state="running"}'] == 0
        assert values["det_agent_log_backlog_lines"] == 0
        assert values["det_agent_draining"] == 0
        assert values["det_agent_uptime_seconds"] >= 0
        assert types["det_agent_tasks"] == "gauge"
        # /healthz for scrapers' liveness checks.
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            assert json.loads(r.read().decode())["status"] == "ok"
    finally:
        c.stop()


# ---------------------------------------------------------------------------
# Acceptance e2e (slow): the full waterfall off a real devcluster trial,
# then the emergency-checkpoint span under a notice-file drain.
# ---------------------------------------------------------------------------


def _span_map(trace):
    out = {}
    for s in trace["spans"]:
        out.setdefault(s["name"], []).append(s)
    return out


@pytest.mark.slow
def test_trace_e2e_full_waterfall(tmp_path, native_binaries):  # noqa: F811
    """A devcluster trial yields a complete waterfall: queue-wait,
    container-start, compile, ≥1 checkpoint commit — correct parentage,
    non-overlapping phase accounting — and `det trial trace` renders it."""
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    try:
        c.start_agent()
        config = _experiment_config(
            tmp_path,
            searcher={"name": "single", "metric": "val_loss",
                      "max_length": {"batches": 12}},
            extra={"entrypoint": "python3 trace_train.py"},
        )
        eid, token = _create_experiment(c, config)
        _wait_experiment(c, eid, token, timeout=180.0)
        trials = c.api("GET", f"/api/v1/experiments/{eid}/trials",
                       token=token)["trials"]
        tid = trials[0]["id"]
        trace = c.api("GET", f"/api/v1/trials/{tid}/trace", token=token)
        spans = _span_map(trace)

        for required in ("trial.lifecycle", "trial.queue_wait",
                         "agent.image_setup", "agent.container_start",
                         "harness.compile", "harness.checkpoint.save",
                         "harness.checkpoint.commit", "agent.log_drain"):
            assert required in spans, (required, sorted(spans))

        # Parentage: the root is span_id == trace_id and closed; every
        # other span's parent resolves to a known span.
        root = spans["trial.lifecycle"][0]
        assert root["span_id"] == trace["trace_id"]
        assert root["end_us"] > root["start_us"] > 0
        ids = {s["span_id"] for s in trace["spans"]}
        for s in trace["spans"]:
            if s["name"] == "trial.lifecycle":
                continue
            assert s["parent"] in ids, (s["name"], s["parent"])

        # Non-overlapping phase accounting along the lifecycle chain:
        # queue wait -> image setup -> container start -> compile ->
        # first checkpoint save -> its commit.
        qw = spans["trial.queue_wait"][0]
        img = spans["agent.image_setup"][0]
        cs = spans["agent.container_start"][0]
        compile_sp = spans["harness.compile"][0]
        save = spans["harness.checkpoint.save"][0]
        commit = spans["harness.checkpoint.commit"][0]
        assert qw["end_us"] <= img["start_us"]
        assert img["end_us"] <= cs["start_us"]
        assert cs["start_us"] <= compile_sp["start_us"]
        assert compile_sp["end_us"] <= save["start_us"]
        assert save["end_us"] <= commit["start_us"]
        for s in (qw, img, cs, compile_sp, save, commit):
            assert s["end_us"] >= s["start_us"] > 0, s["name"]

        # The CLI waterfall renders it (the operator-facing surface).
        from determined_tpu.common.api import Session
        from determined_tpu.common.trace import render_waterfall

        session = Session(c.master_url, token)
        resp = session.get(f"/api/v1/trials/{tid}/trace")
        out = render_waterfall(resp["spans"])
        assert "trial.queue_wait" in out and "harness.compile" in out
    finally:
        c.stop()


@pytest.mark.slow
def test_trace_e2e_emergency_span_under_drain(tmp_path, native_binaries):  # noqa: F811
    """Under a notice-file drain the emergency-checkpoint span lands on
    the trace (flushed before the exit), and the restarted run adds a
    harness.restore span on the survivor."""
    c = Devcluster(str(tmp_path), native_binaries, slots=1)
    c.start_master()
    notice_files = {}
    try:
        for agent_id in ("obs-a", "obs-b"):
            nf = os.path.join(str(tmp_path), f"notice-{agent_id}.json")
            notice_files[agent_id] = nf
            c.start_agent(agent_id,
                          extra_env={"DET_AGENT_NOTICE_FILE": nf})
        config = _experiment_config(
            tmp_path,
            searcher={"name": "single", "metric": "val_loss",
                      "max_length": {"batches": 300}},
            extra={"max_restarts": 2,
                   "entrypoint": "python3 spot_train.py"},
        )
        config["environment"] = {"SPOT_STEP_SLEEP": "0.1"}
        eid, token = _create_experiment(c, config)

        # Mid-run: find the victim agent.
        deadline = time.time() + 120
        trial, victim = None, None
        while time.time() < deadline:
            trials = c.api("GET", f"/api/v1/experiments/{eid}/trials",
                           token=token)["trials"]
            if trials:
                rows = c.api(
                    "GET",
                    f"/api/v1/trials/{trials[0]['id']}/metrics?group=training",
                    token=token)["metrics"]
                if len(rows) >= 5:
                    trial = trials[0]
                    jobs = [j for j in c.api("GET", "/api/v1/job-queues",
                                             token=token)["jobs"]
                            if j.get("experiment_id") == eid]
                    alloc = c.api(
                        "GET",
                        f"/api/v1/allocations/{jobs[0]['allocation_id']}",
                        token=token)["allocation"]
                    victim = alloc["resources"][0]["agent_id"]
                    break
            time.sleep(0.5)
        assert trial is not None and victim in ("obs-a", "obs-b")

        with open(notice_files[victim], "w") as f:
            json.dump({"deadline_seconds": 30,
                       "reason": "spot_preemption"}, f)

        _wait_experiment(c, eid, token, timeout=240.0)
        trials = c.api("GET", f"/api/v1/experiments/{eid}/trials",
                       token=token)["trials"]
        assert trials[0]["restarts"] >= 1

        trace = c.api("GET", f"/api/v1/trials/{trial['id']}/trace",
                      token=token)
        spans = _span_map(trace)
        assert "harness.checkpoint.emergency" in spans, sorted(spans)
        em = spans["harness.checkpoint.emergency"][0]
        assert em["attrs"].get("attempted") in (True, 1, "true", True)
        # The emergency window nests the phase-2 commit under it.
        commits = spans.get("harness.checkpoint.commit", [])
        assert any(s["parent"] == em["span_id"] for s in commits), (
            "no commit span nested under the emergency window")
        # The restarted run restored on the survivor.
        assert "harness.restore" in spans, sorted(spans)
        restore = spans["harness.restore"][-1]
        assert restore["attrs"].get("restored")
        # Two container runs -> two queue_wait / container_start spans.
        assert len(spans["trial.queue_wait"]) >= 2
        assert len(spans["agent.container_start"]) >= 2
    finally:
        c.stop()
