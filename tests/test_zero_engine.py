"""ZeroOneEngine: TPU-native ZeRO-1 optimizer-state sharding
(determined_tpu/pytorch/zero.py), unit + 2-process e2e.

Reference semantics: deepspeed ZeRO stage 1 as configured by
examples/deepspeed/gpt_neox/zero1.yaml — partitioned optimizer state,
full-parameter replicas, averaged gradients.
"""

import json
import os
import socket
import subprocess
import sys

import torch

from determined_tpu.pytorch import ZeroOneEngine
from determined_tpu.pytorch.zero import _partition

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mlp(seed=0):
    torch.manual_seed(seed)
    return torch.nn.Sequential(
        torch.nn.Linear(8, 16), torch.nn.ReLU(), torch.nn.Linear(16, 1))


class TestSingleProcess:
    def test_matches_plain_optimizer(self):
        """World size 1: ZeRO-1 degenerates to plain grad accumulation —
        final params must match a hand-rolled AdamW loop exactly."""
        torch.manual_seed(0)
        x = torch.randn(32, 8)
        y = torch.randn(32, 1)

        ref = _mlp()
        ref_opt = torch.optim.AdamW(ref.parameters(), lr=1e-2)
        for step in range(4):
            for micro in range(2):
                i = (step * 2 + micro) * 4
                loss = torch.nn.functional.mse_loss(ref(x[i:i + 4]), y[i:i + 4])
                (loss / 2).backward()
            ref_opt.step()
            ref_opt.zero_grad(set_to_none=True)

        eng = ZeroOneEngine(
            _mlp(), lambda p: torch.optim.AdamW(p, lr=1e-2),
            micro_batch_size=4, gradient_accumulation=2)
        for step in range(4):
            for micro in range(2):
                i = (step * 2 + micro) * 4
                loss = torch.nn.functional.mse_loss(
                    eng(x[i:i + 4]), y[i:i + 4])
                eng.backward(loss)
                eng.step()

        for pr, pe in zip(ref.parameters(), eng.module.parameters()):
            assert torch.allclose(pr, pe, atol=1e-7), (pr, pe)

    def test_save_load_roundtrip(self, tmp_path):
        eng = ZeroOneEngine(
            _mlp(), lambda p: torch.optim.AdamW(p, lr=1e-2),
            micro_batch_size=4, gradient_accumulation=1)
        x, y = torch.randn(8, 8), torch.randn(8, 1)
        for _ in range(3):
            loss = torch.nn.functional.mse_loss(eng(x), y)
            eng.backward(loss)
            eng.step()
        eng.save_checkpoint(str(tmp_path), tag="t")

        eng2 = ZeroOneEngine(
            _mlp(seed=1), lambda p: torch.optim.AdamW(p, lr=1e-2),
            micro_batch_size=4, gradient_accumulation=1)
        eng2.load_checkpoint(str(tmp_path), tag="t")
        for a, b in zip(eng.module.parameters(), eng2.module.parameters()):
            assert torch.equal(a, b)
        assert eng2.optimizer_state_numel() == eng.optimizer_state_numel()

    def test_mixed_dtype_grads_bucketed_separately(self):
        """bf16 + fp32 params in one model: the flat buckets must group by
        dtype or torch.cat dies. Driven with a duck-typed dist (identity
        all_reduce / broadcast) so no process group is needed."""

        class FakeDist:
            def __init__(self):
                self.reduced = []
                self.broadcasts = []

            def all_reduce(self, t):
                self.reduced.append(t.dtype)

            def broadcast(self, t, src):
                self.broadcasts.append((t.dtype, src))

        model = torch.nn.Sequential(
            torch.nn.Linear(4, 4), torch.nn.Linear(4, 1))
        model[1].to(torch.bfloat16)
        eng = ZeroOneEngine(
            model, lambda p: torch.optim.SGD(p, lr=0.1),
            micro_batch_size=1, gradient_accumulation=1)
        eng._world = 2  # force the collective paths
        for p in eng._params:
            p.grad = torch.zeros_like(p)
        fake = FakeDist()
        eng._allreduce_grads(fake)
        assert set(fake.reduced) == {torch.float32, torch.bfloat16}
        eng._rebroadcast_params(fake)
        assert {d for d, _ in fake.broadcasts} == \
            {torch.float32, torch.bfloat16}
        # the flat-bucket reason: fewer collectives than tensors
        assert len(fake.broadcasts) < len(eng._params)

    def test_partition_balance_and_determinism(self):
        params = [torch.nn.Parameter(torch.zeros(n))
                  for n in (100, 90, 80, 10, 10, 10)]
        owners = _partition(list(params), 2)
        assert owners == _partition(list(params), 2)  # deterministic
        loads = [0, 0]
        for p, o in zip(params, owners):
            loads[o] += p.numel()
        assert abs(loads[0] - loads[1]) <= 90, loads  # roughly balanced
        assert set(owners) == {0, 1}


def test_zero1_two_process_e2e(tmp_path):
    """Real 2-process gloo run through the launch layer: partitioned
    optimizer state, owner-rebroadcast parameter sync, engine-sharded
    save/load (asserts live in the fixture)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
        DET_TORCH_MASTER_PORT=str(port),
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable, "-m", "determined_tpu.launch.torch_distributed",
         "--nproc-per-node", "2", "--",
         sys.executable,
         os.path.join(REPO, "tests", "fixtures", "torch_dist",
                      "train_zero1.py"),
         str(tmp_path)],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
    reports = {}
    for rank in (0, 1):
        with open(tmp_path / f"zero_rank{rank}.json") as f:
            reports[rank] = json.load(f)
    assert reports[0]["steps"] == reports[1]["steps"] == 4
    # each rank holds a real, non-trivial share of the optimizer state
    assert reports[0]["opt_state_numel"] > 0
    assert reports[1]["opt_state_numel"] > 0
    # chief-only platform reporting
    assert reports[0]["n_checkpoints"] >= 1
    assert reports[1]["n_checkpoints"] == 0
