"""Webhook shipping on experiment state change (reference
internal/webhooks/shipper.go): registered URLs get the event POST,
filtered by each webhook's triggers."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tests.test_platform_e2e import (  # noqa: F401
    Devcluster,
    _create_experiment,
    _experiment_config,
    _wait_experiment,
    native_binaries,
)


class Sink:
    def __init__(self):
        self.events = []
        outer = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                outer.events.append(
                    (self.path, json.loads(self.rfile.read(n) or b"{}")))
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

        self.srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.url = f"http://127.0.0.1:{self.srv.server_address[1]}"
        threading.Thread(target=self.srv.serve_forever, daemon=True).start()

    def stop(self):
        self.srv.shutdown()


@pytest.fixture()
def cluster(tmp_path, native_binaries):  # noqa: F811
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    c.start_agent()
    yield c
    c.stop()


def test_webhooks_fire_filtered_by_triggers(cluster, tmp_path):
    sink = Sink()
    try:
        admin = cluster.login("admin")
        # all states; COMPLETED-only; ERROR-only (must stay silent)
        cluster.api("POST", "/api/v1/webhooks",
                    {"url": sink.url + "/all"}, token=admin)
        cluster.api("POST", "/api/v1/webhooks",
                    {"url": sink.url + "/done", "triggers": ["COMPLETED"]},
                    token=admin)
        cluster.api("POST", "/api/v1/webhooks",
                    {"url": sink.url + "/err", "triggers": ["ERROR"]},
                    token=admin)

        eid, token = _create_experiment(cluster, _experiment_config(tmp_path))
        _wait_experiment(cluster, eid, token)

        deadline = time.time() + 20
        while time.time() < deadline and len(sink.events) < 2:
            time.sleep(0.2)
        paths = sorted(p for p, _ in sink.events)
        assert paths == ["/all", "/done"], sink.events
        for _, ev in sink.events:
            assert ev["type"] == "EXPERIMENT_STATE_CHANGE"
            assert ev["experiment_id"] == eid
            assert ev["state"] == "COMPLETED"
    finally:
        sink.stop()
