"""`det deploy local` e2e, including the --tls self-signed bootstrap:
up → verified HTTPS API → down drains over the same TLS channel."""

import json
import os
import ssl
import subprocess
import sys
import time
import urllib.error
import urllib.request

from tests.test_platform_e2e import native_binaries  # noqa: F401

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _cli(home, *args, timeout=120):
    env = dict(
        os.environ,
        HOME=str(home),
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return subprocess.run(
        [sys.executable, "-m", "determined_tpu.cli", *args],
        capture_output=True, text=True, env=env, timeout=timeout)


def test_deploy_local_tls_lifecycle(tmp_path, native_binaries):  # noqa: F811
    home = tmp_path / "home"
    home.mkdir()
    port = _free_port()
    r = _cli(home, "deploy", "local", "up", "--port", str(port),
             "--agents", "1", "--slots", "1", "--tls")
    try:
        assert r.returncode == 0, r.stdout + r.stderr
        assert "TLS on" in r.stdout, r.stdout
        cert = os.path.join(str(home),
                            ".local/share/determined_tpu/master-cert.pem")
        assert os.path.exists(cert)

        # HTTPS answers when verified against the generated cert...
        ctx = ssl.create_default_context(cafile=cert)
        ctx.check_hostname = False
        with urllib.request.urlopen(f"https://127.0.0.1:{port}/api/v1/master",
                                    timeout=10, context=ctx) as resp:
            assert json.loads(resp.read())["cluster_name"]
        # ...and plaintext is refused.
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/api/v1/master",
                                   timeout=5)
            raise AssertionError("plaintext served on a TLS master")
        except (urllib.error.URLError, ConnectionError, OSError):
            pass

        # The agent (TLS-pinned) registers.
        deadline = time.time() + 30
        while time.time() < deadline:
            req = urllib.request.Request(
                f"https://127.0.0.1:{port}/api/v1/agents",
                headers={"Authorization": "Bearer " + _login(port, ctx)})
            with urllib.request.urlopen(req, timeout=10, context=ctx) as resp:
                agents = json.loads(resp.read())["agents"]
            if any(a["alive"] for a in agents):
                break
            time.sleep(0.5)
        else:
            raise TimeoutError("agent never registered over TLS")
    finally:
        r = _cli(home, "deploy", "local", "down")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "cluster stopped" in r.stdout


def _login(port, ctx):
    from determined_tpu.common.api import salted_hash

    req = urllib.request.Request(
        f"https://127.0.0.1:{port}/api/v1/auth/login",
        data=json.dumps({"username": "determined",
                         "password": salted_hash("determined", "")}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=10, context=ctx) as resp:
        return json.loads(resp.read())["token"]
