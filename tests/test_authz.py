"""Authorization enforcement e2e (VERDICT r3 #1).

Reference semantics: master/internal/rbac/rbac.go (roles + workspace-scoped
assignments), internal/usergroup/ (groups), authz plumbing in
api_experiment.go / api_user.go. The TPU-native model: base role per user
(admin|user|viewer) + workspace-scoped grants (viewer|editor|admin) to users
or groups. These tests are the negative-path suite round 3 lacked: every
check asserts a 403/401 actually comes back.
"""

import contextlib
import urllib.error

import pytest

from tests.test_platform_e2e import (  # noqa: F401
    Devcluster,
    _experiment_config,
    native_binaries,
)


@pytest.fixture()
def cluster(tmp_path, native_binaries):  # noqa: F811
    # Master only — authz checks don't need a running agent.
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    yield c
    c.stop()


@contextlib.contextmanager
def expect_status(code):
    try:
        yield
    except urllib.error.HTTPError as e:
        assert e.code == code, f"expected HTTP {code}, got {e.code}: {e.read()}"
    else:
        raise AssertionError(f"expected HTTP {code}, request succeeded")


def _mk_user(cluster, admin_token, name, role="user", password=""):
    cluster.api("POST", "/api/v1/users",
                {"username": name, "role": role, "password": password},
                token=admin_token)
    return cluster.login(name, password)


def _paused_experiment(cluster, token, tmp_path, name="authz-exp"):
    config = _experiment_config(tmp_path)
    config["name"] = name
    resp = cluster.api(
        "POST", "/api/v1/experiments",
        {"config": config, "model_definition": "", "activate": False},
        token=token,
    )
    return resp["id"]


def test_user_management_is_admin_only(cluster):
    admin = cluster.login("admin")
    user = cluster.login("determined")

    # Non-admin cannot mint users (round-3 hole: anyone could mint admins).
    with expect_status(403):
        cluster.api("POST", "/api/v1/users",
                    {"username": "mallory", "role": "admin"}, token=user)
    # Admin can.
    alice = _mk_user(cluster, admin, "alice")
    assert cluster.api("GET", "/api/v1/me", token=alice)["user"]["role"] == "user"

    # Non-admin cannot change someone else's role or deactivate them.
    users = cluster.api("GET", "/api/v1/users", token=user)["users"]
    alice_id = next(u["id"] for u in users if u["username"] == "alice")
    with expect_status(403):
        cluster.api("PATCH", f"/api/v1/users/{alice_id}", {"role": "admin"},
                    token=user)
    with expect_status(403):
        cluster.api("PATCH", f"/api/v1/users/{alice_id}", {"active": False},
                    token=user)
    # Self password change is allowed without admin.
    me = cluster.api("GET", "/api/v1/me", token=alice)["user"]
    cluster.api("PATCH", f"/api/v1/users/{me['id']}", {"password": "s3cret"},
                token=alice)
    assert cluster.login("alice", "s3cret")

    # Deactivation revokes sessions immediately.
    cluster.api("PATCH", f"/api/v1/users/{alice_id}", {"active": False},
                token=admin)
    with expect_status(401):
        cluster.api("GET", "/api/v1/me", token=alice)
    with expect_status(403):
        cluster.login("alice", "s3cret")


def test_non_owner_cannot_touch_experiment(cluster, tmp_path):
    admin = cluster.login("admin")
    alice = _mk_user(cluster, admin, "alice2")
    bob = _mk_user(cluster, admin, "bob2")

    eid = _paused_experiment(cluster, alice, tmp_path)

    # Bob (plain user, not owner, no grants) gets 403 on every mutation.
    for verb in ("activate", "pause", "cancel", "kill", "archive"):
        with expect_status(403):
            cluster.api("POST", f"/api/v1/experiments/{eid}/{verb}",
                        token=bob)
    with expect_status(403):
        cluster.api("DELETE", f"/api/v1/experiments/{eid}", token=bob)
    # Reads stay open.
    exp = cluster.api("GET", f"/api/v1/experiments/{eid}", token=bob)
    assert exp["experiment"]["id"] == eid

    # Owner and admin can mutate.
    cluster.api("POST", f"/api/v1/experiments/{eid}/kill", token=alice)
    eid2 = _paused_experiment(cluster, alice, tmp_path, name="authz-exp-2")
    cluster.api("POST", f"/api/v1/experiments/{eid2}/kill", token=admin)


def test_viewer_is_read_only(cluster, tmp_path):
    admin = cluster.login("admin")
    owner = cluster.login("determined")
    eve = _mk_user(cluster, admin, "eve", role="viewer")

    eid = _paused_experiment(cluster, owner, tmp_path)

    # Viewer can read everything...
    assert cluster.api("GET", "/api/v1/experiments", token=eve)["experiments"]
    assert cluster.api("GET", "/api/v1/workspaces", token=eve)["workspaces"]
    # ...but can create/mutate nothing.
    cfg = _experiment_config(tmp_path)
    with expect_status(403):
        cluster.api("POST", "/api/v1/experiments",
                    {"config": cfg, "model_definition": "", "activate": False},
                    token=eve)
    with expect_status(403):
        cluster.api("POST", f"/api/v1/experiments/{eid}/kill", token=eve)
    with expect_status(403):
        cluster.api("POST", "/api/v1/workspaces", {"name": "eve-ws"}, token=eve)
    with expect_status(403):
        cluster.api("POST", "/api/v1/commands",
                    {"config": {"entrypoint": "true"}}, token=eve)
    with expect_status(403):
        cluster.api("POST", "/api/v1/checkpoints", {"uuid": "x"}, token=eve)
    with expect_status(403):
        cluster.api("POST", "/api/v1/task/logs",
                    {"logs": [{"task_id": "t", "log": "x"}]}, token=eve)


def test_workspace_scoped_grant_raises_rights(cluster, tmp_path):
    admin = cluster.login("admin")
    alice = _mk_user(cluster, admin, "alice3")
    bob = _mk_user(cluster, admin, "bob3")
    bob_id = next(u["id"] for u in
                  cluster.api("GET", "/api/v1/users", token=admin)["users"]
                  if u["username"] == "bob3")

    eid = _paused_experiment(cluster, alice, tmp_path)
    with expect_status(403):
        cluster.api("POST", f"/api/v1/experiments/{eid}/kill", token=bob)

    # Grant bob editor on workspace 1 (Uncategorized — where project 1 lives):
    # now he can kill alice's experiment there.
    grant = cluster.api("POST", "/api/v1/rbac/assignments",
                        {"role": "editor", "user_id": bob_id,
                         "workspace_id": 1}, token=admin)
    cluster.api("POST", f"/api/v1/experiments/{eid}/kill", token=bob)

    # Revoking the grant restores the 403.
    cluster.api("DELETE", f"/api/v1/rbac/assignments/{grant['id']}",
                token=admin)
    eid2 = _paused_experiment(cluster, alice, tmp_path, name="authz-ws-2")
    with expect_status(403):
        cluster.api("POST", f"/api/v1/experiments/{eid2}/kill", token=bob)

    # Non-admin cannot self-grant.
    with expect_status(403):
        cluster.api("POST", "/api/v1/rbac/assignments",
                    {"role": "admin", "user_id": bob_id}, token=bob)


def test_group_grant_raises_viewer_to_editor(cluster, tmp_path):
    admin = cluster.login("admin")
    eve = _mk_user(cluster, admin, "eve2", role="viewer")
    eve_id = next(u["id"] for u in
                  cluster.api("GET", "/api/v1/users", token=admin)["users"]
                  if u["username"] == "eve2")

    cfg = _experiment_config(tmp_path)
    with expect_status(403):
        cluster.api("POST", "/api/v1/experiments",
                    {"config": cfg, "model_definition": "", "activate": False},
                    token=eve)

    # Group management is admin-only.
    with expect_status(403):
        cluster.api("POST", "/api/v1/groups", {"name": "nope"}, token=eve)

    gid = cluster.api("POST", "/api/v1/groups", {"name": "researchers"},
                      token=admin)["id"]
    cluster.api("POST", f"/api/v1/groups/{gid}/members", {"user_id": eve_id},
                token=admin)
    cluster.api("POST", "/api/v1/rbac/assignments",
                {"role": "editor", "group_id": gid, "workspace_id": 1},
                token=admin)

    # Viewer-by-base-role, editor-by-group-grant: create now succeeds.
    resp = cluster.api("POST", "/api/v1/experiments",
                       {"config": cfg, "model_definition": "",
                        "activate": False}, token=eve)
    cluster.api("POST", f"/api/v1/experiments/{resp['id']}/kill", token=eve)

    # Removing membership drops the grant.
    cluster.api("DELETE", f"/api/v1/groups/{gid}/members/{eve_id}",
                token=admin)
    with expect_status(403):
        cluster.api("POST", "/api/v1/experiments",
                    {"config": cfg, "model_definition": "", "activate": False},
                    token=eve)


def test_admin_gates_on_cluster_ops(cluster):
    user = cluster.login("determined")
    with expect_status(403):
        cluster.api("POST", "/api/v1/job-queues/reorder",
                    {"allocation_id": "x", "ahead_of": "y"}, token=user)
    with expect_status(403):
        cluster.api("POST", "/api/v1/master/cleanup_logs", {"days": 1},
                    token=user)
    with expect_status(403):
        cluster.api("POST", "/api/v1/agents/agent-0/disable", token=user)
    with expect_status(403):
        cluster.api("POST", "/api/v1/webhooks",
                    {"url": "http://example.invalid/hook"}, token=user)


def test_agent_drain_admin_path(cluster):
    """Admin can disable/enable agent slots (drain); 404 on unknown agent."""
    admin = cluster.login("admin")
    with expect_status(404):
        cluster.api("POST", "/api/v1/agents/no-such-agent/disable", token=admin)


def test_agent_drain_blocks_scheduling(tmp_path, native_binaries):
    """Drained agents take no new work; enable releases the queue
    (reference api_agent.go DisableAgent semantics)."""
    import time

    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    c.start_agent()
    try:
        admin = c.login("admin")
        user = c.login()
        c.api("POST", "/api/v1/agents/agent-0/disable", token=admin)
        agents = c.api("GET", "/api/v1/agents", token=admin)["agents"]
        assert all(not s["enabled"] for s in agents[0]["slots"])

        tid = c.api("POST", "/api/v1/commands",
                    {"config": {"entrypoint": "echo drained",
                                "resources": {"slots": 1}}},
                    token=user)["id"]
        time.sleep(2.0)  # several scheduler ticks
        task = c.api("GET", f"/api/v1/commands/{tid}", token=user)["task"]
        assert task.get("allocation_state") in (None, "PENDING"), task

        c.api("POST", "/api/v1/agents/agent-0/enable", token=admin)
        deadline = time.time() + 60
        while time.time() < deadline:
            task = c.api("GET", f"/api/v1/commands/{tid}", token=user)["task"]
            if task["state"] == "COMPLETED":
                break
            time.sleep(0.5)
        assert task["state"] == "COMPLETED", task
    finally:
        c.stop()


def test_agent_protocol_requires_agent_role(cluster):
    """A normal user must not be able to register a fake agent: the actions
    stream hands out task environments including per-owner session tokens,
    so this would be privilege escalation (reference isolates the surface
    on the master↔agent websocket)."""
    user = cluster.login("determined")
    with expect_status(403):
        cluster.api("POST", "/api/v1/agents/register",
                    {"id": "evil-agent", "slots": [{"id": 0, "type": "cpu"}]},
                    token=user)
    with expect_status(403):
        cluster.api("GET", "/api/v1/agents/agent-0/actions?timeout_seconds=0",
                    token=user)
    # Password login to the service account is refused — it is token-only.
    with expect_status(403):
        cluster.login("determined-agent")
    # The master-minted bootstrap token (written next to the db) works.
    with open(cluster.db_path + ".agent_token") as f:
        agent_tok = f.read().strip()
    resp = cluster.api("POST", "/api/v1/agents/register",
                       {"id": "test-agent",
                        "slots": [{"id": 0, "type": "cpu"}]},
                       token=agent_tok)
    assert resp["agent_id"] == "test-agent"


def test_cross_user_checkpoint_and_logs_protected(cluster, tmp_path):
    """Bob cannot reset alice's trial resume pointer via checkpoint report,
    flip her checkpoints to DELETED, or forge lines into her task logs."""
    import time

    admin = cluster.login("admin")
    alice = _mk_user(cluster, admin, "alice5")
    bob = _mk_user(cluster, admin, "bob5")
    eid = _paused_experiment(cluster, alice, tmp_path)
    # Activate so the searcher creates the trial row (no agent is running,
    # so the allocation just queues — fine for authz checks).
    cluster.api("POST", f"/api/v1/experiments/{eid}/activate", token=alice)
    trials = []
    deadline = time.time() + 20
    while time.time() < deadline and not trials:
        trials = cluster.api("GET", f"/api/v1/experiments/{eid}/trials",
                             token=alice)["trials"]
        time.sleep(0.2)
    assert trials, "searcher should create a trial row"
    tid = trials[0]["id"]

    with expect_status(403):
        cluster.api("POST", "/api/v1/checkpoints",
                    {"uuid": "bogus", "trial_id": tid}, token=bob)
    cluster.api("POST", "/api/v1/checkpoints",
                {"uuid": "real-ck", "trial_id": tid}, token=alice)
    with expect_status(403):
        cluster.api("PATCH", "/api/v1/checkpoints",
                    {"checkpoints": [{"uuid": "real-ck", "state": "DELETED"}]},
                    token=bob)
    # Forged logs into alice's trial task stream → 403 for bob; the agent
    # service account may ship anything.
    with expect_status(403):
        cluster.api("POST", "/api/v1/task/logs",
                    {"logs": [{"task_id": f"trial-{tid}",
                               "log": "FATAL forged"}]}, token=bob)
    with open(cluster.db_path + ".agent_token") as f:
        agent_tok = f.read().strip()
    cluster.api("POST", "/api/v1/task/logs",
                {"logs": [{"task_id": f"trial-{tid}", "log": "real line"}]},
                token=agent_tok)
    cluster.api("POST", f"/api/v1/experiments/{eid}/kill", token=alice)


def test_ntsc_kill_requires_ownership(cluster):
    admin = cluster.login("admin")
    alice = _mk_user(cluster, admin, "alice4")
    bob = _mk_user(cluster, admin, "bob4")
    resp = cluster.api("POST", "/api/v1/commands",
                       {"config": {"entrypoint": "sleep 60"}}, token=alice)
    with expect_status(403):
        cluster.api("POST", f"/api/v1/commands/{resp['id']}/kill", token=bob)
    cluster.api("POST", f"/api/v1/commands/{resp['id']}/kill", token=alice)
