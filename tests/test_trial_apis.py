"""Trial-API compatibility layers in local mode (no master).

Mirrors the reference's local-training trial tests
(harness/tests/experiment/{pytorch,keras}/ + test_local.py): tiny synthetic
models driven through the full Trainer loop — train, validate, report,
checkpoint, restore.
"""

import os

import numpy as np
import pytest

from determined_tpu import core


# ---------------------------------------------------------------------------
# PyTorchTrial
# ---------------------------------------------------------------------------


def _make_torch_trial(hparams):
    import torch

    from determined_tpu.pytorch import DataLoader, PyTorchTrial, PyTorchTrialContext

    class RegressionSet(torch.utils.data.Dataset):
        def __init__(self, n=256):
            g = torch.Generator().manual_seed(0)
            self.x = torch.randn(n, 4, generator=g)
            self.y = self.x @ torch.tensor([1.0, -2.0, 3.0, 0.5]).unsqueeze(1)

        def __len__(self):
            return len(self.x)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

    class LinearTrial(PyTorchTrial):
        def __init__(self, context: PyTorchTrialContext):
            super().__init__(context)
            self.model = context.wrap_model(torch.nn.Linear(4, 1))
            self.opt = context.wrap_optimizer(
                torch.optim.SGD(self.model.parameters(),
                                lr=context.get_hparam("lr"))
            )
            self.loss_fn = torch.nn.MSELoss()

        def build_training_data_loader(self):
            return DataLoader(RegressionSet(), batch_size=32, shuffle=True)

        def build_validation_data_loader(self):
            return DataLoader(RegressionSet(64), batch_size=32)

        def train_batch(self, batch, epoch_idx, batch_idx):
            x, y = batch
            loss = self.loss_fn(self.model(x), y)
            self.context.backward(loss)
            self.context.step_optimizer(self.opt)
            return {"loss": loss.item()}

        def evaluate_batch(self, batch, batch_idx):
            x, y = batch
            return {"val_loss": self.loss_fn(self.model(x), y).item()}

    ctx = PyTorchTrialContext(hparams=hparams)
    return LinearTrial(ctx)


def test_pytorch_trial_local(tmp_path):
    from determined_tpu.pytorch import Trainer

    ctx = core.init(max_length=30, checkpoint_dir=str(tmp_path))
    trial = _make_torch_trial({"lr": 0.1})
    trial.context._core = ctx
    steps = Trainer(trial, core_context=ctx).fit(report_period=10)
    assert steps == 30
    train_metrics = ctx.train.local_training_metrics
    assert train_metrics[-1]["metrics"]["loss"] < train_metrics[0]["metrics"]["loss"]
    val = ctx.train.local_validation_metrics
    assert val and val[-1]["metrics"]["val_loss"] < 1.0
    assert ctx.checkpoint.local_reported, "final checkpoint must be reported"
    ctx.close()


def test_pytorch_trial_restore(tmp_path):
    import torch

    from determined_tpu.pytorch import Trainer

    ctx = core.init(max_length=10, checkpoint_dir=str(tmp_path))
    trial = _make_torch_trial({"lr": 0.1})
    trial.context._core = ctx
    Trainer(trial, core_context=ctx).fit()
    storage_id = ctx.checkpoint.local_reported[-1]["uuid"]
    want = trial.model.weight.detach().clone()
    ctx.close()

    # Fresh process-equivalent: new trial restores weights + step count.
    os.environ["DET_LATEST_CHECKPOINT"] = storage_id
    try:
        ctx2 = core.init(max_length=10, checkpoint_dir=str(tmp_path))
        trial2 = _make_torch_trial({"lr": 0.1})
        trial2.context._core = ctx2
        trainer2 = Trainer(trial2, core_context=ctx2)
        # restore path reads DET_LATEST_CHECKPOINT via latest_checkpoint —
        # local mode has no ClusterInfo, so call _restore via the public fit
        # after injecting the id:
        ctx2.checkpoint.download(storage_id, str(tmp_path / "manual"))
        state = torch.load(tmp_path / "manual" / "state.pt", weights_only=False)
        trial2.model.load_state_dict(state["models"][0])
        assert torch.allclose(trial2.model.weight, want)
        ctx2.close()
    finally:
        os.environ.pop("DET_LATEST_CHECKPOINT", None)


# ---------------------------------------------------------------------------
# KerasTrial (Keras 3, JAX backend)
# ---------------------------------------------------------------------------


def test_keras_trial_local(tmp_path):
    keras = pytest.importorskip("keras")
    from determined_tpu.keras import KerasTrial, KerasTrialContext, Trainer

    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 4)).astype("float32")
    w = np.array([[1.0], [-2.0], [3.0], [0.5]], dtype="float32")
    y = x @ w

    class LinearKeras(KerasTrial):
        def build_model(self):
            model = keras.Sequential([keras.layers.Dense(1, use_bias=False)])
            model.compile(optimizer=keras.optimizers.SGD(0.1), loss="mse")
            return model

        def build_training_data(self):
            return (x, y)

        def build_validation_data(self):
            return (x[:64], y[:64])

    ctx = core.init(max_length=20, checkpoint_dir=str(tmp_path))
    trial = LinearKeras(KerasTrialContext(ctx, hparams={"global_batch_size": 32}))
    steps = Trainer(trial, core_context=ctx).fit()
    assert steps == 20
    val = ctx.train.local_validation_metrics
    assert val and val[-1]["metrics"]["loss"] < 1.0
    assert ctx.checkpoint.local_reported
    # model.keras artifact exists in storage
    sid = ctx.checkpoint.local_reported[-1]["uuid"]
    assert os.path.exists(os.path.join(str(tmp_path), sid, "model.keras"))
    ctx.close()


# ---------------------------------------------------------------------------
# HuggingFace DetCallback
# ---------------------------------------------------------------------------


def test_hf_detcallback_local(tmp_path):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from determined_tpu.integrations.transformers import DetCallback

    config = transformers.GPT2Config(
        vocab_size=128, n_positions=32, n_embd=32, n_layer=1, n_head=2
    )
    model = transformers.GPT2LMHeadModel(config)

    class Toks(torch.utils.data.Dataset):
        def __init__(self, n=32):
            g = torch.Generator().manual_seed(0)
            self.data = torch.randint(0, 128, (n, 16), generator=g)

        def __len__(self):
            return len(self.data)

        def __getitem__(self, i):
            return {"input_ids": self.data[i], "labels": self.data[i]}

    ctx = core.init(max_length=4, checkpoint_dir=str(tmp_path))
    args = transformers.TrainingArguments(
        output_dir=str(tmp_path / "hf"),
        max_steps=16,
        per_device_train_batch_size=4,
        logging_steps=2,
        eval_strategy="no",
        save_strategy="no",
        report_to=[],
        use_cpu=True,
    )
    trainer = transformers.Trainer(
        model=model,
        args=args,
        train_dataset=Toks(),
        eval_dataset=Toks(8),
        callbacks=[DetCallback(ctx, args)],
    )
    trainer.train()
    # searcher op (max_length=4) must stop training before HF's max_steps=16
    assert trainer.state.global_step <= 6
    assert ctx.train.local_training_metrics, "training metrics reported"
    assert ctx.train.local_validation_metrics, "eval metrics reported"
    assert ctx.searcher.completed_metrics, "searcher op completed"
    ctx.close()
