"""Spot-capacity survival: termination notices, deadline-budgeted
emergency checkpoints, and agent drain → reschedule
(docs/cluster-ops.md "Preemption & drain lifecycle",
docs/checkpointing.md "Emergency checkpoints").

Fast tier-1 tests cover the deadline parsing + backoff/join fixes in the
preemption watcher, the emergency-save budget math, the Trainer's
emergency/skip paths in local mode (bit-identical restore), and the
master's DRAINING lifecycle (notice route, scheduler exclusion, admin
enable/disable) through the native master harness. The `-m slow` e2e
drives a real 2-agent devcluster through a mid-trial spot notice:
emergency COMPLETED checkpoint inside the deadline, DRAINING agent takes
no new work, trial resumes on the survivor.
"""

import json
import os
import sqlite3
import sys
import threading
import time

import jax
import numpy as np
import pytest

from test_platform_e2e import (  # noqa: F401  (fixture re-export)
    FIXTURES,
    Devcluster,
    _create_experiment,
    _experiment_config,
    _wait_experiment,
    native_binaries,
)

from determined_tpu import core
from determined_tpu.core._preempt import PreemptContext, _PreemptionWatcher
from determined_tpu.train import Trainer
from determined_tpu.train.health import PreemptionConfig
from determined_tpu.train.trial import TrialContext

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tests", "fixtures", "selfheal"))

from trial_def import LinearTrial  # noqa: E402


# ---------------------------------------------------------------------------
# Watcher: deadline/reason parsing, falsy-response backoff, bounded join.
# ---------------------------------------------------------------------------


class _ScriptedSession:
    """Fake Session: yields `responses` in order, repeating the last one
    (callables are invoked; exceptions are raised)."""

    def __init__(self, responses):
        self._responses = list(responses)
        self.calls = 0
        self.posts = []

    def get(self, path, params=None, timeout=None):
        self.calls += 1
        r = self._responses[min(self.calls - 1, len(self._responses) - 1)]
        if callable(r):
            r = r()
        if isinstance(r, Exception):
            raise r
        return r

    def post(self, path, body=None, **kwargs):
        self.posts.append(path)
        return {}


def _wait_for(cond, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def test_watcher_parses_deadline_and_reason():
    sess = _ScriptedSession([
        {"preempt": False},
        {"preempt": True, "deadline_seconds": 12.5,
         "reason": "spot_preemption"},
    ])
    ctx = PreemptContext(sess, allocation_id="a1")
    try:
        assert _wait_for(lambda: ctx.should_preempt(auto_ack=False))
        remaining = ctx.preemption_deadline()
        assert remaining is not None and 10.0 < remaining <= 12.5
        assert ctx.preemption_reason() == "spot_preemption"
        # the deadline counts DOWN between calls
        time.sleep(0.05)
        assert ctx.preemption_deadline() < remaining
    finally:
        ctx.close()


def test_watcher_without_deadline_is_unbounded():
    sess = _ScriptedSession([{"preempt": True}])
    ctx = PreemptContext(sess, allocation_id="a1")
    try:
        assert _wait_for(lambda: ctx.should_preempt(auto_ack=False))
        assert ctx.preemption_deadline() is None
    finally:
        ctx.close()


def test_watcher_garbage_deadline_treated_as_unbounded():
    sess = _ScriptedSession([
        {"preempt": True, "deadline_seconds": "soon"}])
    ctx = PreemptContext(sess, allocation_id="a1")
    try:
        assert _wait_for(lambda: ctx.should_preempt(auto_ack=False))
        assert ctx.preemption_deadline() is None
    finally:
        ctx.close()


def test_watcher_backs_off_on_falsy_responses():
    """Satellite: a successful-but-falsy response (master restarting
    behind a proxy, 404 body → None) must not hot-loop the poll."""
    sess = _ScriptedSession([None])
    w = _PreemptionWatcher(sess, "a1", backoff_base=0.05, backoff_cap=0.2)
    w.start()
    time.sleep(0.8)
    w.close()
    # Backoff schedule 0.05, 0.1, 0.2, 0.2... → a handful of calls in
    # 0.8s. A zero-delay hot loop would make thousands.
    assert 2 <= sess.calls <= 20, sess.calls
    assert not w.is_alive()


def test_watcher_backs_off_on_exceptions_capped():
    sess = _ScriptedSession([ConnectionError("down")])
    w = _PreemptionWatcher(sess, "a1", backoff_base=0.05, backoff_cap=0.2)
    w.start()
    time.sleep(0.8)
    w.close()
    assert 2 <= sess.calls <= 20, sess.calls
    assert not w.is_alive()


def test_watcher_long_poll_false_repolls_without_backoff():
    """A well-formed {"preempt": false} is the long-poll timing out — the
    re-poll must be immediate (that IS the protocol), not backed off."""
    sess = _ScriptedSession([{"preempt": False}] * 30 + [{"preempt": True}])
    w = _PreemptionWatcher(sess, "a1", backoff_base=0.5)
    t0 = time.monotonic()
    w.start()
    assert _wait_for(lambda: w.preempted, timeout=2.0)
    assert time.monotonic() - t0 < 1.0, "long-poll returns were backed off"
    assert sess.calls == 31
    w.close()


def test_watcher_close_joins_thread_no_orphans():
    """Satellite: close() joins (bounded) so the threading.enumerate()
    orphan assertions hold for the watcher too."""
    sess = _ScriptedSession([{"preempt": False}])
    ctx = PreemptContext(sess, allocation_id="a1")
    assert any(t.name == "preemption-watcher" for t in threading.enumerate())
    ctx.close()
    assert not any(
        t.name == "preemption-watcher" and t.is_alive()
        for t in threading.enumerate())


def test_force_deadline_local_mode():
    ctx = PreemptContext(None)
    assert ctx.preemption_deadline() is None
    ctx.force(deadline=30.0)
    assert ctx.should_preempt()
    d = ctx.preemption_deadline()
    assert d is not None and 29.0 < d <= 30.0


# ---------------------------------------------------------------------------
# Budget math (PreemptionConfig).
# ---------------------------------------------------------------------------


def test_budget_no_deadline_always_saves():
    assert PreemptionConfig().should_attempt_save(None, None)
    assert PreemptionConfig().should_attempt_save(None, 1e9)


def test_budget_no_estimate_is_optimistic():
    # No observed save cost yet: attempt — a blown budget leaves only a
    # PARTIAL torso that lineage fallback skips, never a corrupt restore.
    assert PreemptionConfig().should_attempt_save(30.0, None)


def test_budget_estimate_fits():
    cfg = PreemptionConfig(budget_safety_factor=1.5, budget_margin_sec=2.0)
    # 10s estimate * 1.5 = 15s <= 30 - 2 → attempt
    assert cfg.should_attempt_save(30.0, 10_000.0)


def test_budget_estimate_does_not_fit():
    cfg = PreemptionConfig(budget_safety_factor=1.5, budget_margin_sec=2.0)
    # 10s estimate * 1.5 = 15s > 15 - 2 → skip
    assert not cfg.should_attempt_save(15.0, 10_000.0)


def test_budget_margin_reserved():
    cfg = PreemptionConfig(budget_safety_factor=1.0, budget_margin_sec=5.0)
    assert not cfg.should_attempt_save(5.0, 1.0)  # margin eats the window
    assert not cfg.should_attempt_save(4.0, None)


def test_budget_disabled_never_saves():
    cfg = PreemptionConfig(emergency_checkpoint=False)
    assert not cfg.should_attempt_save(1e9, 1.0)
    assert not cfg.should_attempt_save(None, None)


def test_preemption_config_resolution_precedence():
    class T:
        preemption = {"budget_margin_sec": 7.0}

    cfg = PreemptionConfig.resolve(
        T(), {"preemption": {"budget_margin_sec": 1.0}})
    assert cfg.budget_margin_sec == 7.0  # trial attribute wins
    cfg = PreemptionConfig.resolve(
        None, {"preemption": {"emergency_checkpoint": False}})
    assert not cfg.emergency_checkpoint
    assert PreemptionConfig.resolve(None, None) == PreemptionConfig()
    # bare bool == emergency_checkpoint switch
    assert not PreemptionConfig.from_block(False).emergency_checkpoint
    # floors applied
    assert PreemptionConfig.from_block(
        {"budget_safety_factor": 0.1}).budget_safety_factor == 1.0


# ---------------------------------------------------------------------------
# Trainer: emergency checkpoint / budget-exhausted skip (local mode).
# ---------------------------------------------------------------------------


def _local_core(tmp_path, max_length):
    return core.init(
        max_length=max_length,
        checkpoint_dir=str(tmp_path / "ckpts"),
        async_checkpointing=False,
    )


def _tree_equal(a, b) -> bool:
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))


def _grace_report(ctx):
    rows = [m for m in ctx.train.local_training_metrics
            if "preemption_grace_used_ms" in m["metrics"]]
    assert rows, "preemption_grace_used_ms never reported"
    return rows[-1]["metrics"]


class _ForcingTrial(LinearTrial):
    """LinearTrial whose data stream raises the (forced) preemption with a
    deadline mid-run — the deterministic stand-in for the notice landing
    between two steps."""

    def __init__(self, tctx, on_batch, action):
        super().__init__(tctx)
        self._on_batch = on_batch
        self._action = action

    def build_training_data(self):
        rng = np.random.default_rng(7)
        for i in range(256):
            if i == self._on_batch:
                self._action()
            yield {"x": rng.normal(size=(8, 4)).astype(np.float32)}


def test_trainer_emergency_checkpoint_commits_within_deadline(tmp_path):
    """Deadline preemption with room in the budget: the Trainer saves
    out-of-band, the COMMIT lands before exit (not in the epilogue), the
    grace metric is reported, and a fresh process restores the emergency
    checkpoint bit-identically."""
    ctx = _local_core(tmp_path, max_length=64)
    trial = _ForcingTrial(
        TrialContext(), on_batch=5,
        action=lambda: ctx.preempt.force(deadline=60.0))
    trainer = Trainer(trial, core_context=ctx)
    state = trainer.fit(report_period=1, preempt_period=1)
    step = int(jax.device_get(state.step))
    assert step == 6, "should have stopped at the first poll past batch 5"

    ck = tmp_path / "ckpts" / f"trial0-step{step}"
    assert (ck / "COMMIT").exists() and (ck / "manifest.json").exists(), (
        "emergency checkpoint must be fully committed, not a torso")
    metrics = _grace_report(ctx)
    assert metrics["preemption_emergency_checkpoint"] == 1.0
    assert metrics["preemption_grace_used_ms"] >= 0.0
    ctx.close()

    # bit-identical resume in a fresh context
    ctx2 = _local_core(tmp_path, max_length=64)
    trainer2 = Trainer(LinearTrial(TrialContext()), core_context=ctx2)
    trainer2._build(seed=0)
    restored = trainer2._restore(f"trial0-step{step}")
    assert restored == f"trial0-step{step}"
    expected = ctx2.checkpoint.restore_state(f"trial0-step{step}",
                                             trainer2.state)
    assert _tree_equal(trainer2.state, expected)
    ctx2.close()


def test_trainer_budget_exhausted_skips_save_and_restores_previous(tmp_path):
    """Acceptance: with a deadline shorter than the estimated save time,
    the trainer skips the emergency save, exits cleanly, and restore
    lands on the previous COMPLETED checkpoint — never a PARTIAL torso."""
    ctx = _local_core(tmp_path, max_length=64)

    def blow_budget():
        # pretend the last durable save took an hour, then give 5s grace
        ctx.checkpoint.last_save_ms = 3_600_000.0
        ctx.preempt.force(deadline=5.0)

    # on_batch=4 → the poll trips at step 5, NOT a checkpoint_period
    # boundary: the newest COMPLETED checkpoint is the periodic step-4 one.
    trial = _ForcingTrial(TrialContext(), on_batch=4, action=blow_budget)
    trainer = Trainer(trial, core_context=ctx)
    state = trainer.fit(report_period=1, preempt_period=1,
                        checkpoint_period=2)
    step = int(jax.device_get(state.step))
    assert step == 5

    # The skipped save must not have touched storage at all: no torso.
    assert not (tmp_path / "ckpts" / f"trial0-step{step}").exists()
    metrics = _grace_report(ctx)
    assert metrics["preemption_emergency_checkpoint"] == 0.0
    # The periodic step-4 checkpoint is the newest COMPLETED one.
    assert ctx.checkpoint.lineage()[0] == "trial0-step4"
    ctx.close()

    # A managed restart would point at step 4; even a stale pointer to
    # the never-written step-6 id walks back to step 4, bit-identically.
    ctx2 = _local_core(tmp_path, max_length=64)
    trainer2 = Trainer(LinearTrial(TrialContext()), core_context=ctx2)
    trainer2._build(seed=0)
    assert trainer2._restore(f"trial0-step{step}") == "trial0-step4"
    expected = ctx2.checkpoint.restore_state("trial0-step4", trainer2.state)
    assert _tree_equal(trainer2.state, expected)
    ctx2.close()


def test_trainer_unbounded_preemption_keeps_old_behavior(tmp_path):
    """No deadline → the pre-existing path: checkpoint at the boundary,
    commit in the epilogue, no grace metric."""
    ctx = _local_core(tmp_path, max_length=64)
    trial = _ForcingTrial(TrialContext(), on_batch=5,
                          action=lambda: ctx.preempt.force())
    trainer = Trainer(trial, core_context=ctx)
    state = trainer.fit(report_period=1, preempt_period=1)
    step = int(jax.device_get(state.step))
    assert (tmp_path / "ckpts" / f"trial0-step{step}" / "COMMIT").exists()
    assert not any("preemption_grace_used_ms" in m["metrics"]
                   for m in ctx.train.local_training_metrics)
    ctx.close()


def test_validation_polls_preemption(tmp_path):
    """Satellite: a long `_validate` pass must poll should_preempt() every
    preempt_period batches and cut the pass short."""
    ctx = _local_core(tmp_path, max_length=8)

    seen = []

    class ValTrial(LinearTrial):
        def evaluate(self, params, batch):
            import jax.numpy as jnp

            return {"loss": jnp.mean((params["w"] - batch["x"]) ** 2)}

        def build_validation_data(self):
            rng = np.random.default_rng(3)
            for i in range(1000):
                if i == 7:
                    ctx.preempt.force(deadline=60.0)
                seen.append(i)
                yield {"x": rng.normal(size=(8, 4)).astype(np.float32)}

    trainer = Trainer(ValTrial(TrialContext()), core_context=ctx)
    trainer.fit(report_period=1, preempt_period=2)
    # The pass was cut short at the first poll after batch 7, nowhere
    # near the 1000 batches the iterator offers.
    assert len(seen) < 20, f"validation never polled preemption: {len(seen)}"
    # ... but the partial averages were still reported.
    assert any("validation_loss" in m["metrics"]
               for m in ctx.train.local_validation_metrics)
    ctx.close()


def test_last_save_ms_observed(tmp_path):
    ctx = _local_core(tmp_path, max_length=4)
    assert ctx.checkpoint.last_save_ms is None
    trainer = Trainer(LinearTrial(TrialContext()), core_context=ctx)
    trainer.fit(report_period=1)
    assert ctx.checkpoint.last_save_ms is not None
    assert ctx.checkpoint.last_save_ms > 0.0
    ctx.close()


# ---------------------------------------------------------------------------
# Master harness: DRAINING lifecycle + scheduler exclusion (tier-1 safe).
# ---------------------------------------------------------------------------


@pytest.fixture()
def master_only(tmp_path, native_binaries):
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    yield c
    c.stop()


def _register_fake_agent(c, admin, agent_id, slots=2):
    out = c.api("POST", "/api/v1/agents/register",
                {"id": agent_id, "resource_pool": "default",
                 "addr": "127.0.0.1",
                 "slots": [{"id": i, "type": "cpu"} for i in range(slots)]},
                token=admin)
    assert out["agent_id"] == agent_id


def _agent(c, token, agent_id):
    agents = c.api("GET", "/api/v1/agents", token=token)["agents"]
    return next(a for a in agents if a["id"] == agent_id)


def _trial_allocation(c, token, eid, timeout=10.0):
    """(allocation_id, state) of the experiment's single trial's job."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        jobs = [j for j in c.api("GET", "/api/v1/job-queues",
                                 token=token)["jobs"]
                if j.get("experiment_id") == eid]
        if jobs:
            return jobs[0]["allocation_id"], jobs[0]["state"]
        time.sleep(0.2)
    raise TimeoutError("trial allocation never appeared")


def _wait_alloc_state(c, token, eid, want, timeout=15.0):
    deadline = time.time() + timeout
    state = None
    while time.time() < deadline:
        _, state = _trial_allocation(c, token, eid)
        if state == want:
            return
        time.sleep(0.2)
    raise AssertionError(f"allocation stuck in {state}, wanted {want}")


def test_preempt_notice_marks_draining_and_pushes_deadline(master_only):
    c = master_only
    admin = c.login("admin")
    _register_fake_agent(c, admin, "fake-1")
    assert _agent(c, admin, "fake-1")["state"] == "ENABLED"

    # An allocation lands on the only agent...
    eid, token = _create_experiment(c, _experiment_config(c.tmpdir))
    _wait_alloc_state(c, token, eid, "SCHEDULED")
    aid, _ = _trial_allocation(c, token, eid)

    # ...then the notice arrives.
    r = c.api("POST", "/api/v1/agents/fake-1/preempt_notice",
              {"deadline_seconds": 25, "reason": "spot_preemption"},
              token=admin)
    assert r["state"] == "DRAINING"
    a = _agent(c, admin, "fake-1")
    assert a["state"] == "DRAINING"
    assert a["drain_reason"] == "spot_preemption"
    assert 20 < a["drain_deadline_seconds"] <= 25

    # The allocation's preemption signal carries the remaining deadline.
    sig = c.api("GET",
                f"/api/v1/allocations/{aid}/signals/preemption"
                "?timeout_seconds=0", token=token)
    assert sig["preempt"] is True
    assert sig["reason"] == "spot_preemption"
    assert 0 < sig["deadline_seconds"] <= 25

    # Repeated notices may only TIGHTEN the deadline.
    c.api("POST", "/api/v1/agents/fake-1/preempt_notice",
          {"deadline_seconds": 10, "reason": "spot_preemption"}, token=admin)
    assert _agent(c, admin, "fake-1")["drain_deadline_seconds"] <= 10
    c.api("POST", "/api/v1/agents/fake-1/preempt_notice",
          {"deadline_seconds": 300, "reason": "host_maintenance"},
          token=admin)
    assert _agent(c, admin, "fake-1")["drain_deadline_seconds"] <= 10

    # Notices persisted for spot-churn audits (migration 18).
    c.kill_master()
    with sqlite3.connect(c.db_path) as db:
        rows = db.execute(
            "SELECT agent_id, reason, deadline_seconds FROM agent_notices "
            "ORDER BY id").fetchall()
    assert rows[0] == ("fake-1", "spot_preemption", 25.0)
    assert len(rows) == 3


def test_draining_agent_excluded_from_placement(master_only):
    c = master_only
    admin = c.login("admin")
    _register_fake_agent(c, admin, "fake-1")
    c.api("POST", "/api/v1/agents/fake-1/preempt_notice",
          {"deadline_seconds": 3600, "reason": "spot_preemption"},
          token=admin)

    eid, token = _create_experiment(c, _experiment_config(c.tmpdir))
    _, state = _trial_allocation(c, token, eid)
    time.sleep(1.5)  # give the scheduler every chance to misplace it
    _, state = _trial_allocation(c, token, eid)
    assert state == "QUEUED", "scheduler placed work on a DRAINING agent"

    # Fresh capacity arrives → the queue drains onto IT.
    _register_fake_agent(c, admin, "fake-2")
    _wait_alloc_state(c, token, eid, "SCHEDULED")
    aid, _ = _trial_allocation(c, token, eid)
    alloc = c.api("GET", f"/api/v1/allocations/{aid}", token=token)[
        "allocation"]
    assert [r["agent_id"] for r in alloc["resources"]] == ["fake-2"]


def test_admin_enable_clears_draining_and_restores_placement(master_only):
    c = master_only
    admin = c.login("admin")
    _register_fake_agent(c, admin, "fake-1")
    c.api("POST", "/api/v1/agents/fake-1/preempt_notice",
          {"deadline_seconds": 3600, "reason": "host_maintenance"},
          token=admin)
    eid, token = _create_experiment(c, _experiment_config(c.tmpdir))
    time.sleep(1.0)
    _, state = _trial_allocation(c, token, eid)
    assert state == "QUEUED"

    # Operator override: the maintenance completed without a termination.
    c.api("POST", "/api/v1/agents/fake-1/enable", {}, token=admin)
    a = _agent(c, admin, "fake-1")
    assert a["state"] == "ENABLED" and a["drain_reason"] == ""
    _wait_alloc_state(c, token, eid, "SCHEDULED")


def test_fresh_register_clears_draining(master_only):
    c = master_only
    admin = c.login("admin")
    _register_fake_agent(c, admin, "fake-1")
    c.api("POST", "/api/v1/agents/fake-1/preempt_notice",
          {"deadline_seconds": 30, "reason": "spot_preemption"}, token=admin)
    assert _agent(c, admin, "fake-1")["state"] == "DRAINING"
    # The replacement machine boots with the same id and registers fresh.
    _register_fake_agent(c, admin, "fake-1")
    assert _agent(c, admin, "fake-1")["state"] == "ENABLED"


def test_preempt_notice_validation_and_auth(master_only):
    import urllib.error

    c = master_only
    admin = c.login("admin")
    user = c.login()
    _register_fake_agent(c, admin, "fake-1")

    try:
        c.api("POST", "/api/v1/agents/fake-1/preempt_notice",
              {"deadline_seconds": 30}, token=user)
        raise AssertionError("non-agent/non-admin notice should 403")
    except urllib.error.HTTPError as e:
        assert e.code == 403
    try:
        c.api("POST", "/api/v1/agents/fake-1/preempt_notice",
              {"deadline_seconds": -5}, token=admin)
        raise AssertionError("negative deadline should 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
    try:
        c.api("POST", "/api/v1/agents/no-such/preempt_notice",
              {"deadline_seconds": 30}, token=admin)
        raise AssertionError("unknown agent should 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404


# ---------------------------------------------------------------------------
# Satellite: the pre-existing admin drain endpoints, previously untested.
# ---------------------------------------------------------------------------


def test_admin_disable_excludes_enable_restores(master_only):
    """POST /api/v1/agents/{id}/enable|disable: disabled slots take no new
    allocations; re-enable restores placement."""
    c = master_only
    admin = c.login("admin")
    _register_fake_agent(c, admin, "fake-1")

    c.api("POST", "/api/v1/agents/fake-1/disable", {}, token=admin)
    a = _agent(c, admin, "fake-1")
    assert a["state"] == "DISABLED"
    assert all(not s["enabled"] for s in a["slots"])

    eid, token = _create_experiment(c, _experiment_config(c.tmpdir))
    time.sleep(1.5)
    _, state = _trial_allocation(c, token, eid)
    assert state == "QUEUED", "disabled slots accepted an allocation"

    c.api("POST", "/api/v1/agents/fake-1/enable", {}, token=admin)
    a = _agent(c, admin, "fake-1")
    assert a["state"] == "ENABLED"
    assert all(s["enabled"] for s in a["slots"])
    _wait_alloc_state(c, token, eid, "SCHEDULED")


def test_admin_drain_endpoints_are_admin_only(master_only):
    import urllib.error

    c = master_only
    admin = c.login("admin")
    user = c.login()
    _register_fake_agent(c, admin, "fake-1")
    for action in ("disable", "enable"):
        try:
            c.api("POST", f"/api/v1/agents/fake-1/{action}", {}, token=user)
            raise AssertionError(f"non-admin {action} should 403")
        except urllib.error.HTTPError as e:
            assert e.code == 403
    # unknown agent → 404 (routed, validated)
    try:
        c.api("POST", "/api/v1/agents/no-such/disable", {}, token=admin)
        raise AssertionError("unknown agent should 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404


# ---------------------------------------------------------------------------
# Capstone e2e (slow): spot notice mid-trial on a 2-agent devcluster.
# ---------------------------------------------------------------------------


def _task_log_text(c, token, trial_id):
    logs = c.api("GET", f"/api/v1/tasks/trial-{trial_id}/logs?offset=0",
                 token=token)["logs"]
    return "\n".join(line["log"] for line in logs)


@pytest.mark.slow
def test_spot_notice_emergency_checkpoint_and_reschedule_e2e(
        tmp_path, native_binaries):
    """Acceptance: a 30s-deadline termination notice mid-trial on a
    2-agent devcluster → the trial commits a COMPLETED (manifest+COMMIT)
    emergency checkpoint within the deadline, the agent goes DRAINING and
    takes no new allocations, and the trial resumes ON THE SURVIVOR from
    exactly the emergency checkpoint (restarts >= 1, no lineage rollback
    past it)."""
    c = Devcluster(str(tmp_path), native_binaries, slots=1)
    c.start_master()
    notice_files = {}
    for agent_id in ("spot-a", "spot-b"):
        nf = os.path.join(str(tmp_path), f"notice-{agent_id}.json")
        notice_files[agent_id] = nf
        c.start_agent(agent_id, extra_env={"DET_AGENT_NOTICE_FILE": nf})
    try:
        config = _experiment_config(
            tmp_path,
            searcher={"name": "single", "metric": "val_loss",
                      "max_length": {"batches": 400}},
            extra={"max_restarts": 2,
                   "entrypoint": "python3 spot_train.py"},
        )
        config["environment"] = {"SPOT_STEP_SLEEP": "0.1"}
        eid, token = _create_experiment(c, config)
        sess_token = token

        # Wait until the trial is mid-run (reporting steps), then find
        # which agent runs it.
        deadline = time.time() + 120
        trial, victim = None, None
        while time.time() < deadline:
            trials = c.api("GET", f"/api/v1/experiments/{eid}/trials",
                           token=token)["trials"]
            if trials:
                rows = c.api(
                    "GET",
                    f"/api/v1/trials/{trials[0]['id']}/metrics?group=training",
                    token=token)["metrics"]
                if len(rows) >= 5:  # several steps in: genuinely mid-trial
                    trial = trials[0]
                    jobs = [j for j in c.api("GET", "/api/v1/job-queues",
                                             token=token)["jobs"]
                            if j.get("experiment_id") == eid]
                    alloc = c.api(
                        "GET", f"/api/v1/allocations/{jobs[0]['allocation_id']}",
                        token=token)["allocation"]
                    victim = alloc["resources"][0]["agent_id"]
                    break
            time.sleep(0.5)
        assert trial is not None and victim in ("spot-a", "spot-b"), (
            "trial never started reporting")
        survivor = "spot-b" if victim == "spot-a" else "spot-a"

        # Checkpoints registered BEFORE the notice (periodic ones).
        def _completed_uuids():
            return {ck["uuid"] for ck in c.api(
                "GET",
                f"/api/v1/trials/{trial['id']}/checkpoints?state=COMPLETED",
                token=token)["checkpoints"]}

        pre_notice = _completed_uuids()

        # The notice: node `victim` disappears in 30 seconds.
        t_notice = time.time()
        with open(notice_files[victim], "w") as f:
            json.dump({"deadline_seconds": 30,
                       "reason": "spot_preemption"}, f)

        # The agent relays it; the master marks it DRAINING.
        deadline = time.time() + 20
        while time.time() < deadline:
            a = _agent(c, c.login("admin"), victim)
            if a["state"] == "DRAINING":
                break
            time.sleep(0.3)
        assert a["state"] == "DRAINING" and a["drain_reason"] == \
            "spot_preemption"

        # The emergency checkpoint must turn up COMPLETED in the registry
        # within the 30s deadline, fully committed on shared storage.
        # Verified MID-RUN: experiment-completion GC sweeps non-best
        # checkpoints later, so the disk evidence must be captured now.
        ck_root = os.path.join(str(tmp_path), "checkpoints")
        committed_mid_run = set()
        deadline = t_notice + 35.0
        settle_until = None  # keep collecting a bit past the first hit:
        # a periodic save can race the emergency one into the diff
        while time.time() < deadline:
            for uuid in _completed_uuids() - pre_notice:
                if uuid in committed_mid_run:
                    continue
                assert os.path.exists(
                    os.path.join(ck_root, uuid, "COMMIT")), uuid
                assert os.path.exists(
                    os.path.join(ck_root, uuid, "manifest.json")), uuid
                committed_mid_run.add(uuid)
            if committed_mid_run and settle_until is None:
                settle_until = time.time() + 8.0
            if settle_until is not None and time.time() > settle_until:
                break
            time.sleep(0.3)
        assert committed_mid_run, (
            "no COMPLETED emergency checkpoint within the 30s deadline")

        # The trial must be rescheduled onto the survivor and run to
        # completion there.
        _wait_experiment(c, eid, token, timeout=240.0)

        trials = c.api("GET", f"/api/v1/experiments/{eid}/trials",
                       token=token)["trials"]
        assert trials[0]["state"] == "COMPLETED"
        assert trials[0]["restarts"] >= 1, (
            "the spot move must be recorded as a restart")

        text = _task_log_text(c, sess_token, trials[0]["id"])
        assert "emergency checkpoint committed" in text, text[-2000:]
        # The resumed run restored exactly the emergency checkpoint (no
        # lineage rollback past it): the step named in the emergency log
        # line is the step named in the restore log line.
        import re

        m = re.search(
            r"deadline preemption \(spot_preemption\) at step (\d+): "
            r"emergency checkpoint committed, grace used (\d+)ms", text)
        assert m, f"no emergency-checkpoint log line:\n{text[-2000:]}"
        em_step, grace_ms = int(m.group(1)), int(m.group(2))
        assert grace_ms < 30_000, "emergency save blew the 30s deadline"
        assert re.search(
            rf"restored from checkpoint trial\d+-step{em_step} at step "
            rf"{em_step}", text), (
            f"resume did not land on the emergency checkpoint:\n"
            f"{text[-2000:]}")

        # The checkpoint we saw committed mid-run IS the emergency one the
        # logs name (registry + disk + logs all agree on the step).
        assert any(u.endswith(f"-step{em_step}") for u in committed_mid_run), (
            f"emergency step {em_step} not among mid-run COMPLETED "
            f"checkpoints {committed_mid_run}")

        # The resumed run landed on the survivor, and the grace metric
        # flowed through the metrics path.
        jobs = [j for j in c.api("GET", "/api/v1/job-queues",
                                 token=token)["jobs"]
                if j.get("experiment_id") == eid]
        if jobs:  # terminal allocations may have left the queue view
            alloc = c.api("GET",
                          f"/api/v1/allocations/{jobs[-1]['allocation_id']}",
                          token=token)["allocation"]
            assert all(r["agent_id"] == survivor
                       for r in alloc["resources"])
        rows = c.api(
            "GET", f"/api/v1/trials/{trials[0]['id']}/metrics?group=training",
            token=token)["metrics"]
        assert any("preemption_grace_used_ms" in r["metrics"] for r in rows)
    finally:
        c.stop()


@pytest.mark.slow
def test_agent_preempt_notice_fault_point_e2e(tmp_path, native_binaries):
    """The `agent.preempt.notice` DET_FAULTS point: armed in the agent's
    environment, it fires once a task is running (mid-trial by
    construction), drains the agent with the DET_AGENT_PREEMPT_DEADLINE_S
    deadline, and the re-enabled agent finishes the trial."""
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    c.start_agent(extra_env={
        "DET_FAULTS": "agent.preempt.notice:error:1",
        "DET_AGENT_PREEMPT_DEADLINE_S": "60",
    })
    try:
        config = _experiment_config(
            tmp_path,
            searcher={"name": "single", "metric": "val_loss",
                      "max_length": {"batches": 120}},
            extra={"max_restarts": 2},
        )
        config["environment"] = {"TRIAL_STEP_SLEEP": "0.05"}
        eid, token = _create_experiment(c, config)
        admin = c.login("admin")

        deadline = time.time() + 60
        a = None
        while time.time() < deadline:
            a = _agent(c, admin, "agent-0")
            if a["state"] == "DRAINING":
                break
            time.sleep(0.3)
        assert a and a["state"] == "DRAINING", (
            "fault point never drained the agent")
        assert a["drain_reason"] == "spot_preemption"
        assert 0 < a["drain_deadline_seconds"] <= 60

        # The sole agent is draining: the preempted trial re-queues but
        # cannot place. The operator re-enables (maintenance survived) →
        # placement restored, trial completes.
        time.sleep(3.0)
        c.api("POST", "/api/v1/agents/agent-0/enable", {}, token=admin)
        _wait_experiment(c, eid, token, timeout=240.0)
        trials = c.api("GET", f"/api/v1/experiments/{eid}/trials",
                       token=token)["trials"]
        assert trials[0]["state"] == "COMPLETED"
        assert trials[0]["restarts"] >= 1
        assert "resumed from checkpoint" in _task_log_text(
            c, token, trials[0]["id"])

        c.kill_master()
        with sqlite3.connect(c.db_path) as db:
            rows = db.execute(
                "SELECT reason, deadline_seconds FROM agent_notices"
            ).fetchall()
        assert ("spot_preemption", 60.0) in rows
    finally:
        c.stop()
