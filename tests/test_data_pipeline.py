"""Async input pipeline (determined_tpu/data): correctness, lifecycle,
chaos, and the ISSUE-3 acceptance contract.

Fast tier-1 module: every test here runs on the virtual 8-device CPU slice
in well under a second except the throughput acceptance test (~2s of
deliberate sleeps).
"""

import threading
import time

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from determined_tpu import core
from determined_tpu.common import faultpoint
from determined_tpu.data import DevicePrefetcher, PrefetchConfig
from determined_tpu.data.bench import ab_compare
from determined_tpu.train import JaxTrial, Trainer
from determined_tpu.train.trial import TrialContext


def prefetch_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith(DevicePrefetcher.THREAD_PREFIX)]


def batches(n=10, size=8):
    rng = np.random.default_rng(0)
    for i in range(n):
        yield {"x": rng.normal(size=(size, 4)).astype(np.float32),
               "i": np.full((size,), i, np.int32)}


@pytest.fixture()
def batch_mesh_sharding(devices):
    mesh = Mesh(np.asarray(devices).reshape(8), ("data",))
    return NamedSharding(mesh, PartitionSpec("data"))


@pytest.fixture(autouse=True)
def _no_leaked_threads():
    """Every test in this module must leave zero prefetch threads."""
    yield
    faultpoint.disarm_all()
    deadline = time.time() + 2.0
    while prefetch_threads() and time.time() < deadline:
        time.sleep(0.01)
    assert prefetch_threads() == []


# ---------------------------------------------------------------------------
# ordering + determinism
# ---------------------------------------------------------------------------


class TestOrdering:
    def test_order_bit_identical_to_sync(self):
        sync = list(batches())
        for depth in (1, 2, 4):
            with DevicePrefetcher(batches(), depth=depth) as pf:
                got = list(pf)
            assert len(got) == len(sync)
            for a, b in zip(got, sync):
                np.testing.assert_array_equal(a["x"], b["x"])
                np.testing.assert_array_equal(a["i"], b["i"])

    def test_depth_does_not_change_order(self, batch_mesh_sharding):
        seen = {}
        for depth in (1, 3):
            with DevicePrefetcher(batches(), sharding=batch_mesh_sharding,
                                  depth=depth) as pf:
                seen[depth] = [np.asarray(jax.device_get(b["x"]))
                               for b in pf]
        for a, b in zip(seen[1], seen[3]):
            np.testing.assert_array_equal(a, b)

    def test_batches_device_resident_and_sharded(self, batch_mesh_sharding):
        with DevicePrefetcher(batches(n=3), sharding=batch_mesh_sharding) as pf:
            out = list(pf)
        for b in out:
            assert isinstance(b["x"], jax.Array)
            assert b["x"].sharding == batch_mesh_sharding
            # resident: no transfer pending when the consumer gets it
            assert b["x"].is_ready()

    def test_window_metrics_flow(self, batch_mesh_sharding):
        pf = DevicePrefetcher(batches(n=5), sharding=batch_mesh_sharding)
        try:
            list(pf)
            m = pf.window_metrics()
            assert set(m) == {"input_wait_ms", "h2d_ms",
                              "prefetch_queue_depth"}
            assert m["h2d_ms"] >= 0.0
            # window resets after the read
            assert pf.window_metrics() == {}
        finally:
            pf.close()


# ---------------------------------------------------------------------------
# lifecycle: exceptions, shutdown, chaos
# ---------------------------------------------------------------------------


class TestLifecycle:
    def test_iterator_exception_propagates_to_consumer(self):
        def flaky():
            yield {"x": np.zeros(2, np.float32)}
            yield {"x": np.ones(2, np.float32)}
            raise RuntimeError("disk ate the shard")

        pf = DevicePrefetcher(flaky())
        try:
            assert next(pf)["x"][0] == 0.0
            assert next(pf)["x"][0] == 1.0
            with pytest.raises(RuntimeError, match="disk ate the shard"):
                next(pf)
        finally:
            pf.close()

    def test_close_is_idempotent_and_joins(self):
        pf = DevicePrefetcher(batches())
        next(pf)
        pf.close()
        pf.close()
        assert prefetch_threads() == []
        with pytest.raises(StopIteration):
            next(pf)

    def test_close_unblocks_full_queue(self):
        def infinite():
            i = 0
            while True:
                yield {"x": np.full((2,), i, np.int32)}
                i += 1

        pf = DevicePrefetcher(infinite(), depth=2)
        next(pf)  # producer now certainly running, queue refills
        pf.close()  # must not deadlock on the full queue
        assert prefetch_threads() == []

    def test_fault_point_error_via_det_faults(self, monkeypatch):
        monkeypatch.setenv("DET_FAULTS", "data.prefetch.queue:error:1")
        faultpoint.reload_env()
        pf = DevicePrefetcher(batches())
        try:
            with pytest.raises(faultpoint.FaultInjected,
                               match="data.prefetch.queue"):
                list(pf)
        finally:
            pf.close()

    def test_fault_point_drop_skips_batches(self):
        faultpoint.arm("data.prefetch.queue", "drop", count=2)
        with DevicePrefetcher(batches(n=6)) as pf:
            got = [b["i"][0] for b in pf]
        assert got == [2, 3, 4, 5]

    def test_fault_point_delay_slows_but_preserves_order(self):
        faultpoint.arm("data.prefetch.queue", "delay-20", count=3)
        with DevicePrefetcher(batches(n=4)) as pf:
            got = [b["i"][0] for b in pf]
        assert got == [0, 1, 2, 3]


# ---------------------------------------------------------------------------
# trainer integration
# ---------------------------------------------------------------------------


class LinearTrial(JaxTrial):
    """Tiny pure-linear trial: fast enough to fit multiple times per test."""

    def __init__(self, context):
        super().__init__(context)
        self.loader_threads = set()

    def init_params(self, rng):
        return {"w": jax.random.normal(rng, (4, 2)) * 0.1}

    def loss(self, params, batch, rng):
        pred = batch["x"] @ params["w"]
        return jax.numpy.mean((pred - batch["y"]) ** 2)

    def build_training_data(self):
        self.loader_threads.add(threading.current_thread().name)
        rng = np.random.default_rng(7)
        for _ in range(200):
            yield {"x": rng.normal(size=(8, 4)).astype(np.float32),
                   "y": rng.normal(size=(8, 2)).astype(np.float32)}

    def build_validation_data(self):
        rng = np.random.default_rng(8)
        for _ in range(2):
            yield {"x": rng.normal(size=(8, 4)).astype(np.float32),
                   "y": rng.normal(size=(8, 2)).astype(np.float32)}

    def evaluate(self, params, batch):
        pred = batch["x"] @ params["w"]
        return {"loss": jax.numpy.mean((pred - batch["y"]) ** 2)}


def _fit(tmp_path, sub, max_length=6, trial_cls=LinearTrial, **fit_kw):
    ctx = core.init(max_length=max_length,
                    checkpoint_dir=str(tmp_path / sub / "ckpts"),
                    async_checkpointing=False)
    trial = trial_cls(TrialContext())
    Trainer(trial, core_context=ctx).fit(report_period=2, **fit_kw)
    ctx.close()
    return trial, ctx


class TestTrainerIntegration:
    def test_prefetch_on_by_default_and_reports_metrics(self, tmp_path):
        trial, ctx = _fit(tmp_path, "on")
        # loader ran on the prefetch thread, not the step loop
        assert any(n.startswith(DevicePrefetcher.THREAD_PREFIX)
                   for n in trial.loader_threads)
        reported = ctx.train.local_training_metrics
        assert reported
        assert "input_wait_ms" in reported[-1]["metrics"]
        assert "h2d_ms" in reported[-1]["metrics"]
        assert "prefetch_queue_depth" in reported[-1]["metrics"]
        assert prefetch_threads() == []

    def test_opt_out_via_trial_attribute(self, tmp_path):
        class NoPrefetch(LinearTrial):
            prefetch = False

        trial, ctx = _fit(tmp_path, "off", trial_cls=NoPrefetch)
        assert trial.loader_threads == {"MainThread"}
        assert "input_wait_ms" not in ctx.train.local_training_metrics[-1]["metrics"]

    def test_losses_bit_identical_prefetch_on_vs_off(self, tmp_path):
        class NoPrefetch(LinearTrial):
            prefetch = False

        _, ctx_on = _fit(tmp_path, "a")
        _, ctx_off = _fit(tmp_path, "b", trial_cls=NoPrefetch)
        on = [m["metrics"]["loss"] for m in ctx_on.train.local_training_metrics]
        off = [m["metrics"]["loss"] for m in ctx_off.train.local_training_metrics]
        assert len(on) == len(off) > 0
        np.testing.assert_array_equal(np.asarray(on), np.asarray(off))

    def test_preemption_mid_prefetch_leaves_no_threads(self, tmp_path):
        ctx = core.init(max_length=1000,
                        checkpoint_dir=str(tmp_path / "pre" / "ckpts"),
                        async_checkpointing=False)
        ctx.preempt.force()
        trial = LinearTrial(TrialContext())
        state = Trainer(trial, core_context=ctx).fit(report_period=2)
        assert int(jax.device_get(state.step)) < 1000
        ctx.close()
        assert prefetch_threads() == []

    def test_mid_epoch_loader_exception_reaches_fit_and_cleans_up(self, tmp_path):
        class Flaky(LinearTrial):
            def build_training_data(self):
                yield {"x": np.zeros((8, 4), np.float32),
                       "y": np.zeros((8, 2), np.float32)}
                raise RuntimeError("loader died mid-epoch")

        ctx = core.init(max_length=50,
                        checkpoint_dir=str(tmp_path / "flaky" / "ckpts"),
                        async_checkpointing=False)
        with pytest.raises(RuntimeError, match="loader died mid-epoch"):
            Trainer(Flaky(TrialContext()), core_context=ctx).fit(report_period=2)
        ctx.close()
        assert prefetch_threads() == []

    def test_validation_prefetches_and_closes(self, tmp_path):
        trial, ctx = _fit(tmp_path, "val")
        assert ctx.train.local_validation_metrics
        assert prefetch_threads() == []


# ---------------------------------------------------------------------------
# config resolution
# ---------------------------------------------------------------------------


class TestPrefetchConfig:
    def test_defaults(self):
        cfg = PrefetchConfig.resolve()
        assert cfg.enabled and cfg.depth == 2 and cfg.shard

    def test_expconf_block(self):
        cfg = PrefetchConfig.resolve(
            expconf={"prefetch": {"enabled": False, "depth": 5}})
        assert not cfg.enabled and cfg.depth == 5

    def test_trial_attr_wins_over_expconf(self):
        class T:
            prefetch = {"depth": 7}

        cfg = PrefetchConfig.resolve(T(), {"prefetch": {"depth": 3}})
        assert cfg.depth == 7 and cfg.enabled

    def test_bool_forms(self):
        assert PrefetchConfig.from_block(False).enabled is False
        assert PrefetchConfig.from_block(True).enabled is True
        with pytest.raises(TypeError):
            PrefetchConfig.from_block("yes")

    def test_depth_floor(self):
        assert PrefetchConfig.from_block({"depth": 0}).depth == 1


# ---------------------------------------------------------------------------
# the acceptance contract: slow host + fixed step -> steady-state step time
# is ~compute, not compute+input
# ---------------------------------------------------------------------------


HOST_DELAY_S = 0.020
STEP_S = 0.050
N_STEPS = 12


def slow_host_iter():
    rng = np.random.default_rng(0)
    for _ in range(N_STEPS):
        time.sleep(HOST_DELAY_S)  # simulated host preprocessing
        yield {"x": rng.normal(size=(8, 16)).astype(np.float32)}


def test_throughput_prefetch_beats_sync(batch_mesh_sharding):
    """ISSUE 3 acceptance: with a 20ms host iterator and a 50ms step,
    prefetch overlaps input with compute — >=1.25x throughput over the
    synchronous path, and reported input_wait_ms drops accordingly."""

    def step_fn(batch):
        time.sleep(STEP_S)  # stands in for dispatched device compute

    result = ab_compare(slow_host_iter, step_fn,
                        sharding=batch_mesh_sharding, depth=2)
    # sync pays host+H2D inline (~70ms/step); prefetch hides it (~50ms).
    assert result["speedup"] >= 1.25, result
    # input wait collapses from ~HOST_DELAY to near-zero.
    assert result["sync"]["input_wait_ms"] >= HOST_DELAY_S * 1e3 * 0.9, result
    assert result["prefetch"]["input_wait_ms"] < HOST_DELAY_S * 1e3 * 0.5, result
    assert result["input_wait_ms_delta"] > 0
