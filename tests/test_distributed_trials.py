"""Distributed compat trial APIs on the 8-device CPU mesh / multi-process.

VERDICT r2 #1: the reference's trial APIs are the *distributed* ones
(TFKerasTrial via Horovod, PyTorchTrial via torchrun+DDP). Here:
  - KerasTrial distributes over the allocation mesh via keras.distribution
    (DataParallel / ModelParallel on the JAX backend)
  - PyTorchTrial runs real multi-process DDP via the
    determined_tpu.launch.torch_distributed launch layer (gloo on CPU,
    xla:// on TPU task images)
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from determined_tpu import core

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Keras distribution over the device mesh
# ---------------------------------------------------------------------------


def _make_keras_trial(keras, hparams, with_layout_map=False):
    from determined_tpu.keras import KerasTrial, KerasTrialContext

    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 4)).astype("float32")
    w = np.array([[1.0], [-2.0], [3.0], [0.5]], dtype="float32")
    y = x @ w

    class LinearKeras(KerasTrial):
        def build_model(self):
            model = keras.Sequential(
                [keras.layers.Dense(8, activation="relu", name="hidden"),
                 keras.layers.Dense(1, use_bias=False, name="out")]
            )
            model.compile(optimizer=keras.optimizers.SGD(0.05), loss="mse")
            model.build((None, 4))
            return model

        def build_training_data(self):
            return (x, y)

        def build_validation_data(self):
            return (x[:64], y[:64])

        if with_layout_map:
            def layout_map(self, device_mesh):
                lm = keras.distribution.LayoutMap(device_mesh)
                # shard Dense kernels' output dim over the model axis
                lm["hidden/kernel"] = (None, "model")
                lm["out/kernel"] = ("model", None)
                return lm

    return LinearKeras(KerasTrialContext(hparams=hparams))


@pytest.fixture(autouse=True)
def _reset_keras_distribution():
    yield
    try:
        import keras

        keras.distribution.set_distribution(None)
    except Exception:
        pass


def test_keras_data_parallel_8dev(tmp_path, devices):
    keras = pytest.importorskip("keras")
    from determined_tpu.keras import Trainer

    ctx = core.init(max_length=10, checkpoint_dir=str(tmp_path))
    trial = _make_keras_trial(
        keras, {"global_batch_size": 32, "mesh": {"data": -1}})
    trial.context._core = ctx
    trainer = Trainer(trial, core_context=ctx)
    assert isinstance(trainer.distribution, keras.distribution.DataParallel)
    # variables replicated across all 8 devices
    v = trainer.model.weights[0].value
    assert len(v.sharding.device_set) == 8
    steps = trainer.fit()
    assert steps == 10
    assert ctx.train.local_validation_metrics
    ctx.close()


def test_keras_model_parallel_8dev(tmp_path, devices):
    keras = pytest.importorskip("keras")
    from determined_tpu.keras import Trainer

    ctx = core.init(max_length=6, checkpoint_dir=str(tmp_path))
    trial = _make_keras_trial(
        keras,
        {"global_batch_size": 32, "mesh": {"data": 2, "tensor": 4}},
        with_layout_map=True,
    )
    trial.context._core = ctx
    trainer = Trainer(trial, core_context=ctx)
    assert isinstance(trainer.distribution, keras.distribution.ModelParallel)
    # hidden kernel [4, 8] sharded 4-way on its output dim: local shard [4, 2]
    hidden = next(w for w in trainer.model.weights
                  if "hidden" in w.path and "kernel" in w.path)
    shard_shape = hidden.value.addressable_shards[0].data.shape
    assert shard_shape == (4, 2), shard_shape
    steps = trainer.fit()
    assert steps == 6
    val = ctx.train.local_validation_metrics[-1]["metrics"]
    assert np.isfinite(val["loss"])
    ctx.close()


def test_keras_model_axes_require_layout_map(tmp_path, devices):
    pytest.importorskip("keras")
    from determined_tpu.keras import Trainer

    ctx = core.init(max_length=2, checkpoint_dir=str(tmp_path))
    trial = _make_keras_trial(
        keras=pytest.importorskip("keras"),
        hparams={"mesh": {"data": 2, "tensor": 4}},
        with_layout_map=False,
    )
    trial.context._core = ctx
    with pytest.raises(ValueError, match="layout_map"):
        Trainer(trial, core_context=ctx)
    ctx.close()


def test_keras_rejects_pipeline_axis(tmp_path, devices):
    pytest.importorskip("keras")
    from determined_tpu.keras import Trainer

    ctx = core.init(max_length=2, checkpoint_dir=str(tmp_path))
    trial = _make_keras_trial(
        keras=pytest.importorskip("keras"),
        hparams={"mesh": {"data": 4, "pipeline": 2}},
    )
    trial.context._core = ctx
    with pytest.raises(ValueError, match="pipeline"):
        Trainer(trial, core_context=ctx)
    ctx.close()


# ---------------------------------------------------------------------------
# torch.distributed launch layer + DDP PyTorchTrial
# ---------------------------------------------------------------------------


class TestTorchLaunchLayer:
    def test_worker_env(self):
        from determined_tpu.launch.torch_distributed import worker_env

        env = worker_env(
            {"PATH": "/usr/bin"},
            node_rank=1, nnodes=2, local_rank=3, nproc_per_node=4,
            master_addr="10.0.0.1", master_port=29400, backend="gloo",
        )
        assert env["RANK"] == "7"
        assert env["WORLD_SIZE"] == "8"
        assert env["LOCAL_RANK"] == "3"
        assert env["MASTER_ADDR"] == "10.0.0.1"
        assert env["MASTER_PORT"] == "29400"
        assert env["DET_TORCH_BACKEND"] == "gloo"
        assert env["PATH"] == "/usr/bin"  # base env preserved

    def test_backend_pick_without_xla(self):
        from determined_tpu.launch.torch_distributed import pick_backend

        assert pick_backend() in ("gloo", "nccl")

    def test_failed_worker_kills_survivors(self, tmp_path):
        """torchrun semantics: rank 1 crashes -> rank 0 (sleeping forever)
        is terminated and the launcher exits non-zero promptly."""
        script = tmp_path / "crashy.py"
        script.write_text(
            "import os, sys, time\n"
            "if os.environ['RANK'] == '1':\n"
            "    sys.exit(3)\n"
            "time.sleep(600)\n"
        )
        env = dict(os.environ, DET_TORCH_MASTER_PORT="29499")
        r = subprocess.run(
            [sys.executable, "-m",
             "determined_tpu.launch.torch_distributed",
             "--nproc-per-node", "2", "--", sys.executable, str(script)],
            capture_output=True, text=True, env=env, timeout=60,
        )
        assert r.returncode == 3, (r.returncode, r.stdout, r.stderr)
        assert "terminating" in r.stderr


def test_pytorch_ddp_two_process_e2e(tmp_path):
    """Real 2-process gloo DDP through the launch layer: synced grads,
    sharded data, chief-only reporting (see the fixture's asserts)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
        DET_TORCH_MASTER_PORT=str(port),
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable, "-m", "determined_tpu.launch.torch_distributed",
         "--nproc-per-node", "2", "--",
         sys.executable,
         os.path.join(REPO, "tests", "fixtures", "torch_dist", "train_ddp.py"),
         str(tmp_path)],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
    # rank-prefixed log wrapping (reference wrap_rank)
    assert "[rank=0]" in r.stdout and "[rank=1]" in r.stdout
    reports = {}
    for rank in (0, 1):
        with open(tmp_path / f"rank{rank}.json") as f:
            reports[rank] = json.load(f)
    assert reports[0]["steps"] == reports[1]["steps"] == 8
    # chief-only reporting: rank 0 reported, rank 1 stayed silent
    assert reports[0]["n_checkpoints"] >= 1
    assert reports[1]["n_checkpoints"] == 0
    assert reports[0]["n_train_metrics"] >= 1
    assert reports[1]["n_train_metrics"] == 0
    assert reports[0]["val"] is not None
