"""OpenAPI schema source of truth ↔ live master contract (VERDICT r3 #7).

Reference: proto/src/determined/api/v1/api.proto defines the service;
bindings are generated from it. Here the source of truth is
proto/gen_openapi.py → proto/openapi.json, and these tests pin BOTH
directions: every spec path is actually routed by the master (no vapor
endpoints), and every /api/v1 path the Python clients + WebUI call is in
the spec (no undocumented surface).
"""

import json
import os
import re
import subprocess
import sys
import urllib.error
import urllib.request

import pytest

from tests.test_platform_e2e import Devcluster, native_binaries  # noqa: F401

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPEC_PATH = os.path.join(REPO, "proto", "openapi.json")


@pytest.fixture(scope="module")
def spec():
    with open(SPEC_PATH) as f:
        return json.load(f)


@pytest.fixture()
def cluster(tmp_path, native_binaries):  # noqa: F811
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    yield c
    c.stop()


def test_spec_is_regenerated(spec):
    """proto/openapi.json must match gen_openapi.py output (codegen
    discipline: edit the table, run the generator, commit both)."""
    out = subprocess.run(
        [sys.executable, "-c",
         "import sys, json; sys.path.insert(0, 'proto'); "
         "import gen_openapi; print(json.dumps(gen_openapi.build()))"],
        capture_output=True, text=True, cwd=REPO, check=True)
    assert json.loads(out.stdout) == spec, (
        "proto/openapi.json is stale — run python proto/gen_openapi.py")


def test_every_spec_path_is_routed(cluster, spec):
    """No vapor endpoints: substitute path params and hit each operation;
    the master must answer with anything but 404-not-found-route. (Many
    answer 400/403/404-entity for bogus ids — that still proves routing.)"""
    token = cluster.login()
    admin = cluster.login("admin")
    subs = {"{id}": "999999", "{uid}": "999999", "{aid}": "x",
            "{uuid}": "no-such", "{name}": "no-such"}
    misses = []
    for path, ops in spec["paths"].items():
        for method in ops:
            p = path
            for k, v in subs.items():
                p = p.replace(k, v)
            req = urllib.request.Request(
                cluster.master_url + p +
                ("?timeout_seconds=0" if method == "get" else ""),
                data=b"{}" if method in ("post", "patch") else None,
                headers={"Authorization": f"Bearer {admin}",
                         "Content-Type": "application/json"},
                method=method.upper())
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    status, body = r.status, ""
            except urllib.error.HTTPError as e:
                status = e.code
                body = e.read().decode(errors="replace")
            if status == 404 and "not found" == json.loads(body or "{}").get(
                    "error", ""):
                misses.append(f"{method.upper()} {path} -> unrouted 404")
    assert not misses, "\n".join(misses)
    (token,)


def test_every_client_path_is_in_spec(spec):
    """No undocumented surface: every /api/v1 literal the Python harness,
    CLI, SDK, tests' Devcluster, and WebUI call must appear in the spec
    (path params normalized)."""
    def compatible(used_path, spec_path):
        # Segment-wise: a parameter on EITHER side matches anything (the
        # client side has f-string members like /{kind}/{id} that cannot
        # be resolved statically).
        u, s = used_path.split("/"), spec_path.split("/")
        if len(u) != len(s):
            return False
        for a, b in zip(u, s):
            if a.startswith("{") or b.startswith("{"):
                continue
            if a != b:
                return False
        return True

    used = set()
    roots = [os.path.join(REPO, "determined_tpu"), os.path.join(REPO, "webui")]
    for root in roots:
        for dirpath, _, files in os.walk(root):
            for fn in files:
                if not fn.endswith((".py", ".js")):
                    continue
                src = open(os.path.join(dirpath, fn),
                           errors="replace").read()
                # literal paths; f-string/template members become {…} params
                for m in re.findall(r"/api/v1/[A-Za-z0-9_\-/{}$.\[\]']*",
                                    src):
                    path = m.split("?")[0]
                    path = re.sub(r"\{[^}]*\}|\$\{[^}]*\}", "{id}", path)
                    path = path.rstrip("/.")  # prose periods, trailing /
                    if path.endswith(("'", "]")) or "[" in path:
                        continue
                    used.add(path)

    unknown = [
        path for path in sorted(used)
        if not any(compatible(path, sp) for sp in spec["paths"])
    ]
    assert not unknown, f"paths used by clients but not in spec: {unknown}"


def test_openapi_served_by_master(cluster, spec):
    token = cluster.login()
    req = urllib.request.Request(
        cluster.master_url + "/api/v1/openapi",
        headers={"Authorization": f"Bearer {token}"})
    with urllib.request.urlopen(req, timeout=10) as r:
        served = json.loads(r.read())
    assert served["paths"].keys() == spec["paths"].keys()


def test_generated_clients_are_regenerated(spec):
    """bindings.py / api_client.js must match gen_client.py output
    (same codegen discipline as the spec itself)."""
    sys.path.insert(0, os.path.join(REPO, "proto"))
    try:
        import gen_client
    finally:
        sys.path.pop(0)
    with open(os.path.join(REPO, "determined_tpu", "common",
                           "bindings.py")) as f:
        assert f.read() == gen_client.gen_python(spec), (
            "bindings.py is stale — run python proto/gen_client.py")
    with open(os.path.join(REPO, "webui", "api_client.js")) as f:
        assert f.read() == gen_client.gen_js(spec), (
            "api_client.js is stale — run python proto/gen_client.py")


def test_bindings_cover_every_operation(spec):
    """One Python method and one JS method per spec operation."""
    from determined_tpu.common.bindings import Bindings

    n_ops = sum(len(ops) for ops in spec["paths"].values())
    methods = [m for m in dir(Bindings) if not m.startswith("_")]
    assert len(methods) == n_ops
    # every method's docstring names a real spec operation
    for m in methods:
        doc = getattr(Bindings, m).__doc__
        verb, path = doc.split(" — ")[0].split(" ", 1)
        assert path in spec["paths"], (m, path)
        assert verb.lower() in spec["paths"][path], (m, verb)

    with open(os.path.join(REPO, "webui", "api_client.js")) as f:
        js = f.read()
    for path, ops in spec["paths"].items():
        for verb in ops:
            assert f"/** {verb.upper()} {path} " in js, (verb, path)


def test_bindings_work_against_live_master(cluster):
    """Smoke: the generated client really drives the master (login →
    list experiments → master info)."""
    from determined_tpu.common.api import Session, salted_hash
    from determined_tpu.common.bindings import Bindings

    anon = Bindings(Session(cluster.master_url))
    token = anon.post_auth_login(
        body={"username": "determined",
              "password": salted_hash("determined", "")})["token"]
    api = Bindings(Session(cluster.master_url, token))
    assert "experiments" in api.get_experiments()
    assert api.get_master()["cluster_name"]
    assert "agents" in api.get_agents()
