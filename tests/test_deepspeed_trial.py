"""DeepSpeedTrial compat surface, pinned with a fake engine (reference
harness/determined/pytorch/deepspeed/_deepspeed_trial.py:729 + _mpu.py).

deepspeed isn't installable here (and the TPU-native capability is the JAX
FSDP stack), so the contract is verified against a duck-typed engine the
same way the torch-xla contract is: the microbatch-iterator train_batch
signature, engine-owned backward/step, MPU-gated reporting/data-loading,
and engine-sharded save/load through the checkpoint context.
"""

import os

import pytest
import torch

from determined_tpu import core
from determined_tpu.pytorch import (
    DataLoader,
    DeepSpeedTrainer,
    DeepSpeedTrial,
    DeepSpeedTrialContext,
    ModelParallelUnit,
)


class FakeEngine:
    """Duck-typed deepspeed engine: owns the model, accumulation, and
    sharded checkpoints."""

    def __init__(self, model, lr=0.05, micro_bs=8, grad_accum=2):
        self.module = model
        self.opt = torch.optim.SGD(model.parameters(), lr=lr)
        self._micro_bs = micro_bs
        self._grad_accum = grad_accum
        self.backward_calls = 0
        self.step_calls = 0
        self.saves = []
        self.loads = []

    def train_micro_batch_size_per_gpu(self):
        return self._micro_bs

    def gradient_accumulation_steps(self):
        return self._grad_accum

    def __call__(self, x):
        return self.module(x)

    def backward(self, loss):
        (loss / self._grad_accum).backward()
        self.backward_calls += 1

    def step(self):
        # deepspeed steps the optimizer only at accumulation boundaries
        self.step_calls += 1
        if self.step_calls % self._grad_accum == 0:
            self.opt.step()
            self.opt.zero_grad(set_to_none=True)

    def save_checkpoint(self, save_dir, tag=None):
        path = os.path.join(save_dir, f"{tag or 'ck'}-rank0.pt")
        torch.save(self.module.state_dict(), path)
        self.saves.append(path)

    def load_checkpoint(self, load_dir, tag=None):
        path = os.path.join(load_dir, f"{tag or 'ck'}-rank0.pt")
        self.module.load_state_dict(
            torch.load(path, weights_only=False))
        self.loads.append(path)


class RegressionSet(torch.utils.data.Dataset):
    def __init__(self, n=256):
        g = torch.Generator().manual_seed(0)
        self.x = torch.randn(n, 4, generator=g)
        self.y = self.x @ torch.tensor([1.0, -2.0, 3.0, 0.5]).unsqueeze(1)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class LinearDSTrial(DeepSpeedTrial):
    def __init__(self, context: DeepSpeedTrialContext):
        super().__init__(context)
        self.engine = context.wrap_model_engine(
            FakeEngine(torch.nn.Linear(4, 1)))
        self.loss_fn = torch.nn.MSELoss()

    def build_training_data_loader(self):
        return DataLoader(RegressionSet(), batch_size=8, shuffle=True)

    def build_validation_data_loader(self):
        return DataLoader(RegressionSet(64), batch_size=8)

    def train_batch(self, dataloader_iter, epoch_idx, batch_idx):
        # Reference semantics: pull num_micro_batches_per_slot microbatches
        # and drive engine.backward/step per microbatch.
        total = 0.0
        n = self.context.num_micro_batches_per_slot()
        for _ in range(n):
            x, y = next(dataloader_iter)
            loss = self.loss_fn(self.engine(x), y)
            self.engine.backward(loss)
            self.engine.step()
            total += loss.item()
        return {"loss": total / n}

    def evaluate_batch(self, dataloader_iter, batch_idx):
        x, y = next(dataloader_iter)
        with torch.no_grad():
            return {"val_loss": self.loss_fn(self.engine(x), y).item()}


def test_deepspeed_trial_local(tmp_path):
    ctx_core = core.init(max_length=20, checkpoint_dir=str(tmp_path))
    trial = LinearDSTrial(DeepSpeedTrialContext(hparams={}))
    trial.context._core = ctx_core
    steps = DeepSpeedTrainer(trial, core_context=ctx_core).fit(
        searcher_metric="val_loss", report_period=5)
    assert steps == 20
    # one engine step per microbatch, grad_accum microbatches per train step
    assert trial.engine.step_calls == 20 * 2
    assert trial.engine.backward_calls == 20 * 2
    tm = ctx_core.train.local_training_metrics
    assert tm and tm[-1]["metrics"]["loss"] < tm[0]["metrics"]["loss"]
    assert ctx_core.checkpoint.local_reported, "engine checkpoint reported"
    assert trial.engine.saves, "engine-sharded save must have run"
    ctx_core.close()


def test_deepspeed_restore_roundtrip(tmp_path):
    ctx_core = core.init(max_length=6, checkpoint_dir=str(tmp_path))
    trial = LinearDSTrial(DeepSpeedTrialContext(hparams={}))
    trial.context._core = ctx_core
    DeepSpeedTrainer(trial, core_context=ctx_core).fit(
        searcher_metric="val_loss")
    sid = ctx_core.checkpoint.local_reported[-1]["uuid"]
    want = trial.engine.module.weight.detach().clone()
    ctx_core.close()

    # Fresh process-equivalent. Local mode has no ClusterInfo, so
    # core.latest_checkpoint is None — inject the id the way a managed
    # restart would deliver it (DET_LATEST_CHECKPOINT → ClusterInfo).
    ctx2 = core.init(max_length=6, checkpoint_dir=str(tmp_path))
    trial2 = LinearDSTrial(DeepSpeedTrialContext(hparams={}))
    trial2.context._core = ctx2
    trainer2 = DeepSpeedTrainer(trial2, core_context=ctx2)

    class _FakeInfo:
        class trial:  # noqa: N801 — attribute shape of ClusterInfo
            latest_checkpoint = sid

    ctx2.info = _FakeInfo()
    restored = trainer2._restore()
    assert restored == 6
    assert trial2.engine.loads
    assert torch.allclose(trial2.engine.module.weight, want)
    ctx2.close()


def test_mpu_gates_data_and_reporting(tmp_path):
    """A model-parallel rank that owns no data loader receives iterator
    None and must not report metrics."""
    ctx_core = core.init(max_length=2, checkpoint_dir=str(tmp_path))

    class MpTrial(LinearDSTrial):
        def __init__(self, context):
            super().__init__(context)
            context.wrap_mpu(ModelParallelUnit(
                data_parallel_rank=0, data_parallel_world_size=1,
                should_report_metrics=False,
                should_build_data_loader=False))
            self.saw_iters = []

        def train_batch(self, dataloader_iter, epoch_idx, batch_idx):
            self.saw_iters.append(dataloader_iter)
            # activation-fed rank: no data, still drives the engine
            self.engine.step()
            return {"loss": 0.0}

        def evaluate_batch(self, dataloader_iter, batch_idx):
            self.saw_iters.append(dataloader_iter)
            return {"val_loss": 0.0}

    trial = MpTrial(DeepSpeedTrialContext(hparams={}))
    trial.context._core = ctx_core
    DeepSpeedTrainer(trial, core_context=ctx_core).fit(
        searcher_metric="val_loss")
    assert all(it is None for it in trial.saw_iters)
    assert not ctx_core.train.local_training_metrics
    ctx_core.close()


def test_auto_grad_accum_disable():
    trial = LinearDSTrial(DeepSpeedTrialContext(hparams={}))
    assert trial.context.num_micro_batches_per_slot() == 2
    trial.context.disable_auto_grad_accumulation()
    assert trial.context.num_micro_batches_per_slot() == 1
    assert trial.context.get_train_micro_batch_size_per_gpu() == 8


def test_trainer_requires_engine(tmp_path):
    ctx_core = core.init(max_length=2, checkpoint_dir=str(tmp_path))

    class NoEngine(DeepSpeedTrial):
        def __init__(self, context):
            super().__init__(context)

    t = NoEngine(DeepSpeedTrialContext(hparams={}))
    t.context._core = ctx_core
    with pytest.raises(ValueError, match="wrap_model_engine"):
        DeepSpeedTrainer(t, core_context=ctx_core)
    ctx_core.close()
