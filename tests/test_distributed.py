"""Control-plane collectives: threads-as-hosts, arbitrary python objects.

The TPU analogue of the reference's `parallel.Execution` harness
(harness/tests/parallel.py:15-60 — N threads, each with a real
DistributedContext over localhost ZMQ): here N threads each hold a
DistributedContext over a shared-memory byte transport, exercising the same
pickle framing the production multihost path uses."""

import concurrent.futures as cf
import os

import numpy as np
import pytest

from determined_tpu.core._checkpoint import CheckpointContext
from determined_tpu.core._distributed import (
    DistributedContext,
    _JaxTransport,
    make_thread_transports,
)
from determined_tpu.storage.base import SharedFSStorageManager


def run_ranks(size, fn):
    """Run fn(dist_context) on `size` threads-as-hosts; returns per-rank results."""
    transports = make_thread_transports(size)
    ctxs = [DistributedContext.for_test(r, size, transports[r]) for r in range(size)]
    with cf.ThreadPoolExecutor(size) as pool:
        return list(pool.map(fn, ctxs))


class TestObjectCollectives:
    def test_allgather_mixed_objects(self):
        """Dicts, strings, arrays — not just numerics (round-1/2 gap)."""

        def work(dist):
            obj = {
                "rank": dist.rank,
                "name": f"host-{dist.rank}",
                "files": [f"shard{dist.rank}.bin"],
                "arr": np.arange(dist.rank + 1),
            }
            return dist.allgather(obj)

        results = run_ranks(4, work)
        for got in results:
            assert [g["rank"] for g in got] == [0, 1, 2, 3]
            assert got[2]["name"] == "host-2"
            np.testing.assert_array_equal(got[3]["arr"], np.arange(4))

    def test_gather_chief_only(self):
        def work(dist):
            return dist.gather(f"payload-{dist.rank}")

        results = run_ranks(3, work)
        assert results[0] == ["payload-0", "payload-1", "payload-2"]
        assert results[1] is None and results[2] is None

    def test_broadcast_object(self):
        def work(dist):
            src = {"cfg": [1, 2, 3], "id": "abc"} if dist.is_chief else None
            return dist.broadcast(src)

        results = run_ranks(4, work)
        assert all(r == {"cfg": [1, 2, 3], "id": "abc"} for r in results)

    def test_empty_payloads(self):
        def work(dist):
            return dist.allgather("" if dist.rank % 2 else {})

        results = run_ranks(2, work)
        assert results[0] == [{}, ""]

    def test_single_process_shortcuts(self):
        dist = DistributedContext.local()
        assert dist.allgather({"a": 1}) == [{"a": 1}]
        assert dist.gather("x") == ["x"]
        assert dist.broadcast(7) == 7


class TestJaxTransport:
    """Single-process sanity of the production byte plane (multi-process is
    covered by dryrun_multichip / real allocations)."""

    def test_allgather_bytes(self):
        t = _JaxTransport()
        out = t.allgather_bytes(b"hello world")
        assert out == [b"hello world"]

    def test_broadcast_bytes(self):
        t = _JaxTransport()
        assert t.broadcast_bytes(b"payload", True) == b"payload"

    def test_empty(self):
        t = _JaxTransport()
        assert t.allgather_bytes(b"") == [b""]


class TestShardedCheckpointMetadataMerge:
    """Reference core/_checkpoint.py:282 — every rank uploads its shard, the
    chief registers the MERGED file list gathered over the object plane."""

    def test_sharded_upload_merges_resources(self, tmp_path):
        storage_root = tmp_path / "storage"

        def work(dist):
            storage = SharedFSStorageManager(str(storage_root))
            ctx = CheckpointContext(None, storage, trial_id=5, distributed=dist)
            src = tmp_path / f"rank{dist.rank}"
            src.mkdir(exist_ok=True)
            shard = src / f"shard-{dist.rank}.bin"
            shard.write_bytes(b"x" * (100 + dist.rank))
            sid = ctx.upload(str(src), metadata={"steps_completed": 7}, shard=True)
            return ctx, sid

        results = run_ranks(4, work)
        ctxs, sids = zip(*results)
        # all ranks agreed on the storage id (broadcast as a string)
        assert len(set(sids)) == 1
        # only the chief reported, with the merged resource list
        assert [len(c.local_reported) for c in ctxs] == [1, 0, 0, 0]
        record = ctxs[0].local_reported[0]
        assert record["resources"] == {
            "shard-0.bin": 100,
            "shard-1.bin": 101,
            "shard-2.bin": 102,
            "shard-3.bin": 103,
        }
        # and the files are really there
        stored = os.listdir(storage_root / sids[0])
        assert sorted(f for f in stored if f.startswith("shard")) == [
            "shard-0.bin",
            "shard-1.bin",
            "shard-2.bin",
            "shard-3.bin",
        ]

    def test_selector_limits_shard_upload(self, tmp_path):
        storage_root = tmp_path / "storage"

        def work(dist):
            storage = SharedFSStorageManager(str(storage_root))
            ctx = CheckpointContext(None, storage, trial_id=6, distributed=dist)
            src = tmp_path / f"sel-rank{dist.rank}"
            src.mkdir(exist_ok=True)
            (src / f"keep-{dist.rank}.bin").write_bytes(b"k")
            (src / f"drop-{dist.rank}.tmp").write_bytes(b"d")
            return ctx, ctx.upload(
                str(src), shard=True, selector=lambda n: n.endswith(".bin")
            )

        results = run_ranks(2, work)
        record = results[0][0].local_reported[0]
        assert set(record["resources"]) == {"keep-0.bin", "keep-1.bin"}
