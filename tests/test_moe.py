"""Expert parallelism: MoE block (ops/moe.py) + Ulysses attention
(ops/ulysses.py) + the decoy-axis guard (VERDICT r3 #6).

Runs on the 8-device virtual CPU mesh (conftest). The reference has no
MoE/sequence parallelism at all (SURVEY.md §2.4) — these are TPU-first
capabilities with no reference counterpart.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec

from determined_tpu.models import gpt2
from determined_tpu.ops.moe import init_moe, moe_block
from determined_tpu.parallel.mesh import MeshConfig, create_mesh


def _moe_setup(num_experts=4, b=2, s=16, d=8, f=16, seed=0):
    rng = jax.random.PRNGKey(seed)
    k_p, k_x = jax.random.split(rng)
    params = init_moe(k_p, d, f, num_experts)
    x = jax.random.normal(k_x, (b, s, d), jnp.float32)
    return params, x


class TestMoEBlock:
    def test_sharded_matches_replicated(self, devices):
        """Expert-parallel execution must be numerically identical to the
        single-device replicated run — the dispatch/combine einsums are
        the same math, only laid out over the expert axis."""
        params, x = _moe_setup(num_experts=4)
        y_ref, aux_ref = jax.jit(
            lambda p, xx: moe_block(xx, p, 4, capacity_factor=2.0)
        )(params, x)

        mesh = create_mesh(MeshConfig(data=2, expert=4).resolve(8), devices)
        with jax.sharding.set_mesh(mesh):
            y_sh, aux_sh = jax.jit(
                lambda p, xx: moe_block(xx, p, 4, capacity_factor=2.0)
            )(params, x)
        np.testing.assert_allclose(
            np.asarray(y_ref), np.asarray(y_sh), rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(
            float(aux_ref), float(aux_sh), rtol=1e-6)

    def test_capacity_overflow_drops_tokens(self):
        """With capacity 1 slot per expert, overflowing tokens contribute
        zero output (Switch drop semantics: the residual carries them)."""
        params, x = _moe_setup(num_experts=2, b=1, s=8)
        y, _ = jax.jit(
            lambda p, xx: moe_block(xx, p, 2, top_k=1, capacity_factor=0.25)
        )(params, x)
        # capacity = ceil(8/2*0.25) = 1 → at most 2 of 8 tokens routed.
        nonzero = np.abs(np.asarray(y)).sum(axis=-1)[0] > 1e-9
        assert nonzero.sum() <= 2, nonzero

    def test_aux_loss_uniform_routing_is_one(self):
        """Balanced router ⇒ aux = E · Σ (1/E)·(1/E) = 1 (its minimum)."""
        d, f, e = 8, 16, 4
        params = init_moe(jax.random.PRNGKey(0), d, f, e)
        # Zero router → uniform probs; top_k then picks arbitrary-but-fixed
        # experts, only aux's f-term varies. Use the probs term: with zero
        # logits p_e = 1/E exactly, so aux = Σ f_e / E · E = 1.
        params["router"]["kernel"] = jnp.zeros((d, e), jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, d), jnp.float32)
        _, aux = moe_block(x, params, e, capacity_factor=2.0)
        assert abs(float(aux) - 1.0) < 1e-5

    def test_experts_differ(self):
        """Routing must actually send tokens to different experts (outputs
        change when one expert's weights are perturbed)."""
        params, x = _moe_setup(num_experts=4, b=2, s=32)
        y0, _ = moe_block(x, params, 4, capacity_factor=2.0)
        p2 = jax.tree_util.tree_map(lambda a: a, params)
        p2["down"]["kernel"] = p2["down"]["kernel"].at[0].mul(5.0)
        y1, _ = moe_block(x, p2, 4, capacity_factor=2.0)
        assert not np.allclose(np.asarray(y0), np.asarray(y1))


class TestGPT2MoE:
    def test_moe_forward_and_grad(self, devices):
        cfg = gpt2.Config(
            vocab_size=128, n_positions=32, d_model=16, n_layer=2, n_head=2,
            attention_impl="dot", remat=False, num_experts=4,
        )
        params = gpt2.init(jax.random.PRNGKey(0), cfg)
        assert "moe" in params["blocks"] and "mlp_up" not in params["blocks"]
        batch = {"tokens": np.random.default_rng(0).integers(
            0, 128, size=(4, 17)).astype(np.int32)}

        mesh = create_mesh(MeshConfig(data=2, expert=4).resolve(8), devices)
        with jax.sharding.set_mesh(mesh):
            loss, grads = jax.jit(jax.value_and_grad(
                lambda p: gpt2.loss_fn(p, batch, cfg)))(params)
        assert np.isfinite(float(loss))
        gnorm = jax.tree_util.tree_reduce(
            lambda a, g: a + float(jnp.sum(jnp.abs(g))), grads, 0.0)
        assert gnorm > 0
        # Router must receive gradient (it only gets one through the
        # combine weights — an easy thing to break).
        assert float(jnp.sum(jnp.abs(
            grads["blocks"]["moe"]["router"]["kernel"]))) > 0

    def test_expert_params_actually_sharded(self, devices):
        from determined_tpu.train import create_train_state
        import optax

        cfg = gpt2.Config(
            vocab_size=128, n_positions=32, d_model=16, n_layer=2, n_head=2,
            attention_impl="dot", remat=False, num_experts=4,
        )
        mesh = create_mesh(MeshConfig(data=2, expert=4).resolve(8), devices)
        with jax.sharding.set_mesh(mesh):
            state = create_train_state(
                lambda r: gpt2.init(r, cfg), optax.sgd(1e-2),
                jax.random.PRNGKey(0), mesh=mesh,
                param_logical_axes=gpt2.param_logical_axes(cfg),
            )
        spec = state.params["blocks"]["moe"]["up"]["kernel"].sharding.spec
        assert "expert" in str(spec), spec


class TestUlysses:
    def test_matches_dense_attention(self, devices):
        from determined_tpu.ops.ulysses import ulysses_attention, _inner_attention

        b, s, h, dh = 2, 32, 4, 8
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.normal(size=(b, s, h, dh)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(b, s, h, dh)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(b, s, h, dh)), jnp.float32)
        want = _inner_attention(q, k, v, causal=True)

        mesh = create_mesh(MeshConfig(data=2, context=4).resolve(8), devices)
        sh = NamedSharding(mesh, PartitionSpec("data", "context", None, None))
        with jax.sharding.set_mesh(mesh):
            got = jax.jit(
                lambda a, bb, c: ulysses_attention(a, bb, c, causal=True),
                in_shardings=(sh, sh, sh),
            )(q, k, v)
        np.testing.assert_allclose(
            np.asarray(want), np.asarray(got), rtol=2e-4, atol=2e-4)

    def test_gpt2_ulysses_matches_dot(self, devices):
        base = dict(vocab_size=128, n_positions=64, d_model=16, n_layer=2,
                    n_head=4, remat=False)
        cfg_dot = gpt2.Config(attention_impl="dot", **base)
        cfg_ul = gpt2.Config(attention_impl="ulysses", **base)
        params = gpt2.init(jax.random.PRNGKey(0), cfg_dot)
        tokens = np.random.default_rng(0).integers(
            0, 128, size=(4, 32)).astype(np.int32)
        want = gpt2.apply(params, tokens, cfg_dot)

        mesh = create_mesh(MeshConfig(data=2, context=4).resolve(8), devices)
        with jax.sharding.set_mesh(mesh):
            got = jax.jit(lambda p, t: gpt2.apply(p, t, cfg_ul))(
                params, tokens)
        np.testing.assert_allclose(
            np.asarray(want, np.float32), np.asarray(got, np.float32),
            rtol=3e-2, atol=3e-2)  # bf16 activations

    def test_head_divisibility_rejected(self, devices):
        from determined_tpu.ops.ulysses import ulysses_attention

        mesh = create_mesh(MeshConfig(data=2, context=4).resolve(8), devices)
        q = jnp.zeros((2, 32, 6, 8), jnp.float32)  # 6 heads % 4 != 0
        with jax.sharding.set_mesh(mesh):
            with pytest.raises(ValueError, match="divisible"):
                jax.jit(lambda a: ulysses_attention(a, a, a))(q)

    def test_local_head_divisibility_with_tensor_axis(self, devices):
        """With tensor>1 the heads are already sharded, so the all_to_all
        splits the LOCAL head count: n_head=4 over tensor=2 leaves 2 local
        heads, which context=4 cannot split — must raise clearly, not die
        inside XLA (ADVICE r4)."""
        from determined_tpu.ops.ulysses import ulysses_attention

        mesh = create_mesh(MeshConfig(tensor=2, context=4).resolve(8), devices)
        q = jnp.zeros((2, 32, 4, 8), jnp.float32)  # global 4 % cp 4 == 0!
        with jax.sharding.set_mesh(mesh):
            with pytest.raises(ValueError, match="per-shard head count"):
                jax.jit(lambda a: ulysses_attention(a, a, a))(q)


class TestExpertAxisGuard:
    def test_dense_trial_rejects_expert_axis(self, devices):
        """mesh expert>1 on a trial without MoE support must fail loudly
        (the round-3 decoy-axis trap), mirroring the pipeline guard."""
        from determined_tpu.train import JaxTrial, Trainer
        from determined_tpu.train.trial import TrialContext

        class Dense(JaxTrial):
            def init_params(self, rng):
                return {"w": jnp.zeros((2, 2))}

            def loss(self, params, batch, rng):
                return jnp.sum(params["w"] ** 2)

            def build_training_data(self):
                while True:
                    yield {}

            def mesh_config(self):
                return MeshConfig(data=-1, expert=2)

        trainer = Trainer(Dense(TrialContext()), devices=devices)
        with pytest.raises(ValueError, match="expert"):
            trainer._build(seed=0)

    def test_moe_trial_accepts_expert_axis(self, devices):
        import sys, os
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "examples", "gpt2"))
        try:
            from model_def import GPT2Trial
        finally:
            sys.path.pop(0)
        from determined_tpu.train import Trainer
        from determined_tpu.train.trial import TrialContext

        hp = {"model_size": "tiny", "num_experts": 4, "attention_impl": "dot",
              "mesh": {"data": 2, "expert": 4}, "global_batch_size": 8,
              "scan_unroll": 1}
        trial = GPT2Trial(TrialContext(hparams=hp, n_devices=8))
        assert trial.supports_expert_parallel()
        trainer = Trainer(trial, devices=devices)
        trainer._build(seed=0)  # must not raise
