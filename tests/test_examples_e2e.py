"""The shipped examples must actually run — via the CLI, like the README says.

Reference anchor: e2e_tests/tests/experiment/ runs the reference's example
configs on a devcluster; here the README quickstart commands are executed
verbatim (CLI `experiment create <config> <context> --follow`) against the
C++ master+agent.
"""

import os
import subprocess
import sys
import time

import pytest

from tests.test_platform_e2e import Devcluster, _wait_experiment

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")


@pytest.fixture()
def cluster(tmp_path, native_binaries):
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    c.start_agent()
    yield c
    c.stop()


@pytest.fixture(scope="session")
def native_binaries():
    subprocess.run(
        ["make", "-C", os.path.join(REPO, "native")], check=True,
        capture_output=True,
    )
    return os.path.join(REPO, "native", "bin")


def _cli(cluster, *args, timeout=300):
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
        HOME=cluster.tmpdir,  # isolate the CLI token cache
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)  # keep the CLI off the axon plugin
    return subprocess.run(
        [sys.executable, "-m", "determined_tpu.cli",
         "-m", cluster.master_url, *args],
        capture_output=True, text=True, env=env, timeout=timeout,
    )


def _patch_storage(tmp_path, config_path, mutate=None):
    """Point the example's checkpoint_storage at the test tmpdir; `mutate`
    may shrink the config further (test-size lengths/models)."""
    import yaml

    with open(config_path) as f:
        cfg = yaml.safe_load(f)
    cfg["checkpoint_storage"]["host_path"] = os.path.join(str(tmp_path), "ckpts")
    if mutate is not None:
        mutate(cfg)
    out = os.path.join(str(tmp_path), os.path.basename(config_path))
    with open(out, "w") as f:
        yaml.safe_dump(cfg, f)
    return out


def test_mnist_example_quickstart(cluster, tmp_path):
    """The README quickstart command, verbatim (storage redirected)."""
    cfg = _patch_storage(tmp_path, os.path.join(EXAMPLES, "mnist", "config.yaml"))
    r = _cli(cluster, "experiment", "create", cfg,
             os.path.join(EXAMPLES, "mnist"), "--follow", timeout=600)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "COMPLETED" in r.stdout, r.stdout[-2000:]

    token = cluster.login()
    trials = cluster.api("GET", "/api/v1/experiments/1/trials", token=token)[
        "trials"]
    assert trials and trials[0]["state"] == "COMPLETED"
    metrics = cluster.api(
        "GET", f"/api/v1/trials/{trials[0]['id']}/metrics",
        token=token)["metrics"]
    assert any(m["group_name"] == "validation" for m in metrics)
    cps = cluster.api("GET", "/api/v1/experiments/1/checkpoints",
                      token=token)["checkpoints"]
    assert cps, "example must produce a checkpoint"


def test_gpt2_example(cluster, tmp_path):
    cfg = _patch_storage(tmp_path, os.path.join(EXAMPLES, "gpt2", "config.yaml"))
    r = _cli(cluster, "experiment", "create", cfg,
             os.path.join(EXAMPLES, "gpt2"), "--follow", timeout=600)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "COMPLETED" in r.stdout, r.stdout[-2000:]


def test_mnist_adaptive_example(cluster, tmp_path):
    """The shipped adaptive_asha config runs a real multi-trial search
    (shrunk trial count/length)."""
    def shrink(cfg):
        cfg["searcher"].update(max_trials=4, max_length={"batches": 8})
        cfg["hyperparameters"]["global_batch_size"] = 32

    out = _patch_storage(
        tmp_path, os.path.join(EXAMPLES, "mnist", "adaptive.yaml"), shrink)
    r = _cli(cluster, "experiment", "create", out,
             os.path.join(EXAMPLES, "mnist"), "--follow", timeout=900)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "COMPLETED" in r.stdout, r.stdout[-2000:]
    token = cluster.login()
    trials = cluster.api("GET", "/api/v1/experiments/1/trials",
                         token=token)["trials"]
    assert len(trials) == 4  # the search really ran multiple trials


def test_hf_trainer_example(cluster, tmp_path):
    """The shipped HF-Trainer DetCallback example, shrunk."""
    def shrink(cfg):
        cfg["searcher"]["max_length"] = {"batches": 4}
        cfg["hyperparameters"].update(max_steps=4, eval_steps=4, seq_len=32)

    out = _patch_storage(
        tmp_path, os.path.join(EXAMPLES, "hf_trainer", "config.yaml"), shrink)
    r = _cli(cluster, "experiment", "create", out,
             os.path.join(EXAMPLES, "hf_trainer"), "--follow", timeout=600)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "COMPLETED" in r.stdout, r.stdout[-2000:]


def test_cifar10_keras_distributed_example(cluster, tmp_path):
    """The BASELINE CIFAR-10 KerasTrial workload, shrunk: DataParallel over
    the trial's 8-device CPU mesh through the full platform."""
    import yaml

    with open(os.path.join(EXAMPLES, "cifar10_keras", "distributed.yaml")) as f:
        cfg = yaml.safe_load(f)
    cfg["checkpoint_storage"]["host_path"] = os.path.join(str(tmp_path), "ckpts")
    cfg["searcher"]["max_length"] = {"batches": 2}
    cfg["hyperparameters"].update(width=8, blocks_per_stage=1,
                                  global_batch_size=64)
    cfg["resources"]["slots_per_trial"] = 2
    out = os.path.join(str(tmp_path), "cifar.yaml")
    with open(out, "w") as f:
        yaml.safe_dump(cfg, f)
    r = _cli(cluster, "experiment", "create", out,
             os.path.join(EXAMPLES, "cifar10_keras"), "--follow", timeout=600)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "COMPLETED" in r.stdout, r.stdout[-2000:]


def test_gpt2_torch_distributed_example(cluster, tmp_path):
    """The torch compat GPT-2 workload, shrunk: 2-process DDP (gloo) via the
    torch_distributed launch layer inside a managed task."""
    import yaml

    with open(os.path.join(EXAMPLES, "gpt2_torch", "distributed.yaml")) as f:
        cfg = yaml.safe_load(f)
    cfg["checkpoint_storage"]["host_path"] = os.path.join(str(tmp_path), "ckpts")
    cfg["searcher"]["max_length"] = {"batches": 2}
    cfg["hyperparameters"].update(
        model_size="tiny", seq_len=32, per_device_batch_size=4, fsdp=False)
    cfg["resources"]["slots_per_trial"] = 2
    cfg["entrypoint"] = (
        "python3 -m determined_tpu.launch.torch_distributed "
        "--nproc-per-node 2 -- python3 model_def.py"
    )
    out = os.path.join(str(tmp_path), "gpt2_torch.yaml")
    with open(out, "w") as f:
        yaml.safe_dump(cfg, f)
    r = _cli(cluster, "experiment", "create", out,
             os.path.join(EXAMPLES, "gpt2_torch"), "--follow", timeout=600)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "COMPLETED" in r.stdout, r.stdout[-2000:]
    # both ranks ran (wrap_rank prefixes) and DDP-wrapped training reported
    assert "[rank=0]" in r.stdout and "[rank=1]" in r.stdout, r.stdout[-2000:]


def test_gpt_neox_zero1_example(cluster, tmp_path):
    """BASELINE config 4: GPT-NeoX through the DeepSpeedTrial API with the
    TPU-native ZeRO-1 engine, shrunk to 2 processes (gloo) in a managed
    task. The shipped zero1.yaml is this with 410m/64 slots."""
    import yaml

    with open(os.path.join(EXAMPLES, "gpt_neox", "zero1.yaml")) as f:
        cfg = yaml.safe_load(f)
    cfg["checkpoint_storage"]["host_path"] = os.path.join(str(tmp_path), "ckpts")
    cfg["searcher"]["max_length"] = {"batches": 2}
    cfg["hyperparameters"].update(
        model_size="tiny", seq_len=32, micro_batch_size=2,
        gradient_accumulation=2)
    cfg["resources"]["slots_per_trial"] = 2
    cfg["entrypoint"] = (
        "python3 -m determined_tpu.launch.torch_distributed "
        "--nproc-per-node 2 -- python3 model_def.py"
    )
    out = os.path.join(str(tmp_path), "gpt_neox.yaml")
    with open(out, "w") as f:
        yaml.safe_dump(cfg, f)
    r = _cli(cluster, "experiment", "create", out,
             os.path.join(EXAMPLES, "gpt_neox"), "--follow", timeout=600)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "COMPLETED" in r.stdout, r.stdout[-2000:]
    assert "[rank=0]" in r.stdout and "[rank=1]" in r.stdout, r.stdout[-2000:]
    # the engine partitioned the optimizer across the two workers
    assert "zero1: rank 0/2" in r.stdout and "zero1: rank 1/2" in r.stdout, \
        r.stdout[-2000:]


def test_diffusion_finetune_asha_example(cluster, tmp_path):
    """BASELINE config 5: diffusion finetune + adaptive_asha across
    sub-slices, shrunk: tiny UNet, 2-slot trials on the 2-slot agent,
    3-trial search. Also exercises the finetune path: a pretrained pickle
    is produced first and pretrained_path points at it."""
    import yaml

    # Pretrain for real (tiny, 4 steps) via the shipped script — this is
    # pretrain.py's only end-to-end coverage, don't hand-pickle instead.
    pre = os.path.join(str(tmp_path), "pretrained.pkl")
    env = dict(os.environ,
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
               JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    pr = subprocess.run(
        [sys.executable, "-m", "examples.diffusion.pretrain",
         "--steps", "4", "--batch", "8", "--model-size", "tiny",
         "--out", pre],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert pr.returncode == 0, pr.stdout[-2000:] + pr.stderr[-2000:]
    assert os.path.exists(pre)

    with open(os.path.join(EXAMPLES, "diffusion", "finetune_asha.yaml")) as f:
        cfg = yaml.safe_load(f)
    cfg["checkpoint_storage"]["host_path"] = os.path.join(str(tmp_path), "ckpts")
    cfg["searcher"].update(max_trials=3, max_length={"batches": 4})
    cfg["hyperparameters"].update(
        model_size="tiny", global_batch_size=8, pretrained_path=pre)
    cfg["resources"]["slots_per_trial"] = 2
    out = os.path.join(str(tmp_path), "diffusion.yaml")
    with open(out, "w") as f:
        yaml.safe_dump(cfg, f)
    r = _cli(cluster, "experiment", "create", out,
             os.path.join(EXAMPLES, "diffusion"), "--follow", timeout=900)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "COMPLETED" in r.stdout, r.stdout[-2000:]
    token = cluster.login()
    trials = cluster.api("GET", "/api/v1/experiments/1/trials",
                         token=token)["trials"]
    assert len(trials) == 3  # the search really ran multiple trials


def test_gpt2_pipeline_example(cluster, tmp_path):
    """pipeline.yaml runs the GPipe path: mesh.pipeline=2 makes the Trainer
    select loss_pipelined inside the spawned trial (8-device CPU mesh via the
    conftest XLA_FLAGS the agent inherits), shrunk to test size."""
    import yaml

    with open(os.path.join(EXAMPLES, "gpt2", "pipeline.yaml")) as f:
        cfg = yaml.safe_load(f)
    cfg["checkpoint_storage"]["host_path"] = os.path.join(str(tmp_path), "ckpts")
    cfg["searcher"]["max_length"] = {"batches": 2}
    cfg["hyperparameters"].update(
        model_size="tiny", seq_len=16, global_batch_size=8,
        mesh={"pipeline": 2, "data": -1})
    cfg["resources"]["slots_per_trial"] = 2
    out = os.path.join(str(tmp_path), "pipeline.yaml")
    with open(out, "w") as f:
        yaml.safe_dump(cfg, f)
    r = _cli(cluster, "experiment", "create", out,
             os.path.join(EXAMPLES, "gpt2"), "--follow", timeout=600)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "COMPLETED" in r.stdout, r.stdout[-2000:]


def test_gpt2_long_context_example(cluster, tmp_path):
    """long_context.yaml runs sequence parallelism inside the spawned
    trial: mesh.context=2 shards the sequence, ulysses all-to-all head
    sharding computes attention (ring needs the pallas kernel's TPU
    shapes; ulysses exercises the same context axis on the CPU mesh).
    seq_len 256 deliberately EXCEEDS tiny's n_positions=128 so the
    config's defining behavior — widening the position table for long
    context — is what the test exercises."""
    def shrink(cfg):
        cfg["searcher"]["max_length"] = {"batches": 2}
        cfg["hyperparameters"].update(
            model_size="tiny", seq_len=256, global_batch_size=4,
            attention_impl="ulysses", scan_unroll=1, remat=False,
            mesh={"context": 2, "data": -1})
        cfg["resources"]["slots_per_trial"] = 2

    out = _patch_storage(
        tmp_path, os.path.join(EXAMPLES, "gpt2", "long_context.yaml"),
        shrink)
    r = _cli(cluster, "experiment", "create", out,
             os.path.join(EXAMPLES, "gpt2"), "--follow", timeout=600)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "COMPLETED" in r.stdout, r.stdout[-2000:]


def test_gpt2_moe_example(cluster, tmp_path):
    """moe.yaml routes every block's FFN over the expert mesh axis inside
    the spawned trial (expert=2 on the agent's 8-device CPU mesh)."""
    import yaml

    with open(os.path.join(EXAMPLES, "gpt2", "moe.yaml")) as f:
        cfg = yaml.safe_load(f)
    cfg["checkpoint_storage"]["host_path"] = os.path.join(str(tmp_path), "ckpts")
    cfg["searcher"]["max_length"] = {"batches": 2}
    cfg["hyperparameters"].update(
        model_size="tiny", seq_len=16, global_batch_size=8, num_experts=4,
        attention_impl="dot", scan_unroll=1,
        mesh={"expert": 2, "data": -1})
    cfg["resources"]["slots_per_trial"] = 2
    out = os.path.join(str(tmp_path), "moe.yaml")
    with open(out, "w") as f:
        yaml.safe_dump(cfg, f)
    r = _cli(cluster, "experiment", "create", out,
             os.path.join(EXAMPLES, "gpt2"), "--follow", timeout=600)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "COMPLETED" in r.stdout, r.stdout[-2000:]
