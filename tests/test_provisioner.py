"""GCP provisioner against a fake TPU API: the full node lifecycle
(VERDICT r4 missing #2 / weak #7).

Reference: rm/agentrm/provisioner/aws/aws_spot.go creates and terminates
cloud instances itself and tolerates spot interruption; scaledecider
terminates idle instances. Here the executor speaks the TPU-VM REST shape
(tpu.googleapis.com v2: nodes create/list/delete) against a fake server,
while REAL agents play the booted VMs: the test starts an agent named
after each created node, so the scheduler path runs for real end to end.
"""

import json
import os
import subprocess
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tests.test_platform_e2e import (  # noqa: F401
    Devcluster,
    _create_experiment,
    _experiment_config,
    _wait_experiment,
    _wait_http,
    native_binaries,
)


class FakeTpuApi:
    """tpu.googleapis.com-shaped fake: nodes create/list/delete."""

    def __init__(self):
        self.nodes = {}   # name -> {"state": ..., "body": ...}
        self.creates = []
        self.deletes = []
        self.fail_creates = False  # 500 every create (failure-storm tests)
        self.failed_creates = []
        self.lock = threading.Lock()
        outer = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                if "/nodes" in self.path and "nodeId=" in self.path:
                    name = self.path.split("nodeId=")[1].split("&")[0]
                    with outer.lock:
                        if outer.fail_creates:
                            outer.failed_creates.append(name)
                            return self._json(
                                500, {"error": "quota exceeded (fake)"})
                        outer.nodes[name] = {"state": "READY", "body": body}
                        outer.creates.append({"name": name, **body})
                    return self._json(200, {"name": name})
                self._json(404, {})

            def do_GET(self):
                if self.path.endswith("/nodes"):
                    with outer.lock:
                        items = [
                            {"name": f"projects/p/locations/z/nodes/{n}",
                             "state": v["state"]}
                            for n, v in outer.nodes.items()
                        ]
                    return self._json(200, {"nodes": items})
                self._json(404, {})

            def do_DELETE(self):
                name = self.path.rsplit("/", 1)[-1]
                with outer.lock:
                    outer.deletes.append(name)
                    outer.nodes.pop(name, None)
                self._json(200, {})

        self.srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.url = f"http://127.0.0.1:{self.srv.server_address[1]}"
        threading.Thread(target=self.srv.serve_forever, daemon=True).start()

    def interrupt(self, name):
        """Spot interruption: the node vanishes out-of-band."""
        with self.lock:
            self.nodes.pop(name, None)

    def node_names(self):
        with self.lock:
            return sorted(self.nodes)

    def stop(self):
        self.srv.shutdown()


def _scrape_metrics(cluster, token):
    """GET /metrics → {series_name_with_labels: float}."""
    import urllib.request

    req = urllib.request.Request(
        cluster.master_url + "/metrics",
        headers={"Authorization": f"Bearer {token}"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        text = resp.read().decode()
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name, value = line.rsplit(" ", 1)
        try:
            out[name] = float(value)
        except ValueError:
            pass
    return out


def _wait(cond, timeout=45, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(0.2)
    raise TimeoutError(f"timed out waiting for {what}")


@pytest.fixture()
def prov_cluster(tmp_path, native_binaries):  # noqa: F811
    fake = FakeTpuApi()
    cfg = {
        # Must exceed the agent's 10s heartbeat period or live agents flap
        # dead between heartbeats.
        "agent_timeout_s": 15,
        "provisioner": {
            "type": "gcp",
            "api_base": fake.url + "/v2",
            "project": "p",
            "zone": "z",
            "accelerator_type": "v5litepod-4",
            "slots_per_node": 2,
            "sustain_seconds": 0.5,
            "cooldown_seconds": 1.5,
            "idle_seconds": 2,
            "reconcile_seconds": 0.3,
            "spot": True,
        },
    }
    cfg_path = tmp_path / "master.json"
    cfg_path.write_text(json.dumps(cfg))
    c = Devcluster(str(tmp_path), native_binaries)
    c.master = subprocess.Popen(
        [os.path.join(c.binaries, "determined-master"),
         "--config", str(cfg_path),
         "--port", str(c.port), "--host", "127.0.0.1", "--db", c.db_path],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    _wait_http(c.master_url + "/api/v1/master")
    agents = []

    def boot_vm(name):
        """Play the role of the created TPU-VM: a real agent whose id is
        the node name (real deploys wire this via instance metadata)."""
        p = subprocess.Popen(
            [os.path.join(c.binaries, "determined-agent"),
             "--master-url", c.master_url,
             "--id", name,
             "--slots", "2",
             "--slot-type", "cpu",
             "--addr", "127.0.0.1",
             "--work-root", os.path.join(c.tmpdir, f"agent-{name}"),
             "--token-file", c.db_path + ".agent_token"],
            env=c.env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        agents.append(p)
        return p

    yield c, fake, boot_vm
    for p in agents:
        if p.poll() is None:
            p.kill()
            p.wait()
    c.stop()
    fake.stop()


def test_up_use_idle_down_lifecycle(prov_cluster, tmp_path):
    cluster, fake, boot_vm = prov_cluster
    token = cluster.login()

    # 1. Demand with zero capacity: a 2-slot command queues.
    cluster.api("POST", "/api/v1/commands",
                {"config": {"entrypoint": "echo provisioned-ran-ok",
                            "resources": {"slots": 2}}}, token=token)

    # 2. UP: the provisioner creates a node through the TPU API.
    _wait(lambda: fake.creates[:] or None, what="node create")
    create = fake.creates[0]
    assert create["acceleratorType"] == "v5litepod-4"
    assert create["schedulingConfig"]["preemptible"] is True
    assert create["labels"]["det-pool"] == "default"
    name = create["name"]
    assert name.startswith("det-prov-default-")

    # Fire-once accounting: while the node "boots" (no agent yet), demand
    # persists past the cooldown but launched capacity must be counted —
    # no second node.
    time.sleep(3.5)
    assert len(fake.creates) == 1, fake.creates

    # 3. USE: the VM boots (real agent registers); the task runs on it.
    boot_vm(name)
    tasks = _wait(
        lambda: [t for t in cluster.api("GET", "/api/v1/tasks",
                                        token=token)["tasks"]
                 if t["state"] == "COMPLETED"] or None,
        what="task completed on provisioned node")
    logs = cluster.api("GET", f"/api/v1/tasks/{tasks[0]['id']}/logs",
                       token=token)["logs"]
    assert any("provisioned-ran-ok" in line["log"] for line in logs)

    # 4. DOWN: with the queue empty the node idles past idle_seconds and
    # the provisioner deletes it through the API.
    _wait(lambda: name in fake.deletes or None, what="idle scale-down")
    assert fake.node_names() == []


def test_spot_interruption_fails_over(prov_cluster, tmp_path):
    cluster, fake, boot_vm = prov_cluster

    # Slow trial so the interruption lands mid-run; max_restarts gives the
    # failover budget.
    cfg = _experiment_config(
        tmp_path,
        extra={
            "resources": {"slots_per_trial": 2},
            "max_restarts": 2,
            "environment": {
                "environment_variables": ["TRIAL_STEP_SLEEP=0.6"]},
        },
    )
    eid, token = _create_experiment(cluster, cfg, activate=True)

    _wait(lambda: fake.creates[:] or None, what="node create")
    name0 = fake.creates[0]["name"]
    agent0 = boot_vm(name0)

    def trial_running():
        trials = cluster.api(
            "GET", f"/api/v1/experiments/{eid}/trials", token=token)["trials"]
        # progress proves the trial is actually training on the node
        return any(t.get("total_batches", 0) > 0 and t["state"] == "ACTIVE"
                   for t in trials) or None

    _wait(trial_running, what="trial running on provisioned node")

    # Spot interruption: the node vanishes AND its agent dies.
    fake.interrupt(name0)
    agent0.kill()
    agent0.wait()

    # The master sweeps the dead agent, the trial goes back to pending,
    # and the provisioner launches a replacement node.
    _wait(lambda: len(fake.creates) >= 2 or None, timeout=60,
          what="replacement node create")
    name1 = fake.creates[-1]["name"]
    assert name1 != name0
    boot_vm(name1)

    _wait_experiment(cluster, eid, token, timeout=180)
    trials = cluster.api(
        "GET", f"/api/v1/experiments/{eid}/trials", token=token)["trials"]
    assert trials[0]["restarts"] >= 1

    # Vanished-node postconditions (the ghost must be fully reaped):
    # the dead agent is swept (not alive), its node is gone from the
    # provisioner's tracking, and demand accounting never double-counted
    # the ghost — exactly ONE replacement node was created for the one
    # lost, even though the dead node + requeued trial coexisted for a
    # while.
    agents = {a["id"]: a for a in
              cluster.api("GET", "/api/v1/agents", token=token)["agents"]}
    assert not agents[name0]["alive"], agents[name0]
    assert len(fake.creates) == 2, [c["name"] for c in fake.creates]
    metrics = _scrape_metrics(cluster, token)
    # All demand drained once the trial finished (held demand decays
    # within demand_hysteresis_seconds).
    _wait(lambda: all(
        v == 0 for k, v in _scrape_metrics(cluster, token).items()
        if k.startswith("det_provisioner_demand_slots")) or None,
        timeout=20, what="demand gauges drained")
    assert "det_provisioner_create_failures_total" in metrics


def test_never_joined_node_cleaned_up_and_capacity_refired(
        tmp_path, native_binaries):  # noqa: F811
    """A created node whose agent never registers must stop suppressing
    scale-up after boot_grace_seconds and be deleted as broken — not
    starve the queue forever."""
    fake = FakeTpuApi()
    cfg = {
        "agent_timeout_s": 15,
        "provisioner": {
            "type": "gcp",
            "api_base": fake.url + "/v2",
            "project": "p", "zone": "z",
            "slots_per_node": 2,
            "sustain_seconds": 0.5,
            "cooldown_seconds": 1,
            "boot_grace_seconds": 3,
            "reconcile_seconds": 0.3,
        },
    }
    cfg_path = tmp_path / "master.json"
    cfg_path.write_text(json.dumps(cfg))
    c = Devcluster(str(tmp_path), native_binaries)
    c.master = subprocess.Popen(
        [os.path.join(c.binaries, "determined-master"),
         "--config", str(cfg_path),
         "--port", str(c.port), "--host", "127.0.0.1", "--db", c.db_path],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    _wait_http(c.master_url + "/api/v1/master")
    try:
        token = c.login()
        c.api("POST", "/api/v1/commands",
              {"config": {"entrypoint": "echo hi",
                          "resources": {"slots": 2}}}, token=token)
        _wait(lambda: fake.creates[:] or None, what="first create")
        name0 = fake.creates[0]["name"]
        # No agent ever boots: past boot grace the node is deleted and a
        # replacement is launched for the still-pending demand.
        _wait(lambda: name0 in fake.deletes or None, timeout=30,
              what="never-joined node deleted")
        _wait(lambda: len(fake.creates) >= 2 or None, timeout=30,
              what="replacement create after cleanup")
    finally:
        c.stop()
        fake.stop()


def _prov_master(tmp_path, native_binaries, fake, prov_extra=None):
    """Master-only cluster against the fake TPU API (no pre-booted
    agents — the test plays the VMs)."""
    cfg = {
        "agent_timeout_s": 15,
        "provisioner": {
            "type": "gcp",
            "api_base": fake.url + "/v2",
            "project": "p", "zone": "z",
            "slots_per_node": 2,
            "sustain_seconds": 0.3,
            "cooldown_seconds": 0.5,
            "idle_seconds": 2,
            "reconcile_seconds": 0.3,
            "demand_hysteresis_seconds": 1,
            **(prov_extra or {}),
        },
    }
    cfg_path = tmp_path / "master.json"
    cfg_path.write_text(json.dumps(cfg))
    c = Devcluster(str(tmp_path), native_binaries)
    c.master = subprocess.Popen(
        [os.path.join(c.binaries, "determined-master"),
         "--config", str(cfg_path),
         "--port", str(c.port), "--host", "127.0.0.1", "--db", c.db_path],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    _wait_http(c.master_url + "/api/v1/master")
    return c


def test_create_failure_storm_backs_off_and_recovers(
        tmp_path, native_binaries):  # noqa: F811
    """A 100%-node-create-failure storm must NOT busy-loop: attempts space
    out on the capped exponential backoff (base * 2^(n-1)), the failure
    counter climbs, and clearing the storm recovers — the next attempt
    creates a node and the queued work runs on it."""
    fake = FakeTpuApi()
    fake.fail_creates = True
    c = _prov_master(tmp_path, native_binaries, fake, {
        "create_backoff_base_seconds": 0.6,
        "create_backoff_max_seconds": 3,
    })
    agents = []
    try:
        token = c.login()
        c.api("POST", "/api/v1/commands",
              {"config": {"entrypoint": "echo recovered-ok",
                          "resources": {"slots": 2}}}, token=token)
        _wait(lambda: len(fake.failed_creates) >= 2 or None, timeout=20,
              what="two failed create attempts")
        # Bounded retry rate: with backoff 0.6 -> 1.2 -> 2.4 -> 3 (cap)
        # a 3.5s window sees ~3 attempts; a busy-loop at the 0.5s
        # cooldown would see ~7.
        t0 = time.time()
        base = len(fake.failed_creates)
        time.sleep(3.5)
        attempts = len(fake.failed_creates) - base
        assert attempts <= 4, (
            f"{attempts} create attempts in {time.time() - t0:.1f}s — "
            "backoff is not holding")
        metrics = _scrape_metrics(c, token)
        assert metrics.get("det_provisioner_create_failures_total", 0) >= 2
        # Storm clears: the next (backed-off) attempt succeeds, the VM
        # "boots", and the queued command completes on it.
        fake.fail_creates = False
        created = _wait(lambda: fake.creates[:] or None, timeout=30,
                        what="create after storm cleared")
        name = created[0]["name"]
        agents.append(subprocess.Popen(
            [os.path.join(c.binaries, "determined-agent"),
             "--master-url", c.master_url, "--id", name,
             "--slots", "2", "--slot-type", "cpu", "--addr", "127.0.0.1",
             "--work-root", os.path.join(c.tmpdir, f"agent-{name}"),
             "--token-file", c.db_path + ".agent_token"],
            env=c.env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        _wait(lambda: [t for t in c.api("GET", "/api/v1/tasks",
                                        token=token)["tasks"]
                       if t["state"] == "COMPLETED"] or None,
              timeout=60, what="task completed after recovery")
    finally:
        for p in agents:
            if p.poll() is None:
                p.kill()
                p.wait()
        c.stop()
        fake.stop()


def test_create_fault_point_runtime_armed(tmp_path, native_binaries):  # noqa: F811
    """`provisioner.create.fail` (DET_FAULTS / debug API): armed with a
    count, it eats exactly that many create attempts inside the master —
    the fake API never sees them — then auto-disarms and the pool
    recovers."""
    fake = FakeTpuApi()
    c = _prov_master(tmp_path, native_binaries, fake, {
        "create_backoff_base_seconds": 0.3,
        "create_backoff_max_seconds": 1,
    })
    agents = []
    try:
        admin = c.login("admin")
        c.api("POST", "/api/v1/debug/faults",
              {"point": "provisioner.create.fail", "mode": "error",
               "count": 2}, token=admin)
        token = c.login()
        c.api("POST", "/api/v1/commands",
              {"config": {"entrypoint": "echo fault-cleared",
                          "resources": {"slots": 2}}}, token=token)
        # Both injected failures burn without any API traffic...
        _wait(lambda: _scrape_metrics(c, token).get(
            "det_provisioner_create_failures_total", 0) >= 2 or None,
            timeout=20, what="two injected create failures")
        assert fake.creates == [] and fake.failed_creates == []
        # ...then the point auto-disarms and the third attempt lands.
        created = _wait(lambda: fake.creates[:] or None, timeout=20,
                        what="create after fault exhausted")
        name = created[0]["name"]
        agents.append(subprocess.Popen(
            [os.path.join(c.binaries, "determined-agent"),
             "--master-url", c.master_url, "--id", name,
             "--slots", "2", "--slot-type", "cpu", "--addr", "127.0.0.1",
             "--work-root", os.path.join(c.tmpdir, f"agent-{name}"),
             "--token-file", c.db_path + ".agent_token"],
            env=c.env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        _wait(lambda: [t for t in c.api("GET", "/api/v1/tasks",
                                        token=token)["tasks"]
                       if t["state"] == "COMPLETED"] or None,
              timeout=60, what="task completed after fault cleared")
    finally:
        for p in agents:
            if p.poll() is None:
                p.kill()
                p.wait()
        c.stop()
        fake.stop()


def test_deployment_deficit_drives_provisioning(
        tmp_path, native_binaries):  # noqa: F811
    """ROADMAP item 3 / the capacity loop: a deployment's replica deficit
    — NOT just queued training slots — summons nodes, labeled under
    demand source "serving"; when the deployment dies, the fleet shrinks
    back to zero nodes."""
    fake = FakeTpuApi()
    c = _prov_master(tmp_path, native_binaries, fake)
    agents = []
    try:
        token = c.login()
        dep = c.api("POST", "/api/v1/deployments", {"config": {
            "name": "prov-dep",
            "entrypoint": "python3 -m tests.fixtures.serving.fake_replica",
            "serving": {"model": "gpt2",
                        "replicas": {"min": 2, "max": 2, "target": 2}},
            "resources": {"slots": 1},
            "environment": {"DET_FAKE_HEARTBEAT_S": "0.3"},
        }}, token=token)
        dep_id = dep["id"]
        # The deficit shows up attributed to serving...
        _wait(lambda: _scrape_metrics(c, token).get(
            'det_provisioner_demand_slots{pool="default",source="serving"}',
            0) > 0 or None, timeout=20, what="serving demand gauge")
        # ...and creates a node (2 replicas x 1 slot = 2 slots = 1 node).
        created = _wait(lambda: fake.creates[:] or None, timeout=30,
                        what="node created for replica deficit")
        name = created[0]["name"]
        agents.append(subprocess.Popen(
            [os.path.join(c.binaries, "determined-agent"),
             "--master-url", c.master_url, "--id", name,
             "--slots", "2", "--slot-type", "cpu", "--addr", "127.0.0.1",
             "--work-root", os.path.join(c.tmpdir, f"agent-{name}"),
             "--token-file", c.db_path + ".agent_token"],
            env=c.env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT))

        def _ready():
            d = c.api("GET", f"/api/v1/deployments/{dep_id}",
                      token=token)["deployment"]
            up = [r for r in d["replicas"]
                  if r.get("allocation_state") == "RUNNING"
                  and r.get("proxy_address")]
            return d if len(up) == 2 else None

        _wait(_ready, timeout=90, what="both replicas running on the node")
        # Demand drains once the replicas are schedulable (the gauge
        # disappears or reads 0).
        _wait(lambda: _scrape_metrics(c, token).get(
            'det_provisioner_demand_slots{pool="default",source="serving"}',
            0) == 0 or None, timeout=20, what="serving demand drained")
        # Deployment gone -> node idles -> fleet shrinks to zero.
        c.api("POST", f"/api/v1/deployments/{dep_id}/kill", token=token)
        _wait(lambda: name in fake.deletes or None, timeout=45,
              what="idle node deleted after deployment kill")
    finally:
        for p in agents:
            if p.poll() is None:
                p.kill()
                p.wait()
        c.stop()
        fake.stop()


def test_elastic_demand_counts_min_size_and_trial_starts_shrunk(
        tmp_path, native_binaries):  # noqa: F811
    """A queued elastic trial demands its MIN size, not its preferred
    size: the provisioner summons one min-sized node (not preferred/
    slots_per_node nodes), and the scheduler STARTS the trial shrunk onto
    it (elastic shrink-to-start) instead of stranding it in the queue."""
    from tests.test_platform_e2e import FIXTURES  # noqa: F401

    fake = FakeTpuApi()
    c = _prov_master(tmp_path, native_binaries, fake)
    agents = []
    try:
        cfg = _experiment_config(
            tmp_path,
            extra={
                "resources": {"slots_per_trial": 4,
                              "elastic": {"min_slots": 1, "max_slots": 4}},
            },
        )
        eid, token = _create_experiment(c, cfg, activate=True)
        # Demand is 1 slot (min), under source "elastic" -> ONE node.
        _wait(lambda: fake.creates[:] or None, timeout=30,
              what="node create for elastic-at-min demand")
        time.sleep(1.5)  # past sustain+cooldown: a 4-slot demand would
        assert len(fake.creates) == 1   # have fired a second node
        metrics = _scrape_metrics(c, token)
        assert metrics.get(
            'det_provisioner_demand_slots{pool="default",source="elastic"}',
            0) in (0, 1), metrics  # 1 while queued, 0 once placed
        name = fake.creates[0]["name"]
        agents.append(subprocess.Popen(
            [os.path.join(c.binaries, "determined-agent"),
             "--master-url", c.master_url, "--id", name,
             "--slots", "2", "--slot-type", "cpu", "--addr", "127.0.0.1",
             "--work-root", os.path.join(c.tmpdir, f"agent-{name}"),
             "--token-file", c.db_path + ".agent_token"],
            env=c.env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        # The trial STARTS shrunk (2 slots fit of 4 preferred) and runs
        # to completion on the single summoned node.
        _wait_experiment(c, eid, token, timeout=180)
        trials = c.api("GET", f"/api/v1/experiments/{eid}/trials",
                       token=token)["trials"]
        assert trials[0]["state"] == "COMPLETED"
        assert len(fake.creates) == 1, [x["name"] for x in fake.creates]
    finally:
        for p in agents:
            if p.poll() is None:
                p.kill()
                p.wait()
        c.stop()
        fake.stop()


def test_master_restart_adopts_provisioned_nodes(tmp_path, native_binaries):  # noqa: F811
    """Master restart must not orphan provisioned TPU-VMs: the reconcile
    pass adopts listed nodes with our prefix, so idle scale-down still
    happens and new launches can't collide with existing names."""
    fake = FakeTpuApi()
    # Pre-existing node from a "previous master life".
    fake.nodes["det-prov-default-0"] = {"state": "READY", "body": {}}
    cfg = {
        "agent_timeout_s": 15,
        "provisioner": {
            "type": "gcp",
            "api_base": fake.url + "/v2",
            "project": "p", "zone": "z",
            "slots_per_node": 2,
            "sustain_seconds": 0.5,
            "cooldown_seconds": 1,
            "idle_seconds": 1.5,
            "boot_grace_seconds": 4,
            "reconcile_seconds": 0.3,
        },
    }
    cfg_path = tmp_path / "master.json"
    cfg_path.write_text(json.dumps(cfg))
    c = Devcluster(str(tmp_path), native_binaries)
    c.master = subprocess.Popen(
        [os.path.join(c.binaries, "determined-master"),
         "--config", str(cfg_path),
         "--port", str(c.port), "--host", "127.0.0.1", "--db", c.db_path],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    _wait_http(c.master_url + "/api/v1/master")
    agent = None
    try:
        # Boot the agent for the adopted node; it registers, sits idle,
        # and the ADOPTED node gets idle-scale-downed — proof the master
        # took ownership back.
        agent = subprocess.Popen(
            [os.path.join(c.binaries, "determined-agent"),
             "--master-url", c.master_url,
             "--id", "det-prov-default-0",
             "--slots", "2", "--slot-type", "cpu", "--addr", "127.0.0.1",
             "--work-root", os.path.join(c.tmpdir, "aw"),
             "--token-file", c.db_path + ".agent_token"],
            env=c.env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        _wait(lambda: "det-prov-default-0" in fake.deletes or None,
              timeout=30, what="adopted node idle-scale-down")
    finally:
        if agent is not None and agent.poll() is None:
            agent.kill()
            agent.wait()
        c.stop()
        fake.stop()
