"""NTSC proxy e2e (reference internal/proxy/proxy.go + tcp.go): the master
forwards /proxy/{task_id}/... to the task's registered proxy address."""

import textwrap
import time
import urllib.error
import urllib.request

import pytest

from tests.test_platform_e2e import Devcluster, native_binaries  # noqa: F401


@pytest.fixture()
def cluster(tmp_path, native_binaries):  # noqa: F811
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    c.start_agent()
    yield c
    c.stop()


SERVER = textwrap.dedent("""
    import http.server, threading, sys
    from determined_tpu.exec._util import report_proxy_address

    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass
        def do_GET(self):
            if self.path.startswith("/hello"):
                body = f"hi from task: {self.path}".encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path == "/jump":
                self.send_response(302)
                self.send_header("Location", "/hello-after-jump")
                self.end_headers()
            else:
                self.send_response(404)
                self.end_headers()
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            body = b"echo:" + self.rfile.read(n)
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    report_proxy_address(f"http://127.0.0.1:{srv.server_address[1]}")
    print("serving", srv.server_address[1])
    sys.stdout.flush()
    srv.serve_forever()
""")


def test_proxy_forwards_to_task(cluster, tmp_path):
    token = cluster.login()
    script = tmp_path / "srv.py"
    script.write_text(SERVER)
    task = cluster.api(
        "POST", "/api/v1/commands",
        {"config": {"entrypoint": f"python3 {script}"}}, token=token)
    tid = task["id"]

    # wait for the proxy address to register
    deadline = time.time() + 30
    addr = None
    while time.time() < deadline:
        t = cluster.api("GET", f"/api/v1/commands/{tid}", token=token)["task"]
        addr = t.get("proxy_address")
        if addr:
            break
        time.sleep(0.3)
    assert addr, "task never registered a proxy address"

    def proxied(method, path, data=None):
        req = urllib.request.Request(
            cluster.master_url + f"/proxy/{tid}{path}",
            data=data, method=method,
            headers={"Authorization": f"Bearer {token}"})
        return urllib.request.urlopen(req, timeout=20)

    # GET with query string
    with proxied("GET", "/hello?x=1") as r:
        assert r.headers.get_content_type() == "text/plain"
        body = r.read().decode()
    assert body.startswith("hi from task: /hello")
    assert "x=1" in body

    # POST body round-trips
    with proxied("POST", "/hello-post") as r:
        pass  # 404 from server is fine — exercise POST on /hello instead
    with proxied("POST", "/hello", data=b"payload-bytes") as r:
        assert r.read() == b"echo:payload-bytes"

    # origin-relative redirects are rewritten into the proxy prefix
    class NoRedirect(urllib.request.HTTPRedirectHandler):
        def redirect_request(self, *a, **k):
            return None

    opener = urllib.request.build_opener(NoRedirect)
    req = urllib.request.Request(
        cluster.master_url + f"/proxy/{tid}/jump",
        headers={"Authorization": f"Bearer {token}"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        opener.open(req, timeout=20)
    assert ei.value.code == 302
    assert ei.value.headers["Location"] == f"/proxy/{tid}/hello-after-jump"

    # unauthenticated proxying rejected; unknown task 502
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            cluster.master_url + f"/proxy/{tid}/hello", timeout=10)
    assert ei.value.code == 401
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            urllib.request.Request(
                cluster.master_url + "/proxy/no-such-task/x",
                headers={"Authorization": f"Bearer {token}"}), timeout=10)
    assert ei.value.code == 502

    cluster.api("POST", f"/api/v1/commands/{tid}/kill", token=token)
