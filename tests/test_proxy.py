"""NTSC proxy e2e (reference internal/proxy/proxy.go + tcp.go): the master
forwards /proxy/{task_id}/... to the task's registered proxy address."""

import textwrap
import time
import urllib.error
import urllib.request

import pytest

from tests.test_platform_e2e import Devcluster, native_binaries  # noqa: F401


@pytest.fixture()
def cluster(tmp_path, native_binaries):  # noqa: F811
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    c.start_agent()
    yield c
    # Teardown kills the task process groups the SIGKILLed agent can no
    # longer reap (VERDICT item 6: the spawned proxy/ws/shell servers used
    # to outlive the suite) — and proves it left nothing behind.
    c.stop()
    assert c.find_orphans() == [], (
        "devcluster teardown leaked task processes")


SERVER = textwrap.dedent("""
    import http.server, threading, sys
    from determined_tpu.exec._util import report_proxy_address

    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass
        def do_GET(self):
            if self.path.startswith("/hello"):
                body = f"hi from task: {self.path}".encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path == "/jump":
                self.send_response(302)
                self.send_header("Location", "/hello-after-jump")
                self.end_headers()
            else:
                self.send_response(404)
                self.end_headers()
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            body = b"echo:" + self.rfile.read(n)
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    report_proxy_address(f"http://127.0.0.1:{srv.server_address[1]}")
    print("serving", srv.server_address[1])
    sys.stdout.flush()
    srv.serve_forever()
""")


def test_proxy_forwards_to_task(cluster, tmp_path):
    token = cluster.login()
    script = tmp_path / "srv.py"
    script.write_text(SERVER)
    task = cluster.api(
        "POST", "/api/v1/commands",
        {"config": {"entrypoint": f"python3 {script}"}}, token=token)
    tid = task["id"]

    # wait for the proxy address to register
    deadline = time.time() + 30
    addr = None
    while time.time() < deadline:
        t = cluster.api("GET", f"/api/v1/commands/{tid}", token=token)["task"]
        addr = t.get("proxy_address")
        if addr:
            break
        time.sleep(0.3)
    assert addr, "task never registered a proxy address"

    def proxied(method, path, data=None):
        req = urllib.request.Request(
            cluster.master_url + f"/proxy/{tid}{path}",
            data=data, method=method,
            headers={"Authorization": f"Bearer {token}"})
        return urllib.request.urlopen(req, timeout=20)

    # GET with query string
    with proxied("GET", "/hello?x=1") as r:
        assert r.headers.get_content_type() == "text/plain"
        body = r.read().decode()
    assert body.startswith("hi from task: /hello")
    assert "x=1" in body

    # POST body round-trips
    with proxied("POST", "/hello-post") as r:
        pass  # 404 from server is fine — exercise POST on /hello instead
    with proxied("POST", "/hello", data=b"payload-bytes") as r:
        assert r.read() == b"echo:payload-bytes"

    # origin-relative redirects are rewritten into the proxy prefix
    class NoRedirect(urllib.request.HTTPRedirectHandler):
        def redirect_request(self, *a, **k):
            return None

    opener = urllib.request.build_opener(NoRedirect)
    req = urllib.request.Request(
        cluster.master_url + f"/proxy/{tid}/jump",
        headers={"Authorization": f"Bearer {token}"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        opener.open(req, timeout=20)
    assert ei.value.code == 302
    assert ei.value.headers["Location"] == f"/proxy/{tid}/hello-after-jump"

    # unauthenticated proxying rejected; unknown task 502
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            cluster.master_url + f"/proxy/{tid}/hello", timeout=10)
    assert ei.value.code == 401
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            urllib.request.Request(
                cluster.master_url + "/proxy/no-such-task/x",
                headers={"Authorization": f"Bearer {token}"}), timeout=10)
    assert ei.value.code == 404

    # non-owner cannot tunnel into the task (it executes as the owner)
    admin = cluster.login("admin")
    cluster.api("POST", "/api/v1/users",
                {"username": "proxy-bob", "role": "user"}, token=admin)
    bob = cluster.login("proxy-bob")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            urllib.request.Request(
                cluster.master_url + f"/proxy/{tid}/hello",
                headers={"Authorization": f"Bearer {bob}"}), timeout=10)
    assert ei.value.code == 403

    cluster.api("POST", f"/api/v1/commands/{tid}/kill", token=token)


# Minimal RFC6455 server fixture: handshake + unmasked echo of masked
# client text frames. Enough to prove the master splices the upgrade +
# bidirectional frames (reference proxy/ws.go).
WS_SERVER = textwrap.dedent("""
    import base64, hashlib, socket, sys, threading
    from determined_tpu.exec._util import report_proxy_address

    MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

    def handle(conn):
        buf = b""
        while b"\\r\\n\\r\\n" not in buf:
            d = conn.recv(4096)
            if not d:
                return
            buf += d
        head, rest = buf.split(b"\\r\\n\\r\\n", 1)
        key = ""
        for line in head.decode().split("\\r\\n"):
            if line.lower().startswith("sec-websocket-key:"):
                key = line.split(":", 1)[1].strip()
        accept = base64.b64encode(
            hashlib.sha1((key + MAGIC).encode()).digest()).decode()
        conn.sendall((
            "HTTP/1.1 101 Switching Protocols\\r\\n"
            "Upgrade: websocket\\r\\nConnection: Upgrade\\r\\n"
            f"Sec-WebSocket-Accept: {accept}\\r\\n\\r\\n").encode())
        data = rest
        while True:
            while len(data) < 6:
                d = conn.recv(4096)
                if not d:
                    return
                data += d
            ln = data[1] & 0x7F
            need = 6 + ln
            while len(data) < need:
                data += conn.recv(4096)
            mask = data[2:6]
            payload = bytes(b ^ mask[i % 4]
                            for i, b in enumerate(data[6:need]))
            data = data[need:]
            out = bytes([0x81, len(payload)]) + payload
            conn.sendall(out)

    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    report_proxy_address(f"http://127.0.0.1:{srv.getsockname()[1]}")
    print("ws serving", srv.getsockname()[1]); sys.stdout.flush()
    while True:
        c, _ = srv.accept()
        threading.Thread(target=handle, args=(c,), daemon=True).start()
""")


def _wait_proxy_addr(cluster, token, kind, tid, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        t = cluster.api("GET", f"/api/v1/{kind}/{tid}", token=token)["task"]
        if t.get("proxy_address"):
            return t["proxy_address"]
        time.sleep(0.3)
    raise TimeoutError("task never registered a proxy address")


def test_websocket_proxy_echo(cluster, tmp_path):
    """WS upgrade through /proxy/{task}/: handshake forwarded upstream,
    frames pumped both ways (reference proxy/ws.go)."""
    import base64
    import hashlib
    import socket

    token = cluster.login()
    script = tmp_path / "ws.py"
    script.write_text(WS_SERVER)
    tid = cluster.api(
        "POST", "/api/v1/commands",
        {"config": {"entrypoint": f"python3 {script}"}}, token=token)["id"]
    _wait_proxy_addr(cluster, token, "commands", tid)

    host, port = "127.0.0.1", cluster.port
    s = socket.create_connection((host, port), timeout=20)
    key = base64.b64encode(b"0123456789abcdef").decode()
    s.sendall((
        f"GET /proxy/{tid}/ HTTP/1.1\r\nHost: {host}\r\n"
        f"Authorization: Bearer {token}\r\n"
        "Connection: Upgrade\r\nUpgrade: websocket\r\n"
        f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n\r\n"
    ).encode())
    buf = b""
    while b"\r\n\r\n" not in buf:
        d = s.recv(4096)
        assert d, f"closed during handshake: {buf!r}"
        buf += d
    head, rest = buf.split(b"\r\n\r\n", 1)
    assert b"101" in head.split(b"\r\n", 1)[0], head
    magic = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"
    want_accept = base64.b64encode(
        hashlib.sha1((key + magic).encode()).digest()).decode()
    assert want_accept.encode() in head, head

    # two masked text frames round-trip through the tunnel
    for msg in (b"hello-ws", b"second-message"):
        mask = b"\x01\x02\x03\x04"
        masked = bytes(b ^ mask[i % 4] for i, b in enumerate(msg))
        s.sendall(bytes([0x81, 0x80 | len(msg)]) + mask + masked)
        want = bytes([0x81, len(msg)]) + msg
        got = rest
        rest = b""
        while len(got) < len(want):
            d = s.recv(4096)
            assert d, "tunnel closed mid-frame"
            got += d
        assert got == want, (got, want)
    s.close()
    cluster.api("POST", f"/api/v1/commands/{tid}/kill", token=token)


def test_shell_round_trip(cluster, tmp_path):
    """`det shell run`: start a shell task, run a command through the
    det-tcp tunnel (reference: ssh over proxy/tcp.go; here exec/shell.py),
    driven through the real CLI as a subprocess."""
    import os
    import subprocess
    import sys

    token = cluster.login()
    tid = cluster.api("POST", "/api/v1/shells", {"config": {}},
                      token=token)["id"]
    _wait_proxy_addr(cluster, token, "shells", tid, timeout=60)

    env = dict(cluster.env, HOME=str(tmp_path))  # isolate the token cache
    r = subprocess.run(
        [sys.executable, "-m", "determined_tpu.cli",
         "-m", cluster.master_url, "shell", "run", tid,
         "echo tunnel-says-$((20+3))"],
        capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 0, r.stderr
    assert "tunnel-says-23" in r.stdout, (r.stdout, r.stderr)

    # Direct connection WITHOUT the per-task secret (ADVICE r4 high): the
    # shell binds 0.0.0.0, so anyone with network reach could otherwise run
    # commands as the owner. A connection that doesn't lead with
    # DET_PROXY_SECRET must be dropped with no shell spawned.
    import socket as socketmod

    addr = _wait_proxy_addr(cluster, token, "shells", tid)
    hostport = addr.split("://", 1)[1]
    host, port = hostport.rsplit(":", 1)
    s = socketmod.create_connection((host, int(port)), timeout=10)
    s.sendall(b"wrong-secret\necho direct-pwned-$((40+2))\n")
    s.shutdown(socketmod.SHUT_WR)
    got = b""
    s.settimeout(10)
    try:
        while True:
            d = s.recv(4096)
            if not d:
                break
            got += d
    except OSError:
        pass
    s.close()
    assert b"direct-pwned-42" not in got, got
    cluster.api("POST", f"/api/v1/shells/{tid}/kill", token=token)
