"""torch-xla contract pinned with a stubbed module (VERDICT r3 #8).

torch_xla is not installable in this (or any CPU) test image, so the
xla-specific paths in the torch compat layer — backend selection, one
process per host, `xla://` init, per-step `mark_step`, FSDP-not-DDP — had
zero coverage and a typo would ship green. These tests inject a fake
`torch_xla` into sys.modules and pin the exact contract
(reference harness/determined/launch/torch_distributed.py:74 and
_pytorch_context.py device/wrap semantics).
"""

import importlib.machinery
import json
import os
import sys
import types

import pytest
import torch


@pytest.fixture()
def fake_torch_xla(monkeypatch):
    """Install a minimal torch_xla into sys.modules; yields the xm stub."""
    xm = types.ModuleType("torch_xla.core.xla_model")
    xm.mark_step_calls = 0
    xm.xla_device = lambda: torch.device("cpu")  # stand-in device

    def mark_step():
        xm.mark_step_calls += 1

    xm.mark_step = mark_step

    core = types.ModuleType("torch_xla.core")
    core.xla_model = xm
    root = types.ModuleType("torch_xla")
    root.core = core
    # find_spec (used by the launcher) consults sys.modules first; a
    # module needs a __spec__ for that lookup to succeed.
    for name, mod in (("torch_xla", root), ("torch_xla.core", core),
                      ("torch_xla.core.xla_model", xm)):
        mod.__spec__ = importlib.machinery.ModuleSpec(name, None)
        monkeypatch.setitem(sys.modules, name, mod)
    return xm


def test_launcher_picks_xla_one_proc_per_host(fake_torch_xla, monkeypatch,
                                              capfd):
    """With torch_xla importable the launcher must choose backend=xla and
    ONE worker per host (a torch-xla process owns all local chips), wiring
    RANK/WORLD_SIZE from the node topology, not from a per-device fanout."""
    from determined_tpu.launch import torch_distributed as launch

    assert launch.pick_backend() == "xla"

    monkeypatch.setenv("DET_NODE_RANK", "1")
    monkeypatch.setenv("DET_NUM_NODES", "2")
    monkeypatch.setenv("DET_CHIEF_IP", "10.9.8.7")
    monkeypatch.setenv("DET_NPROC_PER_NODE", "4")  # must be IGNORED for xla
    rc = launch.main([
        "--", sys.executable, "-c",
        "import os, json; print(json.dumps({k: os.environ[k] for k in "
        "['RANK','WORLD_SIZE','LOCAL_WORLD_SIZE','MASTER_ADDR',"
        "'DET_TORCH_BACKEND']}))",
    ])
    assert rc == 0
    out = capfd.readouterr().out
    # exactly one worker, rank-prefixed
    payloads = [line for line in out.splitlines() if "{" in line]
    assert len(payloads) == 1 and payloads[0].startswith("[rank=1] ")
    env = json.loads(payloads[0].split(" ", 1)[1])
    assert env == {"RANK": "1", "WORLD_SIZE": "2", "LOCAL_WORLD_SIZE": "1",
                   "MASTER_ADDR": "10.9.8.7", "DET_TORCH_BACKEND": "xla"}


def test_xla_process_group_init(fake_torch_xla, monkeypatch):
    """DET_TORCH_BACKEND=xla must init the process group with the xla
    backend over an xla:// store — not env:// (reference
    launch/torch_distributed.py:74's USE_TORCH_DISTRIBUTED contract)."""
    from determined_tpu.pytorch import _trial

    calls = []
    monkeypatch.setattr(_trial, "torch", torch)
    import torch.distributed as dist

    monkeypatch.setattr(dist, "is_initialized", lambda: False)
    monkeypatch.setattr(
        dist, "init_process_group",
        lambda backend, init_method=None: calls.append((backend, init_method)))
    monkeypatch.setattr(dist, "get_rank", lambda: 0)
    monkeypatch.setattr(dist, "get_world_size", lambda: 2)
    monkeypatch.setenv("WORLD_SIZE", "2")
    monkeypatch.setenv("DET_TORCH_BACKEND", "xla")

    ctx = _trial.init_torch_distributed()
    assert calls == [("xla", "xla://")]
    assert ctx is not None and ctx.size == 2


def test_default_device_is_xla(fake_torch_xla):
    from determined_tpu.pytorch import _trial

    assert _trial._default_device() == fake_torch_xla.xla_device()


def test_mark_step_per_optimizer_step(fake_torch_xla):
    """step_optimizer must cut the lazy-tensor graph with xm.mark_step()
    once per optimizer step — forgetting it makes torch-xla accumulate an
    unbounded graph (the classic silent perf cliff)."""
    from determined_tpu.pytorch._trial import PyTorchTrialContext

    ctx = PyTorchTrialContext(hparams={})
    model = torch.nn.Linear(4, 2)
    opt = ctx.wrap_optimizer(torch.optim.SGD(model.parameters(), lr=0.1))
    loss = model(torch.zeros(1, 4)).sum()
    ctx.backward(loss)
    before = fake_torch_xla.mark_step_calls
    ctx.step_optimizer(opt)
    ctx.step_optimizer(opt)
    assert fake_torch_xla.mark_step_calls == before + 2


def test_fsdp_wrapped_model_skips_ddp(fake_torch_xla):
    """An (Xla)FullyShardedDataParallel model must NOT be re-wrapped in
    DDP: FSDP owns its reduce-scatter comms and DDP on top would
    all-reduce sharded grads (wrong math)."""
    from determined_tpu.core._distributed import DistributedContext
    from determined_tpu.pytorch._trial import PyTorchTrialContext

    class XlaFullyShardedDataParallel(torch.nn.Module):
        def __init__(self, module):
            super().__init__()
            self.module = module

        def forward(self, x):
            return self.module(x)

    ctx = PyTorchTrialContext(hparams={})
    # Simulate a 2-way distributed launch without a process group.
    ctx.dist = DistributedContext(rank=0, size=2, transport=None)

    fsdp = XlaFullyShardedDataParallel(torch.nn.Linear(4, 2))
    wrapped = ctx.wrap_model(fsdp)
    assert wrapped is fsdp  # untouched

    # ...while a plain module WOULD be DDP-wrapped (guard sanity) — DDP
    # needs a real process group, so expect its constructor to be reached
    # and fail loudly rather than being skipped.
    with pytest.raises((RuntimeError, ValueError)):
        ctx.wrap_model(torch.nn.Linear(4, 2))
