"""model_hub HF adapters in local mode (reference model_hub/ trial
adapters; offline — models built from configs, synthetic data)."""

import pytest

from determined_tpu import core


def test_causal_lm_trial(tmp_path):
    transformers = pytest.importorskip("transformers")  # noqa: F841
    from determined_tpu.model_hub import CausalLMTrial
    from determined_tpu.pytorch import PyTorchTrialContext, Trainer

    ctx = core.init(max_length=24, checkpoint_dir=str(tmp_path))
    trial = CausalLMTrial(PyTorchTrialContext(hparams={
        "model_config": {"config_type": "GPT2Config", "vocab_size": 128,
                         "n_positions": 32, "n_embd": 32, "n_layer": 1,
                         "n_head": 2},
        "seq_len": 16,
        "per_device_batch_size": 4,
        "synthetic_examples": 16,  # tiny set → memorizable in a few epochs
        "learning_rate": 3e-3,
    }))
    trial.context._core = ctx
    steps = Trainer(trial, core_context=ctx).fit(report_period=4)
    assert steps == 24
    m = ctx.train.local_training_metrics
    assert m[-1]["metrics"]["loss"] < m[0]["metrics"]["loss"]
    ctx.close()


def test_sequence_classification_trial(tmp_path):
    transformers = pytest.importorskip("transformers")  # noqa: F841
    from determined_tpu.model_hub import SequenceClassificationTrial
    from determined_tpu.pytorch import PyTorchTrialContext, Trainer

    ctx = core.init(max_length=30, checkpoint_dir=str(tmp_path))
    trial = SequenceClassificationTrial(PyTorchTrialContext(hparams={
        "model_config": {"config_type": "BertConfig", "vocab_size": 64,
                         "hidden_size": 32, "num_hidden_layers": 1,
                         "num_attention_heads": 2, "intermediate_size": 64,
                         "max_position_embeddings": 64},
        "num_labels": 4,
        "seq_len": 8,
        "per_device_batch_size": 16,
        "learning_rate": 3e-3,
    }))
    trial.context._core = ctx
    Trainer(trial, core_context=ctx).fit(report_period=10)
    val = ctx.train.local_validation_metrics[-1]["metrics"]
    # rule is learnable (label = f(first token)): must beat random (0.25)
    assert val["accuracy"] > 0.3, val
    ctx.close()
