"""GCS storage backend against a local fake JSON-API server.

The canonical TPU-VM checkpoint path is a GCS bucket (docs/
checkpointing.md), but until now `GCSStorageManager` was the one backend
with zero test coverage. The fake implements the JSON-API subset the
google-cloud-storage SDK uses for the staged-copy paths — multipart
upload, list-objects-with-prefix, `alt=media` download, delete — and the
SDK is pointed at it via `STORAGE_EMULATOR_HOST` (the SDK's own emulator
hook: anonymous credentials, no project). Array checkpoints normally
bypass these paths entirely (tensorstore writes the `url_for` gs:// URL
natively), so `url_for` is pinned here too.
"""

import http.server
import json
import os
import threading
import urllib.parse

import pytest

from determined_tpu.storage.cloud import GCSStorageManager


class FakeGCSService(http.server.BaseHTTPRequestHandler):
    """The JSON-API subset google-cloud-storage hits for staged copies:

      POST   /upload/storage/v1/b/{bucket}/o?uploadType=multipart
      GET    /storage/v1/b/{bucket}/o?prefix=...          (list)
      GET    /download/storage/v1/b/{bucket}/o/{name}?alt=media
      DELETE /storage/v1/b/{bucket}/o/{name}
    """

    store = {}  # (bucket, name) -> bytes
    requests = []  # (method, path) log, for protocol assertions

    def log_message(self, *a):
        pass

    def _json(self, status, obj):
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        parsed = urllib.parse.urlparse(self.path)
        FakeGCSService.requests.append(("POST", parsed.path))
        query = dict(urllib.parse.parse_qsl(parsed.query))
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        if not parsed.path.startswith("/upload/storage/v1/b/") or \
                query.get("uploadType") != "multipart":
            self._json(400, {"error": "only multipart upload supported"})
            return
        bucket = parsed.path.split("/")[5]
        # multipart/related: part 1 = metadata JSON, part 2 = content.
        # The boundary is the body's first line — no need to parse the
        # Content-Type header.
        boundary = body.split(b"\r\n", 1)[0]
        parts = [p for p in body.split(boundary) if p.strip(b"-\r\n")]
        meta_part, content_part = parts[0], parts[1]
        meta = json.loads(meta_part.split(b"\r\n\r\n", 1)[1])
        content = content_part.split(b"\r\n\r\n", 1)[1]
        if content.endswith(b"\r\n"):
            content = content[:-2]
        name = meta.get("name") or query.get("name")
        FakeGCSService.store[(bucket, name)] = content
        self._json(200, {"name": name, "bucket": bucket,
                         "size": str(len(content))})

    def do_GET(self):
        parsed = urllib.parse.urlparse(self.path)
        FakeGCSService.requests.append(("GET", parsed.path))
        query = dict(urllib.parse.parse_qsl(parsed.query))
        if parsed.path.startswith("/download/storage/v1/b/"):
            segs = parsed.path.split("/")
            bucket = segs[5]
            name = urllib.parse.unquote(segs[7])
            data = FakeGCSService.store.get((bucket, name))
            if data is None or query.get("alt") != "media":
                self._json(404, {"error": {"code": 404,
                                           "message": "No such object"}})
                return
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        if parsed.path.startswith("/storage/v1/b/") and \
                parsed.path.endswith("/o"):
            bucket = parsed.path.split("/")[4]
            prefix = query.get("prefix", "")
            items = [
                {"name": n, "bucket": b, "size": str(len(data))}
                for (b, n), data in sorted(FakeGCSService.store.items())
                if b == bucket and n.startswith(prefix)
            ]
            self._json(200, {"kind": "storage#objects", "items": items})
            return
        self._json(404, {"error": {"code": 404, "message": "not found"}})

    def do_DELETE(self):
        parsed = urllib.parse.urlparse(self.path)
        FakeGCSService.requests.append(("DELETE", parsed.path))
        segs = parsed.path.split("/")
        bucket = segs[4]
        name = urllib.parse.unquote(segs[6])
        if (bucket, name) not in FakeGCSService.store:
            self._json(404, {"error": {"code": 404,
                                       "message": "No such object"}})
            return
        del FakeGCSService.store[(bucket, name)]
        self.send_response(204)
        self.send_header("Content-Length", "0")
        self.end_headers()


@pytest.fixture()
def gcs_server(monkeypatch):
    FakeGCSService.store = {}
    FakeGCSService.requests = []
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), FakeGCSService)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    endpoint = f"http://127.0.0.1:{srv.server_address[1]}"
    # The SDK's own emulator hook: anonymous credentials, no project —
    # exactly how fake-gcs-server deployments point clients at a double.
    monkeypatch.setenv("STORAGE_EMULATOR_HOST", endpoint)
    yield endpoint
    srv.shutdown()


class TestUrlFor:
    def test_tensorstore_url(self, gcs_server):
        """Array checkpoints skip staging entirely: url_for hands orbax/
        tensorstore a native gs:// URL (CheckpointContext checks this
        before choosing the staged path)."""
        mgr = GCSStorageManager("my-bucket", prefix="exp7")
        assert mgr.url_for("trial3-step10") == \
            "gs://my-bucket/exp7/trial3-step10"
        assert GCSStorageManager("b").url_for("x") == "gs://b/x"
        assert mgr.requires_staging is True  # file checkpoints still stage

    def test_from_config(self, gcs_server):
        from determined_tpu.storage import from_config

        mgr = from_config({"type": "gcs", "bucket": "ckpts",
                           "prefix": "team/a"})
        assert isinstance(mgr, GCSStorageManager)
        assert mgr.url_for("id") == "gs://ckpts/team/a/id"


class TestGCSManager:
    def test_upload_list_download_roundtrip(self, gcs_server, tmp_path):
        mgr = GCSStorageManager("ckpts", prefix="exp1")
        src = tmp_path / "src"
        (src / "sub").mkdir(parents=True)
        (src / "model.bin").write_bytes(b"weights" * 100)
        (src / "sub" / "meta.json").write_text("{}")

        mgr.upload(str(src), "ck-1")
        files = mgr.list_files("ck-1")
        assert files == {"model.bin": 700, "sub/meta.json": 2}
        # Keys carry the prefix server-side (the bucket layout contract).
        assert ("ckpts", "exp1/ck-1/model.bin") in FakeGCSService.store

        dst = tmp_path / "dst"
        mgr.download("ck-1", str(dst))
        assert (dst / "model.bin").read_bytes() == b"weights" * 100
        assert (dst / "sub" / "meta.json").read_text() == "{}"
        # The staged path really exercised multipart upload + media
        # download, not some other surface.
        assert any(m == "POST" and p.startswith("/upload/")
                   for m, p in FakeGCSService.requests)
        assert any(m == "GET" and "alt=media" not in p and
                   p.startswith("/download/")
                   for m, p in FakeGCSService.requests)

    def test_names_needing_percent_encoding(self, gcs_server, tmp_path):
        mgr = GCSStorageManager("ckpts")
        src = tmp_path / "src"
        src.mkdir()
        (src / "my model.bin").write_bytes(b"mm")
        mgr.upload(str(src), "ck-sp")
        dst = tmp_path / "dst"
        mgr.download("ck-sp", str(dst))
        assert (dst / "my model.bin").read_bytes() == b"mm"

    def test_selector_download(self, gcs_server, tmp_path):
        mgr = GCSStorageManager("ckpts")
        src = tmp_path / "src"
        src.mkdir()
        (src / "a.txt").write_text("a")
        (src / "b.txt").write_text("b")
        mgr.upload(str(src), "ck-2")
        dst = tmp_path / "dst"
        mgr.download("ck-2", str(dst), selector=lambda rel: rel == "a.txt")
        assert os.listdir(dst) == ["a.txt"]

    def test_delete_with_globs(self, gcs_server, tmp_path):
        mgr = GCSStorageManager("ckpts")
        src = tmp_path / "src"
        src.mkdir()
        (src / "keep.json").write_text("k")
        (src / "drop.bin").write_bytes(b"d")
        mgr.upload(str(src), "ck-3")
        remaining = mgr.delete("ck-3", globs=["*.bin"])
        assert remaining == {"keep.json": 1}
        assert mgr.list_files("ck-3") == {"keep.json": 1}
        assert mgr.delete("ck-3") == {}
        assert mgr.list_files("ck-3") == {}

    def test_store_path_uploads_on_exit_and_restore_path(
            self, gcs_server, tmp_path):
        """store_path stages locally and pushes on exit; restore_path
        re-downloads and raises FileNotFoundError for unknown ids — the
        exact base-class contract file checkpoints rely on."""
        mgr = GCSStorageManager("ckpts")
        with mgr.store_path() as (sid, path):
            with open(os.path.join(path, "model.keras"), "wb") as f:
                f.write(b"K" * 64)
        assert mgr.list_files(sid) == {"model.keras": 64}
        assert not os.path.exists(mgr.path_for(sid))  # staging cleaned
        with mgr.restore_path(sid) as rpath:
            with open(os.path.join(rpath, "model.keras"), "rb") as f:
                assert f.read() == b"K" * 64
        assert not os.path.exists(mgr.path_for(sid))
        with pytest.raises(FileNotFoundError):
            with mgr.restore_path("no-such-checkpoint"):
                pass

    def test_checkpoint_context_file_roundtrip(self, gcs_server, tmp_path):
        """CheckpointContext file-mode save/restore over GCS staging (the
        keras/pytorch trial path; array mode goes tensorstore-native via
        url_for and never touches the fake)."""
        from determined_tpu.core._checkpoint import CheckpointContext

        mgr = GCSStorageManager("ckpts")
        ctx = CheckpointContext(None, mgr, trial_id=4, async_save=False)
        with ctx.store_path() as (path, sid):
            with open(os.path.join(path, "weights.pt"), "wb") as f:
                f.write(b"P" * 32)
        assert mgr.list_files(sid)["weights.pt"] == 32
        with ctx.restore_path(sid) as rpath:
            with open(os.path.join(rpath, "weights.pt"), "rb") as f:
                assert f.read() == b"P" * 32
