"""Core API v2 / unmanaged experiments e2e (reference
experimental/core_v2/_core_v2.py + _unmanaged.py: "det as a library").

The training process here is the TEST process — no agent, no scheduling;
the master just tracks the run."""

import numpy as np
import pytest

from determined_tpu.experimental import core_v2
from tests.test_platform_e2e import Devcluster, native_binaries  # noqa: F401


@pytest.fixture()
def cluster(tmp_path, native_binaries):  # noqa: F811
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()  # NOTE: no agent — unmanaged runs need none
    yield c
    c.stop()


def test_unmanaged_run_e2e(cluster, tmp_path):
    ctx = core_v2.init(
        config={"name": "laptop-run",
                "searcher": {"name": "single", "metric": "loss",
                             "max_length": {"batches": 6}}},
        master=cluster.master_url,
        hparams={"lr": 0.1},
        checkpoint_storage={"type": "shared_fs",
                            "host_path": str(tmp_path / "ckpts")},
        max_length=6,
    )
    # the module-level handles work like the reference's core_v2 globals
    losses = []
    for op in core_v2.searcher.operations():
        for step in range(1, op.length + 1):
            loss = 1.0 / step
            losses.append(loss)
            core_v2.train.report_training_metrics(step, {"loss": loss})
        core_v2.train.report_validation_metrics(op.length, {"loss": losses[-1]})
        op.report_completed(losses[-1])
    sid = core_v2.checkpoint.upload(
        _make_ckpt_dir(tmp_path), metadata={"steps_completed": 6})
    core_v2.close()

    token = cluster.login()
    exps = cluster.api("GET", "/api/v1/experiments", token=token)["experiments"]
    e = next(x for x in exps if x["id"] == ctx.experiment_id)
    assert e["state"] == "COMPLETED"
    assert e["name"] == "laptop-run"
    trials = cluster.api(
        "GET", f"/api/v1/experiments/{ctx.experiment_id}/trials",
        token=token)["trials"]
    assert len(trials) == 1 and trials[0]["state"] == "COMPLETED"
    metrics = cluster.api(
        "GET", f"/api/v1/trials/{ctx.trial_id}/metrics", token=token
    )["metrics"]
    assert [m for m in metrics if m["group_name"] == "training"]
    cps = cluster.api(
        "GET", f"/api/v1/experiments/{ctx.experiment_id}/checkpoints",
        token=token)["checkpoints"]
    assert [c for c in cps if c["uuid"] == sid]


def test_managed_experiments_reject_manual_trials(cluster, tmp_path):
    import determined_tpu.cli as cli
    from tests.test_platform_e2e import FIXTURES, _experiment_config

    token = cluster.login()
    resp = cluster.api(
        "POST", "/api/v1/experiments",
        {"config": _experiment_config(tmp_path),
         "model_definition": cli._tar_context(FIXTURES), "activate": False},
        token=token)
    import urllib.error

    with pytest.raises(urllib.error.HTTPError):
        cluster.api("POST", f"/api/v1/experiments/{resp['id']}/trials",
                    {}, token=token)


def _make_ckpt_dir(tmp_path):
    d = tmp_path / "artifact"
    d.mkdir(exist_ok=True)
    np.save(d / "weights.npy", np.arange(4.0))
    return str(d)
