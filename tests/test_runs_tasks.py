"""Runs view + generic task trees (reference api_runs.go:70 SearchRuns,
api_runs.go:262 MoveRuns, api_generic_tasks.go:207/:432)."""

import time

import pytest

from tests.test_platform_e2e import (  # noqa: F401
    Devcluster,
    _create_experiment,
    _experiment_config,
    _wait_experiment,
    native_binaries,
)


@pytest.fixture()
def cluster(tmp_path, native_binaries):  # noqa: F811
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    c.start_agent()
    yield c
    c.stop()


def test_runs_flat_view_and_move(cluster, tmp_path):
    eid, token = _create_experiment(
        cluster, _experiment_config(tmp_path), activate=True)
    _wait_experiment(cluster, eid, token)

    runs = cluster.api("GET", "/api/v1/runs", token=token)["runs"]
    mine = [r for r in runs if r["experiment_id"] == eid]
    assert mine and mine[0]["state"] == "COMPLETED"
    assert mine[0]["experiment_name"] == "e2e-fixture"
    assert "lr" in mine[0]["hparams"]

    # filters
    runs = cluster.api(
        "GET", f"/api/v1/runs?experiment_id={eid}&state=COMPLETED",
        token=token)["runs"]
    assert len(runs) == 1

    # move to a new project
    proj = cluster.api(
        "POST", "/api/v1/projects",
        {"name": "moved-into", "workspace_id": 1}, token=token)
    pid = proj.get("id") or proj.get("project", {}).get("id")
    out = cluster.api("POST", "/api/v1/runs/move",
                      {"run_ids": [mine[0]["id"]], "project_id": pid},
                      token=token)
    assert out["moved"] == 1
    runs = cluster.api(
        "GET", f"/api/v1/runs?project_id={pid}", token=token)["runs"]
    assert [r["id"] for r in runs] == [mine[0]["id"]]


def test_generic_task_tree_kill_propagates(cluster):
    token = cluster.login()
    parent = cluster.api(
        "POST", "/api/v1/generic-tasks",
        {"config": {"entrypoint": "sleep 600"}}, token=token)
    child = cluster.api(
        "POST", "/api/v1/generic-tasks",
        {"config": {"entrypoint": "sleep 600"},
         "parent_task_id": parent["id"]}, token=token)
    # both running
    deadline = time.time() + 30
    while time.time() < deadline:
        states = [
            cluster.api("GET", f"/api/v1/generic-tasks/{t['id']}",
                        token=token)["task"].get("allocation_state")
            for t in (parent, child)
        ]
        if states == ["RUNNING", "RUNNING"]:
            break
        time.sleep(0.3)
    assert states == ["RUNNING", "RUNNING"], states

    cluster.api("POST", f"/api/v1/generic-tasks/{parent['id']}/kill",
                token=token)
    deadline = time.time() + 30
    while time.time() < deadline:
        rows = [cluster.api("GET", f"/api/v1/generic-tasks/{t['id']}",
                            token=token)["task"] for t in (parent, child)]
        if all(r["state"] == "CANCELED" for r in rows):
            break
        time.sleep(0.3)
    assert all(r["state"] == "CANCELED" for r in rows), rows


def test_generic_task_bad_parent_rejected(cluster):
    token = cluster.login()
    import urllib.error

    with pytest.raises(urllib.error.HTTPError):
        cluster.api("POST", "/api/v1/generic-tasks",
                    {"config": {"entrypoint": "true"},
                     "parent_task_id": "no-such"}, token=token)


def test_completed_task_logs_immediately_readable(cluster):
    """Log durability vs task completion (VERDICT r4 weak #1): the agent
    must ship remaining log lines BEFORE the exit report, so the moment a
    task reads terminal its logs are already served. Two shapes: a fast
    task that exits on its own, and a killed task."""
    token = cluster.login()

    def logs_text(tid):
        logs = cluster.api("GET", f"/api/v1/tasks/{tid}/logs",
                           token=token)["logs"]
        return "\n".join(line["log"] for line in logs)

    # (a) fast-exit: marker printed immediately before exit
    tid = cluster.api(
        "POST", "/api/v1/commands",
        {"config": {"entrypoint":
                    "python3 -c \"print('durable-marker-%d' % (41+1))\""}},
        token=token)["id"]
    deadline = time.time() + 60
    state = None
    while time.time() < deadline:
        t = cluster.api("GET", f"/api/v1/commands/{tid}", token=token)["task"]
        state = t["state"]
        if state in ("COMPLETED", "ERROR", "CANCELED"):
            break
        time.sleep(0.05)
    assert state == "COMPLETED", state
    # NO sleep here — terminal state must imply logs are durable.
    assert "durable-marker-42" in logs_text(tid)

    # (b) killed mid-run: everything printed before the kill must be there
    tid2 = cluster.api(
        "POST", "/api/v1/commands",
        {"config": {"entrypoint":
                    "python3 -u -c \"print('pre-kill-%d' % (50+5)); "
                    "import time; time.sleep(600)\""}},
        token=token)["id"]
    deadline = time.time() + 60
    while time.time() < deadline:
        t = cluster.api("GET", f"/api/v1/commands/{tid2}", token=token)["task"]
        if t["state"] == "RUNNING":
            break
        time.sleep(0.1)
    time.sleep(1.0)  # give the task a beat to print
    cluster.api("POST", f"/api/v1/commands/{tid2}/kill", token=token)
    deadline = time.time() + 60
    state2 = None
    while time.time() < deadline:
        t = cluster.api("GET", f"/api/v1/commands/{tid2}", token=token)["task"]
        state2 = t["state"]
        if state2 in ("COMPLETED", "ERROR", "CANCELED"):
            break
        time.sleep(0.05)
    assert state2 in ("COMPLETED", "ERROR", "CANCELED"), state2
    assert "pre-kill-55" in logs_text(tid2)
