"""Elastic re-meshing: trials resize across capacity loss instead of
requeueing (docs/elasticity.md).

Fast tier-1 tests cover the expconf `resources.elastic` block, the
resize-offer parsing/deadline on the preemption signal, the DTL204
every-size feasibility rule, the DevicePrefetcher detach (data-order
preservation), the Trainer's in-process reshard pipeline — including the
acceptance bit-identity contract: a 4-slot run resized to 2 matches an
uninterrupted 2-slot run restored from the same checkpoint — and the
master's full resize lifecycle (offer on drain, same-allocation
re-placement with restarts untouched, size history, grow-back, and the
`master.resize.offer.drop` fault proving requeue remains the fallback)
through the native master harness. The `-m slow` e2e drives a real
heterogeneous devcluster through a notice-file drain: shrink 2->1 slots
without a requeue, then grow back on re-enable.
"""

import json
import os
import sqlite3
import sys
import time

import jax
import numpy as np
import pytest

from test_platform_e2e import (  # noqa: F401  (fixture re-export)
    FIXTURES,
    Devcluster,
    _create_experiment,
    _experiment_config,
    _wait_experiment,
    native_binaries,
)
from test_preemption import (  # noqa: F401
    _ScriptedSession,
    _register_fake_agent,
    _agent,
    _trial_allocation,
    _wait_alloc_state,
    _wait_for,
)

from determined_tpu import core, expconf
from determined_tpu.analysis import config_rules
from determined_tpu.core._preempt import PreemptContext
from determined_tpu.data import DevicePrefetcher
from determined_tpu.parallel.mesh import MeshConfig
from determined_tpu.train import Trainer
from determined_tpu.train.trial import TrialContext

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tests", "fixtures", "selfheal"))

from trial_def import LinearTrial  # noqa: E402


# ---------------------------------------------------------------------------
# expconf: the resources.elastic block.
# ---------------------------------------------------------------------------


def _base_config(**resources):
    return {
        "entrypoint": "python3 train.py",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 8}},
        "resources": {"slots_per_trial": 4, **resources},
    }


def test_expconf_elastic_valid_and_defaults():
    cfg = _base_config(elastic={"min_slots": 2})
    assert expconf.validate(cfg) == []
    out = expconf.apply_defaults(cfg)
    assert out["resources"]["elastic"] == {"min_slots": 2, "max_slots": 4}


def test_expconf_elastic_rejects_bad_blocks():
    assert any("must be a mapping" in e for e in expconf.validate(
        _base_config(elastic=3)))
    assert any("unknown keys" in e for e in expconf.validate(
        _base_config(elastic={"minimum": 1})))
    assert any("positive int" in e for e in expconf.validate(
        _base_config(elastic={"min_slots": 0})))
    assert any("min_slots > max_slots" in e for e in expconf.validate(
        _base_config(elastic={"min_slots": 4, "max_slots": 2})))
    assert any("below" in e for e in expconf.validate(
        _base_config(elastic={"min_slots": 8, "max_slots": 16})))
    assert any("exceeds" in e for e in expconf.validate(
        _base_config(elastic={"min_slots": 1, "max_slots": 2})))


# ---------------------------------------------------------------------------
# DTL204: elastic configs must be runnable at EVERY size in [min, max].
# ---------------------------------------------------------------------------


def _dtl204_codes(cfg):
    return [d for d in config_rules.check_config(cfg) if d.code == "DTL204"]


def test_dtl204_flags_indivisible_batch_sizes():
    cfg = {
        "resources": {"slots_per_trial": 4,
                      "elastic": {"min_slots": 1, "max_slots": 4}},
        "hyperparameters": {"global_batch_size": 32, "mesh": {"data": -1}},
    }
    diags = _dtl204_codes(cfg)
    # 32 divides 1, 2, 4 but not 3.
    assert len(diags) == 1 and "elastic size 3" in diags[0].message


def test_dtl204_flags_unresolvable_mesh_sizes():
    cfg = {
        "resources": {"slots_per_trial": 4,
                      "elastic": {"min_slots": 2, "max_slots": 4}},
        "hyperparameters": {"global_batch_size": 32,
                            "mesh": {"tensor": 2, "data": -1}},
    }
    diags = _dtl204_codes(cfg)
    # tensor=2 cannot divide 3 slots.
    assert len(diags) == 1 and "does not resolve" in diags[0].message


def test_dtl204_clean_for_divisor_ranges():
    cfg = {
        "resources": {"slots_per_trial": 4,
                      "elastic": {"min_slots": 2, "max_slots": 4}},
        "hyperparameters": {"global_batch_size": 32, "mesh": {"data": -1}},
    }
    assert _dtl204_codes(cfg) == [] or all(
        "elastic size 3" in d.message for d in _dtl204_codes(cfg))
    # non-elastic configs never fire DTL204
    cfg2 = {
        "resources": {"slots_per_trial": 3},
        "hyperparameters": {"global_batch_size": 32, "mesh": {"data": -1}},
    }
    assert _dtl204_codes(cfg2) == []


def test_dtl204_suppressible():
    from determined_tpu.analysis import _preflight

    cfg = {
        "entrypoint": "python3 x.py",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 1}},
        "resources": {"slots_per_trial": 4,
                      "elastic": {"min_slots": 1, "max_slots": 4}},
        "hyperparameters": {"global_batch_size": 32, "mesh": {"data": -1}},
        "preflight": {"suppress": ["DTL204"]},
    }
    report = _preflight.preflight(cfg)
    d204 = [d for d in report.diagnostics if d.code == "DTL204"]
    assert d204 and all(d.suppressed for d in d204)


def test_dtl204_hbm_leg_per_candidate_mesh():
    """The abstract-trace engine runs per candidate size: a model that fits
    at the preferred size but blows the per-device budget at min_slots is
    flagged as DTL204 naming that size."""
    from determined_tpu.analysis._preflight import _elastic_hbm_diags

    class BigTrial(LinearTrial):
        def init_params(self, rng):
            import jax

            # ~4 MiB of params, fsdp-sharded: per-device share doubles
            # every halving of the mesh.
            return {"w": jax.random.normal(rng, (1024, 1024))}

        def param_logical_axes(self):
            return {"w": ("fsdp_dim", None)}

        def sharding_rules(self):
            from determined_tpu.parallel.sharding import LogicalRules

            return LogicalRules(rules=[("fsdp_dim", "fsdp"),
                                       ("batch", ("data", "fsdp"))])

        def mesh_config(self):
            return MeshConfig(data=1, fsdp=-1)

        def build_training_data(self):
            yield {"x": np.zeros((8, 1024), np.float32)}

        def loss(self, params, batch, rng):
            import jax.numpy as jnp

            return jnp.mean((batch["x"] @ params["w"]) ** 2)

    cfg = {
        "resources": {"slots_per_trial": 8,
                      "elastic": {"min_slots": 1, "max_slots": 8}},
        "hyperparameters": {},
    }
    # Budget chosen between the 8-way share and the 1-way share: fine at
    # the preferred 8, over budget at small sizes.
    trial = BigTrial(TrialContext(n_devices=8))
    diags = _elastic_hbm_diags(trial, cfg, preferred=8,
                               hbm_budget=6 * 2**20, source_file=None)
    assert diags, "undersized candidate meshes must flag DTL204"
    assert all(d.code == "DTL204" for d in diags)
    assert any("elastic size 1" in d.message for d in diags)
    # No budget armed -> no HBM leg (same contract as DTL004).
    assert _elastic_hbm_diags(trial, cfg, 8, None, None) == []


# ---------------------------------------------------------------------------
# Resize-offer parsing on the preemption signal.
# ---------------------------------------------------------------------------


def test_watcher_parses_resize_offer():
    sess = _ScriptedSession([
        {"preempt": False},
        {"preempt": True, "resize": True, "target_slots": 2,
         "deadline_seconds": 25.0, "reason": "spot_preemption"},
    ])
    ctx = PreemptContext(sess, allocation_id="a1")
    try:
        assert _wait_for(lambda: ctx.should_preempt(auto_ack=False))
        assert ctx.resize_target() == 2
        remaining = ctx.preemption_deadline()
        assert remaining is not None and 20.0 < remaining <= 25.0
        assert ctx.preemption_reason() == "spot_preemption"
    finally:
        ctx.close()


def test_watcher_garbage_resize_target_is_plain_preemption():
    sess = _ScriptedSession([
        {"preempt": True, "resize": True, "target_slots": "lots"}])
    ctx = PreemptContext(sess, allocation_id="a1")
    try:
        assert _wait_for(lambda: ctx.should_preempt(auto_ack=False))
        assert ctx.resize_target() is None
    finally:
        ctx.close()


def test_force_resize_and_reset():
    ctx = PreemptContext(None)
    assert ctx.resize_target() is None
    ctx.force_resize(2, deadline=30.0)
    assert ctx.should_preempt()
    assert ctx.resize_target() == 2
    d = ctx.preemption_deadline()
    assert d is not None and 29.0 < d <= 30.0
    ctx.reset()
    assert not ctx.should_preempt()
    assert ctx.resize_target() is None
    assert ctx.preemption_deadline() is None


def test_mesh_resolvable():
    assert MeshConfig().resolvable(3)
    assert MeshConfig(tensor=2).resolvable(4)
    assert not MeshConfig(tensor=2).resolvable(3)
    assert not MeshConfig(data=4).resolvable(2)


# ---------------------------------------------------------------------------
# DevicePrefetcher.detach — the data-order contract under a resize.
# ---------------------------------------------------------------------------


def test_prefetcher_detach_preserves_order():
    pf = DevicePrefetcher(iter(range(64)), depth=4)
    consumed = [next(pf) for _ in range(10)]
    assert consumed == list(range(10))
    # Let the producer fill the queue before detaching.
    time.sleep(0.2)
    staged, rest = pf.detach()
    remaining = staged + list(rest)
    assert consumed + remaining == list(range(64)), (
        "detach dropped or reordered batches")


def test_prefetcher_detach_then_rewrap():
    import itertools

    pf = DevicePrefetcher(iter(range(20)), depth=2)
    head = [next(pf) for _ in range(5)]
    staged, rest = pf.detach()
    pf2 = DevicePrefetcher(itertools.chain(staged, rest), depth=2)
    tail = list(pf2)
    assert head + tail == list(range(20))


# ---------------------------------------------------------------------------
# Trainer: the in-process reshard pipeline.
# ---------------------------------------------------------------------------


def _local_core(tmp_path, max_length):
    return core.init(
        max_length=max_length,
        checkpoint_dir=str(tmp_path / "ckpts"),
        async_checkpointing=False,
    )


def _tree_equal(a, b) -> bool:
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))


class IndexedTrial(LinearTrial):
    """LinearTrial over an index-addressed batch stream, so a comparison
    run can start mid-stream and consume bit-identical batches."""

    def __init__(self, tctx, start=0, n=64, on_batch=None, action=None):
        super().__init__(tctx)
        self._start, self._n = start, n
        self._on_batch, self._action = on_batch, action

    @staticmethod
    def batch(i):
        rng = np.random.default_rng(1000 + i)
        return {"x": rng.normal(size=(8, 4)).astype(np.float32)}

    def build_training_data(self):
        for i in range(self._start, self._n):
            if self._on_batch is not None and i == self._on_batch:
                self._action()
            yield self.batch(i)


def _losses(ctx, lo=None):
    out = []
    for m in ctx.train.local_training_metrics:
        if "loss" in m["metrics"] and (
                lo is None or m["steps_completed"] > lo):
            out.append((m["steps_completed"], float(m["metrics"]["loss"])))
    return out


def test_resize_bit_identity_vs_uninterrupted_target_run(tmp_path):
    """Acceptance: train on 4 slots, resize to 2 mid-run; the post-resize
    loss trajectory and final state are BIT-identical (f32, fixed seed) to
    an uninterrupted 2-slot run restored from the same checkpoint and fed
    the same batches."""
    devices = jax.devices()
    ctx = _local_core(tmp_path, max_length=12)
    trial = IndexedTrial(
        TrialContext(), on_batch=5,
        action=lambda: ctx.preempt.force_resize(2, deadline=60.0))
    trainer = Trainer(trial, core_context=ctx, devices=devices[:4])
    state = trainer.fit(report_period=1, preempt_period=1, seed=0)
    assert trainer.mesh.size == 2, "mesh did not resize"
    step = int(jax.device_get(state.step))
    assert step == 12
    # The resize happened at step 6 (first poll past batch 5): the
    # emergency checkpoint is trial0-step6, COMPLETED on disk.
    ck = tmp_path / "ckpts" / "trial0-step6"
    assert (ck / "COMMIT").exists() and (ck / "manifest.json").exists()
    resized_losses = _losses(ctx, lo=6)
    rows = [m["metrics"] for m in ctx.train.local_training_metrics
            if "resize_downtime_ms" in m["metrics"]]
    assert rows and rows[0]["resize_from_slots"] == 4.0
    assert rows[0]["resize_target_slots"] == 2.0
    ctx.close()

    # Uninterrupted 2-slot run from the same checkpoint, same batches.
    ctx2 = _local_core(tmp_path, max_length=12)
    trainer2 = Trainer(IndexedTrial(TrialContext(), start=6),
                       core_context=ctx2, devices=devices[:2])
    state2 = trainer2.fit(report_period=1, seed=0,
                          resume_from="trial0-step6")
    assert int(jax.device_get(state2.step)) == 12
    baseline_losses = _losses(ctx2, lo=6)
    assert resized_losses == baseline_losses, (
        "post-resize loss trajectory diverged from the uninterrupted "
        "2-slot run")
    assert _tree_equal(state, state2), (
        "post-resize state is not bit-identical to the uninterrupted run")
    ctx2.close()


def test_resize_grow_in_process(tmp_path):
    """Shrink is not the only direction: a grow offer re-meshes 2 -> 4."""
    devices = jax.devices()
    ctx = _local_core(tmp_path, max_length=10)
    trial = IndexedTrial(
        TrialContext(), on_batch=4,
        action=lambda: ctx.preempt.force_resize(4))
    trainer = Trainer(trial, core_context=ctx, devices=devices[:2])
    trainer._devices = list(devices[:4])  # capacity returns mid-run
    state = trainer.fit(report_period=1, preempt_period=1)
    assert trainer.mesh.size == 4
    assert int(jax.device_get(state.step)) == 10
    ctx.close()


def test_resize_budget_exhausted_falls_back_to_lineage(tmp_path):
    """A resize whose deadline cannot cover a fresh save reshard-restores
    the newest COMPLETED checkpoint instead (steps rewind, nothing is
    corrupted) and still finishes."""
    devices = jax.devices()
    ctx = _local_core(tmp_path, max_length=12)

    def blow_budget():
        ctx.checkpoint.last_save_ms = 3_600_000.0
        ctx.preempt.force_resize(2, deadline=5.0)

    # on_batch=4 -> the poll trips at step 5, NOT a checkpoint_period
    # boundary: the newest COMPLETED checkpoint is the periodic step-4 one.
    trial = IndexedTrial(TrialContext(), n=128, on_batch=4,
                         action=blow_budget)
    trainer = Trainer(trial, core_context=ctx, devices=devices[:4])
    state = trainer.fit(report_period=1, preempt_period=1,
                        checkpoint_period=2)
    assert trainer.mesh.size == 2
    assert int(jax.device_get(state.step)) == 12
    # No step-5 emergency checkpoint was written; the reshard restored the
    # periodic step-4 one and the run rewound one step.
    assert not (tmp_path / "ckpts" / "trial0-step5").exists()
    assert (tmp_path / "ckpts" / "trial0-step4" / "COMMIT").exists()
    ctx.close()


def test_resize_with_prefetch_preserves_stream(tmp_path):
    """The detach()+rewrap pipeline: a resized run with prefetch ON is
    bit-identical to the same run with prefetch OFF (any dropped or
    reordered staged batch would diverge the SGD trajectory)."""
    devices = jax.devices()
    states = []
    for prefetch in (False, {"depth": 3}):
        ctx = _local_core(tmp_path, max_length=12)
        # Pin the resize to the very first poll so both runs reshard at
        # the same step regardless of producer lookahead.
        ctx.preempt.force_resize(2, deadline=60.0)
        trial = IndexedTrial(TrialContext())
        trial.prefetch = prefetch
        trainer = Trainer(trial, core_context=ctx, devices=devices[:4])
        states.append(trainer.fit(report_period=1, preempt_period=1))
        assert trainer.mesh.size == 2
        ctx.close()
    assert _tree_equal(states[0], states[1]), (
        "prefetch detach/rewrap changed the consumed batch stream")


# ---------------------------------------------------------------------------
# Master harness: the full resize lifecycle (tier-1 safe, fake agents).
# ---------------------------------------------------------------------------


@pytest.fixture()
def master_only(tmp_path, native_binaries):
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    yield c
    c.stop()


def _elastic_config(tmp_path, min_slots=1, max_slots=2, slots=2, extra=None):
    config = _experiment_config(tmp_path)
    config["resources"] = {
        "slots_per_trial": slots,
        "elastic": {"min_slots": min_slots, "max_slots": max_slots},
    }
    config.update(extra or {})
    return config


def _report_exit(c, admin, agent_id, aid, exit_code=0):
    c.api("POST", f"/api/v1/agents/{agent_id}/allocations/{aid}/state",
          {"state": "EXITED", "exit_code": exit_code}, token=admin)


def _signal(c, token, aid):
    return c.api(
        "GET",
        f"/api/v1/allocations/{aid}/signals/preemption?timeout_seconds=0",
        token=token)


def _alloc(c, token, aid):
    return c.api("GET", f"/api/v1/allocations/{aid}", token=token)[
        "allocation"]


def _trial(c, token, eid):
    return c.api("GET", f"/api/v1/experiments/{eid}/trials",
                 token=token)["trials"][0]


def test_master_resize_offer_shrink_and_grow_lifecycle(master_only):
    """Drain a 2-slot agent under an elastic 2-slot trial with a 1-slot
    survivor: the master offers a shrink to 1, the clean exit becomes a
    same-allocation re-placement (restarts unchanged, size history 2->1),
    and once the drained agent is re-enabled the trial gets a grow offer
    back to 2."""
    c = master_only
    admin = c.login("admin")
    _register_fake_agent(c, admin, "big", slots=2)
    _register_fake_agent(c, admin, "small", slots=1)

    eid, token = _create_experiment(c, _elastic_config(c.tmpdir))
    _wait_alloc_state(c, token, eid, "SCHEDULED")
    aid, _ = _trial_allocation(c, token, eid)
    alloc = _alloc(c, token, aid)
    assert alloc["slots"] == 2
    assert {r["agent_id"] for r in alloc["resources"]} == {"big"}

    # The notice arrives: the signal carries a RESIZE offer, not a bare
    # preemption.
    c.api("POST", "/api/v1/agents/big/preempt_notice",
          {"deadline_seconds": 60, "reason": "spot_preemption"}, token=admin)
    sig = _signal(c, token, aid)
    assert sig["preempt"] is True
    assert sig.get("resize") is True
    assert sig.get("target_slots") == 1
    assert 0 < sig["deadline_seconds"] <= 60

    # Harness contract: budgeted checkpoint, clean exit.
    _report_exit(c, admin, "big", aid)

    # Same allocation, new size, surviving agent — no trial requeue.
    deadline = time.time() + 15
    while time.time() < deadline:
        alloc = _alloc(c, token, aid)
        if alloc["slots"] == 1 and alloc["resources"] and \
                alloc["resources"][0]["agent_id"] == "small":
            break
        time.sleep(0.2)
    assert alloc["slots"] == 1, f"allocation never shrank: {alloc}"
    assert [r["agent_id"] for r in alloc["resources"]] == ["small"]
    t = _trial(c, token, eid)
    assert t.get("restarts", 0) == 0, "elastic resize must not burn restarts"
    assert t.get("current_slots") == 1

    hist = c.api("GET", f"/api/v1/allocations/{aid}/size_history",
                 token=token)["size_history"]
    assert [(h["from_slots"], h["to_slots"]) for h in hist] == [(2, 1)]
    assert hist[0]["reason"] == "spot_preemption"

    # Container comes up on the survivor; capacity returns; cooldown
    # passes -> the scheduler offers a grow back toward the preferred 2.
    c.api("POST", f"/api/v1/agents/small/allocations/{aid}/state",
          {"state": "RUNNING"}, token=admin)
    c.api("POST", "/api/v1/agents/big/enable", {}, token=admin)
    deadline = time.time() + 20  # 5s grow cooldown + scheduler ticks
    sig = {}
    while time.time() < deadline:
        sig = _signal(c, token, aid)
        if sig.get("resize"):
            break
        time.sleep(0.5)
    assert sig.get("resize") is True and sig.get("target_slots") == 2, sig
    # reason distinguishes opportunistic grows from drains
    assert "scale-up" in sig.get("reason", "")

    # Accept it: clean exit -> re-placed at 2 slots on the big agent.
    _report_exit(c, admin, "small", aid)
    deadline = time.time() + 15
    while time.time() < deadline:
        alloc = _alloc(c, token, aid)
        if alloc["slots"] == 2 and alloc["resources"]:
            break
        time.sleep(0.2)
    assert alloc["slots"] == 2
    assert {r["agent_id"] for r in alloc["resources"]} == {"big"}
    hist = c.api("GET", f"/api/v1/allocations/{aid}/size_history",
                 token=token)["size_history"]
    assert [(h["from_slots"], h["to_slots"]) for h in hist] == \
        [(2, 1), (1, 2)]
    assert _trial(c, token, eid).get("restarts", 0) == 0

    # Persisted for post-mortems (migration 20).
    c.kill_master()
    with sqlite3.connect(c.db_path) as db:
        rows = db.execute(
            "SELECT from_slots, to_slots FROM allocation_size_history "
            "ORDER BY id").fetchall()
    assert rows == [(2, 1), (1, 2)]


def test_master_non_elastic_keeps_requeue_behavior(master_only):
    """Control: without resources.elastic the PR-5 pipeline is untouched —
    plain deadline preemption, clean exit requeues the trial with
    restarts += 1 under a NEW allocation."""
    c = master_only
    admin = c.login("admin")
    _register_fake_agent(c, admin, "big", slots=2)
    _register_fake_agent(c, admin, "small", slots=1)

    config = _experiment_config(c.tmpdir)
    config["resources"] = {"slots_per_trial": 1}
    eid, token = _create_experiment(c, config)
    _wait_alloc_state(c, token, eid, "SCHEDULED")
    aid, _ = _trial_allocation(c, token, eid)

    victim = _alloc(c, token, aid)["resources"][0]["agent_id"]
    c.api("POST", f"/api/v1/agents/{victim}/preempt_notice",
          {"deadline_seconds": 60, "reason": "spot_preemption"}, token=admin)
    sig = _signal(c, token, aid)
    assert sig["preempt"] is True and "resize" not in sig

    _report_exit(c, admin, victim, aid)
    deadline = time.time() + 15
    new_aid = aid
    while time.time() < deadline:
        new_aid, state = _trial_allocation(c, token, eid)
        if new_aid != aid and state == "SCHEDULED":
            break
        time.sleep(0.2)
    assert new_aid != aid, "non-elastic trial should requeue a NEW allocation"
    assert _trial(c, token, eid).get("restarts", 0) == 1


def test_master_resize_offer_drop_falls_back_to_requeue(master_only):
    """The `master.resize.offer.drop` fault point eats the offer: the
    drain degrades to the PR-5 path (plain preemption, trial requeue,
    restarts += 1) — proving requeue remains the fallback."""
    c = master_only
    admin = c.login("admin")
    _register_fake_agent(c, admin, "big", slots=2)
    _register_fake_agent(c, admin, "small", slots=1)
    c.api("POST", "/api/v1/debug/faults",
          {"point": "master.resize.offer.drop", "mode": "error"},
          token=admin)

    eid, token = _create_experiment(c, _elastic_config(c.tmpdir))
    _wait_alloc_state(c, token, eid, "SCHEDULED")
    aid, _ = _trial_allocation(c, token, eid)

    c.api("POST", "/api/v1/agents/big/preempt_notice",
          {"deadline_seconds": 60, "reason": "spot_preemption"}, token=admin)
    sig = _signal(c, token, aid)
    assert sig["preempt"] is True and "resize" not in sig, sig

    _report_exit(c, admin, "big", aid)
    deadline = time.time() + 15
    new_aid = aid
    while time.time() < deadline:
        new_aid, _ = _trial_allocation(c, token, eid)
        if new_aid != aid:
            break
        time.sleep(0.2)
    assert new_aid != aid, "dropped offer must fall back to a requeue"
    assert _trial(c, token, eid).get("restarts", 0) == 1
    # No size transition was recorded.
    hist = c.api("GET", f"/api/v1/allocations/{aid}/size_history",
                 token=token)["size_history"]
    assert hist == []


def test_master_unclean_exit_with_offer_requeues(master_only):
    """A nonzero exit while a resize offer is outstanding must NOT become
    a size transition — the trial takes the ordinary failure/restart
    path."""
    c = master_only
    admin = c.login("admin")
    _register_fake_agent(c, admin, "big", slots=2)
    _register_fake_agent(c, admin, "small", slots=1)

    eid, token = _create_experiment(c, _elastic_config(c.tmpdir))
    _wait_alloc_state(c, token, eid, "SCHEDULED")
    aid, _ = _trial_allocation(c, token, eid)
    c.api("POST", "/api/v1/agents/big/preempt_notice",
          {"deadline_seconds": 60, "reason": "spot_preemption"}, token=admin)
    assert _signal(c, token, aid).get("resize") is True

    _report_exit(c, admin, "big", aid, exit_code=137)
    deadline = time.time() + 15
    new_aid = aid
    while time.time() < deadline:
        new_aid, _ = _trial_allocation(c, token, eid)
        if new_aid != aid:
            break
        time.sleep(0.2)
    assert new_aid != aid
    assert _trial(c, token, eid).get("restarts", 0) == 1
    assert c.api("GET", f"/api/v1/allocations/{aid}/size_history",
                 token=token)["size_history"] == []


# ---------------------------------------------------------------------------
# Capstone e2e (slow): heterogeneous devcluster, notice-file drain.
# ---------------------------------------------------------------------------


def _task_log_text(c, token, trial_id):
    logs = c.api("GET", f"/api/v1/tasks/trial-{trial_id}/logs?offset=0",
                 token=token)["logs"]
    return "\n".join(line["log"] for line in logs)


@pytest.mark.slow
def test_elastic_shrink_grow_e2e(tmp_path, native_binaries):
    """Acceptance: an elastic trial on a draining 2-slot agent shrinks to
    the 1-slot survivor and resumes WITHOUT a requeue (same allocation,
    restarts unchanged, size history records 2->1), then grows back to 2
    when the drained agent is re-enabled."""
    c = Devcluster(str(tmp_path), native_binaries, slots=2)
    c.start_master()
    nf = os.path.join(str(tmp_path), "notice-big.json")
    # XLA_FLAGS cleared so exec/launch sizes the virtual CPU "chips" to the
    # granted slot count — the re-placed run really re-resolves its mesh.
    c.start_agent("big", slots=2, extra_env={
        "DET_AGENT_NOTICE_FILE": nf, "XLA_FLAGS": ""})
    c.start_agent("small", slots=1, extra_env={"XLA_FLAGS": ""})
    try:
        config = _elastic_config(
            tmp_path,
            extra={
                "entrypoint": "python3 elastic_train.py",
                "searcher": {"name": "single", "metric": "val_loss",
                             "max_length": {"batches": 600}},
                "max_restarts": 2,
                "environment": {"ELASTIC_STEP_SLEEP": "0.1"},
            })
        eid, token = _create_experiment(c, config)
        admin = c.login("admin")

        # Mid-run on the big agent.
        deadline = time.time() + 120
        aid = None
        while time.time() < deadline:
            try:
                aid, state = _trial_allocation(c, token, eid)
            except TimeoutError:
                continue
            if state == "SCHEDULED":
                trials = c.api("GET", f"/api/v1/experiments/{eid}/trials",
                               token=token)["trials"]
                if trials and len(c.api(
                        "GET",
                        f"/api/v1/trials/{trials[0]['id']}/metrics"
                        "?group=training", token=token)["metrics"]) >= 5:
                    break
            time.sleep(0.5)
        alloc = _alloc(c, token, aid)
        assert alloc["slots"] == 2
        assert {r["agent_id"] for r in alloc["resources"]} == {"big"}
        trial_id = _trial(c, token, eid)["id"]

        # The notice: the big agent disappears in 45s.
        with open(nf, "w") as f:
            json.dump({"deadline_seconds": 45,
                       "reason": "spot_preemption"}, f)

        # Shrink: same allocation id lands on the survivor at 1 slot.
        deadline = time.time() + 60
        while time.time() < deadline:
            alloc = _alloc(c, token, aid)
            if alloc["slots"] == 1 and alloc["resources"] and \
                    alloc["resources"][0]["agent_id"] == "small":
                break
            time.sleep(0.5)
        assert alloc["slots"] == 1, f"never shrank: {alloc}"
        assert [r["agent_id"] for r in alloc["resources"]] == ["small"]
        hist = c.api("GET", f"/api/v1/allocations/{aid}/size_history",
                     token=token)["size_history"]
        assert [(h["from_slots"], h["to_slots"]) for h in hist] == [(2, 1)]
        assert _trial(c, token, eid).get("restarts", 0) == 0, (
            "elastic shrink must not consume a restart")

        # The harness took the resize path: budgeted emergency checkpoint,
        # then the re-placed run restored it.
        deadline = time.time() + 60
        text = ""
        while time.time() < deadline:
            text = _task_log_text(c, token, trial_id)
            if "resize preemption" in text and \
                    "restored from checkpoint" in text:
                break
            time.sleep(0.5)
        assert "resize preemption" in text, text[-2000:]
        assert "emergency checkpoint committed" in text, text[-2000:]
        assert "restored from checkpoint" in text, text[-2000:]

        # Capacity returns: the drained node dies (the agent exits once
        # idle+drained); its spot replacement boots with the same id and
        # registers FRESH, which clears the drain. The grow offer then
        # moves the trial back to 2 slots.
        os.unlink(nf)
        if c.agent.poll() is None:  # "big" was the first agent started
            c.agent.kill()
            c.agent.wait()
        c.start_agent("big", slots=2, extra_env={"XLA_FLAGS": ""})
        assert _agent(c, admin, "big")["state"] == "ENABLED"
        deadline = time.time() + 90
        while time.time() < deadline:
            alloc = _alloc(c, token, aid)
            hist = c.api("GET",
                         f"/api/v1/allocations/{aid}/size_history",
                         token=token)["size_history"]
            if len(hist) >= 2 and alloc["slots"] == 2:
                break
            time.sleep(1.0)
        assert alloc["slots"] == 2, f"never grew back: {alloc} {hist}"
        assert [(h["from_slots"], h["to_slots"]) for h in hist][:2] == \
            [(2, 1), (1, 2)]
        assert "scale-up" in hist[1]["reason"]
        assert _trial(c, token, eid).get("restarts", 0) == 0

        # And the trial still finishes.
        _wait_experiment(c, eid, token, timeout=300.0)
        t = _trial(c, token, eid)
        assert t["state"] == "COMPLETED"
        assert t.get("restarts", 0) == 0
    finally:
        c.stop()
