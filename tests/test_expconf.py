"""expconf schema tests: searcher/storage/mesh validation and defaults.

Reference discipline: schemas/expconf/v0/*.json validation in the master's
pkg/schemas/expconf (SURVEY.md §5 "Config/flag system")."""

import pytest

from determined_tpu import expconf


def base_config(**over):
    c = {
        "entrypoint": "python3 train.py",
        "searcher": {
            "name": "single",
            "metric": "loss",
            "max_length": {"batches": 10},
        },
    }
    c.update(over)
    return c


class TestValidate:
    def test_valid_minimal(self):
        assert expconf.validate(base_config()) == []

    def test_missing_entrypoint(self):
        c = base_config()
        del c["entrypoint"]
        assert any("entrypoint" in e for e in expconf.validate(c))

    def test_azure_requires_container(self):
        c = base_config(checkpoint_storage={"type": "azure"})
        assert any("container" in e for e in expconf.validate(c))
        c = base_config(
            checkpoint_storage={"type": "azure", "container": "ckpts"}
        )
        assert expconf.validate(c) == []


class TestMeshValidation:
    """hyperparameters.mesh is the single validated home of the mesh config."""

    def test_valid_mesh(self):
        c = base_config(
            hyperparameters={"mesh": {"data": -1, "fsdp": 4}},
            resources={"slots_per_trial": 8},
        )
        assert expconf.validate(c) == []

    def test_unknown_axis_rejected(self):
        c = base_config(hyperparameters={"mesh": {"warp": 2}})
        errs = expconf.validate(c)
        assert any("unknown axes" in e and "warp" in e for e in errs)

    def test_two_minus_ones_rejected(self):
        c = base_config(hyperparameters={"mesh": {"data": -1, "fsdp": -1}})
        assert any("at most one axis may be -1" in e for e in expconf.validate(c))

    def test_zero_size_rejected(self):
        c = base_config(hyperparameters={"mesh": {"data": 0}})
        assert any("positive int or -1" in e for e in expconf.validate(c))

    def test_bool_size_rejected(self):
        # YAML `data: true` must not slip through as int(1)
        c = base_config(hyperparameters={"mesh": {"data": True}})
        assert any("positive int or -1" in e for e in expconf.validate(c))

    def test_product_must_match_slots(self):
        c = base_config(
            hyperparameters={"mesh": {"data": 2, "tensor": 3}},
            resources={"slots_per_trial": 8},
        )
        assert any("axis product 6" in e for e in expconf.validate(c))

    def test_mesh_without_resources_checks_default_slots(self):
        # apply_defaults sets slots_per_trial=1; a fixed 8-chip mesh with no
        # resources block must fail at submit, not at MeshConfig.resolve().
        c = base_config(hyperparameters={"mesh": {"data": 8}})
        assert any("axis product 8" in e for e in expconf.validate(c))

    def test_slots_divisibility_with_wildcard(self):
        c = base_config(
            hyperparameters={"mesh": {"data": -1, "tensor": 3}},
            resources={"slots_per_trial": 8},
        )
        assert any("not divisible" in e for e in expconf.validate(c))

    def test_check_raises_on_bad_mesh(self):
        c = base_config(hyperparameters={"mesh": {"bogus": 1}})
        with pytest.raises(ValueError, match="bogus"):
            expconf.check(c)


class TestOptimizationsBlock:
    """`optimizations:` — TPU training-perf knobs (docs/training-perf.md),
    validated at submit so a typo'd attention_impl fails before compile."""

    def test_valid_block(self):
        c = base_config(optimizations={
            "attention_impl": "pallas",
            "attention_bf16": True,
            "overlap_allgather": True,
            "prepartition_inputs": False,
        })
        assert expconf.validate(c) == []

    @pytest.mark.parametrize("impl", ["auto", "pallas", "reference", "dense"])
    def test_every_impl_accepted(self, impl):
        c = base_config(optimizations={"attention_impl": impl})
        assert expconf.validate(c) == []

    def test_bad_impl_rejected(self):
        c = base_config(optimizations={"attention_impl": "palas"})
        assert any("attention_impl" in e and "palas" in e
                   for e in expconf.validate(c))

    def test_unknown_key_rejected(self):
        c = base_config(optimizations={"attension_bf16": True})
        assert any("attension_bf16" in e for e in expconf.validate(c))

    def test_non_bool_flag_rejected(self):
        c = base_config(optimizations={"attention_bf16": "yes"})
        assert any("attention_bf16" in e for e in expconf.validate(c))

    def test_must_be_mapping(self):
        c = base_config(optimizations=["attention_impl"])
        assert any("optimizations" in e and "mapping" in e
                   for e in expconf.validate(c))

    def test_defaults_fill_block(self):
        out = expconf.apply_defaults(base_config())
        assert out["optimizations"] == {
            "attention_impl": "auto",
            "attention_bf16": False,
            "overlap_allgather": False,
            "prepartition_inputs": True,
        }

    def test_defaults_keep_explicit_values(self):
        out = expconf.apply_defaults(
            base_config(optimizations={"attention_impl": "dense"}))
        assert out["optimizations"]["attention_impl"] == "dense"
        assert out["optimizations"]["prepartition_inputs"] is True


class TestDefaults:
    def test_no_dead_tpu_block(self):
        # The mesh config has exactly one home: hyperparameters.mesh.
        out = expconf.apply_defaults(base_config())
        assert "tpu" not in out

    def test_core_defaults(self):
        out = expconf.apply_defaults(base_config())
        assert out["max_restarts"] == 5
        assert out["resources"]["slots_per_trial"] == 1


class TestLegacyShims:
    """Version shims (reference pkg/schemas/expconf/legacy.go): old config
    shapes keep working through expconf.check()."""

    def _base(self, **searcher):
        return {
            "entrypoint": "python3 train.py",
            "searcher": {"name": "single", "metric": "loss", **searcher},
        }

    def test_bare_int_lengths(self):
        cfg = self._base(max_length=500)
        cfg["min_validation_period"] = 50
        out = expconf.check(cfg)
        assert out["searcher"]["max_length"] == {"batches": 500}
        assert out["min_validation_period"] == {"batches": 50}

    def test_max_steps_alias(self):
        out = expconf.check(self._base(max_steps=100))
        assert out["searcher"]["max_length"] == {"batches": 100}

    def test_resources_slots_alias(self):
        cfg = self._base(max_length={"batches": 4})
        cfg["resources"] = {"slots": 8}
        out = expconf.check(cfg)
        assert out["resources"]["slots_per_trial"] == 8

    def test_dropped_container_era_keys_warn(self):
        import warnings

        cfg = self._base(max_length={"batches": 4})
        cfg["bind_mounts"] = [{"host_path": "/x", "container_path": "/y"}]
        cfg["optimizations"] = {"aggregation_frequency": 2}
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            out = expconf.check(cfg)
        assert "bind_mounts" not in out
        # The torch-era key is shimmed away; the block itself survives as
        # the TPU optimizations knobs, filled with defaults.
        assert "aggregation_frequency" not in out["optimizations"]
        assert out["optimizations"]["attention_impl"] == "auto"
        joined = " ".join(str(x.message) for x in w)
        assert "bind_mounts" in joined and "aggregation_frequency" in joined

    def test_legacy_adaptive_runs_through(self):
        out = expconf.check({
            "entrypoint": "python3 train.py",
            "searcher": {"name": "adaptive", "metric": "loss",
                         "max_length": 16, "max_trials": 4},
        })
        assert out["searcher"]["max_length"] == {"batches": 16}
        assert out["searcher"]["divisor"] == 4


class TestPreflightBlock:
    """The `preflight:` config block (docs/preflight.md) is schema-checked
    like every other block."""

    def test_valid_block(self):
        c = base_config(preflight={"gate": "error",
                                   "suppress": ["DTL001", "DTL201"],
                                   "hbm_gb_per_device": 16})
        assert expconf.validate(c) == []

    def test_bad_gate(self):
        c = base_config(preflight={"gate": "maybe"})
        assert any("preflight.gate" in e for e in expconf.validate(c))

    def test_bad_suppress_code(self):
        c = base_config(preflight={"suppress": ["DTL1", 7]})
        errs = expconf.validate(c)
        assert sum("preflight.suppress" in e for e in errs) == 2

    def test_bad_hbm(self):
        c = base_config(preflight={"hbm_gb_per_device": -1})
        assert any("hbm_gb_per_device" in e for e in expconf.validate(c))


class TestPrefetchBlock:
    """The `prefetch:` config block (async input pipeline,
    docs/trial-api.md): on by default, opt-out + depth knobs."""

    def test_valid_block(self):
        c = base_config(prefetch={"enabled": True, "depth": 4,
                                  "shard": True})
        assert expconf.validate(c) == []

    def test_bare_bool(self):
        assert expconf.validate(base_config(prefetch=False)) == []

    def test_bad_depth(self):
        for depth in (0, -1, 1.5, True, "two"):
            c = base_config(prefetch={"depth": depth})
            assert any("prefetch.depth" in e for e in expconf.validate(c)), depth

    def test_bad_enabled(self):
        c = base_config(prefetch={"enabled": "yes"})
        assert any("prefetch.enabled" in e for e in expconf.validate(c))

    def test_unknown_key(self):
        c = base_config(prefetch={"buffers": 3})
        assert any("unknown keys" in e for e in expconf.validate(c))

    def test_defaults_applied(self):
        out = expconf.apply_defaults(base_config())
        assert out["prefetch"] == {"enabled": True, "depth": 2}

    def test_defaults_keep_user_values(self):
        out = expconf.apply_defaults(base_config(prefetch={"depth": 8}))
        assert out["prefetch"] == {"enabled": True, "depth": 8}


class TestHealthBlock:
    """The `health:` config block (self-healing loop,
    docs/checkpointing.md): divergence sentinel policy + step watchdog."""

    def test_valid_block(self):
        c = base_config(health={"on_nan": "rollback", "rollback_window": 4,
                                "max_rollbacks": 2, "step_timeout_sec": 120})
        assert expconf.validate(c) == []

    def test_bad_on_nan(self):
        c = base_config(health={"on_nan": "explode"})
        assert any("health.on_nan" in e for e in expconf.validate(c))

    def test_bad_window(self):
        for w in (-1, 1.5, True, "many"):
            c = base_config(health={"rollback_window": w})
            assert any("rollback_window" in e for e in expconf.validate(c)), w

    def test_zero_max_rollbacks_rejected(self):
        c = base_config(health={"max_rollbacks": 0})
        assert any("max_rollbacks" in e for e in expconf.validate(c))

    def test_bad_timeout(self):
        c = base_config(health={"step_timeout_sec": -5})
        assert any("step_timeout_sec" in e for e in expconf.validate(c))

    def test_unknown_key(self):
        c = base_config(health={"watchdog": True})
        assert any("unknown keys" in e for e in expconf.validate(c))

    def test_not_a_mapping(self):
        c = base_config(health=True)
        assert any("health must be a mapping" in e for e in expconf.validate(c))

    def test_defaults_applied(self):
        out = expconf.apply_defaults(base_config())
        assert out["health"] == {"on_nan": "warn", "rollback_window": 8,
                                 "max_rollbacks": 3, "step_timeout_sec": 0}

    def test_defaults_keep_user_values(self):
        out = expconf.apply_defaults(base_config(health={"on_nan": "fail"}))
        assert out["health"]["on_nan"] == "fail"
        assert out["health"]["step_timeout_sec"] == 0


class TestPreemptionBlock:
    """The `preemption:` config block (spot-survival emergency checkpoint,
    docs/checkpointing.md "Emergency checkpoints")."""

    def test_valid_block(self):
        c = base_config(preemption={"emergency_checkpoint": True,
                                    "budget_safety_factor": 2.0,
                                    "budget_margin_sec": 5})
        assert expconf.validate(c) == []

    def test_bare_bool_is_valid(self):
        assert expconf.validate(base_config(preemption=False)) == []

    def test_bad_emergency_checkpoint(self):
        c = base_config(preemption={"emergency_checkpoint": "yes"})
        assert any("emergency_checkpoint" in e for e in expconf.validate(c))

    def test_bad_safety_factor(self):
        for v in (0, 0.5, True, "fast"):
            c = base_config(preemption={"budget_safety_factor": v})
            assert any("budget_safety_factor" in e
                       for e in expconf.validate(c)), v

    def test_bad_margin(self):
        for v in (-1, True, "soon"):
            c = base_config(preemption={"budget_margin_sec": v})
            assert any("budget_margin_sec" in e
                       for e in expconf.validate(c)), v

    def test_unknown_key(self):
        c = base_config(preemption={"grace": 30})
        assert any("unknown keys" in e for e in expconf.validate(c))

    def test_not_a_mapping(self):
        c = base_config(preemption=[30])
        assert any("preemption must be a bool or a mapping" in e
                   for e in expconf.validate(c))

    def test_defaults_applied(self):
        out = expconf.apply_defaults(base_config())
        assert out["preemption"] == {"emergency_checkpoint": True,
                                     "budget_safety_factor": 1.5,
                                     "budget_margin_sec": 2.0}

    def test_defaults_keep_user_values(self):
        out = expconf.apply_defaults(
            base_config(preemption={"budget_margin_sec": 7}))
        assert out["preemption"]["budget_margin_sec"] == 7
        assert out["preemption"]["emergency_checkpoint"] is True


class TestServingBlock:
    """`serving:` — a det serve deployment config (docs/serving.md)."""

    def _config(self, **serving):
        return {
            "name": "serve-test",
            "serving": {"checkpoint": "trial0-step2", **serving},
            "checkpoint_storage": {"type": "shared_fs",
                                   "host_path": "/tmp/x"},
        }

    def test_minimal_serving_config_valid(self):
        # No entrypoint, no searcher: serving configs are deployments.
        assert expconf.validate(self._config()) == []

    def test_defaults_fill_capacity_knobs(self):
        c = expconf.check(self._config())
        s = c["serving"]
        assert s["max_batch_size"] == 8
        assert s["max_seq_len"] == 256
        assert s["kv_block_size"] == 16
        assert s["queue_depth"] == 64
        assert s["model"] == "gpt2"
        # and no searcher machinery was bolted on
        assert "searcher" not in c

    def test_unknown_keys_flagged(self):
        errs = expconf.validate(self._config(batch_sise=4))
        assert any("unknown keys" in e for e in errs)

    def test_bad_values_flagged(self):
        errs = expconf.validate(self._config(max_batch_size=0))
        assert any("max_batch_size" in e for e in errs)
        errs = expconf.validate(self._config(model="bert"))
        assert any("serving.model" in e for e in errs)
        errs = expconf.validate(self._config(prefill_buckets=[64, 32]))
        assert any("ascending" in e for e in errs)
        errs = expconf.validate(self._config(prefill_buckets=[]))
        assert any("prefill_buckets" in e for e in errs)

    def test_paged_kv_knobs_validate_and_default(self):
        # Defaults: paged layout with prefix caching on, impl auto.
        c = expconf.check(self._config())
        assert c["serving"]["prefix_cache"] is True
        assert c["serving"]["attention_impl"] == "auto"
        assert "kv_num_blocks" not in c["serving"]  # derived, not defaulted
        # Valid explicit values pass.
        assert expconf.validate(self._config(
            attention_impl="pallas", prefix_cache=False,
            kv_num_blocks=128)) == []
        # Bad values are rejected.
        errs = expconf.validate(self._config(attention_impl="flash"))
        assert any("attention_impl" in e for e in errs)
        errs = expconf.validate(self._config(prefix_cache="yes"))
        assert any("prefix_cache" in e for e in errs)
        errs = expconf.validate(self._config(kv_num_blocks=0))
        assert any("kv_num_blocks" in e for e in errs)

    def test_serving_must_be_mapping(self):
        errs = expconf.validate({"name": "x", "serving": "yes"})
        assert any("serving must be a mapping" in e for e in errs)

    def test_trial_configs_still_require_searcher(self):
        errs = expconf.validate({"name": "x", "entrypoint": "python3 t.py"})
        assert any("searcher is required" in e for e in errs)

    # -- model lifecycle (docs/serving.md "Model lifecycle") ------------

    def test_serving_adapters_valid(self):
        cfg = self._config(adapters=[
            {"name": "ft-a", "checkpoint": "ck-a"},
            {"name": "ft-b", "checkpoint": "ck-b"},
        ])
        assert expconf.validate(cfg) == []

    @pytest.mark.parametrize("adapters,needle", [
        ("ft", "must be a list"),
        ([["x"]], "must be a mapping"),
        ([{"checkpoint": "ck"}], "name must be a non-empty string"),
        ([{"name": "", "checkpoint": "ck"}], "non-empty string"),
        ([{"name": "a", "checkpoint": "c1"},
          {"name": "a", "checkpoint": "c2"}], "duplicate"),
        ([{"name": "base", "checkpoint": "ck"}], "reserved"),
        ([{"name": "a"}], "checkpoint must be a checkpoint storage id"),
        ([{"name": "a", "checkpoint": "ck", "rank": 8}], "unknown keys"),
    ])
    def test_serving_adapters_invalid(self, adapters, needle):
        errs = expconf.validate(self._config(adapters=adapters))
        assert any(needle in e for e in errs), (adapters, errs)

    def test_serving_canary_valid_and_defaults(self):
        cfg = self._config(canary={"model": "m", "version": 2,
                                   "fraction": 0.1})
        assert expconf.validate(cfg) == []
        out = expconf.check(cfg)
        assert out["serving"]["canary"]["replicas"] == 1
        # fraction defaults to 0.05 when omitted
        out = expconf.check(self._config(canary={"checkpoint": "ck-2"}))
        assert out["serving"]["canary"]["fraction"] == 0.05

    @pytest.mark.parametrize("canary,needle", [
        ("v2", "must be a mapping"),
        ({"fraction": 0.1}, "requires `model`"),
        ({"model": "m", "fraction": 0}, "(0, 1)"),
        ({"model": "m", "fraction": 1}, "(0, 1)"),
        ({"model": "m", "fraction": True}, "(0, 1)"),
        ({"model": "m", "version": 0}, "positive int"),
        ({"checkpoint": "ck", "version": 2}, "requires `model`"),
        ({"model": "m", "replicas": 0}, "replicas must be a positive"),
        ({"model": "m", "surge": 1}, "unknown keys"),
    ])
    def test_serving_canary_invalid(self, canary, needle):
        errs = expconf.validate(self._config(canary=canary))
        assert any(needle in e for e in errs), (canary, errs)

    def test_serving_model_version_label(self):
        assert expconf.validate(self._config(model_version="m:3")) == []
        errs = expconf.validate(self._config(model_version=""))
        assert any("model_version" in e for e in errs)


class TestRegistryBlock:
    """`registry:` — train→serve auto-promotion (docs/serving.md
    'Model lifecycle')."""

    def _config(self, registry):
        return {
            "name": "t",
            "entrypoint": "python3 train.py",
            "searcher": {"name": "single", "metric": "loss",
                         "max_length": {"batches": 4}},
            "registry": registry,
        }

    def test_valid_and_promote_default(self):
        cfg = self._config({"model": "prod-gpt2"})
        assert expconf.validate(cfg) == []
        out = expconf.check(cfg)
        assert out["registry"]["promote"] == "best"
        assert expconf.validate(
            self._config({"model": "m", "promote": "latest"})) == []

    @pytest.mark.parametrize("registry,needle", [
        ("m", "registry must be a mapping"),
        ({}, "registry.model"),
        ({"model": ""}, "registry.model"),
        ({"model": 3}, "registry.model"),
        ({"model": "m:2"}, "bare model name"),
        ({"model": "m", "promote": "newest"}, "best, latest"),
        ({"model": "m", "version": 2}, "unknown keys"),
    ])
    def test_invalid(self, registry, needle):
        errs = expconf.validate(self._config(registry))
        assert any(needle in e for e in errs), (registry, errs)

    def test_registry_refused_on_serving_configs(self):
        cfg = {"name": "d", "serving": {"model": "gpt2"},
               "registry": {"model": "m"}}
        errs = expconf.validate(cfg)
        assert any("belongs to training configs" in e for e in errs)


class TestCrossFieldDiagnostics:
    """Cross-field checks surface as DTL rules (the same codes the native
    master enforces at experiment create), not bare exceptions."""

    def test_batch_mesh_divisible_clean(self):
        c = base_config(
            hyperparameters={"global_batch_size": 32},
            resources={"slots_per_trial": 8},
        )
        assert expconf.cross_field_diagnostics(c) == []

    def test_batch_mesh_mismatch_dtl201(self):
        c = base_config(
            hyperparameters={"global_batch_size": 30},
            resources={"slots_per_trial": 8},
        )
        diags = expconf.cross_field_diagnostics(c)
        assert [d.code for d in diags] == ["DTL201"]
        assert diags[0].level == "error"
        assert "30" in diags[0].message

    def test_explicit_mesh_batch_axes(self):
        # data=2 x fsdp=2 (tensor=2 is not a batch axis) -> product 4.
        c = base_config(
            hyperparameters={
                "global_batch_size": 6,
                "mesh": {"data": 2, "fsdp": 2, "tensor": 2},
            },
            resources={"slots_per_trial": 8},
        )
        assert [d.code for d in expconf.cross_field_diagnostics(c)] == [
            "DTL201"]
        c["hyperparameters"]["global_batch_size"] = 8
        assert expconf.cross_field_diagnostics(c) == []

    def test_const_hparam_spec_unwrapped(self):
        c = base_config(
            hyperparameters={
                "global_batch_size": {"type": "const", "val": 30}},
            resources={"slots_per_trial": 8},
        )
        assert [d.code for d in expconf.cross_field_diagnostics(c)] == [
            "DTL201"]

    def _asha(self, max_length, num_rungs=5, divisor=4):
        return base_config(searcher={
            "name": "async_halving", "metric": "loss",
            "max_length": {"batches": max_length},
            "num_rungs": num_rungs, "divisor": divisor,
        })

    def test_asha_budget_too_small_dtl202(self):
        diags = expconf.cross_field_diagnostics(self._asha(100))
        assert [d.code for d in diags] == ["DTL202"]
        assert diags[0].level == "error"

    def test_asha_budget_sufficient(self):
        assert expconf.cross_field_diagnostics(self._asha(256)) == []

    def test_asha_legacy_bare_int_length_shimmed(self):
        c = self._asha(100)
        c["searcher"]["max_length"] = 100  # legacy bare int
        assert [d.code for d in expconf.cross_field_diagnostics(c)] == [
            "DTL202"]


def test_all_shipped_example_configs_validate():
    """Every yaml under examples/ must pass expconf.check — shipped
    configs rotting against schema changes is exactly what the reference's
    schema CI prevents."""
    import glob
    import os

    import yaml

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    configs = sorted(glob.glob(os.path.join(repo, "examples", "*", "*.yaml")))
    assert len(configs) >= 8, configs
    for path in configs:
        with open(path) as f:
            cfg = yaml.safe_load(f)
        try:
            expconf.check(cfg)
        except ValueError as e:
            raise AssertionError(f"{path} fails validation: {e}") from None
