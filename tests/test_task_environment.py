"""Task environment management e2e: env vars, python_path, venv activation.

Reference: the task-spec builder renders env images/mounts/env vars into the
container spec (master/pkg/tasks/task.go:194-234). The TPU equivalent is
process-level: the master injects config env vars into the task env, and the
launch layer (determined_tpu/exec/launch.py apply_task_environment) performs
venv activation + PYTHONPATH extension before exec'ing the entrypoint."""

import os
import sys
import time

import pytest

from determined_tpu.exec.launch import apply_task_environment
from tests.test_platform_e2e import (  # noqa: F401
    FIXTURES,
    Devcluster,
    _wait_experiment,
    native_binaries,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TASKENV_FIXTURES = os.path.join(REPO, "tests", "fixtures", "taskenv")


class TestApplyTaskEnvironment:
    def test_env_vars_list_form(self):
        env = apply_task_environment(
            {}, {"environment": {"environment_variables": ["A=1", "B=x=y"]}}
        )
        assert env["A"] == "1"
        assert env["B"] == "x=y"  # split on first '=' only

    def test_venv_activation(self):
        env = apply_task_environment(
            {"PATH": "/usr/bin", "PYTHONHOME": "/opt/py"},
            {"environment": {"venv": "/opt/task-venv"}},
        )
        assert env["VIRTUAL_ENV"] == "/opt/task-venv"
        assert env["PATH"].startswith("/opt/task-venv/bin" + os.pathsep)
        assert "PYTHONHOME" not in env

    def test_python_path_appended(self):
        env = apply_task_environment(
            {"PYTHONPATH": "/ctx"},
            {"environment": {"python_path": ["/pkgs/a", "/pkgs/b"]}},
        )
        assert env["PYTHONPATH"] == os.pathsep.join(["/ctx", "/pkgs/a", "/pkgs/b"])

    def test_no_environment_block(self):
        assert apply_task_environment({"X": "1"}, {}) == {"X": "1"}


class TestExpconfEnvironmentValidation:
    def test_valid(self):
        from determined_tpu import expconf

        c = {
            "entrypoint": "python3 t.py",
            "searcher": {"name": "single", "metric": "m",
                         "max_length": {"batches": 1}},
            "environment": {
                "FOO": "bar",
                "environment_variables": ["K=V"],
                "venv": "/opt/venv",
                "python_path": ["/pkgs"],
            },
        }
        assert expconf.validate(c) == []

    def test_bad_entries(self):
        from determined_tpu import expconf

        c = {
            "entrypoint": "python3 t.py",
            "searcher": {"name": "single", "metric": "m",
                         "max_length": {"batches": 1}},
            "environment": {
                "environment_variables": ["NOEQUALS"],
                "venv": 7,
                "python_path": "notalist",
                "NUM": 3,
            },
        }
        errs = expconf.validate(c)
        assert any("NOEQUALS" in e for e in errs)
        assert any("venv" in e for e in errs)
        assert any("python_path" in e for e in errs)
        assert any("environment.NUM" in e for e in errs)


@pytest.fixture()
def cluster(tmp_path, native_binaries):  # noqa: F811
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    c.start_agent()
    yield c
    c.stop()


def test_task_environment_e2e(cluster, tmp_path):
    """A trial sees its configured env vars, imports from an extra package
    root, and runs under the task venv's interpreter."""
    # Extra package root (outside the context dir).
    extra = tmp_path / "extra-pkgs"
    extra.mkdir()
    (extra / "extra_pkg.py").write_text("VALUE = 42\n")
    # Fake venv whose bin/python3 is the real interpreter.
    venv = tmp_path / "fake-venv"
    (venv / "bin").mkdir(parents=True)
    os.symlink(sys.executable, venv / "bin" / "python3")

    import determined_tpu.cli as cli

    config = {
        "name": "taskenv-e2e",
        "entrypoint": "python3 train_env.py",
        "searcher": {
            "name": "single",
            "metric": "val_loss",
            "max_length": {"batches": 2},
        },
        "checkpoint_storage": {
            "type": "shared_fs",
            "host_path": os.path.join(str(tmp_path), "ckpts"),
        },
        "environment": {
            "MY_TASK_FLAG": "from-config",
            "environment_variables": ["MY_TASK_FLAG2=listed"],
            "venv": str(venv),
            "python_path": [str(extra)],
        },
        "resources": {"slots_per_trial": 1},
        "max_restarts": 0,
    }
    token = cluster.login()
    resp = cluster.api(
        "POST", "/api/v1/experiments",
        {
            "config": config,
            "model_definition": cli._tar_context(TASKENV_FIXTURES),
            "activate": True,
        },
        token=token,
    )
    _wait_experiment(cluster, resp["id"], token, timeout=120)
    # The fixture asserts the environment before reporting; reaching
    # COMPLETED proves env vars + python_path + venv all applied.
    logs = cluster.api(
        "GET", f"/api/v1/experiments/{resp['id']}/trials", token=token
    )["trials"]
    assert logs[0]["state"] == "COMPLETED"


def test_startup_hook_runs_before_entrypoint(cluster, tmp_path):
    """startup-hook.sh in the context dir runs before the entrypoint
    (reference exec/prep_container.py); a failing hook fails the task."""
    import shutil

    ctx = tmp_path / "hookctx"
    ctx.mkdir()
    shutil.copy(os.path.join(FIXTURES, "train.py"), ctx / "train.py")
    (ctx / "startup-hook.sh").write_text(
        "echo hook-side-effect > hook_output.txt\n"
        "echo startup-hook-ran-$((40+4))\n")
    (ctx / "reader.py").write_text(
        "print('hook says:', open('hook_output.txt').read().strip())\n")

    token = cluster.login()
    import determined_tpu.cli as cli

    tid = cluster.api(
        "POST", "/api/v1/commands",
        {"config": {"entrypoint": "python3 reader.py"},
         "context": cli._tar_context(str(ctx))}, token=token)["id"]
    deadline = time.time() + 60
    state = None
    while time.time() < deadline:
        t = cluster.api("GET", f"/api/v1/commands/{tid}", token=token)["task"]
        state = t["state"]
        if state in ("COMPLETED", "ERROR", "CANCELED"):
            break
        time.sleep(0.2)
    assert state == "COMPLETED", state
    logs = cluster.api("GET", f"/api/v1/tasks/{tid}/logs",
                       token=token)["logs"]
    text = "\n".join(line["log"] for line in logs)
    assert "startup-hook-ran-44" in text       # hook output shipped
    assert "hook says: hook-side-effect" in text  # entrypoint saw its work

    # Failing hook → task fails, entrypoint never runs.
    ctx2 = tmp_path / "hookctx2"
    ctx2.mkdir()
    (ctx2 / "startup-hook.sh").write_text("echo doomed; exit 3\n")
    (ctx2 / "nope.py").write_text("print('must-not-run')\n")
    tid2 = cluster.api(
        "POST", "/api/v1/commands",
        {"config": {"entrypoint": "python3 nope.py"},
         "context": cli._tar_context(str(ctx2))}, token=token)["id"]
    deadline = time.time() + 60
    while time.time() < deadline:
        t = cluster.api("GET", f"/api/v1/commands/{tid2}",
                        token=token)["task"]
        if t["state"] in ("COMPLETED", "ERROR", "CANCELED"):
            break
        time.sleep(0.2)
    assert t["state"] == "ERROR", t["state"]
    logs2 = cluster.api("GET", f"/api/v1/tasks/{tid2}/logs",
                        token=token)["logs"]
    text2 = "\n".join(line["log"] for line in logs2)
    assert "must-not-run" not in text2


def test_cli_cmd_run_with_context(cluster, tmp_path):
    """`det cmd run --context DIR …` ships the dir (reference parity)."""
    import subprocess

    ctx = tmp_path / "clictx"
    ctx.mkdir()
    (ctx / "data.txt").write_text("context-payload-99\n")
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
        HOME=cluster.tmpdir,
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable, "-m", "determined_tpu.cli",
         "-m", cluster.master_url, "cmd", "run", "--context", str(ctx),
         "cat", "data.txt"],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    tid = r.stdout.split("Started ")[1].split(" ")[0]
    token = cluster.login()
    deadline = time.time() + 60
    while time.time() < deadline:
        t = cluster.api("GET", f"/api/v1/commands/{tid}", token=token)["task"]
        if t["state"] in ("COMPLETED", "ERROR", "CANCELED"):
            break
        time.sleep(0.2)
    assert t["state"] == "COMPLETED", t["state"]
    logs = cluster.api("GET", f"/api/v1/tasks/{tid}/logs",
                       token=token)["logs"]
    assert any("context-payload-99" in line["log"] for line in logs)


def test_task_context_released_on_terminal(cluster, tmp_path):
    """A terminal task releases its content-store claim: blobs must not
    accumulate per `det cmd run --context` invocation."""
    import sqlite3

    ctx = tmp_path / "relctx"
    ctx.mkdir()
    (ctx / "unique.txt").write_text(f"payload-{tmp_path}\n")
    import determined_tpu.cli as cli

    token = cluster.login()
    tid = cluster.api(
        "POST", "/api/v1/commands",
        {"config": {"entrypoint": "cat unique.txt"},
         "context": cli._tar_context(str(ctx))}, token=token)["id"]
    deadline = time.time() + 60
    while time.time() < deadline:
        t = cluster.api("GET", f"/api/v1/commands/{tid}", token=token)["task"]
        if t["state"] in ("COMPLETED", "ERROR", "CANCELED"):
            break
        time.sleep(0.2)
    assert t["state"] == "COMPLETED", t["state"]

    deadline = time.time() + 15
    while time.time() < deadline:
        con = sqlite3.connect(f"file:{cluster.db_path}?mode=ro", uri=True)
        try:
            row = con.execute(
                "SELECT context_hash FROM tasks WHERE id=?", (tid,)
            ).fetchone()
            n_blobs = con.execute(
                "SELECT COUNT(*) FROM model_defs WHERE refcount <= 0"
            ).fetchone()[0]
        finally:
            con.close()
        if row and row[0] is None and n_blobs == 0:
            return
        time.sleep(0.5)
    raise AssertionError(f"context not released: hash={row}, "
                         f"zombie blobs={n_blobs}")
