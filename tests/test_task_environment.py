"""Task environment management e2e: env vars, python_path, venv activation.

Reference: the task-spec builder renders env images/mounts/env vars into the
container spec (master/pkg/tasks/task.go:194-234). The TPU equivalent is
process-level: the master injects config env vars into the task env, and the
launch layer (determined_tpu/exec/launch.py apply_task_environment) performs
venv activation + PYTHONPATH extension before exec'ing the entrypoint."""

import os
import sys

import pytest

from determined_tpu.exec.launch import apply_task_environment
from tests.test_platform_e2e import Devcluster, _wait_experiment, native_binaries  # noqa: F401

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TASKENV_FIXTURES = os.path.join(REPO, "tests", "fixtures", "taskenv")


class TestApplyTaskEnvironment:
    def test_env_vars_list_form(self):
        env = apply_task_environment(
            {}, {"environment": {"environment_variables": ["A=1", "B=x=y"]}}
        )
        assert env["A"] == "1"
        assert env["B"] == "x=y"  # split on first '=' only

    def test_venv_activation(self):
        env = apply_task_environment(
            {"PATH": "/usr/bin", "PYTHONHOME": "/opt/py"},
            {"environment": {"venv": "/opt/task-venv"}},
        )
        assert env["VIRTUAL_ENV"] == "/opt/task-venv"
        assert env["PATH"].startswith("/opt/task-venv/bin" + os.pathsep)
        assert "PYTHONHOME" not in env

    def test_python_path_appended(self):
        env = apply_task_environment(
            {"PYTHONPATH": "/ctx"},
            {"environment": {"python_path": ["/pkgs/a", "/pkgs/b"]}},
        )
        assert env["PYTHONPATH"] == os.pathsep.join(["/ctx", "/pkgs/a", "/pkgs/b"])

    def test_no_environment_block(self):
        assert apply_task_environment({"X": "1"}, {}) == {"X": "1"}


class TestExpconfEnvironmentValidation:
    def test_valid(self):
        from determined_tpu import expconf

        c = {
            "entrypoint": "python3 t.py",
            "searcher": {"name": "single", "metric": "m",
                         "max_length": {"batches": 1}},
            "environment": {
                "FOO": "bar",
                "environment_variables": ["K=V"],
                "venv": "/opt/venv",
                "python_path": ["/pkgs"],
            },
        }
        assert expconf.validate(c) == []

    def test_bad_entries(self):
        from determined_tpu import expconf

        c = {
            "entrypoint": "python3 t.py",
            "searcher": {"name": "single", "metric": "m",
                         "max_length": {"batches": 1}},
            "environment": {
                "environment_variables": ["NOEQUALS"],
                "venv": 7,
                "python_path": "notalist",
                "NUM": 3,
            },
        }
        errs = expconf.validate(c)
        assert any("NOEQUALS" in e for e in errs)
        assert any("venv" in e for e in errs)
        assert any("python_path" in e for e in errs)
        assert any("environment.NUM" in e for e in errs)


@pytest.fixture()
def cluster(tmp_path, native_binaries):  # noqa: F811
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    c.start_agent()
    yield c
    c.stop()


def test_task_environment_e2e(cluster, tmp_path):
    """A trial sees its configured env vars, imports from an extra package
    root, and runs under the task venv's interpreter."""
    # Extra package root (outside the context dir).
    extra = tmp_path / "extra-pkgs"
    extra.mkdir()
    (extra / "extra_pkg.py").write_text("VALUE = 42\n")
    # Fake venv whose bin/python3 is the real interpreter.
    venv = tmp_path / "fake-venv"
    (venv / "bin").mkdir(parents=True)
    os.symlink(sys.executable, venv / "bin" / "python3")

    import determined_tpu.cli as cli

    config = {
        "name": "taskenv-e2e",
        "entrypoint": "python3 train_env.py",
        "searcher": {
            "name": "single",
            "metric": "val_loss",
            "max_length": {"batches": 2},
        },
        "checkpoint_storage": {
            "type": "shared_fs",
            "host_path": os.path.join(str(tmp_path), "ckpts"),
        },
        "environment": {
            "MY_TASK_FLAG": "from-config",
            "environment_variables": ["MY_TASK_FLAG2=listed"],
            "venv": str(venv),
            "python_path": [str(extra)],
        },
        "resources": {"slots_per_trial": 1},
        "max_restarts": 0,
    }
    token = cluster.login()
    resp = cluster.api(
        "POST", "/api/v1/experiments",
        {
            "config": config,
            "model_definition": cli._tar_context(TASKENV_FIXTURES),
            "activate": True,
        },
        token=token,
    )
    _wait_experiment(cluster, resp["id"], token, timeout=120)
    # The fixture asserts the environment before reporting; reaching
    # COMPLETED proves env vars + python_path + venv all applied.
    logs = cluster.api(
        "GET", f"/api/v1/experiments/{resp['id']}/trials", token=token
    )["trials"]
    assert logs[0]["state"] == "COMPLETED"
