"""Azure Blob storage backend against a local fake Blob service.

The fake implements the REST subset the stdlib client uses (Put/Get/Delete
Blob, Put Block / Put Block List, List Blobs with prefix) and recomputes the
SharedKey signature for EVERY request with the account key — the signing
path (including the PUT-only Content-Length/Content-Type slots) is exercised
end-to-end, not just the happy bytes."""

import base64
import hashlib
import hmac
import http.server
import os
import threading
import urllib.parse

import pytest

from determined_tpu.storage.azure import AzureBlobClient, parse_connection_string
from determined_tpu.storage.cloud import AzureStorageManager

ACCOUNT = "testacct"
KEY = base64.b64encode(b"0123456789abcdef0123456789abcdef").decode()


class FakeBlobService(http.server.BaseHTTPRequestHandler):
    store = {}  # (container, name) -> bytes
    blocks = {}  # (container, name, block_id) -> bytes
    auth_failures = []

    def log_message(self, *a):
        pass

    def _check_auth(self, content_length: int):
        auth = self.headers.get("Authorization", "")
        parsed = urllib.parse.urlparse(self.path)
        query = dict(urllib.parse.parse_qsl(parsed.query))
        canon_res = f"/{ACCOUNT}{parsed.path}"
        for k in sorted(query):
            canon_res += f"\n{k.lower()}:{query[k]}"
        ms = sorted(
            (k.lower(), v.strip())
            for k, v in self.headers.items()
            if k.lower().startswith("x-ms-")
        )
        canon_headers = "".join(f"{k}:{v}\n" for k, v in ms)
        sts = "\n".join(
            [
                self.command,
                self.headers.get("Content-Encoding", ""),
                self.headers.get("Content-Language", ""),
                str(content_length) if content_length else "",
                self.headers.get("Content-MD5", ""),
                self.headers.get("Content-Type", ""),
                "",
                self.headers.get("If-Modified-Since", ""),
                self.headers.get("If-Match", ""),
                self.headers.get("If-None-Match", ""),
                self.headers.get("If-Unmodified-Since", ""),
                self.headers.get("Range", ""),
            ]
        ) + "\n" + canon_headers + canon_res
        want = base64.b64encode(
            hmac.new(base64.b64decode(KEY), sts.encode(), hashlib.sha256).digest()
        ).decode()
        if auth != f"SharedKey {ACCOUNT}:{want}":
            FakeBlobService.auth_failures.append(
                f"bad-sig {self.command} {self.path}"
            )

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        self._check_auth(length)
        body = self.rfile.read(length)
        container, name = self._parse()
        query = dict(urllib.parse.parse_qsl(urllib.parse.urlparse(self.path).query))
        if query.get("comp") == "block":
            FakeBlobService.blocks[(container, name, query["blockid"])] = body
        elif query.get("comp") == "blocklist":
            # Assemble committed blocks in list order.
            import xml.etree.ElementTree as ET

            ids = [el.text for el in ET.fromstring(body).iter("Latest")]
            data = b"".join(
                FakeBlobService.blocks.pop((container, name, i)) for i in ids
            )
            FakeBlobService.store[(container, name)] = data
        else:
            FakeBlobService.store[(container, name)] = body
        self.send_response(201)
        self.end_headers()

    def do_GET(self):
        self._check_auth(0)
        parsed = urllib.parse.urlparse(self.path)
        query = dict(urllib.parse.parse_qsl(parsed.query))
        if query.get("comp") == "list":
            container = parsed.path.strip("/")
            prefix = query.get("prefix", "")
            blobs = "".join(
                f"<Blob><Name>{n}</Name><Properties><Content-Length>{len(b)}"
                "</Content-Length></Properties></Blob>"
                for (c, n), b in sorted(FakeBlobService.store.items())
                if c == container and n.startswith(prefix)
            )
            body = (
                "<?xml version='1.0'?><EnumerationResults>"
                f"<Blobs>{blobs}</Blobs><NextMarker/></EnumerationResults>"
            ).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        container, name = self._parse()
        data = FakeBlobService.store.get((container, name))
        if data is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_DELETE(self):
        self._check_auth(0)
        container, name = self._parse()
        FakeBlobService.store.pop((container, name), None)
        self.send_response(202)
        self.end_headers()

    def _parse(self):
        path = urllib.parse.urlparse(self.path).path
        container, _, name = path.strip("/").partition("/")
        return container, urllib.parse.unquote(name)


@pytest.fixture()
def blob_server():
    FakeBlobService.store = {}
    FakeBlobService.blocks = {}
    FakeBlobService.auth_failures = []
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), FakeBlobService)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


def conn_str(endpoint):
    return f"AccountName={ACCOUNT};AccountKey={KEY};BlobEndpoint={endpoint}"


class TestConnectionString:
    def test_parse(self):
        parts = parse_connection_string(
            "DefaultEndpointsProtocol=https;AccountName=a;AccountKey=az==;"
            "EndpointSuffix=core.windows.net"
        )
        assert parts["AccountKey"] == "az=="  # keeps '=' padding

    def test_default_endpoint(self):
        c = AzureBlobClient(
            f"DefaultEndpointsProtocol=https;AccountName=x;AccountKey={KEY}"
        )
        assert c.endpoint == "https://x.blob.core.windows.net"

    def test_missing_raises(self):
        os.environ.pop("AZURE_STORAGE_CONNECTION_STRING", None)
        with pytest.raises(ValueError, match="connection_string"):
            AzureBlobClient("")


class TestAzureManager:
    def test_roundtrip(self, blob_server, tmp_path):
        mgr = AzureStorageManager("ckpts", conn_str(blob_server), prefix="exp1")
        src = tmp_path / "src"
        (src / "sub").mkdir(parents=True)
        (src / "model.bin").write_bytes(b"weights" * 100)
        (src / "sub" / "meta.json").write_text("{}")

        mgr.upload(str(src), "ck-1")
        files = mgr.list_files("ck-1")
        assert files == {"model.bin": 700, "sub/meta.json": 2}

        dst = tmp_path / "dst"
        mgr.download("ck-1", str(dst))
        assert (dst / "model.bin").read_bytes() == b"weights" * 100
        assert (dst / "sub" / "meta.json").read_text() == "{}"
        assert FakeBlobService.auth_failures == []

    def test_block_upload_large_file(self, blob_server, tmp_path, monkeypatch):
        """Files over BLOCK_SIZE go through Put Block / Put Block List."""
        monkeypatch.setattr(AzureBlobClient, "BLOCK_SIZE", 1024)
        mgr = AzureStorageManager("ckpts", conn_str(blob_server))
        src = tmp_path / "src"
        src.mkdir()
        payload = bytes(range(256)) * 20  # 5120 bytes = 5 blocks
        (src / "shard.bin").write_bytes(payload)
        mgr.upload(str(src), "ck-big")
        dst = tmp_path / "dst"
        mgr.download("ck-big", str(dst))
        assert (dst / "shard.bin").read_bytes() == payload
        assert FakeBlobService.auth_failures == []
        assert FakeBlobService.blocks == {}  # all blocks committed

    def test_names_needing_percent_encoding(self, blob_server, tmp_path):
        """Signature must be over the encoded path (Azure canonicalizes the
        encoded request URL); a space in a filename exercises it."""
        mgr = AzureStorageManager("ckpts", conn_str(blob_server))
        src = tmp_path / "src"
        src.mkdir()
        (src / "my model.bin").write_bytes(b"mm")
        mgr.upload(str(src), "ck-sp")
        dst = tmp_path / "dst"
        mgr.download("ck-sp", str(dst))
        assert (dst / "my model.bin").read_bytes() == b"mm"
        assert FakeBlobService.auth_failures == []

    def test_selector_download(self, blob_server, tmp_path):
        mgr = AzureStorageManager("ckpts", conn_str(blob_server))
        src = tmp_path / "src"
        src.mkdir()
        (src / "a.txt").write_text("a")
        (src / "b.txt").write_text("b")
        mgr.upload(str(src), "ck-2")
        dst = tmp_path / "dst"
        mgr.download("ck-2", str(dst), selector=lambda rel: rel == "a.txt")
        assert os.listdir(dst) == ["a.txt"]

    def test_delete(self, blob_server, tmp_path):
        mgr = AzureStorageManager("ckpts", conn_str(blob_server))
        src = tmp_path / "src"
        src.mkdir()
        (src / "a.txt").write_text("a")
        mgr.upload(str(src), "ck-3")
        assert mgr.delete("ck-3") == {}
        assert mgr.list_files("ck-3") == {}

    def test_store_path_uploads_on_exit(self, blob_server, tmp_path):
        """store_path stages locally and pushes to the bucket on exit —
        the path file checkpoints (keras/pytorch trials) take."""
        mgr = AzureStorageManager("ckpts", conn_str(blob_server))
        with mgr.store_path() as (sid, path):
            with open(os.path.join(path, "model.keras"), "wb") as f:
                f.write(b"K" * 64)
        assert mgr.list_files(sid) == {"model.keras": 64}
        # staging is cleaned up after the upload
        assert not os.path.exists(mgr.path_for(sid))
        # restore_path re-downloads from the bucket and cleans up after
        with mgr.restore_path(sid) as rpath:
            assert open(os.path.join(rpath, "model.keras"), "rb").read() == b"K" * 64
        assert not os.path.exists(mgr.path_for(sid))
        # a bogus id raises like the base class
        with pytest.raises(FileNotFoundError):
            with mgr.restore_path("no-such-checkpoint"):
                pass

    def test_checkpoint_context_array_roundtrip(self, blob_server, tmp_path):
        """CheckpointContext.save_state/restore_state over azure: the orbax
        save is staged locally then uploaded (no az:// tensorstore driver)."""
        import numpy as np

        from determined_tpu.core._checkpoint import CheckpointContext

        mgr = AzureStorageManager("ckpts", conn_str(blob_server))
        ctx = CheckpointContext(None, mgr, trial_id=9, async_save=False)
        state = {"w": np.arange(8.0), "step": np.asarray(3)}
        sid = ctx.save_state(state, steps_completed=3)
        # The bucket (not just staging) must hold the orbax files, and the
        # local staging copy is gone after the upload.
        assert any(k.startswith("state/") for k in mgr.list_files(sid))
        assert not os.path.exists(mgr.path_for(sid))
        restored = ctx.restore_state(sid, state)
        np.testing.assert_array_equal(restored["w"], state["w"])
        assert int(restored["step"]) == 3
        assert FakeBlobService.auth_failures == []

    def test_from_config(self, blob_server):
        from determined_tpu.storage import from_config

        mgr = from_config(
            {
                "type": "azure",
                "container": "ckpts",
                "connection_string": conn_str(blob_server),
            }
        )
        assert isinstance(mgr, AzureStorageManager)
        assert mgr.url_for("x") is None  # no tensorstore scheme → staged copies
