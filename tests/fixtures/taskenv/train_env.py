"""Fixture trial asserting the task environment was applied before exec:
config env vars (flat + list form), python_path package roots, and venv
interpreter activation (reference task-spec rendering,
master/pkg/tasks/task.go:194-234)."""

import os
import shutil
import sys


def main() -> int:
    # Env vars from the config's environment block — flat form rendered by
    # the master, list form by master + launch layer.
    assert os.environ.get("MY_TASK_FLAG") == "from-config", os.environ.get(
        "MY_TASK_FLAG"
    )
    assert os.environ.get("MY_TASK_FLAG2") == "listed", os.environ.get(
        "MY_TASK_FLAG2"
    )

    # Extra package root from environment.python_path.
    import extra_pkg

    assert extra_pkg.VALUE == 42

    # venv activation: VIRTUAL_ENV exported and its bin/ first on PATH, so
    # `python3` resolves inside the venv.
    venv = os.environ.get("VIRTUAL_ENV", "")
    assert venv.endswith("fake-venv"), venv
    resolved = shutil.which("python3") or ""
    assert resolved.startswith(venv), f"python3 -> {resolved}, venv {venv}"

    from determined_tpu import core

    with core.init(async_checkpointing=False) as ctx:
        for op in ctx.searcher.operations():
            ctx.train.report_training_metrics(op.length, {"loss": 0.5})
            ctx.train.report_validation_metrics(op.length, {"val_loss": 0.1})
            op.report_completed(0.1)
    print("task environment verified")
    return 0


if __name__ == "__main__":
    sys.exit(main())
