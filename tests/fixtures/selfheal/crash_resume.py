"""Subprocess half of the SIGKILL-mid-async-save chaos tests.

Modes (argv[1]; argv[2] = checkpoint dir):

  seed           train 2 steps; checkpoint trial0-step2 commits COMPLETED.
  truncate-kill  resume from trial0-step2, train to 4; the step-4
                 checkpoint's commit is chaos-truncated (torn shard AFTER
                 its checksum was recorded, COMMIT still written), then the
                 process SIGKILLs itself — a checkpoint the registry calls
                 COMPLETED but only checksum verification can catch.
  commit-crash   same resume, but the process dies (exit 137) INSIDE the
                 phase-2 commit of the step-4 checkpoint: shards durable,
                 no COMMIT marker — the classic killed-mid-async-save
                 torso.

The parent test then resumes from trial0-step4 and asserts the restore
falls back to trial0-step2 with bit-identical state.
"""

import os
import signal
import sys


def main() -> int:
    mode, ckpt_dir = sys.argv[1], sys.argv[2]
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from determined_tpu import core
    from determined_tpu.common import faultpoint
    from determined_tpu.train import Trainer
    from determined_tpu.train.trial import TrialContext
    from trial_def import LinearTrial

    if mode == "seed":
        ctx = core.init(max_length=2, checkpoint_dir=ckpt_dir,
                        async_checkpointing=True)
        Trainer(LinearTrial(TrialContext()), core_context=ctx).fit(
            report_period=1)
        ctx.close()
        return 0

    if mode == "truncate-kill":
        faultpoint.arm("checkpoint.write.truncate", "error", count=1)
    elif mode == "commit-crash":
        faultpoint.arm("checkpoint.commit.drop", "crash", count=1)
    else:
        raise SystemExit(f"unknown mode {mode!r}")

    ctx = core.init(max_length=4, checkpoint_dir=ckpt_dir,
                    async_checkpointing=True)
    Trainer(LinearTrial(TrialContext()), core_context=ctx).fit(
        report_period=1, resume_from="trial0-step2")
    # commit-crash never reaches here: the crash fires inside the phase-2
    # commit during fit's final wait(). truncate-kill falls through — the
    # corrupt checkpoint has COMMITted — and dies the hard way.
    os.kill(os.getpid(), signal.SIGKILL)
    return 1  # unreachable


if __name__ == "__main__":
    sys.exit(main())
