"""Tiny deterministic linear trial shared by the self-healing chaos tests.

Imported both by tests/test_selfheal.py (in-process) and by
crash_resume.py (the subprocess that gets SIGKILLed mid-save): the two
sides must build bit-identical TrainState structures so restored states
can be compared leaf-for-leaf.
"""

import numpy as np
import optax

from determined_tpu.parallel.mesh import MeshConfig
from determined_tpu.train import JaxTrial


class LinearTrial(JaxTrial):
    prefetch = False  # deterministic batch consumption for the chaos tests

    def init_params(self, rng):
        import jax

        return {"w": jax.random.normal(rng, (4,)) * 0.1}

    def param_logical_axes(self):
        # Replicated under the mesh — but THROUGH the mesh machinery, so
        # the restore template carries a mesh sharding and a checkpoint
        # written on one device layout restores onto another (the tests
        # run both 1- and 8-device CPU slices over the same directory).
        return {"w": (None,)}

    def loss(self, params, batch, rng):
        import jax.numpy as jnp

        return jnp.mean((params["w"] - batch["x"]) ** 2)

    def optimizer(self):
        return optax.sgd(0.1)

    def mesh_config(self):
        return MeshConfig()

    def build_training_data(self):
        rng = np.random.default_rng(7)
        for _ in range(64):
            yield {"x": rng.normal(size=(8, 4)).astype(np.float32)}
