"""Worker script for the torch-distributed launch-layer e2e test.

Launched as `python -m determined_tpu.launch.torch_distributed
--nproc-per-node 2 -- python train_ddp.py <outdir>`: each worker trains a
DDP-wrapped linear model through the PyTorchTrial Trainer, then proves the
distributed plumbing worked:
  - gradients synced: model weights identical across ranks after training
  - data sharded: each rank consumed a distinct DistributedSampler shard
  - chief-only reporting: only rank 0 reported checkpoints/metrics
"""

import json
import os
import sys

import torch

from determined_tpu import core
from determined_tpu.pytorch import (
    DataLoader,
    PyTorchTrial,
    PyTorchTrialContext,
    Trainer,
)


class RegressionSet(torch.utils.data.Dataset):
    def __init__(self, n=256):
        g = torch.Generator().manual_seed(0)
        self.x = torch.randn(n, 4, generator=g)
        self.y = self.x @ torch.tensor([1.0, -2.0, 3.0, 0.5]).unsqueeze(1)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class LinearTrial(PyTorchTrial):
    def __init__(self, context):
        super().__init__(context)
        self.model = context.wrap_model(torch.nn.Linear(4, 1))
        self.opt = context.wrap_optimizer(
            torch.optim.SGD(self.model.parameters(), lr=0.1)
        )
        self.loss_fn = torch.nn.MSELoss()
        self.seen = 0

    def build_training_data_loader(self):
        return DataLoader(RegressionSet(), batch_size=16)

    def build_validation_data_loader(self):
        return DataLoader(RegressionSet(64), batch_size=16)

    def train_batch(self, batch, epoch_idx, batch_idx):
        x, y = batch
        self.seen += len(x)
        loss = self.loss_fn(self.model(x), y)
        self.context.backward(loss)
        self.context.step_optimizer(self.opt)
        return {"loss": loss.item()}

    def evaluate_batch(self, batch, batch_idx):
        x, y = batch
        return {"val_loss": self.loss_fn(self.model(x), y).item()}


def main() -> int:
    outdir = sys.argv[1]
    ctx = PyTorchTrialContext(hparams={})
    assert ctx.dist is not None and ctx.dist.size == 2, ctx.dist
    core_ctx = core.init(
        max_length=8,
        distributed=ctx.dist,
        checkpoint_dir=os.path.join(outdir, "ckpts"),
        async_checkpointing=False,
    )
    ctx._core = core_ctx
    trial = LinearTrial(ctx)
    assert isinstance(
        trial.model, torch.nn.parallel.DistributedDataParallel
    ), "wrap_model must DDP-wrap when launched distributed"
    trainer = Trainer(trial, core_context=core_ctx)
    steps = trainer.fit(report_period=4)

    # weights must be identical across ranks (DDP allreduce) — compare via
    # the object control plane.
    w = trial.model.module.weight.detach().numpy().tolist()
    gathered = ctx.dist.allgather(w)
    assert gathered[0] == gathered[1], f"weights diverged: {gathered}"

    # every rank saw its own half of the data: 8 steps * 16 batch = 128
    # samples = half of the 256-sample epoch + start of the next shard pass
    assert trial.seen == 8 * 16, trial.seen

    rank = ctx.dist.rank
    report = {
        "rank": rank,
        "steps": steps,
        "n_checkpoints": len(core_ctx.checkpoint.local_reported),
        "n_train_metrics": len(core_ctx.train.local_training_metrics),
        "val": core_ctx.train.local_validation_metrics[-1]["metrics"]
        if core_ctx.train.local_validation_metrics
        else None,
    }
    with open(os.path.join(outdir, f"rank{rank}.json"), "w") as f:
        json.dump(report, f)
    print(f"rank {rank} done: {report}")
    core_ctx.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
