"""Worker script for the ZeRO-1 engine 2-process e2e test.

Launched as `python -m determined_tpu.launch.torch_distributed
--nproc-per-node 2 -- python train_zero1.py <outdir>`: each worker trains a
GPT-NeoX-tiny through the DeepSpeedTrial surface with the real ZeroOneEngine,
then proves the ZeRO-1 semantics held:
  - optimizer state is PARTITIONED: each rank holds a proper subset and
    the union covers AdamW's 2×numel state exactly;
  - parameters stay identical across ranks (owner-rebroadcast worked);
  - engine-sharded save/load round-trips this rank's shard.
"""

import json
import os
import sys

import torch

from determined_tpu import core
from determined_tpu.pytorch import DeepSpeedTrainer, DeepSpeedTrialContext

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))
from examples.gpt_neox.model_def import NeoXZeroTrial  # noqa: E402


def main() -> int:
    outdir = sys.argv[1]
    hp = {"model_size": "tiny", "seq_len": 32, "micro_batch_size": 4,
          "gradient_accumulation": 2, "learning_rate": 1e-3}
    ctx = DeepSpeedTrialContext(hparams=hp)
    assert ctx.dist is not None and ctx.dist.size == 2, ctx.dist
    core_ctx = core.init(
        max_length=4,
        distributed=ctx.dist,
        checkpoint_dir=os.path.join(outdir, "ckpts"),
        async_checkpointing=False,
    )
    ctx._core = core_ctx
    trial = NeoXZeroTrial(ctx)
    engine = trial.engine

    steps = DeepSpeedTrainer(trial, core_context=core_ctx).fit(
        searcher_metric="val_loss", report_period=2)

    # ZeRO-1 partitioning: AdamW keeps exp_avg + exp_avg_sq (+ a scalar
    # `step` tensor) per owned param; the union across ranks must cover
    # every trainable param exactly once, each rank a proper subset.
    trainable = [p for p in engine.module.parameters() if p.requires_grad]
    total_numel = sum(p.numel() for p in trainable)
    mine = engine.optimizer_state_numel()
    both = ctx.dist.allgather(mine)
    assert sum(both) == 2 * total_numel + len(trainable), (both, total_numel)
    assert all(0 < n < 2 * total_numel for n in both), both

    # Owner-rebroadcast: parameters identical across ranks.
    flat = torch.cat([p.detach().reshape(-1)
                      for p in engine.module.parameters()])
    digest = float(flat.sum()), float(flat.abs().sum())
    gathered = ctx.dist.allgather(digest)
    assert gathered[0] == gathered[1], f"params diverged: {gathered}"

    # Engine-sharded save/load round-trip (both ranks write + read their
    # own shard; rank 0 writes the model).
    save_dir = os.path.join(outdir, "engine_ckpt")
    os.makedirs(save_dir, exist_ok=True)
    engine.save_checkpoint(save_dir, tag="t")
    ctx.dist.allgather(0)  # barrier: rank0's model file must exist
    engine.load_checkpoint(save_dir, tag="t")
    flat2 = torch.cat([p.detach().reshape(-1)
                       for p in engine.module.parameters()])
    assert torch.equal(flat, flat2)

    rank = ctx.dist.rank
    report = {
        "rank": rank,
        "steps": steps,
        "opt_state_numel": mine,
        "n_checkpoints": len(core_ctx.checkpoint.local_reported),
        "n_train_metrics": len(core_ctx.train.local_training_metrics),
    }
    with open(os.path.join(outdir, f"zero_rank{rank}.json"), "w") as f:
        json.dump(report, f)
    print(f"rank {rank} done: {report}")
    core_ctx.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
