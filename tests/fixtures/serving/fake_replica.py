"""Featherweight serving replica for deployment-controller tests.

Speaks just enough of the real replica's protocol (serve/task.py +
serve/http.py) to exercise the master's deployment subsystem — proxy
registration, serve_stats heartbeats, the preemption-drain handshake —
without building a model or compiling anything, so router/reconciler/
autoscaler tests run in tier-1 time.

Endpoints:
  POST /v1/generate     sleeps DET_FAKE_GEN_MS (or body.delay_ms), then
                        {"id", "tokens": [...], "replica": <task id>} —
                        the replica field lets tests assert dispatch.
                        Honors X-Request-Id and emits the REAL request
                        span tree (serve/tracing.py RequestTracer) +
                        latency histograms (serve/scheduler.py
                        LatencyHist), so router/observability tests
                        exercise the production span + heartbeat protocol
                        without building a model.
  GET  /v1/stats        the heartbeat payload as currently reported
  POST /force_stats     override the reported stats (least-loaded /
                        all-full scenarios); {} clears the override
  POST /die             os._exit(1) mid-service (connection-refused +
                        respawn path)
  GET  /healthz         {"status": "ok"|"draining"}
"""

import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from determined_tpu.common.api import Session  # noqa: E402
from determined_tpu.core._preempt import PreemptContext  # noqa: E402
from determined_tpu.exec._util import report_proxy_address  # noqa: E402
from determined_tpu.serve.scheduler import (  # noqa: E402
    LatencyHist,
    Request,
    now_us,
)
from determined_tpu.serve.tracing import RequestTracer  # noqa: E402

TASK_ID = os.environ.get("DET_TASK_ID", "fake")
ALLOCATION_ID = os.environ.get("DET_ALLOCATION_ID", "")
# Model lifecycle (docs/serving.md "Model lifecycle"): the version label
# the deployment controller pinned at spawn — echoed on the heartbeat
# (the real replica does the same) and on generate responses so swap/
# canary tests and the lifecycle bench can attribute every request to
# the version that served it.
MODEL_VERSION = os.environ.get("DET_MODEL_VERSION", "")
GEN_MS = float(os.environ.get("DET_FAKE_GEN_MS", "30"))
HEARTBEAT_S = float(os.environ.get("DET_FAKE_HEARTBEAT_S", "0.5"))
# Per-replica service capacity: at most SLOTS generates run concurrently,
# like the real batcher's slot count. Requests beyond it queue on the
# semaphore — the capacity bound that makes replica-scaling benchmarks
# honest (each replica models an engine that owns its own accelerator).
SLOTS = int(os.environ.get("DET_FAKE_SLOTS", "4"))

_slots_sem = threading.Semaphore(SLOTS)
_lock = threading.Lock()
_state = {
    "inflight": 0,   # holding a slot
    "waiting": 0,    # queued on the semaphore
    "completed": 0,
    "draining": False,
    "override": None,  # forced stats dict, or None
}

# The REAL latency histograms + span tracer (serve/scheduler.py /
# serve/tracing.py): the fake only fakes the model, never the
# observability protocol.
_hists = {
    "ttft": LatencyHist(),
    "tpot": LatencyHist(),
    "e2e": LatencyHist(),
    "queue_wait": LatencyHist(),
}


def heartbeat_stats():
    with _lock:
        latency = {k: h.to_wire() for k, h in _hists.items()}
        if _state["override"] is not None:
            stats = dict(_state["override"])
            stats.setdefault("draining", _state["draining"])
            stats.setdefault("latency", latency)
            if MODEL_VERSION:
                stats.setdefault("model_version", MODEL_VERSION)
            return stats
        return {
            "queue_depth": _state["waiting"],
            "queue_capacity": 4 * SLOTS,
            "active": _state["inflight"],
            "slots": SLOTS,
            "kv_blocks_free": 64,
            "kv_blocks_total": 64,
            "draining": _state["draining"],
            "retry_after_hint_s": 1,
            # The real replica reports how its engine got executables
            # (warm-AOT "deserialize" vs cold "trace"); the fake defaults
            # to the warm path so cold-start tests see the real contract.
            "engine_source": os.environ.get("DET_FAKE_ENGINE_SOURCE",
                                            "deserialize"),
            "model_version": MODEL_VERSION,
            "latency": latency,
        }


class Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def _send(self, status, body):
        data = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802
        if self.path == "/healthz":
            self._send(200, {"status": "draining" if _state["draining"]
                             else "ok"})
        elif self.path == "/v1/stats":
            self._send(200, heartbeat_stats())
        else:
            self._send(404, {"error": "not found"})

    def do_POST(self):  # noqa: N802
        length = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(length) or b"{}")
        if self.path == "/v1/generate":
            if _state["draining"]:
                self._send(503, {"error": "draining"})
                return
            rid = (self.headers.get("X-Request-Id") or "").strip() or None
            req = Request(
                np.asarray(body.get("tokens") or [1, 2, 3], np.int32),
                max_new_tokens=max(1, int(body.get("max_new_tokens", 4))),
                request_id=rid)
            with _lock:
                _state["waiting"] += 1
            _slots_sem.acquire()
            with _lock:
                _state["waiting"] -= 1
                _state["inflight"] += 1
            # Phase stamps mirror the real batcher's: admit = slot grant,
            # "prefill" = a fixed slice of the service sleep, decode = the
            # rest — so the spans and histograms carry honest shapes.
            req.admitted_us = req.prefill_start_us = now_us()
            req.occupancy_at_admit = _state["inflight"]
            req.bucket = 8
            req.blocks_allocated = 2
            try:
                delay_s = float(body.get("delay_ms", GEN_MS)) / 1e3
                time.sleep(delay_s * 0.25)
                req.prefill_end_us = req.first_token_us = now_us()
                time.sleep(delay_s * 0.75)
                n = int(body.get("max_new_tokens", 4))
                req.out_tokens = list(range(n))
                req.decode_steps = max(0, n - 1)
                req._finish(notify=False)
                with _lock:
                    _hists["e2e"].observe(
                        (req.finished_us - req.submitted_us) / 1e6)
                    _hists["queue_wait"].observe(
                        (req.admitted_us - req.submitted_us) / 1e6)
                    _hists["ttft"].observe(
                        (req.first_token_us - req.submitted_us) / 1e6)
                    if len(req.out_tokens) > 1:
                        _hists["tpot"].observe(
                            (req.finished_us - req.first_token_us) / 1e6
                            / (len(req.out_tokens) - 1))
                if _tracer is not None:
                    # Record + flush BEFORE the response leaves: by the
                    # time the caller can ask for the trace, it exists.
                    _tracer.record(req)
                    _tracer.flush()
                self._send(200, {"id": req.id,
                                 "tokens": list(req.out_tokens),
                                 "replica": TASK_ID,
                                 "model_version": MODEL_VERSION})
            finally:
                _slots_sem.release()
                with _lock:
                    _state["inflight"] -= 1
                    _state["completed"] += 1
        elif self.path == "/force_stats":
            with _lock:
                _state["override"] = body or None
            beat()
            self._send(200, {"ok": True})
        elif self.path == "/die":
            self._send(200, {"bye": True})
            self.wfile.flush()
            os._exit(1)
        else:
            self._send(404, {"error": "not found"})


def make_session():
    master = os.environ.get("DET_MASTER")
    if not master or not ALLOCATION_ID:
        return None
    return Session(master, os.environ.get("DET_SESSION_TOKEN"))


_session = make_session()
_tracer = None
if _session is not None:
    _tracer = RequestTracer(
        _session, ALLOCATION_ID,
        sample=float(os.environ.get("DET_FAKE_TRACE_SAMPLE", "1.0")),
        slo_ms=float(os.environ.get("DET_FAKE_SLO_MS", "0") or 0) or None)


def beat():
    if _session is None:
        return
    try:
        _session.post(f"/api/v1/allocations/{ALLOCATION_ID}/serve_stats",
                      body=heartbeat_stats())
    except Exception:
        pass


def main():
    import socket

    # DET_FAKE_STARTING_S models a replica whose proxy address is known
    # before the engine is actually up (the real engine compiles/restores
    # after the port is chosen): the address is reported, then the socket
    # stays CLOSED for the window — connections are refused, exactly the
    # STARTING shape the router's breaker guard must not count.
    starting_s = float(os.environ.get("DET_FAKE_STARTING_S", "0") or 0)
    if starting_s > 0:
        probe = socket.socket()
        probe.bind(("0.0.0.0", 0))
        port = probe.getsockname()[1]
        probe.close()
        addr = f"http://{socket.gethostname()}:{port}"
        report_proxy_address(addr)
        print(f"fake replica {TASK_ID} STARTING at {addr} "
              f"({starting_s}s)", flush=True)
        time.sleep(starting_s)
        httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
    else:
        httpd = ThreadingHTTPServer(("0.0.0.0", 0), Handler)
        addr = f"http://{socket.gethostname()}:{httpd.server_address[1]}"
        report_proxy_address(addr)
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    print(f"fake replica {TASK_ID} at {addr}", flush=True)

    preempt = PreemptContext(_session, ALLOCATION_ID or None)
    try:
        while True:
            if preempt.should_preempt():
                break
            beat()
            time.sleep(HEARTBEAT_S)
        # Drain handshake: report draining NOW, finish in-flight, exit 0.
        with _lock:
            _state["draining"] = True
        beat()
        deadline = time.time() + 30
        while time.time() < deadline:
            with _lock:
                if _state["inflight"] == 0 and _state["waiting"] == 0:
                    break
            time.sleep(0.05)
        print("fake replica drained; exiting 0", flush=True)
        return 0
    finally:
        preempt.close()
        httpd.shutdown()


if __name__ == "__main__":
    sys.exit(main())
