"""Compile-farm e2e/bench fixture (docs/compile-farm.md): a compile-heavy
GPT-2 JaxTrial under the Trainer.

The trial class is module-level on purpose — the farm worker discovers and
instantiates it exactly like `det preflight` does, so the background AOT
compile runs the same program the trial will. `inject_hyperparams` keeps
the learning rate out of the compiled program (optimizer STATE, not a
baked constant), which is what lets an lr sweep share one executable
across signatures via the worker's fingerprint link.
"""

import os
import sys

import numpy as np
import optax

from determined_tpu.models import gpt2
from determined_tpu.train.trial import JaxTrial, TrialContext

VOCAB = 512
SEQ = 128


class FarmTrial(JaxTrial):
    prefetch = False  # keep the compile measurement free of pipeline noise

    def _cfg(self):
        return gpt2.Config(
            vocab_size=VOCAB,
            n_positions=SEQ,
            d_model=int(os.environ.get("FARM_D_MODEL", "512")),
            n_layer=int(os.environ.get("FARM_N_LAYER", "6")),
            n_head=8,
            remat=False,
        )

    def init_params(self, rng):
        return gpt2.init(rng, self._cfg())

    def loss(self, params, batch, rng):
        return gpt2.loss_fn(params, batch, self._cfg())

    def optimizer(self):
        return optax.inject_hyperparams(optax.adamw)(
            learning_rate=float(self.context.hparams.get("lr", 1e-3)))

    def build_training_data(self):
        rng = np.random.default_rng(0)
        bs = int(self.context.hparams.get("global_batch_size", 8))
        while True:
            yield {"tokens": rng.integers(
                0, VOCAB, size=(bs, SEQ + 1)).astype(np.int32)}


def main() -> int:
    from determined_tpu import core
    from determined_tpu.train import Trainer

    with core.init(async_checkpointing=False) as ctx:
        trial = FarmTrial(TrialContext(hparams=ctx.hparams,
                                       core_context=ctx))
        trainer = Trainer(trial, core_context=ctx)
        trainer.fit(report_period=2)
    print("farm fixture: trial complete", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
