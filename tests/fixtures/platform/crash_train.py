"""Fixture for log-pattern policy e2e: prints a recognizable fatal line,
then exits non-zero — the master's policies decide whether it retries and
where."""

import os
import sys
import time

from determined_tpu import core


def main() -> int:
    with core.init(async_checkpointing=False) as ctx:
        ctx.train.report_training_metrics(1, {"loss": 1.0})
        print(f"run on agent {os.environ.get('DET_AGENT_ID')}")
        sys.stdout.flush()
        print("UNRECOVERABLE_CONDITION: device melted")
        sys.stdout.flush()
        time.sleep(1.0)  # let the log batch ship before dying
    return 17


if __name__ == "__main__":
    sys.exit(main())
