"""Minimal Core-API trial used by the platform e2e tests.

Mirrors the shape of the reference's e2e fixture trials
(e2e_tests/tests/fixtures/): trains `op.length` synthetic steps, reports
metrics, honors preemption by checkpointing and exiting cleanly, and resumes
from the latest checkpoint.
"""

import json
import os
import sys
import time

from determined_tpu import core


def main() -> int:
    with core.init(async_checkpointing=False) as ctx:
        hp = ctx.hparams
        steps = 0
        # Resume (reference: info.latest_checkpoint → restore path).
        if ctx.latest_checkpoint:
            with ctx.checkpoint.restore_path(ctx.latest_checkpoint) as path:
                with open(os.path.join(path, "state.json")) as f:
                    steps = json.load(f)["steps"]
            print(f"resumed from checkpoint at step {steps}")

        step_sleep = float(os.environ.get("TRIAL_STEP_SLEEP", "0.01"))
        for op in ctx.searcher.operations():
            while steps < op.length:
                steps += 1
                time.sleep(step_sleep)
                if steps % 4 == 0 or steps == op.length:
                    ctx.train.report_training_metrics(steps, {"loss": 1.0 / steps})
                if ctx.preempt.should_preempt():
                    with ctx.checkpoint.store_path({"steps_completed": steps}) as (
                        path,
                        _sid,
                    ):
                        with open(os.path.join(path, "state.json"), "w") as f:
                            json.dump({"steps": steps}, f)
                    print(f"preempted at step {steps}")
                    return 0
            metric = float(hp.get("lr", 0.1)) / (1.0 + steps)
            ctx.train.report_validation_metrics(steps, {"val_loss": metric})
            op.report_completed(metric)
            # Checkpoint at each rung boundary so an idle-exited (paused)
            # trial resumes exactly here if promoted later.
            with ctx.checkpoint.store_path({"steps_completed": steps}) as (
                path,
                _sid,
            ):
                with open(os.path.join(path, "state.json"), "w") as f:
                    json.dump({"steps": steps}, f)

        with ctx.checkpoint.store_path({"steps_completed": steps}) as (path, _sid):
            with open(os.path.join(path, "state.json"), "w") as f:
                json.dump({"steps": steps}, f)
        print(f"trial complete at step {steps}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
