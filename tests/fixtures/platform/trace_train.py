"""Observability e2e fixture (docs/observability.md): a real JaxTrial
under the Trainer so the full harness span set lands on the trial's
lifecycle trace — harness.compile (the jitted step), periodic
harness.checkpoint.save / harness.checkpoint.commit, harness.restore on a
resumed run, and harness.checkpoint.emergency when a drain notice arrives
mid-run. Slow enough (per-batch sleep) that a notice can land mid-run.
"""

import logging
import os
import sys
import time

import numpy as np
import optax


def main() -> int:
    logging.basicConfig(
        level=logging.INFO, stream=sys.stderr,
        format="%(name)s: %(message)s")

    from determined_tpu import core
    from determined_tpu.parallel.mesh import MeshConfig
    from determined_tpu.train import JaxTrial, Trainer
    from determined_tpu.train.trial import TrialContext

    step_sleep = float(os.environ.get("TRACE_STEP_SLEEP", "0.02"))

    class TraceTrial(JaxTrial):
        prefetch = False

        def init_params(self, rng):
            import jax

            return {"w": jax.random.normal(rng, (4,)) * 0.1}

        def param_logical_axes(self):
            return {"w": (None,)}

        def loss(self, params, batch, rng):
            import jax.numpy as jnp

            return jnp.mean((params["w"] - batch["x"]) ** 2)

        def optimizer(self):
            return optax.sgd(0.1)

        def mesh_config(self):
            return MeshConfig()

        def build_training_data(self):
            rng = np.random.default_rng(7)
            while True:
                time.sleep(step_sleep)
                yield {"x": rng.normal(size=(8, 4)).astype(np.float32)}

    with core.init(async_checkpointing=False) as ctx:
        trainer = Trainer(TraceTrial(TrialContext()), core_context=ctx)
        trainer.fit(report_period=2, checkpoint_period=4)
    print("trace fixture: trial complete", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
