"""Fixture trial for the checkpoint-GC e2e: checkpoints + validates every
2 steps with a non-monotonic metric (best at mid-training), so the
retention policy has distinct best/latest/doomed checkpoints to act on.

DET_GC_HOLD_FILE (optional): after training, wait (<=60s) until the named
file exists before exiting — the GC-exclusion tests use the window to
register a model version / pin a deployment against checkpoints that are
already COMPLETED, BEFORE experiment completion launches the GC task."""

import json
import os
import sys
import time

from determined_tpu import core


def main() -> int:
    with core.init(async_checkpointing=False) as ctx:
        steps = 0
        for op in ctx.searcher.operations():
            while steps < op.length:
                steps += 1
                if steps % 2 == 0:
                    # best at steps==4: val = (steps-4)^2
                    val = float((steps - 4) ** 2)
                    ctx.train.report_validation_metrics(
                        steps, {"val_loss": val})
                    with ctx.checkpoint.store_path(
                        {"steps_completed": steps}
                    ) as (path, _sid):
                        with open(os.path.join(path, "state.json"), "w") as f:
                            json.dump({"steps": steps}, f)
            op.report_completed(0.0)
        hold = os.environ.get("DET_GC_HOLD_FILE")
        if hold:
            deadline = time.time() + 60
            while not os.path.exists(hold) and time.time() < deadline:
                time.sleep(0.2)
        print(f"gc fixture trained {steps} steps")
    return 0


if __name__ == "__main__":
    sys.exit(main())
