"""Fixture trial for the autotune e2e: pretends sizes above
FAKE_MEMORY_LIMIT OOM, otherwise reports a throughput that grows with
batch size (bigger batches amortize overhead), so the autotuner's best
should be the largest fitting size."""

import os
import sys

from determined_tpu import core


def main() -> int:
    with core.init(async_checkpointing=False) as ctx:
        size = int(ctx.hparams["global_batch_size"])
        limit = int(os.environ.get("FAKE_MEMORY_LIMIT", "64"))
        if size > limit:
            print(f"RESOURCE_EXHAUSTED: fake OOM at batch {size}")
            return 1
        sps = size * 10.0 / (1.0 + size / 100.0)
        for op in ctx.searcher.operations():
            ctx.train.report_validation_metrics(
                op.length, {"samples_per_second": sps})
            op.report_completed(sps)
        print(f"profiled batch {size}: {sps:.1f} samples/s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
