"""Compile-heavy Core-API trial: a REAL jitted GPT-2 train step per trial.

The ASHA bench fixture for compile-reuse (SURVEY hard part b): short
trials whose cost is dominated by jit retrace+compile, exactly the shape
ASHA schedules by the dozen. With the agent-injected DET_XLA_CACHE_DIR
(persistent XLA compilation cache) only the first trial on a host pays
the compile; identical-shape successors load from cache.
"""

import json
import os
import time

import numpy as np


def main() -> int:
    t_start = time.time()
    import jax  # noqa: F401  (import cost is part of the trial)
    import optax

    from determined_tpu import core
    from determined_tpu.models import gpt2
    from determined_tpu.train import create_train_state, make_train_step

    with core.init(async_checkpointing=False) as ctx:
        hp = ctx.hparams
        cfg = gpt2.Config(
            vocab_size=512, n_positions=128, d_model=512, n_layer=6,
            n_head=8, remat=False,
        )
        # HP-invariant compilation: inject_hyperparams makes the searched
        # lr optimizer STATE (device data) instead of a baked-in constant,
        # so every ASHA trial shares ONE compiled program and the
        # persistent cache actually hits across trials. A plain
        # optax.adamw(lr) would give each lr value its own cache key.
        tx = optax.inject_hyperparams(optax.adamw)(
            learning_rate=float(hp.get("lr", 1e-3)))
        state = create_train_state(
            lambda r: gpt2.init(r, cfg), tx, jax.random.PRNGKey(0))
        step = make_train_step(
            lambda p, b, r: gpt2.loss_fn(p, b, cfg), tx)
        tokens = np.random.default_rng(0).integers(
            0, 512, size=(8, 129)).astype(np.int32)
        batch = {"tokens": tokens}

        t_compile0 = time.time()
        state, metrics = step(state, batch, jax.random.PRNGKey(0))
        float(metrics["loss"])  # force execution
        compile_s = time.time() - t_compile0

        steps = 1
        for op in ctx.searcher.operations():
            while steps < op.length:
                state, metrics = step(state, batch, jax.random.PRNGKey(steps))
                steps += 1
            val = float(metrics["loss"])
            ctx.train.report_validation_metrics(
                steps, {"val_loss": val, "compile_s": compile_s,
                        "trial_wall_s": time.time() - t_start})
            op.report_completed(val)
        print(json.dumps({"compile_s": round(compile_s, 2),
                          "wall_s": round(time.time() - t_start, 2),
                          "cache_dir": os.environ.get("DET_XLA_CACHE_DIR")}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
