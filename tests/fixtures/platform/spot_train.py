"""Spot-survival e2e fixture: a real JaxTrial under the Trainer, slow
enough (per-batch sleep) that a termination notice lands mid-run.

Run 1 is drained by a spot notice: the deadline preemption makes the
Trainer take an out-of-band emergency checkpoint (two-phase COMMIT inside
the grace window) and exit 0. The scheduler requeues the trial away from
the DRAINING agent; run 2 restores the emergency checkpoint and trains
through. Logging is configured so the Trainer's restore / emergency-save
lines land in the task log for the test's assertions.
"""

import logging
import os
import sys
import time

import numpy as np
import optax


def main() -> int:
    logging.basicConfig(
        level=logging.INFO, stream=sys.stderr,
        format="%(name)s: %(message)s")

    from determined_tpu import core
    from determined_tpu.parallel.mesh import MeshConfig
    from determined_tpu.train import JaxTrial, Trainer
    from determined_tpu.train.trial import TrialContext

    step_sleep = float(os.environ.get("SPOT_STEP_SLEEP", "0.1"))

    class SlowTrial(JaxTrial):
        prefetch = False  # keep batch consumption deterministic

        def init_params(self, rng):
            import jax

            return {"w": jax.random.normal(rng, (4,)) * 0.1}

        def param_logical_axes(self):
            return {"w": (None,)}

        def loss(self, params, batch, rng):
            import jax.numpy as jnp

            return jnp.mean((params["w"] - batch["x"]) ** 2)

        def optimizer(self):
            return optax.sgd(0.1)

        def mesh_config(self):
            return MeshConfig()

        def build_training_data(self):
            rng = np.random.default_rng(7)
            while True:
                time.sleep(step_sleep)
                yield {"x": rng.normal(size=(8, 4)).astype(np.float32)}

    with core.init(async_checkpointing=False) as ctx:
        trainer = Trainer(SlowTrial(TrialContext()), core_context=ctx)
        # checkpoint_period=0 (op boundaries only): the ONLY mid-run
        # checkpoint is the emergency one — the test can identify it, and
        # the preempt poll can never land on a just-checkpointed step.
        trainer.fit(report_period=1)
    print("spot fixture: trial complete", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
