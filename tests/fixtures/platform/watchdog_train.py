"""Watchdog e2e fixture: hangs once, is killed by the step watchdog, and
completes after the scheduler-driven restart.

The first run of this trial arms the `step.hang` fault point (a 60s stall
in the Trainer's hot loop — far past `health.step_timeout_sec`), so the
watchdog fires: all-thread stack dump to stderr (the task log), exit-reason
report, exit 87. The agent reports the nonzero exit, the master restarts
the trial within `max_restarts`, and the second run — finding the marker
file left by the first — trains straight through.
"""

import os
import sys

import numpy as np
import optax


def main() -> int:
    from determined_tpu import core
    from determined_tpu.common import faultpoint
    from determined_tpu.parallel.mesh import MeshConfig
    from determined_tpu.train import JaxTrial, Trainer
    from determined_tpu.train.trial import TrialContext

    marker = os.path.join(os.environ["WATCHDOG_MARKER_DIR"], "hung-once")
    first_run = not os.path.exists(marker)
    if first_run:
        with open(marker, "w") as f:
            f.write("armed")
        faultpoint.arm("step.hang", "delay-60000", count=1)
        print("watchdog fixture: first run, step.hang armed", flush=True)
    else:
        print("watchdog fixture: restarted run, no hang", flush=True)

    class TinyTrial(JaxTrial):
        health = {"step_timeout_sec": 3.0}
        prefetch = False

        def init_params(self, rng):
            import jax

            return {"w": jax.random.normal(rng, (4,))}

        def loss(self, params, batch, rng):
            import jax.numpy as jnp

            return jnp.mean((params["w"] - batch["x"]) ** 2)

        def optimizer(self):
            return optax.sgd(0.1)

        def mesh_config(self):
            return MeshConfig()

        def build_training_data(self):
            rng = np.random.default_rng(0)
            for _ in range(64):
                yield {"x": rng.normal(size=(8, 4)).astype(np.float32)}

    with core.init(async_checkpointing=False) as ctx:
        trainer = Trainer(TinyTrial(TrialContext()), core_context=ctx)
        trainer.fit(report_period=1)
    print("watchdog fixture: trial complete", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
