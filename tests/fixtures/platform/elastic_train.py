"""Elastic re-meshing e2e fixture (docs/elasticity.md): a real JaxTrial
under the Trainer, slow enough (per-batch sleep) that a drain notice lands
mid-run.

Run 1 holds the preferred size; a spot notice on its agent makes the master
issue a RESIZE OFFER instead of a plain preemption. The Trainer takes a
deadline-budgeted emergency checkpoint and exits clean; the master
re-places the SAME allocation at target_slots on surviving capacity (no
trial requeue, restarts untouched). Run 2 restores the emergency
checkpoint under the smaller mesh — orbax reshards on read — and trains
on; a later grow offer moves it back the same way. Logging is configured
so the Trainer's resize / restore lines land in the task log for the
test's assertions.
"""

import logging
import os
import sys
import time

import numpy as np
import optax


def main() -> int:
    logging.basicConfig(
        level=logging.INFO, stream=sys.stderr,
        format="%(name)s: %(message)s")

    from determined_tpu import core
    from determined_tpu.parallel.mesh import MeshConfig
    from determined_tpu.train import JaxTrial, Trainer
    from determined_tpu.train.trial import TrialContext

    step_sleep = float(os.environ.get("ELASTIC_STEP_SLEEP", "0.1"))

    class ElasticTrial(JaxTrial):
        prefetch = False  # keep batch consumption deterministic

        def init_params(self, rng):
            import jax

            return {"w": jax.random.normal(rng, (4,)) * 0.1}

        def param_logical_axes(self):
            return {"w": (None,)}

        def loss(self, params, batch, rng):
            import jax.numpy as jnp

            return jnp.mean((params["w"] - batch["x"]) ** 2)

        def optimizer(self):
            return optax.sgd(0.1)

        def mesh_config(self):
            # data=-1 absorbs whatever slot count the scheduler granted —
            # the shape every elastic trial wants (preflight DTL204 checks
            # the fixed axes divide every size in [min_slots, max_slots]).
            return MeshConfig()

        def build_training_data(self):
            rng = np.random.default_rng(7)
            while True:
                time.sleep(step_sleep)
                # batch of 8 divides every elastic size the test uses
                yield {"x": rng.normal(size=(8, 4)).astype(np.float32)}

    with core.init(async_checkpointing=False) as ctx:
        import jax

        print(f"elastic fixture: {jax.device_count()} device(s) visible",
              flush=True)
        trainer = Trainer(ElasticTrial(TrialContext()), core_context=ctx)
        trainer.fit(report_period=1)
    print("elastic fixture: trial complete", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
