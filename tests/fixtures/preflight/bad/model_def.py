"""Preflight fixture: a trial with exactly three preflight defects.

  - donate_state=False            -> DTL001 (state not donated, 2x HBM)
  - a 32 MiB embedding with no
    sharded dimension on an
    8-chip mesh                   -> DTL002 (implicit replication)
  - .item() inside the step       -> DTL101 (host sync in traced code)

Everything else is deliberately clean: the batch divides the mesh batch
axes, there is no Python RNG / wall clock / shape branching in the step.
"""

import jax
import jax.numpy as jnp
import numpy as np

from determined_tpu.train import JaxTrial


class BadTrial(JaxTrial):
    donate_state = False  # DTL001

    def init_params(self, rng):
        k1, k2 = jax.random.split(rng)
        return {
            # 32768 x 256 f32 = 32 MiB, no logical axes -> replicated.
            "embedding": jax.random.normal(k1, (32768, 256)) * 0.02,
            "head": jax.random.normal(k2, (256, 8)) * 0.02,
        }

    def loss(self, params, batch, rng):
        x = params["embedding"][batch["tokens"]]
        logits = jnp.mean(x, axis=1) @ params["head"]
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, batch["labels"][:, None], -1)[:, 0]
        loss = jnp.mean(nll)
        metrics = {"loss_scalar": loss.item()}  # DTL101
        return loss, metrics

    def build_training_data(self):
        rng = np.random.default_rng(0)
        while True:
            yield {
                "tokens": rng.integers(0, 32768, (64, 16)),
                "labels": rng.integers(0, 8, (64,)).astype(np.int32),
            }
