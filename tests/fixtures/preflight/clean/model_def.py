"""Preflight fixture: a trial every preflight rule passes."""

import jax
import jax.numpy as jnp
import numpy as np

from determined_tpu.train import JaxTrial


class CleanTrial(JaxTrial):
    def init_params(self, rng):
        k1, k2 = jax.random.split(rng)
        return {
            "w1": jax.random.normal(k1, (64, 128)) * 0.05,
            "w2": jax.random.normal(k2, (128, 8)) * 0.05,
        }

    def param_logical_axes(self):
        return {"w1": ("embed", "mlp"), "w2": ("mlp", None)}

    def loss(self, params, batch, rng):
        h = jax.nn.relu(batch["x"] @ params["w1"])
        logits = h @ params["w2"]
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, batch["labels"][:, None], -1)[:, 0]
        return jnp.mean(nll), {}

    def build_training_data(self):
        rng = np.random.default_rng(0)
        while True:
            yield {
                "x": rng.normal(size=(64, 64)).astype(np.float32),
                "labels": rng.integers(0, 8, (64,)).astype(np.int32),
            }
