"""Compile farm (docs/compile-farm.md): signatures, bucketing, AOT
executable round-trips, the Trainer's warm-start path, DTL205, the master's
job queue + artifact store, and the blob-sweep refcount regression.

The acceptance contract lives in test_trainer_warm_start_bit_identity: a
warm-cache trial's training trajectory is BIT-identical to a cold-compile
run of the same config — the deserialized executable is the same XLA
program, not an approximation of it.
"""

import base64
import json
import os
import sqlite3
import subprocess
import sys
import time

import numpy as np
import optax
import pytest

from test_platform_e2e import (  # noqa: F401  (fixture re-export)
    Devcluster,
    _wait_experiment,
    native_binaries,
)

import jax

from determined_tpu import core as core_mod
from determined_tpu.analysis._preflight import preflight
from determined_tpu.analysis.config_rules import check_config
from determined_tpu.compile import (
    CompileConfig,
    FarmClient,
    aot_artifact_name,
    bucket_size,
    bucketed_iter,
    config_signature,
    pad_batch,
    step_fingerprint,
)
from determined_tpu.compile.runtime import load_compiled, serialize_compiled
from determined_tpu.train.step import make_train_step
from determined_tpu.train.trial import JaxTrial, TrialContext

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FARM_FIXTURES = os.path.join(REPO, "tests", "fixtures", "compile_farm")


class TinyTrial(JaxTrial):
    """Small but non-trivial: deterministic data, hparam-invariant lr."""

    prefetch = False

    def init_params(self, rng):
        k1, k2 = jax.random.split(rng)
        return {"w1": jax.random.normal(k1, (16, 32)) * 0.1,
                "w2": jax.random.normal(k2, (32, 4)) * 0.1}

    def loss(self, params, batch, rng):
        h = jax.numpy.tanh(batch["x"] @ params["w1"])
        pred = h @ params["w2"]
        return ((pred - batch["y"]) ** 2).mean()

    def optimizer(self):
        return optax.inject_hyperparams(optax.adamw)(
            learning_rate=float(self.context.hparams.get("lr", 1e-2)))

    def build_training_data(self):
        rng = np.random.default_rng(42)
        bs = int(self.context.hparams.get("global_batch_size", 8))
        while True:
            yield {"x": rng.normal(size=(bs, 16)).astype(np.float32),
                   "y": rng.normal(size=(bs, 4)).astype(np.float32)}


# ---------------------------------------------------------------- bucketing


def test_bucket_size_pow2_and_explicit():
    assert bucket_size(1) == 1
    assert bucket_size(5) == 8
    assert bucket_size(8) == 8
    assert bucket_size(9) == 16
    assert bucket_size(5, [4, 16, 64]) == 16
    assert bucket_size(64, [4, 16, 64]) == 64
    # above the largest explicit bucket: exact (no silent mega-padding)
    assert bucket_size(65, [4, 16, 64]) == 65


def test_pad_batch_wraps_rows():
    b = {"x": np.arange(10, dtype=np.float32).reshape(5, 2),
         "y": np.arange(5), "scalar": np.float32(3.0)}
    p = pad_batch(b, 8)
    assert p["x"].shape == (8, 2) and p["y"].shape == (8,)
    # wrap-around: pad rows repeat real rows, never zeros
    assert (p["x"][5] == b["x"][0]).all() and (p["x"][7] == b["x"][2]).all()
    assert p["scalar"] == b["scalar"]
    # already at/above target: untouched
    assert pad_batch(b, 5)["x"] is b["x"]


def test_bucketed_iter_consistent_shapes():
    cfg = CompileConfig(bucket_batch_sizes=True)
    batches = [{"x": np.ones((n, 3), np.float32)} for n in (5, 6, 8, 9)]
    out = list(bucketed_iter(iter(batches), cfg))
    assert [b["x"].shape[0] for b in out] == [8, 8, 8, 16]


def test_compile_config_resolve_precedence():
    cfg = CompileConfig.resolve(None, {"compile": {"bucket_batch_sizes": True,
                                                   "max_executables": 4}})
    assert cfg.bucket_batch_sizes and cfg.max_executables == 4

    class T(TinyTrial):
        compile = {"enabled": False}

    t = T(TrialContext())
    assert not CompileConfig.resolve(t, {"compile": {"enabled": True}}).enabled
    assert CompileConfig.from_block(False).enabled is False
    assert CompileConfig.from_block(None).enabled is True


# --------------------------------------------------------------- signatures


def test_config_signature_key_properties():
    cfg = CompileConfig(bucket_batch_sizes=True)
    s1 = config_signature({"lr": 0.1, "global_batch_size": 48},
                          "python3 t.py", "h1", 1, cfg)
    # order-insensitive, bucket-merged
    s2 = config_signature({"global_batch_size": 60, "lr": 0.1},
                          "python3 t.py", "h1", 1, cfg)
    assert s1 == s2
    # every hparam value matters (no lossy shape guessing on this key)
    assert s1 != config_signature({"lr": 0.2, "global_batch_size": 48},
                                  "python3 t.py", "h1", 1, cfg)
    # entrypoint / model-def / slots all matter
    assert s1 != config_signature({"lr": 0.1, "global_batch_size": 48},
                                  "python3 other.py", "h1", 1, cfg)
    assert s1 != config_signature({"lr": 0.1, "global_batch_size": 48},
                                  "python3 t.py", "h2", 1, cfg)
    assert s1 != config_signature({"lr": 0.1, "global_batch_size": 48},
                                  "python3 t.py", "h1", 2, cfg)
    # without bucketing the raw batch size separates the keys
    s3 = config_signature({"lr": 0.1, "global_batch_size": 48},
                          "python3 t.py", "h1", 1)
    s4 = config_signature({"lr": 0.1, "global_batch_size": 60},
                          "python3 t.py", "h1", 1)
    assert s3 != s4


_FP_PROBE = """
import json, sys
sys.path.insert(0, {repo!r})
import numpy as np
sys.path.insert(0, {testdir!r})
from test_compile_farm import TinyTrial
from determined_tpu.compile import step_fingerprint
from determined_tpu.train.trial import TrialContext
fp, detail = step_fingerprint(TinyTrial(TrialContext({hp})), 1)
print(json.dumps({{"fp": fp}}))
"""


def _probe_fingerprint(hparams: dict) -> str:
    code = _FP_PROBE.format(repo=REPO,
                            testdir=os.path.join(REPO, "tests"),
                            hp=repr(hparams))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=300, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    return json.loads(r.stdout.strip().splitlines()[-1])["fp"]


def test_fingerprint_stable_across_processes():
    """Same config => identical signature across processes — the property
    that lets artifacts compiled on one host serve trials on another."""
    fp1 = _probe_fingerprint({"lr": 0.01})
    fp2 = _probe_fingerprint({"lr": 0.01})
    assert fp1 == fp2
    # and matches this process too
    fp3, _ = step_fingerprint(TinyTrial(TrialContext({"lr": 0.01})), 1)
    assert fp3 == fp1


def test_fingerprint_sensitivity():
    base, _ = step_fingerprint(TinyTrial(TrialContext({"lr": 0.01})), 1)

    # inject_hyperparams lr is optimizer STATE: hparam-invariant program
    same, _ = step_fingerprint(TinyTrial(TrialContext({"lr": 0.5})), 1)
    assert same == base

    # a BAKED lr is a jaxpr constant: the fingerprint must differ
    class Baked(TinyTrial):
        def optimizer(self):
            return optax.adamw(float(self.context.hparams.get("lr", 1e-2)))

    b1, _ = step_fingerprint(Baked(TrialContext({"lr": 0.01})), 1)
    b2, _ = step_fingerprint(Baked(TrialContext({"lr": 0.5})), 1)
    assert b1 != b2 and b1 != base

    # batch shape changes it...
    big, _ = step_fingerprint(
        TinyTrial(TrialContext({"global_batch_size": 16})), 1)
    assert big != base

    # ...unless bucketing folds the sizes into one bucket
    cfg = CompileConfig(bucket_batch_sizes=True)
    f6, _ = step_fingerprint(
        TinyTrial(TrialContext({"global_batch_size": 6})), 1, cfg=cfg)
    f8, _ = step_fingerprint(
        TinyTrial(TrialContext({"global_batch_size": 8})), 1, cfg=cfg)
    f9, _ = step_fingerprint(
        TinyTrial(TrialContext({"global_batch_size": 9})), 1, cfg=cfg)
    assert f6 == f8 and f9 != f8

    # donation pattern changes it
    class NoDonate(TinyTrial):
        donate_state = False

    nd, _ = step_fingerprint(NoDonate(TrialContext()), 1)
    assert nd != base

    # mesh shape changes it (2-device dp over the same program)
    class Mesh2(TinyTrial):
        def mesh_config(self):
            from determined_tpu.parallel.mesh import MeshConfig

            return MeshConfig(data=2)

    m2, _ = step_fingerprint(Mesh2(TrialContext()), 2)
    assert m2 != base

    # dtype changes it
    class F16(TinyTrial):
        def init_params(self, rng):
            p = TinyTrial.init_params(self, rng)
            return jax.tree_util.tree_map(
                lambda x: x.astype(jax.numpy.bfloat16), p)

    f16, _ = step_fingerprint(F16(TrialContext()), 1)
    assert f16 != base


def test_fingerprint_attention_impl_sensitivity():
    """`optimizations.attention_impl` is program identity (docs/
    training-perf.md): dense and reference trace to the SAME jaxpr (same
    arithmetic — the farm shares one executable), while the pallas kernel
    (and its bf16 variant) are different XLA programs and must fingerprint
    apart, or a warm farm would serve a dense executable to a flash trial."""
    from determined_tpu.models import gpt2

    def make_trial(impl, bf16=False):
        # pallas-supported geometry: d_model/n_head = 64, s % 128 == 0
        cfg = gpt2.Config(vocab_size=128, n_positions=128, d_model=256,
                          n_layer=1, n_head=4, remat=False,
                          attention_impl=impl, attention_bf16=bf16)

        class AttnTrial(JaxTrial):
            prefetch = False

            def init_params(self, rng):
                return gpt2.init(rng, cfg)

            def loss(self, params, batch, rng):
                return gpt2.loss_fn(params, batch, cfg)

            def optimizer(self):
                return optax.adamw(1e-3)

            def build_training_data(self):
                drng = np.random.default_rng(0)
                while True:
                    yield {"tokens": drng.integers(0, 128, size=(2, 129))
                           .astype(np.int32)}

        return AttnTrial(TrialContext())

    dense, _ = step_fingerprint(make_trial("dense"), 1)
    reference, _ = step_fingerprint(make_trial("reference"), 1)
    assert reference == dense  # identical arithmetic => shared executable

    pallas, _ = step_fingerprint(make_trial("pallas"), 1)
    assert pallas != dense

    pallas_bf16, _ = step_fingerprint(make_trial("pallas", bf16=True), 1)
    assert pallas_bf16 != pallas


# -------------------------------------------------------------- AOT runtime


def _fresh_state_and_step(trial):
    from determined_tpu.train.state import create_train_state

    tx = trial.optimizer()
    state = create_train_state(trial.init_params, tx, jax.random.PRNGKey(0))
    step = make_train_step(trial.loss, tx)
    return state, step


def test_aot_roundtrip_bit_identity():
    """serialize -> deserialize -> N steps must be bit-identical to the
    jit-dispatch path: a deserialized executable IS the same XLA program."""
    trial = TinyTrial(TrialContext())
    batch = next(iter(trial.build_training_data()))

    state_a, step = _fresh_state_and_step(trial)
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state_a)
    batch_sds = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), batch)
    rng_sds = jax.ShapeDtypeStruct((2,), np.uint32)
    blob = serialize_compiled(step.lower(abstract, batch_sds, rng_sds)
                              .compile())
    loaded = load_compiled(blob)

    state_b, _ = _fresh_state_and_step(trial)
    losses_a, losses_b = [], []
    for i in range(3):
        rng = jax.random.PRNGKey(i)
        state_a, ma = step(state_a, batch, rng)
        state_b, mb = loaded(state_b, batch, rng)
        losses_a.append(float(ma["loss"]))
        losses_b.append(float(mb["loss"]))
    assert losses_a == losses_b
    pa = jax.device_get(state_a.params)
    pb = jax.device_get(state_b.params)
    for la, lb in zip(jax.tree_util.tree_leaves(pa),
                      jax.tree_util.tree_leaves(pb)):
        assert np.array_equal(la, lb)


class _FakeSession:
    """Capture FarmClient round-trips without a master."""

    def __init__(self):
        self.store = {}  # signature -> {name: bytes}
        self.posts = []

    def get(self, path, params=None, **kw):
        sig = path.rsplit("/", 1)[-1]
        files = self.store.get(sig, {})
        name = (params or {}).get("name")
        return {"signature": sig, "files": [
            {"name": n, "b64": base64.b64encode(b).decode(), "size": len(b)}
            for n, b in files.items() if name is None or n == name]}

    def post(self, path, body=None, **kw):
        self.posts.append((path, body))
        if "/compile_cache/" in path:
            sig = path.rsplit("/", 1)[-1]
            dest = self.store.setdefault(sig, {})
            for n, b64 in (body or {}).get("files", {}).items():
                dest[n] = base64.b64decode(b64)
        return {}


def _run_trainer(tmp_path, run_name, farm_client=None, steps=4):
    """One local Trainer run; returns (final_state, training metrics)."""
    from determined_tpu.train.trainer import Trainer

    ctx = core_mod.init(
        max_length=steps,
        checkpoint_dir=os.path.join(str(tmp_path), f"ckpt-{run_name}"),
        async_checkpointing=False)
    try:
        trainer = Trainer(TinyTrial(TrialContext({"lr": 0.01})),
                          core_context=ctx)
        if farm_client is not None:
            trainer._farm = farm_client
        state = trainer.fit(report_period=steps, seed=7)
        return state, list(ctx.train.local_training_metrics)
    finally:
        ctx.close()


def test_trainer_warm_start_bit_identity(tmp_path, monkeypatch):
    """ACCEPTANCE: cold-compile run vs warm-cache run of the same config —
    identical loss series and bit-identical final params, with the warm
    run's first flush reporting compile_cache_hit=1."""
    monkeypatch.delenv("DET_COMPILE_SIGNATURE", raising=False)
    monkeypatch.delenv("DET_COMPILE_AOT_DIR", raising=False)
    sig = "farmtest-" + "0" * 8
    session = _FakeSession()

    # Cold run: fresh compile; the farm client exports + uploads the
    # serialized executable in the background (fit() joins the thread).
    cold_client = FarmClient(session, signature=sig, aot_dir="",
                             xla_cache_dir="")
    state_cold, metrics_cold = _run_trainer(tmp_path, "cold", cold_client)
    aot_name = aot_artifact_name("train_step")
    assert aot_name in session.store.get(sig, {}), (
        "fresh compile must upload its serialized executable")
    cold_flush = next(m["metrics"] for m in metrics_cold
                      if "compile_ms" in m["metrics"])
    assert cold_flush["compile_cache_hit"] == 0.0

    # Pre-warm the local AOT dir the way the agent does, then run warm
    # WITHOUT a session — artifacts come from disk alone.
    aot_dir = tmp_path / "aot_cache"
    (aot_dir / sig).mkdir(parents=True)
    (aot_dir / sig / aot_name).write_bytes(session.store[sig][aot_name])
    warm_client = FarmClient(None, signature=sig, aot_dir=str(aot_dir),
                             xla_cache_dir="")
    state_warm, metrics_warm = _run_trainer(tmp_path, "warm", warm_client)
    warm_flush = next(m["metrics"] for m in metrics_warm
                      if "compile_ms" in m["metrics"])
    assert warm_flush["compile_cache_hit"] == 1.0

    # Bit-identical trajectory: loss series and final params.
    assert [m["metrics"].get("loss") for m in metrics_cold] == \
        [m["metrics"].get("loss") for m in metrics_warm]
    for la, lb in zip(
            jax.tree_util.tree_leaves(jax.device_get(state_cold.params)),
            jax.tree_util.tree_leaves(jax.device_get(state_warm.params))):
        assert np.array_equal(la, lb)


def test_trainer_bad_artifact_falls_back(tmp_path):
    """A corrupt/mismatched AOT artifact must cost a fallback, never the
    trial: the run completes with cache_hit=0."""
    sig = "farmtest-bad"
    aot_dir = tmp_path / "aot"
    (aot_dir / sig).mkdir(parents=True)
    (aot_dir / sig / aot_artifact_name("train_step")).write_bytes(
        b"not a pickled executable")
    client = FarmClient(None, signature=sig, aot_dir=str(aot_dir),
                        xla_cache_dir="")
    state, metrics = _run_trainer(tmp_path, "bad", client)
    flush = next(m["metrics"] for m in metrics
                 if "compile_ms" in m["metrics"])
    assert flush["compile_cache_hit"] == 0.0
    assert state is not None


def test_farm_client_disabled_and_dead_sink():
    # no signature: every surface is a no-op
    c = FarmClient(None, signature="", aot_dir="", xla_cache_dir="")
    assert not c.enabled
    assert c.fetch("x") is None and c.load_executable("train_step") is None
    assert c.upload({"a": b"b"}) is False

    # a raising session must never propagate (farm is best-effort)
    class Dead:
        def get(self, *a, **k):
            raise ConnectionError("down")

        def post(self, *a, **k):
            raise ConnectionError("down")

    d = FarmClient(Dead(), signature="s", aot_dir="", xla_cache_dir="")
    assert d.fetch("x") is None
    assert d.upload({"a": b"b"}) is False


# ------------------------------------------------------------------- DTL205


def _sweep_config(**over):
    cfg = {
        "searcher": {"name": "random", "metric": "loss",
                     "max_length": {"batches": 8}, "max_trials": 32},
        "hyperparameters": {
            "lr": {"type": "log", "minval": -4, "maxval": -1},
            "global_batch_size": {"type": "int", "minval": 16,
                                  "maxval": 256},
        },
        "resources": {"slots_per_trial": 1},
        "entrypoint": "python3 t.py",
    }
    cfg.update(over)
    return cfg


def test_dtl205_fires_on_raw_batch_sweep():
    d = [x for x in check_config(_sweep_config()) if x.code == "DTL205"]
    assert len(d) == 1 and d[0].level == "warning"
    assert "global_batch_size" in d[0].message
    assert "bucket_batch_sizes" in d[0].message  # the actionable hint


def test_dtl205_bucketing_silences():
    cfg = _sweep_config(compile={"bucket_batch_sizes": True})
    assert not [x for x in check_config(cfg) if x.code == "DTL205"]


def test_dtl205_quiet_cases():
    # single searcher: one executable regardless
    cfg = _sweep_config(searcher={"name": "single", "metric": "loss",
                                  "max_length": {"batches": 8}})
    assert not [x for x in check_config(cfg) if x.code == "DTL205"]
    # non-shape sweep only
    cfg = _sweep_config(hyperparameters={
        "lr": {"type": "log", "minval": -4, "maxval": -1}})
    assert not [x for x in check_config(cfg) if x.code == "DTL205"]
    # max_trials bounds the executable count
    cfg = _sweep_config()
    cfg["searcher"]["max_trials"] = 4
    assert not [x for x in check_config(cfg) if x.code == "DTL205"]
    # raised ceiling
    cfg = _sweep_config(compile={"max_executables": 1000})
    assert not [x for x in check_config(cfg) if x.code == "DTL205"]


def test_dtl205_shape_categorical_and_unbounded_double():
    cfg = _sweep_config(hyperparameters={
        "d_model": {"type": "categorical",
                    "vals": [64 * i for i in range(1, 13)]}})
    assert [x for x in check_config(cfg) if x.code == "DTL205"]
    # double-sweeping a shape hparam without count: unbounded
    cfg = _sweep_config(hyperparameters={
        "hidden_size": {"type": "double", "minval": 64, "maxval": 1024}})
    d = [x for x in check_config(cfg) if x.code == "DTL205"]
    assert d and "unbounded" in d[0].message


def test_dtl205_suppressible():
    cfg = _sweep_config(preflight={"suppress": ["DTL205"]})
    report = preflight(cfg, context_dir=None)
    d = [x for x in report.diagnostics if x.code == "DTL205"]
    assert d and all(x.suppressed for x in d)


# ------------------------------------------------------------------ expconf


def test_expconf_compile_block():
    from determined_tpu import expconf

    base = {"entrypoint": "python3 t.py",
            "searcher": {"name": "single", "metric": "m",
                         "max_length": {"batches": 1}}}
    assert not expconf.validate(dict(base, compile={
        "enabled": True, "background": True, "bucket_batch_sizes": True,
        "buckets": [8, 16], "max_executables": 4, "upload": False}))
    assert not expconf.validate(dict(base, compile=True))
    assert expconf.validate(dict(base, compile={"bogus": 1}))
    assert expconf.validate(dict(base, compile={"max_executables": 0}))
    assert expconf.validate(dict(base, compile={"buckets": []}))
    assert expconf.validate(dict(base, compile={"buckets": [0]}))
    assert expconf.validate(dict(base, compile={"background": "yes"}))
    assert expconf.validate(dict(base, compile=3))
    c = expconf.apply_defaults(dict(base))
    assert c["compile"] == {"enabled": True, "background": False,
                            "bucket_batch_sizes": False,
                            "max_executables": 8, "upload": True}


# ------------------------------------------- master: queue + artifact store


@pytest.fixture()
def master_only(tmp_path, native_binaries):
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    yield c
    c.stop()


def _upload_artifacts(cluster, token, sig, files, **extra):
    body = {"files": {n: base64.b64encode(b).decode()
                      for n, b in files.items()}}
    body.update(extra)
    return cluster.api("POST", f"/api/v1/compile_cache/{sig}", body,
                       token=token)


def test_master_compile_cache_roundtrip(master_only):
    cluster = master_only
    token = cluster.login()
    sig = "a" * 64
    files = {"aot-train_step-deadbeef.bin": b"\x00\x01exec",
             "xlacache-entry": b"cachedata"}
    out = _upload_artifacts(cluster, token, sig, files, compile_ms=1234.0,
                            fingerprint="fp1")
    assert out["stored"] == 2

    got = cluster.api("GET", f"/api/v1/compile_cache/{sig}", token=token)
    assert {f["name"] for f in got["files"]} == set(files)
    for f in got["files"]:
        assert base64.b64decode(f["b64"]) == files[f["name"]]

    # ?name= filter
    got = cluster.api(
        "GET", f"/api/v1/compile_cache/{sig}?name=xlacache-entry",
        token=token)
    assert [f["name"] for f in got["files"]] == ["xlacache-entry"]

    # artifact arrival marked the job DONE with the fingerprint
    jobs = cluster.api("GET", "/api/v1/compile_jobs?state=DONE",
                       token=token)["jobs"]
    row = next(j for j in jobs if j["signature"] == sig)
    assert row["fingerprint"] == "fp1"
    assert row["compile_ms"] == 1234.0

    # idempotent re-upload: no duplicate rows, no double blob claims
    out = _upload_artifacts(cluster, token, sig, files)
    assert out["stored"] == 0


def test_master_compile_jobs_link_and_fingerprint_query(master_only):
    cluster = master_only
    token = cluster.login()
    sig_a, sig_b = "b" * 64, "c" * 64
    _upload_artifacts(cluster, token, sig_a,
                      {"aot-train_step-t.bin": b"exec-a"},
                      fingerprint="sharedfp")
    # worker's pre-compile lookup: DONE jobs by fingerprint
    jobs = cluster.api(
        "GET", "/api/v1/compile_jobs?state=DONE&fingerprint=sharedfp",
        token=token)["jobs"]
    assert [j["signature"] for j in jobs] == [sig_a]

    out = cluster.api("POST", f"/api/v1/compile_jobs/{sig_b}/link",
                      {"from": sig_a, "fingerprint": "sharedfp"},
                      token=token)
    assert out["linked"] == 1
    got = cluster.api("GET", f"/api/v1/compile_cache/{sig_b}", token=token)
    assert [f["name"] for f in got["files"]] == ["aot-train_step-t.bin"]
    assert base64.b64decode(got["files"][0]["b64"]) == b"exec-a"


def test_master_enqueue_on_trial_create(master_only, tmp_path):
    """compile.background experiments enumerate one QUEUED job per
    distinct signature at trial creation; no-block experiments enqueue
    nothing."""
    import determined_tpu.cli as cli

    cluster = master_only
    token = cluster.login()
    model_def = cli._tar_context(FARM_FIXTURES)

    def config(name, background):
        c = {
            "name": name,
            "entrypoint": "python3 train_farm.py",
            "searcher": {"name": "random", "metric": "val_loss",
                         "max_length": {"batches": 2}, "max_trials": 3},
            "hyperparameters": {"lr": 0.01, "global_batch_size": 8},
            "resources": {"slots_per_trial": 1},
            "checkpoint_storage": {
                "type": "shared_fs",
                "host_path": os.path.join(str(tmp_path), "ckpts")},
        }
        if background:
            c["compile"] = {"background": True}
        return c

    cluster.api("POST", "/api/v1/experiments",
                {"config": config("no-farm", False),
                 "model_definition": model_def, "activate": True},
                token=token)
    jobs = cluster.api("GET", "/api/v1/compile_jobs", token=token)["jobs"]
    assert jobs == []

    eid = cluster.api("POST", "/api/v1/experiments",
                      {"config": config("farm", True),
                       "model_definition": model_def, "activate": True},
                      token=token)["id"]
    jobs = cluster.api("GET", "/api/v1/compile_jobs", token=token)["jobs"]
    # 3 trials, identical (const) hparams -> exactly one signature
    assert len(jobs) == 1
    assert jobs[0]["state"] == "QUEUED"  # no agent: nothing to dispatch to
    assert jobs[0]["experiment_id"] == eid
    assert jobs[0]["slots"] == 1

    # prometheus sees the queue
    import urllib.request

    req = urllib.request.Request(
        cluster.master_url + "/metrics",
        headers={"Authorization": f"Bearer {token}"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        text = resp.read().decode()
    assert 'det_compile_jobs{state="QUEUED"} 1' in text
    assert "det_compile_artifact_uploads_total" in text


def test_blob_sweep_respects_compile_artifacts(master_only):
    """REGRESSION (docs/compile-farm.md): the blob sweep must not GC a
    blob a live compile-artifact row references, even at refcount 0 —
    linked signatures reference blobs without fresh claims."""
    cluster = master_only
    token = cluster.login()
    sig = "d" * 64
    _upload_artifacts(cluster, token, sig, {"aot-x.bin": b"payload"})

    db = sqlite3.connect(cluster.db_path)
    try:
        (blob_hash,) = db.execute(
            "SELECT blob_hash FROM compile_artifacts WHERE signature=?",
            (sig,)).fetchone()
        # Simulate every claim draining away (task/experiment releases).
        db.execute("UPDATE model_defs SET refcount=0 WHERE hash=?",
                   (blob_hash,))
        # Control: an unreferenced zero-refcount blob must still be swept.
        db.execute(
            "INSERT INTO model_defs (hash, blob, refcount) "
            "VALUES ('unreferenced-hash', 'x', 0)")
        db.commit()
    finally:
        db.close()

    admin_token = cluster.login("admin")
    cluster.api("POST", "/api/v1/master/cleanup_blobs", {},
                token=admin_token)

    db = sqlite3.connect(cluster.db_path)
    try:
        assert db.execute(
            "SELECT COUNT(*) FROM model_defs WHERE hash=?",
            (blob_hash,)).fetchone()[0] == 1, "artifact blob was GC'd"
        assert db.execute(
            "SELECT COUNT(*) FROM model_defs WHERE hash='unreferenced-hash'"
        ).fetchone()[0] == 0, "control blob should have been swept"
    finally:
        db.close()

    # and the artifact still serves
    got = cluster.api("GET", f"/api/v1/compile_cache/{sig}", token=token)
    assert base64.b64decode(got["files"][0]["b64"]) == b"payload"


def test_compile_artifact_ttl_eviction(tmp_path, native_binaries):
    """Retention (compile_cache.ttl_days, ROADMAP item 5 leftover): the
    blob sweep evicts artifact rows past the TTL — INCLUDING rows holding
    a blob only through a fingerprint link — so their blobs get swept,
    while fresh artifacts survive untouched. Default-off: artifacts on a
    master without the flag persist forever (the pre-TTL behavior)."""
    cluster = Devcluster(str(tmp_path), native_binaries)
    cluster.start_master(extra_args=["--compile-ttl-days", "7"])
    try:
        token = cluster.login()
        sig_old, sig_linked, sig_fresh = "e" * 64, "f" * 64, "0" * 64
        _upload_artifacts(cluster, token, sig_old,
                          {"aot-old.bin": b"old-exec"},
                          fingerprint="ttlfp")
        # Linked signature: holds the SAME blob through the link only.
        cluster.api("POST", f"/api/v1/compile_jobs/{sig_linked}/link",
                    {"from": sig_old, "fingerprint": "ttlfp"}, token=token)
        _upload_artifacts(cluster, token, sig_fresh,
                          {"aot-fresh.bin": b"fresh-exec"})

        db = sqlite3.connect(cluster.db_path)
        try:
            (old_hash,) = db.execute(
                "SELECT blob_hash FROM compile_artifacts WHERE signature=?",
                (sig_old,)).fetchone()
            # Age the original AND the linked rows past the 7-day TTL;
            # drain the upload's task claim so only compile_artifacts
            # holds the blob (the linked-row scenario).
            db.execute(
                "UPDATE compile_artifacts SET "
                "created_at = datetime('now', '-10 days') "
                "WHERE signature IN (?, ?)", (sig_old, sig_linked))
            db.execute("UPDATE model_defs SET refcount=0 WHERE hash=?",
                       (old_hash,))
            db.commit()
        finally:
            db.close()

        admin = cluster.login("admin")
        out = cluster.api("POST", "/api/v1/master/cleanup_blobs", {},
                          token=admin)
        assert out["compile_artifacts_evicted"] == 2, out

        db = sqlite3.connect(cluster.db_path)
        try:
            # Expired rows gone (both the original and the linked one),
            # their job rows re-enqueueable, their blob swept.
            assert db.execute(
                "SELECT COUNT(*) FROM compile_artifacts WHERE "
                "signature IN (?, ?)", (sig_old, sig_linked)
            ).fetchone()[0] == 0
            assert db.execute(
                "SELECT COUNT(*) FROM compile_jobs WHERE "
                "signature IN (?, ?)", (sig_old, sig_linked)
            ).fetchone()[0] == 0
            assert db.execute(
                "SELECT COUNT(*) FROM model_defs WHERE hash=?",
                (old_hash,)).fetchone()[0] == 0, "expired blob not swept"
            # The fresh artifact and its blob survive.
            assert db.execute(
                "SELECT COUNT(*) FROM compile_artifacts WHERE signature=?",
                (sig_fresh,)).fetchone()[0] == 1
        finally:
            db.close()
        got = cluster.api("GET", f"/api/v1/compile_cache/{sig_fresh}",
                          token=token)
        assert base64.b64decode(got["files"][0]["b64"]) == b"fresh-exec"
        got = cluster.api("GET", f"/api/v1/compile_cache/{sig_old}",
                          token=token)
        assert got["files"] == []
    finally:
        cluster.stop()


def test_compile_artifact_ttl_off_by_default(master_only):
    """No ttl flag → aged artifacts persist through the sweep."""
    cluster = master_only
    token = cluster.login()
    sig = "9" * 64
    _upload_artifacts(cluster, token, sig, {"aot-keep.bin": b"keep"})
    db = sqlite3.connect(cluster.db_path)
    try:
        db.execute(
            "UPDATE compile_artifacts SET "
            "created_at = datetime('now', '-400 days') WHERE signature=?",
            (sig,))
        db.commit()
    finally:
        db.close()
    out = cluster.api("POST", "/api/v1/master/cleanup_blobs", {},
                      token=cluster.login("admin"))
    assert out["compile_artifacts_evicted"] == 0
    got = cluster.api("GET", f"/api/v1/compile_cache/{sig}", token=token)
    assert base64.b64decode(got["files"][0]["b64"]) == b"keep"


def test_worker_run_job_compiles_and_uploads(master_only, tmp_path,
                                             monkeypatch):
    """The farm worker end to end against a real master: download the
    model-def, trace the fingerprint, AOT-compile, upload artifacts, mark
    the job DONE — then a second signature with the same fingerprint LINKS
    instead of recompiling."""
    import determined_tpu.cli as cli
    from determined_tpu.common.api import Session
    from determined_tpu.compile.worker import run_job

    # Tiny model: the worker compiles a real GPT-2 step; keep it fast.
    monkeypatch.setenv("FARM_D_MODEL", "64")
    monkeypatch.setenv("FARM_N_LAYER", "1")
    monkeypatch.setenv("DET_XLA_CACHE_DIR",
                       os.path.join(str(tmp_path), "xla"))

    cluster = master_only
    token = cluster.login()
    model_def = cli._tar_context(FARM_FIXTURES)
    config = {
        "name": "worker-test",
        "entrypoint": "python3 train_farm.py",
        "searcher": {"name": "single", "metric": "val_loss",
                     "max_length": {"batches": 2}},
        "hyperparameters": {"lr": 0.01, "global_batch_size": 4},
        "resources": {"slots_per_trial": 1},
    }
    eid = cluster.api("POST", "/api/v1/experiments",
                      {"config": config, "model_definition": model_def,
                       "activate": False}, token=token)["id"]
    session = Session(cluster.master_url, token)

    sig = "e" * 64
    summary = run_job(session, sig, {"lr": 0.01, "global_batch_size": 4}, 1,
                      eid, config)
    assert summary["artifacts"] >= 1 and summary["compile_ms"] > 0

    got = cluster.api("GET", f"/api/v1/compile_cache/{sig}", token=token)
    names = {f["name"] for f in got["files"]}
    assert any(n.startswith("aot-train_step-") for n in names)
    jobs = cluster.api("GET", "/api/v1/compile_jobs?state=DONE",
                       token=token)["jobs"]
    row = next(j for j in jobs if j["signature"] == sig)
    assert row["fingerprint"] == summary["fingerprint"]

    # Same program under a different signature (e.g. a different lr with
    # inject_hyperparams): the worker links, no second compile.
    sig2 = "f" * 64
    summary2 = run_job(session, sig2, {"lr": 0.5, "global_batch_size": 4},
                       1, eid, config)
    assert summary2.get("linked_from") == sig
    got2 = cluster.api("GET", f"/api/v1/compile_cache/{sig2}", token=token)
    assert {f["name"] for f in got2["files"]} == names


# ------------------------------------------------------------- slow e2e


@pytest.mark.slow
def test_e2e_background_compile_on_idle_agent(tmp_path, native_binaries):
    """Queued time becomes compile time: an unplaceable trial (needs 2
    slots on a 1-slot agent) leaves the agent idle; the master dispatches
    the compile job to it; the worker compiles and uploads while the trial
    is still waiting."""
    import determined_tpu.cli as cli

    cluster = Devcluster(str(tmp_path), native_binaries, slots=1)
    try:
        cluster.start_master()
        cluster.start_agent()
        token = cluster.login()
        model_def = cli._tar_context(FARM_FIXTURES)
        config = {
            "name": "farm-bg",
            "entrypoint": "python3 train_farm.py",
            "searcher": {"name": "single", "metric": "val_loss",
                         "max_length": {"batches": 2}},
            "hyperparameters": {"lr": 0.01, "global_batch_size": 4},
            "resources": {"slots_per_trial": 2},  # never places on 1 slot
            "compile": {"background": True},
            "environment": {"environment_variables":
                            ["FARM_D_MODEL=64", "FARM_N_LAYER=1"]},
            "checkpoint_storage": {
                "type": "shared_fs",
                "host_path": os.path.join(str(tmp_path), "ckpts")},
        }
        eid = cluster.api("POST", "/api/v1/experiments",
                          {"config": config, "model_definition": model_def,
                           "activate": True}, token=token)["id"]
        deadline = time.time() + 240
        row = None
        while time.time() < deadline:
            jobs = cluster.api("GET", "/api/v1/compile_jobs",
                               token=token)["jobs"]
            row = next((j for j in jobs if j["experiment_id"] == eid), None)
            if row and row["state"] in ("DONE", "FAILED"):
                break
            time.sleep(2)
        assert row is not None and row["state"] == "DONE", row
        sig = row["signature"]
        got = cluster.api("GET", f"/api/v1/compile_cache/{sig}",
                          token=token)
        assert any(f["name"].startswith("aot-train_step-")
                   for f in got["files"])
        cluster.api("POST", f"/api/v1/experiments/{eid}/kill", token=token)
    finally:
        cluster.stop()


@pytest.mark.slow
def test_e2e_warm_trial_cache_hit(tmp_path, native_binaries):
    """The full loop on a devcluster: trial 1 compiles fresh and uploads;
    the agent pre-warms trial 2's caches before its container starts;
    trial 2 reports cache_hit with a near-zero compile span."""
    import determined_tpu.cli as cli

    cluster = Devcluster(str(tmp_path), native_binaries, slots=1)
    try:
        cluster.start_master()
        cluster.start_agent()
        token = cluster.login()
        model_def = cli._tar_context(FARM_FIXTURES)
        config = {
            "name": "farm-warm",
            "entrypoint": "python3 train_farm.py",
            # const hparams: both trials share one signature
            "searcher": {"name": "random", "metric": "val_loss",
                         "max_length": {"batches": 2}, "max_trials": 2,
                         "max_concurrent_trials": 1},
            "hyperparameters": {"lr": 0.01, "global_batch_size": 4},
            "resources": {"slots_per_trial": 1},
            "environment": {"environment_variables":
                            ["FARM_D_MODEL=256", "FARM_N_LAYER=2"]},
            "checkpoint_storage": {
                "type": "shared_fs",
                "host_path": os.path.join(str(tmp_path), "ckpts")},
            "max_restarts": 0,
        }
        eid = cluster.api("POST", "/api/v1/experiments",
                          {"config": config, "model_definition": model_def,
                           "activate": True}, token=token)["id"]
        _wait_experiment(cluster, eid, token, timeout=600)
        trials = cluster.api("GET", f"/api/v1/experiments/{eid}/trials",
                             token=token)["trials"]
        assert len(trials) == 2
        per_trial = {}
        for t in trials:
            for m in cluster.api("GET",
                                 f"/api/v1/trials/{t['id']}/metrics",
                                 token=token)["metrics"]:
                mm = m["metrics"]
                if "compile_ms" in mm:
                    per_trial[t["id"]] = (float(mm["compile_ms"]),
                                          float(mm["compile_cache_hit"]))
        assert len(per_trial) == 2, per_trial
        ordered = [per_trial[t["id"]] for t in
                   sorted(trials, key=lambda x: x["id"])]
        (cold_ms, cold_hit), (warm_ms, warm_hit) = ordered
        assert cold_hit == 0.0 and warm_hit == 1.0, ordered
        # the headline: warm compile is a deserialize, not a compile
        assert warm_ms < cold_ms / 3, ordered

        # spans: trial 2 has agent.cache_warm with files>0 and a
        # harness.compile span with cache_hit true
        t2 = sorted(trials, key=lambda x: x["id"])[1]
        spans = cluster.api("GET", f"/api/v1/trials/{t2['id']}/trace",
                            token=token)["spans"]
        by_name = {}
        for s in spans:
            by_name.setdefault(s["name"], []).append(s)
        warm_spans = by_name.get("agent.cache_warm", [])
        assert warm_spans and any(
            int(s["attrs"].get("files", 0)) > 0 for s in warm_spans), spans
        compile_spans = by_name.get("harness.compile", [])
        assert any(s["attrs"].get("cache_hit") for s in compile_spans)
        assert all(s["attrs"].get("signature") for s in compile_spans)
    finally:
        cluster.stop()
