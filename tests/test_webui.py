"""WebUI smoke test: the master serves the SPA, and the exact API sequence
the app makes (login → experiments → detail → trials → metrics → agents →
job queue) returns the shapes the JS consumes.

Reference: webui/react served by the Go master; no browser ships in the test
image, so this drives the app's own request sequence over HTTP. (Manual
browser pass: see .claude/skills/verify.)"""

import json
import os
import re
import time
import urllib.error
import urllib.request

import pytest

from tests.test_platform_e2e import (  # noqa: F401
    Devcluster,
    FIXTURES,
    _create_experiment,
    _experiment_config,
    _wait_experiment,
    native_binaries,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def cluster(tmp_path, native_binaries):  # noqa: F811
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    c.start_agent()
    yield c
    c.stop()


def _get(url, content_type=None):
    with urllib.request.urlopen(url, timeout=10) as r:
        if content_type:
            assert r.headers.get("Content-Type", "").startswith(content_type)
        return r.read().decode()


def test_static_serving(cluster):
    html = _get(cluster.master_url + "/", "text/html")
    assert "<title>determined-tpu</title>" in html
    # assets referenced by the shell exist and carry correct types
    for ref, ctype in (("/ui/app.js", "application/javascript"),
                       ("/ui/style.css", "text/css")):
        assert ref in html
        body = _get(cluster.master_url + ref, ctype)
        assert body.strip()
    # traversal is rejected
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(cluster.master_url + "/ui/../master/db.cc")
    assert ei.value.code == 404


def test_app_api_sequence(cluster, tmp_path):
    """Every endpoint + field the SPA reads, end-to-end with a real run."""
    eid, token = _create_experiment(
        cluster, _experiment_config(tmp_path), activate=True)
    _wait_experiment(cluster, eid, token)

    exps = cluster.api("GET", "/api/v1/experiments", token=token)["experiments"]
    e = next(x for x in exps if x["id"] == eid)
    assert e["name"] == "e2e-fixture"
    assert e["state"] == "COMPLETED"
    assert e["config"]["searcher"]["name"] == "single"

    detail = cluster.api(
        "GET", f"/api/v1/experiments/{eid}", token=token)["experiment"]
    assert detail["config"]["resources"]["slots_per_trial"] == 1

    trials = cluster.api(
        "GET", f"/api/v1/experiments/{eid}/trials", token=token)["trials"]
    assert trials and trials[0]["state"] == "COMPLETED"

    metrics = cluster.api(
        "GET", f"/api/v1/trials/{trials[0]['id']}/metrics", token=token
    )["metrics"]
    # the chart builder needs group_name, total_batches, numeric metrics
    train_pts = [(m["total_batches"], m["metrics"].get("loss"))
                 for m in metrics if m["group_name"] == "training"]
    assert train_pts and all(
        isinstance(x, int) and isinstance(y, float) for x, y in train_pts)
    val_pts = [m for m in metrics if m["group_name"] == "validation"]
    assert val_pts and "val_loss" in val_pts[-1]["metrics"]

    agents = cluster.api("GET", "/api/v1/agents", token=token)["agents"]
    assert agents[0]["slots"] and {"id", "enabled", "allocation_id"} <= set(
        agents[0]["slots"][0])

    jobs = cluster.api("GET", "/api/v1/job-queues", token=token)["jobs"]
    assert isinstance(jobs, list)  # drained after completion


def test_trial_log_viewer_flow(cluster, tmp_path):
    """The trial page's log viewer: paged fetch by offset, then a follow
    long-poll that returns promptly once lines exist (reference TrialLogs)."""
    eid, token = _create_experiment(
        cluster, _experiment_config(tmp_path), activate=True)
    _wait_experiment(cluster, eid, token)
    trials = cluster.api(
        "GET", f"/api/v1/experiments/{eid}/trials", token=token)["trials"]
    tid = trials[0]["id"]

    # trial metadata the page header reads
    t = cluster.api("GET", f"/api/v1/trials/{tid}", token=token)["trial"]
    assert t["experiment_id"] == eid and t["total_batches"] >= 8

    # paged fetch exactly as the viewer does
    offset, lines = 0, []
    while True:
        logs = cluster.api(
            "GET",
            f"/api/v1/tasks/trial-{tid}/logs?offset={offset}&follow=false",
            token=token)["logs"]
        if not logs:
            break
        for line in logs:
            offset = max(offset, line["id"])
            lines.append(line["log"])
        assert all({"id", "log"} <= set(line) for line in logs)
    assert any("trial complete" in line for line in lines)

    # follow=true from a fresh offset returns immediately with data
    logs = cluster.api(
        "GET",
        f"/api/v1/tasks/trial-{tid}/logs?offset=0&follow=true"
        f"&timeout_seconds=5",
        token=token)["logs"]
    assert logs


def test_hp_search_view_data(cluster, tmp_path):
    """The experiment page's HP table + hparam-vs-metric scatter need per-
    trial hparams and searcher_metric_value from an adaptive search."""
    searcher = {
        "name": "adaptive_asha", "metric": "val_loss",
        "max_length": {"batches": 8}, "max_trials": 4, "max_rungs": 2,
        "divisor": 2, "max_concurrent_trials": 2,
    }
    config = _experiment_config(
        tmp_path, searcher=searcher,
        extra={"hyperparameters": {"lr": {"type": "log", "minval": -2,
                                          "maxval": 0}}})
    eid, token = _create_experiment(cluster, config, activate=True)
    _wait_experiment(cluster, eid, token, timeout=180.0)
    trials = cluster.api(
        "GET", f"/api/v1/experiments/{eid}/trials", token=token)["trials"]
    assert len(trials) == 4
    scored = [t for t in trials if t.get("searcher_metric_value") is not None]
    assert len(scored) >= 2, "scatter needs >=2 scored trials"
    for t in scored:
        assert isinstance(t["hparams"].get("lr"), float)
    # distinct sampled hparams → a real scatter, not a vertical line
    assert len({t["hparams"]["lr"] for t in scored}) >= 2
    # trial-comparison chart data: per-trial validation series exist, and
    # ASHA rung geometry shows as different curve lengths across trials
    lengths = set()
    for t in trials:
        vm = cluster.api(
            "GET", f"/api/v1/trials/{t['id']}/metrics?group=validation",
            token=token)["metrics"]
        assert vm, f"trial {t['id']} has no validation series"
        assert all("val_loss" in m["metrics"] for m in vm)
        lengths.add(max(m["total_batches"] for m in vm))
    assert len(lengths) >= 2, f"expected distinct rung lengths, got {lengths}"


def test_stream_live_update_contract(cluster, tmp_path):
    """The list page's live refresh: an experiment state change surfaces as
    a stream event the follower can react to."""
    token = cluster.login()
    out = cluster.api(
        "GET", "/api/v1/stream?since=0&timeout_seconds=0", token=token)
    since = out["latest_seq"]
    eid, token = _create_experiment(
        cluster, _experiment_config(tmp_path), activate=True)
    _wait_experiment(cluster, eid, token)
    out = cluster.api(
        "GET",
        f"/api/v1/stream?since={since}&entities=experiments"
        f"&timeout_seconds=5",
        token=token)
    assert any(e["entity"] == "experiments" and e["payload"]["id"] == eid
               for e in out["events"])


def test_user_admin_page_data(cluster):
    """The users/admin page's API sequence: list users + me + assignments,
    admin mutations (create / role change / deactivate / grant / revoke)."""
    admin = cluster.login("admin")
    me = cluster.api("GET", "/api/v1/me", token=admin)["user"]
    assert me["role"] == "admin"
    cluster.api("POST", "/api/v1/users",
                {"username": "ui-user", "role": "viewer"}, token=admin)
    users = cluster.api("GET", "/api/v1/users", token=admin)["users"]
    u = next(x for x in users if x["username"] == "ui-user")
    assert u["role"] == "viewer" and u["active"] == 1
    cluster.api("PATCH", f"/api/v1/users/{u['id']}", {"role": "user"},
                token=admin)
    grant = cluster.api("POST", "/api/v1/rbac/assignments",
                        {"role": "editor", "user_id": u["id"],
                         "workspace_id": 1}, token=admin)
    rows = cluster.api("GET", "/api/v1/rbac/assignments",
                       token=admin)["assignments"]
    assert any(r["id"] == grant["id"] and r["username"] == "ui-user"
               for r in rows)
    cluster.api("DELETE", f"/api/v1/rbac/assignments/{grant['id']}",
                token=admin)
    cluster.api("PATCH", f"/api/v1/users/{u['id']}", {"active": False},
                token=admin)
    users = cluster.api("GET", "/api/v1/users", token=admin)["users"]
    assert next(x for x in users if x["id"] == u["id"])["active"] == 0


def test_app_js_references_real_endpoints(cluster):
    """Static check: every /api/v1 path in app.js is routed by the master
    (no dead fetches shipped in the UI)."""
    js = _get(cluster.master_url + "/ui/app.js")
    token = cluster.login()
    paths = set(re.findall(r'"(/api/v1/[a-z\-/]+)', js))
    assert paths  # sanity
    for p in paths:
        if p.startswith("/api/v1/auth"):
            continue  # POST-only; covered by login itself
        status = 0
        req = urllib.request.Request(
            cluster.master_url + p,
            headers={"Authorization": f"Bearer {token}"})
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                status = r.status
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 200, f"{p} -> {status}"


def test_tasks_page_and_kill_flow(cluster):
    """The Tasks page's API sequence: list → kill (per-kind route) →
    state reflects the outcome (VERDICT r4 #9 NTSC/tasks page)."""
    token = cluster.login()
    tid = cluster.api("POST", "/api/v1/commands",
                      {"config": {"entrypoint": "sleep 600"}},
                      token=token)["id"]
    tasks = cluster.api("GET", "/api/v1/tasks", token=token)["tasks"]
    mine = [t for t in tasks if t["id"] == tid]
    assert mine and mine[0]["type"] == "COMMAND"
    # the kill button's route for COMMAND
    cluster.api("POST", f"/api/v1/commands/{tid}/kill", token=token)
    import time as _t
    deadline = _t.time() + 30
    while _t.time() < deadline:
        t = cluster.api("GET", f"/api/v1/commands/{tid}", token=token)["task"]
        if t["state"] in ("COMPLETED", "ERROR", "CANCELED"):
            break
        _t.sleep(0.2)
    assert t["state"] in ("COMPLETED", "ERROR", "CANCELED")


def test_admin_page_webhook_template_flow(cluster):
    """The Admin page's API sequence: webhook + template CRUD."""
    admin = cluster.login("admin")
    hook = cluster.api("POST", "/api/v1/webhooks",
                       {"url": "http://127.0.0.1:1/x",
                        "triggers": [{"trigger_type":
                                      "EXPERIMENT_STATE_CHANGE",
                                      "condition": {"state": "COMPLETED"}}]},
                       token=admin)
    hid = hook.get("id") or hook.get("webhook", {}).get("id")
    hooks = cluster.api("GET", "/api/v1/webhooks", token=admin)["webhooks"]
    assert any(h["id"] == hid for h in hooks)
    cluster.api("DELETE", f"/api/v1/webhooks/{hid}", token=admin)

    cluster.api("POST", "/api/v1/templates",
                {"name": "ui-tpl",
                 "config": {"resources": {"slots_per_trial": 2}}},
                token=admin)
    tpls = cluster.api("GET", "/api/v1/templates", token=admin)["templates"]
    assert any(t["name"] == "ui-tpl" for t in tpls)
    cluster.api("DELETE", "/api/v1/templates/ui-tpl", token=admin)


def test_experiments_pagination(cluster, tmp_path):
    """Server-side pagination the experiments page rides: limit/offset +
    total (VERDICT r4 #9: no list endpoint rendered whole)."""
    token = None
    for i in range(5):
        cfg = _experiment_config(tmp_path)
        cfg["name"] = f"pg-{i}"
        _, token = _create_experiment(cluster, cfg, activate=False)
    page1 = cluster.api("GET", "/api/v1/experiments?limit=2&offset=0",
                        token=token)
    assert len(page1["experiments"]) == 2
    assert page1["pagination"]["total"] == 5
    page3 = cluster.api("GET", "/api/v1/experiments?limit=2&offset=4",
                        token=token)
    assert len(page3["experiments"]) == 1
    ids = {e["id"] for e in page1["experiments"]} | \
        {e["id"] for e in page3["experiments"]}
    assert len(ids) == 3  # pages don't overlap


def test_model_version_detail_flow(cluster, tmp_path):
    """Model registry version rows expand to the backing checkpoint —
    the page's API sequence: versions → checkpoint detail."""
    eid, token = _create_experiment(
        cluster, _experiment_config(tmp_path), activate=True)
    _wait_experiment(cluster, eid, token)
    cps = cluster.api("GET", f"/api/v1/experiments/{eid}/checkpoints",
                      token=token)["checkpoints"]
    # Only COMMITTED checkpoints register (docs/serving.md "Model
    # lifecycle" — a version is a serving promise, PARTIALs refuse).
    cps = [c for c in cps if c["state"] == "COMPLETED"]
    assert cps
    cluster.api("POST", "/api/v1/models",
                {"name": "ui-model", "description": "", "metadata": {},
                 "labels": []}, token=token)
    cluster.api("POST", "/api/v1/models/ui-model/versions",
                {"checkpoint_uuid": cps[0]["uuid"], "metadata": {}},
                token=token)
    versions = cluster.api("GET", "/api/v1/models/ui-model/versions",
                           token=token)["model_versions"]
    assert versions
    ck = cluster.api(
        "GET", f"/api/v1/checkpoints/{versions[0]['checkpoint_uuid']}",
        token=token)["checkpoint"]
    assert ck["uuid"] == cps[0]["uuid"]
    assert "steps_completed" in ck


# ---------------------------------------------------------------------------
# WebUI JS execution harness (VERDICT weak #4). No JS engine ships in the
# test image, so the JS is "executed" at the data-binding level: the
# generated api_client.js is parsed into its operation table and checked
# against the served OpenAPI document, every `API.x(...)` call site in
# app.js must resolve to a generated operation, and the fields each view
# function dereferences on API payloads are EXTRACTED FROM THE JS SOURCE
# and asserted present on live master responses — if app.js starts
# reading a field the API stopped (or never started) serving, these fail.
# ---------------------------------------------------------------------------

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CLIENT_OP_RE = re.compile(
    r"^\s*(?P<name>\w+): \((?P<args>[^)]*)\) => "
    r"api\('(?P<method>[A-Z]+)', (?P<path>[`'][^`']+[`'])",
    re.M)


def _js(name):
    with open(os.path.join(REPO_ROOT, "webui", name)) as f:
        return f.read()


def _parse_api_client():
    """api_client.js → {opName: (METHOD, /api/v1/... template)} with JS
    `${x}` path params normalized back to the spec's {x} form."""
    ops = {}
    for m in _CLIENT_OP_RE.finditer(_js("api_client.js")):
        path = m.group("path").strip("`'")
        path = re.sub(r"\$\{(\w+)\}", r"{\1}", path)
        ops[m.group("name")] = (m.group("method"), path)
    return ops


def _fn_body(js, name):
    """Body of `async function <name>(...)` by brace matching."""
    start = js.index(f"async function {name}")
    i = js.index("{", start)
    depth = 0
    for j in range(i, len(js)):
        if js[j] == "{":
            depth += 1
        elif js[j] == "}":
            depth -= 1
            if depth == 0:
                return js[i:j + 1]
    raise AssertionError(f"unbalanced braces in {name}")


def _fields_read(body, var):
    """Every `<var>.<field>` the view dereferences (incl. optional
    chaining), minus JS builtins that aren't payload fields."""
    builtins = {"map", "filter", "length", "toFixed", "includes", "push",
                "join", "forEach", "entries", "keys", "slice", "sort"}
    return {f for f in re.findall(rf"\b{var}(?:\?)?\.(\w+)", body)
            if f not in builtins}


def test_api_client_operations_match_openapi():
    """The generated client and the spec cannot drift: one client op per
    spec operation, with the same method + path template."""
    ops = _parse_api_client()
    with open(os.path.join(REPO_ROOT, "proto", "openapi.json")) as f:
        spec = json.load(f)
    spec_ops = {(m.upper(), p)
                for p, methods in spec["paths"].items() for m in methods}
    client_ops = set(ops.values())
    assert client_ops == spec_ops, (
        f"client-only: {sorted(client_ops - spec_ops)}; "
        f"spec-only: {sorted(spec_ops - client_ops)}")
    # The lifecycle surface shipped (docs/serving.md "Model lifecycle").
    for needed in ("postDeploymentsIdUpdate", "postDeploymentsIdCanary",
                   "getModelsNameVersionsV"):
        assert needed in ops, sorted(ops)


def test_app_js_api_calls_resolve():
    """Every API.<op>( call site in app.js exists in the generated
    client — a renamed/removed operation fails here, not as a runtime
    TypeError in the browser."""
    ops = _parse_api_client()
    calls = set(re.findall(r"\bAPI\.(\w+)\(", _js("app.js")))
    assert calls, "app.js makes no API calls?"
    missing = calls - set(ops)
    assert not missing, f"app.js calls unknown client ops: {sorted(missing)}"


def test_serving_and_model_views_bind_live_payloads(cluster):
    """Execute the Serving / deployment-detail / Models views' data
    bindings against a REAL master: every field the JS reads from each
    response object must exist on the live payload (the view field sets
    are extracted from app.js, so UI↔API drift fails in either
    direction). The fixture deployment carries a model version AND an
    active canary so the new lifecycle bindings are exercised."""
    token = cluster.login()
    # Registry fixtures: model + two versions over committed checkpoints.
    cluster.api("POST", "/api/v1/models",
                {"name": "ui-bind", "metadata": {}, "labels": []},
                token=token)
    for uuid in ("ui-ck-1", "ui-ck-2"):
        cluster.api("POST", "/api/v1/checkpoints",
                    {"uuid": uuid, "state": "COMPLETED"}, token=token)
        cluster.api("POST", "/api/v1/models/ui-bind/versions",
                    {"checkpoint_uuid": uuid}, token=token)
    # A live deployment on version 1 with a canary split on version 2.
    dep_cfg = {
        "name": "ui-dep",
        "entrypoint": "python3 -m tests.fixtures.serving.fake_replica",
        "serving": {"model": "gpt2", "model_version": "ui-bind:1",
                    "replicas": {"min": 1, "max": 2, "target": 1}},
        "resources": {"slots_per_trial": 0},
        "environment": {"DET_FAKE_HEARTBEAT_S": "0.3"},
    }
    dep_id = cluster.api("POST", "/api/v1/deployments",
                         {"config": dep_cfg}, token=token)["id"]
    cluster.api("POST", f"/api/v1/deployments/{dep_id}/canary",
                {"model": "ui-bind", "version": 2, "fraction": 0.25},
                token=token)
    # Wait until both replicas heartbeat so latency/report fields exist.
    deadline = time.time() + 90
    detail = {}
    while time.time() < deadline:
        detail = cluster.api("GET", f"/api/v1/deployments/{dep_id}",
                             token=token)["deployment"]
        fresh = [r for r in detail.get("replicas", [])
                 if r.get("allocation_state") == "RUNNING"
                 and 0 <= (r.get("report_age_s") or -1) < 10]
        if len(fresh) == 2:
            break
        time.sleep(0.3)
    assert len(detail.get("replicas", [])) == 2, detail

    js = _js("app.js")

    # pageServing: deployments table binds `d.*`, tasks table binds `t.*`.
    serving_body = _fn_body(js, "pageServing")
    deployments = cluster.api("GET", "/api/v1/deployments",
                              token=token)["deployments"]
    assert deployments
    d = deployments[0]
    for field in _fields_read(serving_body, "d"):
        assert field in d, f"pageServing reads d.{field}; payload: {sorted(d)}"
    # The lifecycle columns really render from the payload.
    assert d["model_version"] == "ui-bind:1"
    assert d["canary"]["version"] == "ui-bind:2"
    serving_tasks = cluster.api("GET", "/api/v1/serving",
                                token=token)["serving"]
    assert serving_tasks
    t0 = serving_tasks[0]
    for field in _fields_read(serving_body, "t"):
        assert field in t0, (
            f"pageServing reads t.{field}; payload: {sorted(t0)}")

    # pageDeployment: header + latency tables bind `d.*`, replica rows
    # bind `r.*`, slow-request rows bind `s.*`. `swap` only exists while
    # a rollout is in flight.
    detail_body = _fn_body(js, "pageDeployment")
    optional = {"swap"}
    for field in _fields_read(detail_body, "d") - optional:
        assert field in detail, (
            f"pageDeployment reads d.{field}; payload: {sorted(detail)}")
    r0 = detail["replicas"][0]
    for field in _fields_read(detail_body, "r"):
        assert field in r0, (
            f"pageDeployment reads r.{field}; payload: {sorted(r0)}")
    assert {"ui-bind:1", "ui-bind:2"} == {
        r["model_version"] for r in detail["replicas"]}

    # pageModels: model rows bind `m.*`, version rows bind `v.*`.
    models_body = _fn_body(js, "pageModels")
    models = cluster.api("GET", "/api/v1/models", token=token)["models"]
    m0 = next(m for m in models if m["name"] == "ui-bind")
    for field in _fields_read(models_body, "m"):
        assert field in m0, (
            f"pageModels reads m.{field}; payload: {sorted(m0)}")
    versions = cluster.api("GET", "/api/v1/models/ui-bind/versions",
                           token=token)["model_versions"]
    v0 = versions[0]
    for field in _fields_read(models_body, "v"):
        assert field in v0, (
            f"pageModels reads v.{field}; payload: {sorted(v0)}")

    cluster.api("POST", f"/api/v1/deployments/{dep_id}/kill", token=token)
