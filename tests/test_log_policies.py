"""Log-pattern policies e2e (reference logpattern/logpattern.go:232 +
schemas/expconf/v0/log-policy.json): regexes over shipped task logs drive
cancel_retries / exclude_node actions."""

import time

import pytest

from determined_tpu import expconf
from tests.test_platform_e2e import (  # noqa: F401
    Devcluster,
    _create_experiment,
    _experiment_config,
    _wait_experiment,
    native_binaries,
)


@pytest.fixture()
def cluster(tmp_path, native_binaries):  # noqa: F811
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    c.start_agent()
    yield c
    c.stop()


class TestExpconfValidation:
    def base(self, policies):
        return {
            "entrypoint": "python3 t.py",
            "searcher": {"name": "single", "metric": "m",
                         "max_length": {"batches": 1}},
            "log_policies": policies,
        }

    def test_valid(self):
        assert expconf.validate(self.base([
            {"pattern": ".*OOM.*", "action": {"type": "cancel_retries"}},
            {"pattern": "bad node", "action": "exclude_node"},
        ])) == []

    def test_bad_regex(self):
        errs = expconf.validate(self.base([
            {"pattern": "(unclosed", "action": "cancel_retries"}]))
        assert any("invalid regex" in e for e in errs)

    def test_bad_action(self):
        errs = expconf.validate(self.base([
            {"pattern": "x", "action": "explode"}]))
        assert any("cancel_retries or" in e for e in errs)


def test_cancel_retries_policy(cluster, tmp_path):
    """A matching fatal line stops retries: trial ERRORs with 0 restarts
    despite max_restarts=3."""
    config = _experiment_config(tmp_path)
    config["entrypoint"] = "python3 crash_train.py"
    config["max_restarts"] = 3
    config["log_policies"] = [
        {"pattern": "UNRECOVERABLE_CONDITION",
         "action": {"type": "cancel_retries"}},
    ]
    eid, token = _create_experiment(cluster, config, activate=True)
    _wait_experiment(cluster, eid, token, want=("ERROR",))
    trials = cluster.api("GET", f"/api/v1/experiments/{eid}/trials",
                         token=token)["trials"]
    assert trials[0]["state"] == "ERROR"
    assert trials[0]["restarts"] == 0, trials[0]


def test_without_policy_retries_happen(cluster, tmp_path):
    """Control: same crash without the policy consumes max_restarts."""
    config = _experiment_config(tmp_path)
    config["entrypoint"] = "python3 crash_train.py"
    config["max_restarts"] = 1
    eid, token = _create_experiment(cluster, config, activate=True)
    _wait_experiment(cluster, eid, token, want=("ERROR",))
    trials = cluster.api("GET", f"/api/v1/experiments/{eid}/trials",
                         token=token)["trials"]
    assert trials[0]["restarts"] == 1, trials[0]


def test_exclude_node_policy(cluster, tmp_path):
    """exclude_node: the restart must land on a different agent."""
    import os
    import subprocess

    # second agent so the excluded trial has somewhere to go
    second = subprocess.Popen(
        [os.path.join(cluster.binaries, "determined-agent"),
         "--master-url", cluster.master_url,
         "--id", "agent-1", "--slots", "2", "--slot-type", "cpu",
         "--addr", "127.0.0.1",
         "--work-root", os.path.join(cluster.tmpdir, "agent1-work"),
         "--token-file", cluster.db_path + ".agent_token"],
        env=cluster.env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        token = cluster.login()
        deadline = time.time() + 20
        while time.time() < deadline:
            agents = cluster.api("GET", "/api/v1/agents", token=token)["agents"]
            if sum(1 for a in agents if a["alive"]) == 2:
                break
            time.sleep(0.2)

        config = _experiment_config(tmp_path)
        config["entrypoint"] = "python3 crash_train.py"
        config["max_restarts"] = 1
        config["log_policies"] = [
            {"pattern": "UNRECOVERABLE_CONDITION",
             "action": {"type": "exclude_node"}},
        ]
        eid, token = _create_experiment(cluster, config, activate=True)
        _wait_experiment(cluster, eid, token, want=("ERROR",))
        trials = cluster.api("GET", f"/api/v1/experiments/{eid}/trials",
                             token=token)["trials"]
        assert trials[0]["restarts"] == 1
        # the two runs used two different agents
        logs = cluster.api(
            "GET", f"/api/v1/tasks/trial-{trials[0]['id']}/logs",
            token=token)["logs"]
        used = {l["agent_id"] for l in logs if l.get("agent_id")}
        assert len(used) == 2, used
    finally:
        second.kill()
        second.wait()
