"""Searcher-engine tests via the native simulator.

Mirrors the reference's whole-search simulations
(master/pkg/searcher/simulate.go, asha_test.go, adaptive_asha_test.go):
drive each search method end-to-end with a synthetic metric and check trial
counts, rung geometry, promotion behavior, determinism, and mid-search
snapshot/restore.
"""

import json
import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SIM = os.path.join(REPO, "native", "bin", "searcher_sim")


@pytest.fixture(scope="session")
def sim(native_binaries):
    return SIM


@pytest.fixture(scope="session")
def native_binaries():
    subprocess.run(["make", "-C", os.path.join(REPO, "native")], check=True,
                   capture_output=True)


def run_sim(sim, searcher, hparams=None, seed=7, **kwargs):
    payload = {
        "searcher": searcher,
        "hyperparameters": hparams or {"lr": {"type": "double", "minval": 0,
                                              "maxval": 1}},
        "seed": seed,
        **kwargs,
    }
    out = subprocess.run(
        [sim], input=json.dumps(payload), capture_output=True, text=True,
        check=True,
    )
    return json.loads(out.stdout)


def test_single(sim):
    r = run_sim(sim, {"name": "single", "metric": "loss",
                      "max_length": {"batches": 100}})
    assert r["trials_created"] == 1
    assert r["total_units"] == 100
    assert r["shutdown"]


def test_random(sim):
    r = run_sim(sim, {"name": "random", "metric": "loss", "max_length": 50,
                      "max_trials": 5})
    assert r["trials_created"] == 5
    assert r["total_units"] == 250
    assert all(t["units"] == 50 for t in r["trials"].values())
    assert r["shutdown"]


def test_grid(sim):
    hp = {
        "lr": {"type": "log", "minval": -3, "maxval": -1, "count": 3},
        "bs": {"type": "categorical", "vals": [16, 32]},
        "depth": {"type": "const", "val": 4},
        "nested": {"opt": {"type": "int", "minval": 1, "maxval": 2, "count": 2}},
    }
    r = run_sim(sim, {"name": "grid", "metric": "loss", "max_length": 10}, hp)
    assert r["trials_created"] == 3 * 2 * 1 * 2
    assert r["shutdown"]


def test_asha_rung_geometry_and_promotions(sim):
    # max_length 16, divisor 4, 3 rungs → cumulative rungs 1, 5, 21
    # (reference asha.go:62-66 cumulative units).
    r = run_sim(
        sim,
        {"name": "async_halving", "metric": "loss", "max_length": 16,
         "num_rungs": 3, "divisor": 4, "max_trials": 16,
         "max_concurrent_trials": 16},
    )
    assert r["trials_created"] == 16
    assert r["shutdown"]
    units = sorted(t["units"] for t in r["trials"].values())
    assert set(units) <= {1, 5, 21}
    # 16 trials / divisor 4 → 4 reach rung 1; 4/4 → 1 reaches rung 2.
    assert units.count(21) >= 1
    assert sum(1 for u in units if u >= 5) >= 4


def test_asha_stop_once(sim):
    r = run_sim(
        sim,
        {"name": "async_halving", "metric": "loss", "max_length": 16,
         "num_rungs": 2, "divisor": 4, "max_trials": 8, "stop_once": True},
    )
    assert r["trials_created"] == 8
    assert r["shutdown"]


def test_adaptive_asha_brackets(sim):
    r = run_sim(
        sim,
        {"name": "adaptive_asha", "metric": "loss",
         "max_length": {"batches": 64}, "max_rungs": 3, "divisor": 4,
         "max_trials": 12, "mode": "standard"},
    )
    assert r["trials_created"] == 12
    assert r["shutdown"]
    # standard mode with R=3 → 2 brackets, request ids prefixed b0-/b1-.
    prefixes = {rid.split("-")[0] for rid in r["trials"]}
    assert prefixes == {"b0", "b1"}


def test_determinism(sim):
    cfg = {"name": "random", "metric": "loss", "max_length": 10,
           "max_trials": 4}
    r1 = run_sim(sim, cfg, seed=123)
    r2 = run_sim(sim, cfg, seed=123)
    assert r1 == r2
    r3 = run_sim(sim, cfg, seed=124)
    assert r3["best_metric"] != r1["best_metric"]


def test_snapshot_restore_midway(sim):
    """Snapshot + restore mid-search must not change the outcome
    (reference restore.go exact-resume semantics)."""
    cfg = {"name": "async_halving", "metric": "loss", "max_length": 16,
           "num_rungs": 3, "divisor": 4, "max_trials": 16,
           "max_concurrent_trials": 16}
    base = run_sim(sim, cfg, seed=99)
    restored = run_sim(sim, cfg, seed=99, restore_midway=True)
    assert base == restored


def test_smaller_is_better_false(sim):
    cfg = {"name": "async_halving", "metric": "acc", "smaller_is_better": False,
           "max_length": 16, "num_rungs": 2, "divisor": 2, "max_trials": 4}
    r = run_sim(sim, cfg)
    assert r["shutdown"]
    # With larger-is-better, promoted (longer-trained) trials are the ones
    # with the HIGHEST raw metric among rung-0 peers.
    trials = list(r["trials"].values())
    top = max(trials, key=lambda t: t["units"])
    assert top["units"] > min(t["units"] for t in trials)
