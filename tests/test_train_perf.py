"""Training-perf contracts (docs/training-perf.md): bf16 attention parity,
overlap-allgather scan equivalence, and the pre-partitioned step-input
contract (no-reshard compiled HLO + bit-identical batch order)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec

from determined_tpu.data.prefetch import DevicePrefetcher
from determined_tpu.models import gpt2
from determined_tpu.parallel import MeshConfig, create_mesh
from determined_tpu.parallel.sharding import LogicalRules
from determined_tpu.train import create_train_state, make_train_step
from determined_tpu.train.step import step_input_shardings

VOCAB = 256


def _cfg(**over):
    kw = dict(vocab_size=VOCAB, n_positions=128, d_model=64, n_layer=2,
              n_head=4, remat=False, attention_impl="reference")
    kw.update(over)
    return gpt2.Config(**kw)


def _batches(n, b=8, s=128, seed=0):
    rng = np.random.default_rng(seed)
    return [{"tokens": rng.integers(0, VOCAB, size=(b, s + 1))
             .astype(np.int32)} for _ in range(n)]


class TestBf16AttentionParity:
    """`optimizations.attention_bf16` keeps only the probability matmuls in
    bf16 (softmax stats stay fp32), so the loss trajectory must track the
    f32 attention path within the documented tolerance (|Δloss| < 0.05 over
    the first 8 steps at this scale — docs/training-perf.md)."""

    def _trajectory(self, bf16):
        cfg = _cfg(attention_bf16=bf16)
        tx = optax.adamw(1e-3)
        state = create_train_state(lambda r: gpt2.init(r, cfg), tx,
                                   jax.random.PRNGKey(0))
        step = make_train_step(lambda p, b, r: gpt2.loss_fn(p, b, cfg), tx)
        losses = []
        for i, batch in enumerate(_batches(8)):
            state, m = step(state, batch, jax.random.PRNGKey(i))
            losses.append(float(m["loss"]))
        return np.asarray(losses)

    def test_loss_trajectory_parity(self):
        f32 = self._trajectory(False)
        bf16 = self._trajectory(True)
        assert np.all(np.isfinite(bf16)), bf16
        assert float(np.max(np.abs(f32 - bf16))) < 0.05, (f32, bf16)
        # and it actually trains, not a frozen graph
        assert bf16[-1] < bf16[0], bf16


class TestOverlapAllgather:
    """`optimizations.overlap_allgather` restructures the layer scan (one-
    layer-ahead param gather) without changing the arithmetic: loss and
    grads must match the plain scan."""

    @pytest.mark.parametrize("remat", [False, True])
    def test_matches_plain_scan(self, devices, remat):
        mesh = create_mesh(MeshConfig(data=2, fsdp=4), devices)
        rules = LogicalRules()
        plain, ov = _cfg(remat=remat), _cfg(remat=remat,
                                            overlap_allgather=True)
        params = gpt2.init(jax.random.PRNGKey(0), plain)
        batch = _batches(1)[0]

        def run(cfg):
            with jax.sharding.set_mesh(mesh):
                lfn = lambda p: gpt2.loss_fn(p, batch, cfg, rules)
                loss, grads = jax.jit(jax.value_and_grad(lfn))(params)
            return float(loss), grads

        l0, g0 = run(plain)
        l1, g1 = run(ov)
        assert abs(l0 - l1) < 1e-4, (l0, l1)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                atol=1e-3, rtol=1e-3),
            g0, g1)

    def test_no_mesh_falls_back_to_plain_scan(self):
        # rules=None (no mesh): overlap must be a silent no-op, not a crash.
        cfg = _cfg(overlap_allgather=True)
        params = gpt2.init(jax.random.PRNGKey(0), cfg)
        loss = float(gpt2.loss_fn(params, _batches(1)[0], cfg))
        assert np.isfinite(loss)


class TestPrepartitionedInputs:
    """`optimizations.prepartition_inputs`: the DevicePrefetcher places
    batches with the jitted step's exact input NamedShardings, and the
    step declares them as in_shardings — so the compiled executable finds
    its inputs already laid out and inserts no resharding before the
    first layer."""

    def test_compiled_step_has_no_resharding(self, devices):
        mesh = create_mesh(MeshConfig(data=2, fsdp=4), devices)
        rules = LogicalRules()
        cfg = _cfg()
        tx = optax.adamw(1e-3)
        in_shard = step_input_shardings(mesh, rules)
        state = create_train_state(lambda r: gpt2.init(r, cfg), tx,
                                   jax.random.PRNGKey(0))
        step = make_train_step(
            lambda p, b, r: gpt2.loss_fn(p, b, cfg, rules), tx,
            mesh=mesh, rules=rules, input_sharding=in_shard)
        batch = _batches(1)[0]
        compiled = step.lower(state, batch, jax.random.PRNGKey(1)).compile()

        # (a) the compiled argument layout IS the declared batch layout —
        # the prefetcher's device_put layout arrives ready to consume.
        flat_in, _ = jax.tree_util.tree_flatten(compiled.input_shardings[0])
        batch_spec = PartitionSpec(rules.mesh_axes("batch"))
        assert any(getattr(s, "spec", None) == batch_spec for s in flat_in), \
            flat_in
        # (b) no resharding collective precedes the first layer: a layout
        # mismatch on entry shows up as all-to-all / collective-permute in
        # the compiled module.
        txt = compiled.as_text()
        assert "all-to-all" not in txt, "input resharding in compiled HLO"
        assert "collective-permute" not in txt, \
            "input resharding in compiled HLO"

        # and the step still runs end to end from prefetcher-placed inputs.
        placed = jax.device_put(batch, in_shard)
        state2, m = step(state, placed, jax.random.PRNGKey(1))
        assert np.isfinite(float(m["loss"]))

    def test_prefetcher_batch_order_bit_identical(self, devices):
        mesh = create_mesh(MeshConfig(data=2, fsdp=4), devices)
        rules = LogicalRules()
        shard = step_input_shardings(mesh, rules)
        batches = _batches(6, seed=3)
        got = []
        with DevicePrefetcher(iter(list(batches)), sharding=shard,
                              depth=2) as pf:
            for b in pf:
                got.append(b)
        assert len(got) == len(batches)
        expected_spec = PartitionSpec(rules.mesh_axes("batch"))
        for host, dev in zip(batches, got):
            # placed with the step's declared sharding...
            assert dev["tokens"].sharding.spec == expected_spec
            # ...and bit-identical to the host batch, in order.
            np.testing.assert_array_equal(np.asarray(dev["tokens"]),
                                          host["tokens"])

    def test_input_shardings_per_leaf_tree(self, devices):
        mesh = create_mesh(MeshConfig(data=8), devices)
        batch = {"tokens": np.zeros((8, 129), np.int32),
                 "scale": np.float32(1.0)}
        tree = step_input_shardings(mesh, batch=batch)
        # array leaves get the batch sharding; sub-rank leaves replicate
        assert tree["tokens"].spec == PartitionSpec(
            LogicalRules().mesh_axes("batch"))
        assert tree["scale"].spec == PartitionSpec()
        # multi-step window layout: steps axis unsharded
        win = step_input_shardings(mesh, leading_dims=2)
        assert win.spec == PartitionSpec(
            None, LogicalRules().mesh_axes("batch"))
