"""Preflight analyzer tests — every shipped DTL rule, positive + negative.

Engine coverage:
  abstract (DTL001-DTL005): inline trial classes driven through
      analysis.abstract.analyze_trial (no AST involvement).
  AST lint (DTL101-DTL104): source strings through analysis.lint_source.
  config   (DTL201-DTL202): dicts through analysis.check_config (the
      native master mirror is covered by native/tests/test_native.cc).
  end-to-end: the tests/fixtures/preflight/{bad,clean} pair through the
      real `det preflight` CLI — the acceptance contract: bad reports
      exactly {DTL001, DTL002, DTL101}, clean reports nothing.
"""

import json
import os

import jax
import numpy as np

from determined_tpu.analysis import RULES, check_config
from determined_tpu.analysis.abstract import analyze_trial
from determined_tpu.analysis.astlint import lint_source
from determined_tpu.train.trial import JaxTrial, TrialContext

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "preflight")


def codes(diags):
    return sorted({d.code for d in diags if not d.suppressed})


# ---------------------------------------------------------------------------
# abstract engine (DTL001-DTL005)
# ---------------------------------------------------------------------------


class SmallTrial(JaxTrial):
    """Clean baseline: small params, divisible batch, donation on."""

    def __init__(self, context, batch=32):
        super().__init__(context)
        self._batch = batch

    def init_params(self, rng):
        return {"w": jax.random.normal(rng, (16, 8)) * 0.1}

    def loss(self, params, batch, rng):
        logits = batch["x"] @ params["w"]
        return jax.numpy.mean((logits - batch["y"]) ** 2)

    def build_training_data(self):
        while True:
            yield {
                "x": np.zeros((self._batch, 16), np.float32),
                "y": np.zeros((self._batch, 8), np.float32),
            }


class NoDonateTrial(SmallTrial):
    donate_state = False


class BigReplicatedTrial(SmallTrial):
    """One 32 MiB leaf, no logical axes -> replicated on every chip."""

    def init_params(self, rng):
        return {"emb": jax.random.normal(rng, (32768, 256))}

    def loss(self, params, batch, rng):
        return jax.numpy.mean(params["emb"]) * jax.numpy.mean(batch["x"])


class BigShardedTrial(BigReplicatedTrial):
    """Same leaf, annotated; under mesh fsdp=4 it shards -> no DTL002."""

    def param_logical_axes(self):
        return {"emb": ("embed", None)}  # embed -> fsdp

    def mesh_config(self):
        from determined_tpu.parallel.mesh import MeshConfig

        return MeshConfig(data=2, fsdp=4)


class BrokenLossTrial(SmallTrial):
    def loss(self, params, batch, rng):
        return batch["x"] @ params["w"] @ batch["x"]  # shape error


def _ctx(**hp):
    return TrialContext(hparams=hp, n_devices=8)


class TestAbstractEngine:
    def test_clean_trial_no_diagnostics(self):
        diags, hbm, _ = analyze_trial(SmallTrial(_ctx()), 8)
        assert codes(diags) == []
        assert hbm["total_bytes"] > 0
        assert hbm["donated"] is True

    def test_dtl001_not_donated(self):
        diags, hbm, _ = analyze_trial(NoDonateTrial(_ctx()), 8)
        assert codes(diags) == ["DTL001"]
        assert hbm["donation_extra_bytes"] == (
            hbm["params_bytes"] + hbm["opt_state_bytes"])

    def test_dtl002_replicated_large_leaf(self):
        diags, _, _ = analyze_trial(BigReplicatedTrial(_ctx()), 8)
        assert codes(diags) == ["DTL002"]
        assert "emb" in diags[0].message

    def test_dtl002_negative_when_sharded(self):
        diags, hbm, _ = analyze_trial(BigShardedTrial(_ctx()), 8)
        assert codes(diags) == []
        # fsdp=4 shards the 32 MiB leaf -> 8 MiB per device.
        assert hbm["params_bytes"] == 32 * 2**20 // 4

    def test_dtl002_negative_single_device(self):
        diags, _, _ = analyze_trial(BigReplicatedTrial(
            TrialContext(hparams={}, n_devices=1)), 1)
        assert codes(diags) == []

    def test_dtl003_batch_not_divisible(self):
        diags, _, _ = analyze_trial(SmallTrial(_ctx(), batch=30), 8)
        assert codes(diags) == ["DTL003"]
        assert diags[0].level == "error"

    def test_dtl003_negative_divisible(self):
        diags, _, _ = analyze_trial(SmallTrial(_ctx(), batch=32), 8)
        assert codes(diags) == []

    def test_dtl004_hbm_over_budget(self):
        diags, _, _ = analyze_trial(
            BigReplicatedTrial(_ctx()), 8, hbm_budget_bytes=16 * 2**20)
        assert "DTL004" in codes(diags)

    def test_dtl004_negative_under_budget(self):
        diags, _, _ = analyze_trial(
            SmallTrial(_ctx()), 8, hbm_budget_bytes=2**30)
        assert "DTL004" not in codes(diags)

    def test_dtl005_trace_failure(self):
        diags, _, _ = analyze_trial(BrokenLossTrial(_ctx()), 8)
        assert codes(diags) == ["DTL005"]

    def test_dtl005_excused_by_ast_finding(self):
        diags, _, notes = analyze_trial(
            BrokenLossTrial(_ctx()), 8, trace_failure_excused=True)
        assert codes(diags) == []
        assert any("does not trace" in n for n in notes)

    def test_hbm_footprint_scales_with_mesh(self):
        _, hbm8, _ = analyze_trial(BigShardedTrial(_ctx()), 8)
        _, hbm1, _ = analyze_trial(
            BigReplicatedTrial(TrialContext(hparams={}, n_devices=1)), 1)
        assert hbm8["params_bytes"] * 4 == hbm1["params_bytes"]


# ---------------------------------------------------------------------------
# AST lint engine (DTL101-DTL104)
# ---------------------------------------------------------------------------


def _lint(body, cls_extra=""):
    src = (
        "import time, random\n"
        "import numpy as np\n"
        "import jax\n"
        "from determined_tpu.train import JaxTrial\n"
        "class T(JaxTrial):\n"
        "    def init_params(self, rng):\n"
        "        return {}\n"
        f"{cls_extra}"
        "    def loss(self, params, batch, rng):\n"
        f"{body}"
        "        return batch\n"
    )
    return lint_source(src, "t.py")


class TestAstEngine:
    def test_dtl101_item(self):
        assert codes(_lint("        x = batch.sum().item()\n")) == ["DTL101"]

    def test_dtl101_device_get(self):
        assert codes(_lint("        x = jax.device_get(batch)\n")) == [
            "DTL101"]

    def test_dtl101_block_until_ready(self):
        assert codes(_lint("        batch.block_until_ready()\n")) == [
            "DTL101"]

    def test_dtl101_np_asarray_on_value(self):
        assert codes(_lint("        x = np.asarray(batch)\n")) == ["DTL101"]

    def test_dtl101_negative_np_constant(self):
        # np.asarray of a literal is a trace-time constant: fine.
        assert codes(_lint("        x = np.asarray([1.0, 2.0])\n")) == []

    def test_dtl101_negative_outside_traced(self):
        src = (
            "import jax\n"
            "def report(metrics):\n"
            "    return {k: v.item() for k, v in metrics.items()}\n"
        )
        assert codes(lint_source(src, "t.py")) == []

    def test_dtl102_python_rng(self):
        assert codes(_lint("        x = random.random()\n")) == ["DTL102"]
        assert codes(_lint("        x = np.random.normal()\n")) == ["DTL102"]

    def test_dtl102_negative_jax_rng(self):
        assert codes(_lint("        x = jax.random.normal(rng, (2,))\n")) == []

    def test_dtl103_wall_clock(self):
        assert codes(_lint("        t = time.time()\n")) == ["DTL103"]

    def test_dtl103_negative_outside_traced(self):
        src = "import time\ndef tick():\n    return time.time()\n"
        assert codes(lint_source(src, "t.py")) == []

    def test_dtl104_shape_branch(self):
        out = _lint("        if batch.shape[0] > 2:\n            pass\n")
        assert codes(out) == ["DTL104"]

    def test_dtl104_while_len(self):
        out = _lint("        while len(batch) > 2:\n            pass\n")
        assert codes(out) == ["DTL104"]

    def test_dtl104_negative_plain_reshape(self):
        # Using .shape outside a branch is normal traced code.
        assert codes(_lint(
            "        x = batch.reshape(batch.shape[0], -1)\n")) == []

    def test_noqa_line_suppression(self):
        out = _lint("        x = batch.sum().item()  # det: noqa[DTL101]\n")
        assert codes(out) == []
        assert [d.code for d in out if d.suppressed] == ["DTL101"]

    def test_noqa_bare_suppresses_all(self):
        out = _lint("        x = batch.sum().item()  # det: noqa\n")
        assert codes(out) == []

    def test_noqa_wrong_code_does_not_suppress(self):
        out = _lint("        x = batch.sum().item()  # det: noqa[DTL104]\n")
        assert codes(out) == ["DTL101"]

    def test_jit_factory_idiom_is_traced(self):
        src = (
            "import jax, time\n"
            "def make_step(loss):\n"
            "    def step(state, batch):\n"
            "        t = time.time()\n"
            "        return state\n"
            "    return jax.jit(step, donate_argnums=(0,))\n"
        )
        assert codes(lint_source(src, "t.py")) == ["DTL103"]

    def test_module_loss_fn_closure(self):
        src = (
            "import time\n"
            "def _helper(x):\n"
            "    return time.time()\n"
            "def loss_fn(params, batch):\n"
            "    return _helper(batch)\n"
        )
        out = lint_source(src, "t.py")
        assert codes(out) == ["DTL103"]

    def test_torch_trials_not_traced(self):
        src = (
            "class MyTrial(PyTorchTrial):\n"
            "    def evaluate(self, params, batch):\n"
            "        return {'loss': batch.sum().item()}\n"
        )
        assert codes(lint_source(src, "t.py")) == []


def _loader_src(body):
    return (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "from determined_tpu.train import JaxTrial\n"
        "class T(JaxTrial):\n"
        "    def init_params(self, rng):\n"
        "        return {}\n"
        "    def loss(self, params, batch, rng):\n"
        "        return batch\n"
        "    def build_training_data(self):\n"
        f"{body}"
    )


class TestDataLoaderRule:
    """DTL105 — device transfer inside build_*_data double-transfers with
    the async input pipeline (determined_tpu/data)."""

    def test_dtl105_device_put_in_loader(self):
        out = lint_source(_loader_src(
            "        while True:\n"
            "            yield jax.device_put({'x': np.zeros(4)})\n"), "t.py")
        assert codes(out) == ["DTL105"]
        assert "device_put" in out[0].message

    def test_dtl105_jnp_yield(self):
        assert codes(lint_source(_loader_src(
            "        for _ in range(4):\n"
            "            yield jnp.zeros((8, 4))\n"), "t.py")) == ["DTL105"]

    def test_dtl105_validation_loader_return(self):
        src = (
            "import jax.numpy as jnp\n"
            "from determined_tpu.train import JaxTrial\n"
            "class T(JaxTrial):\n"
            "    def build_validation_data(self):\n"
            "        return jnp.zeros((2, 4))\n"
        )
        assert codes(lint_source(src, "t.py")) == ["DTL105"]

    def test_dtl105_negative_numpy_loader(self):
        assert codes(lint_source(_loader_src(
            "        while True:\n"
            "            yield {'x': np.zeros((8, 4), np.float32)}\n"),
            "t.py")) == []

    def test_dtl105_negative_device_put_outside_loader(self):
        src = (
            "import jax\n"
            "def stage(batch):\n"
            "    return jax.device_put(batch)\n"
        )
        assert codes(lint_source(src, "t.py")) == []

    def test_dtl105_negative_torch_loader(self):
        src = (
            "import jax\n"
            "class MyTrial(PyTorchTrial):\n"
            "    def build_training_data(self):\n"
            "        yield jax.device_put({'x': 1})\n"
        )
        assert codes(lint_source(src, "t.py")) == []

    def test_dtl105_noqa_suppression(self):
        out = lint_source(_loader_src(
            "        while True:\n"
            "            yield jax.device_put({'x': np.zeros(4)})"
            "  # det: noqa[DTL105]\n"), "t.py")
        assert codes(out) == []
        assert [d.code for d in out if d.suppressed] == ["DTL105"]

    def test_dtl105_level_is_warning(self):
        out = lint_source(_loader_src(
            "        yield jnp.zeros((8, 4))\n"), "t.py")
        assert out[0].level == "warning"


class TestThreadStopRule:
    """DTL106 — `_stop` shadowing on threading.Thread subclasses crashes
    join() at thread exit (Thread._stop() is an internal method)."""

    def test_dtl106_instance_event(self):
        out = lint_source(
            "import threading\n"
            "class Worker(threading.Thread):\n"
            "    def __init__(self):\n"
            "        super().__init__()\n"
            "        self._stop = threading.Event()\n", "t.py")
        assert codes(out) == ["DTL106"]
        assert "_stop_evt" in out[0].message
        assert out[0].level == "error"

    def test_dtl106_class_attribute(self):
        assert codes(lint_source(
            "from threading import Thread\n"
            "class Worker(Thread):\n"
            "    _stop = None\n", "t.py")) == ["DTL106"]

    def test_dtl106_method(self):
        assert codes(lint_source(
            "import threading\n"
            "class Worker(threading.Thread):\n"
            "    def _stop(self):\n"
            "        pass\n", "t.py")) == ["DTL106"]

    def test_dtl106_subclass_of_subclass(self):
        assert codes(lint_source(
            "import threading\n"
            "class Base(threading.Thread):\n"
            "    pass\n"
            "class Worker(Base):\n"
            "    def run(self):\n"
            "        self._stop = threading.Event()\n", "t.py")) == ["DTL106"]

    def test_dtl106_negative_stop_evt(self):
        assert codes(lint_source(
            "import threading\n"
            "class Worker(threading.Thread):\n"
            "    def __init__(self):\n"
            "        super().__init__()\n"
            "        self._stop_evt = threading.Event()\n", "t.py")) == []

    def test_dtl106_negative_not_a_thread(self):
        assert codes(lint_source(
            "import threading\n"
            "class Manager:\n"
            "    def __init__(self):\n"
            "        self._stop = threading.Event()\n", "t.py")) == []

    def test_dtl106_noqa_suppression(self):
        out = lint_source(
            "import threading\n"
            "class Worker(threading.Thread):\n"
            "    def __init__(self):\n"
            "        self._stop = threading.Event()  # det: noqa[DTL106]\n",
            "t.py")
        assert codes(out) == []
        assert [d.code for d in out if d.suppressed] == ["DTL106"]

    def test_dtl106_tree_is_clean(self):
        """No Thread subclass in the tree shadows `_stop` (the long-running
        watchers use `_stop_evt`)."""
        from determined_tpu.analysis.astlint import lint_paths

        diags = lint_paths([os.path.join(REPO, "determined_tpu")])
        assert [d for d in diags if d.code == "DTL106"] == []


class TestAttnRule:
    """DTL107 — hand-rolled attention softmax inside traced trial code
    bypasses `optimizations.attention_impl` kernel selection."""

    def test_dtl107_softmax_in_loss(self):
        out = _lint("        p = jax.nn.softmax(batch)\n")
        assert codes(out) == ["DTL107"]
        assert "attention_impl" in out[0].message
        assert out[0].level == "warning"

    def test_dtl107_helper_closure(self):
        # A same-class helper called from loss() is linted as trial code.
        src = (
            "import jax\n"
            "from determined_tpu.train import JaxTrial\n"
            "class T(JaxTrial):\n"
            "    def _attn(self, q, k, v):\n"
            "        return jax.nn.softmax(q @ k.T) @ v\n"
            "    def loss(self, params, batch, rng):\n"
            "        return self._attn(batch, batch, batch)\n"
        )
        assert codes(lint_source(src, "t.py")) == ["DTL107"]

    def test_dtl107_negative_model_library_fn(self):
        # Module-level apply*/loss_fn* roots are the model *library* idiom
        # (ops/flash_attention.py's reference path) — not trial code.
        src = (
            "import jax\n"
            "def apply_attention(q, k, v):\n"
            "    return jax.nn.softmax(q @ k.T) @ v\n"
        )
        assert codes(lint_source(src, "t.py")) == []

    def test_dtl107_negative_log_softmax(self):
        # log_softmax is the cross-entropy idiom, not attention.
        assert codes(_lint(
            "        p = jax.nn.log_softmax(batch)\n")) == []

    def test_dtl107_negative_torch_trial(self):
        src = (
            "import torch\n"
            "class MyTrial(PyTorchTrial):\n"
            "    def loss(self, params, batch, rng):\n"
            "        return torch.nn.softmax(batch)\n"
        )
        assert codes(lint_source(src, "t.py")) == []

    def test_dtl107_noqa_suppression(self):
        out = _lint(
            "        p = jax.nn.softmax(batch)  # det: noqa[DTL107]\n")
        assert codes(out) == []
        assert [d.code for d in out if d.suppressed] == ["DTL107"]

    def test_dtl107_tree_is_clean(self):
        """The platform's own trials (examples/) route attention through
        the model library; none hand-roll softmax in traced methods."""
        from determined_tpu.analysis.astlint import lint_paths

        diags = lint_paths([os.path.join(REPO, "determined_tpu"),
                            os.path.join(REPO, "examples")])
        assert [d for d in diags if d.code == "DTL107"] == []


# ---------------------------------------------------------------------------
# config rules (DTL201-DTL202) — python side; native mirror in
# native/tests/test_native.cc
# ---------------------------------------------------------------------------


def _config(**over):
    c = {
        "entrypoint": "python3 train.py",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 64}},
        "resources": {"slots_per_trial": 8},
        "hyperparameters": {},
    }
    c.update(over)
    return c


class TestConfigRules:
    def test_dtl201(self):
        c = _config(hyperparameters={"global_batch_size": 30})
        assert codes(check_config(c)) == ["DTL201"]
        c["hyperparameters"]["global_batch_size"] = 32
        assert check_config(c) == []

    def test_dtl202(self):
        c = _config(searcher={"name": "async_halving", "metric": "loss",
                              "max_length": {"batches": 100},
                              "num_rungs": 5, "divisor": 4})
        assert codes(check_config(c)) == ["DTL202"]
        c["searcher"]["max_length"] = {"batches": 256}
        assert check_config(c) == []

    def test_dtl203_explicit_zero_with_restarts(self):
        c = _config(min_checkpoint_period={"batches": 0}, max_restarts=3)
        assert codes(check_config(c)) == ["DTL203"]
        # default max_restarts (5) counts as "restarts configured"
        c = _config(min_checkpoint_period={"batches": 0})
        assert codes(check_config(c)) == ["DTL203"]

    def test_dtl206_block_size_must_divide_max_seq(self):
        c = {"serving": {"checkpoint": "latest", "kv_block_size": 24,
                         "max_seq_len": 256}}
        diags = check_config(c)
        assert codes(diags) == ["DTL206"]
        assert diags[0].level == "error"
        c["serving"]["kv_block_size"] = 16
        assert check_config(c) == []

    def test_dtl206_pool_must_hold_one_sequence(self):
        c = {"serving": {"checkpoint": "latest", "kv_block_size": 16,
                         "max_seq_len": 256, "kv_num_blocks": 8}}  # 128 tok
        assert codes(check_config(c)) == ["DTL206"]
        c["serving"]["kv_num_blocks"] = 16  # exactly one sequence
        assert check_config(c) == []
        # Derived pool (no explicit kv_num_blocks) can never underrun.
        del c["serving"]["kv_num_blocks"]
        assert check_config(c) == []

    def test_dtl206_negative(self):
        # Defaults (16 | 256) are clean; dense layout is exempt — the
        # dense cache has no block tables to tile.
        assert check_config({"serving": {"checkpoint": "latest"}}) == []
        c = {"serving": {"checkpoint": "latest", "kv_block_size": 24,
                         "max_seq_len": 256, "attention_impl": "dense"}}
        assert check_config(c) == []
        # Non-serving configs never fire it.
        assert "DTL206" not in codes(check_config(_config()))

    def test_dtl206_suppressible(self):
        from determined_tpu.analysis import filter_suppressed

        c = {"serving": {"checkpoint": "latest", "kv_block_size": 24,
                         "max_seq_len": 256}}
        diags = filter_suppressed(check_config(c), ["DTL206"])
        assert [d.code for d in diags] == ["DTL206"]
        assert diags[0].suppressed

    def test_dtl203_negative(self):
        # absent key: the default is also 0 batches, but only an EXPLICIT
        # zero is flagged (otherwise every config would warn)
        assert check_config(_config(max_restarts=3)) == []
        # periodic checkpoints configured: nothing to flag
        c = _config(min_checkpoint_period={"batches": 50}, max_restarts=3)
        assert check_config(c) == []
        # restarts off: nothing to restart, rule moot
        c = _config(min_checkpoint_period={"batches": 0}, max_restarts=0)
        assert check_config(c) == []


# ---------------------------------------------------------------------------
# end-to-end: fixtures through preflight() and the det CLI
# ---------------------------------------------------------------------------


def _load_yaml(path):
    import yaml

    with open(path) as f:
        return yaml.safe_load(f)


class TestEndToEnd:
    def test_bad_fixture_exact_codes(self):
        from determined_tpu.analysis import preflight

        report = preflight(
            _load_yaml(os.path.join(FIXTURES, "bad", "config.yaml")),
            context_dir=os.path.join(FIXTURES, "bad"))
        # The acceptance contract: exactly these three, nothing else.
        assert report.codes() == ["DTL001", "DTL002", "DTL101"]
        assert report.hbm["donation_extra_bytes"] > 0

    def test_clean_fixture_reports_none(self):
        from determined_tpu.analysis import preflight

        report = preflight(
            _load_yaml(os.path.join(FIXTURES, "clean", "config.yaml")),
            context_dir=os.path.join(FIXTURES, "clean"))
        assert report.codes() == []
        assert report.errors == []

    def test_config_suppression_via_preflight_block(self):
        from determined_tpu.analysis import preflight

        cfg = _load_yaml(os.path.join(FIXTURES, "bad", "config.yaml"))
        cfg["preflight"] = {"suppress": ["DTL001", "DTL002", "DTL101"]}
        report = preflight(cfg, context_dir=os.path.join(FIXTURES, "bad"))
        assert report.codes() == []
        assert sum(1 for d in report.diagnostics if d.suppressed) == 3

    def test_cli_bad_fixture(self, capsys):
        from determined_tpu.cli import main

        rc = main(["preflight",
                   os.path.join(FIXTURES, "bad", "config.yaml"),
                   os.path.join(FIXTURES, "bad"), "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1  # error-level findings -> nonzero exit
        assert out["summary"]["codes"] == ["DTL001", "DTL002", "DTL101"]

    def test_cli_clean_fixture(self, capsys):
        from determined_tpu.cli import main

        rc = main(["preflight",
                   os.path.join(FIXTURES, "clean", "config.yaml"),
                   os.path.join(FIXTURES, "clean"), "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert out["summary"]["codes"] == []

    def test_every_shipped_rule_is_documented(self):
        doc = open(os.path.join(REPO, "docs", "preflight.md")).read()
        for code in RULES:
            assert code in doc, f"{code} missing from docs/preflight.md"

    def test_tree_is_lint_clean(self):
        """The dogfood gate: the platform's own models and examples pass
        the platform's own lint (suppressions must be annotated)."""
        from determined_tpu.analysis.astlint import lint_paths

        diags = lint_paths([os.path.join(REPO, "determined_tpu"),
                            os.path.join(REPO, "examples")])
        active = [d for d in diags if not d.suppressed]
        assert active == [], [f"{d.location()}: {d.code}" for d in active]
