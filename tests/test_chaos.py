"""Chaos tests: deterministic fault injection + crash-recovery hardening.

The platform's core promise (trial restart-on-failure, agent reattach,
master restore-on-boot) is exercised adversarially here instead of being
trusted incidentally: faults are armed through `DET_FAULTS` / the
admin-gated `POST /api/v1/debug/faults` route (docs/chaos.md), and the
recovery paths are asserted at the DB level — exact metric counts, no
idempotency-key replays applied twice, refcounts that balance.

Tier-1-safe tests run unmarked; the kill-the-master and 30%-5xx
end-to-end runs are behind `-m slow` to hold the tier-1 time budget.
"""

import os
import signal
import sqlite3
import time
import urllib.error
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from threading import Thread

import pytest

from test_platform_e2e import (  # noqa: F401  (fixture re-export)
    FIXTURES,
    Devcluster,
    _create_experiment,
    _experiment_config,
    _wait_experiment,
    native_binaries,
)

from determined_tpu.common import api as api_mod
from determined_tpu.common.api import APIError, Session

KNOWN_POINTS = {
    "api.response.5xx",
    "api.response.drop",
    "db.write.delay",
    "master.allocation.exit.crash",
    "agent.heartbeat.drop",
    "agent.exit_report.drop",
}


@pytest.fixture()
def master_only(tmp_path, native_binaries):
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    yield c
    c.stop()


@pytest.fixture()
def cluster(tmp_path, native_binaries):
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    c.start_agent()
    yield c
    c.stop()


def _arm(cluster, admin_token, **body):
    return cluster.api("POST", "/api/v1/debug/faults", body, token=admin_token)


def _disarm_all(cluster, admin_token):
    return _arm(cluster, admin_token, mode="off")


def _training_rows(sess, trial_id):
    return sess.get(f"/api/v1/trials/{trial_id}/metrics",
                    params={"group": "training"})["metrics"]


def _assert_no_duplicate_reports(rows):
    """Idempotency at the DB level: no (run, group, batch) applied twice."""
    seen = set()
    for m in rows:
        key = (m["trial_run_id"], m["group_name"], m["total_batches"])
        assert key not in seen, f"duplicated metric report {key}"
        seen.add(key)


# ---------------------------------------------------------------------------
# Fault-point surface (tier-1 safe).
# ---------------------------------------------------------------------------


def test_fault_points_listable_and_admin_gated(master_only):
    c = master_only
    user_token = c.login()
    admin_token = c.login("admin")

    listing = c.api("GET", "/api/v1/debug/faults", token=user_token)
    names = {p["name"] for p in listing["points"]}
    assert KNOWN_POINTS <= names
    assert listing["armed"] == []

    # Arming is admin-only: it is a cluster-wide DoS lever.
    try:
        _arm(c, user_token, point="api.response.5xx", mode="error", count=1)
        raise AssertionError("non-admin arm should 403")
    except urllib.error.HTTPError as e:
        assert e.code == 403

    # Bad mode is rejected with a diagnostic.
    try:
        _arm(c, admin_token, point="api.response.5xx", mode="explode")
        raise AssertionError("bad mode should 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400

    out = _arm(c, admin_token, point="api.response.5xx", mode="error", count=2)
    assert out["armed"][0]["point"] == "api.response.5xx"
    assert out["armed"][0]["remaining"] == 2

    # Exactly two requests fail, then the point auto-disarms.
    for _ in range(2):
        try:
            c.api("GET", "/api/v1/agents", token=user_token)
            raise AssertionError("armed fault should inject a 500")
        except urllib.error.HTTPError as e:
            assert e.code == 500
    assert c.api("GET", "/api/v1/agents", token=user_token)["agents"] == []
    listing = c.api("GET", "/api/v1/debug/faults", token=user_token)
    assert listing["armed"] == [], "count-armed fault must auto-disarm"


def test_unarmed_fault_points_are_noop(master_only):
    c = master_only
    token = c.login()
    admin = c.login("admin")
    _arm(c, admin, point="db.write.delay", mode="delay-50", count=1)
    _disarm_all(c, admin)
    t0 = time.time()
    for _ in range(50):
        c.api("GET", "/api/v1/master")
    assert time.time() - t0 < 10.0
    assert c.api("GET", "/api/v1/debug/faults", token=token)["armed"] == []


def test_db_write_delay_fault(master_only):
    c = master_only
    admin = c.login("admin")
    _arm(c, admin, point="db.write.delay", mode="delay-200", count=1)
    t0 = time.time()
    # login writes a session row → one delayed DB write.
    c.login()
    assert time.time() - t0 >= 0.2


# ---------------------------------------------------------------------------
# Session retry policy: backoff, jitter, Retry-After, idempotent replay.
# ---------------------------------------------------------------------------


class _FlakyHandler(BaseHTTPRequestHandler):
    calls = []
    plan = []  # list of (status, headers) consumed per call; then 200

    def do_GET(self):  # noqa: N802 (stdlib naming)
        _FlakyHandler.calls.append(time.time())
        if _FlakyHandler.plan:
            status, headers = _FlakyHandler.plan.pop(0)
            self.send_response(status)
            for k, v in headers.items():
                self.send_header(k, v)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        body = b'{"ok": true}'
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):
        pass


@pytest.fixture()
def flaky_server():
    _FlakyHandler.calls = []
    _FlakyHandler.plan = []
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FlakyHandler)
    t = Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}", _FlakyHandler
    srv.shutdown()


def test_429_and_retry_after_honored(flaky_server):
    url, handler = flaky_server
    handler.plan = [(429, {"Retry-After": "1"}), (429, {"Retry-After": "1"})]
    t0 = time.time()
    out = Session(url, max_retries=5).get("/anything")
    assert out == {"ok": True}
    assert len(handler.calls) == 3
    # Retry-After floors both sleeps.
    assert time.time() - t0 >= 1.8


def test_500_not_retried_for_non_idempotent_post():
    # POSTs without an idempotency key must NOT retry a bare 500: the
    # master may have applied the mutation.
    calls = []

    class PostHandler(BaseHTTPRequestHandler):
        def do_POST(self):  # noqa: N802
            calls.append(self.headers.get("X-Idempotency-Key"))
            self.send_response(500)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *args):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), PostHandler)
    Thread(target=srv.serve_forever, daemon=True).start()
    try:
        s = Session(f"http://127.0.0.1:{srv.server_address[1]}",
                    max_retries=4)
        with pytest.raises(APIError):
            s.post("/mutate", body={})
        assert len(calls) == 1, "non-idempotent POST must not retry a 500"
        # With idempotent=True the same 500 IS retried, with a stable key.
        with pytest.raises(APIError):
            s.post("/mutate", body={}, idempotent=True)
        keyed = calls[1:]
        assert len(keyed) == 4
        assert keyed[0] is not None and len(set(keyed)) == 1, (
            "idempotency key must be generated once per logical request")
    finally:
        srv.shutdown()


def test_backoff_full_jitter_is_capped(monkeypatch):
    sleeps = []
    monkeypatch.setattr(api_mod.time, "sleep", sleeps.append)
    s = Session("http://127.0.0.1:9", max_retries=5,
                backoff_base=0.1, backoff_cap=0.4)
    with pytest.raises(ConnectionError):
        s.get("/x", timeout=0.2)
    assert len(sleeps) == 4
    for i, d in enumerate(sleeps):
        assert 0.0 <= d <= min(0.4, 0.1 * 2 ** i) + 1e-9


# ---------------------------------------------------------------------------
# Master-side idempotent replay, verified at the DB level (tier-1 safe).
# ---------------------------------------------------------------------------


def _unmanaged_trial(cluster, token):
    eid = cluster.api(
        "POST", "/api/v1/experiments",
        {"unmanaged": True, "config": {"name": "chaos-unmanaged"}},
        token=token)["id"]
    tid = cluster.api(
        "POST", f"/api/v1/experiments/{eid}/trials", {"hparams": {}},
        token=token)["id"]
    return eid, tid


def test_idempotent_metric_report_survives_5xx_and_dropped_response(
        master_only):
    c = master_only
    token = c.login()
    admin = c.login("admin")
    _, tid = _unmanaged_trial(c, token)
    sess = Session(c.master_url, token=token, backoff_base=0.02)

    # Injected 500 BEFORE processing: the retry must deliver exactly once.
    _arm(c, admin, point="api.response.5xx", mode="error", count=1)
    sess.post(f"/api/v1/trials/{tid}/metrics",
              body={"group": "training", "steps_completed": 1,
                    "trial_run_id": 0, "metrics": {"loss": 1.0}},
              idempotent=True)
    rows = _training_rows(sess, tid)
    assert len(rows) == 1

    # Processed-then-dropped response: the retry must be answered from
    # the replay cache, not re-applied — the classic double-count.
    _arm(c, admin, point="api.response.drop", mode="drop", count=1)
    sess.post(f"/api/v1/trials/{tid}/metrics",
              body={"group": "training", "steps_completed": 2,
                    "trial_run_id": 0, "metrics": {"loss": 0.5}},
              idempotent=True)
    rows = _training_rows(sess, tid)
    assert len(rows) == 2, f"dropped-response retry double-applied: {rows}"
    _assert_no_duplicate_reports(rows)

    # The key is recorded server-side.
    c.kill_master()
    with sqlite3.connect(c.db_path) as db:
        n = db.execute("SELECT COUNT(*) FROM idempotency_keys").fetchone()[0]
    assert n >= 2


def test_checkpoint_report_replay_does_not_double_register(master_only):
    c = master_only
    token = c.login()
    admin = c.login("admin")
    _, tid = _unmanaged_trial(c, token)
    sess = Session(c.master_url, token=token, backoff_base=0.02)
    _arm(c, admin, point="api.response.drop", mode="drop", count=1)
    sess.post("/api/v1/checkpoints",
              body={"uuid": "ck-chaos-1", "trial_id": tid,
                    "steps_completed": 4, "metadata": {}, "resources": {}},
              idempotent=True)
    ck = sess.get("/api/v1/checkpoints/ck-chaos-1")["checkpoint"]
    assert ck["trial_id"] == tid
    trial = sess.get(f"/api/v1/trials/{tid}")["trial"]
    assert trial["latest_checkpoint"] == "ck-chaos-1"


def test_partial_checkpoint_never_becomes_resume_pointer(master_only):
    """Two-phase commit at the registry (docs/checkpointing.md): a PARTIAL
    report must not advance latest_checkpoint; the COMPLETED phase-2
    report for the same uuid must; and the lineage endpoint filters by
    state so Trainer fallback only ever sees verified checkpoints."""
    c = master_only
    token = c.login()
    _, tid = _unmanaged_trial(c, token)
    sess = Session(c.master_url, token=token, backoff_base=0.02)

    def report(uuid, steps, state):
        sess.post("/api/v1/checkpoints",
                  body={"uuid": uuid, "trial_id": tid,
                        "steps_completed": steps, "metadata": {},
                        "resources": {}, "state": state},
                  idempotent=True)

    report("ck-good-2", 2, "PARTIAL")
    report("ck-good-2", 2, "COMPLETED")
    report("ck-partial-4", 4, "PARTIAL")  # phase 2 never lands (crash)

    trial = sess.get(f"/api/v1/trials/{tid}")["trial"]
    assert trial["latest_checkpoint"] == "ck-good-2", (
        "a PARTIAL checkpoint must never become the resume pointer")
    assert sess.get("/api/v1/checkpoints/ck-partial-4")["checkpoint"][
        "state"] == "PARTIAL"

    # Lineage endpoint: newest-first, state-filtered.
    lineage = sess.get(f"/api/v1/trials/{tid}/checkpoints",
                       params={"state": "COMPLETED"})["checkpoints"]
    assert [ck["uuid"] for ck in lineage] == ["ck-good-2"]
    everything = sess.get(f"/api/v1/trials/{tid}/checkpoints")["checkpoints"]
    assert [ck["uuid"] for ck in everything] == ["ck-partial-4", "ck-good-2"]

    # Bad state values are rejected, not stored.
    try:
        report("ck-bad", 6, "SHRUG")
        raise AssertionError("invalid state should 400")
    except APIError as e:
        assert e.status == 400


# ---------------------------------------------------------------------------
# Context-blob sweep refcount regression (ADVICE.md #1, tier-1 safe).
# ---------------------------------------------------------------------------


def test_blob_sweep_releases_once_per_ended_task_and_never_live_claims(
        tmp_path, native_binaries):
    """Master restart with two ended tasks sharing one context hash plus a
    live experiment model-def on the same hash: the sweep must release
    exactly the two task claims (not one, not three) and the experiment's
    model definition must survive until the experiment itself is deleted."""
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    try:
        eid, token = _create_experiment(
            c, _experiment_config(tmp_path), activate=False)
        c.kill_master()

        # Manufacture the orphan state the advisory describes: the tasks
        # ended (end_time set) but the inline release never ran — the old
        # master died first. Both share the experiment's context hash.
        with sqlite3.connect(c.db_path) as db:
            (h,) = db.execute(
                "SELECT model_def_hash FROM experiments WHERE id=?",
                (eid,)).fetchone()
            assert h
            db.execute(
                "UPDATE model_defs SET refcount = refcount + 2 WHERE hash=?",
                (h,))
            for tid in ("cmd-orphan-a", "cmd-orphan-b"):
                db.execute(
                    "INSERT INTO tasks (id, type, state, end_time, "
                    "context_hash) VALUES (?, 'COMMAND', 'COMPLETED', "
                    "datetime('now'), ?)", (tid, h))
            db.commit()

        c.start_master()
        admin = c.login("admin")
        out = c.api("POST", "/api/v1/master/cleanup_blobs", {}, token=admin)
        assert out["released"] == 2, (
            "sweep must release one claim per ended-task row")
        # The live experiment's claim survives: model_def still served.
        md = c.api("GET", f"/api/v1/experiments/{eid}/model_def",
                   token=admin)
        assert md["b64_tgz"], "sweep purged a blob with a live claim"
        # Idempotent: a second sweep releases nothing further.
        out = c.api("POST", "/api/v1/master/cleanup_blobs", {}, token=admin)
        assert out["released"] == 0
        md = c.api("GET", f"/api/v1/experiments/{eid}/model_def",
                   token=admin)
        assert md["b64_tgz"]

        # Deleting the experiment drops the LAST claim → blob purged
        # (fails if the sweep leaked or double-released refcounts).
        c.api("POST", f"/api/v1/experiments/{eid}/cancel", {}, token=admin)
        deadline = time.time() + 30
        while time.time() < deadline:
            state = c.api("GET", f"/api/v1/experiments/{eid}",
                          token=admin)["experiment"]["state"]
            if state in ("CANCELED", "COMPLETED", "ERROR"):
                break
            time.sleep(0.2)
        c.api("DELETE", f"/api/v1/experiments/{eid}", token=admin)
        c.kill_master()
        with sqlite3.connect(c.db_path) as db:
            n = db.execute("SELECT COUNT(*) FROM model_defs").fetchone()[0]
        assert n == 0, "refcount accounting leaked the blob"
    finally:
        c.stop()


# ---------------------------------------------------------------------------
# Chaos smoke: experiment completes under injected 5xx (tier-1 safe).
# ---------------------------------------------------------------------------


def test_smoke_experiment_completes_under_injected_5xx(cluster, tmp_path):
    config = _experiment_config(tmp_path)
    eid, token = _create_experiment(cluster, config)
    admin = cluster.login("admin")
    _arm(cluster, admin, point="api.response.5xx", mode="error",
         probability=0.15)
    sess = Session(cluster.master_url, token=token)
    try:
        deadline = time.time() + 120
        state = None
        while time.time() < deadline:
            state = sess.get(f"/api/v1/experiments/{eid}")["experiment"][
                "state"]
            if state in ("COMPLETED", "CANCELED", "ERROR"):
                break
            time.sleep(0.5)
    finally:
        _disarm_all(cluster, admin)
    assert state == "COMPLETED", f"experiment under 15% 5xx ended {state}"
    trial = sess.get(f"/api/v1/experiments/{eid}/trials")["trials"][0]
    rows = _training_rows(sess, trial["id"])
    _assert_no_duplicate_reports(rows)
    batches = sorted(m["total_batches"] for m in rows
                     if m["trial_run_id"] == max(
                         r["trial_run_id"] for r in rows))
    assert batches[-1] == 8, f"final report missing: {batches}"


# ---------------------------------------------------------------------------
# Capstone e2e (slow): SIGKILL the master / kill the agent / 30% 5xx.
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_master_sigkill_mid_trial_no_lost_or_duplicated_metrics(
        cluster, tmp_path):
    config = _experiment_config(
        tmp_path,
        searcher={"name": "single", "metric": "val_loss",
                  "max_length": {"batches": 60}},
        extra={"max_restarts": 2},
    )
    config["environment"] = {"TRIAL_STEP_SLEEP": "0.05"}
    eid, token = _create_experiment(cluster, config)

    # Wait until the trial is mid-run and reporting.
    sess = Session(cluster.master_url, token=token)
    deadline = time.time() + 60
    trial = None
    while time.time() < deadline:
        trials = sess.get(f"/api/v1/experiments/{eid}/trials")["trials"]
        if trials and _training_rows(sess, trials[0]["id"]):
            trial = trials[0]
            break
        time.sleep(0.3)
    assert trial is not None, "trial never started reporting"

    cluster.kill_master()  # SIGKILL: no snapshot flush, no goodbyes
    time.sleep(1.0)
    cluster.start_master()  # same db: restore-on-boot + re-adoption
    token = cluster.login()
    sess = Session(cluster.master_url, token=token)

    _wait_experiment(cluster, eid, token, timeout=180.0)
    trials = sess.get(f"/api/v1/experiments/{eid}/trials")["trials"]
    assert trials[0]["state"] == "COMPLETED"
    assert trials[0]["total_batches"] >= 60

    rows = _training_rows(sess, trials[0]["id"])
    # Zero duplicated: no (run, batch) applied twice — retried reports
    # during the outage must have been replayed, not re-applied.
    _assert_no_duplicate_reports(rows)
    # Zero lost: the final run reaches 60, and every 4-step report since
    # its resume point is present exactly once.
    final_run = max(m["trial_run_id"] for m in rows)
    final_batches = sorted(m["total_batches"] for m in rows
                           if m["trial_run_id"] == final_run)
    assert final_batches[-1] == 60
    start = final_batches[0]
    assert final_batches == list(range(start, 61, 4)), (
        f"gaps in final run's reports: {final_batches}")


@pytest.mark.slow
def test_agent_and_task_killed_restart_from_checkpoint_within_max_restarts(
        cluster, tmp_path):
    config = _experiment_config(
        tmp_path,
        searcher={"name": "single", "metric": "val_loss",
                  "max_length": {"batches": 200}},
        extra={"max_restarts": 2},
    )
    config["environment"] = {"TRIAL_STEP_SLEEP": "0.05"}
    eid, token = _create_experiment(cluster, config)

    import json as _json

    registry = os.path.join(cluster.tmpdir, "agent-work", "running.json")

    def _registry_pids():
        try:
            with open(registry) as f:
                return {e["pid"] for e in _json.load(f)
                        if e.get("pid", -1) > 0}
        except Exception:
            return set()

    # Force a mid-run checkpoint via pause (preempt → checkpoint → exit).
    time.sleep(4.0)
    pre_pause_pids = _registry_pids()
    cluster.api("POST", f"/api/v1/experiments/{eid}/pause", token=token)
    deadline = time.time() + 60
    while time.time() < deadline:
        trials = cluster.api("GET", f"/api/v1/experiments/{eid}/trials",
                             token=token)["trials"]
        if trials and trials[0].get("latest_checkpoint"):
            break
        time.sleep(0.5)
    assert trials[0]["latest_checkpoint"], "pause did not checkpoint"
    cluster.api("POST", f"/api/v1/experiments/{eid}/activate", token=token)

    # Wait for the RESUMED container (a fresh, live pid — not the
    # pre-pause task still draining out of the registry), then kill BOTH
    # the agent and the task process tree — a whole-node death, not a
    # reattachable agent restart.
    def _alive(pid):
        try:
            os.kill(pid, 0)
            return True
        except (ProcessLookupError, PermissionError):
            return False

    deadline = time.time() + 60
    pids = []
    while time.time() < deadline:
        pids = [p for p in _registry_pids()
                if p not in pre_pause_pids and _alive(p)]
        if pids:
            break
        time.sleep(0.3)
    assert pids, "resumed task never appeared in the agent registry"
    time.sleep(2.0)  # let it train past the checkpoint
    cluster.agent.kill()
    cluster.agent.wait()
    for pid in pids:
        try:
            os.killpg(pid, signal.SIGKILL)  # task runs as its own pgroup
        except (ProcessLookupError, PermissionError):
            pass
    time.sleep(1.0)
    cluster.start_agent()  # reattach finds the task dead → exit 137

    _wait_experiment(cluster, eid, token, timeout=240.0)
    trials = cluster.api("GET", f"/api/v1/experiments/{eid}/trials",
                         token=token)["trials"]
    assert trials[0]["state"] == "COMPLETED"
    assert 1 <= trials[0]["restarts"] <= 2, (
        f"expected restart within max_restarts, got {trials[0]['restarts']}")
    logs = cluster.api(
        "GET", f"/api/v1/tasks/trial-{trials[0]['id']}/logs?offset=0",
        token=token)["logs"]
    assert any("resumed from checkpoint" in line["log"] for line in logs), (
        "restart must resume from the latest checkpoint")


@pytest.mark.slow
def test_experiment_completes_exactly_under_30pct_5xx(cluster, tmp_path):
    config = _experiment_config(
        tmp_path,
        searcher={"name": "single", "metric": "val_loss",
                  "max_length": {"batches": 24}},
        extra={"max_restarts": 2},
    )
    config["environment"] = {"TRIAL_STEP_SLEEP": "0.02"}
    eid, token = _create_experiment(cluster, config)
    admin = cluster.login("admin")
    _arm(cluster, admin, point="api.response.5xx", mode="error",
         probability=0.3)
    sess = Session(cluster.master_url, token=token)
    try:
        deadline = time.time() + 240
        state = None
        while time.time() < deadline:
            state = sess.get(f"/api/v1/experiments/{eid}")["experiment"][
                "state"]
            if state in ("COMPLETED", "CANCELED", "ERROR"):
                break
            time.sleep(0.5)
    finally:
        _disarm_all(cluster, admin)
    assert state == "COMPLETED", f"experiment under 30% 5xx ended {state}"

    trial = sess.get(f"/api/v1/experiments/{eid}/trials")["trials"][0]
    rows = _training_rows(sess, trial["id"])
    _assert_no_duplicate_reports(rows)
    final_run = max(m["trial_run_id"] for m in rows)
    final_batches = sorted(m["total_batches"] for m in rows
                           if m["trial_run_id"] == final_run)
    start = final_batches[0]
    assert final_batches == list(range(start, 25, 4)), (
        f"lost or duplicated reports under 30% 5xx: {final_batches}")
    val = sess.get(f"/api/v1/trials/{trial['id']}/metrics",
                   params={"group": "validation"})["metrics"]
    assert [m for m in val if m["trial_run_id"] == final_run], (
        "validation report lost")
