"""Split-brain safety: lease-based ownership, fencing epochs, partition chaos.

The master mints a monotonic fencing epoch per allocation run (the trial's
run_id at mint time) and hands it to tasks as DET_ALLOCATION_EPOCH; every
state-mutating harness POST carries it back as X-Allocation-Epoch and a
writer from a superseded run gets a distinct 409 plus a
det_fenced_writes_total{route=...} bump (docs/cluster-ops.md "Leases,
fencing & split-brain"). Liveness is the agent-side ownership lease:
renewed only by register/heartbeat ACKs, so a partitioned agent
self-terminates its tasks at lease expiry — the fence is the backstop for
the zombie that doesn't.

Tier-1-safe tests drive the fence through the api.write.stale_epoch fault
point; the real partition (agent.heartbeat.blackhole mid-trial, master
reassigns, zombie's late COMMIT fenced, survivor trajectory identical to
an unpartitioned control) runs behind -m slow.
"""

import json
import os
import sqlite3
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from threading import Thread

import pytest

from test_platform_e2e import (  # noqa: F401  (fixture re-export)
    FIXTURES,
    Devcluster,
    _create_experiment,
    _experiment_config,
    _wait_experiment,
    native_binaries,
)

from determined_tpu.common.api import APIError, Session

NEW_POINTS = {
    "agent.heartbeat.blackhole",
    "master.lease.expire",
    "api.write.stale_epoch",
}


@pytest.fixture()
def master_only(tmp_path, native_binaries):
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    yield c
    c.stop()


@pytest.fixture()
def cluster(tmp_path, native_binaries):
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    c.start_agent()
    yield c
    c.stop()


def _arm(cluster, admin_token, **body):
    return cluster.api("POST", "/api/v1/debug/faults", body, token=admin_token)


def _disarm_all(cluster, admin_token):
    return _arm(cluster, admin_token, mode="off")


def _unmanaged_trial(cluster, token):
    eid = cluster.api(
        "POST", "/api/v1/experiments",
        {"unmanaged": True, "config": {"name": "fencing-unmanaged"}},
        token=token)["id"]
    tid = cluster.api(
        "POST", f"/api/v1/experiments/{eid}/trials", {"hparams": {}},
        token=token)["id"]
    return eid, tid


def _scrape(master_url, token):
    req = urllib.request.Request(
        master_url + "/metrics",
        headers={"Authorization": f"Bearer {token}"})
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.read().decode()


def _metric_value(text, name, label_frag=""):
    """Value of the first sample line for `name` containing `label_frag`;
    None when the series was never emitted (e.g. an empty counter map)."""
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        if line.startswith(name) and label_frag in line:
            return float(line.rsplit(" ", 1)[1])
    return None


# ---------------------------------------------------------------------------
# Fault-point surface (tier-1 safe).
# ---------------------------------------------------------------------------


def test_partition_fault_points_are_registered(master_only):
    c = master_only
    token = c.login()
    listing = c.api("GET", "/api/v1/debug/faults", token=token)
    names = {p["name"] for p in listing["points"]}
    assert NEW_POINTS <= names
    assert listing["armed"] == []


# ---------------------------------------------------------------------------
# The stale-epoch fence (tier-1 safe, driven via api.write.stale_epoch).
# ---------------------------------------------------------------------------


def test_stale_epoch_write_gets_distinct_409_and_counter(master_only):
    c = master_only
    token = c.login()
    admin = c.login("admin")
    _, tid = _unmanaged_trial(c, token)
    plain = Session(c.master_url, token=token, backoff_base=0.02)
    # A session whose every write carries the allocation epoch — what
    # core.init() builds from DET_ALLOCATION_EPOCH.
    epoch0 = Session(c.master_url, token=token, backoff_base=0.02,
                     headers={"X-Allocation-Epoch": "0"})

    def report(sess, step):
        sess.post(f"/api/v1/trials/{tid}/metrics",
                  body={"group": "training", "steps_completed": step,
                        "trial_run_id": 0, "metrics": {"loss": 1.0}})

    # Un-fenced baseline: epoch 0 matches the unmanaged trial's run_id 0.
    report(epoch0, 1)

    # Armed fault forces the stale branch for epoch-carrying writes only.
    _arm(c, admin, point="api.write.stale_epoch", mode="error")
    try:
        report(epoch0, 2)
        raise AssertionError("stale-epoch write should 409")
    except APIError as e:
        assert e.status == 409
        body = json.loads(e.body)
        assert body["fenced"] is True
        assert body["route"] == "metrics"
        assert body["claimed_epoch"] == 0
        assert "current_epoch" in body

    # Epoch-less writers (CLI, unmanaged back-compat) are never fenced,
    # even while the fault is armed: no header, no staleness claim.
    report(plain, 3)

    _disarm_all(c, admin)
    report(epoch0, 4)

    rows = plain.get(f"/api/v1/trials/{tid}/metrics",
                     params={"group": "training"})["metrics"]
    assert {m["total_batches"] for m in rows} == {1, 3, 4}, (
        "exactly the fenced write must be missing")
    assert _metric_value(_scrape(c.master_url, token), "det_fenced_writes_total",
                         'route="metrics"') == 1.0


def test_fenced_commit_never_advances_pointer_and_sweeps_partial(master_only):
    """A zombie's phase-2 COMMIT must neither advance latest_checkpoint nor
    leave its PARTIAL torso behind (docs/checkpointing.md)."""
    c = master_only
    token = c.login()
    admin = c.login("admin")
    _, tid = _unmanaged_trial(c, token)
    sess = Session(c.master_url, token=token, backoff_base=0.02)
    stale = Session(c.master_url, token=token, backoff_base=0.02,
                    headers={"X-Allocation-Epoch": "0"})

    def report(s, uuid, steps, state):
        s.post("/api/v1/checkpoints",
               body={"uuid": uuid, "trial_id": tid, "steps_completed": steps,
                     "metadata": {}, "resources": {}, "state": state})

    report(sess, "ck-good", 2, "PARTIAL")
    report(sess, "ck-good", 2, "COMPLETED")
    report(sess, "ck-zombie", 4, "PARTIAL")

    _arm(c, admin, point="api.write.stale_epoch", mode="error")
    try:
        report(stale, "ck-zombie", 4, "COMPLETED")
        raise AssertionError("zombie COMMIT should 409")
    except APIError as e:
        assert e.status == 409
        assert json.loads(e.body)["route"] == "checkpoints"
    _disarm_all(c, admin)

    trial = sess.get(f"/api/v1/trials/{tid}")["trial"]
    assert trial["latest_checkpoint"] == "ck-good", (
        "a fenced COMMIT must never become the resume pointer")
    # The fenced uuid's PARTIAL row was swept in the same stroke.
    try:
        sess.get("/api/v1/checkpoints/ck-zombie")
        raise AssertionError("fenced checkpoint's PARTIAL should be swept")
    except APIError as e:
        assert e.status == 404
    lineage = sess.get(f"/api/v1/trials/{tid}/checkpoints",
                       params={"state": "COMPLETED"})["checkpoints"]
    assert [ck["uuid"] for ck in lineage] == ["ck-good"]
    assert _metric_value(_scrape(c.master_url, token), "det_fenced_writes_total",
                         'route="checkpoints"') == 1.0


# ---------------------------------------------------------------------------
# Idempotency-replay horizon pinned to the lease TTL (tier-1 safe).
# ---------------------------------------------------------------------------


def test_idempotency_sweep_horizon_tracks_lease_ttl(tmp_path, native_binaries):
    """Replay entries must outlive the longest lease: horizon is
    max(24h, 2 x lease_ttl_s). With lease_ttl_s=90000 a 25h-old key
    survives the sweep (horizon 50h); back at the default lease it is
    swept by the 24h floor."""
    c = Devcluster(str(tmp_path), native_binaries)
    try:
        c.start_master(extra_args=("--lease-ttl", "90000"))
        c.login()  # provision default users before direct db writes
        c.kill_master()
        with sqlite3.connect(c.db_path) as db:
            for key, age in (("k-25h", "-25 hours"), ("k-60h", "-60 hours")):
                db.execute(
                    "INSERT INTO idempotency_keys (key, status, body, "
                    "created_at) VALUES (?, 200, '{}', "
                    "datetime('now', ?))", (key, age))
            db.commit()

        c.start_master(extra_args=("--lease-ttl", "90000"))
        admin = c.login("admin")
        user = c.login()
        try:
            c.api("POST", "/api/v1/master/sweep_idempotency", {}, token=user)
            raise AssertionError("sweep is admin-only")
        except urllib.error.HTTPError as e:
            assert e.code == 403
        out = c.api("POST", "/api/v1/master/sweep_idempotency", {},
                    token=admin)
        assert out["horizon_seconds"] == 180000
        assert out["deleted"] == 1, "only the 60h key is past 2x lease"

        # Default lease (30s): the 24h floor governs and the 25h key goes.
        c.kill_master()
        c.start_master()
        admin = c.login("admin")
        out = c.api("POST", "/api/v1/master/sweep_idempotency", {},
                    token=admin)
        assert out["horizon_seconds"] == 86400
        assert out["deleted"] == 1
        c.kill_master()
        with sqlite3.connect(c.db_path) as db:
            keys = {r[0] for r in db.execute(
                "SELECT key FROM idempotency_keys").fetchall()}
        assert "k-25h" not in keys and "k-60h" not in keys
    finally:
        c.stop()


# ---------------------------------------------------------------------------
# Lease accounting + steady-state zero (tier-1 safe, real agent).
# ---------------------------------------------------------------------------


def test_lease_steady_state_is_zero_and_expiry_counts_once(cluster, tmp_path):
    """An un-partitioned run must see ZERO fenced writes and ZERO lease
    expirations — proof the harness epoch header matches run_id end to end
    — and a forced lapse (master.lease.expire) counts each agent once, not
    once per sweep tick."""
    c = cluster
    eid, token = _create_experiment(cluster, _experiment_config(tmp_path))
    _wait_experiment(cluster, eid, token)

    agents = c.api("GET", "/api/v1/agents", token=token)["agents"]
    assert agents and agents[0]["lease_expired"] is False
    assert agents[0]["lease_remaining_seconds"] > 0

    text = _scrape(c.master_url, token)
    assert _metric_value(text, "det_lease_expirations_total") == 0.0
    for line in text.splitlines():
        if line.startswith("det_fenced_writes_total"):
            assert line.endswith(" 0"), f"steady-state fenced write: {line}"

    # Forced lapse: fires once (count=1); the 200ms sweep must count the
    # agent once per lapse, and the next heartbeat renews the lease.
    admin = c.login("admin")
    _arm(c, admin, point="master.lease.expire", mode="error", count=1)
    deadline = time.time() + 10
    while time.time() < deadline:
        if _metric_value(_scrape(c.master_url, token),
                         "det_lease_expirations_total") == 1.0:
            break
        time.sleep(0.2)
    else:
        raise AssertionError("forced lease lapse never counted")
    time.sleep(1.0)  # several more sweep ticks: still exactly one
    assert _metric_value(_scrape(c.master_url, token),
                         "det_lease_expirations_total") == 1.0


# ---------------------------------------------------------------------------
# Session hardening: connection reset mid-response-body is retryable.
# ---------------------------------------------------------------------------


def test_session_retries_connection_reset_mid_response_body():
    """A peer that dies after the status line, partway through the body,
    surfaces as http.client.IncompleteRead — which urlopen does NOT wrap
    in URLError. The Session must back off and retry instead of crashing
    the caller mid-trial."""
    calls = []
    body = json.dumps({"ok": True}).encode()

    class TruncatingHandler(BaseHTTPRequestHandler):
        def do_GET(self):
            calls.append(1)
            self.send_response(200)
            if len(calls) == 1:
                # Promise more bytes than we send, then cut the socket.
                self.send_header("Content-Length", str(len(body) + 64))
                self.end_headers()
                self.wfile.write(body[: len(body) // 2])
                self.wfile.flush()
                self.connection.close()
            else:
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        def log_message(self, *args):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), TruncatingHandler)
    Thread(target=srv.serve_forever, daemon=True).start()
    try:
        s = Session(f"http://127.0.0.1:{srv.server_address[1]}",
                    max_retries=4, backoff_base=0.01)
        assert s.get("/status") == {"ok": True}
        assert len(calls) == 2, "mid-body reset must be retried exactly once"
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Partition e2e (slow): lease liveness + the full split-brain scenario.
# ---------------------------------------------------------------------------


def _task_pids(work_root):
    try:
        with open(os.path.join(work_root, "running.json")) as f:
            return [e["pid"] for e in json.load(f) if "exit_code" not in e]
    except (OSError, ValueError):
        return []


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _wait_training_started(c, eid, token, timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        trials = c.api("GET", f"/api/v1/experiments/{eid}/trials",
                       token=token)["trials"]
        if trials:
            tid = trials[0]["id"]
            rows = c.api(
                "GET", f"/api/v1/trials/{tid}/metrics?group=training",
                token=token)["metrics"]
            if rows:
                return trials[0]
        time.sleep(0.3)
    raise TimeoutError("trial never started reporting")


@pytest.mark.slow
def test_partitioned_agent_self_fences_within_lease_ttl(
        tmp_path, native_binaries):
    """Liveness half of split-brain safety: an agent that cannot renew its
    lease kills its own tasks within lease_ttl_s — BEFORE the master's
    reclaim (agent_timeout_s) hands the allocation to someone else."""
    c = Devcluster(str(tmp_path), native_binaries)
    faults_file = os.path.join(str(tmp_path), "agent-faults.txt")
    try:
        c.start_master()
        c.start_agent(extra_env={"DET_AGENT_LEASE_TTL_S": "4",
                                 "DET_AGENT_FAULTS_FILE": faults_file})
        work_root = os.path.join(str(tmp_path), "agent-work")

        config = _experiment_config(
            tmp_path,
            searcher={"name": "single", "metric": "val_loss",
                      "max_length": {"batches": 2000}})
        config["environment"] = {"TRIAL_STEP_SLEEP": "0.1"}
        eid, token = _create_experiment(c, config)
        _wait_training_started(c, eid, token)

        pids = _task_pids(work_root)
        assert pids, "running.json should list the live task"
        assert all(_pid_alive(p) for p in pids)

        # Partition: total heartbeat + long-poll silence, armed mid-run.
        with open(faults_file, "w") as f:
            f.write("agent.heartbeat.blackhole:drop")

        # Lease TTL is 4s (pinned agent-side); allow kill + reap slack but
        # stay well inside the master's 15s reclaim window.
        deadline = time.time() + 12
        while time.time() < deadline:
            if all(not _pid_alive(p) for p in pids):
                break
            time.sleep(0.3)
        else:
            raise AssertionError(
                f"partitioned agent did not self-fence its tasks: {pids}")
        # The agent itself survives — it fenced its tasks, not itself.
        assert c.agent.poll() is None
    finally:
        c.stop()


@pytest.mark.slow
def test_split_brain_partition_fences_zombie_and_preserves_trajectory(
        tmp_path, native_binaries):
    """The acceptance scenario (ISSUE.md): partition a 2-agent devcluster
    mid-trial, master reassigns past the zombie, the zombie's late writes
    (including its checkpoint COMMIT) are fenced with 409s, the partition
    heals, and the surviving trajectory is bit-identical to an
    unpartitioned control run with exactly one COMPLETED lineage."""
    c = Devcluster(str(tmp_path), native_binaries)
    faults_file = os.path.join(str(tmp_path), "agent0-faults.txt")
    try:
        c.start_master()  # --agent-timeout 15 (Devcluster default)
        # Zombie-to-be: lease TTL pinned huge so self-fencing never saves
        # us — this test is about the fence being a sufficient backstop
        # when the liveness half fails.
        c.start_agent(agent_id="agent-0",
                      extra_env={"DET_AGENT_LEASE_TTL_S": "9999",
                                 "DET_AGENT_FAULTS_FILE": faults_file})

        total_batches = 120
        config = _experiment_config(
            tmp_path,
            searcher={"name": "single", "metric": "val_loss",
                      "max_length": {"batches": total_batches}})
        config["environment"] = {"TRIAL_STEP_SLEEP": "0.2"}
        eid, token = _create_experiment(c, config)
        trial = _wait_training_started(c, eid, token)
        tid = trial["id"]
        old_epoch = trial["run_id"]

        # Healthy standby capacity, then the partition.
        c.start_agent(agent_id="agent-1")
        with open(faults_file, "w") as f:
            f.write("agent.heartbeat.blackhole:drop")

        # Master declares agent-0 dead at agent_timeout_s and requeues:
        # run_id bumps, so the new allocation's epoch supersedes the
        # zombie's.
        deadline = time.time() + 60
        while time.time() < deadline:
            t = c.api("GET", f"/api/v1/trials/{tid}", token=token)["trial"]
            if t["run_id"] > old_epoch:
                break
            time.sleep(0.5)
        else:
            raise AssertionError("master never reassigned past the zombie")

        # The zombie's late writes, driven deterministically with its
        # minted epoch (the task process itself also keeps reporting and
        # crashes on its first natural 409 — not blocked by the agent-side
        # blackhole, which silences only the control channel).
        zombie = Session(c.master_url, token=token, backoff_base=0.02,
                         headers={"X-Allocation-Epoch": str(old_epoch)})
        plain = Session(c.master_url, token=token, backoff_base=0.02)
        try:
            zombie.post(f"/api/v1/trials/{tid}/metrics",
                        body={"group": "training", "steps_completed": 999,
                              "trial_run_id": old_epoch,
                              "metrics": {"loss": 123.0}})
            raise AssertionError("zombie metric write should 409")
        except APIError as e:
            assert e.status == 409
            assert json.loads(e.body)["fenced"] is True

        # Its two-phase COMMIT: PARTIAL landed before the fence matters
        # (simulating phase 1 completing pre-partition), phase 2 is fenced
        # and the torso swept.
        plain.post("/api/v1/checkpoints",
                   body={"uuid": "ck-zombie", "trial_id": tid,
                         "steps_completed": 999, "metadata": {},
                         "resources": {}, "state": "PARTIAL"})
        try:
            zombie.post("/api/v1/checkpoints",
                        body={"uuid": "ck-zombie", "trial_id": tid,
                              "steps_completed": 999, "metadata": {},
                              "resources": {}, "state": "COMPLETED"})
            raise AssertionError("zombie COMMIT should 409")
        except APIError as e:
            assert e.status == 409

        # Survivor finishes on agent-1.
        _wait_experiment(c, eid, token, timeout=180)
        survivor = c.api("GET", f"/api/v1/trials/{tid}", token=token)["trial"]
        assert survivor["state"] == "COMPLETED"
        assert survivor["latest_checkpoint"] != "ck-zombie"
        new_epoch = survivor["run_id"]
        try:
            plain.get("/api/v1/checkpoints/ck-zombie")
            raise AssertionError("zombie PARTIAL should be swept")
        except APIError as e:
            assert e.status == 404

        # Exactly one COMPLETED lineage: every COMPLETED checkpoint
        # belongs to the surviving run, none to the zombie's.
        lineage = plain.get(f"/api/v1/trials/{tid}/checkpoints",
                            params={"state": "COMPLETED"})["checkpoints"]
        assert lineage, "survivor must have committed checkpoints"
        assert len({ck["uuid"] for ck in lineage}) == len(lineage)
        assert "ck-zombie" not in {ck["uuid"] for ck in lineage}

        text = _scrape(c.master_url, token)
        assert (_metric_value(text, "det_fenced_writes_total",
                              'route="metrics"') or 0) >= 1
        assert (_metric_value(text, "det_fenced_writes_total",
                              'route="checkpoints"') or 0) >= 1

        # Heal: removing the faults file disarms the blackhole and the
        # zombie agent re-registers.
        os.remove(faults_file)
        deadline = time.time() + 30
        while time.time() < deadline:
            agents = c.api("GET", "/api/v1/agents", token=token)["agents"]
            if any(a["id"] == "agent-0" and a["alive"] for a in agents):
                break
            time.sleep(0.5)
        else:
            raise AssertionError("healed agent never re-registered")

        # Control: the same config on the healed cluster, no partition.
        # The fixture's trajectory is deterministic (loss = 1/steps,
        # val_loss = lr/(1+steps)), so the surviving run's reports must be
        # bit-identical to the control's.
        ceid, _ = _create_experiment(c, config)
        _wait_experiment(c, ceid, token, timeout=180)
        ctrial = c.api("GET", f"/api/v1/experiments/{ceid}/trials",
                       token=token)["trials"][0]

        def rows(trial_id, group, run_id=None):
            out = c.api(
                "GET", f"/api/v1/trials/{trial_id}/metrics?group={group}",
                token=token)["metrics"]
            if run_id is not None:
                out = [m for m in out if m["trial_run_id"] == run_id]
            return [(m["total_batches"], m["metrics"]) for m in out]

        assert rows(tid, "validation", new_epoch) == rows(
            ctrial["id"], "validation", ctrial["run_id"])
        assert rows(tid, "training", new_epoch) == rows(
            ctrial["id"], "training", ctrial["run_id"])
    finally:
        c.stop()
