"""Autotune (dsat-equivalent) tests: pure search-logic unit tests + a full
custom-searcher e2e on the devcluster (reference
pytorch/dsat/_dsat_search_method.py workflow)."""

import pytest

from determined_tpu.autotune import BatchSizeSearchMethod
from tests.test_platform_e2e import Devcluster, native_binaries  # noqa: F401


class TestSearchLogic:
    def drive(self, method, fits):
        """Simulate the master: run ops until Shutdown; `fits(size)` decides
        OOM. Returns the per-size throughput the method collected."""
        ops = list(method.initial_operations())
        guard = 0
        while ops and guard < 100:
            guard += 1
            op = ops.pop(0)
            kind = type(op).__name__
            if kind == "Create":
                self.sizes[op.request_id] = op.hparams["global_batch_size"]
            elif kind == "ValidateAfter":
                size = self.sizes[op.request_id]
                if fits(size):
                    # throughput grows with size (amortized overhead)
                    ops += method.on_validation_completed(
                        op.request_id, size * 10.0 / (1 + size / 100), op.length)
                else:
                    ops += method.on_trial_exited_early(
                        op.request_id, "errored")
            elif kind == "Close":
                ops += method.on_trial_closed(op.request_id)
            elif kind == "Shutdown":
                return
        raise AssertionError("search did not shut down")

    def setup_method(self, m):
        self.sizes = {}

    def test_cliff_then_binary_search(self):
        method = BatchSizeSearchMethod(start_size=8, max_size=1024)
        self.drive(method, fits=lambda s: s <= 100)
        best, sps = method.best()
        # doubling: 8,16,32,64 fit; 128 fails; binary: 96 fits...
        assert 64 <= best <= 100
        assert method.failed_sizes and min(method.failed_sizes) <= 128
        assert method.progress() == 1.0

    def test_everything_fits_caps_at_max(self):
        method = BatchSizeSearchMethod(start_size=8, max_size=64)
        self.drive(method, fits=lambda s: True)
        best, _ = method.best()
        assert best == 64
        assert method.failed_sizes == []

    def test_nothing_fits(self):
        method = BatchSizeSearchMethod(start_size=8)
        self.drive(method, fits=lambda s: False)
        assert method.results == {}
        assert method.progress() == 1.0

    def test_transient_failure_retried_not_bounded(self):
        """A one-off crash (flaky node) must not become the OOM cliff."""
        flaked = []

        def fits(size):
            if size == 16 and not flaked:
                flaked.append(size)
                return False  # transient: fails once, then fits
            return size <= 40

        method = BatchSizeSearchMethod(start_size=8, max_size=256)
        self.drive(method, fits=fits)
        best, _ = method.best()
        assert best >= 32, (best, method.results)  # recovered past 16
        assert 16 not in method.failed_sizes

    def test_user_cancel_stops_search(self):
        method = BatchSizeSearchMethod(start_size=8)
        ops = method.initial_operations()
        rid = ops[0].request_id
        out = method.on_trial_exited_early(rid, "user_canceled")
        assert type(out[0]).__name__ == "Shutdown"
        assert method.progress() == 1.0

    def test_extra_hparams_passthrough(self):
        method = BatchSizeSearchMethod(
            start_size=8, base_hparams={"remat": True})
        ops = method.initial_operations()
        assert ops[0].hparams == {"remat": True, "global_batch_size": 8}


@pytest.fixture()
def cluster(tmp_path, native_binaries):  # noqa: F811
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    c.start_agent()
    yield c
    c.stop()


def test_autotune_e2e(cluster, tmp_path):
    """The full dsat-style workflow: RemoteSearchRunner drives the
    autotuner against real trials that fake an OOM cliff at 64."""
    import os

    from determined_tpu.experimental.client import Determined
    from determined_tpu.searcher import RemoteSearchRunner
    from tests.test_platform_e2e import FIXTURES

    os.environ["DET_MASTER"] = cluster.master_url
    try:
        client = Determined(cluster.master_url)
        method = BatchSizeSearchMethod(start_size=8, max_size=512,
                                       profile_steps=2)
        runner = RemoteSearchRunner(method, client=client)
        config = {
            "name": "autotune-batch-size",
            "entrypoint": "python3 autotune_train.py",
            "searcher": {"name": "custom", "metric": "samples_per_second",
                         "smaller_is_better": False},
            "environment": {"FAKE_MEMORY_LIMIT": "64",
                            "TRIAL_STEP_SLEEP": "0.0"},
            "checkpoint_storage": {
                "type": "shared_fs",
                "host_path": str(tmp_path / "ckpts")},
            "resources": {"slots_per_trial": 1},
            "max_restarts": 0,
        }
        eid = runner.run(config, model_dir=FIXTURES)
        assert eid > 0
        best, sps = method.best()
        assert best == 64, (best, method.results, method.failed_sizes)
        assert sps > 0
        # the cliff hunt tried 128 and failed it
        assert 128 in method.failed_sizes
    finally:
        os.environ.pop("DET_MASTER", None)
