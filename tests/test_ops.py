"""Attention kernel tests: fused flash vs reference, ring vs single-device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from determined_tpu.ops.flash_attention import _xla_attention, flash_attention
from determined_tpu.ops.ring_attention import ring_attention
from determined_tpu.parallel import MeshConfig, create_mesh


def _qkv(key, b=2, s=32, h=4, d=8, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    mk = lambda k: jax.random.normal(k, (b, s, h, d), dtype)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


class TestFlashAttention:
    def test_matches_reference_causal(self):
        q, k, v = _qkv(jax.random.PRNGKey(0))
        out = flash_attention(q, k, v, causal=True)
        ref = _xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_causality(self):
        q, k, v = _qkv(jax.random.PRNGKey(1))
        out1 = flash_attention(q, k, v, causal=True)
        k2 = k.at[:, -1].set(99.0)
        v2 = v.at[:, -1].set(99.0)
        out2 = flash_attention(q, k2, v2, causal=True)
        np.testing.assert_allclose(
            np.asarray(out1[:, :-1]), np.asarray(out2[:, :-1]), atol=1e-5
        )


class TestPallasFlashAttention:
    """Numerical equivalence of the pallas kernel vs _xla_attention.

    Runs the TPU kernel in interpreter mode on the CPU test mesh; on real
    TPU hardware the same code path compiles via Mosaic (exercised by
    bench.py and the dryrun gate).
    """

    def _run(self, fn, *args):
        from jax.experimental.pallas import tpu as pltpu

        with pltpu.force_tpu_interpret_mode():
            return fn(*args)

    def test_fwd_matches_reference(self):
        from determined_tpu.ops.pallas_attention import pallas_flash_attention

        q, k, v = _qkv(jax.random.PRNGKey(0), b=1, s=256, h=2, d=64)
        out = self._run(pallas_flash_attention, q, k, v)
        ref = _xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=1e-4)

    def test_bwd_matches_reference(self):
        from determined_tpu.ops.pallas_attention import pallas_flash_attention

        q, k, v = _qkv(jax.random.PRNGKey(3), b=1, s=256, h=2, d=64)

        def loss_p(q, k, v):
            return jnp.sum(pallas_flash_attention(q, k, v, True) ** 2)

        def loss_x(q, k, v):
            return jnp.sum(_xla_attention(q, k, v, True) ** 2)

        gp = self._run(jax.grad(loss_p, argnums=(0, 1, 2)), q, k, v)
        gx = jax.grad(loss_x, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gx):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-3, rtol=1e-3)

    def test_multiblock_causality(self):
        """Blocks beyond the causal frontier must not leak (s > block sizes)."""
        from determined_tpu.ops import pallas_attention as pa

        q, k, v = _qkv(jax.random.PRNGKey(4), b=1, s=512, h=1, d=64)
        out1 = self._run(pa.pallas_flash_attention, q, k, v)
        k2 = k.at[:, 300:].add(50.0)
        v2 = v.at[:, 300:].add(50.0)
        out2 = self._run(pa.pallas_flash_attention, q, k2, v2)
        np.testing.assert_allclose(np.asarray(out1[:, :300]),
                                   np.asarray(out2[:, :300]), atol=1e-4)


class TestPallasReferenceEquivalence:
    """PR 18 gates: pallas ≡ reference forward AND backward (interpret mode
    on CPU — the same kernel code Mosaic compiles on TPU) across the shape
    families the trainer produces: single-block, multi-block causal,
    non-causal (padded batches run full attention over the padded length),
    and the MoE/large-head geometry (d=128, non-pow2 sequence)."""

    SHAPES = [
        pytest.param(2, 128, 2, 64, True, id="single-block-causal"),
        pytest.param(1, 256, 2, 64, True, id="multi-block-causal"),
        pytest.param(1, 256, 2, 64, False, id="non-causal-padded"),
        pytest.param(1, 384, 1, 128, True, id="moe-head128-nonpow2-seq"),
    ]

    def _run(self, fn, *args):
        from jax.experimental.pallas import tpu as pltpu

        with pltpu.force_tpu_interpret_mode():
            return fn(*args)

    @pytest.mark.parametrize("b,s,h,d,causal", SHAPES)
    @pytest.mark.parametrize("bf16", [False, True],
                             ids=["f32", "bf16"])
    def test_fwd_and_bwd_match_reference(self, b, s, h, d, causal, bf16):
        from determined_tpu.ops.flash_attention import (
            pallas_flash_attention, reference_attention)

        q, k, v = _qkv(jax.random.PRNGKey(7), b=b, s=s, h=h, d=d)

        out = self._run(pallas_flash_attention, q, k, v, causal, bf16)
        ref = reference_attention(q, k, v, causal=causal, bf16=bf16)
        # bf16 probability matmuls lose mantissa; fp32 stats keep the
        # error bounded to bf16 resolution.
        fwd_tol = dict(atol=1e-2, rtol=1e-2) if bf16 else \
            dict(atol=2e-5, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   **fwd_tol)

        def loss_p(q, k, v):
            return jnp.sum(
                pallas_flash_attention(q, k, v, causal, bf16) ** 2)

        def loss_r(q, k, v):
            return jnp.sum(
                reference_attention(q, k, v, causal=causal,
                                    bf16=bf16) ** 2)

        gp = self._run(jax.grad(loss_p, argnums=(0, 1, 2)), q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        bwd_tol = dict(atol=5e-2, rtol=5e-2) if bf16 else \
            dict(atol=2e-3, rtol=2e-3)
        for a, r in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       **bwd_tol)

    @pytest.mark.parametrize("causal", [True, False])
    def test_reference_grad_matches_naive_dense(self, causal):
        """The reference path is exactly dense-attention arithmetic: its
        jax.grad must equal jax.grad of an inline naive implementation."""
        from determined_tpu.ops.flash_attention import reference_attention

        q, k, v = _qkv(jax.random.PRNGKey(11), b=2, s=48, h=2, d=16)

        def naive(q, k, v):
            scale = 1.0 / np.sqrt(q.shape[-1])
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
            if causal:
                s = q.shape[1]
                mask = jnp.tril(jnp.ones((s, s), jnp.bool_))
                logits = jnp.where(mask, logits,
                                   jnp.finfo(jnp.float32).min)
            probs = jax.nn.softmax(logits, axis=-1)
            return jnp.einsum("bhqk,bkhd->bqhd", probs, v)

        def l_ref(q, k, v):
            return jnp.sum(
                reference_attention(q, k, v, causal=causal) ** 2)

        def l_naive(q, k, v):
            return jnp.sum(naive(q, k, v) ** 2)

        gr = jax.grad(l_ref, argnums=(0, 1, 2))(q, k, v)
        gn = jax.grad(l_naive, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gn):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5)

    def test_explicit_pallas_unsupported_shape_falls_back(self):
        from determined_tpu.ops.flash_attention import (
            _xla_attention, flash_attention)

        # d=8 can't tile on the MXU: explicit pallas must still answer,
        # via the reference path, with dense arithmetic.
        q, k, v = _qkv(jax.random.PRNGKey(12), b=1, s=32, h=2, d=8)
        out = flash_attention(q, k, v, causal=True, impl="pallas")
        ref = _xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_single_device(self, devices, causal):
        mesh = create_mesh(MeshConfig(data=2, context=4), devices)
        q, k, v = _qkv(jax.random.PRNGKey(0), b=4, s=32)
        ref = _xla_attention(q, k, v, causal=causal)
        with jax.sharding.set_mesh(mesh):
            out = jax.jit(
                lambda q, k, v: ring_attention(q, k, v, causal=causal, mesh=mesh)
            )(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)

    def test_context_axis_size_one_falls_back(self, devices):
        mesh = create_mesh(MeshConfig(data=8), devices)
        q, k, v = _qkv(jax.random.PRNGKey(2))
        with jax.sharding.set_mesh(mesh):
            out = ring_attention(q, k, v, mesh=mesh)
        ref = _xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_gpt2_with_ring_attention(self, devices):
        """End-to-end: GPT-2 tiny configured with attention_impl='ring'."""
        from determined_tpu.models import gpt2

        cfg_ring = gpt2.Config(
            vocab_size=128, n_positions=64, d_model=32, n_layer=1, n_head=2,
            attention_impl="ring", remat=False, dtype=jnp.float32,
        )
        cfg_dot = gpt2.Config(
            vocab_size=128, n_positions=64, d_model=32, n_layer=1, n_head=2,
            attention_impl="dot", remat=False, dtype=jnp.float32,
        )
        params = gpt2.init(jax.random.PRNGKey(0), cfg_dot)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
        ref = gpt2.apply(params, tokens, cfg_dot)
        mesh = create_mesh(MeshConfig(data=2, context=4), devices)
        with jax.sharding.set_mesh(mesh):
            out = jax.jit(lambda p, t: gpt2.apply(p, t, cfg_ring))(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)
