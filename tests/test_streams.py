"""Streaming updates e2e (reference internal/stream/publisher.go +
common/streams/_client.py): entity-change events long-polled while an
experiment runs."""

import threading

import pytest

from determined_tpu.common.api import Session
from determined_tpu.common.streams import StreamClient
from tests.test_platform_e2e import (  # noqa: F401
    Devcluster,
    _create_experiment,
    _experiment_config,
    _wait_experiment,
    native_binaries,
)


@pytest.fixture()
def cluster(tmp_path, native_binaries):  # noqa: F811
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    c.start_agent()
    yield c
    c.stop()


def test_stream_events_during_experiment(cluster, tmp_path):
    token = cluster.login()
    session = Session(cluster.master_url, token)
    client = StreamClient(session)

    events = []
    stop = threading.Event()

    def consume():
        while not stop.is_set():
            events.extend(client.poll(timeout_seconds=2))

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    eid, _ = _create_experiment(
        cluster, _experiment_config(tmp_path), activate=True)
    _wait_experiment(cluster, eid, token)
    stop.set()
    t.join(timeout=10)

    entities = {e["entity"] for e in events}
    assert {"experiments", "trials", "metrics", "checkpoints"} <= entities, (
        entities)
    # ordered, gapless sequence numbers
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs)
    assert len(set(seqs)) == len(seqs)
    # lifecycle visible: ACTIVE before COMPLETED for our experiment
    states = [e["payload"]["state"] for e in events
              if e["entity"] == "experiments" and e["payload"]["id"] == eid]
    assert "ACTIVE" in states and states[-1] == "COMPLETED", states
    # trial completion observed
    tstates = [e["payload"]["state"] for e in events
               if e["entity"] == "trials"]
    assert "COMPLETED" in tstates
    assert not client.dropped


def test_stream_entity_filter_and_since(cluster, tmp_path):
    token = cluster.login()
    session = Session(cluster.master_url, token)
    eid, _ = _create_experiment(
        cluster, _experiment_config(tmp_path), activate=True)
    _wait_experiment(cluster, eid, token)

    only_exp = StreamClient(session).poll(
        entities=["experiments"], timeout_seconds=1)
    assert only_exp and all(e["entity"] == "experiments" for e in only_exp)

    # since-cursor: polling from the last seq returns nothing new
    c2 = StreamClient(session)
    first = c2.poll(timeout_seconds=1)
    assert first
    again = c2.poll(timeout_seconds=1)
    assert again == []
