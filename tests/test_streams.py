"""Streaming updates e2e (reference internal/stream/publisher.go +
common/streams/_client.py): entity-change events long-polled while an
experiment runs."""

import threading

import pytest

from determined_tpu.common.api import Session
from determined_tpu.common.streams import StreamClient
from tests.test_platform_e2e import (  # noqa: F401
    Devcluster,
    _create_experiment,
    _experiment_config,
    _wait_experiment,
    native_binaries,
)


@pytest.fixture()
def cluster(tmp_path, native_binaries):  # noqa: F811
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    c.start_agent()
    yield c
    c.stop()


def test_stream_events_during_experiment(cluster, tmp_path):
    token = cluster.login()
    session = Session(cluster.master_url, token)
    client = StreamClient(session)

    events = []
    stop = threading.Event()

    def consume():
        while not stop.is_set():
            events.extend(client.poll(timeout_seconds=2))

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    eid, _ = _create_experiment(
        cluster, _experiment_config(tmp_path), activate=True)
    _wait_experiment(cluster, eid, token)
    stop.set()
    t.join(timeout=10)

    entities = {e["entity"] for e in events}
    assert {"experiments", "trials", "metrics", "checkpoints"} <= entities, (
        entities)
    # ordered, gapless sequence numbers
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs)
    assert len(set(seqs)) == len(seqs)
    # lifecycle visible: ACTIVE before COMPLETED for our experiment
    states = [e["payload"]["state"] for e in events
              if e["entity"] == "experiments" and e["payload"]["id"] == eid]
    assert "ACTIVE" in states and states[-1] == "COMPLETED", states
    # trial completion observed
    tstates = [e["payload"]["state"] for e in events
               if e["entity"] == "trials"]
    assert "COMPLETED" in tstates
    assert not client.dropped


def test_stream_resync_marker_on_overflow(tmp_path, native_binaries):  # noqa: F811
    """Bounded backlog (docs/cluster-ops.md "Overload, quotas & fair
    use"): a slow subscriber whose cursor fell off the capped ring gets a
    synthetic `resync` marker at the head of its next batch (plus the
    response-level dropped flag) and must re-list; a subscriber that keeps
    up loses nothing."""
    import json as _json
    import os as _os

    cfg_path = _os.path.join(str(tmp_path), "master-ring.json")
    with open(cfg_path, "w") as f:
        _json.dump({"stream_backlog_cap": 16}, f)
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master(extra_args=("--config", cfg_path))
    try:
        token = c.login()
        session = Session(c.master_url, token)
        eid = c.api("POST", "/api/v1/experiments",
                    {"unmanaged": True, "config": {"name": "stream-ring"}},
                    token=token)["id"]
        tid = c.api("POST", f"/api/v1/experiments/{eid}/trials",
                    {"hparams": {}}, token=token)["id"]

        slow = StreamClient(session)
        fast = StreamClient(session)
        # Prime both cursors with one real event: a fresh subscriber
        # (since=0) is exempt from drop detection by design — only a
        # cursor that points at evicted history must resync.
        c.api("POST", f"/api/v1/trials/{tid}/metrics",
              {"group": "training", "steps_completed": 0,
               "trial_run_id": 0, "metrics": {"loss": 9.0}}, token=token)
        assert slow.poll(timeout_seconds=2.0)
        assert fast.poll(timeout_seconds=2.0)

        fast_events = []
        for batch in range(6):
            for i in range(10):
                c.api("POST", f"/api/v1/trials/{tid}/metrics",
                      {"group": "training",
                       "steps_completed": 1 + batch * 10 + i,
                       "trial_run_id": 0, "metrics": {"loss": 1.0}},
                      token=token)
            # The fast subscriber drains between bursts — each burst (10)
            # fits the 16-slot ring, so it never falls behind.
            fast_events += fast.poll(timeout_seconds=1.0)

        # 60 events went past a 16-slot ring: the slow cursor is gone.
        events = slow.poll(timeout_seconds=1.0)
        assert slow.dropped
        assert events and events[0]["entity"] == "resync", events[:2]
        marker = events[0]["payload"]
        assert marker["latest_seq"] >= marker["since"]
        assert "re-list" in marker["reason"]
        # The marker precedes real events; the cursor still advances.
        assert all(e["entity"] != "resync" for e in events[1:])

        # The fast subscriber saw every report exactly once, in order.
        assert not fast.dropped
        metrics = [e for e in fast_events if e["entity"] == "metrics"]
        assert len(metrics) == 60, len(metrics)
        seqs = [e["seq"] for e in fast_events]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    finally:
        c.stop()


def test_stream_entity_filter_and_since(cluster, tmp_path):
    token = cluster.login()
    session = Session(cluster.master_url, token)
    eid, _ = _create_experiment(
        cluster, _experiment_config(tmp_path), activate=True)
    _wait_experiment(cluster, eid, token)

    only_exp = StreamClient(session).poll(
        entities=["experiments"], timeout_seconds=1)
    assert only_exp and all(e["entity"] == "experiments" for e in only_exp)

    # since-cursor: polling from the last seq returns nothing new
    c2 = StreamClient(session)
    first = c2.poll(timeout_seconds=1)
    assert first
    again = c2.poll(timeout_seconds=1)
    assert again == []
