"""`det deploy gke` generator (reference harness/determined/deploy/gke/):
the manifests must be valid YAML, pair with the kubernetes RM's config
contract, and wire the headless-service DNS the RM relies on."""

import json
import subprocess
import sys

import yaml


def test_gke_manifests(tmp_path):
    from determined_tpu.deploy import gke

    out = gke.generate(str(tmp_path / "gke"), project="p", cluster="c",
                       namespace="ns", slots_per_pod=4, num_nodes=3)

    master_docs = list(yaml.safe_load_all(open(f"{out}/master.yaml")))
    kinds = [d["kind"] for d in master_docs]
    assert kinds == ["PersistentVolumeClaim", "ConfigMap", "Deployment",
                     "Service"]
    cfg = json.loads(master_docs[1]["data"]["master.json"])
    # The served config must match the master's kubernetes RM schema
    # (MasterConfig::from_json keys).
    assert cfg["resource_manager"] == "kubernetes"
    assert cfg["kubernetes"]["namespace"] == "ns"
    assert cfg["kubernetes"]["slots_per_pod"] == 4
    # Shape round-trip (VERDICT r4 #7): the node pool the cluster script
    # creates and the selectors task pods will carry must agree.
    assert cfg["kubernetes"]["accelerator_type"] == "tpu-v5-lite-podslice"
    assert cfg["kubernetes"]["topology"] == "2x2"  # 4-chip v5e host shape
    cluster_sh = open(f"{out}/cluster.sh").read()
    assert "--tpu-topology 2x2" in cluster_sh
    assert cfg["advertised_url"].startswith("http://determined-master.ns")
    dep = master_docs[2]
    assert dep["spec"]["template"]["spec"]["serviceAccountName"] == \
        "determined-master"

    rbac = list(yaml.safe_load_all(open(f"{out}/rbac.yaml")))
    role = next(d for d in rbac if d["kind"] == "Role")
    assert {"create", "delete", "list"} <= set(role["rules"][0]["verbs"])

    svc = yaml.safe_load(open(f"{out}/task-svc.yaml"))
    assert svc["spec"]["clusterIP"] == "None"  # k8s headless literal
    assert svc["metadata"]["name"] == cfg["kubernetes"]["service_subdomain"]
    assert svc["spec"]["selector"] == {"det-managed": "true"}

    sh = open(f"{out}/cluster.sh").read()
    assert "ct5lp-hightpu-4t" in sh and "--num-nodes 3" in sh

    # bad host shape rejected
    import pytest
    with pytest.raises(ValueError, match="slots_per_pod"):
        gke.generate(str(tmp_path / "bad"), project="p", slots_per_pod=3)


def test_gke_cli(tmp_path):
    r = subprocess.run(
        [sys.executable, "-m", "determined_tpu.cli", "deploy", "gke",
         str(tmp_path / "out"), "--project", "p", "--slots-per-pod", "8"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "kubectl apply" in r.stdout
    assert (tmp_path / "out" / "master.yaml").exists()
