"""Test bootstrap: run everything on a virtual 8-device CPU "slice".

Mirrors the reference's threads-as-ranks / artificial-slots testing ideas
(SURVEY.md §4): shardings and collectives are exercised for real, on CPU.
Must run before jax initialises any backend, hence top of conftest.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("KERAS_BACKEND", "jax")  # Keras 3 on the JAX backend

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from determined_tpu import _jax_compat  # noqa: E402

_jax_compat.install()  # jax.sharding.set_mesh & co on jax < 0.5

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual cpu devices, got {devs}"
    return devs


@pytest.fixture()
def rng():
    return jax.random.PRNGKey(0)


@pytest.fixture()
def np_rng():
    return np.random.default_rng(0)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running e2e (excluded from the tier-1 time budget)",
    )
