"""Model forward/loss sanity on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from determined_tpu.models import diffusion, gpt2, mnist, resnet
from determined_tpu.parallel import MeshConfig, create_mesh
from determined_tpu.train import create_train_state, make_train_step


class TestGPT2:
    @pytest.fixture(scope="class")
    def cfg(self):
        return gpt2.Config.tiny()

    def test_forward_shapes(self, cfg):
        params = gpt2.init(jax.random.PRNGKey(0), cfg)
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = jax.jit(lambda p, t: gpt2.apply(p, t, cfg))(params, tokens)
        assert logits.shape == (2, 16, cfg.vocab_size)

    def test_loss_decreases(self, cfg):
        params = gpt2.init(jax.random.PRNGKey(0), cfg)
        tx = optax.adam(1e-2)
        state = create_train_state(lambda r: gpt2.init(r, cfg), tx, jax.random.PRNGKey(0))
        step = make_train_step(lambda p, b, r: gpt2.loss_fn(p, b, cfg), tx)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0, 64)
        batch = {"tokens": tokens}
        first = None
        for i in range(10):
            state, metrics = step(state, batch, jax.random.PRNGKey(i))
            if first is None:
                first = float(metrics["loss"])
        assert float(metrics["loss"]) < first

    def test_causality(self, cfg):
        """Changing a future token must not change past logits."""
        params = gpt2.init(jax.random.PRNGKey(0), cfg)
        t1 = jnp.zeros((1, 8), jnp.int32)
        t2 = t1.at[0, 7].set(5)
        l1 = gpt2.apply(params, t1, cfg)
        l2 = gpt2.apply(params, t2, cfg)
        np.testing.assert_allclose(l1[0, :7], l2[0, :7], atol=1e-5)

    def test_sharded_train_step(self, cfg, devices):
        mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2), devices)
        tx = optax.adamw(1e-3)
        with jax.sharding.set_mesh(mesh):
            state = create_train_state(
                lambda r: gpt2.init(r, cfg),
                tx,
                jax.random.PRNGKey(0),
                mesh=mesh,
                param_logical_axes=gpt2.param_logical_axes(cfg),
            )
            step = make_train_step(lambda p, b, r: gpt2.loss_fn(p, b, cfg), tx, mesh=mesh)
            tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0, 64)
            state, metrics = step(state, {"tokens": tokens}, jax.random.PRNGKey(2))
        assert np.isfinite(float(metrics["loss"]))
        # qkv kernel sharded over fsdp rows and tensor cols
        qkv = state.params["blocks"]["qkv"]["kernel"]
        assert qkv.sharding.spec == jax.sharding.PartitionSpec(None, "fsdp", "tensor")

    def test_sharded_matches_single_device(self, cfg, devices):
        """DP/TP sharding must not change the math."""
        tx = optax.sgd(1e-2)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0, 64)
        batch = {"tokens": tokens}

        state1 = create_train_state(lambda r: gpt2.init(r, cfg), tx, jax.random.PRNGKey(0))
        step1 = make_train_step(lambda p, b, r: gpt2.loss_fn(p, b, cfg), tx)
        _, m1 = step1(state1, batch, jax.random.PRNGKey(2))

        mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2), devices)
        with jax.sharding.set_mesh(mesh):
            state8 = create_train_state(
                lambda r: gpt2.init(r, cfg), tx, jax.random.PRNGKey(0),
                mesh=mesh, param_logical_axes=gpt2.param_logical_axes(cfg),
            )
            step8 = make_train_step(lambda p, b, r: gpt2.loss_fn(p, b, cfg), tx, mesh=mesh)
            _, m8 = step8(state8, batch, jax.random.PRNGKey(2))
        np.testing.assert_allclose(float(m1["loss"]), float(m8["loss"]), rtol=2e-2)

    def test_param_count_gpt2_small(self):
        assert abs(gpt2.param_count(gpt2.Config.small()) - 124e6) / 124e6 < 0.02


class TestMNIST:
    def test_train_improves_accuracy(self, np_rng):
        cfg = mnist.Config()
        tx = optax.adam(1e-3)
        state = create_train_state(lambda r: mnist.init(r, cfg), tx, jax.random.PRNGKey(0))
        step = make_train_step(lambda p, b, r: mnist.loss_fn(p, b, cfg), tx)
        # learnable synthetic task: label = quadrant of bright blob
        images = np_rng.normal(size=(64, 28, 28, 1)).astype(np.float32)
        labels = (images.sum((1, 2, 3)) > 0).astype(np.int32)
        batch = {"images": jnp.asarray(images), "labels": jnp.asarray(labels)}
        for i in range(30):
            state, metrics = step(state, batch, jax.random.PRNGKey(i))
        assert float(metrics["accuracy"]) > 0.9


class TestResNet:
    def test_stateful_step_updates_bn(self):
        cfg = resnet.Config(stage_sizes=(1, 1), num_filters=8)
        params, stats = resnet.init(jax.random.PRNGKey(0), cfg)
        tx = optax.sgd(1e-2)
        state = create_train_state(
            lambda r: resnet.init(r, cfg)[0], tx, jax.random.PRNGKey(0), extra=stats
        )
        step = make_train_step(
            lambda p, e, b, r: resnet.loss_fn(p, e, b, r, cfg),
            tx,
            stateful=True,
        )
        images = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
        labels = jnp.zeros((4,), jnp.int32)
        old_mean = np.asarray(state.extra["stem_bn"]["mean"]).copy()
        state, metrics = step(state, {"images": images, "labels": labels}, jax.random.PRNGKey(2))
        assert np.isfinite(float(metrics["loss"]))
        assert not np.allclose(np.asarray(state.extra["stem_bn"]["mean"]), old_mean)

    def test_eval_mode_uses_running_stats(self):
        cfg = resnet.Config(stage_sizes=(1, 1), num_filters=8)
        params, stats = resnet.init(jax.random.PRNGKey(0), cfg)
        images = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        logits, new_stats = resnet.apply(params, stats, images, cfg, train=False)
        assert logits.shape == (2, cfg.n_classes)
        # eval must not touch stats
        np.testing.assert_array_equal(
            np.asarray(new_stats["stem_bn"]["mean"]), np.asarray(stats["stem_bn"]["mean"])
        )

    def test_resnet50_shapes(self):
        cfg = resnet.Config.resnet50(n_classes=100)
        params, stats = jax.eval_shape(lambda r: resnet.init(r, cfg), jax.random.PRNGKey(0))
        assert params["head"]["kernel"].shape == (2048, 100)


class TestDiffusion:
    def test_apply_shapes_and_dtype(self):
        cfg = diffusion.Config.tiny()
        p = diffusion.init(jax.random.PRNGKey(0), cfg)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16, 3))
        t = jnp.array([0, cfg.timesteps - 1], jnp.int32)
        out = jax.jit(lambda p, x, t: diffusion.apply(p, x, t, cfg))(p, x, t)
        assert out.shape == x.shape and out.dtype == jnp.float32
        # zero-init output conv: the untrained denoiser predicts ~0
        assert float(jnp.abs(out).max()) == 0.0

    def test_loss_decreases(self):
        cfg = diffusion.Config.tiny()
        tx = optax.adam(2e-3)
        state = create_train_state(
            lambda r: diffusion.init(r, cfg), tx, jax.random.PRNGKey(0))
        step = make_train_step(
            lambda p, b, r: diffusion.loss_fn(p, b, cfg, r), tx)
        images = np.clip(np.random.default_rng(0).normal(
            0, 0.3, (16, 16, 16, 3)), -1, 1).astype(np.float32)
        batch = {"images": jnp.asarray(images)}
        losses = []
        for i in range(30):
            state, metrics = step(state, batch, jax.random.PRNGKey(i % 4))
            losses.append(float(metrics["loss"]))
        assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses

    def test_logical_axes_match_param_tree(self):
        cfg = diffusion.Config.tiny()
        p = diffusion.init(jax.random.PRNGKey(0), cfg)
        ax = diffusion.param_logical_axes(cfg)
        # tree_map raises if the structures disagree
        jax.tree_util.tree_map(
            lambda arr, spec: None, p, ax,
            is_leaf=lambda a: isinstance(a, tuple))

    def test_sharded_train_step_on_mesh(self, devices):
        cfg = diffusion.Config.tiny()
        mesh = create_mesh(MeshConfig(data=2, fsdp=4).resolve(8), devices)
        tx = optax.adam(1e-3)
        with jax.sharding.set_mesh(mesh):
            state = create_train_state(
                lambda r: diffusion.init(r, cfg), tx, jax.random.PRNGKey(0),
                mesh=mesh, param_logical_axes=diffusion.param_logical_axes(cfg),
            )
            step = make_train_step(
                lambda p, b, r: diffusion.loss_fn(p, b, cfg, r), tx,
                mesh=mesh)
            images = jnp.zeros((8, 16, 16, 3))
            state, metrics = step(
                state, {"images": images}, jax.random.PRNGKey(0))
        assert np.isfinite(float(metrics["loss"]))
        # the big mid conv kernels actually sharded over fsdp
        spec = state.params["mid"]["res1"]["conv1"]["kernel"].sharding.spec
        assert "fsdp" in str(spec), spec

    def test_sample_shape_and_range(self):
        cfg = diffusion.Config.tiny()
        p = diffusion.init(jax.random.PRNGKey(0), cfg)
        imgs = diffusion.sample(p, jax.random.PRNGKey(1), 2, cfg)
        assert imgs.shape == (2, 16, 16, 3)
        assert float(jnp.abs(imgs).max()) <= 1.0
