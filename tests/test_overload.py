"""Overload-safe multi-tenant master (docs/cluster-ops.md "Overload,
quotas & fair use", docs/chaos.md `db.tx.stall` / `api.overload.force_shed`).

Fast tests (tier-1): pagination abuse is refused with 400 and honest
envelopes, per-token admission control answers 429 + Retry-After, and the
idempotency-key dedupe survives group-commit batching — a retry landing in
the SAME flush window and one landing AFTER the flush both resolve to one
row and a replayed response.

Slow tests (`make chaos`): a stalled/failing DB under a keyed retry storm
turns into bounded 429/503 backpressure with EXACTLY one row per report
(zero lost, zero duplicated), and a forced brownout sheds interactive
reads with the distinct 503 while trial-critical writes pass untouched,
then recovers through the hysteresis hold once the pressure clears.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from test_platform_e2e import (  # noqa: F401  (fixture re-export)
    Devcluster,
    native_binaries,
)


@pytest.fixture()
def master_only(tmp_path, native_binaries):
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    yield c
    c.stop()


def _boot(tmp_path, native_binaries, config):
    """A master booted with an overload --config (the deployment shape:
    flags still win, the file sets what flags don't cover)."""
    path = os.path.join(str(tmp_path), "master-overload.json")
    with open(path, "w") as f:
        json.dump(config, f)
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master(extra_args=("--config", path))
    return c


def _raw(cluster, method, path, body=None, token=None, headers=None,
         timeout=30.0):
    """(status, json, headers) — never raises on HTTP errors; these tests
    exist to SEE the 400/429/503s."""
    req = urllib.request.Request(
        cluster.master_url + path, method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json",
                 **({"Authorization": f"Bearer {token}"} if token else {}),
                 **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return (resp.status, json.loads(resp.read() or b"{}"),
                    dict(resp.headers))
    except urllib.error.HTTPError as e:
        try:
            out = json.loads(e.read() or b"{}")
        except Exception:  # noqa: BLE001 — error bodies are advisory
            out = {}
        return e.code, out, dict(e.headers)


def _unmanaged_trial(cluster, token, name="overload", n_trials=1):
    eid = cluster.api(
        "POST", "/api/v1/experiments",
        {"unmanaged": True, "config": {"name": name}}, token=token)["id"]
    tids = [cluster.api("POST", f"/api/v1/experiments/{eid}/trials",
                        {"hparams": {}}, token=token)["id"]
            for _ in range(n_trials)]
    return eid, tids


def _metric_rows(cluster, token, tid):
    return cluster.api("GET", f"/api/v1/trials/{tid}/metrics?group=training",
                       token=token)["metrics"]


def _scrape(cluster, token, name, labels=None):
    """Sum of a /metrics series; None if absent. The scrape is
    authenticated like every API route."""
    req = urllib.request.Request(
        cluster.master_url + "/metrics",
        headers={"Authorization": f"Bearer {token}"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        text = resp.read().decode()
    total = None
    for line in text.splitlines():
        if line.startswith("#") or not line.startswith(name):
            continue
        head, _, val = line.rpartition(" ")
        if labels is None:
            if head != name and not head.startswith(name + "{"):
                continue
        elif "{" not in head or not all(
                f'{k}="{v}"' in head[head.index("{"):]
                for k, v in labels.items()):
            continue
        total = (total or 0.0) + float(val)
    return total


# ---------------------------------------------------------------------------
# Pagination: 400 on abuse, honest envelopes (covering indexes in
# migration 28 keep these index scans, not table scans).
# ---------------------------------------------------------------------------

def test_pagination_rejects_abuse(master_only):
    token = master_only.login()
    eid, (tid,) = _unmanaged_trial(master_only, token)

    for path in (
            "/api/v1/experiments?limit=0",
            "/api/v1/experiments?limit=1001",
            "/api/v1/experiments?offset=-1",
            f"/api/v1/experiments/{eid}/trials?limit=99999",
            f"/api/v1/experiments/{eid}/checkpoints?limit=0",
            f"/api/v1/trials/{tid}/checkpoints?offset=-5",
            "/api/v1/tasks?limit=0",
            # task-log limit is validated before the task lookup: the
            # abuse cap refuses even for ids that don't exist.
            "/api/v1/tasks/no-such-task/logs?limit=0",
            "/api/v1/tasks/no-such-task/logs?limit=6000",
    ):
        status, body, _ = _raw(master_only, "GET", path, token=token)
        assert status == 400, (path, status, body)
        assert "limit" in body.get("error", "") or \
            "offset" in body.get("error", ""), (path, body)


def test_pagination_envelopes(master_only):
    token = master_only.login()
    eid, tids = _unmanaged_trial(master_only, token, n_trials=25)

    out = master_only.api(
        "GET", f"/api/v1/experiments/{eid}/trials?limit=10", token=token)
    assert len(out["trials"]) == 10
    assert out["pagination"] == {"total": 25, "offset": 0, "limit": 10}

    out = master_only.api(
        "GET", f"/api/v1/experiments/{eid}/trials?limit=10&offset=20",
        token=token)
    assert len(out["trials"]) == 5
    assert out["pagination"]["total"] == 25

    out = master_only.api("GET", "/api/v1/experiments?limit=200",
                          token=token)
    assert out["pagination"]["total"] >= 1

    # Checkpoint lineage pages the same way.
    for i in range(5):
        master_only.api("POST", "/api/v1/checkpoints",
                        {"uuid": f"ovl-ckpt-{i}", "trial_id": tids[0],
                         "steps_completed": i + 1, "metadata": {},
                         "resources": {}, "state": "COMPLETED"},
                        token=token)
    out = master_only.api(
        "GET", f"/api/v1/trials/{tids[0]}/checkpoints?limit=2&offset=4",
        token=token)
    assert len(out["checkpoints"]) == 1
    assert out["pagination"] == {"total": 5, "offset": 4, "limit": 2}

    # The experiment-scoped listing (what `det checkpoint list` hits)
    # pages the same way.
    out = master_only.api(
        "GET", f"/api/v1/experiments/{eid}/checkpoints?limit=2&offset=4",
        token=token)
    assert len(out["checkpoints"]) == 1
    assert out["pagination"] == {"total": 5, "offset": 4, "limit": 2}

    out = master_only.api("GET", "/api/v1/tasks?limit=5", token=token)
    assert "pagination" in out


# ---------------------------------------------------------------------------
# Idempotency under group commit: retry in the SAME batch and AFTER the
# flush both dedupe to one row.
# ---------------------------------------------------------------------------

def test_idempotent_retry_in_same_batch_dedupes(master_only):
    token = master_only.login()
    _, (tid,) = _unmanaged_trial(master_only, token)
    body = {"group": "training", "steps_completed": 1, "trial_run_id": 0,
            "metrics": {"loss": 0.5}}
    key = "same-batch-key-1"

    results, barrier = [], threading.Barrier(2)

    def post():
        barrier.wait()
        results.append(_raw(master_only, "POST",
                            f"/api/v1/trials/{tid}/metrics", body,
                            token=token,
                            headers={"X-Idempotency-Key": key}))

    threads = [threading.Thread(target=post) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # Both callers succeed — one executed, one was held by the in-flight
    # gate and answered from the replay table — and exactly one row landed.
    assert [st for st, _, _ in results] == [200, 200], results
    assert sum(1 for _, _, h in results
               if h.get("x-idempotent-replay")) == 1, results
    assert len(_metric_rows(master_only, token, tid)) == 1


def test_idempotent_retry_after_flush_replays(master_only):
    token = master_only.login()
    _, (tid,) = _unmanaged_trial(master_only, token)
    body = {"group": "training", "steps_completed": 2, "trial_run_id": 0,
            "metrics": {"loss": 0.25}}
    key = "post-flush-key-1"

    st, _, hdrs = _raw(master_only, "POST", f"/api/v1/trials/{tid}/metrics",
                       body, token=token, headers={"X-Idempotency-Key": key})
    assert st == 200 and not hdrs.get("x-idempotent-replay")
    time.sleep(0.1)  # several flush windows past the commit
    st, _, hdrs = _raw(master_only, "POST", f"/api/v1/trials/{tid}/metrics",
                       body, token=token, headers={"X-Idempotency-Key": key})
    assert st == 200 and hdrs.get("x-idempotent-replay") == "true"
    assert len(_metric_rows(master_only, token, tid)) == 1

    # A DIFFERENT key is a different report.
    st, _, _ = _raw(master_only, "POST", f"/api/v1/trials/{tid}/metrics",
                    dict(body, steps_completed=3), token=token,
                    headers={"X-Idempotency-Key": "post-flush-key-2"})
    assert st == 200
    assert len(_metric_rows(master_only, token, tid)) == 2


# ---------------------------------------------------------------------------
# Admission control: per-token buckets, computed Retry-After.
# ---------------------------------------------------------------------------

def test_rate_limit_429_with_retry_after(tmp_path, native_binaries):
    cluster = _boot(tmp_path, native_binaries, {
        "overload": {"rate_limit": {"rps": 3, "burst": 3}}})
    try:
        token = cluster.login()
        statuses, retry_after = [], None
        for _ in range(15):
            st, body, hdrs = _raw(cluster, "GET", "/api/v1/experiments",
                                  token=token)
            statuses.append(st)
            if st == 429:
                assert body.get("rate_limited") is True
                assert body.get("token") == "determined"
                retry_after = hdrs.get("Retry-After")
        assert 429 in statuses, statuses
        assert retry_after is not None and int(retry_after) >= 1

        # The bucket refills: after waiting out the advertised delay the
        # same token is admitted again (the authenticated scrape draws
        # from the same bucket, so it also waits for the refill).
        time.sleep(min(int(retry_after), 5) + 0.2)
        assert _scrape(cluster, token, "det_rate_limited_total",
                       labels={"token": "determined"}) >= 1
        st, _, _ = _raw(cluster, "GET", "/api/v1/experiments", token=token)
        assert st == 200
    finally:
        cluster.stop()


def test_group_commit_disabled_falls_back_to_direct_writes(
        tmp_path, native_binaries):
    cluster = _boot(tmp_path, native_binaries, {
        "overload": {"group_commit": False}})
    try:
        token = cluster.login()
        _, (tid,) = _unmanaged_trial(cluster, token)
        st, _, _ = _raw(cluster, "POST", f"/api/v1/trials/{tid}/metrics",
                        {"group": "training", "steps_completed": 1,
                         "trial_run_id": 0, "metrics": {"loss": 1.0}},
                        token=token, headers={"X-Idempotency-Key": "gc-off"})
        assert st == 200
        assert len(_metric_rows(cluster, token, tid)) == 1
    finally:
        cluster.stop()


# ---------------------------------------------------------------------------
# Chaos (-m slow): stalled/failing DB → backpressure, exactly-once rows;
# forced brownout → sheds reads, never trial-critical writes, recovers.
# ---------------------------------------------------------------------------

def _keyed_storm(cluster, token, tid, n_threads, per_thread, base_step,
                 statuses):
    """Concurrent keyed reports retrying 429/503 per Retry-After, ONE key
    per report across its retries (the harness Session contract)."""
    errors = []
    lock = threading.Lock()

    def worker(wi):
        try:
            for i in range(per_thread):
                step = base_step + wi * per_thread + i
                key = f"storm-{base_step}-{wi}-{i}"
                body = {"group": "training", "steps_completed": step,
                        "trial_run_id": 0, "metrics": {"loss": 1.0}}
                deadline = time.time() + 120
                while True:
                    st, _, hdrs = _raw(
                        cluster, "POST", f"/api/v1/trials/{tid}/metrics",
                        body, token=token,
                        headers={"X-Idempotency-Key": key})
                    with lock:
                        statuses.append(st)
                    if st == 200:
                        break
                    if st not in (429, 503) or time.time() > deadline:
                        raise RuntimeError(f"report got {st}")
                    ra = hdrs.get("Retry-After")
                    time.sleep(min(float(ra) if ra else 0.2, 2.0))
        except Exception as e:  # noqa: BLE001 — re-raised after join
            with lock:
                errors.append(str(e))

    threads = [threading.Thread(target=worker, args=(wi,))
               for wi in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise RuntimeError(errors[0])


@pytest.mark.slow
def test_db_stall_backpressure_zero_lost_zero_duplicated(
        tmp_path, native_binaries):
    # Tiny queue cap: a stalled DB must visibly refuse (429), not queue
    # without bound.
    cluster = _boot(tmp_path, native_binaries, {
        "overload": {"group_commit": {"enabled": True, "window_ms": 5,
                                      "queue_cap": 4}}})
    try:
        token = cluster.login()
        admin = cluster.login("admin")
        _, (tid,) = _unmanaged_trial(cluster, token)
        statuses = []

        # Phase 1: every transaction stalls 250ms — flushes back up, the
        # cap turns into 429 + Retry-After, retries keep their keys.
        cluster.api("POST", "/api/v1/debug/faults",
                    {"point": "db.tx.stall", "mode": "delay-250"},
                    token=admin)
        _keyed_storm(cluster, token, tid, 8, 4, 0, statuses)

        # Phase 2: transactions FAIL outright (counted arm: the storm must
        # outlive it) — whole batches fall back to standalone retry, the
        # still-failing ones answer 503, clients retry the same key.
        cluster.api("POST", "/api/v1/debug/faults",
                    {"point": "db.tx.stall", "mode": "error", "count": 12},
                    token=admin)
        _keyed_storm(cluster, token, tid, 8, 2, 1000, statuses)

        cluster.api("POST", "/api/v1/debug/faults", {"mode": "off"},
                    token=admin)

        refused = sum(1 for s in statuses if s in (429, 503))
        assert refused > 0, (
            "a stalled DB was absorbed silently — expected 429/503 "
            f"backpressure (statuses: {sorted(set(statuses))})")

        # Zero lost, zero duplicated: exactly one row per report.
        steps = [r["total_batches"]
                 for r in _metric_rows(cluster, token, tid)]
        assert len(steps) == 48 and len(set(steps)) == 48, (
            f"{len(steps)} rows, {len(set(steps))} unique — expected 48/48")
    finally:
        cluster.stop()


@pytest.mark.slow
def test_forced_brownout_sheds_reads_never_trial_writes(
        tmp_path, native_binaries):
    cluster = _boot(tmp_path, native_binaries, {
        "overload": {"shedding": {"recover_hold_seconds": 0.3}}})
    try:
        token = cluster.login()
        admin = cluster.login("admin")
        _, (tid,) = _unmanaged_trial(cluster, token)

        cluster.api("POST", "/api/v1/debug/faults",
                    {"point": "api.overload.force_shed", "mode": "error"},
                    token=admin)
        # The brownout decision runs on the scheduler tick (200ms).
        deadline = time.time() + 5
        status, body, hdrs = None, {}, {}
        while time.time() < deadline and status != 503:
            status, body, hdrs = _raw(cluster, "GET", "/api/v1/experiments",
                                      token=token)
            time.sleep(0.05)
        assert status == 503, "brownout never engaged"
        assert body.get("shed") is True
        assert body.get("route_family") == "experiments"
        assert int(hdrs.get("Retry-After", "0")) >= 1

        # Trial-critical writes pass untouched while reads shed.
        st, _, _ = _raw(cluster, "POST", f"/api/v1/trials/{tid}/metrics",
                        {"group": "training", "steps_completed": 7,
                         "trial_run_id": 0, "metrics": {"loss": 0.1}},
                        token=token,
                        headers={"X-Idempotency-Key": "brownout-write"})
        assert st == 200
        # ...and so do trial reads (only the interactive list families shed).
        st, _, _ = _raw(cluster, "GET", f"/api/v1/trials/{tid}/metrics",
                        token=token)
        assert st == 200

        assert _scrape(cluster, token, "det_master_shed_total",
                       labels={"route_family": "experiments"}) >= 1
        assert not _scrape(cluster, token, "det_master_shed_total",
                           labels={"route_family": "trials"})

        # Recovery hysteresis: disarm, and the shed clears after the
        # signals hold below the recover thresholds for the hold window.
        cluster.api("POST", "/api/v1/debug/faults", {"mode": "off"},
                    token=admin)
        deadline = time.time() + 10
        while time.time() < deadline:
            status, _, _ = _raw(cluster, "GET", "/api/v1/experiments",
                                token=token)
            if status == 200:
                break
            time.sleep(0.1)
        assert status == 200, "brownout never recovered after disarm"
    finally:
        cluster.stop()
