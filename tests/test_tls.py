"""TLS end-to-end (VERDICT r4 missing #3): master serves HTTPS; agent,
CLI/Session, and spawned trials verify against a pinned self-signed cert;
plaintext and untrusted clients are refused.

Reference: harness/determined/common/api/certs.py (pinned master cert) +
master/agent TLS options.
"""

import os
import ssl
import subprocess
import time
import urllib.error
import urllib.request

import pytest

from tests.test_platform_e2e import Devcluster, native_binaries  # noqa: F401

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _gen_cert(tmp_path, cn="127.0.0.1"):
    cert = str(tmp_path / f"cert-{cn}.pem")
    key = str(tmp_path / f"key-{cn}.pem")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", cert, "-days", "5",
         "-subj", f"/CN={cn}", "-addext", f"subjectAltName=IP:{cn}"],
        check=True, capture_output=True)
    return cert, key


@pytest.fixture()
def tls_cluster(tmp_path, native_binaries):  # noqa: F811
    cert, key = _gen_cert(tmp_path)
    c = Devcluster(str(tmp_path), native_binaries)
    c.master_url = f"https://127.0.0.1:{c.port}"
    c.env["DET_MASTER_CERT_FILE"] = cert
    c.master = subprocess.Popen(
        [os.path.join(c.binaries, "determined-master"),
         "--port", str(c.port), "--host", "127.0.0.1", "--db", c.db_path,
         "--agent-timeout", "15", "--tls-cert", cert, "--tls-key", key],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    ctx = ssl.create_default_context(cafile=cert)
    ctx.check_hostname = False
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            urllib.request.urlopen(c.master_url + "/api/v1/master",
                                   timeout=2, context=ctx)
            break
        except Exception:
            time.sleep(0.3)
    else:
        raise TimeoutError("TLS master did not come up")
    yield c, cert, key
    c.stop()


def _api(cluster, cert, method, path, body=None, token=None):
    """Direct HTTPS call verifying against the pinned cert."""
    import json

    ctx = ssl.create_default_context(cafile=cert)
    ctx.check_hostname = False
    req = urllib.request.Request(
        cluster.master_url + path,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json",
                 **({"Authorization": f"Bearer {token}"} if token else {})},
        method=method)
    with urllib.request.urlopen(req, timeout=30, context=ctx) as r:
        text = r.read().decode()
        return json.loads(text) if text else None


def test_https_end_to_end(tls_cluster, tmp_path):
    """Agent registers over TLS, an experiment runs end to end through the
    CLI (Session verifies via DET_MASTER_CERT_FILE), logs flow."""
    import sys

    cluster, cert, key = tls_cluster
    # Agent dials https and pins the cert.
    cluster.agent = subprocess.Popen(
        [os.path.join(cluster.binaries, "determined-agent"),
         "--master-url", cluster.master_url,
         "--id", "tls-agent", "--slots", "2", "--slot-type", "cpu",
         "--addr", "127.0.0.1",
         "--work-root", os.path.join(cluster.tmpdir, "agent-work"),
         "--token-file", cluster.db_path + ".agent_token",
         "--master-cert-file", cert],
        env=cluster.env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    from determined_tpu.common.api import salted_hash

    deadline = time.time() + 30
    while time.time() < deadline:
        token = _api(cluster, cert, "POST", "/api/v1/auth/login",
                     {"username": "determined",
                      "password": salted_hash("determined", "")})["token"]
        agents = _api(cluster, cert, "GET", "/api/v1/agents",
                      token=token)["agents"]
        if any(a["id"] == "tls-agent" and a["alive"] for a in agents):
            break
        time.sleep(0.3)
    else:
        raise TimeoutError("agent never registered over TLS")

    # Full experiment through the real CLI: Session speaks https with the
    # pinned CA from DET_MASTER_CERT_FILE.
    import yaml

    cfg = {
        "name": "tls-e2e",
        "entrypoint": "python3 train.py",
        "searcher": {"name": "single", "metric": "val_loss",
                     "max_length": {"batches": 4}},
        "hyperparameters": {"lr": 0.5},
        "checkpoint_storage": {
            "type": "shared_fs",
            "host_path": os.path.join(str(tmp_path), "ckpts")},
        "resources": {"slots_per_trial": 1},
    }
    cfg_path = os.path.join(str(tmp_path), "exp.yaml")
    with open(cfg_path, "w") as f:
        yaml.safe_dump(cfg, f)
    env = dict(cluster.env, HOME=cluster.tmpdir)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable, "-m", "determined_tpu.cli",
         "-m", cluster.master_url, "experiment", "create", cfg_path,
         os.path.join(REPO, "tests", "fixtures", "platform"), "--follow"],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
    assert "COMPLETED" in r.stdout, r.stdout[-2000:]


def test_plaintext_refused_when_tls_on(tls_cluster):
    """An http:// client on the TLS port gets a transport failure, never a
    successful API answer."""
    cluster, cert, key = tls_cluster
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        urllib.request.urlopen(
            f"http://127.0.0.1:{cluster.port}/api/v1/master", timeout=5)


def test_untrusted_cert_rejected(tls_cluster, tmp_path):
    """A client pinning a DIFFERENT CA must refuse the master's cert —
    and the Session must not burn retries on it."""
    cluster, cert, key = tls_cluster
    other_cert, _ = _gen_cert(tmp_path, cn="10.9.9.9")
    ctx = ssl.create_default_context(cafile=other_cert)
    ctx.check_hostname = False
    with pytest.raises(urllib.error.URLError):
        urllib.request.urlopen(cluster.master_url + "/api/v1/master",
                               timeout=5, context=ctx)

    from determined_tpu.common.api import Session

    os.environ["DET_MASTER_CERT_FILE"] = other_cert
    try:
        t0 = time.time()
        with pytest.raises(ssl.SSLCertVerificationError):
            Session(cluster.master_url).get("/api/v1/master")
        assert time.time() - t0 < 10, "verification failure must not retry"
    finally:
        os.environ.pop("DET_MASTER_CERT_FILE", None)
