"""Kubernetes RM + provisioner against a fake API server (VERDICT r3 #7).

Reference: master/internal/rm/kubernetesrm/pods.go (pods as allocation
nodes) and rm/agentrm/provisioner (scale-up on sustained demand). The
master boots with `resource_manager: kubernetes` from a config FILE (the
viper-style layering), creates pods through the API server's REST
interface, reconciles pod phases into allocation state, deletes pods on
kill — all observed through an in-test fake API server.
"""

import json
import socket
import subprocess
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tests.test_platform_e2e import (  # noqa: F401
    Devcluster,
    _wait_http,
    native_binaries,
)


class FakeK8s:
    """Just enough of the pods API: create/list/delete + phase control."""

    def __init__(self):
        self.pods = {}  # name -> manifest (with injected status)
        self.deletes = []
        self.scaleups = []
        self.lock = threading.Lock()
        outer = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                if self.path == "/scaleup":
                    with outer.lock:
                        outer.scaleups.append(body)
                    return self._json(200, {})
                if self.path.endswith("/pods"):
                    name = body["metadata"]["name"]
                    with outer.lock:
                        body["status"] = {"phase": "Pending"}
                        outer.pods[name] = body
                    return self._json(201, body)
                self._json(404, {})

            def do_GET(self):
                if "/pods" in self.path:
                    with outer.lock:
                        items = list(outer.pods.values())
                    return self._json(200, {"items": items})
                self._json(404, {})

            def do_DELETE(self):
                name = self.path.rsplit("/", 1)[-1]
                with outer.lock:
                    outer.deletes.append(name)
                    outer.pods.pop(name, None)
                self._json(200, {})

        self.srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.url = f"http://127.0.0.1:{self.srv.server_address[1]}"
        threading.Thread(target=self.srv.serve_forever, daemon=True).start()

    def set_phase(self, name, phase, pod_ip=None, exit_code=None):
        with self.lock:
            status = {"phase": phase}
            if pod_ip:
                status["podIP"] = pod_ip
            if exit_code is not None:
                status["containerStatuses"] = [
                    {"state": {"terminated": {"exitCode": exit_code}}}]
            self.pods[name]["status"] = status

    def pod_names(self):
        with self.lock:
            return sorted(self.pods)

    def stop(self):
        self.srv.shutdown()


@pytest.fixture()
def k8s_cluster(tmp_path, native_binaries):
    fake = FakeK8s()
    cfg = {
        "resource_manager": "kubernetes",
        "kubernetes": {
            "api_url": fake.url,
            "namespace": "det-test",
            "image": "determined-tpu-task:test",
            "slots_per_pod": 2,
            "max_pods": 2,
            "accelerator_type": "tpu-v5-lite-podslice",
            "topology": "2x4",
        },
        "provisioner": {
            "webhook_url": fake.url + "/scaleup",
            "sustain_seconds": 1,
            "cooldown_seconds": 2,
        },
    }
    cfg_path = tmp_path / "master.json"
    cfg_path.write_text(json.dumps(cfg))
    c = Devcluster(str(tmp_path), native_binaries)

    # Boot the master from the config FILE + flags for port/db.
    import os

    c.master = subprocess.Popen(
        [os.path.join(c.binaries, "determined-master"),
         "--config", str(cfg_path),
         "--port", str(c.port), "--host", "127.0.0.1", "--db", c.db_path],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    _wait_http(c.master_url + "/api/v1/master")
    yield c, fake
    c.stop()
    fake.stop()


def _wait(cond, timeout=30, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(0.2)
    raise TimeoutError(f"timed out waiting for {what}")


def test_pods_lifecycle_and_reconcile(k8s_cluster):
    cluster, fake = k8s_cluster
    token = cluster.login()

    # A 4-slot command task → ceil(4/2) = 2 pods with the DET_* env.
    resp = cluster.api(
        "POST", "/api/v1/commands",
        {"config": {"entrypoint": "echo hi",
                    "resources": {"slots": 4}}}, token=token)
    aid = resp["allocation_id"]
    names = _wait(lambda: fake.pod_names() if len(fake.pod_names()) == 2
                  else None, what="2 pods created")
    assert all(n.startswith("det-") for n in names)
    manifest = fake.pods[names[0]]
    env = {e["name"]: e.get("value") for e in
           manifest["spec"]["containers"][0]["env"]}
    assert env["DET_ALLOCATION_ID"] == aid
    assert env["DET_NUM_NODES"] == "2"
    assert "DET_SESSION_TOKEN" in env
    assert manifest["metadata"]["namespace"] == "det-test"
    assert manifest["spec"]["containers"][0]["resources"]["limits"][
        "google.com/tpu"] == 2
    # Topology-aware placement (VERDICT r4 #7): shape nodeSelectors pin
    # the pod to the matching TPU node pool; the 2-node allocation also
    # carries the same-node-pool affinity hint (one ICI domain).
    sel = manifest["spec"]["nodeSelector"]
    assert sel["cloud.google.com/gke-tpu-accelerator"] == \
        "tpu-v5-lite-podslice"
    assert sel["cloud.google.com/gke-tpu-topology"] == "2x4"
    aff = manifest["spec"]["affinity"]["podAffinity"][
        "preferredDuringSchedulingIgnoredDuringExecution"][0]
    assert aff["podAffinityTerm"]["topologyKey"] == \
        "cloud.google.com/gke-nodepool"
    assert aff["podAffinityTerm"]["labelSelector"]["matchLabels"][
        "det-allocation"] == aid
    # Node-local XLA compilation cache rides a hostPath (pods are
    # ephemeral; the compile-reuse must survive them).
    assert env["DET_XLA_CACHE_DIR"] == "/det-xla-cache"
    assert manifest["spec"]["volumes"][0]["hostPath"]["path"] == \
        "/var/determined/xla-cache"
    assert manifest["spec"]["containers"][0]["volumeMounts"][0][
        "mountPath"] == "/det-xla-cache"

    # Phase Running + podIP reconciles into allocation RUNNING with
    # rendezvous addresses.
    for i, n in enumerate(names):
        fake.set_phase(n, "Running", pod_ip=f"10.0.0.{i + 1}")
    _wait(lambda: cluster.api(
        "GET", f"/api/v1/allocations/{aid}", token=token
    )["allocation"]["state"] == "RUNNING", what="allocation RUNNING")

    # Success reconciles to COMPLETED and the pods are deleted.
    for n in names:
        fake.set_phase(n, "Succeeded", exit_code=0)
    _wait(lambda: cluster.api(
        "GET", f"/api/v1/commands/{resp['id']}", token=token
    )["task"]["state"] == "COMPLETED", what="task COMPLETED")
    assert set(names) <= set(fake.deletes)


def test_kill_deletes_pods(k8s_cluster):
    cluster, fake = k8s_cluster
    token = cluster.login()
    resp = cluster.api(
        "POST", "/api/v1/commands",
        {"config": {"entrypoint": "sleep 999",
                    "resources": {"slots": 2}}}, token=token)
    names = _wait(lambda: fake.pod_names() or None, what="pod created")
    cluster.api("POST", f"/api/v1/commands/{resp['id']}/kill", token=token)
    _wait(lambda: set(names) <= set(fake.deletes), what="pods deleted")


def test_multirm_routes_pools_to_backends(tmp_path, native_binaries):
    """resource_manager: multi (reference rm/multirm): the 'gke' pool goes
    to the kubernetes RM (fake API observes the pod), the default pool to
    the agent RM (a real agent runs the task to completion)."""
    import os

    fake = FakeK8s()
    cfg = {
        "resource_manager": "multi",
        "kubernetes": {
            "api_url": fake.url, "namespace": "det-test",
            "image": "x", "slots_per_pod": 2, "max_pods": 2,
            "pools": ["gke"],
        },
    }
    cfg_path = tmp_path / "master.json"
    cfg_path.write_text(json.dumps(cfg))
    c = Devcluster(str(tmp_path), native_binaries)
    c.master = subprocess.Popen(
        [os.path.join(c.binaries, "determined-master"),
         "--config", str(cfg_path),
         "--port", str(c.port), "--host", "127.0.0.1", "--db", c.db_path],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        _wait_http(c.master_url + "/api/v1/master")
        c.start_agent()  # registers into the default pool
        token = c.login()

        # k8s-pool task → a pod appears on the fake API server.
        c.api("POST", "/api/v1/commands",
              {"config": {"entrypoint": "sleep 999",
                          "resources": {"slots": 2,
                                        "resource_pool": "gke"}}},
              token=token)
        _wait(lambda: fake.pod_names() or None, what="k8s pod created")

        # default-pool task → runs on the agent to completion.
        tid = c.api("POST", "/api/v1/commands",
                    {"config": {"entrypoint": "echo agent-pool-ran"}},
                    token=token)["id"]
        _wait(lambda: c.api("GET", f"/api/v1/commands/{tid}", token=token)
              ["task"]["state"] == "COMPLETED", what="agent task COMPLETED")
        logs = c.api("GET", f"/api/v1/tasks/{tid}/logs?offset=0",
                     token=token)["logs"]
        assert any("agent-pool-ran" in line["log"] for line in logs)
        assert len(fake.pod_names()) == 1  # agent task never touched k8s
    finally:
        c.stop()
        fake.stop()


def test_provisioner_fires_on_sustained_demand(k8s_cluster):
    cluster, fake = k8s_cluster
    token = cluster.login()
    # Fill capacity (max_pods=2 × 2 slots), then queue one more: demand
    # exceeds free slots for > sustain_seconds → scale-up webhook.
    a = cluster.api("POST", "/api/v1/commands",
                    {"config": {"entrypoint": "sleep 999",
                                "resources": {"slots": 4}}}, token=token)
    _wait(lambda: len(fake.pod_names()) == 2, what="capacity filled")
    cluster.api("POST", "/api/v1/commands",
                {"config": {"entrypoint": "sleep 999",
                            "resources": {"slots": 2}}}, token=token)
    scale = _wait(lambda: fake.scaleups[:] or None, timeout=30,
                  what="scale-up webhook")[0]
    assert scale["event"] == "scale_up"
    assert scale["pending_slots"] >= 2
    assert scale["desired_total_slots"] > scale["total_slots"] - scale[
        "free_slots"] - 1
    (a,)
