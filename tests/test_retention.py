"""Checkpoint GC, log retention, job-queue reordering (VERDICT r2 #9).

Reference: checkpoint_gc.go:76 + exec/gc_checkpoints.py (GC runs as a
master-spawned zero-slot task), internal/logretention/, job queue
ahead-of/behind ops."""

import os
import time

import pytest

from tests.test_platform_e2e import (  # noqa: F401
    FIXTURES,
    Devcluster,
    _create_experiment,
    _experiment_config,
    _wait_experiment,
    native_binaries,
)


@pytest.fixture()
def cluster(tmp_path, native_binaries):  # noqa: F811
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    c.start_agent()
    yield c
    c.stop()


def test_checkpoint_gc_retention(cluster, tmp_path):
    """Completed experiment keeps best + latest checkpoints only; the rest
    are deleted from storage by the GC task and marked DELETED in the
    registry."""
    storage_root = os.path.join(str(tmp_path), "checkpoints")
    config = _experiment_config(tmp_path)
    config["entrypoint"] = "python3 gc_train.py"
    config["checkpoint_storage"].update(
        save_experiment_best=0, save_trial_best=1, save_trial_latest=1)
    eid, token = _create_experiment(cluster, config, activate=True)
    _wait_experiment(cluster, eid, token)

    # fixture checkpoints at steps 2,4,6,8 with val=(s-4)^2: best=step4,
    # latest=step8 → steps 2 and 6 fall outside retention.
    deadline = time.time() + 60
    deleted = {}
    while time.time() < deadline:
        cps = cluster.api("GET", f"/api/v1/experiments/{eid}/checkpoints",
                          token=token)["checkpoints"]
        deleted = {c["uuid"]: c for c in cps if c["state"] == "DELETED"}
        if len(deleted) == 2:
            break
        time.sleep(0.5)
    assert len(deleted) == 2, f"GC did not run: {[(c['uuid'], c['state']) for c in cps]}"
    kept = {c["uuid"]: c for c in cps if c["state"] == "COMPLETED"}
    kept_steps = sorted(c["steps_completed"] for c in kept.values())
    assert kept_steps == [4, 8], kept_steps  # best + latest
    # files really deleted from storage / kept for the survivors
    for uuid in deleted:
        assert not os.path.isdir(os.path.join(storage_root, uuid)), uuid
    for uuid in kept:
        assert os.path.isdir(os.path.join(storage_root, uuid)), uuid


def test_log_retention_sweep(cluster):
    """Old task logs are deleted by the manual cleanup endpoint (the hourly
    sweep shares the same sweep_task_logs path)."""
    token = cluster.login()
    cluster.api("POST", "/api/v1/task/logs", {"logs": [
        {"task_id": "t-old", "log": "ancient line",
         "timestamp": "2020-01-01 00:00:00"},
        {"task_id": "t-new", "log": "fresh line"},
    ]}, token=token)
    admin = cluster.login("admin")  # cleanup is an admin operation
    out = cluster.api("POST", "/api/v1/master/cleanup_logs", {"days": 30},
                      token=admin)
    assert out["deleted"] == 1
    # idempotent second sweep
    out = cluster.api("POST", "/api/v1/master/cleanup_logs", {"days": 30},
                      token=admin)
    assert out["deleted"] == 0


def test_job_queue_reorder(cluster, tmp_path):
    """ahead-of moves a queued allocation in front of another."""
    token = cluster.login()
    # Fill both slots with a long-running experiment, then queue two more.
    cfgs = []
    for i in range(3):
        c = _experiment_config(
            tmp_path,
            searcher={"name": "single", "metric": "val_loss",
                      "max_length": {"batches": 400}},
        )
        c["name"] = f"queue-{i}"
        c["resources"] = {"slots_per_trial": 2, "priority": 40 + i}
        c["environment"] = {"TRIAL_STEP_SLEEP": "0.05"}
        cfgs.append(c)
    eids = [_create_experiment(cluster, c, activate=True)[0] for c in cfgs]

    def queued():
        jobs = cluster.api("GET", "/api/v1/job-queues", token=token)["jobs"]
        return [j for j in jobs if j["state"] == "QUEUED"]

    deadline = time.time() + 30
    while time.time() < deadline and len(queued()) < 2:
        time.sleep(0.3)
    q = queued()
    assert len(q) == 2, q
    # priority order: exp2 (41) ahead of exp3 (42). Move the last one ahead.
    last = next(j for j in q if j["priority"] == 42)
    first = next(j for j in q if j["priority"] == 41)
    # Queue reordering is an admin operation (jumps other users' work).
    cluster.api("POST", "/api/v1/job-queues/reorder", {
        "allocation_id": last["allocation_id"],
        "ahead_of": first["allocation_id"],
    }, token=cluster.login("admin"))
    q2 = queued()
    pos = {j["allocation_id"]: j["queue_position"] for j in q2}
    assert pos[last["allocation_id"]] < pos[first["allocation_id"]], q2
    # clean up: kill everything so teardown is fast
    for eid in eids:
        cluster.api("POST", f"/api/v1/experiments/{eid}/kill", token=token)
