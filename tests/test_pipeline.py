"""Pipeline parallelism: pipelined execution must match the plain scan.

Reference anchor: the reference is only pipeline-*aware* via DeepSpeed's MPU
(harness/determined/pytorch/deepspeed/_mpu.py); here PP is first-class
(determined_tpu/parallel/pipeline.py), so correctness is checked directly
against single-device execution on the virtual CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from determined_tpu.models import gpt2
from determined_tpu.parallel import MeshConfig, create_mesh, pipeline_apply


def _tiny_cfg(**kw):
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("remat", False)
    kw.setdefault("attention_impl", "dot")
    return gpt2.Config(
        vocab_size=128, n_positions=64, d_model=32, n_layer=4, n_head=2, **kw
    )


class TestPipelineApply:
    def test_matches_scan_mlp_stack(self, devices):
        """A generic 4-layer MLP stack: pipelined == sequential."""
        mesh = create_mesh(MeshConfig(data=2, pipeline=4), devices)
        rng = jax.random.PRNGKey(0)
        L, D, B = 4, 16, 8
        w = jax.random.normal(rng, (L, D, D)) * 0.3

        def block(x, wl):
            return jnp.tanh(x @ wl)

        x = jax.random.normal(jax.random.PRNGKey(1), (B, D))

        def ref(w, x):
            def body(c, wl):
                return block(c, wl), None
            y, _ = jax.lax.scan(body, x, w)
            return y

        want = ref(w, x)
        with jax.sharding.set_mesh(mesh):
            got = jax.jit(
                lambda w, x: pipeline_apply(
                    block, w, x, mesh=mesh, num_microbatches=4)
            )(w, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)

    @pytest.mark.parametrize("microbatches", [2, 4, 8])
    def test_microbatch_counts(self, devices, microbatches):
        mesh = create_mesh(MeshConfig(data=1, pipeline=2), devices[:2])
        L, D, B = 2, 8, 8
        w = jax.random.normal(jax.random.PRNGKey(2), (L, D, D)) * 0.3
        x = jax.random.normal(jax.random.PRNGKey(3), (B, D))

        def block(x, wl):
            return jnp.tanh(x @ wl)

        def ref(w, x):
            def body(c, wl):
                return block(c, wl), None
            return jax.lax.scan(body, x, w)[0]

        want = ref(w, x)
        with jax.sharding.set_mesh(mesh):
            got = jax.jit(lambda w, x: pipeline_apply(
                block, w, x, mesh=mesh, num_microbatches=microbatches))(w, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    def test_no_pipeline_axis_falls_back_to_scan(self, devices):
        mesh = create_mesh(MeshConfig(data=8), devices)
        L, D, B = 3, 8, 4
        w = jax.random.normal(jax.random.PRNGKey(4), (L, D, D)) * 0.3
        x = jax.random.normal(jax.random.PRNGKey(5), (B, D))

        def block(x, wl):
            return x @ wl

        with jax.sharding.set_mesh(mesh):
            got = pipeline_apply(block, w, x, mesh=mesh, num_microbatches=2)
        want = x
        for i in range(L):
            want = want @ w[i]
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)


class TestGPT2Pipelined:
    def test_forward_matches_single_device(self, devices):
        cfg = _tiny_cfg()
        params = gpt2.init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 128)
        want = gpt2.apply(params, tokens, cfg)

        mesh = create_mesh(MeshConfig(data=2, pipeline=2, tensor=2), devices)
        with jax.sharding.set_mesh(mesh):
            got = jax.jit(
                lambda p, t: gpt2.apply_pipelined(
                    p, t, cfg, mesh, num_microbatches=4)
            )(params, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-4, rtol=2e-4)

    def test_train_step_pipelined(self, devices):
        """Full pp×dp×tp train step: loss finite and params update."""
        import optax

        from determined_tpu.train import create_train_state, make_train_step

        cfg = _tiny_cfg(remat=True)
        mesh = create_mesh(MeshConfig(data=2, pipeline=2, tensor=2), devices)
        tx = optax.adamw(1e-3)
        batch = {
            "tokens": np.random.default_rng(0)
            .integers(0, cfg.vocab_size, size=(8, 33))
            .astype(np.int32)
        }
        with jax.sharding.set_mesh(mesh):
            state = create_train_state(
                lambda r: gpt2.init(r, cfg), tx, jax.random.PRNGKey(0),
                mesh=mesh, param_logical_axes=gpt2.param_logical_axes(cfg))
            # layer stack must actually be sharded over the pipeline axis
            qkv = state.params["blocks"]["qkv"]["kernel"]
            assert "pipeline" in jax.tree_util.tree_leaves(
                [qkv.sharding.spec])[0:1][0] or qkv.sharding.spec[0] == "pipeline"
            step = make_train_step(
                lambda p, b, r: gpt2.loss_fn_pipelined(
                    p, b, cfg, mesh, num_microbatches=4),
                tx, mesh=mesh)
            before = np.asarray(jax.device_get(state.params["wte"]))
            state2, metrics = step(state, batch, jax.random.PRNGKey(1))
        assert np.isfinite(float(metrics["loss"]))
        after = np.asarray(jax.device_get(state2.params["wte"]))
        assert not np.allclose(before, after)
