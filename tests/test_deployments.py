"""Serving deployments (docs/serving.md "Deployments & autoscaling"):
replica-set controller, master-side request router, signal-driven
autoscaler.

Fast tests run the REAL master + agent (devcluster) with a featherweight
fake replica (tests/fixtures/serving/fake_replica.py) that speaks the
replica protocol — proxy registration, serve_stats heartbeats, the
preemption-drain handshake — without building a model, so router and
controller semantics are exercised end-to-end in tier-1 time. The -m slow
e2e at the bottom runs the full lifecycle with REAL engines in `make
chaos`.

The acceptance contracts:
  - the reconciler keeps a deployment at target (spawn on deficit,
    drain-retire on surplus, respawn on death);
  - the router dispatches least-loaded, retries connection refusals once
    on another replica (never an in-flight generation), ejects a failing
    replica via the circuit breaker and re-admits it after respawn;
  - 429/Retry-After when every replica reports a full admission queue;
  - scale-down always drains: zero accepted requests dropped.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from tests.test_platform_e2e import (  # noqa: F401  (fixture re-export)
    Devcluster,
    native_binaries,
)

from determined_tpu import expconf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# expconf: serving.replicas validation + defaults.
# ---------------------------------------------------------------------------


def _serving_cfg(replicas):
    return {"name": "d", "serving": {"model": "gpt2", "replicas": replicas},
            "resources": {"slots_per_trial": 1}}


def test_expconf_replicas_valid_and_defaults():
    cfg = expconf.check(_serving_cfg({"min": 1, "max": 4, "target": 2}))
    rep = cfg["serving"]["replicas"]
    assert (rep["min"], rep["target"], rep["max"]) == (1, 2, 4)
    # Defaults fill from min upward.
    cfg = expconf.check(_serving_cfg({"min": 2}))
    rep = cfg["serving"]["replicas"]
    assert (rep["min"], rep["target"], rep["max"]) == (2, 2, 2)
    # Autoscaler knobs pass through.
    cfg = expconf.check(_serving_cfg(
        {"min": 1, "max": 2, "scale_up_after_s": 0.5,
         "scale_down_after_s": 1, "scale_up_threshold": 0.5,
         "scale_down_threshold": 0.2}))
    assert cfg["serving"]["replicas"]["scale_up_after_s"] == 0.5


@pytest.mark.parametrize("bad,needle", [
    ({"min": -1}, "non-negative int"),
    ({"min": 0, "max": 0}, "positive int"),       # max >= 1 always
    ({"min": 3, "max": 2}, "min must be <= max"),
    ({"min": 1, "max": 2, "target": 5}, "within [min, max]"),
    ({"min": 1, "bogus": 2}, "unknown keys"),
    ({"min": 1, "scale_up_after_s": -1}, "non-negative"),
    ({"min": 1, "scale_up_threshold": 3}, "(0, 2]"),
    ({"min": 0, "max": 2, "on_demand_floor": 3}, "on_demand_floor"),
    ({"min": 0, "max": 2, "on_demand_floor": -1}, "on_demand_floor"),
    ({"min": 0, "max": 2, "cold_start_budget_s": 0}, "cold_start_budget_s"),
    ({"min": 0, "max": 2, "cold_start_budget_s": -5},
     "cold_start_budget_s"),
    ("two", "must be a mapping"),
])
def test_expconf_replicas_invalid(bad, needle):
    errors = expconf.validate(_serving_cfg(bad))
    assert any(needle in e for e in errors), (bad, errors)


def test_expconf_scale_to_zero_and_capacity_knobs():
    """min: 0 (scale-to-zero) is legal, defaults stay consistent, and the
    spot/cold-start knobs validate ± (docs/serving.md 'Scale to zero')."""
    cfg = expconf.check(_serving_cfg({"min": 0, "max": 2}))
    rep = cfg["serving"]["replicas"]
    assert (rep["min"], rep["target"], rep["max"]) == (0, 0, 2)
    # min: 0 alone: target defaults to 0, max defaults to 1 (never 0).
    cfg = expconf.check(_serving_cfg({"min": 0}))
    rep = cfg["serving"]["replicas"]
    assert (rep["min"], rep["target"], rep["max"]) == (0, 0, 1)
    # Capacity knobs pass through.
    cfg = expconf.check(_serving_cfg(
        {"min": 0, "max": 3, "on_demand_floor": 1,
         "cold_start_budget_s": 20.5}))
    rep = cfg["serving"]["replicas"]
    assert rep["on_demand_floor"] == 1
    assert rep["cold_start_budget_s"] == 20.5


def test_preflight_dtl207_capacity_knobs_mirror():
    """The Python preflight's DTL207 fires on unsatisfiable capacity
    knobs and stays silent on legal scale-to-zero configs (the native
    master mirror is exercised via the deployment-create gate)."""
    from determined_tpu.analysis.config_rules import check_config

    def codes(cfg):
        return [d.code for d in check_config(cfg)]

    ok = _serving_cfg({"min": 0, "max": 2, "on_demand_floor": 1,
                       "cold_start_budget_s": 30})
    assert "DTL207" not in codes(ok)
    bad_floor = _serving_cfg({"min": 0, "max": 2, "on_demand_floor": 5})
    assert "DTL207" in codes(bad_floor)
    bad_budget = _serving_cfg(
        {"min": 0, "max": 2, "cold_start_budget_s": -1})
    assert "DTL207" in codes(bad_budget)
    bad_min = dict(_serving_cfg({"min": 1}))
    bad_min["serving"]["replicas"]["min"] = -2
    assert "DTL207" in codes(bad_min)


def test_preflight_dtl208_canary_fraction_mirror():
    """DTL208 fires on a canary fraction outside (0, 1) and stays silent
    on real fractions / omitted fraction (the native master mirror is
    exercised via the deployment-create gate in
    test_lifecycle_expconf_and_create_gate)."""
    from determined_tpu.analysis.config_rules import check_config

    def codes(cfg):
        return [d.code for d in check_config(cfg)]

    def cfg_with(**canary):
        c = _serving_cfg({"min": 1})
        c["serving"]["canary"] = {"model": "m", **canary}
        return c

    assert "DTL208" not in codes(cfg_with(fraction=0.05))
    assert "DTL208" not in codes(cfg_with())  # defaulted at create
    for bad in (0, 1, 1.5, -0.1, True, "lots"):
        assert "DTL208" in codes(cfg_with(fraction=bad)), bad
    # Suppressible like every DTL2xx rule.
    from determined_tpu.analysis import filter_suppressed

    diags = filter_suppressed(
        check_config(cfg_with(fraction=0)), ["DTL208"])
    assert [d.code for d in diags] == ["DTL208"] and diags[0].suppressed


def test_expconf_heartbeat_period():
    cfg = _serving_cfg({"min": 1})
    cfg["serving"]["heartbeat_period_s"] = 0.5
    assert not expconf.validate(cfg)
    cfg["serving"]["heartbeat_period_s"] = 0
    assert any("heartbeat_period_s" in e for e in expconf.validate(cfg))


# ---------------------------------------------------------------------------
# Devcluster plumbing.
# ---------------------------------------------------------------------------


def _http(method, url, body=None, token=None, timeout=60.0, headers=None):
    """Raw request returning (status, headers, parsed-json) — unlike
    Devcluster.api it surfaces 4xx/5xx instead of raising."""
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json",
                 **({"Authorization": f"Bearer {token}"} if token else {}),
                 **(headers or {})},
        method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            text = resp.read().decode()
            return resp.status, dict(resp.headers), (
                json.loads(text) if text else None)
    except urllib.error.HTTPError as e:
        text = e.read().decode(errors="replace")
        try:
            parsed = json.loads(text) if text else None
        except ValueError:
            parsed = {"raw": text}
        return e.code, dict(e.headers), parsed


def _dep_config(min_r=1, max_r=4, target=2, heartbeat_s=0.3, **rep_extra):
    replicas = {"min": min_r, "max": max_r, "target": target}
    replicas.update(rep_extra)
    return {
        "name": "fake-dep",
        # Fake replica instead of the real engine: the subsystem under
        # test is the master's controller/router, not the batcher.
        "entrypoint": "python3 -m tests.fixtures.serving.fake_replica",
        "serving": {"model": "gpt2", "replicas": replicas},
        "resources": {"slots_per_trial": 0},
        "environment": {"DET_FAKE_HEARTBEAT_S": str(heartbeat_s)},
    }


@pytest.fixture()
def master_only(tmp_path, native_binaries):  # noqa: F811
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    yield c
    c.stop()


@pytest.fixture()
def fleet(tmp_path, native_binaries):  # noqa: F811
    c = Devcluster(str(tmp_path), native_binaries, slots=4)
    c.start_master()
    c.start_agent()
    yield c
    c.stop()


def _wait_ready(c, token, dep_id, n, timeout=90.0):
    """Until `n` replicas are RUNNING with a proxy address and a fresh
    heartbeat; returns the deployment detail."""
    deadline = time.time() + timeout
    detail = None
    while time.time() < deadline:
        detail = c.api("GET", f"/api/v1/deployments/{dep_id}",
                       token=token)["deployment"]
        ready = [r for r in detail["replicas"]
                 if r.get("allocation_state") == "RUNNING"
                 and r.get("proxy_address")
                 and 0 <= (r.get("report_age_s") or -1) < 10
                 and not r["retiring"]]
        if len(ready) == n and len(detail["replicas"]) == n:
            return detail
        time.sleep(0.2)
    raise TimeoutError(f"deployment never reached {n} ready replicas: "
                       f"{json.dumps(detail, indent=2)}")


def _replica_addr(detail, task_id):
    for r in detail["replicas"]:
        if r["task_id"] == task_id:
            return r["proxy_address"]
    raise KeyError(task_id)


def _generate(c, token, dep_id, body=None, timeout=60.0, headers=None):
    return _http("POST", f"{c.master_url}/serve/{dep_id}/v1/generate",
                 body or {"max_new_tokens": 4}, token=token,
                 timeout=timeout, headers=headers)


def _trace(c, token, dep_id, rid):
    return _http(
        "GET",
        f"{c.master_url}/api/v1/deployments/{dep_id}/requests/{rid}/trace",
        token=token)


# ---------------------------------------------------------------------------
# Controller: create / reconcile / scale / kill (no agent needed).
# ---------------------------------------------------------------------------


def test_deployment_create_scale_kill(master_only):
    c = master_only
    token = c.login()
    resp = c.api("POST", "/api/v1/deployments",
                 {"config": _dep_config(target=2)}, token=token)
    dep_id = resp["id"]
    assert dep_id.startswith("deploy-") and len(resp["replicas"]) == 2

    # Replicas exist as SERVING tasks (PENDING without an agent).
    serving = c.api("GET", "/api/v1/serving", token=token)["serving"]
    ours = [t for t in serving if t["id"] in resp["replicas"]]
    assert len(ours) == 2 and all(t["state"] == "ACTIVE" for t in ours)

    # Scale up: reconciler spawns the deficit.
    c.api("POST", f"/api/v1/deployments/{dep_id}/scale", {"target": 3},
          token=token)
    deadline = time.time() + 10
    while time.time() < deadline:
        detail = c.api("GET", f"/api/v1/deployments/{dep_id}",
                       token=token)["deployment"]
        if len(detail["replicas"]) == 3:
            break
        time.sleep(0.2)
    assert len(detail["replicas"]) == 3

    # Out-of-range manual scale is a 400, not a clamp-and-shrug.
    status, _, body = _http(
        "POST", f"{c.master_url}/api/v1/deployments/{dep_id}/scale",
        {"target": 9}, token=token)
    assert status == 400 and "within" in body["error"]

    # Scale down: PENDING surplus replicas terminate immediately.
    c.api("POST", f"/api/v1/deployments/{dep_id}/scale", {"target": 1},
          token=token)
    deadline = time.time() + 10
    while time.time() < deadline:
        detail = c.api("GET", f"/api/v1/deployments/{dep_id}",
                       token=token)["deployment"]
        if len(detail["replicas"]) == 1:
            break
        time.sleep(0.2)
    assert len(detail["replicas"]) == 1

    # Kill: deployment ends, remaining replica task goes terminal.
    c.api("POST", f"/api/v1/deployments/{dep_id}/kill", token=token)
    deps = c.api("GET", "/api/v1/deployments", token=token)["deployments"]
    assert deps[0]["state"] == "KILLED" and deps[0]["end_time"]
    serving = c.api("GET", "/api/v1/serving", token=token)["serving"]
    assert all(t["state"] in ("CANCELED", "COMPLETED", "ERROR")
               for t in serving if t["id"] in resp["replicas"] or
               any(t["id"] == r["task_id"] for r in detail["replicas"]))


def test_deployment_requires_serving_block_and_valid_range(master_only):
    c = master_only
    token = c.login()
    status, _, body = _http(
        "POST", f"{c.master_url}/api/v1/deployments",
        {"config": {"name": "x"}}, token=token)
    assert status == 400 and "serving" in body["error"]
    status, _, body = _http(
        "POST", f"{c.master_url}/api/v1/deployments",
        {"config": {"serving": {"replicas": {"min": 3, "max": 1}}}},
        token=token)
    assert status == 400
    status, _, body = _http(
        "GET", f"{c.master_url}/api/v1/deployments/deploy-nope", token=token)
    assert status == 404 and body["error"] == "no such deployment"


# ---------------------------------------------------------------------------
# Router: dispatch, least-loaded, 429-all-full, failover, breaker.
# ---------------------------------------------------------------------------


def test_router_dispatch_and_least_loaded(fleet):
    c = fleet
    token = c.login()
    resp = c.api("POST", "/api/v1/deployments",
                 {"config": _dep_config(target=2)}, token=token)
    dep_id = resp["id"]
    detail = _wait_ready(c, token, dep_id, 2)
    tids = [r["task_id"] for r in detail["replicas"]]

    # Equal load: the rotation spreads requests over both replicas.
    seen = set()
    for _ in range(6):
        status, _, body = _generate(c, token, dep_id)
        assert status == 200, body
        seen.add(body["replica"])
    assert seen == set(tids)

    # Routing by name works too.
    status, _, body = _generate(c, token, "fake-dep")
    assert status == 200

    # Load up replica A: everything flows to B until A clears.
    a, b = tids[0], tids[1]
    addr_a = _replica_addr(detail, a)
    status, _, _ = _http("POST", f"{addr_a}/force_stats",
                         {"queue_depth": 7, "queue_capacity": 8,
                          "active": 4, "slots": 4})
    assert status == 200
    time.sleep(0.2)  # force_stats beats immediately; allow the hop
    for _ in range(4):
        status, _, body = _generate(c, token, dep_id)
        assert status == 200 and body["replica"] == b, body
    _http("POST", f"{addr_a}/force_stats", {})


def test_router_429_when_every_replica_full(fleet):
    c = fleet
    token = c.login()
    resp = c.api("POST", "/api/v1/deployments",
                 {"config": _dep_config(target=2)}, token=token)
    dep_id = resp["id"]
    detail = _wait_ready(c, token, dep_id, 2)
    full = {"queue_depth": 8, "queue_capacity": 8, "active": 4, "slots": 4,
            "retry_after_hint_s": 7}
    for r in detail["replicas"]:
        status, _, _ = _http(
            "POST", f"{r['proxy_address']}/force_stats", full)
        assert status == 200
    time.sleep(0.3)
    status, headers, body = _generate(c, token, dep_id)
    assert status == 429, body
    # The Retry-After hint is the smallest replica-computed backoff.
    assert headers.get("Retry-After") == "7", headers
    # One replica clears → requests flow again (to that replica).
    clear = detail["replicas"][0]
    _http("POST", f"{clear['proxy_address']}/force_stats", {})
    time.sleep(0.3)
    status, _, body = _generate(c, token, dep_id)
    assert status == 200 and body["replica"] == clear["task_id"]


def test_router_failover_ejection_and_readmission(fleet):
    """The satellite contract: kill one replica of a 2-replica deployment
    mid-burst — connection-refused requests retry onto the survivor (zero
    accepted requests dropped), the dead replica is ejected, and after the
    master respawns it the router re-admits it."""
    c = fleet
    token = c.login()
    resp = c.api("POST", "/api/v1/deployments",
                 {"config": _dep_config(target=2, max_r=2)}, token=token)
    dep_id = resp["id"]
    detail = _wait_ready(c, token, dep_id, 2)
    tids = {r["task_id"] for r in detail["replicas"]}
    victim = detail["replicas"][0]
    survivor_tid = (tids - {victim["task_id"]}).pop()

    results, failures = [], []

    def _burst(n):
        for _ in range(n):
            status, _, body = _generate(
                c, token, dep_id, {"max_new_tokens": 2, "delay_ms": 40})
            (results if status == 200 else failures).append((status, body))

    threads = [threading.Thread(target=_burst, args=(6,)) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.15)
    # Kill the victim process mid-burst (its socket dies with it).
    try:
        _http("POST", f"{victim['proxy_address']}/die", {}, timeout=5)
    except Exception:
        pass  # the process may die before finishing the response
    for t in threads:
        t.join(timeout=120)

    # Zero dropped: every request either succeeded (possibly via the
    # retry-once path) or was an explicit router rejection — never a
    # torso. In-flight requests on the victim die WITH their connection
    # (the router must not replay a possibly-generating request), so the
    # caller sees an explicit 502 for those, and only those.
    assert len(results) >= 18, (len(results), failures)
    for status, body in failures:
        assert status == 502, (status, body)
    assert len(failures) <= 6, failures

    # The retry path actually ran: router counters prove the refusals
    # were re-dispatched rather than surfaced.
    raw = urllib.request.urlopen(urllib.request.Request(
        f"{c.master_url}/metrics",
        headers={"Authorization": f"Bearer {token}"}), timeout=10
    ).read().decode()
    retries = [line for line in raw.splitlines()
               if line.startswith("det_serve_router_retries_total")]
    assert retries and int(retries[0].split()[-1]) >= 1, retries

    # Survivor kept serving throughout; victim respawns (restarts >= 1)
    # and is re-admitted by the router after the breaker hold. Poll the
    # restarts bump FIRST: right after the burst the dead replica can
    # still look RUNNING with a fresh-enough heartbeat until the agent's
    # exit report lands, so a bare ready-check can win the race against
    # the requeue (same pattern as test_replica_death_respawns_to_target).
    deadline = time.time() + 120
    task = {}
    while time.time() < deadline:
        task = c.api("GET", f"/api/v1/serving/{victim['task_id']}",
                     token=token)["task"]
        if int(task.get("restarts") or 0) >= 1:
            break
        time.sleep(0.2)
    assert int(task.get("restarts") or 0) >= 1, task
    detail = _wait_ready(c, token, dep_id, 2, timeout=120)
    assert {r["task_id"] for r in detail["replicas"]} == tids
    deadline = time.time() + 60
    seen = set()
    while time.time() < deadline and len(seen) < 2:
        status, _, body = _generate(c, token, dep_id,
                                    {"max_new_tokens": 2, "delay_ms": 1})
        if status == 200:
            seen.add(body["replica"])
    assert seen == tids, f"victim never re-admitted: {seen}"
    assert survivor_tid in seen


def test_scale_down_drains_running_replica_zero_dropped(fleet):
    c = fleet
    token = c.login()
    resp = c.api("POST", "/api/v1/deployments",
                 {"config": _dep_config(target=2, max_r=2)}, token=token)
    dep_id = resp["id"]
    _wait_ready(c, token, dep_id, 2)

    results, failures = [], []

    def _burst(n):
        for _ in range(n):
            status, _, body = _generate(
                c, token, dep_id, {"max_new_tokens": 2, "delay_ms": 30})
            (results if status == 200 else failures).append((status, body))

    threads = [threading.Thread(target=_burst, args=(8,)) for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.1)
    c.api("POST", f"/api/v1/deployments/{dep_id}/scale", {"target": 1},
          token=token)
    for t in threads:
        t.join(timeout=120)
    # The drain is cooperative: every accepted request completed; the
    # router stopped dispatching to the retiring replica the moment its
    # preemption landed, so nothing was refused either.
    assert not failures, failures
    assert len(results) == 24

    deadline = time.time() + 60
    while time.time() < deadline:
        detail = c.api("GET", f"/api/v1/deployments/{dep_id}",
                       token=token)["deployment"]
        if len(detail["replicas"]) == 1:
            break
        time.sleep(0.2)
    assert len(detail["replicas"]) == 1
    # The retired replica finished COMPLETED — a drain, not a kill — and
    # was NOT respawned.
    serving = c.api("GET", "/api/v1/serving", token=token)["serving"]
    done = [t for t in serving if t["state"] == "COMPLETED"]
    assert len(done) == 1, serving
    assert int(done[0].get("restarts") or 0) == 0


def test_autoscaler_scales_up_on_backpressure_down_when_idle(fleet):
    c = fleet
    token = c.login()
    cfg = _dep_config(min_r=1, max_r=2, target=1, heartbeat_s=0.2,
                      scale_up_after_s=0.5, scale_down_after_s=0.5,
                      scale_up_threshold=0.5, scale_down_threshold=0.2)
    resp = c.api("POST", "/api/v1/deployments", {"config": cfg},
                 token=token)
    dep_id = resp["id"]
    detail = _wait_ready(c, token, dep_id, 1)
    addr = detail["replicas"][0]["proxy_address"]

    # Sustained backpressure: the replica reports a full queue + full
    # batch until the smoothed signal crosses the threshold.
    _http("POST", f"{addr}/force_stats",
          {"queue_depth": 8, "queue_capacity": 8, "active": 4, "slots": 4})
    deadline = time.time() + 45
    while time.time() < deadline:
        detail = c.api("GET", f"/api/v1/deployments/{dep_id}",
                       token=token)["deployment"]
        if detail["target_replicas"] == 2:
            break
        time.sleep(0.2)
    assert detail["target_replicas"] == 2, detail
    detail = _wait_ready(c, token, dep_id, 2)

    # Quiet down: the signal decays, the idle cooldown passes, target
    # returns to min — via drain (completed, not canceled/killed).
    _http("POST", f"{addr}/force_stats", {})
    deadline = time.time() + 90
    while time.time() < deadline:
        detail = c.api("GET", f"/api/v1/deployments/{dep_id}",
                       token=token)["deployment"]
        if detail["target_replicas"] == 1 and len(detail["replicas"]) == 1:
            break
        time.sleep(0.3)
    assert detail["target_replicas"] == 1, detail
    assert len(detail["replicas"]) == 1

    # Scale events are published on the stream and counted in /metrics.
    raw = urllib.request.urlopen(urllib.request.Request(
        f"{c.master_url}/metrics",
        headers={"Authorization": f"Bearer {token}"}), timeout=10
    ).read().decode()
    ups = [line for line in raw.splitlines() if line.startswith(
        'det_deployment_scale_events_total{direction="up"}')]
    downs = [line for line in raw.splitlines() if line.startswith(
        'det_deployment_scale_events_total{direction="down"}')]
    assert ups and int(ups[0].split()[-1]) >= 1
    assert downs and int(downs[0].split()[-1]) >= 1
    stream = c.api("GET", "/api/v1/stream?entities=deployments&"
                   "timeout_seconds=0", token=token)
    assert any(e["payload"].get("direction") == "up"
               for e in stream["events"]), stream


def test_scale_to_zero_idle_drain_and_demand_wake_cold_start(fleet):
    """docs/serving.md "Scale to zero": min 0 lets the idle cooldown
    drain the LAST replica (the deployment costs nothing while idle); the
    next request is NOT shed — the router wakes target 0 -> 1, HOLDS the
    request within cold_start_budget_s, and serves it, leaving a
    serve.cold_start span (engine_source=deserialize: the warm-AOT path,
    never a re-trace) on the request's trace."""
    c = fleet
    token = c.login()
    cfg = _dep_config(min_r=0, max_r=1, target=1, heartbeat_s=0.3,
                      scale_down_after_s=1.0, scale_down_threshold=0.5,
                      cold_start_budget_s=45)
    dep_id = c.api("POST", "/api/v1/deployments", {"config": cfg},
                   token=token)["id"]
    _wait_ready(c, token, dep_id, 1)

    # Idle cooldown drains to ZERO replicas.
    deadline = time.time() + 60
    while time.time() < deadline:
        d = c.api("GET", f"/api/v1/deployments/{dep_id}",
                  token=token)["deployment"]
        if int(d["target_replicas"]) == 0 and not d["replicas"]:
            break
        time.sleep(0.3)
    else:
        raise TimeoutError(f"never drained to zero: {d}")

    # The wake: one request, held through the cold start, answered 200.
    t0 = time.time()
    status, headers, body = _generate(c, token, dep_id, timeout=90.0)
    assert status == 200, (status, body)
    rid = headers.get("X-Request-Id")
    assert rid
    # Target is back at 1 and the replica that answered is live.
    d = c.api("GET", f"/api/v1/deployments/{dep_id}",
              token=token)["deployment"]
    assert int(d["target_replicas"]) == 1
    # The trace carries the cold-start phase with warm-AOT provenance.
    status, _, trace = _trace(c, token, dep_id, rid)
    assert status == 200, trace
    by_name = {s["name"]: s for s in trace["spans"]}
    assert "serve.cold_start" in by_name, sorted(by_name)
    cold = by_name["serve.cold_start"]
    assert cold["attrs"]["engine_source"] == "deserialize", cold
    assert 0 <= cold["attrs"]["wait_ms"] <= (time.time() - t0) * 1000 + 1
    assert cold["attrs"]["budget_s"] == 45


def test_cold_deployment_answers_503_with_computed_retry_after(master_only):
    """A deployment with zero READY replicas but NONZERO target (replicas
    still starting — here: no agent exists at all) answers 503 with a
    Retry-After computed from the spawn + warm-AOT budget, never a
    connection error, and never opens breakers against replicas that have
    not started."""
    c = master_only
    token = c.login()
    cfg = _dep_config(min_r=1, max_r=1, target=1,
                      cold_start_budget_s=20)
    dep_id = c.api("POST", "/api/v1/deployments", {"config": cfg},
                   token=token)["id"]
    status, headers, body = _generate(c, token, dep_id)
    assert status == 503, (status, body)
    # No observed cold start yet -> budget/4 = 5s.
    assert headers.get("Retry-After") == "5", headers
    # Repeatable — shedding, not an error path.
    status, headers, _ = _generate(c, token, dep_id)
    assert status == 503 and headers.get("Retry-After") == "5"


def test_breaker_ignores_starting_replica_refusals(fleet):
    """A replica whose proxy address is registered but whose engine is
    still loading refuses connections; those refusals are boot noise and
    must NOT open the circuit breaker — the first real request after the
    engine comes up goes straight through."""
    c = fleet
    token = c.login()
    cfg = _dep_config(min_r=1, max_r=1, target=1, heartbeat_s=0.3)
    cfg["environment"]["DET_FAKE_STARTING_S"] = "4"
    dep_id = c.api("POST", "/api/v1/deployments", {"config": cfg},
                   token=token)["id"]

    # Wait for the proxy address (replica looks routable, engine is not).
    deadline = time.time() + 60
    while time.time() < deadline:
        d = c.api("GET", f"/api/v1/deployments/{dep_id}",
                  token=token)["deployment"]
        reps = [r for r in d["replicas"] if r.get("proxy_address")
                and r.get("allocation_state") == "RUNNING"]
        if reps:
            break
        time.sleep(0.2)
    else:
        raise TimeoutError(f"replica never registered a proxy: {d}")

    # Hammer it during the STARTING window: refusals surface (502) but
    # must not count toward the breaker.
    refusals = 0
    for _ in range(4):
        status, _, _ = _generate(c, token, dep_id, timeout=20.0)
        if status in (502, 503):
            refusals += 1
        time.sleep(0.2)
    assert refusals >= 3, "expected connection refusals while STARTING"
    d = c.api("GET", f"/api/v1/deployments/{dep_id}",
              token=token)["deployment"]
    rep = d["replicas"][0]
    assert rep["consecutive_failures"] == 0, rep
    assert not rep["breaker_open"], rep

    # Engine up (first heartbeat arrives) -> immediate success, no
    # breaker hold to wait out.
    _wait_ready(c, token, dep_id, 1)
    status, _, body = _generate(c, token, dep_id)
    assert status == 200, (status, body)


def test_spot_placement_floor_and_drain_retarget(tmp_path, native_binaries):  # noqa: F811
    """Spot-aware serving (docs/cluster-ops.md "Capacity loop"): the
    on_demand_floor replica lands on non-preemptible capacity, the
    surplus replica lands on the spot agent first; a PR-5 preemption
    notice on the spot agent drains its replica cooperatively (zero
    dropped) while the reconciler immediately spawns the replacement on
    surviving on-demand capacity."""
    c = Devcluster(str(tmp_path), native_binaries, slots=4)
    c.start_master()
    c.start_agent("agent-od")
    c.start_agent("agent-spot", extra_env={"DET_AGENT_PREEMPTIBLE": "1"})
    try:
        token = c.login()
        agents = {a["id"]: a for a in
                  c.api("GET", "/api/v1/agents", token=token)["agents"]}
        assert agents["agent-spot"]["preemptible"] is True
        assert agents["agent-od"]["preemptible"] is False

        cfg = _dep_config(min_r=2, max_r=2, target=2, heartbeat_s=0.3,
                          on_demand_floor=1)
        cfg["resources"]["slots"] = 1
        dep_id = c.api("POST", "/api/v1/deployments", {"config": cfg},
                       token=token)["id"]
        detail = _wait_ready(c, token, dep_id, 2)
        placed = {r["capacity_class"]: r for r in detail["replicas"]}
        assert set(placed) == {"on_demand", "spot_first"}, detail
        assert placed["on_demand"]["agent"] == "agent-od", detail
        assert placed["spot_first"]["agent"] == "agent-spot", detail
        spot_task = placed["spot_first"]["task_id"]

        # Spot reclamation: termination notice on the spot agent. The
        # replica drains inside the deadline; the replacement respawns on
        # the on-demand agent; requests keep flowing throughout.
        c.api("POST", "/api/v1/agents/agent-spot/preempt_notice",
              {"deadline_seconds": 20, "reason": "spot_preemption"},
              token=c.login("admin"))
        status, _, body = _generate(c, token, dep_id)
        assert status == 200, (status, body)  # zero dropped during drain

        deadline = time.time() + 60
        while time.time() < deadline:
            d = c.api("GET", f"/api/v1/deployments/{dep_id}",
                      token=token)["deployment"]
            live = [r for r in d["replicas"]
                    if not r["retiring"]
                    and r.get("allocation_state") == "RUNNING"
                    and r.get("proxy_address")]
            if (len(live) == 2
                    and all(r["agent"] == "agent-od" for r in live)
                    and spot_task not in [r["task_id"] for r in live]):
                break
            time.sleep(0.3)
        else:
            raise TimeoutError(f"replacement never landed on-demand: {d}")
        # The drained spot replica finished cleanly (drain, not a kill).
        status, _, body = _generate(c, token, dep_id)
        assert status == 200, (status, body)
    finally:
        c.stop()


def test_replica_death_respawns_to_target(fleet):
    """A replica that dies (nonzero exit) respawns via the PR-6 requeue
    machinery under the SAME task id — the deployment holds target."""
    c = fleet
    token = c.login()
    resp = c.api("POST", "/api/v1/deployments",
                 {"config": _dep_config(target=1, max_r=1)}, token=token)
    dep_id = resp["id"]
    detail = _wait_ready(c, token, dep_id, 1)
    tid = detail["replicas"][0]["task_id"]
    try:
        _http("POST", f"{detail['replicas'][0]['proxy_address']}/die", {},
              timeout=5)
    except Exception:
        pass
    # First the death lands (restarts bumps), then the respawn comes up.
    deadline = time.time() + 120
    task = {}
    while time.time() < deadline:
        task = c.api("GET", f"/api/v1/serving/{tid}", token=token)["task"]
        if int(task.get("restarts") or 0) >= 1:
            break
        time.sleep(0.2)
    assert int(task.get("restarts") or 0) >= 1, task
    detail = _wait_ready(c, token, dep_id, 1, timeout=120)
    assert detail["replicas"][0]["task_id"] == tid


# ---------------------------------------------------------------------------
# Request-path observability: per-request traces, latency aggregation,
# slow-request ring (ISSUE 12; docs/serving.md "Request latency & SLOs").
# ---------------------------------------------------------------------------


def test_request_trace_end_to_end_with_waterfall(fleet):
    """The acceptance contract: a request served through
    /serve/{deployment} yields a PERSISTED span tree with router-dispatch,
    queue-wait, prefill, and decode phases, and `det serve trace` renders
    it as a waterfall."""
    from determined_tpu.common.trace import render_waterfall

    c = fleet
    token = c.login()
    resp = c.api("POST", "/api/v1/deployments",
                 {"config": _dep_config(target=2)}, token=token)
    dep_id = resp["id"]
    _wait_ready(c, token, dep_id, 2)

    # Caller-supplied X-Request-Id is adopted and echoed.
    rid = "trace-me-1"
    status, headers, body = _generate(
        c, token, dep_id, {"max_new_tokens": 4},
        headers={"X-Request-Id": rid})
    assert status == 200, body
    assert headers.get("X-Request-Id") == rid
    assert body["id"] == rid  # the replica served under the same id

    status, _, trace = _trace(c, token, dep_id, rid)
    assert status == 200, trace
    spans = trace["spans"]
    names = {s["name"] for s in spans}
    assert {"serve.request", "serve.router.dispatch", "serve.queue_wait",
            "serve.prefill", "serve.decode"} <= names, names
    # One trace: every span rides the request id; the root IS the id.
    assert all(s["trace_id"] == rid for s in spans)
    root = [s for s in spans if s["name"] == "serve.request"][0]
    assert root["span_id"] == rid
    for s in spans:
        if s["name"] != "serve.request":
            assert s["parent"] == rid, s
    # Phase attrs made it through the store.
    prefill = [s for s in spans if s["name"] == "serve.prefill"][0]
    assert prefill["attrs"]["suffix_len"] >= 1
    dispatch = [s for s in spans if s["name"] == "serve.router.dispatch"][0]
    assert dispatch["attrs"]["status"] == 200
    assert dispatch["attrs"]["retried"] is False
    # Spans are closed and ordered on one timeline.
    assert all(s["end_us"] >= s["start_us"] > 0 for s in spans)
    # The CLI waterfall renders it (same renderer as `det trial trace`).
    out = render_waterfall(spans)
    assert "serve.router.dispatch" in out and "serve.decode" in out
    assert "#" in out  # duration bars drawn

    # Router-minted ids: no header → a fresh rq-* id comes back and its
    # trace is just as queryable (by deployment NAME too).
    status, headers, body = _generate(c, token, dep_id,
                                      {"max_new_tokens": 2})
    assert status == 200
    minted = headers.get("X-Request-Id", "")
    assert minted.startswith("rq-")
    status, _, trace = _trace(c, token, "fake-dep", minted)
    assert status == 200 and trace["deployment_id"] == dep_id

    # Unknown request id → 404 that names the miss, not a routing 404.
    status, _, body = _trace(c, token, dep_id, "rq-never-happened")
    assert status == 404 and "no spans" in body["error"]


def test_request_trace_retried_dispatch_shows_both_attempts(fleet):
    """A connection-refused dispatch that retries onto the survivor leaves
    BOTH attempts in the trace: attempt 0 with the error, attempt 1 with
    the 200 — the 'why was THIS request slow' answer for failover."""
    c = fleet
    token = c.login()
    resp = c.api("POST", "/api/v1/deployments",
                 {"config": _dep_config(target=2, max_r=2)}, token=token)
    dep_id = resp["id"]
    detail = _wait_ready(c, token, dep_id, 2)
    victim = detail["replicas"][0]
    try:
        _http("POST", f"{victim['proxy_address']}/die", {}, timeout=5)
    except Exception:
        pass  # the process may die before finishing the response

    # The router learns of the death only by connecting: issue requests
    # until one draws the dead replica first (tie rotation alternates, so
    # this converges in a couple of tries).
    retried_trace = None
    for i in range(12):
        rid = f"retry-{i}"
        status, _, body = _generate(
            c, token, dep_id, {"max_new_tokens": 2, "delay_ms": 1},
            headers={"X-Request-Id": rid})
        if status != 200:
            continue  # in-flight edge cases surface as explicit errors
        status, _, trace = _trace(c, token, dep_id, rid)
        if status != 200:
            continue
        dispatches = [s for s in trace["spans"]
                      if s["name"] == "serve.router.dispatch"]
        if len(dispatches) == 2:
            retried_trace = (rid, trace, dispatches)
            break
    assert retried_trace is not None, "no request drew the dead replica"
    rid, trace, dispatches = retried_trace
    dispatches.sort(key=lambda s: s["attrs"]["attempt"])
    first, second = dispatches
    assert first["attrs"]["attempt"] == 0 and "error" in first["attrs"]
    assert first["attrs"]["replica"] == victim["task_id"]
    assert second["attrs"]["attempt"] == 1
    assert second["attrs"]["retried"] is True
    assert second["attrs"]["status"] == 200
    # The replica-side phases exist alongside both dispatch attempts.
    names = {s["name"] for s in trace["spans"]}
    assert {"serve.request", "serve.prefill", "serve.decode"} <= names


def test_deployment_latency_aggregation_and_slow_ring(fleet):
    """Replica heartbeats carry TTFT/TPOT/e2e/queue-wait histograms; the
    master aggregates fresh ones into per-deployment p50/p99 on the
    detail API, exposes det_serve_request_seconds{deployment=...} on
    /metrics, and records SLO breaches in the slow-request ring."""
    c = fleet
    token = c.login()
    cfg = _dep_config(target=2, heartbeat_s=0.2)
    # Every fake generation takes ~30 ms — a 1 ms SLO makes each one a
    # breach, so the ring fills deterministically.
    cfg["serving"]["slo_ms"] = 1
    resp = c.api("POST", "/api/v1/deployments", {"config": cfg},
                 token=token)
    dep_id = resp["id"]
    _wait_ready(c, token, dep_id, 2)

    rids = []
    for i in range(6):
        status, headers, _ = _generate(c, token, dep_id,
                                       {"max_new_tokens": 4})
        assert status == 200
        rids.append(headers["X-Request-Id"])

    # Aggregation rides the heartbeat: poll until all 6 requests landed.
    deadline = time.time() + 30
    lat = {}
    while time.time() < deadline:
        detail = c.api("GET", f"/api/v1/deployments/{dep_id}",
                       token=token)["deployment"]
        lat = detail.get("latency") or {}
        if (lat.get("e2e") or {}).get("count", 0) >= 6:
            break
        time.sleep(0.2)
    assert lat["e2e"]["count"] >= 6, detail
    for key in ("ttft", "tpot", "e2e", "queue_wait"):
        h = lat[key]
        assert h["count"] >= 1 and h["p99_ms"] >= h["p50_ms"] >= 0, (key, h)
    # TTFT ≈ 25% of the ~30 ms service time; e2e covers all of it.
    assert lat["e2e"]["p50_ms"] > lat["ttft"]["p50_ms"] > 0
    # Per-replica summaries ride the detail too.
    assert any((r.get("latency") or {}).get("e2e", {}).get("count", 0) > 0
               for r in detail["replicas"])

    # The list API (what `det serve status` prints) carries the same
    # aggregation.
    deps = c.api("GET", "/api/v1/deployments", token=token)["deployments"]
    mine = [d for d in deps if d["id"] == dep_id][0]
    assert mine["latency"]["e2e"]["count"] >= 6

    # Slow-request ring: every request breached the 1 ms SLO; entries are
    # traceable ids, newest first.
    assert detail["slo_ms"] == 1
    ring = detail["slow_requests"]
    assert ring, detail
    assert all(s["ms"] > 1 and s["request_id"] for s in ring)
    assert {s["request_id"] for s in ring} <= set(rids)

    # CLI smoke: `det serve status` renders the p50/p99 latency columns
    # and `det serve trace` renders a slow request's waterfall.
    import argparse
    import contextlib
    import io

    from determined_tpu.cli import cmd_serve
    from determined_tpu.common.api import Session

    sess = Session(c.master_url, token)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        cmd_serve(sess, argparse.Namespace(
            target="status", extra=[], local=False, json=False))
    out = buf.getvalue()
    assert "ttft_ms" in out and "tpot_ms" in out and "e2e_ms" in out
    assert dep_id in out
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        cmd_serve(sess, argparse.Namespace(
            target="trace", extra=[dep_id, ring[0]["request_id"]],
            local=False, json=False))
    out = buf.getvalue()
    assert "serve.router.dispatch" in out and "serve.decode" in out

    # Master /metrics: per-deployment latency histogram + counters.
    raw = urllib.request.urlopen(urllib.request.Request(
        f"{c.master_url}/metrics",
        headers={"Authorization": f"Bearer {token}"}), timeout=10
    ).read().decode()
    count_lines = [line for line in raw.splitlines() if line.startswith(
        f'det_serve_request_seconds_count{{deployment="{dep_id}"}}')]
    assert count_lines and int(count_lines[0].split()[-1]) >= 6, count_lines
    spans_total = [line for line in raw.splitlines()
                   if line.startswith("det_request_spans_ingested_total")]
    assert spans_total and int(spans_total[0].split()[-1]) >= 6
    breaches = [line for line in raw.splitlines()
                if line.startswith("det_serve_slo_breaches_total")]
    assert breaches and int(breaches[0].split()[-1]) >= 6


# ---------------------------------------------------------------------------
# Model lifecycle: registry-driven rolling swaps, canary routing, version
# surfacing (docs/serving.md "Model lifecycle").
# ---------------------------------------------------------------------------


def _register_versions(c, token, model, uuids):
    """Trial-less COMPLETED checkpoint rows + registry versions 1..N for
    them; returns nothing (versions are 1-based in uuid order)."""
    _http("POST", f"{c.master_url}/api/v1/models",
          {"name": model, "metadata": {}, "labels": []}, token=token)
    for uuid in uuids:
        c.api("POST", "/api/v1/checkpoints",
              {"uuid": uuid, "state": "COMPLETED"}, token=token)
        c.api("POST", f"/api/v1/models/{model}/versions",
              {"checkpoint_uuid": uuid}, token=token)


def _live_versions(detail):
    return sorted((r["model_version"], r.get("canary", False))
                  for r in detail["replicas"] if not r["retiring"])


def test_register_version_requires_committed_checkpoint(master_only):
    """Registry versions are immutable promises: only COMPLETED
    checkpoints register; unknown/PARTIAL refuse; numbering is
    sequential; the version detail carries the checkpoint; registration
    publishes a `models` stream event."""
    c = master_only
    token = c.login()
    c.api("POST", "/api/v1/models",
          {"name": "m", "metadata": {}, "labels": []}, token=token)
    # Unknown checkpoint: 404.
    status, _, body = _http(
        "POST", f"{c.master_url}/api/v1/models/m/versions",
        {"checkpoint_uuid": "nope"}, token=token)
    assert status == 404, body
    # PARTIAL checkpoint: 400 (torsos never become versions).
    c.api("POST", "/api/v1/checkpoints",
          {"uuid": "ck-partial", "state": "PARTIAL"}, token=token)
    status, _, body = _http(
        "POST", f"{c.master_url}/api/v1/models/m/versions",
        {"checkpoint_uuid": "ck-partial"}, token=token)
    assert status == 400 and "PARTIAL" in body["error"], body
    # COMPLETED registers, versions count up, detail resolves.
    _register_versions(c, token, "m", ["ck-1", "ck-2"])
    vers = c.api("GET", "/api/v1/models/m/versions",
                 token=token)["model_versions"]
    assert [v["version"] for v in vers] == [1, 2]
    one = c.api("GET", "/api/v1/models/m/versions/2",
                token=token)["model_version"]
    assert one["checkpoint_uuid"] == "ck-2"
    stream = c.api("GET", "/api/v1/stream?entities=models&timeout_seconds=0",
                   token=token)
    assert any(e["payload"].get("version") == 2
               and e["payload"].get("model") == "m"
               for e in stream["events"]), stream


def test_rolling_update_swap_and_rollback(fleet):
    """`det serve update` semantics: the deployment rolls to the new
    version one replica at a time — spawn-at-new BEFORE drain-at-old
    (live never exceeds target+1, dispatch never fails) — and rolling
    back is the same call with the prior version. The completed swap
    leaves a serve.swap span reachable through the stream's swap_id."""
    c = fleet
    token = c.login()
    cfg = _dep_config(min_r=1, max_r=4, target=2, heartbeat_s=0.3)
    dep_id = c.api("POST", "/api/v1/deployments", {"config": cfg},
                   token=token)["id"]
    detail = _wait_ready(c, token, dep_id, 2)
    # Initial version label derives from the pinned checkpoint.
    assert detail["model_version"] == "checkpoint:latest"
    v0_tasks = {r["task_id"] for r in detail["replicas"]}

    _register_versions(c, token, "m", ["ck-v1", "ck-v2"])
    resp = c.api("POST", f"/api/v1/deployments/{dep_id}/update",
                 {"model": "m", "version": 2}, token=token)
    assert resp["rolling"] and resp["model_version"] == "m:2"
    assert resp["checkpoint"] == "ck-v2"

    # Roll to completion: every generation keeps succeeding, live
    # non-retiring never exceeds target+1 (the one-at-a-time surge).
    deadline = time.time() + 120
    while time.time() < deadline:
        status, _, out = _generate(c, token, dep_id)
        assert status == 200, out
        detail = c.api("GET", f"/api/v1/deployments/{dep_id}",
                       token=token)["deployment"]
        live = [r for r in detail["replicas"] if not r["retiring"]]
        assert len(live) <= 3, _live_versions(detail)
        if (len(detail["replicas"]) == 2
                and all(r["model_version"] == "m:2"
                        for r in detail["replicas"])
                and "swap" not in detail):
            break
        time.sleep(0.3)
    assert detail["model_version"] == "m:2"
    assert all(r["model_version"] == "m:2" for r in detail["replicas"]), \
        _live_versions(detail)
    # Blue-green for real: the v2 set is a fresh replica set.
    assert not v0_tasks & {r["task_id"] for r in detail["replicas"]}
    # A generation now reports the new version (fake echoes
    # DET_MODEL_VERSION, exactly like the real replica's heartbeat).
    status, _, out = _generate(c, token, dep_id)
    assert status == 200 and out["model_version"] == "m:2", out

    # serve.swap span: the stream's swap_complete event names the span's
    # request-id scope; the trace endpoint serves it back.
    stream = c.api(
        "GET", "/api/v1/stream?entities=deployments&timeout_seconds=0",
        token=token)
    done = [e["payload"] for e in stream["events"]
            if e["payload"].get("swap_complete")]
    assert done and done[-1]["model_version"] == "m:2", stream
    status, _, tr = _trace(c, token, dep_id, done[-1]["swap_id"])
    assert status == 200
    swap_spans = [s for s in tr["spans"] if s["name"] == "serve.swap"]
    assert swap_spans, tr
    attrs = swap_spans[0]["attrs"]
    assert attrs["to"] == "m:2" and attrs["replicas_swapped"] == 2, attrs

    # Rollback = update back to the prior version (still registered).
    resp = c.api("POST", f"/api/v1/deployments/{dep_id}/update",
                 {"model": "m", "version": 1}, token=token)
    assert resp["model_version"] == "m:1"
    deadline = time.time() + 120
    while time.time() < deadline:
        detail = c.api("GET", f"/api/v1/deployments/{dep_id}",
                       token=token)["deployment"]
        if (len(detail["replicas"]) == 2
                and all(r["model_version"] == "m:1"
                        for r in detail["replicas"])):
            break
        time.sleep(0.3)
    assert all(r["model_version"] == "m:1" for r in detail["replicas"]), \
        _live_versions(detail)
    # No-op update answers rolling=false.
    resp = c.api("POST", f"/api/v1/deployments/{dep_id}/update",
                 {"model": "m", "version": 1}, token=token)
    assert resp["rolling"] is False
    # Unknown version/model: 400 with a useful message.
    status, _, body = _http(
        "POST", f"{c.master_url}/api/v1/deployments/{dep_id}/update",
        {"model": "m", "version": 9}, token=token)
    assert status == 400 and "no version 9" in body["error"], body
    status, _, body = _http(
        "POST", f"{c.master_url}/api/v1/deployments/{dep_id}/update",
        {"model": "ghost"}, token=token)
    assert status == 400 and "no such model" in body["error"], body


def test_canary_split_observed_fraction_and_promote(fleet):
    """Canary routing: a 0.25 split sends EXACTLY every 4th traced
    generation to the canary replica (deterministic debt accounting),
    per-version latency aggregates separately, and promote folds the
    canary version into the deployment via the rolling-swap path."""
    c = fleet
    token = c.login()
    cfg = _dep_config(min_r=1, max_r=2, target=1, heartbeat_s=0.3)
    dep_id = c.api("POST", "/api/v1/deployments", {"config": cfg},
                   token=token)["id"]
    _wait_ready(c, token, dep_id, 1)
    _register_versions(c, token, "m", ["ck-v1", "ck-v2"])

    # Fraction gate: the API refuses anything outside (0, 1) — the
    # DTL208 contract at the verb.
    for bad in (0, 1.0, -0.25, 2):
        status, _, body = _http(
            "POST", f"{c.master_url}/api/v1/deployments/{dep_id}/canary",
            {"model": "m", "version": 2, "fraction": bad}, token=token)
        assert status == 400 and "(0, 1)" in body["error"], (bad, body)
    # Promote/abort without a canary: 400.
    for verb in ({"promote": True}, {"abort": True}):
        status, _, body = _http(
            "POST", f"{c.master_url}/api/v1/deployments/{dep_id}/canary",
            verb, token=token)
        assert status == 400, (verb, body)

    resp = c.api("POST", f"/api/v1/deployments/{dep_id}/canary",
                 {"model": "m", "version": 2, "fraction": 0.25},
                 token=token)
    assert resp["canary"] == "m:2" and resp["fraction"] == 0.25

    # Wait for the canary replica to become routable beside stable.
    deadline = time.time() + 90
    while time.time() < deadline:
        detail = c.api("GET", f"/api/v1/deployments/{dep_id}",
                       token=token)["deployment"]
        ready_canary = [
            r for r in detail["replicas"]
            if r.get("canary") and r.get("allocation_state") == "RUNNING"
            and r.get("proxy_address")
            and 0 <= (r.get("report_age_s") or -1) < 10]
        if ready_canary:
            break
        time.sleep(0.2)
    assert ready_canary, detail
    assert detail["canary"]["version"] == "m:2"

    # 40 traced generations: the debt accumulator routes exactly 10 to
    # the canary (both groups stayed routable throughout).
    by_version = {}
    for _ in range(40):
        status, _, out = _generate(c, token, dep_id)
        assert status == 200, out
        v = out.get("model_version") or "stable"
        by_version[v] = by_version.get(v, 0) + 1
    assert by_version.get("m:2") == 10, by_version

    detail = c.api("GET", f"/api/v1/deployments/{dep_id}",
                   token=token)["deployment"]
    canary = detail["canary"]
    assert canary["routed"] == 10 and canary["routed_stable"] == 30, canary
    assert abs(canary["observed_fraction"] - 0.25) < 1e-9
    # Canary-vs-stable latency side by side (after the next heartbeat
    # ships the histograms).
    deadline = time.time() + 15
    byv = {}
    while time.time() < deadline:
        detail = c.api("GET", f"/api/v1/deployments/{dep_id}",
                       token=token)["deployment"]
        byv = detail.get("latency_by_version") or {}
        if len(byv) >= 2 and all(
                (v.get("e2e") or {}).get("count") for v in byv.values()):
            break
        time.sleep(0.3)
    assert "m:2" in byv and len(byv) == 2, byv
    # The split shows up on master /metrics.
    raw = urllib.request.urlopen(urllib.request.Request(
        f"{c.master_url}/metrics",
        headers={"Authorization": f"Bearer {token}"}), timeout=10
    ).read().decode()
    assert (f'det_serve_canary_requests_total{{deployment="{dep_id}"'
            ',group="canary"} 10') in raw, raw

    # Promote: the canary replica becomes the stable set; the old stable
    # replica drains; deployment lands on m:2 with target replicas.
    canary_task = ready_canary[0]["task_id"]
    resp = c.api("POST", f"/api/v1/deployments/{dep_id}/canary",
                 {"promote": True}, token=token)
    assert resp["promoted"] == "m:2"
    assert resp["canary_stats"]["routed"] == 10
    deadline = time.time() + 120
    while time.time() < deadline:
        detail = c.api("GET", f"/api/v1/deployments/{dep_id}",
                       token=token)["deployment"]
        if (len(detail["replicas"]) == 1
                and detail["replicas"][0]["model_version"] == "m:2"
                and not detail["replicas"][0]["retiring"]):
            break
        time.sleep(0.3)
    assert detail["model_version"] == "m:2"
    assert detail.get("canary") is None
    # The promoted replica IS the canary task (already at m:2 — no
    # needless respawn), demoted to a regular replica.
    assert detail["replicas"][0]["task_id"] == canary_task
    assert detail["replicas"][0]["canary"] is False


def test_canary_abort_drains_canary_only(fleet):
    """Abort drains the canary replicas and leaves stable untouched —
    the cheap exit when the canary's p99 looks wrong."""
    c = fleet
    token = c.login()
    cfg = _dep_config(min_r=1, max_r=2, target=1, heartbeat_s=0.3)
    dep_id = c.api("POST", "/api/v1/deployments", {"config": cfg},
                   token=token)["id"]
    detail = _wait_ready(c, token, dep_id, 1)
    stable_task = detail["replicas"][0]["task_id"]
    _register_versions(c, token, "m", ["ck-v1"])
    c.api("POST", f"/api/v1/deployments/{dep_id}/canary",
          {"model": "m", "version": 1, "fraction": 0.5}, token=token)
    deadline = time.time() + 90
    while time.time() < deadline:
        detail = c.api("GET", f"/api/v1/deployments/{dep_id}",
                       token=token)["deployment"]
        if any(r.get("canary") for r in detail["replicas"]):
            break
        time.sleep(0.2)
    assert any(r.get("canary") for r in detail["replicas"]), detail

    resp = c.api("POST", f"/api/v1/deployments/{dep_id}/canary",
                 {"abort": True}, token=token)
    assert resp["aborted"] == "m:1"
    deadline = time.time() + 120
    while time.time() < deadline:
        detail = c.api("GET", f"/api/v1/deployments/{dep_id}",
                       token=token)["deployment"]
        if (len(detail["replicas"]) == 1
                and not detail["replicas"][0].get("canary")):
            break
        time.sleep(0.3)
    assert detail["replicas"][0]["task_id"] == stable_task, detail
    assert detail.get("canary") is None
    assert detail["model_version"] == "checkpoint:latest"
    # Post-abort traffic is 100% stable (the initial checkpoint label).
    status, _, out = _generate(c, token, dep_id)
    assert status == 200, out
    assert out.get("model_version") == "checkpoint:latest", out


def test_lifecycle_expconf_and_create_gate(master_only):
    """Config-declared lifecycle blocks: serving.canary arms the split at
    deployment create (resolved through the registry), and the DTL208
    fraction gate refuses a bad fraction at POST /deployments when the
    preflight gate is armed."""
    c = master_only
    token = c.login()
    _register_versions(c, token, "m", ["ck-v1", "ck-v2"])
    cfg = _dep_config(min_r=1, max_r=2, target=1)
    cfg["serving"]["canary"] = {"model": "m", "version": 2,
                                "fraction": 0.1}
    cfg = expconf.check(cfg)  # client-side validation passes + defaults
    assert cfg["serving"]["canary"]["replicas"] == 1
    dep_id = c.api("POST", "/api/v1/deployments", {"config": cfg},
                   token=token)["id"]
    detail = c.api("GET", f"/api/v1/deployments/{dep_id}",
                   token=token)["deployment"]
    assert detail["canary"]["version"] == "m:2"
    assert detail["canary"]["fraction"] == 0.1
    # One canary replica spawns beside the stable target within a couple
    # of reconcile ticks (the crash-loop spawn throttle spaces it from
    # the stable spawn; no agent in this cluster, so they stay PENDING —
    # fine for the check).
    deadline = time.time() + 15
    while time.time() < deadline:
        detail = c.api("GET", f"/api/v1/deployments/{dep_id}",
                       token=token)["deployment"]
        if sum(1 for r in detail["replicas"] if r["canary"]) == 1:
            break
        time.sleep(0.3)
    assert sum(1 for r in detail["replicas"] if r["canary"]) == 1, detail

    # Master-side DTL208 gate (same gate:error semantics as experiments).
    bad = _dep_config(min_r=1, max_r=2, target=1)
    bad["serving"]["canary"] = {"model": "m", "fraction": 1.5}
    bad["preflight"] = {"gate": "error"}
    status, _, body = _http("POST", f"{c.master_url}/api/v1/deployments",
                            {"config": bad}, token=token)
    assert status == 400, body
    assert any(d.get("code") == "DTL208"
               for d in body.get("preflight", [])), body

    # serving.model_version pins a registered version at create.
    pinned = _dep_config(min_r=1, max_r=2, target=1)
    pinned["serving"]["model_version"] = "m:1"
    resp = c.api("POST", "/api/v1/deployments", {"config": pinned},
                 token=token)
    assert resp["model_version"] == "m:1"
    detail = c.api("GET", f"/api/v1/deployments/{resp['id']}",
                   token=token)["deployment"]
    assert detail["model_version"] == "m:1"
    assert all(r["model_version"] == "m:1" for r in detail["replicas"])
    # Unknown registry label at create: 400, not a broken deployment.
    pinned["serving"]["model_version"] = "ghost:7"
    status, _, body = _http("POST", f"{c.master_url}/api/v1/deployments",
                            {"config": pinned}, token=token)
    assert status == 400 and "no such model" in body["error"], body


# ---------------------------------------------------------------------------
# Full lifecycle with REAL replicas (make chaos).
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_deployment_lifecycle_real_replicas_e2e(tmp_path, native_binaries):  # noqa: F811
    """Scale-up under real load, scale-down via drain, zero dropped — with
    real engines serving a real checkpoint through the router."""
    import jax
    import jax.numpy as jnp

    from determined_tpu import core
    from determined_tpu.models import gpt2

    cfg = gpt2.Config(
        vocab_size=256, n_positions=64, d_model=32, n_layer=2, n_head=2,
        dtype=jnp.float32, remat=False, attention_impl="dot")
    params = gpt2.init(jax.random.PRNGKey(0), cfg)
    ctx = core.init(max_length=2,
                    checkpoint_dir=os.path.join(str(tmp_path), "ckpts"))
    ctx.checkpoint.save_state(
        {"step": jnp.asarray(2, jnp.int32), "params": params,
         "opt_state": {"count": jnp.zeros((), jnp.int32)}}, 2)
    ctx.checkpoint.wait()
    ctx.close()

    config = {
        "name": "real-dep",
        "serving": {
            "checkpoint": "trial0-step2",
            "model": "gpt2",
            "model_config": {"model_size": "tiny", "seq_len": 64,
                             "dtype": "float32", "vocab_size": 256,
                             "n_positions": 64, "d_model": 32,
                             "n_layer": 2, "n_head": 2},
            "max_batch_size": 4,
            "max_seq_len": 32,
            "prefill_buckets": [8],
            "queue_depth": 32,
            "heartbeat_period_s": 0.3,
            "replicas": {"min": 1, "max": 2, "target": 1,
                         "scale_up_after_s": 1.0,
                         "scale_down_after_s": 2.0,
                         "scale_up_threshold": 0.5,
                         "scale_down_threshold": 0.05},
        },
        "resources": {"slots_per_trial": 1},
        "checkpoint_storage": {
            "type": "shared_fs",
            "host_path": os.path.join(str(tmp_path), "ckpts"),
        },
    }

    c = Devcluster(str(tmp_path), native_binaries, slots=1)
    c.start_master()
    c.start_agent("fleet-a")
    c.start_agent("fleet-b")
    try:
        token = c.login()
        dep_id = c.api("POST", "/api/v1/deployments", {"config": config},
                       token=token)["id"]
        _wait_ready(c, token, dep_id, 1, timeout=240)

        stop_load = threading.Event()
        results, failures = [], []

        def _loader():
            while not stop_load.is_set():
                status, _, body = _generate(
                    c, token, dep_id,
                    {"tokens": [5, 9, 17, 3], "max_new_tokens": 16,
                     "timeout_s": 120}, timeout=150)
                if status == 200:
                    results.append(body)
                elif status in (429, 503):
                    time.sleep(0.2)  # explicit backpressure, not a drop
                else:
                    failures.append((status, body))

        threads = [threading.Thread(target=_loader) for _ in range(8)]
        for t in threads:
            t.start()

        # Sustained backpressure on the single replica → autoscale to 2.
        deadline = time.time() + 240
        scaled = False
        while time.time() < deadline:
            detail = c.api("GET", f"/api/v1/deployments/{dep_id}",
                           token=token)["deployment"]
            if detail["target_replicas"] == 2:
                scaled = True
                break
            time.sleep(0.5)
        assert scaled, f"never scaled up: {json.dumps(detail, indent=2)}"
        _wait_ready(c, token, dep_id, 2, timeout=240)

        # Load off → idle cooldown → drain back to 1 with zero dropped.
        stop_load.set()
        for t in threads:
            t.join(timeout=180)
        assert not failures, failures[:5]
        assert results, "no request completed under load"
        assert all(len(r["tokens"]) == 16 for r in results)

        deadline = time.time() + 240
        while time.time() < deadline:
            detail = c.api("GET", f"/api/v1/deployments/{dep_id}",
                           token=token)["deployment"]
            if (detail["target_replicas"] == 1
                    and len(detail["replicas"]) == 1):
                break
            time.sleep(0.5)
        assert detail["target_replicas"] == 1, detail
        # The drained replica completed cleanly (zero-dropped drain).
        serving = c.api("GET", "/api/v1/serving", token=token)["serving"]
        assert any(t["state"] == "COMPLETED" for t in serving), serving

        c.api("POST", f"/api/v1/deployments/{dep_id}/kill", token=token)
    finally:
        c.stop()
