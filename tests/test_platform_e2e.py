"""End-to-end platform tests: real master + agent as local processes.

Mirrors the reference's devcluster-based e2e strategy
(e2e_tests/tests/cluster/managed_cluster.py:27 — db+master+agent as local
processes, fault injection via kill/restart :50-98). Here the cluster is the
C++ master + C++ agent with artificial CPU slots; trials are real processes
running the Core API fixture in tests/fixtures/platform/.
"""

import json
import os
import shutil
import signal
import socket
import subprocess
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE_BIN = os.path.join(REPO, "native", "bin")
FIXTURES = os.path.join(REPO, "tests", "fixtures", "platform")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_http(url: str, timeout: float = 20.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2):
                return
        except Exception:
            time.sleep(0.2)
    raise TimeoutError(f"server at {url} did not come up")


@pytest.fixture(scope="session")
def native_binaries():
    subprocess.run(
        ["make", "-C", os.path.join(REPO, "native")], check=True,
        capture_output=True,
    )
    return NATIVE_BIN


class Devcluster:
    """One master + one agent with N artificial slots."""

    def __init__(self, tmpdir: str, binaries: str, slots: int = 2):
        self.tmpdir = tmpdir
        self.binaries = binaries
        self.slots = slots
        self.port = _free_port()
        self.master_url = f"http://127.0.0.1:{self.port}"
        self.db_path = os.path.join(tmpdir, "master.db")
        self.master = None
        self.agent = None
        self.extra_agents = []  # second+ agents (spot/drain tests)
        self.env = dict(
            os.environ,
            PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
            JAX_PLATFORMS="cpu",
        )
        # The axon TPU plugin's sitecustomize re-forces JAX_PLATFORMS=axon
        # when this is set — e2e trials must stay on the virtual CPU mesh
        # (and off the single real chip).
        self.env.pop("PALLAS_AXON_POOL_IPS", None)

    def start_master(self, extra_args=()):
        self.master = subprocess.Popen(
            [
                os.path.join(self.binaries, "determined-master"),
                "--port", str(self.port),
                "--host", "127.0.0.1",
                "--db", self.db_path,
                "--agent-timeout", "15",
                *extra_args,
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        _wait_http(self.master_url + "/api/v1/master")

    def start_agent(self, agent_id="agent-0", work_root=None, extra_env=None,
                    slots=None):
        """Start an agent. The first live one is `self.agent` (restart
        semantics of the older tests); further agents — multi-node drain /
        spot tests — land in `self.extra_agents`. `slots` overrides the
        cluster default (heterogeneous pools for elastic shrink tests).
        Returns the process."""
        if work_root is None:
            work_root = os.path.join(
                self.tmpdir,
                "agent-work" if agent_id == "agent-0" else f"work-{agent_id}")
        env = dict(self.env)
        env.update(extra_env or {})
        proc = subprocess.Popen(
            [
                os.path.join(self.binaries, "determined-agent"),
                "--master-url", self.master_url,
                "--id", agent_id,
                "--slots", str(slots if slots is not None else self.slots),
                "--slot-type", "cpu",
                "--addr", "127.0.0.1",
                "--work-root", work_root,
                # Agent service-account bootstrap token minted by the master.
                "--token-file", self.db_path + ".agent_token",
            ],
            env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        if self.agent is None or self.agent.poll() is not None:
            self.agent = proc
        else:
            self.extra_agents.append(proc)
        token = self.login()
        deadline = time.time() + 20
        while time.time() < deadline:
            agents = self.api("GET", "/api/v1/agents", token=token)["agents"]
            if any(a["id"] == agent_id and a["alive"] for a in agents):
                return proc
            time.sleep(0.2)
        raise TimeoutError("agent did not register")

    def kill_master(self):
        self.master.kill()
        self.master.wait()

    @staticmethod
    def _child_pids(pid: int):
        """Direct children of `pid` (Linux /proc)."""
        out = set()
        try:
            for tid in os.listdir(f"/proc/{pid}/task"):
                try:
                    with open(f"/proc/{pid}/task/{tid}/children") as f:
                        out.update(int(c) for c in f.read().split())
                except OSError:
                    continue
        except OSError:
            pass
        return out

    def find_orphans(self):
        """Pids of task processes spawned under this cluster that are
        still alive — the agent setpgid()s every task tree, so after
        stop() this must be empty (VERDICT item 6: the proxy suite's
        spawned servers used to outlive teardown)."""
        orphans = []
        for pid in os.listdir("/proc"):
            if not pid.isdigit():
                continue
            try:
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    cmdline = f.read().decode(errors="replace")
            except OSError:
                continue
            if self.tmpdir in cmdline:
                orphans.append(int(pid))
        return orphans

    def stop(self):
        # Collect the agents' task process groups BEFORE SIGKILLing the
        # agents: a killed agent can't run its own kill/reap path, and the
        # tasks (each setpgid'd into its own group, native/agent/main.cc)
        # would reparent to init and leak — the test_proxy servers did
        # exactly that.
        task_pgids = set()
        for proc in (*self.extra_agents, self.agent):
            if proc is not None and proc.poll() is None:
                task_pgids.update(self._child_pids(proc.pid))
        for proc in (*self.extra_agents, self.agent, self.master):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait()
        for pgid in task_pgids:
            try:
                os.killpg(pgid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        # Anything still holding on (e.g. a task that escaped its group):
        # kill by cmdline match so no suite leaks process trees.
        deadline = time.time() + 5
        while time.time() < deadline:
            orphans = self.find_orphans()
            if not orphans:
                break
            for pid in orphans:
                try:
                    os.kill(pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
            time.sleep(0.1)

    # -- tiny API client -----------------------------------------------
    def api(self, method: str, path: str, body=None, token=None):
        req = urllib.request.Request(
            self.master_url + path,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json",
                     **({"Authorization": f"Bearer {token}"} if token else {})},
            method=method,
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            text = resp.read().decode()
            return json.loads(text) if text else None

    def login(self, user: str = "determined", password: str = "") -> str:
        return self.api("POST", "/api/v1/auth/login",
                        {"username": user, "password": password})["token"]


@pytest.fixture()
def cluster(tmp_path, native_binaries):
    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    c.start_agent()
    yield c
    c.stop()


def _experiment_config(tmp_path, searcher=None, extra=None):
    config = {
        "name": "e2e-fixture",
        "entrypoint": "python3 train.py",
        "searcher": searcher
        or {
            "name": "single",
            "metric": "val_loss",
            "max_length": {"batches": 8},
        },
        "hyperparameters": {"lr": 0.5},
        "checkpoint_storage": {
            "type": "shared_fs",
            "host_path": os.path.join(str(tmp_path), "checkpoints"),
        },
        "resources": {"slots_per_trial": 1},
        "max_restarts": 1,
    }
    config.update(extra or {})
    return config


def _create_experiment(cluster, config, activate=True):
    import determined_tpu.cli as cli

    token = cluster.login()
    model_def = cli._tar_context(FIXTURES)
    resp = cluster.api(
        "POST", "/api/v1/experiments",
        {"config": config, "model_definition": model_def, "activate": activate},
        token=token,
    )
    return resp["id"], token


def _wait_experiment(cluster, eid, token, timeout=120.0, want=("COMPLETED",)):
    deadline = time.time() + timeout
    state = None
    while time.time() < deadline:
        state = cluster.api("GET", f"/api/v1/experiments/{eid}", token=token)[
            "experiment"]["state"]
        if state in ("COMPLETED", "CANCELED", "ERROR"):
            assert state in want, f"experiment finished {state}, wanted {want}"
            return state
        time.sleep(0.5)
    raise TimeoutError(f"experiment {eid} stuck in {state}")


# ---------------------------------------------------------------------------


def test_devcluster_boots_from_config_files(tmp_path, native_binaries):
    """Master AND agent boot from JSON config files alone (viper-style
    file+env+flags layering, reference cmd/determined-master/init.go:13 and
    agent/internal/options/options.go) and run an experiment end to end."""
    port = _free_port()
    db_path = os.path.join(str(tmp_path), "m.db")
    master_cfg = {"host": "127.0.0.1", "port": port, "db_path": db_path,
                  "cluster_name": "from-config", "agent_timeout_s": 15}
    agent_cfg = {"master_url": f"http://127.0.0.1:{port}", "id": "cfg-agent",
                 "addr": "127.0.0.1", "slots": 2, "slot_type": "cpu",
                 "work_root": os.path.join(str(tmp_path), "work"),
                 "token_file": db_path + ".agent_token"}
    mp = os.path.join(str(tmp_path), "master.json")
    ap = os.path.join(str(tmp_path), "agent.json")
    with open(mp, "w") as f:
        json.dump(master_cfg, f)
    with open(ap, "w") as f:
        json.dump(agent_cfg, f)

    env = dict(os.environ,
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
               JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    master = subprocess.Popen(
        [os.path.join(native_binaries, "determined-master"), "--config", mp],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    agent = None
    try:
        _wait_http(f"http://127.0.0.1:{port}/api/v1/master")
        agent = subprocess.Popen(
            [os.path.join(native_binaries, "determined-agent"),
             "--config", ap],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

        c = Devcluster.__new__(Devcluster)
        c.master_url = f"http://127.0.0.1:{port}"
        info = c.api("GET", "/api/v1/master")
        assert info["cluster_name"] == "from-config"
        token = c.login()
        deadline = time.time() + 20
        while time.time() < deadline:
            agents = c.api("GET", "/api/v1/agents", token=token)["agents"]
            if any(a["id"] == "cfg-agent" and a["alive"] for a in agents):
                break
            time.sleep(0.2)
        else:
            raise TimeoutError("config-file agent did not register")

        import determined_tpu.cli as cli
        model_def = cli._tar_context(FIXTURES)
        eid = c.api("POST", "/api/v1/experiments",
                    {"config": _experiment_config(tmp_path),
                     "model_definition": model_def, "activate": True},
                    token=token)["id"]
        _wait_experiment(c, eid, token)
    finally:
        for proc in (agent, master):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait()


def test_master_info_and_agent_registration(cluster):
    info = cluster.api("GET", "/api/v1/master")
    assert info["cluster_name"] == "determined-tpu"
    token = cluster.login()
    # Every route except master-info/login now requires a session token.
    try:
        cluster.api("GET", "/api/v1/agents")
        raise AssertionError("unauthenticated /agents should 401")
    except urllib.error.HTTPError as e:
        assert e.code == 401
    agents = cluster.api("GET", "/api/v1/agents", token=token)["agents"]
    assert len(agents) == 1
    assert len(agents[0]["slots"]) == 2


def test_single_experiment_end_to_end(cluster, tmp_path):
    eid, token = _create_experiment(cluster, _experiment_config(tmp_path))
    _wait_experiment(cluster, eid, token)

    trials = cluster.api("GET", f"/api/v1/experiments/{eid}/trials", token=token)[
        "trials"]
    assert len(trials) == 1
    trial = trials[0]
    assert trial["state"] == "COMPLETED"
    assert trial["total_batches"] >= 8

    metrics = cluster.api(
        "GET", f"/api/v1/trials/{trial['id']}/metrics?group=training", token=token
    )["metrics"]
    assert metrics, "training metrics should be reported"
    val = cluster.api(
        "GET", f"/api/v1/trials/{trial['id']}/metrics?group=validation", token=token
    )["metrics"]
    assert val and "val_loss" in val[-1]["metrics"]

    cps = cluster.api(
        "GET", f"/api/v1/experiments/{eid}/checkpoints", token=token
    )["checkpoints"]
    assert cps, "checkpoint should be reported"
    ckpt_dir = os.path.join(str(tmp_path), "checkpoints", cps[-1]["uuid"])
    assert os.path.exists(os.path.join(ckpt_dir, "state.json"))

    logs = cluster.api(
        "GET", f"/api/v1/tasks/trial-{trial['id']}/logs?offset=0", token=token
    )["logs"]
    assert any("trial complete" in line["log"] for line in logs)


def test_metric_summary_rollups(cluster, tmp_path):
    """trials.summary_metrics (min/max/last/mean/count per metric per
    group) is maintained incrementally on report and must agree with a
    full scan of raw_metrics (reference
    static/srv/calculate-full-trial-summary-metrics.sql)."""
    eid, token = _create_experiment(cluster, _experiment_config(tmp_path))
    _wait_experiment(cluster, eid, token)
    trial = cluster.api("GET", f"/api/v1/experiments/{eid}/trials",
                        token=token)["trials"][0]
    summary = trial["summary_metrics"]
    raw = cluster.api(
        "GET", f"/api/v1/trials/{trial['id']}/metrics", token=token
    )["metrics"]
    assert summary, "rollups missing"
    for group in ("training", "validation"):
        vals = {}
        for m in raw:
            if m["group_name"] != group:
                continue
            for k, v in m["metrics"].items():
                if isinstance(v, (int, float)):
                    vals.setdefault(k, []).append(float(v))
        assert vals, f"no raw {group} metrics"
        for k, xs in vals.items():
            s = summary[group][k]
            assert s["count"] == len(xs)
            assert abs(s["min"] - min(xs)) < 1e-9
            assert abs(s["max"] - max(xs)) < 1e-9
            assert abs(s["last"] - xs[-1]) < 1e-9
            assert abs(s["mean"] - sum(xs) / len(xs)) < 1e-9


def test_asha_search_end_to_end(cluster, tmp_path):
    searcher = {
        "name": "async_halving",
        "metric": "val_loss",
        "max_length": {"batches": 8},
        "num_rungs": 2,
        "divisor": 2,
        "max_trials": 4,
        "max_concurrent_trials": 2,
    }
    config = _experiment_config(
        tmp_path, searcher=searcher,
        extra={"hyperparameters": {"lr": {"type": "log", "minval": -2,
                                          "maxval": 0}}},
    )
    eid, token = _create_experiment(cluster, config)
    _wait_experiment(cluster, eid, token, timeout=180.0)
    trials = cluster.api("GET", f"/api/v1/experiments/{eid}/trials", token=token)[
        "trials"]
    assert len(trials) == 4
    assert all(t["state"] == "COMPLETED" for t in trials)
    # rung geometry (cumulative, reference asha.go:62-66): rung0 = 8/2 = 4,
    # rung1 = 4 + 8 = 12. Everyone reaches 4; promoted trials reach 12.
    batches = sorted(t["total_batches"] for t in trials)
    assert batches[0] >= 4
    assert batches[-1] >= 12


def test_pause_resume_preempts_and_resumes_from_checkpoint(cluster, tmp_path):
    config = _experiment_config(
        tmp_path,
        searcher={"name": "single", "metric": "val_loss",
                  "max_length": {"batches": 200}},
    )
    config["environment"] = {"TRIAL_STEP_SLEEP": "0.05"}
    eid, token = _create_experiment(cluster, config)

    # Let it run a bit, then pause (→ preemption signal → checkpoint+exit).
    time.sleep(4.0)
    cluster.api("POST", f"/api/v1/experiments/{eid}/pause", token=token)
    deadline = time.time() + 60
    while time.time() < deadline:
        trials = cluster.api(
            "GET", f"/api/v1/experiments/{eid}/trials", token=token)["trials"]
        if trials and trials[0].get("latest_checkpoint"):
            break
        time.sleep(0.5)
    trials = cluster.api("GET", f"/api/v1/experiments/{eid}/trials", token=token)[
        "trials"]
    assert trials[0]["latest_checkpoint"], "pause should checkpoint the trial"

    cluster.api("POST", f"/api/v1/experiments/{eid}/activate", token=token)
    _wait_experiment(cluster, eid, token, timeout=180.0)
    logs = cluster.api(
        "GET", f"/api/v1/tasks/trial-{trials[0]['id']}/logs?offset=0",
        token=token)["logs"]
    assert any("resumed from checkpoint" in line["log"] for line in logs)


def test_agent_restart_reattaches_running_task(cluster, tmp_path):
    """Kill -9 the agent mid-trial and restart it: the task process (its
    own process group, logging to files) survives, the new agent adopts it
    from running.json, and the trial COMPLETES with restarts == 0 — a
    reattach, not a restart-from-checkpoint (reference
    containers/manager.go:76 ReattachContainers)."""
    config = _experiment_config(
        tmp_path,
        searcher={"name": "single", "metric": "val_loss",
                  "max_length": {"batches": 150}},
    )
    config["environment"] = {"TRIAL_STEP_SLEEP": "0.05"}
    eid, token = _create_experiment(cluster, config)

    # Wait until the trial is actually running and logging.
    deadline = time.time() + 60
    trial = None
    while time.time() < deadline:
        trials = cluster.api("GET", f"/api/v1/experiments/{eid}/trials",
                             token=token)["trials"]
        if trials:
            logs = cluster.api(
                "GET", f"/api/v1/tasks/trial-{trials[0]['id']}/logs?offset=0",
                token=token)["logs"]
            if logs:
                trial = trials[0]
                break
        time.sleep(0.3)
    assert trial is not None, "trial never started logging"

    cluster.agent.kill()  # SIGKILL: no cleanup, the task is orphaned
    cluster.agent.wait()
    time.sleep(1.0)
    cluster.start_agent()  # same id + work_root → reattach path

    _wait_experiment(cluster, eid, token, timeout=180.0)
    trials = cluster.api("GET", f"/api/v1/experiments/{eid}/trials",
                         token=token)["trials"]
    assert trials[0]["state"] == "COMPLETED"
    assert trials[0]["restarts"] == 0, (
        "reattach must not consume a restart: the surviving process "
        "finished the trial")
    logs = cluster.api(
        "GET", f"/api/v1/tasks/trial-{trials[0]['id']}/logs?offset=0",
        token=token)["logs"]
    assert any("trial complete" in line["log"] for line in logs)


def test_master_restart_restores_experiment(cluster, tmp_path):
    config = _experiment_config(
        tmp_path,
        searcher={"name": "single", "metric": "val_loss",
                  "max_length": {"batches": 120}},
    )
    config["environment"] = {"TRIAL_STEP_SLEEP": "0.05"}
    eid, token = _create_experiment(cluster, config)
    time.sleep(3.0)

    cluster.kill_master()
    time.sleep(1.0)
    cluster.start_master()  # same db; snapshot restore (restore.go analogue)
    token = cluster.login()

    _wait_experiment(cluster, eid, token, timeout=180.0)
    trials = cluster.api("GET", f"/api/v1/experiments/{eid}/trials", token=token)[
        "trials"]
    assert trials[0]["state"] == "COMPLETED"


def test_cancel_experiment(cluster, tmp_path):
    config = _experiment_config(
        tmp_path,
        searcher={"name": "single", "metric": "val_loss",
                  "max_length": {"batches": 10000}},
    )
    config["environment"] = {"TRIAL_STEP_SLEEP": "0.05"}
    eid, token = _create_experiment(cluster, config)
    time.sleep(3.0)
    cluster.api("POST", f"/api/v1/experiments/{eid}/cancel", token=token)
    state = _wait_experiment(cluster, eid, token, timeout=60.0,
                             want=("CANCELED", "COMPLETED"))
    assert state in ("CANCELED", "COMPLETED")


def test_sdk_workflow(cluster, tmp_path):
    """Drive the flow through the experimental SDK (reference
    determined.experimental.client)."""
    from determined_tpu.experimental import Determined

    d = Determined(cluster.master_url)
    assert d.get_master_info()["cluster_name"] == "determined-tpu"
    assert len(d.get_agents()) == 1

    exp = d.create_experiment(_experiment_config(tmp_path), FIXTURES)
    assert exp.wait(timeout=120.0) == "COMPLETED"
    trials = exp.get_trials()
    assert len(trials) == 1 and trials[0].state == "COMPLETED"
    metrics = list(trials[0].iter_metrics("validation"))
    assert metrics and "val_loss" in metrics[-1]["metrics"]

    ckpt = exp.top_checkpoint()
    assert ckpt.uuid
    local = ckpt.download(os.path.join(str(tmp_path), "dl"))
    assert os.path.exists(os.path.join(local, "state.json"))

    model = d.create_model("sdk-model")
    version = model.register_version(ckpt.uuid)
    assert version.version == 1
    assert d.get_model("sdk-model").get_versions()[0].checkpoint_uuid == ckpt.uuid


def test_command_task(cluster):
    """NTSC command task end to end (reference command/command.go)."""
    token = cluster.login()
    resp = cluster.api(
        "POST", "/api/v1/commands",
        {"config": {"entrypoint": "echo hello-from-command"}}, token=token,
    )
    task_id = resp["id"]
    deadline = time.time() + 60
    task = None
    while time.time() < deadline:
        task = cluster.api("GET", f"/api/v1/commands/{task_id}", token=token)["task"]
        if task["state"] in ("COMPLETED", "ERROR", "CANCELED"):
            break
        time.sleep(0.5)
    assert task and task["state"] == "COMPLETED", task
    logs = cluster.api(
        "GET", f"/api/v1/tasks/{task_id}/logs?offset=0", token=token)["logs"]
    assert any("hello-from-command" in line["log"] for line in logs)

    listed = cluster.api("GET", "/api/v1/commands", token=token)["commands"]
    assert any(t["id"] == task_id for t in listed)


def test_tensorboard_metrics_synced_to_storage(cluster, tmp_path):
    """Trial tfevents must land in checkpoint storage under
    tensorboard/<exp>/<trial>/ (reference tensorboard/base.py sync)."""
    eid, token = _create_experiment(cluster, _experiment_config(tmp_path))
    _wait_experiment(cluster, eid, token)
    trials = cluster.api("GET", f"/api/v1/experiments/{eid}/trials", token=token)[
        "trials"]
    tb_dir = os.path.join(str(tmp_path), "checkpoints", "tensorboard",
                          str(eid), str(trials[0]["id"]))
    assert os.path.isdir(tb_dir), f"no synced tfevents dir at {tb_dir}"
    assert any(name.startswith("events.") for name in os.listdir(tb_dir))


def test_custom_searcher(cluster, tmp_path):
    """User-defined SearchMethod driving trials through the master's
    custom-searcher event queue (reference custom_search.go +
    searcher/_remote_search_runner.py)."""
    from determined_tpu.experimental import Determined
    from determined_tpu.searcher import (
        Close, Create, RemoteSearchRunner, SearchMethod, Shutdown,
        ValidateAfter,
    )

    class TwoRoundSearch(SearchMethod):
        """2 trials; the better one trains a second round."""

        def __init__(self):
            self.results = {}
            self.closed = 0
            self.extended = None

        def initial_operations(self):
            ops = []
            for lr in (0.1, 0.9):
                create = Create({"lr": lr})
                ops += [create, ValidateAfter(create.request_id, 4)]
            return ops

        def on_validation_completed(self, request_id, metric, train_length):
            self.results[request_id] = metric
            if train_length >= 8:
                return [Close(request_id)]
            if len(self.results) < 2:
                return []
            best = min(self.results, key=self.results.get)
            if self.extended is None:
                self.extended = best
                ops = [ValidateAfter(best, 8)]
                ops += [Close(r) for r in self.results if r != best]
                return ops
            return [Close(request_id)]

        def on_trial_closed(self, request_id):
            self.closed += 1
            return [Shutdown()] if self.closed == 2 else []

        def progress(self):
            return min(1.0, self.closed / 2)

    config = _experiment_config(
        tmp_path, searcher={"name": "custom", "metric": "val_loss"})
    runner = RemoteSearchRunner(TwoRoundSearch(),
                                Determined(cluster.master_url))
    eid = runner.run(config, FIXTURES, poll_timeout=5.0)

    d = Determined(cluster.master_url)
    exp = d.get_experiment(eid)
    assert exp.state == "COMPLETED"
    trials = exp.get_trials()
    assert len(trials) == 2
    batches = sorted(t.total_batches for t in trials)
    assert batches == [4, 8]


def test_cli_workflow(cluster, tmp_path, monkeypatch, capsys):
    """Drive the same flow through the det CLI."""
    import determined_tpu.cli as cli

    monkeypatch.setattr(cli, "TOKEN_CACHE",
                        os.path.join(str(tmp_path), "tokens.json"))
    cfg_path = os.path.join(str(tmp_path), "config.json")
    with open(cfg_path, "w") as f:
        json.dump(_experiment_config(tmp_path), f)

    rc = cli.main(["-m", cluster.master_url, "experiment", "create",
                   cfg_path, FIXTURES, "--follow"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Created experiment" in out
    assert "COMPLETED" in out

    rc = cli.main(["-m", cluster.master_url, "experiment", "list"])
    assert rc == 0
    assert "e2e-fixture" in capsys.readouterr().out

    rc = cli.main(["-m", cluster.master_url, "agent", "list"])
    assert rc == 0
    assert "agent-0" in capsys.readouterr().out


def test_model_def_content_store_and_file_tree(cluster, tmp_path):
    """Content-addressed model-def store (reference master/internal/cache
    role): identical context tarballs dedupe to one blob; trials still
    fetch their context; file_tree lists the tarball's files; delete
    releases the reference."""
    cfg = _experiment_config(tmp_path)
    e1, token = _create_experiment(cluster, cfg, activate=False)
    e2, _ = _create_experiment(cluster, cfg, activate=False)

    # Same tarball → the two experiments share one blob.
    md1 = cluster.api("GET", f"/api/v1/experiments/{e1}/model_def",
                      token=token)["b64_tgz"]
    md2 = cluster.api("GET", f"/api/v1/experiments/{e2}/model_def",
                      token=token)["b64_tgz"]
    assert md1 == md2 and md1

    tree = cluster.api("GET", f"/api/v1/experiments/{e1}/file_tree",
                       token=token)["files"]
    paths = {f["path"] for f in tree}
    assert "train.py" in paths, paths
    assert all(f["size"] >= 0 for f in tree)
    # PAX/GNU metadata records must not leak as pseudo-files.
    assert not any("PaxHeader" in p for p in paths), paths

    # A run still gets its context after dedupe (activate e1, let the
    # trial extract + complete).
    cluster.api("POST", f"/api/v1/experiments/{e1}/activate", token=token)
    _wait_experiment(cluster, e1, token)

    # Cancel + delete e2: the blob must survive (e1 still references it).
    cluster.api("POST", f"/api/v1/experiments/{e2}/cancel", token=token)
    deadline = time.time() + 30
    while time.time() < deadline:
        st = cluster.api("GET", f"/api/v1/experiments/{e2}",
                         token=token)["experiment"]["state"]
        if st in ("CANCELED", "COMPLETED", "ERROR"):
            break
        time.sleep(0.2)
    cluster.api("DELETE", f"/api/v1/experiments/{e2}", token=token)
    md1_after = cluster.api("GET", f"/api/v1/experiments/{e1}/model_def",
                            token=token)["b64_tgz"]
    assert md1_after == md1


def test_preflight_gate_and_persistence(tmp_path, native_binaries):
    """The master-side preflight gate (docs/preflight.md): DTL2xx config
    rules run natively at experiment create; diagnostics persist on the
    record and surface through the API; `preflight: {gate: error}` rejects
    with 400; suppression waives the gate. Master-only cluster — nothing
    is scheduled."""
    import urllib.error

    c = Devcluster(str(tmp_path), native_binaries)
    c.start_master()
    try:
        token = c.login()

        def config(gate=None, suppress=None, gbs=30):
            cfg = {
                "name": "preflight-e2e",
                "entrypoint": "python3 train.py",
                "searcher": {"name": "single", "metric": "loss",
                             "max_length": {"batches": 8}},
                "resources": {"slots_per_trial": 8},
                "hyperparameters": {"global_batch_size": gbs},
            }
            pf = {}
            if gate:
                pf["gate"] = gate
            if suppress:
                pf["suppress"] = suppress
            if pf:
                cfg["preflight"] = pf
            return cfg

        # Default gate (warn): created, diagnostics persisted + returned.
        out = c.api("POST", "/api/v1/experiments",
                    {"config": config(), "model_definition": "",
                     "activate": False}, token=token)
        assert [d["code"] for d in out["preflight"]] == ["DTL201"]
        eid = out["id"]
        got = c.api("GET", f"/api/v1/experiments/{eid}", token=token)
        assert [d["code"] for d in got["experiment"]["preflight"]] == [
            "DTL201"]

        # gate: error -> 400 with diagnostics in the body.
        try:
            c.api("POST", "/api/v1/experiments",
                  {"config": config(gate="error"), "model_definition": "",
                   "activate": False}, token=token)
            raise AssertionError("gated create unexpectedly succeeded")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            body = json.loads(e.read().decode())
            assert [d["code"] for d in body["preflight"]] == ["DTL201"]

        # Suppressing the code waives the gate.
        out = c.api("POST", "/api/v1/experiments",
                    {"config": config(gate="error", suppress=["DTL201"]),
                     "model_definition": "", "activate": False}, token=token)
        assert out["preflight"][0]["suppressed"] is True

        # A clean config carries no diagnostics.
        out = c.api("POST", "/api/v1/experiments",
                    {"config": config(gbs=32), "model_definition": "",
                     "activate": False}, token=token)
        assert out["preflight"] == []
    finally:
        c.stop()
