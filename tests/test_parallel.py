"""Mesh + sharding-rule unit tests."""

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from determined_tpu.parallel import (
    DEFAULT_RULES,
    LogicalRules,
    MeshConfig,
    create_mesh,
    logical_to_mesh_spec,
)


class TestMeshConfig:
    def test_resolve_default_absorbs_all(self):
        cfg = MeshConfig().resolve(8)
        assert cfg.data == 8 and cfg.tensor == 1

    def test_resolve_mixed(self):
        cfg = MeshConfig(data=-1, fsdp=2, tensor=2).resolve(8)
        assert (cfg.data, cfg.fsdp, cfg.tensor) == (2, 2, 2)

    def test_resolve_indivisible_raises(self):
        with pytest.raises(ValueError):
            MeshConfig(data=-1, fsdp=3).resolve(8)

    def test_resolve_wrong_product_raises(self):
        with pytest.raises(ValueError):
            MeshConfig(data=4, fsdp=4).resolve(8)

    def test_from_dict_unknown_axis(self):
        with pytest.raises(ValueError):
            MeshConfig.from_dict({"sequence": 2})  # not a mesh axis name

    def test_from_dict_pipeline_axis(self):
        assert MeshConfig.from_dict({"pipeline": 2}).pipeline == 2


class TestCreateMesh:
    def test_axes_and_shape(self, devices):
        mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2), devices)
        assert mesh.axis_names == (
            "data", "pipeline", "fsdp", "expert", "context", "tensor")
        assert mesh.shape["data"] == 2
        assert mesh.shape["tensor"] == 2
        assert mesh.devices.size == 8

    def test_default_all_data(self, devices):
        mesh = create_mesh(devices=devices)
        assert mesh.shape["data"] == 8


class TestLogicalRules:
    def test_batch_spec_uses_data_and_fsdp(self):
        spec = logical_to_mesh_spec(("batch", "seq", "embed"))
        assert spec == P(("data", "fsdp"), "context", None)  # embed consumed? no:
        # embed maps to fsdp which is already used by batch → replicated.

    def test_param_spec(self):
        spec = logical_to_mesh_spec(("embed", "mlp"))
        assert spec == P("fsdp", "tensor")

    def test_mesh_axis_used_once(self):
        # both dims want tensor → second falls back to replication
        spec = logical_to_mesh_spec(("mlp", "vocab"))
        assert spec == P("tensor", None)

    def test_unknown_axis_raises(self):
        with pytest.raises(KeyError):
            logical_to_mesh_spec(("nonexistent",))

    def test_override(self):
        rules = LogicalRules().override(embed=None)
        assert rules.spec(("embed",)) == P(None)
