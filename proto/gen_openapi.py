#!/usr/bin/env python
"""Generate proto/openapi.json — the REST surface's schema source of truth.

Reference: proto/src/determined/api/v1/api.proto (230 gRPC RPCs) +
swagger→client codegen in bindings/. The TPU-native master speaks plain
REST/JSON, so the source of truth is an OpenAPI 3 document generated from
the terse route table below (same codegen discipline: edit the table, run
this script, commit both). Contract tests (tests/test_openapi.py) assert
the spec and the live master agree in BOTH directions — every spec path is
routed, and every path the Python clients call is in the spec.
"""

import json
import os

# (method, path, tag, summary). {x} segments are path parameters.
ROUTES = [
    ("post", "/api/v1/auth/login", "auth", "Log in; returns a bearer token"),
    ("post", "/api/v1/auth/logout", "auth", "Invalidate the current token"),
    ("get", "/api/v1/master", "master", "Cluster info (no auth required)"),
    ("post", "/api/v1/master/cleanup_logs", "master",
     "Manual task-log retention sweep (admin)"),
    ("get", "/api/v1/stream", "stream",
     "Long-poll entity-change events (since/entities/timeout_seconds)"),
    ("get", "/api/v1/me", "users", "Current user"),
    ("get", "/api/v1/users", "users", "List users"),
    ("post", "/api/v1/users", "users", "Create user (admin)"),
    ("get", "/api/v1/users/{id}", "users", "Get user"),
    ("patch", "/api/v1/users/{id}", "users",
     "Patch user: active/role/password (admin; self for password)"),
    ("get", "/api/v1/groups", "rbac", "List user groups with members"),
    ("post", "/api/v1/groups", "rbac", "Create group (admin)"),
    ("delete", "/api/v1/groups/{id}", "rbac", "Delete group (admin)"),
    ("post", "/api/v1/groups/{id}/members", "rbac", "Add member (admin)"),
    ("delete", "/api/v1/groups/{id}/members/{uid}", "rbac",
     "Remove member (admin)"),
    ("get", "/api/v1/rbac/assignments", "rbac", "List role assignments"),
    ("post", "/api/v1/rbac/assignments", "rbac",
     "Grant viewer/editor/admin to a user or group, optionally "
     "workspace-scoped"),
    ("delete", "/api/v1/rbac/assignments/{id}", "rbac", "Revoke assignment"),
    ("get", "/api/v1/agents", "agents", "List agents and slots"),
    ("post", "/api/v1/agents/register", "agents",
     "Agent registration (agent service account)"),
    ("get", "/api/v1/agents/{id}/actions", "agents",
     "Agent action long-poll (agent service account)"),
    ("post", "/api/v1/agents/{id}/heartbeat", "agents",
     "Agent heartbeat + reconcile (agent service account)"),
    ("post", "/api/v1/agents/{id}/allocations/{aid}/state", "agents",
     "Report a container state change (agent service account)"),
    ("post", "/api/v1/agents/{id}/enable", "agents", "Enable slots (admin)"),
    ("post", "/api/v1/agents/{id}/disable", "agents",
     "Drain: disable slots (admin)"),
    ("post", "/api/v1/agents/{id}/preempt_notice", "agents",
     "Infrastructure termination notice: mark the agent DRAINING and push "
     "a deadline preemption to its allocations (agent service account)"),
    ("get", "/api/v1/experiments", "experiments", "List experiments"),
    ("post", "/api/v1/experiments", "experiments",
     "Create experiment (managed, or unmanaged with unmanaged: true)"),
    ("get", "/api/v1/experiments/{id}", "experiments", "Get experiment"),
    ("delete", "/api/v1/experiments/{id}", "experiments",
     "Delete a terminal experiment"),
    ("get", "/api/v1/experiments/{id}/trials", "experiments",
     "List trials (paginated: limit/offset)"),
    ("post", "/api/v1/experiments/{id}/trials", "experiments",
     "Create a trial on an unmanaged experiment"),
    ("post", "/api/v1/experiments/{id}/complete", "experiments",
     "Close out an unmanaged experiment"),
    ("get", "/api/v1/experiments/{id}/checkpoints", "experiments",
     "List experiment checkpoints"),
    ("get", "/api/v1/experiments/{id}/model_def", "experiments",
     "Download the model definition tarball (base64)"),
    ("get", "/api/v1/experiments/{id}/file_tree", "experiments",
     "List the model definition's files (content-cached by tarball hash)"),
    ("get", "/api/v1/experiments/{id}/searcher_events", "experiments",
     "Custom-searcher event long-poll"),
    ("post", "/api/v1/experiments/{id}/searcher_operations", "experiments",
     "Submit custom-searcher operations"),
    ("post", "/api/v1/experiments/{id}/activate", "experiments", "Activate"),
    ("post", "/api/v1/experiments/{id}/pause", "experiments", "Pause"),
    ("post", "/api/v1/experiments/{id}/cancel", "experiments", "Cancel"),
    ("post", "/api/v1/experiments/{id}/kill", "experiments", "Kill"),
    ("post", "/api/v1/experiments/{id}/archive", "experiments", "Archive"),
    ("post", "/api/v1/experiments/{id}/unarchive", "experiments",
     "Unarchive"),
    ("get", "/api/v1/trials/{id}", "trials", "Get trial"),
    ("get", "/api/v1/trials/{id}/progress", "trials", "Searcher progress"),
    ("post", "/api/v1/trials/{id}/progress", "trials", "Report progress"),
    ("get", "/api/v1/trials/{id}/searcher/operation", "trials",
     "Long-poll the current searcher op (length to train to)"),
    ("post", "/api/v1/trials/{id}/searcher/completed_operation", "trials",
     "Report the searcher metric for a completed op"),
    ("get", "/api/v1/trials/{id}/metrics", "trials", "Read metrics"),
    ("post", "/api/v1/trials/{id}/metrics", "trials",
     "Report metrics (also maintains the summary rollups)"),
    ("post", "/api/v1/trials/{id}/spans", "trials",
     "Ingest lifecycle-trace spans (idempotency-keyed batch; span_id "
     "deduped)"),
    ("get", "/api/v1/trials/{id}/trace", "trials",
     "Full lifecycle trace, ordered by start time"),
    ("post", "/api/v1/trials/{id}/run_prepare", "trials",
     "RunPrepareForReporting analogue"),
    ("post", "/api/v1/trials/{id}/runner/metadata", "trials",
     "Runner heartbeat/state"),
    ("get", "/api/v1/trials/{id}/logs", "trials", "Trial log alias"),
    ("get", "/api/v1/trials/{id}/checkpoints", "trials",
     "Checkpoint lineage, newest first; ?state= filters (COMPLETED = the "
     "restore-fallback chain); paginated: limit/offset"),
    ("get", "/api/v1/allocations/{id}", "allocations", "Introspect"),
    ("get", "/api/v1/allocations/{id}/size_history", "allocations",
     "Elastic allocation-size transitions (shrink on drain, grow-back), "
     "oldest first"),
    ("get", "/api/v1/allocations/{id}/signals/preemption", "allocations",
     "Preemption long-poll; elastic resize offers ride the same signal as "
     "{resize, target_slots, deadline_seconds}"),
    ("post", "/api/v1/allocations/{id}/signals/ack_preemption",
     "allocations", "Ack preemption before checkpointing"),
    ("get", "/api/v1/allocations/{id}/rendezvous", "allocations",
     "Block until all hosts are up; returns ranked addresses"),
    ("post", "/api/v1/allocations/{id}/all_gather", "allocations",
     "REST-level allgather barrier"),
    ("post", "/api/v1/allocations/{id}/proxy_address", "allocations",
     "Register the task's proxy target (owner/agent)"),
    ("post", "/api/v1/allocations/{id}/ready", "allocations",
     "NotifyContainerRunning analogue"),
    ("post", "/api/v1/allocations/{id}/exit_reason", "allocations",
     "Task names the cause of its imminent nonzero exit (step watchdog, "
     "divergence fail-stop)"),
    ("post", "/api/v1/allocations/{id}/serve_stats", "serving",
     "Serving-replica heartbeat: queue depth + occupancy + drain state + "
     "token-latency histograms (the router's least-loaded signal, the "
     "autoscaler's input, the deployment p50/p99 source)"),
    ("post", "/api/v1/allocations/{id}/request_spans", "serving",
     "Serving request-span batch from a replica "
     "(serve.request/queue_wait/prefill/decode; trace id = X-Request-Id)"),
    ("post", "/api/v1/checkpoints", "checkpoints", "Report checkpoint"),
    ("patch", "/api/v1/checkpoints", "checkpoints",
     "Batch state updates (GC)"),
    ("get", "/api/v1/checkpoints/{uuid}", "checkpoints", "Get checkpoint"),
    ("post", "/api/v1/task/logs", "logs",
     "Batched task-log shipping (agent / task owner)"),
    ("get", "/api/v1/tasks", "tasks",
     "List all tasks (trials/NTSC/generic/GC), optional ?type=; "
     "paginated: limit/offset"),
    ("get", "/api/v1/tasks/{id}", "tasks", "Get task"),
    ("get", "/api/v1/tasks/{id}/context", "tasks",
     "Model-def tarball for the task"),
    ("get", "/api/v1/tasks/{id}/logs", "tasks",
     "Task logs (offset/follow/timeout_seconds; limit caps the batch)"),
    ("get", "/api/v1/runs", "runs", "Flat runs view over trials"),
    ("post", "/api/v1/runs/move", "runs", "Move runs' experiments"),
    ("get", "/api/v1/job-queues", "jobs", "Queue introspection"),
    ("post", "/api/v1/job-queues/reorder", "jobs",
     "Reorder ahead-of/behind (admin)"),
    ("get", "/api/v1/workspaces", "workspaces", "List"),
    ("post", "/api/v1/workspaces", "workspaces", "Create"),
    ("get", "/api/v1/workspaces/{id}", "workspaces", "Get"),
    ("delete", "/api/v1/workspaces/{id}", "workspaces", "Archive"),
    ("get", "/api/v1/workspaces/{id}/projects", "workspaces",
     "List projects"),
    ("post", "/api/v1/projects", "projects", "Create"),
    ("get", "/api/v1/projects/{id}", "projects", "Get"),
    ("delete", "/api/v1/projects/{id}", "projects", "Archive"),
    ("get", "/api/v1/models", "models", "List models"),
    ("post", "/api/v1/models", "models", "Create model"),
    ("get", "/api/v1/models/{name}", "models", "Get model"),
    ("delete", "/api/v1/models/{name}", "models", "Archive model"),
    ("get", "/api/v1/models/{name}/versions", "models", "List versions"),
    ("post", "/api/v1/models/{name}/versions", "models",
     "Register a COMMITTED checkpoint as the next immutable version "
     "(pins it against GC; docs/serving.md 'Model lifecycle')"),
    ("get", "/api/v1/models/{name}/versions/{v}", "models",
     "Get one version (checkpoint uuid + train provenance) — the "
     "resolution target of `det serve update <dep> <name>:<v>`"),
    ("get", "/api/v1/templates", "templates", "List"),
    ("post", "/api/v1/templates", "templates", "Create/replace"),
    ("get", "/api/v1/templates/{name}", "templates", "Get"),
    ("delete", "/api/v1/templates/{name}", "templates", "Delete"),
    ("get", "/api/v1/webhooks", "webhooks", "List"),
    ("post", "/api/v1/webhooks", "webhooks", "Create (admin)"),
    ("delete", "/api/v1/webhooks/{id}", "webhooks", "Delete (admin)"),
    ("get", "/api/v1/openapi", "master", "This document"),
]

# NTSC task kinds share one route shape.
for kind in ("commands", "notebooks", "shells", "tensorboards",
             "generic-tasks"):
    ROUTES += [
        ("get", f"/api/v1/{kind}", "ntsc", f"List {kind}"),
        ("post", f"/api/v1/{kind}", "ntsc",
         f"Launch a {kind[:-1]} task (config.entrypoint/resources/"
         "environment/idle_timeout_s)"),
        ("get", f"/api/v1/{kind}/{{id}}", "ntsc", "Get task"),
        ("post", f"/api/v1/{kind}/{{id}}/kill", "ntsc",
         "Kill (propagates down the task tree)"),
    ]

# Serving (`det serve`, docs/serving.md): same task-shaped lifecycle, its
# own tag — replicas are rescheduled on drain rather than finished.
ROUTES += [
    ("get", "/api/v1/serving", "serving",
     "List serving tasks (allocation state, proxy address, restarts)"),
    ("post", "/api/v1/serving", "serving",
     "Launch a serve replica (config.serving/resources/checkpoint_storage)"),
    ("get", "/api/v1/serving/{id}", "serving", "Get serving task"),
    ("post", "/api/v1/serving/{id}/kill", "serving",
     "Kill the serving task (no respawn)"),
    # Deployments (docs/serving.md "Deployments & autoscaling"): replica
    # sets kept at target by the reconciler, routed via /serve/{id}/...,
    # autoscaled within [min, max] from the replica heartbeat signal.
    ("get", "/api/v1/deployments", "serving",
     "List deployments (replica counts, target, smoothed load)"),
    ("post", "/api/v1/deployments", "serving",
     "Create a deployment from a serving config with serving.replicas"),
    ("get", "/api/v1/deployments/{id}", "serving",
     "Get deployment detail incl. per-replica health/breaker state, "
     "aggregated TTFT/TPOT/e2e/queue-wait p50/p99, and the slow-request "
     "ring (serving.slo_ms)"),
    ("get", "/api/v1/deployments/{id}/requests/{rid}/trace", "serving",
     "One served request's span tree (router dispatch + replica "
     "queue-wait/prefill/decode), ordered by start time — rendered by "
     "`det serve trace <deployment> <request-id>`"),
    ("post", "/api/v1/deployments/{id}/scale", "serving",
     "Manually set target replicas within [min, max]"),
    ("post", "/api/v1/deployments/{id}/update", "serving",
     "Rolling blue-green weight swap to {model[:version]} or "
     "{checkpoint}: spawn-at-new before drain-at-old, one replica at a "
     "time, zero dropped (docs/serving.md 'Model lifecycle')"),
    ("post", "/api/v1/deployments/{id}/canary", "serving",
     "Start ({model|checkpoint, fraction, replicas?}), promote "
     "({promote: true}) or abort ({abort: true}) a canary traffic "
     "split with per-version latency aggregation"),
    ("post", "/api/v1/deployments/{id}/kill", "serving",
     "Kill the deployment and every replica (hard stop; scale to min "
     "first for a graceful teardown)"),
    # Compile farm (docs/compile-farm.md): the AOT artifact store over the
    # content-addressed blobs + the background compile-job queue.
    ("get", "/api/v1/compile_cache/{signature}", "compile",
     "Fetch a signature's precompiled artifacts (?name= filters; agents "
     "pre-warm from this before a container starts)"),
    ("post", "/api/v1/compile_cache/{signature}", "compile",
     "Store artifacts {files: {name: b64}} for a signature (marks its "
     "compile job DONE; idempotent per filename)"),
    ("get", "/api/v1/compile_jobs", "compile",
     "List AOT compile jobs (?state=&fingerprint=&experiment_id=)"),
    ("post", "/api/v1/compile_jobs/{signature}", "compile",
     "Worker/agent result report {state: DONE|FAILED, fingerprint, "
     "compile_ms, error}"),
    ("post", "/api/v1/compile_jobs/{signature}/link", "compile",
     "Share another signature's artifacts ({from}) after a fingerprint "
     "match — executable sharing without recompiling"),
    # Chaos/debug surface (docs/chaos.md): admin-gated fault injection.
    ("get", "/api/v1/debug/faults", "debug",
     "List compiled-in fault points and the currently armed set"),
    ("post", "/api/v1/debug/faults", "debug",
     "Arm ({point, mode, count?, probability?} or {spec}) or disarm "
     "({point, mode: off}; no point = disarm all) fault points at "
     "runtime"),
]


# Paginated list endpoints: limit/offset with sane caps — the master
# answers 400 on abuse instead of letting a hostile caller force a
# full-table scan (docs/cluster-ops.md "Overload, quotas & fair use").
PAGINATED = {
    ("get", "/api/v1/experiments"),
    ("get", "/api/v1/experiments/{id}/trials"),
    ("get", "/api/v1/experiments/{id}/checkpoints"),
    ("get", "/api/v1/trials/{id}/checkpoints"),
    ("get", "/api/v1/tasks"),
}


def build() -> dict:
    paths: dict = {}
    for method, path, tag, summary in ROUTES:
        params = [
            {"name": seg[1:-1], "in": "path", "required": True,
             "schema": {"type": "string"}}
            for seg in path.split("/") if seg.startswith("{")
        ]
        op = {
            "tags": [tag],
            "summary": summary,
            "responses": {"200": {"description": "OK"}},
        }
        if (method, path) in PAGINATED:
            params += [
                {"name": "limit", "in": "query", "required": False,
                 "schema": {"type": "integer", "minimum": 1,
                            "maximum": 1000, "default": 200}},
                {"name": "offset", "in": "query", "required": False,
                 "schema": {"type": "integer", "minimum": 0, "default": 0}},
            ]
            op["responses"]["400"] = {
                "description": "limit/offset out of range"}
        if (method, path) == ("get", "/api/v1/tasks/{id}/logs"):
            params.append(
                {"name": "limit", "in": "query", "required": False,
                 "schema": {"type": "integer", "minimum": 1,
                            "maximum": 5000, "default": 1000}})
            op["responses"]["400"] = {"description": "limit out of range"}
        # Overload contract: admission control and brownout shedding sit
        # in front of routing, so every non-debug operation can answer
        # 429 (over fair-share rate limit, or write queue at capacity)
        # or 503 (brownout shed / failed write) with a Retry-After the
        # client should honor before retrying.
        if not path.startswith("/api/v1/debug/"):
            op["responses"]["429"] = {
                "description": "Rate limited or write backpressure; "
                               "retry after Retry-After seconds"}
            op["responses"]["503"] = {
                "description": "Brownout shed (interactive reads only) "
                               "or write failure; honor Retry-After"}
        if params:
            op["parameters"] = params
        if path not in ("/api/v1/auth/login", "/api/v1/master"):
            op["security"] = [{"bearerAuth": []}]
        paths.setdefault(path, {})[method] = op
    return {
        "openapi": "3.0.3",
        "info": {
            "title": "determined-tpu master API",
            "version": "0.1.0",
            "description": (
                "REST surface of the TPU-native master. Long-poll endpoints "
                "(stream, searcher ops, preemption, rendezvous, agent "
                "actions, log follow) take timeout_seconds. /proxy/{task}/ "
                "additionally serves HTTP, websocket, and det-tcp tunnels "
                "outside this JSON surface."
            ),
        },
        "components": {
            "securitySchemes": {
                "bearerAuth": {"type": "http", "scheme": "bearer"}
            }
        },
        "paths": dict(sorted(paths.items())),
    }


if __name__ == "__main__":
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "openapi.json")
    with open(out, "w") as f:
        json.dump(build(), f, indent=1, sort_keys=False)
        f.write("\n")
    print(f"wrote {out} ({len(ROUTES)} operations)")
