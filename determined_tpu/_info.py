"""Container/task-side view of the cluster: the DET_* env contract.

TPU-native analogue of the reference's ClusterInfo
(harness/determined/_info.py:162, get_cluster_info :394) and the env-var
contract in SURVEY.md Appendix B. A task process launched by the agent reads
everything it needs about master/trial/allocation identity from environment
variables plus ``$DET_RUN_DIR/info/*.json`` files written at prep time.

TPU additions over the reference contract: ``DET_TPU_WORKER_ID``,
``DET_TPU_WORKER_HOSTNAMES``, ``DET_COORDINATOR_ADDR`` (for
``jax.distributed.initialize``), and ``DET_MESH_CONFIG`` (the allocation's
named mesh axes).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional


def _env(name: str, default: Optional[str] = None) -> Optional[str]:
    v = os.environ.get(name)
    return v if v not in (None, "") else default


@dataclasses.dataclass
class TrialInfo:
    trial_id: int
    experiment_id: int
    trial_seed: int
    hparams: Dict[str, Any]
    config: Dict[str, Any]
    steps_completed: int = 0
    latest_checkpoint: Optional[str] = None
    # Which run of the trial this process is (bumped on every requeue /
    # restart); stamped onto metric reports as trial_run_id so reports
    # from different runs never collide.
    run_id: int = 0

    @classmethod
    def _from_env(cls) -> Optional["TrialInfo"]:
        tid = _env("DET_TRIAL_ID")
        if tid is None:
            return None
        return cls(
            trial_id=int(tid),
            experiment_id=int(_env("DET_EXPERIMENT_ID", "0")),
            trial_seed=int(_env("DET_TRIAL_SEED", "0")),
            hparams=json.loads(_env("DET_HPARAMS", "{}")),
            config=json.loads(_env("DET_EXPERIMENT_CONFIG", "{}")),
            steps_completed=int(_env("DET_STEPS_COMPLETED", "0")),
            latest_checkpoint=_env("DET_LATEST_CHECKPOINT"),
            run_id=int(_env("DET_TRIAL_RUN_ID", "0")),
        )


@dataclasses.dataclass
class RendezvousInfo:
    """Addresses/ranks for all hosts of one allocation (reference:
    AllocationRendezvousInfo, master/internal/api_trials.go:1495)."""

    container_addrs: List[str]
    container_rank: int
    slot_ids: List[int]
    coordinator_addr: Optional[str] = None  # for jax.distributed.initialize

    @property
    def num_hosts(self) -> int:
        return len(self.container_addrs)


@dataclasses.dataclass
class ClusterInfo:
    master_url: str
    cluster_id: str = "local"
    agent_id: str = "local"
    task_id: Optional[str] = None
    task_type: str = "TRIAL"
    allocation_id: Optional[str] = None
    # Fencing epoch for this allocation run (docs/cluster-ops.md "Leases,
    # fencing & split-brain"): minted by the master when the allocation
    # was created, echoed on every state-mutating API call as
    # X-Allocation-Epoch so a superseded (zombie) run's late writes are
    # rejected with 409 instead of corrupting the successor's lineage.
    # None = launched outside a fenced allocation (CLI, unmanaged trial).
    allocation_epoch: Optional[int] = None
    session_token: Optional[str] = None
    run_dir: Optional[str] = None
    trial: Optional[TrialInfo] = None
    rendezvous: Optional[RendezvousInfo] = None
    mesh_config: Optional[Dict[str, int]] = None
    tpu_worker_id: int = 0

    @property
    def task_container_rank(self) -> int:
        return self.rendezvous.container_rank if self.rendezvous else 0

    @classmethod
    def from_env(cls) -> Optional["ClusterInfo"]:
        master = _env("DET_MASTER")
        if master is None:
            return None
        run_dir = _env("DET_RUN_DIR")
        rendezvous = None
        if run_dir and os.path.exists(os.path.join(run_dir, "info", "rendezvous.json")):
            with open(os.path.join(run_dir, "info", "rendezvous.json")) as f:
                rendezvous = RendezvousInfo(**json.load(f))
        elif _env("DET_CONTAINER_ADDRS"):
            rendezvous = RendezvousInfo(
                container_addrs=_env("DET_CONTAINER_ADDRS", "").split(","),
                container_rank=int(_env("DET_CONTAINER_RANK", "0")),
                slot_ids=[int(s) for s in _env("DET_SLOT_IDS", "0").split(",") if s],
                coordinator_addr=_env("DET_COORDINATOR_ADDR"),
            )
        mesh_cfg = _env("DET_MESH_CONFIG")
        return cls(
            master_url=master,
            cluster_id=_env("DET_CLUSTER_ID", "local"),
            agent_id=_env("DET_AGENT_ID", "local"),
            task_id=_env("DET_TASK_ID"),
            task_type=_env("DET_TASK_TYPE", "TRIAL"),
            allocation_id=_env("DET_ALLOCATION_ID"),
            allocation_epoch=(
                int(_env("DET_ALLOCATION_EPOCH", ""))
                if _env("DET_ALLOCATION_EPOCH") is not None
                else None
            ),
            session_token=_env("DET_SESSION_TOKEN"),
            run_dir=run_dir,
            trial=TrialInfo._from_env(),
            rendezvous=rendezvous,
            mesh_config=json.loads(mesh_cfg) if mesh_cfg else None,
            tpu_worker_id=int(_env("DET_TPU_WORKER_ID", "0")),
        )


_cluster_info_cache: Optional[ClusterInfo] = None
_cluster_info_loaded = False


def get_cluster_info(refresh: bool = False) -> Optional[ClusterInfo]:
    """None when running outside a determined-tpu task (local mode)."""
    global _cluster_info_cache, _cluster_info_loaded
    if refresh or not _cluster_info_loaded:
        _cluster_info_cache = ClusterInfo.from_env()
        _cluster_info_loaded = True
    return _cluster_info_cache
