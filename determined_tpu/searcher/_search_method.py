"""SearchMethod ABC + operations (reference _search_method.py)."""

from __future__ import annotations

import abc
import uuid
from typing import Any, Dict, List, Optional


class Operation:
    """Base class for searcher operations sent to the master."""

    def to_json(self) -> Dict[str, Any]:
        raise NotImplementedError


class Create(Operation):
    """Create a trial with the given hparams (reference Create op)."""

    def __init__(self, hparams: Dict[str, Any],
                 request_id: Optional[str] = None, seed: int = 0):
        self.request_id = request_id or f"custom-{uuid.uuid4().hex[:12]}"
        self.hparams = hparams
        self.seed = seed

    def to_json(self) -> Dict[str, Any]:
        return {"type": "Create", "request_id": self.request_id,
                "hparams": self.hparams, "seed": self.seed}


class ValidateAfter(Operation):
    """Train the trial to `length` cumulative units, then validate."""

    def __init__(self, request_id: str, length: int):
        self.request_id = request_id
        self.length = int(length)

    def to_json(self) -> Dict[str, Any]:
        return {"type": "ValidateAfter", "request_id": self.request_id,
                "length": self.length}


class Close(Operation):
    def __init__(self, request_id: str):
        self.request_id = request_id

    def to_json(self) -> Dict[str, Any]:
        return {"type": "Close", "request_id": self.request_id}


class Shutdown(Operation):
    def __init__(self, cancel: bool = False, failure: bool = False):
        self.cancel = cancel
        self.failure = failure

    def to_json(self) -> Dict[str, Any]:
        return {"type": "Shutdown", "cancel": self.cancel,
                "failure": self.failure}


class Progress:
    """Wrapper for progress updates (reference _search_method Progress)."""

    def __init__(self, progress: float):
        self.progress = float(progress)


class SearchMethod(abc.ABC):
    """User-defined search logic; event handlers return operations.

    State the method keeps between events must be picklable if you want to
    resume a crashed runner (reference: searcher_state checkpointing); the
    master itself snapshots the pending event queue.
    """

    @abc.abstractmethod
    def initial_operations(self) -> List[Operation]:
        ...

    @abc.abstractmethod
    def on_validation_completed(self, request_id: str, metric: float,
                                train_length: int) -> List[Operation]:
        ...

    def on_trial_closed(self, request_id: str) -> List[Operation]:
        return []

    def on_trial_exited_early(self, request_id: str,
                              reason: str) -> List[Operation]:
        return []

    def progress(self) -> float:
        return 0.0
