"""RemoteSearchRunner — drives a custom-searcher experiment.

Reference: harness/determined/searcher/_remote_search_runner.py:14. Creates
(or attaches to) an experiment whose config uses ``searcher: {name:
custom}``, then loops: long-poll the master's event queue, dispatch to the
user's SearchMethod, post the returned operations with the ack id.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

from determined_tpu.experimental import Determined
from determined_tpu.searcher._search_method import Operation, SearchMethod

logger = logging.getLogger("determined_tpu.searcher")

TERMINAL = {"COMPLETED", "CANCELED", "ERROR", "DELETED"}


class RemoteSearchRunner:
    def __init__(self, search_method: SearchMethod,
                 client: Optional[Determined] = None):
        self.search_method = search_method
        self.client = client or Determined()

    def run(
        self,
        exp_config: Dict[str, Any],
        model_dir: Optional[str] = None,
        experiment_id: Optional[int] = None,
        poll_timeout: float = 30.0,
    ) -> int:
        """Create the experiment (unless attaching) and drive it to a
        terminal state; returns the experiment id."""
        searcher_cfg = exp_config.setdefault("searcher", {})
        if searcher_cfg.get("name") != "custom":
            raise ValueError("RemoteSearchRunner needs searcher.name == 'custom'")

        if experiment_id is None:
            exp = self.client.create_experiment(exp_config, model_dir)
            experiment_id = exp.id
            logger.info("created custom-searcher experiment %s", experiment_id)
        session = self.client._session

        while True:
            resp = session.get(
                f"/api/v1/experiments/{experiment_id}/searcher_events",
                params={"timeout_seconds": poll_timeout},
                timeout=poll_timeout + 30,
            )
            if resp.get("experiment_state") in TERMINAL:
                logger.info("experiment %s reached %s", experiment_id,
                            resp["experiment_state"])
                return experiment_id
            events = resp.get("events", [])
            if not events:
                continue
            for event in events:
                ops = self._dispatch(event)
                session.post(
                    f"/api/v1/experiments/{experiment_id}/searcher_operations",
                    body={
                        "operations": [op.to_json() for op in ops],
                        "triggered_by_event_id": event["id"],
                        "progress": self.search_method.progress(),
                    },
                )

    def _dispatch(self, event: Dict[str, Any]) -> List[Operation]:
        etype = event["type"]
        data = event.get("data", {})
        if etype == "initial_operations":
            return self.search_method.initial_operations()
        if etype == "validation_completed":
            return self.search_method.on_validation_completed(
                data["request_id"], data["metric"], data["length"]
            )
        if etype == "trial_closed":
            return self.search_method.on_trial_closed(data["request_id"])
        if etype == "trial_exited_early":
            return self.search_method.on_trial_exited_early(
                data["request_id"], data.get("reason", "")
            )
        logger.warning("unknown searcher event %s", etype)
        return []
