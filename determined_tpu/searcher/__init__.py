"""Custom searcher client (reference: harness/determined/searcher/).

A user subclasses :class:`SearchMethod` (op model Create / ValidateAfter /
Close / Shutdown, reference _search_method.py:99-201) and drives a
multi-trial experiment with :class:`RemoteSearchRunner`
(_remote_search_runner.py:14) against the master's custom-searcher event
queue.
"""

from determined_tpu.searcher._search_method import (  # noqa: F401
    Close,
    Create,
    Operation,
    Progress,
    SearchMethod,
    Shutdown,
    ValidateAfter,
)
from determined_tpu.searcher._remote_search_runner import RemoteSearchRunner  # noqa: F401
