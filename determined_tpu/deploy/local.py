"""`det deploy local` — single-box cluster of native master + agent(s).

Reference: deploy/local/cluster_utils.py (docker-based fixture_up/down);
here the native binaries run as supervised host processes with state in
``~/.config/determined_tpu/local-cluster.json``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request
from typing import Any, Dict, Optional

STATE_FILE = os.path.expanduser("~/.config/determined_tpu/local-cluster.json")


def _find_bin(name: str) -> str:
    candidates = [
        os.path.join(os.path.dirname(__file__), "..", "..", "native", "bin", name),
        os.path.join(os.environ.get("DET_NATIVE_BIN", ""), name),
    ]
    for c in candidates:
        c = os.path.abspath(c)
        if os.path.isfile(c) and os.access(c, os.X_OK):
            return c
    raise FileNotFoundError(
        f"{name} not found; build it with `make -C native` or set DET_NATIVE_BIN"
    )


def _save_state(state: Dict[str, Any]) -> None:
    os.makedirs(os.path.dirname(STATE_FILE), exist_ok=True)
    with open(STATE_FILE, "w") as f:
        json.dump(state, f)


def _load_state() -> Optional[Dict[str, Any]]:
    try:
        with open(STATE_FILE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def generate_self_signed_cert(cert_path: str, key_path: str,
                              host: str = "127.0.0.1") -> None:
    """Self-signed TLS bootstrap (reference `det deploy` security
    bootstrap): one openssl invocation, cert doubles as the CA bundle
    clients pin via DET_MASTER_CERT_FILE."""
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key_path, "-out", cert_path, "-days", "825",
         "-subj", f"/CN={host}",
         "-addext", f"subjectAltName=IP:{host}"
         if host.replace(".", "").isdigit() else
         f"subjectAltName=DNS:{host}"],
        check=True, capture_output=True,
    )


def cluster_up(
    port: int = 8080,
    agents: int = 1,
    slots: Optional[int] = None,
    db_path: Optional[str] = None,
    work_root: Optional[str] = None,
    wait_s: float = 20.0,
    tls: bool = False,
) -> Dict[str, Any]:
    if _load_state() is not None:
        raise RuntimeError("local cluster already running; `det deploy local down` first")
    base = os.path.expanduser("~/.local/share/determined_tpu")
    os.makedirs(base, exist_ok=True)
    db_path = db_path or os.path.join(base, "master.db")
    work_root = work_root or os.path.join(base, "agent-work")
    master_log = os.path.join(base, "master.log")

    master_cmd = [_find_bin("determined-master"), "--port", str(port),
                  "--db", db_path]
    cert_path = os.path.join(base, "master-cert.pem")
    key_path = os.path.join(base, "master-key.pem")
    if tls:
        if not (os.path.exists(cert_path) and os.path.exists(key_path)):
            generate_self_signed_cert(cert_path, key_path)
        master_cmd += ["--tls-cert", cert_path, "--tls-key", key_path]

    master = subprocess.Popen(
        master_cmd,
        stdout=open(master_log, "a"), stderr=subprocess.STDOUT,
        start_new_session=True,
    )
    scheme = "https" if tls else "http"
    url = f"{scheme}://127.0.0.1:{port}"
    ssl_ctx = None
    if tls:
        import ssl as ssl_mod

        ssl_ctx = ssl_mod.create_default_context(cafile=cert_path)
        ssl_ctx.check_hostname = False
    deadline = time.time() + wait_s
    while time.time() < deadline:
        try:
            urllib.request.urlopen(url + "/api/v1/master", timeout=2,
                                   context=ssl_ctx)
            break
        except Exception:
            time.sleep(0.3)
    else:
        master.kill()
        raise RuntimeError(f"master did not come up; see {master_log}")

    env = dict(os.environ)
    repo_root = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    agent_pids = []
    for i in range(agents):
        cmd = [
            _find_bin("determined-agent"), "--master-url", url,
            "--id", f"agent-{i}", "--addr", "127.0.0.1",
            "--work-root", work_root,
            # Agent service-account bootstrap token minted by the master.
            "--token-file", db_path + ".agent_token",
        ]
        if tls:
            cmd += ["--master-cert-file", cert_path]
            # Spawned trials reach the master through the same pinned CA.
            env["DET_MASTER_CERT_FILE"] = cert_path
        if slots is not None:
            cmd += ["--slots", str(slots), "--slot-type", "cpu"]
        agent = subprocess.Popen(
            cmd, env=env,
            stdout=open(os.path.join(base, f"agent-{i}.log"), "a"),
            stderr=subprocess.STDOUT, start_new_session=True,
        )
        agent_pids.append(agent.pid)

    state = {"master_pid": master.pid, "agent_pids": agent_pids,
             "port": port, "db_path": db_path, "logs": base,
             "tls": tls, "cert": cert_path if tls else None}
    _save_state(state)
    return state


def cluster_down(drain_timeout: float = 20.0) -> bool:
    state = _load_state()
    if state is None:
        return False
    # Task processes live in their own process groups (the agent detaches
    # them), so killing the daemons alone would orphan running trials/NTSC
    # tasks. Ask the master to kill all active work first and let the agents
    # deliver the kills.
    scheme = "https" if state.get("tls") else "http"
    url = f"{scheme}://127.0.0.1:{state['port']}"
    try:
        _kill_all_work(url, drain_timeout, cert=state.get("cert"))
    except Exception:
        pass  # master already dead — nothing to drain
    for pid in state.get("agent_pids", []) + [state.get("master_pid")]:
        if pid:
            try:
                os.killpg(os.getpgid(pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
    os.unlink(STATE_FILE)
    return True


def _kill_all_work(url: str, drain_timeout: float,
                   cert: Optional[str] = None) -> None:
    import json as jsonlib

    ssl_ctx = None
    if cert:
        import ssl as ssl_mod

        ssl_ctx = ssl_mod.create_default_context(cafile=cert)
        ssl_ctx.check_hostname = False

    def api(method: str, path: str, body: Optional[dict] = None,
            token: Optional[str] = None):
        req = urllib.request.Request(
            url + path,
            data=jsonlib.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json",
                     **({"Authorization": f"Bearer {token}"} if token else {})},
            method=method,
        )
        with urllib.request.urlopen(req, timeout=10, context=ssl_ctx) as resp:
            text = resp.read().decode()
            return jsonlib.loads(text) if text else None

    token = api("POST", "/api/v1/auth/login",
                {"username": "determined", "password": ""})["token"]
    for exp in api("GET", "/api/v1/experiments", token=token)["experiments"]:
        if exp["state"] not in ("COMPLETED", "CANCELED", "ERROR", "DELETED"):
            api("POST", f"/api/v1/experiments/{exp['id']}/kill", token=token)
    for kind in ("commands", "notebooks", "shells", "tensorboards"):
        for task in api("GET", f"/api/v1/{kind}", token=token)[kind]:
            if task.get("allocation_state") not in (None, "TERMINATED"):
                api("POST", f"/api/v1/{kind}/{task['id']}/kill", token=token)
    # Give agents a moment to deliver SIGTERM/SIGKILL to task groups.
    deadline = time.time() + drain_timeout
    while time.time() < deadline:
        jobs = api("GET", "/api/v1/job-queues", token=token)["jobs"]
        if not jobs:
            return
        time.sleep(0.5)


def cluster_status() -> Optional[Dict[str, Any]]:
    state = _load_state()
    if state is None:
        return None

    def alive(pid: int) -> bool:
        try:
            os.kill(pid, 0)
            return True
        except (ProcessLookupError, PermissionError):
            return False

    state["master_alive"] = alive(state["master_pid"])
    state["agents_alive"] = sum(1 for p in state["agent_pids"] if alive(p))
    return state
