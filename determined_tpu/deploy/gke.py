"""`det deploy gke` — GKE deployment generator for the kubernetes RM.

Reference: harness/determined/deploy/gke/ (gcloud/kubectl wrapper creating
a GPU cluster + helm install). The TPU-native variant pairs with the
master's kubernetes resource manager (native/master/rm_k8s.cc): it writes

  - cluster.sh        gcloud commands: GKE cluster + a TPU node pool
                      (ct5lp machine types for v5e) sized for the RM's
                      slots_per_pod shape
  - master.yaml       master Deployment + Service (+ PVC for the SQLite
                      db) running with `resource_manager: kubernetes`
                      against the in-cluster API via its service account
  - rbac.yaml         ServiceAccount + Role (pods CRUD in the task
                      namespace) + RoleBinding for the master
  - task-svc.yaml     the headless Service whose subdomain gives task
                      pods DNS (<pod>.<subdomain> — rm_k8s.cc sets
                      spec.hostname/subdomain to match)

The operator reviews and applies (`bash cluster.sh && kubectl apply -f .`);
no cloud credentials are touched from inside this tool.
"""

from __future__ import annotations

import os

CLUSTER_SH = """#!/bin/bash
set -ex
# GKE cluster + TPU v5e node pool for determined-tpu (review before running)
gcloud container clusters create {cluster} \\
  --project {project} --zone {zone} \\
  --num-nodes 1 --machine-type e2-standard-8 --release-channel regular

gcloud container node-pools create tpu-v5e \\
  --project {project} --zone {zone} --cluster {cluster} \\
  --machine-type {machine_type} \\
  --tpu-topology {topology} \\
  --num-nodes {num_nodes} --spot

gcloud container clusters get-credentials {cluster} \\
  --project {project} --zone {zone}
"""

RBAC_YAML = """apiVersion: v1
kind: ServiceAccount
metadata:
  name: determined-master
  namespace: {namespace}
---
apiVersion: rbac.authorization.k8s.io/v1
kind: Role
metadata:
  name: determined-master-pods
  namespace: {namespace}
rules:
  - apiGroups: [""]
    resources: ["pods"]
    verbs: ["create", "delete", "get", "list", "watch"]
---
apiVersion: rbac.authorization.k8s.io/v1
kind: RoleBinding
metadata:
  name: determined-master-pods
  namespace: {namespace}
subjects:
  - kind: ServiceAccount
    name: determined-master
    namespace: {namespace}
roleRef:
  kind: Role
  name: determined-master-pods
  apiGroup: rbac.authorization.k8s.io
"""

TASK_SVC_YAML = """# Headless service: task pods set spec.hostname + spec.subdomain to this
# name, so rank-0's DNS (<pod>.{subdomain}.{namespace}.svc) resolves for
# multi-host rendezvous (rm_k8s.cc pod_manifest).
apiVersion: v1
kind: Service
metadata:
  name: {subdomain}
  namespace: {namespace}
spec:
  clusterIP: None
  selector:
    det-managed: "true"
"""

MASTER_YAML = """apiVersion: v1
kind: PersistentVolumeClaim
metadata:
  name: determined-master-db
  namespace: {namespace}
spec:
  accessModes: ["ReadWriteOnce"]
  resources:
    requests:
      storage: 10Gi
---
apiVersion: v1
kind: ConfigMap
metadata:
  name: determined-master-config
  namespace: {namespace}
data:
  master.json: |
    {{
      "port": 8080,
      "db_path": "/var/determined/master.db",
      "cluster_name": "{cluster}",
      "resource_manager": "kubernetes",
      "advertised_url": "http://determined-master.{namespace}.svc:8080",
      "kubernetes": {{
        "api_url": "https://kubernetes.default.svc",
        "namespace": "{namespace}",
        "image": "{task_image}",
        "slots_per_pod": {slots_per_pod},
        "max_pods": {max_pods},
        "service_subdomain": "{subdomain}",
        "accelerator_type": "{accelerator}",
        "topology": "{topology}"
      }}
    }}
---
apiVersion: apps/v1
kind: Deployment
metadata:
  name: determined-master
  namespace: {namespace}
spec:
  replicas: 1
  selector:
    matchLabels: {{ app: determined-master }}
  template:
    metadata:
      labels: {{ app: determined-master }}
    spec:
      serviceAccountName: determined-master
      containers:
        - name: master
          image: {master_image}
          command: ["/opt/determined-tpu/determined-master",
                    "--config", "/etc/determined/master.json"]
          ports: [{{ containerPort: 8080 }}]
          volumeMounts:
            - name: db
              mountPath: /var/determined
            - name: config
              mountPath: /etc/determined
      volumes:
        - name: db
          persistentVolumeClaim: {{ claimName: determined-master-db }}
        - name: config
          configMap: {{ name: determined-master-config }}
---
apiVersion: v1
kind: Service
metadata:
  name: determined-master
  namespace: {namespace}
spec:
  selector: {{ app: determined-master }}
  ports:
    - port: 8080
      targetPort: 8080
"""

# v5e GKE machine shapes: chips per host → (machine type, topology).
V5E_SHAPES = {
    1: ("ct5lp-hightpu-1t", "1x1"),
    4: ("ct5lp-hightpu-4t", "2x2"),
    8: ("ct5lp-hightpu-8t", "2x4"),
}


def generate(
    target_dir: str,
    project: str,
    cluster: str = "determined-tpu",
    zone: str = "us-east5-b",
    namespace: str = "default",
    slots_per_pod: int = 4,
    num_nodes: int = 2,
    max_pods: int = 64,
    master_image: str = "determined-tpu-master:latest",
    task_image: str = "determined-tpu-task:latest",
    subdomain: str = "determined-tpu",
) -> str:
    if slots_per_pod not in V5E_SHAPES:
        raise ValueError(
            f"slots_per_pod must be one of {sorted(V5E_SHAPES)} "
            f"(v5e host shapes), got {slots_per_pod}")
    machine_type, topology = V5E_SHAPES[slots_per_pod]
    os.makedirs(target_dir, exist_ok=True)
    files = {
        "cluster.sh": CLUSTER_SH.format(
            project=project, cluster=cluster, zone=zone,
            machine_type=machine_type, topology=topology,
            num_nodes=num_nodes),
        "rbac.yaml": RBAC_YAML.format(namespace=namespace),
        "task-svc.yaml": TASK_SVC_YAML.format(
            namespace=namespace, subdomain=subdomain),
        "master.yaml": MASTER_YAML.format(
            namespace=namespace, cluster=cluster, task_image=task_image,
            master_image=master_image, slots_per_pod=slots_per_pod,
            max_pods=max_pods, subdomain=subdomain,
            accelerator="tpu-v5-lite-podslice", topology=topology),
    }
    for name, content in files.items():
        with open(os.path.join(target_dir, name), "w") as f:
            f.write(content)
    return target_dir
