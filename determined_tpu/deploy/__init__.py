"""`det deploy` — cluster deployment tooling.

Reference: harness/determined/deploy/ — `local` (docker compose of
master+db+agent, cluster_utils.py:56), `gcp` (terraform driven from python,
gcp/gcp.py:35), `aws` (CloudFormation). TPU-native differences:

- `local` runs the native master+agent binaries as host processes (no
  docker dependency; the binaries are self-contained).
- `gcp` generates terraform for **TPU-VM pod slices** (google_tpu_v2_vm)
  with the agent in each VM's startup script, instead of GPU instance
  groups. Applying it is left to the operator (`terraform apply`) so no
  cloud credentials are needed here.
"""

from determined_tpu.deploy.local import cluster_up, cluster_down, cluster_status  # noqa: F401
