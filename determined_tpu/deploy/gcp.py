"""`det deploy gcp` — terraform generator for TPU-VM clusters.

Reference: harness/determined/deploy/gcp/gcp.py:35 (terraform plan/apply
driven from python over templates in deploy/gcp/terraform/). Here the
deployment target is TPU-native: a master VM and one or more **TPU-VM pod
slices** (`google_tpu_v2_vm`), each worker host running the native agent
from its startup script. The generator writes a self-contained terraform
dir; the operator reviews and applies it (no cloud credentials are touched
from inside this tool).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict

STARTUP_SCRIPT = """#!/bin/bash
set -ex
# determined-tpu agent bootstrap (runs on every TPU-VM worker host)
mkdir -p /opt/determined-tpu
gsutil cp gs://${artifact_bucket}/determined-agent /opt/determined-tpu/
gsutil -m cp -r gs://${artifact_bucket}/determined_tpu /opt/determined-tpu/
chmod +x /opt/determined-tpu/determined-agent
export PYTHONPATH=/opt/determined-tpu:$PYTHONPATH
/opt/determined-tpu/determined-agent \\
  --master-url http://${master_addr}:8080 \\
  --id "$(hostname)" \\
  --resource-pool ${resource_pool} \\
  --addr "$(hostname -I | awk '{print $1}')" \\
  --work-root /var/determined-tpu/work &
"""


def generate(
    target_dir: str,
    project: str,
    zone: str = "us-east5-b",
    accelerator_type: str = "v5litepod-8",
    num_slices: int = 1,
    artifact_bucket: str = "my-determined-tpu-artifacts",
    resource_pool: str = "default",
) -> str:
    """Write main.tf + terraform.tfvars.json; returns the directory."""
    os.makedirs(target_dir, exist_ok=True)

    main_tf = """
terraform {
  required_providers {
    google = { source = "hashicorp/google" }
  }
}

provider "google" {
  project = var.project
  zone    = var.zone
}

variable "project" { type = string }
variable "zone" { type = string }
variable "accelerator_type" { type = string }
variable "num_slices" { type = number }
variable "artifact_bucket" { type = string }
variable "resource_pool" { type = string }

# Master control-plane VM (CPU-only; serves the REST API + scheduler).
resource "google_compute_instance" "master" {
  name         = "determined-tpu-master"
  machine_type = "n2-standard-8"
  boot_disk {
    initialize_params { image = "debian-cloud/debian-12" }
  }
  network_interface {
    network = "default"
    access_config {}
  }
  metadata_startup_script = <<-EOT
    #!/bin/bash
    set -ex
    mkdir -p /opt/determined-tpu /var/determined-tpu
    gsutil cp gs://${var.artifact_bucket}/determined-master /opt/determined-tpu/
    chmod +x /opt/determined-tpu/determined-master
    /opt/determined-tpu/determined-master --port 8080 \\
      --db /var/determined-tpu/master.db &
  EOT
}

# TPU pod slices; every worker host runs the agent and owns its local chips.
resource "google_tpu_v2_vm" "slice" {
  count            = var.num_slices
  name             = "determined-tpu-slice-${count.index}"
  zone             = var.zone
  runtime_version  = "tpu-ubuntu2204-base"
  accelerator_type = var.accelerator_type
  metadata = {
    startup-script = templatefile("${path.module}/agent-startup.sh.tftpl", {
      artifact_bucket = var.artifact_bucket
      master_addr     = google_compute_instance.master.network_interface[0].network_ip
      resource_pool   = var.resource_pool
    })
  }
}

output "master_ip" {
  value = google_compute_instance.master.network_interface[0].access_config[0].nat_ip
}
"""
    with open(os.path.join(target_dir, "main.tf"), "w") as f:
        f.write(main_tf)
    with open(os.path.join(target_dir, "agent-startup.sh.tftpl"), "w") as f:
        f.write(STARTUP_SCRIPT)
    tfvars: Dict[str, Any] = {
        "project": project,
        "zone": zone,
        "accelerator_type": accelerator_type,
        "num_slices": num_slices,
        "artifact_bucket": artifact_bucket,
        "resource_pool": resource_pool,
    }
    with open(os.path.join(target_dir, "terraform.tfvars.json"), "w") as f:
        json.dump(tfvars, f, indent=2)
    return target_dir
