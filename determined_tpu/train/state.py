"""Sharded train state.

One pytree carrying step/params/opt_state, with helpers to compute its GSPMD
shardings from the model's logical axes and to initialise it *already sharded*
(params materialise directly on their owning devices via jit out_shardings —
no host-side full copy, which matters when params exceed one chip's HBM).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from determined_tpu.parallel.sharding import LogicalRules


@struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any
    # Non-gradient mutable collections (e.g. BatchNorm running stats). None for
    # purely functional models.
    extra: Any = None

    def apply_gradients(
        self, grads: Any, tx: optax.GradientTransformation, new_extra: Any = None
    ) -> "TrainState":
        updates, new_opt_state = tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return TrainState(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            extra=self.extra if new_extra is None else new_extra,
        )


def param_specs(param_logical_axes: Any, rules: Optional[LogicalRules] = None) -> Any:
    """Pytree of PartitionSpec matching a params pytree of logical-axis tuples."""
    rules = rules or LogicalRules()
    return jax.tree_util.tree_map(
        lambda axes: rules.spec(axes),
        param_logical_axes,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def _lookup_axes(param_logical_axes: Any, path) -> Optional[tuple]:
    """Logical-axis tuple at `path` (a tree_util key path) or None."""
    node = param_logical_axes
    for key in path:
        name = getattr(key, "key", getattr(key, "idx", None))
        if isinstance(node, dict) and name in node:
            node = node[name]
        elif isinstance(node, (list, tuple)) and isinstance(name, int) \
                and not isinstance(node, tuple) and name < len(node):
            node = node[name]
        else:
            return None
    return node if isinstance(node, tuple) else None


def aligned_param_specs(
    params_shapes: Any,
    param_logical_axes: Any,
    rules: Optional[LogicalRules] = None,
) -> Any:
    """PartitionSpecs with the structure of the ACTUAL params tree.

    `param_logical_axes` is a *partial* annotation: leaves it matches (by
    key path) get their logical spec, everything else replicates. This keeps
    a trial whose annotation tree drifts from its params tree (an override
    of one but not the other) buildable — the annotation never dictates the
    params structure, it only decorates it.
    """
    rules = rules or LogicalRules()

    def spec_for(path, leaf):
        axes = _lookup_axes(param_logical_axes, path)
        return rules.spec(axes) if axes is not None else PartitionSpec()

    return jax.tree_util.tree_map_with_path(spec_for, params_shapes)


def state_specs(
    init_fn: Callable[[jax.Array], Any],
    tx: optax.GradientTransformation,
    param_logical_axes: Any,
    rules: Optional[LogicalRules] = None,
    rng: Optional[jax.Array] = None,
) -> TrainState:
    """PartitionSpecs for the full TrainState.

    Optimizer-state sharding is derived structurally: optax states are pytrees
    whose array leaves either mirror params (mu/nu → same spec) or are scalars
    (count → replicated). We eval the shapes abstractly and match leaves to
    param leaves by shape.
    """
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    def init_state(r):
        params = init_fn(r)
        return TrainState(
            step=jax.numpy.zeros((), jax.numpy.int32),
            params=params,
            opt_state=tx.init(params),
        )

    shapes = jax.eval_shape(init_state, rng)
    # Align the annotation to the ACTUAL params structure (partial
    # annotation semantics: unmatched leaves replicate) — the specs tree
    # must mirror shapes.params or out_shardings rejects the jit.
    pspecs = aligned_param_specs(shapes.params, param_logical_axes, rules)

    flat_params, _ = jax.tree_util.tree_flatten(shapes.params)
    flat_pspecs, _ = jax.tree_util.tree_flatten(pspecs, is_leaf=lambda x: isinstance(x, PartitionSpec))
    shape_to_spec = {}
    for leaf, spec in zip(flat_params, flat_pspecs):
        shape_to_spec.setdefault((leaf.shape, leaf.dtype), spec)

    def opt_spec(leaf):
        return shape_to_spec.get((leaf.shape, leaf.dtype), PartitionSpec())

    return TrainState(
        step=PartitionSpec(),
        params=pspecs,
        opt_state=jax.tree_util.tree_map(opt_spec, shapes.opt_state),
        extra=None,
    )


def abstract_train_state(
    init_fn: Callable[[jax.Array], Any],
    tx: optax.GradientTransformation,
    mesh: Mesh,
    param_logical_axes: Optional[Any] = None,
    rules: Optional[LogicalRules] = None,
    extra: Any = None,
) -> Any:
    """ShapeDtypeStruct TrainState template carrying `mesh` NamedShardings.

    The restore-by-resharding target for elastic resize
    (docs/elasticity.md): a checkpoint written under one mesh restores
    straight into the layout this template declares for the NEW mesh —
    tensorstore reshards on read — without paying a jitted random init
    the restore immediately overwrites (which is what the restart path's
    create_train_state+restore does)."""

    def init_state(r):
        params = init_fn(r)
        return TrainState(
            step=jax.numpy.zeros((), jax.numpy.int32),
            params=params,
            opt_state=tx.init(params),
            extra=extra,
        )

    shapes = jax.eval_shape(init_state, jax.random.PRNGKey(0))
    if param_logical_axes is not None:
        specs = state_specs(init_fn, tx, param_logical_axes, rules)
        specs = specs.replace(
            extra=jax.tree_util.tree_map(lambda _: PartitionSpec(), extra)
        )
    else:
        specs = jax.tree_util.tree_map(lambda _: PartitionSpec(), shapes)
    flat_shapes, treedef = jax.tree_util.tree_flatten(shapes)
    flat_specs, _ = jax.tree_util.tree_flatten(
        specs, is_leaf=lambda x: isinstance(x, PartitionSpec))
    leaves = [
        jax.ShapeDtypeStruct(s.shape, s.dtype,
                             sharding=NamedSharding(mesh, p))
        for s, p in zip(flat_shapes, flat_specs)
    ]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def create_train_state(
    init_fn: Callable[[jax.Array], Any],
    tx: optax.GradientTransformation,
    rng: jax.Array,
    mesh: Optional[Mesh] = None,
    param_logical_axes: Optional[Any] = None,
    rules: Optional[LogicalRules] = None,
    extra: Any = None,
) -> TrainState:
    """Initialise TrainState; sharded over `mesh` if given.

    `extra` is a concrete pytree of non-gradient state (replicated)."""

    def init_state(r):
        params = init_fn(r)
        return TrainState(
            step=jax.numpy.zeros((), jax.numpy.int32),
            params=params,
            opt_state=tx.init(params),
            extra=extra,
        )

    if mesh is None or param_logical_axes is None:
        return jax.jit(init_state)(rng)

    specs = state_specs(init_fn, tx, param_logical_axes, rules, rng)
    specs = specs.replace(
        extra=jax.tree_util.tree_map(lambda _: PartitionSpec(), extra)
    )
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )
    return jax.jit(init_state, out_shardings=shardings)(rng)
