"""Trainer — owns the loop (reference pytorch.Trainer.fit,
harness/determined/pytorch/_trainer.py:70 + _PyTorchTrialController.run,
_pytorch_trial.py:548).

Responsibilities: mesh bring-up, sharded state init, jitted step, searcher-op
loop, periodic validation/checkpoint/metric reporting, preemption, resume.
TPU specifics:
  - one jit compile per trial (static shapes); the op loop never retraces
  - metric device→host syncs are batched every `report_period` steps so the
    train loop stays ahead of the device (async dispatch)
  - input is prefetched to device by a background thread (determined_tpu.
    data): batches are sharded, transferred and resident on HBM before the
    step that consumes them is dispatched, so host preprocessing + H2D
    overlap the previous step's compute (opt-out via `prefetch:`)
  - checkpoints are async orbax saves off the critical path
  - on preemption: ack → save → exit 0 (scheduler restarts elsewhere)
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, Iterable, Iterator, Optional

import jax
import numpy as np

from determined_tpu import _jax_compat
from determined_tpu import core as core_mod
from determined_tpu.common import faultpoint
from determined_tpu.common import trace as trace_mod
from determined_tpu.compile.bucketing import CompileConfig, bucketed_iter
from determined_tpu.compile.runtime import FarmClient
from determined_tpu.data import DevicePrefetcher, PrefetchConfig
from determined_tpu.parallel.mesh import create_mesh
from determined_tpu.train.health import (
    DivergenceError,
    HealthConfig,
    PreemptionConfig,
)
from determined_tpu.train.state import TrainState, create_train_state
from determined_tpu.train.step import (
    batch_sharding,
    make_eval_step,
    make_train_step,
    step_input_shardings,
)
from determined_tpu.train.trial import JaxTrial
from determined_tpu.train.watchdog import StepWatchdog

_jax_compat.install()  # jax.sharding.set_mesh on jax < 0.5

logger = logging.getLogger("determined_tpu.train")


def _timed_first_call(fn, tracer, executable: str, install,
                      farm=None, compile_cfg=None, report=None,
                      extra_attrs=None):
    """Wrap a jitted step so its FIRST invocation is the compile-farm
    integration point (docs/compile-farm.md):

      1. try the signature's AOT artifact (agent-prewarmed or fetched from
         the master) — a hit deserializes a compiled executable and skips
         trace+lowering+compile entirely; a load/aval mismatch falls back
         to the jit path, so a wrong artifact can cost time but never
         correctness (XLA rejects mismatched avals before executing);
      2. land a harness.compile span with cache_hit/signature attrs and
         feed (compile_ms, cache_hit) into the next metrics flush via
         `report`;
      3. on a fresh compile, export+upload the serialized executable and
         the new persistent-cache entries in a background thread.

    The wrapper then UNINSTALLS itself via `install(...)` — steady-state
    steps dispatch the bare compiled callable, so all of this adds zero
    per-step cost (the `make bench-trace` <1% gate)."""
    farm_on = (farm is not None and farm.enabled
               and (compile_cfg is None or compile_cfg.enabled))
    if (tracer is None or not tracer.enabled) and not farm_on \
            and report is None:
        return fn

    def wrapped(*args, **kwargs):
        t0 = time.monotonic()
        t0_us = trace_mod.now_us()
        out = None
        cache_hit = False
        if farm_on:
            loaded = farm.load_executable(executable)
            if loaded is not None:
                try:
                    out = loaded(*args, **kwargs)
                    cache_hit = True
                    install(loaded)
                except Exception:
                    logger.warning(
                        "AOT executable for %s did not match this trial "
                        "(shapes/shardings drifted?); compiling fresh",
                        executable, exc_info=True)
        if out is None:
            out = fn(*args, **kwargs)
            install(fn)
        compile_ms = (time.monotonic() - t0) * 1000.0
        if tracer is not None and tracer.enabled:
            attrs = {"executable": executable, "cache_hit": cache_hit}
            if farm is not None and farm.signature:
                attrs["signature"] = farm.signature
            if extra_attrs:
                attrs.update(extra_attrs)
            tracer.emit("harness.compile", t0_us, trace_mod.now_us(), attrs)
        if report is not None:
            report(executable, compile_ms, cache_hit)
        if farm_on and not cache_hit and \
                (compile_cfg is None or compile_cfg.upload):
            farm.export_and_upload_async(fn, args, executable,
                                         compile_ms=compile_ms)
        return out

    return wrapped


def _repeat(iterable_factory) -> Iterator[Any]:
    while True:
        it = iterable_factory()
        empty = True
        for batch in it:
            empty = False
            yield batch
        if empty:
            raise RuntimeError("training data iterable is empty")


class Trainer:
    def __init__(
        self,
        trial: JaxTrial,
        core_context: Optional[core_mod.Context] = None,
        devices: Optional[list] = None,
    ):
        self.trial = trial
        self.core = core_context
        mesh_cfg = trial.mesh_config()
        if devices is None:
            devices = jax.devices()
        # Full device list, kept past mesh construction: elastic resize
        # re-resolves the mesh over a prefix of it (docs/elasticity.md).
        self._devices = list(devices)
        self.mesh = create_mesh(mesh_cfg.resolve(len(devices)), devices)
        self.rules = trial.sharding_rules()
        self.state: Optional[TrainState] = None
        self._tx = None
        self._axes = None
        self._train_step = None
        self._eval_step = None
        self._pf_cfg: Optional[PrefetchConfig] = None
        self._health_cfg: Optional[HealthConfig] = None
        self._preempt_cfg: Optional[PreemptionConfig] = None
        self._preempt_period = 0
        self._watchdog: Optional[StepWatchdog] = None
        self._rollbacks = 0
        # Compile farm (docs/compile-farm.md): artifact client for this
        # trial's signature (DET_COMPILE_SIGNATURE, master-minted) and the
        # (executable, compile_ms, cache_hit) events the first-call
        # wrappers feed into the next metrics flush.
        self._farm: Optional[FarmClient] = None
        self._compile_cfg: Optional[CompileConfig] = None
        self._compile_events: list = []
        # Resolved `optimizations.attention_impl` (auto → pallas/reference
        # by backend) — attached to the harness.compile span and the
        # compile-event metrics flush so A/B runs are attributable.
        self._attention_impl: Optional[str] = None

    # -- setup ---------------------------------------------------------

    def _ensure_core(self, max_length: Optional[int]) -> core_mod.Context:
        if self.core is None:
            self.core = core_mod.init(max_length=max_length)
        elif max_length is not None and self.core.searcher._local_max_length is None:
            self.core.searcher._local_max_length = max_length
        return self.core

    def _build(self, seed: int) -> None:
        trial = self.trial
        tx = self._tx = trial.optimizer()
        axes = self._axes = trial.param_logical_axes()
        rng = jax.random.PRNGKey(seed)

        self._check_mesh_support()
        with jax.sharding.set_mesh(self.mesh):
            self.state = create_train_state(
                trial.init_params,
                tx,
                rng,
                mesh=self.mesh if axes is not None else None,
                param_logical_axes=axes,
                rules=self.rules,
                extra=trial.init_extra(),
            )
        self._build_steps()

    def _check_mesh_support(self) -> None:
        # Config checks BEFORE state init — a misconfigured pipeline mesh
        # must fail in milliseconds, not after sharding a large model.
        trial = self.trial
        pipelined = self.mesh.shape.get("pipeline", 1) > 1
        if pipelined:
            # A pipeline axis without a pipelined loss would silently run the
            # plain scan while GSPMD gathers each layer's params every step —
            # reject it instead (VERDICT r2 weak #1).
            if not trial.supports_pipeline():
                raise ValueError(
                    f"mesh requests pipeline={self.mesh.shape['pipeline']} but "
                    f"{type(trial).__name__} does not implement "
                    "loss_pipelined(); implement it (see models/gpt2."
                    "loss_fn_pipelined) or drop the pipeline axis"
                )
            if trial.stateful:
                raise ValueError(
                    "pipeline parallelism does not support stateful trials "
                    "(non-gradient extra state crossing stage boundaries)"
                )
        expert = self.mesh.shape.get("expert", 1)
        if expert > 1 and not trial.supports_expert_parallel():
            # Same guard as pipeline: an expert axis the model doesn't
            # route over would silently replicate compute across expert
            # chips (VERDICT r3 weak #4 — the decoy-axis trap).
            raise ValueError(
                f"mesh requests expert={expert} but {type(trial).__name__} "
                "does not declare expert-parallel support; use a MoE model "
                "(ops/moe.py, gpt2.Config(num_experts=...)) and override "
                "supports_expert_parallel(), or drop the expert axis"
            )

    def _build_steps(self) -> None:
        """(Re)build the jitted train/eval steps for the CURRENT self.mesh.
        Called at _build and again after an elastic re-mesh — the steps
        close over the mesh, so a resize retraces them (once) while the
        restored state is already laid out for the new mesh."""
        trial = self.trial
        tx = self._tx
        pipelined = self.mesh.shape.get("pipeline", 1) > 1
        loss = trial.loss
        if pipelined:
            mesh = self.mesh

            def loss(params, batch, rng):  # noqa: F811 — pipelined selection
                return trial.loss_pipelined(params, batch, rng, mesh)

        tracer = self.core.tracer if self.core is not None else None
        if self._compile_cfg is None:
            self._compile_cfg = self._compile_config(self.core)
        if self._farm is None:
            session = (self.core.checkpoint._session
                       if self.core is not None else None)
            self._farm = FarmClient(session)

        def install_train(fn):
            self._train_step = fn

        def install_eval(fn):
            self._eval_step = fn

        def report(executable, compile_ms, cache_hit):
            self._compile_events.append(
                {"executable": executable, "compile_ms": compile_ms,
                 "cache_hit": cache_hit})

        from determined_tpu.ops.flash_attention import resolve_attention_impl

        opt = self._optimizations_config(self.core)
        self._attention_impl = resolve_attention_impl(
            opt.get("attention_impl"))
        span_attrs = {"attention_impl": self._attention_impl}
        # Pre-partitioned step inputs (docs/training-perf.md): declare the
        # batch argument's in_shardings; fit() hands the DevicePrefetcher
        # the same value, so arrivals already match the compiled layout.
        in_shard = (step_input_shardings(self.mesh, self.rules)
                    if opt.get("prepartition_inputs", True) else None)
        self._train_step = _timed_first_call(
            make_train_step(
                loss, tx, mesh=self.mesh, rules=self.rules,
                donate_state=trial.donate_state, stateful=trial.stateful,
                input_sharding=in_shard,
            ),
            tracer, "train_step", install_train,
            farm=self._farm, compile_cfg=self._compile_cfg, report=report,
            extra_attrs=span_attrs)
        has_eval = type(trial).evaluate is not JaxTrial.evaluate
        if pipelined and trial.supports_pipelined_eval():
            mesh = self.mesh
            self._eval_step = _timed_first_call(
                make_eval_step(
                    lambda params, batch: trial.evaluate_pipelined(
                        params, batch, mesh
                    ),
                    mesh=self.mesh, rules=self.rules, stateful=trial.stateful,
                    input_sharding=in_shard,
                ),
                tracer, "eval_step", install_eval,
                farm=self._farm, compile_cfg=self._compile_cfg,
                report=report, extra_attrs=span_attrs)
        elif has_eval:
            if pipelined:
                logger.warning(
                    "%s has no evaluate_pipelined(); validation will gather "
                    "pipeline-sharded params every eval step (slow but "
                    "correct) — implement evaluate_pipelined() to fix",
                    type(trial).__name__,
                )
            self._eval_step = _timed_first_call(
                make_eval_step(
                    trial.evaluate, mesh=self.mesh, rules=self.rules,
                    stateful=trial.stateful, input_sharding=in_shard,
                ),
                tracer, "eval_step", install_eval,
                farm=self._farm, compile_cfg=self._compile_cfg,
                report=report, extra_attrs=span_attrs)
        else:
            self._eval_step = None

    # -- the loop --------------------------------------------------------

    def _prefetch_config(self, core) -> PrefetchConfig:
        expconf = None
        if core is not None and core.info is not None and core.info.trial:
            expconf = core.info.trial.config
        return PrefetchConfig.resolve(self.trial, expconf)

    def _health_config(self, core) -> HealthConfig:
        expconf = None
        if core is not None and core.info is not None and core.info.trial:
            expconf = core.info.trial.config
        return HealthConfig.resolve(self.trial, expconf)

    def _preemption_config(self, core) -> PreemptionConfig:
        expconf = None
        if core is not None and core.info is not None and core.info.trial:
            expconf = core.info.trial.config
        return PreemptionConfig.resolve(self.trial, expconf)

    def _compile_config(self, core) -> CompileConfig:
        expconf = None
        if core is not None and core.info is not None and core.info.trial:
            expconf = core.info.trial.config
        return CompileConfig.resolve(self.trial, expconf)

    def _optimizations_config(self, core) -> Dict[str, Any]:
        """The validated `optimizations:` block ({} outside a cluster run;
        callers .get() with the documented defaults)."""
        if core is not None and core.info is not None and core.info.trial:
            block = (core.info.trial.config or {}).get("optimizations")
            if isinstance(block, dict):
                return block
        return {}

    def fit(
        self,
        max_length: Optional[int] = None,
        validation_period: int = 0,
        checkpoint_period: int = 0,
        report_period: int = 10,
        preempt_period: int = 10,
        seed: int = 0,
        profile: bool = False,
        resume_from: Optional[str] = None,
    ) -> TrainState:
        """Train through all searcher operations; returns final state.

        Lengths are in steps (batches). validation/checkpoint_period of 0 =
        only at op boundaries. `preempt_period` is the preemption-poll
        cadence in steps — independent of `report_period`, so report_period=0
        does not poll the master every step. `resume_from` overrides the
        cluster's latest-checkpoint (managed restarts pass it via
        DET_LATEST_CHECKPOINT).
        """
        core = self._ensure_core(max_length)
        seed = core.trial_seed or seed
        self._build(seed)
        assert self.state is not None

        resume_from = resume_from or core.latest_checkpoint
        if resume_from:
            self._restore(resume_from)
        if profile:
            core.profiler.set_flops_per_step(
                self.trial.flops_per_step(), n_devices=self.mesh.size
            )
            core.profiler.on()

        self._pf_cfg = self._prefetch_config(core)
        health = self._health_cfg = self._health_config(core)
        self._preempt_cfg = self._preemption_config(core)
        self._rollbacks = 0
        data_iter: Any = _repeat(self.trial.build_training_data)
        if self._compile_cfg is not None and \
                self._compile_cfg.bucket_batch_sizes:
            # Shape canonicalization (docs/compile-farm.md): pad host
            # batches to the signed bucket BEFORE sharding/transfer so the
            # jitted step only ever sees the bucketed shapes.
            data_iter = bucketed_iter(data_iter, self._compile_cfg)
        prefetcher: Optional[DevicePrefetcher] = None
        if self._pf_cfg.enabled:
            # step_input_shardings == the train step's declared batch
            # in_shardings (pre-partitioned input contract): arrivals are
            # already in the compiled layout, no resharding copy on entry.
            sharding = (step_input_shardings(self.mesh, self.rules)
                        if self._pf_cfg.shard else None)
            prefetcher = DevicePrefetcher(
                data_iter, sharding=sharding, depth=self._pf_cfg.depth,
                name="train")
            data_iter = prefetcher
        rng = jax.random.PRNGKey(seed + 1)
        step = int(jax.device_get(self.state.step))
        preempt_period = self._preempt_period = max(1, preempt_period)
        preempted = False
        last = None  # (step, device_metrics) of the newest step
        last_validated = last_checkpointed = step
        last_val: Dict[str, Any] = {}
        t_report = time.time()
        n_report = 0

        # Step watchdog (train/watchdog.py): beaten at every metrics flush
        # (a real host sync proving the device made progress); fires — stack
        # dump, exit-reason report, nonzero exit — when nothing lands within
        # health.step_timeout_sec. The timeout must cover the first step's
        # jit compile; 0 disables.
        watchdog = self._watchdog = StepWatchdog(
            health.step_timeout_sec,
            session=core.checkpoint._session,
            allocation_id=core.checkpoint._allocation_id,
        )

        def flush() -> Optional[Dict[str, Any]]:
            nonlocal last, t_report, n_report
            host = None
            if last is not None:
                host = self._flush_metrics(
                    core, last, t_report, n_report, prefetcher)
            last, t_report, n_report = None, time.time(), 0
            watchdog.beat()
            return host

        def diverged(host: Optional[Dict[str, Any]]) -> bool:
            return host is not None and float(host.get("all_finite", 1.0)) < 1.0

        def handle_divergence() -> bool:
            """Apply health.on_nan; True = state was rolled back (`step`
            has been rewound and the data stream advanced)."""
            nonlocal step, rng, last_validated, last_checkpointed
            failed_step = step
            if health.on_nan == "fail":
                raise DivergenceError(failed_step)
            if health.on_nan == "warn":
                logger.warning(
                    "divergence at step %d (non-finite loss/gradients); "
                    "health.on_nan=warn — continuing", failed_step)
                return False
            # rollback: restore the last COMPLETED checkpoint, advance the
            # data stream past the offending window, reseed the step rng.
            if self._rollbacks >= health.max_rollbacks:
                raise DivergenceError(
                    failed_step,
                    f"diverged again after {health.max_rollbacks} rollbacks")
            self._rollbacks += 1
            core.checkpoint.wait()  # commit pending: lineage must be current
            restored = self._restore_chain(core.checkpoint.lineage())
            if restored is None:
                raise DivergenceError(
                    failed_step, "health.on_nan=rollback but no COMPLETED "
                    "checkpoint exists to roll back to")
            step = int(jax.device_get(self.state.step))
            # The data iterator keeps its position (already past the batches
            # that produced the NaN); skipping rollback_window more batches
            # moves the replayed window onto fresh data, and folding the
            # rollback count into the rng changes dropout/noise on replay.
            for _ in range(health.rollback_window):
                next(data_iter)
            rng = jax.random.fold_in(rng, self._rollbacks)
            last_validated = last_checkpointed = step
            logger.warning(
                "divergence at step %d: rolled back to checkpoint %s "
                "(step %d), skipped %d batches (rollback %d/%d)",
                failed_step, restored, step, health.rollback_window,
                self._rollbacks, health.max_rollbacks)
            watchdog.beat()
            return True

        import contextlib

        self._mesh_stack = mesh_stack = contextlib.ExitStack()
        try:
            watchdog.start()
            with mesh_stack:
                mesh_stack.enter_context(jax.sharding.set_mesh(self.mesh))
                for op in core.searcher.operations():
                    while True:
                        while step < op.length and not preempted:
                            # Chaos (docs/chaos.md): a delay-mode arm here
                            # models a wedged host/collective — exactly what
                            # the watchdog exists to catch.
                            faultpoint.fire("step.hang")
                            batch = next(data_iter)
                            rng, step_rng = jax.random.split(rng)
                            self.state, metrics = self._train_step(self.state, batch, step_rng)
                            step += 1
                            n_report += 1
                            last = (step, metrics)

                            if report_period and step % report_period == 0:
                                host = flush()
                                core.profiler.set_step(step)
                                if diverged(host) and handle_divergence():
                                    continue  # rolled back: step rewound
                            if validation_period and step % validation_period == 0:
                                last_val = self._validate(core, step)
                                last_validated = step
                                watchdog.beat()
                                # The pass itself polls and cuts short on a
                                # drain/deadline; pick the flag up here so a
                                # long validation can't outlive the grace.
                                if core.preempt.should_preempt():
                                    preempted = True
                            if checkpoint_period and step % checkpoint_period == 0:
                                self._checkpoint(core, step)
                                last_checkpointed = step
                                watchdog.beat()
                            if step % preempt_period == 0 and core.preempt.should_preempt():
                                preempted = True

                        host = flush()
                        if diverged(host) and not preempted \
                                and handle_divergence():
                            continue  # step rewound below op.length
                        if preempted:
                            # Elastic resize (docs/elasticity.md): reshard
                            # in place and keep training instead of
                            # checkpoint-and-exit, when this process can
                            # host the target size itself.
                            target = core.preempt.resize_target()
                            if target is not None and \
                                    self._can_resize_in_process(target):
                                step, data_iter, prefetcher = \
                                    self._resize_in_process(
                                        core, target, step,
                                        last_checkpointed, data_iter,
                                        prefetcher)
                                last_checkpointed = step
                                preempted = False
                                watchdog.beat()
                                continue  # resharded: keep training
                        break

                    if preempted:
                        self._preempt_checkpoint(core, step, last_checkpointed)
                        break

                    val = last_val if last_validated == step else self._validate(core, step)
                    watchdog.beat()
                    if core.preempt.should_preempt():
                        # Preemption arrived during the boundary validation
                        # (which polls and returns early): checkpoint and
                        # exit WITHOUT reporting the op completed — the
                        # restart finishes it.
                        preempted = True
                        self._preempt_checkpoint(core, step, last_checkpointed)
                        break
                    if last_checkpointed != step:
                        self._checkpoint(core, step)
                        last_checkpointed = step
                    if not op.completed:
                        metric = (
                            self.trial.searcher_metric(val)
                            if val
                            else float(jax.device_get(self.state.step))
                        )
                        op.report_completed(metric)
        finally:
            # Preemption, op boundaries and mid-epoch iterator exceptions
            # all pass through here: the watchdog and prefetch threads must
            # be joined, not orphaned, before the process checkpoints/exits.
            watchdog.stop()
            if prefetcher is not None:
                prefetcher.close()

        core.checkpoint.wait()
        if self._farm is not None:
            # Fresh compiles export in the background; short ASHA trials
            # exit fast — give successors their artifacts before dying.
            self._farm.wait(30.0)
        if profile:
            core.profiler.off()
        return self.state

    # -- helpers ---------------------------------------------------------

    def _flush_metrics(self, core, last, t_start, n_steps,
                       prefetcher: Optional[DevicePrefetcher] = None,
                       ) -> Dict[str, Any]:
        last_step, last_metrics = last
        # One device_get for the whole metrics tree: per-key fetches would
        # pay the host round-trip once per metric instead of once per flush.
        host = {k: np.asarray(v)
                for k, v in jax.device_get(last_metrics).items()}
        dt = time.time() - t_start
        if n_steps and dt > 0:
            host["steps_per_second"] = n_steps / dt
            core.profiler.observe_steps(n_steps, dt)
        if prefetcher is not None:
            wait, h2d, depth, n = prefetcher.window_sums()
            if n:
                host["input_wait_ms"] = wait / n
                host["h2d_ms"] = h2d / n
                host["prefetch_queue_depth"] = depth / n
                core.profiler.observe_input(wait, h2d, depth, n)
        if self._compile_events:
            # First-call compile events land in the flush AFTER the compile
            # (i.e. the first one): `det trial trace` shows hit/miss via
            # the span attrs, dashboards via these two keys.
            events, self._compile_events = self._compile_events, []
            host["compile_ms"] = sum(e["compile_ms"] for e in events)
            host["compile_cache_hit"] = (
                1.0 if all(e["cache_hit"] for e in events) else 0.0)
            if self._attention_impl is not None:
                # Rides the same once-per-compile flush as compile_ms so
                # A/B dashboards can attribute the run's kernel choice.
                host["attention_impl"] = self._attention_impl
        # The divergence sentinel's event channel: a non-finite step marks
        # this flush's report so dashboards/webhooks see `divergence: 1`
        # exactly where the loss went bad (train/health.py).
        if float(host.get("all_finite", 1.0)) < 1.0:
            host["divergence"] = 1.0
        core.train.report_training_metrics(last_step, host)
        # Span batches ride the metric-flush cadence (buffer appends are
        # the only tracing cost on the step path; the POST happens here).
        core.tracer.flush()
        return host

    def _validate(self, core, step: int) -> Dict[str, Any]:
        if self._eval_step is None:
            return {}
        with core.tracer.span("harness.validate", step=step):
            return self._validate_inner(core, step)

    def _validate_inner(self, core, step: int) -> Dict[str, Any]:
        # Accumulate per-batch metrics ON DEVICE and fetch once at the end:
        # a device_get per eval batch would serialize the eval loop on the
        # host round-trip (the same DTL101 host-sync hazard the preflight
        # analyzer flags in train steps).
        sums: Dict[str, Any] = {}
        count = 0
        pf_cfg = self._pf_cfg or self._prefetch_config(core)
        data: Any = self.trial.build_validation_data()
        if self._compile_cfg is not None and \
                self._compile_cfg.bucket_batch_sizes:
            data = bucketed_iter(data, self._compile_cfg)
        prefetcher: Optional[DevicePrefetcher] = None
        if pf_cfg.enabled:
            sharding = (batch_sharding(self.mesh, self.rules)
                        if pf_cfg.shard else None)
            prefetcher = DevicePrefetcher(
                data, sharding=sharding, depth=pf_cfg.depth, name="val")
            data = prefetcher
        preempt_period = max(1, self._preempt_period)
        try:
            for batch in data:
                m = self._eval_step(self.state, batch)
                for k, v in m.items():
                    sums[k] = sums[k] + v if k in sums else v
                count += 1
                # A long validation pass must not outlive a drain deadline:
                # poll at the same cadence as the train loop and cut the
                # pass short (partial averages are still reported).
                if count % preempt_period == 0 and \
                        core.preempt.should_preempt():
                    logger.info(
                        "preemption during validation after %d batches; "
                        "cutting the pass short", count)
                    break
        finally:
            if prefetcher is not None:
                prefetcher.close()
        if count == 0:
            return {}
        sums = {k: float(np.asarray(jax.device_get(v)))
                for k, v in sums.items()}
        avg = {f"validation_{k}" if not k.startswith("validation_") else k: v / count
               for k, v in sums.items()}
        core.train.report_validation_metrics(step, avg)
        return avg

    def _checkpoint(self, core, step: int) -> None:
        core.checkpoint.save_state(self.state, step)

    def _preempt_checkpoint(self, core, step: int,
                            last_checkpointed: int) -> None:
        """Preemption exit path (docs/checkpointing.md "Emergency
        checkpoints").

        Ordinary (unbounded) preemption: save at the current step and let
        the fit() epilogue commit it. Deadline preemption (spot drain /
        maintenance): the node dies in `preemption_deadline()` seconds —
        take an out-of-band emergency checkpoint NOW and force the
        two-phase COMMIT inside the grace window, *budgeted* against the
        deadline using the last observed durable-save cost. When the
        budget can't cover a durable COMMIT, skip the save entirely: a
        clean exit restores from the previous COMPLETED checkpoint, which
        beats burning the whole grace window writing a torso."""
        deadline = core.preempt.preemption_deadline()
        resize_target = core.preempt.resize_target()
        if deadline is None:
            if last_checkpointed != step:
                self._checkpoint(core, step)
            if resize_target is not None:
                # Managed elastic resize without a drain deadline (grow
                # offer): commit now and exit clean — the master re-places
                # this allocation at target_slots, restarts untouched.
                core.checkpoint.wait()
                logger.info(
                    "resize preemption to %d slots at step %d: emergency "
                    "checkpoint committed; exiting for re-placement",
                    resize_target, step)
            else:
                logger.info("preempted at step %d; checkpoint saved", step)
            return
        cfg = self._preempt_cfg or PreemptionConfig()
        t0 = time.monotonic()
        estimate_ms = core.checkpoint.last_save_ms
        attempt = last_checkpointed != step and cfg.should_attempt_save(
            deadline, estimate_ms)
        # The emergency window on the lifecycle trace; the phase-1/phase-2
        # checkpoint spans nest under it.
        with core.tracer.span("harness.checkpoint.emergency",
                              deadline_s=deadline, attempted=attempt,
                              step=step):
            if attempt:
                self._checkpoint(core, step)
                core.checkpoint.wait()  # COMMIT must land inside the window
            else:
                if last_checkpointed != step:
                    logger.warning(
                        "preemption deadline %.1fs cannot cover a durable "
                        "save (last save %.0fms x%.1f safety + %.1fs "
                        "margin); skipping the emergency checkpoint — "
                        "restore will use the previous COMPLETED checkpoint",
                        deadline, estimate_ms or 0.0,
                        cfg.budget_safety_factor, cfg.budget_margin_sec)
                # Commit whatever periodic save is still pending — that is
                # the checkpoint the restart will land on.
                core.checkpoint.wait()
        core.tracer.flush()  # the process exits right after; don't lose it
        grace_used_ms = (time.monotonic() - t0) * 1000.0
        if resize_target is not None:
            # Managed elastic shrink on a drain: same budget math, but the
            # clean exit becomes an allocation-size transition master-side.
            logger.info(
                "resize preemption (%s) to %d slots at step %d: %s, grace "
                "used %.0fms of %.1fs; exiting for re-placement",
                core.preempt.preemption_reason() or "unknown", resize_target,
                step,
                "emergency checkpoint committed" if attempt
                else "emergency checkpoint skipped", grace_used_ms, deadline)
        else:
            logger.info(
                "deadline preemption (%s) at step %d: %s, grace used %.0fms "
                "of %.1fs",
                core.preempt.preemption_reason() or "unknown", step,
                "emergency checkpoint committed" if attempt
                else "emergency checkpoint skipped", grace_used_ms, deadline)
        core.train.report_training_metrics(step, {
            "preemption_grace_used_ms": grace_used_ms,
            "preemption_emergency_checkpoint": 1.0 if attempt else 0.0,
        })

    # -- elastic resize (docs/elasticity.md) ---------------------------

    def _can_resize_in_process(self, target: int) -> bool:
        """Whether THIS process can serve the resize by resharding in
        place. Cluster mode says no: the signal usually means this node is
        going away, so the transition is master-side — budgeted checkpoint,
        clean exit, same-allocation re-placement at the new size. Local
        mode (tests, bench, masterless runs) reshards without exiting."""
        if self.core is not None and self.core.info is not None:
            return False
        if target == self.mesh.size:
            return False  # nothing to reshard
        if target > len(self._devices):
            return False
        return self.trial.mesh_config().resolvable(target)

    def _resize_in_process(self, core, target: int, step: int,
                           last_checkpointed: int, data_iter,
                           prefetcher: Optional[DevicePrefetcher]):
        """The resize pipeline: deadline-budgeted COMPLETED checkpoint →
        re-resolve the mesh for `target` slots → restore by RESHARDING
        through the declared logical-axis PartitionSpecs → rebuild the
        input pipeline around the new batch sharding (data order
        preserved) → resume. Returns (step, data_iter, prefetcher).

        Downtime is checkpoint + reshard + one retrace — never a queue
        wait, and `restarts` is untouched."""
        with core.tracer.span("harness.resize.downtime",
                              from_slots=self.mesh.size, target=target):
            return self._resize_in_process_inner(
                core, target, step, last_checkpointed, data_iter, prefetcher)

    def _resize_in_process_inner(self, core, target: int, step: int,
                                 last_checkpointed: int, data_iter,
                                 prefetcher: Optional[DevicePrefetcher]):
        from determined_tpu.train.state import abstract_train_state

        t0 = time.monotonic()
        from_slots = self.mesh.size
        deadline = core.preempt.preemption_deadline()
        reason = core.preempt.preemption_reason() or "resize"
        cfg = self._preempt_cfg or PreemptionConfig()

        # 1) A COMPLETED checkpoint at (or as near as the budget allows to)
        # the current step, committed before any device state is dropped.
        core.checkpoint.wait()
        restore_id = None
        if last_checkpointed == step:
            restore_id = f"trial{core.checkpoint._trial_id}-step{step}"
        elif cfg.should_attempt_save(deadline, core.checkpoint.last_save_ms):
            self._checkpoint(core, step)
            core.checkpoint.wait()  # COMMIT inside the grace window
            restore_id = f"trial{core.checkpoint._trial_id}-step{step}"
        else:
            lineage = core.checkpoint.lineage()
            if not lineage:
                raise RuntimeError(
                    "resize offered but no COMPLETED checkpoint exists and "
                    "the deadline cannot cover one; cannot reshard")
            restore_id = lineage[0]
            logger.warning(
                "resize budget cannot cover a fresh save (deadline %.1fs, "
                "last save %s ms); resharding from %s instead",
                deadline or -1.0, core.checkpoint.last_save_ms, restore_id)

        # 2+3 are the reshard proper on the lifecycle trace (the restore
        # span nests under it).
        with core.tracer.span("harness.reshard", target=target):
            # 2) Re-resolve the mesh for the target size over a prefix of
            # the device list (preflight DTL204 guarantees every size in
            # [min_slots, max_slots] resolves for elastic configs).
            new_mesh = create_mesh(
                self.trial.mesh_config().resolve(target),
                self._devices[:target])
            self._mesh_stack.close()
            self.mesh = new_mesh
            self._mesh_stack.enter_context(jax.sharding.set_mesh(new_mesh))
            self._build_steps()

            # 3) Restore by resharding: the template declares the NEW
            # layout (aligned_param_specs under the new mesh); tensorstore
            # reads each device's shard directly. No jitted random init is
            # paid — the template is abstract.
            self.state = abstract_train_state(
                self.trial.init_params, self._tx, new_mesh, self._axes,
                self.rules, extra=self.trial.init_extra())
            restored = self._restore_chain([restore_id])
            if restored is None:
                raise RuntimeError(
                    f"resize to {target} slots failed: no restorable "
                    f"checkpoint in the lineage of {restore_id}")
            step = int(jax.device_get(self.state.step))

        # 4) Rebuild the input pipeline around the new batch sharding.
        # detach() preserves position: staged batches (sharded for the old
        # mesh) re-device_put onto the new one, then the untouched
        # iterator continues — global batch and data order unchanged; only
        # the per-device share moves.
        if prefetcher is not None:
            import itertools

            staged, inner = prefetcher.detach()
            stream = itertools.chain(staged, inner)
            sharding = (batch_sharding(self.mesh, self.rules)
                        if self._pf_cfg and self._pf_cfg.shard else None)
            prefetcher = DevicePrefetcher(
                stream, sharding=sharding,
                depth=self._pf_cfg.depth if self._pf_cfg else 2,
                name="train")
            data_iter = prefetcher

        # 5) Re-arm the preemption watcher: this signal is consumed.
        core.preempt.reset()
        downtime_ms = (time.monotonic() - t0) * 1000.0
        logger.info(
            "elastic resize (%s): %d -> %d slots at step %d, restored %s, "
            "downtime %.0fms (no requeue, restarts unchanged)",
            reason, from_slots, target, step, restored, downtime_ms)
        core.train.report_training_metrics(step, {
            "resize_from_slots": float(from_slots),
            "resize_target_slots": float(target),
            "resize_downtime_ms": downtime_ms,
        })
        return step, data_iter, prefetcher

    def _restore(self, storage_id: str) -> Optional[str]:
        """Restore `storage_id`, falling back through the COMPLETED lineage
        when it is missing or fails integrity verification. Returns the
        storage id actually restored, or None (fresh start — only when the
        entire lineage is exhausted)."""
        restored = self._restore_chain([storage_id])
        if restored is None:
            logger.warning(
                "no restorable checkpoint in the lineage of %s; "
                "starting fresh", storage_id)
        return restored

    def _restore_chain(self, candidates) -> Optional[str]:
        """Try each candidate in order, extending with the registry lineage
        after the first failure. Missing (FileNotFoundError) and corrupt
        (CorruptCheckpoint) checkpoints fall through to the next candidate;
        anything else is a programming error (sharding/shape mismatch, a
        bug) and re-raises — silently discarding training progress on those
        was the seed behavior this replaces."""
        assert self.state is not None
        with self.core.tracer.span(
                "harness.restore",
                requested=candidates[0] if candidates else "") as sp:
            restored = self._restore_chain_inner(candidates)
            if sp is not None:
                sp.attrs["restored"] = restored or ""
            return restored

    def _restore_chain_inner(self, candidates) -> Optional[str]:
        queue = list(candidates)
        tried = set()
        extended = not queue  # empty input: nothing to extend from
        while queue:
            sid = queue.pop(0)
            if sid in tried:
                continue
            tried.add(sid)
            try:
                self.state = self.core.checkpoint.restore_state(sid, self.state)
                logger.info(
                    "restored from checkpoint %s at step %d",
                    sid, int(jax.device_get(self.state.step)))
                return sid
            except FileNotFoundError:
                logger.warning(
                    "checkpoint %s missing; walking lineage back", sid)
            except core_mod.CorruptCheckpoint as e:
                logger.warning(
                    "checkpoint %s failed integrity verification (%s); "
                    "walking lineage back", sid, e.reason)
            if not extended:
                extended = True
                try:
                    lineage = self.core.checkpoint.lineage()
                except Exception:
                    logger.warning("lineage unavailable", exc_info=True)
                    continue
                # Fallback only walks BACKWARD: a checkpoint newer than the
                # one requested is never a substitute for it (an explicit
                # resume_from points at a specific point in training).
                limit = core_mod.state_id_step(sid)
                for cand in lineage:
                    cstep = core_mod.state_id_step(cand)
                    if limit is not None and cstep is not None \
                            and cstep > limit:
                        continue
                    queue.append(cand)
        return None
