"""JaxTrial — the class users subclass (reference PyTorchTrial,
harness/determined/pytorch/_pytorch_trial.py:1391, re-shaped functional).

A trial is a bundle of pure functions over pytrees; the Trainer owns the mesh
and the loop. Hyperparameters arrive via `self.context.hparams` exactly like
the reference's `context.get_hparam`.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, Iterable, Optional

import optax

from determined_tpu.parallel.mesh import MeshConfig
from determined_tpu.parallel.sharding import LogicalRules


class TrialContext:
    """What a trial sees of its environment (hparams, topology, per-host batch)."""

    def __init__(
        self,
        hparams: Optional[Dict[str, Any]] = None,
        core_context=None,
        global_batch_size: Optional[int] = None,
        n_devices: int = 1,
    ):
        self.hparams = dict(hparams or {})
        self.core = core_context
        self.n_devices = n_devices
        self._global_batch_size = global_batch_size or self.hparams.get(
            "global_batch_size", 32
        )

    def get_hparam(self, name: str, default: Any = None) -> Any:
        if default is None and name not in self.hparams:
            raise KeyError(f"hyperparameter {name!r} not set")
        return self.hparams.get(name, default)

    @property
    def global_batch_size(self) -> int:
        return int(self._global_batch_size)

    @property
    def optimizations(self) -> Dict[str, Any]:
        """The experiment config's `optimizations:` block (validated +
        default-filled by expconf.check): attention_impl, attention_bf16,
        overlap_allgather, prepartition_inputs. Empty dict when the trial
        runs without a core context (unit tests, bare scripts) — callers
        use .get() with the documented defaults."""
        info = getattr(self.core, "info", None)
        trial_info = getattr(info, "trial", None)
        cfg = getattr(trial_info, "config", None) or {}
        block = cfg.get("optimizations") if isinstance(cfg, dict) else None
        return dict(block) if isinstance(block, dict) else {}

    @property
    def per_device_batch_size(self) -> int:
        return max(1, self.global_batch_size // max(1, self.n_devices))


class JaxTrial(abc.ABC):
    """Subclass and implement the pure pieces; Trainer does the rest."""

    # Trials that keep non-gradient state (BatchNorm stats) set this and use
    # the stateful loss signature (see train.step.make_train_step).
    stateful = False

    # Donate the TrainState to the jitted step so XLA reuses its buffers for
    # the new state (params + optimizer state exist once in HBM, not twice).
    # Set False only if the host must keep reading the pre-step state; the
    # preflight analyzer flags that as DTL001 (docs/preflight.md).
    donate_state = True

    # Async input pipeline (determined_tpu.data): None inherits the
    # experiment config's `prefetch:` block (default: on, depth 2). Set
    # False to opt out (batches feed the step synchronously), or a dict
    # like {"depth": 4} / {"shard": False} to tune it. Loaders must yield
    # HOST (numpy) batches — the pipeline owns the device transfer; a
    # loader that device_puts itself double-transfers (preflight DTL105).
    prefetch = None

    def __init__(self, context: TrialContext):
        self.context = context

    # -- model ---------------------------------------------------------

    @abc.abstractmethod
    def init_params(self, rng) -> Any:
        """Build the initial parameter pytree (called under jit)."""

    @abc.abstractmethod
    def loss(self, params, batch, rng):
        """Stateless: (params, batch, rng) -> loss | (loss, metrics).
        Stateful: (params, extra, batch, rng) -> (loss, metrics, new_extra)."""

    def loss_pipelined(self, params, batch, rng, mesh):
        """Pipeline-parallel loss, used by the Trainer whenever the mesh has
        `pipeline > 1`. Implementations run the model's layer stack through
        `parallel.pipeline.pipeline_apply` over `mesh` (see
        models/gpt2.loss_fn_pipelined). Trials that do not implement this
        cannot run with a pipeline axis — the Trainer rejects the mesh
        loudly instead of silently degrading to a gathered non-pipelined
        step."""
        raise NotImplementedError

    def supports_pipeline(self) -> bool:
        return type(self).loss_pipelined is not JaxTrial.loss_pipelined

    def supports_expert_parallel(self) -> bool:
        """Trials whose model routes tokens over the mesh `expert` axis
        (a MoE block — ops/moe.py) override this to return True. Meshes
        requesting `expert > 1` are rejected for trials that don't — a
        decoy expert axis would silently replicate compute across those
        chips (same guard pattern as pipeline above)."""
        return False

    def init_extra(self) -> Any:
        """Initial non-gradient state (stateful trials only)."""
        return None

    def optimizer(self) -> optax.GradientTransformation:
        lr = self.context.hparams.get("learning_rate", 1e-3)
        return optax.adamw(lr)

    def param_logical_axes(self) -> Optional[Any]:
        """Pytree of logical-axis tuples for GSPMD layout; None → replicate."""
        return None

    def sharding_rules(self) -> LogicalRules:
        return LogicalRules()

    def mesh_config(self) -> MeshConfig:
        """Default: pure data parallel over the allocation's chips."""
        mc = self.context.hparams.get("mesh")
        return MeshConfig.from_dict(mc) if mc else MeshConfig()

    # -- data ----------------------------------------------------------

    @abc.abstractmethod
    def build_training_data(self) -> Iterable[Any]:
        """Iterable of global batches (numpy/jax pytrees). Restarts when
        exhausted; infinite iterators are idiomatic for TPU."""

    def build_validation_data(self) -> Iterable[Any]:
        return ()

    # -- evaluation ----------------------------------------------------

    def evaluate(self, params, batch) -> Dict[str, Any]:
        """Per-batch validation metrics; averaged over batches by the Trainer.
        Stateful trials receive (params, extra, batch)."""
        raise NotImplementedError(
            "implement evaluate() or leave build_validation_data() empty"
        )

    def evaluate_pipelined(self, params, batch, mesh) -> Dict[str, Any]:
        """Pipeline-parallel evaluate, selected by the Trainer when
        mesh.pipeline > 1 (mirrors loss/loss_pipelined). Without it, the
        plain evaluate() runs under the pipeline mesh — correct but slow
        (GSPMD gathers each stage's params every eval); the Trainer warns."""
        raise NotImplementedError

    def supports_pipelined_eval(self) -> bool:
        return (
            type(self).evaluate_pipelined is not JaxTrial.evaluate_pipelined
        )

    # -- knobs ----------------------------------------------------------

    def flops_per_step(self) -> Optional[float]:
        """Model FLOPs per global optimizer step (fwd+bwd). When provided,
        the profiler reports a `device_flops_util` series — achieved FLOPs
        over the chips' bf16 peak (the TPU utilization measure SURVEY §5
        asks the profiler pipeline for)."""
        return None

    def searcher_metric(self, val_metrics: Dict[str, Any]) -> float:
        """Scalar the HP searcher optimises; default: validation loss."""
        for k in ("validation_loss", "loss"):
            if k in val_metrics:
                return float(val_metrics[k])
        return float(next(iter(val_metrics.values())))
