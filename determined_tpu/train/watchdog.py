"""Step watchdog — detects a trial that is alive but stuck.

A hung collective, a wedged host thread, or a deadlocked data loader leaves
the trial process running forever: the scheduler sees a healthy task, the
master sees heartbeats, and nothing restarts it. The watchdog closes that
gap: the Trainer beats it at every metrics flush (a flush is a real host
sync — the device has provably produced new step results), and a monitor
thread fires when no beat lands within ``health.step_timeout_sec``.

On fire it is deliberately LOUD, then fatal:

  1. every thread's stack is dumped via :mod:`faulthandler` to stderr (the
     task log) — the one artifact that makes a hang debuggable post-mortem;
  2. live device / allocation state is logged (device list, live array
     count + bytes) — distinguishes "device wedged" from "host wedged";
  3. a distinct exit reason is posted to the master
     (``POST /api/v1/allocations/{id}/exit_reason``) so the WebUI says
     "step watchdog" instead of a bare exit code;
  4. the process exits with :data:`WATCHDOG_EXIT_CODE` (nonzero), handing
     recovery to the existing ``max_restarts`` + agent-reclaim machinery —
     which now restarts from a checkpoint that integrity verification
     guarantees is good.

Chaos: the ``step.hang`` fault point in the Trainer's hot loop
(``DET_FAULTS=step.hang:delay-30000``) simulates the wedge deterministically
(docs/chaos.md).
"""

from __future__ import annotations

import faulthandler
import logging
import os
import sys
import threading
import time
from typing import Callable, Optional

logger = logging.getLogger("determined_tpu.train")

# Distinct from 137 (SIGKILL / chaos crash) and ordinary tracebacks (1):
# greppable in task logs and agent exit reports.
WATCHDOG_EXIT_CODE = 87

EXIT_REASON = "step watchdog: no training progress within timeout"


def _dump_device_state(stream) -> None:
    """Best-effort live device/allocation snapshot for the task log."""
    try:
        import jax

        devices = jax.devices()
        print(f"watchdog: devices: {[str(d) for d in devices]}",
              file=stream, flush=True)
        arrs = jax.live_arrays()
        total = sum(getattr(a, "nbytes", 0) for a in arrs)
        print(f"watchdog: {len(arrs)} live arrays, "
              f"{total / (1 << 20):.1f} MiB on device", file=stream,
              flush=True)
    except Exception as e:  # the process is already doomed — never mask why
        print(f"watchdog: device state unavailable: {e}", file=stream,
              flush=True)


class StepWatchdog:
    """Monitor thread armed with a per-flush heartbeat.

    `timeout_sec` <= 0 disables the watchdog entirely (start() is a no-op).
    Tests inject `exit_fn` / `stream` to observe the firing without dying.
    """

    def __init__(
        self,
        timeout_sec: float,
        session=None,
        allocation_id: Optional[str] = None,
        exit_fn: Callable[[int], None] = os._exit,
        stream=None,
    ):
        self.timeout_sec = float(timeout_sec)
        self._session = session
        self._allocation_id = allocation_id
        self._exit_fn = exit_fn
        self._stream = stream if stream is not None else sys.stderr
        self._beat = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fired = False

    @property
    def enabled(self) -> bool:
        return self.timeout_sec > 0

    def beat(self) -> None:
        """Record progress. Called from the Trainer at every metrics flush
        (and after compile/restore/validation — any long legitimate gap)."""
        self._beat = time.monotonic()

    def start(self) -> "StepWatchdog":
        if not self.enabled or self._thread is not None:
            return self
        self.beat()
        self._thread = threading.Thread(
            target=self._run, name="det-step-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "StepWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- internals -----------------------------------------------------

    def _run(self) -> None:
        # Poll at a fraction of the timeout: cheap, and a beat always has
        # a full window before the next check can fire.
        interval = max(0.05, min(self.timeout_sec / 4.0, 10.0))
        while not self._stop.wait(interval):
            idle = time.monotonic() - self._beat
            if idle >= self.timeout_sec:
                self._fire(idle)
                return

    def _fire(self, idle: float) -> None:
        self.fired = True
        print(
            f"watchdog: no training progress for {idle:.1f}s "
            f"(step_timeout_sec={self.timeout_sec:.1f}) — dumping all "
            "thread stacks and exiting for a scheduler restart",
            file=self._stream, flush=True)
        try:
            faulthandler.dump_traceback(file=self._stream, all_threads=True)
        except Exception as e:
            print(f"watchdog: stack dump failed: {e}", file=self._stream,
                  flush=True)
        _dump_device_state(self._stream)
        self._report_exit_reason()
        self._exit_fn(WATCHDOG_EXIT_CODE)

    def _report_exit_reason(self) -> None:
        if self._session is None or not self._allocation_id:
            return
        try:
            self._session.post(
                f"/api/v1/allocations/{self._allocation_id}/exit_reason",
                body={"reason": EXIT_REASON,
                      "exit_code": WATCHDOG_EXIT_CODE})
        except Exception as e:
            print(f"watchdog: exit-reason report failed: {e}",
                  file=self._stream, flush=True)
