"""Jitted train/eval step factories.

The hot loop. One `jit` per trial covering forward+backward+optimizer update;
batch sharded over (data, fsdp) on entry; all cross-device communication is
GSPMD-inserted XLA collectives (psum for grads over data axes,
reduce-scatter/all-gather for fsdp params) riding ICI — the TPU-native
replacement for DDP allreduce / ZeRO (reference:
harness/determined/pytorch/_pytorch_context.py:297 wrap_model → DDP).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from determined_tpu.parallel.sharding import LogicalRules
from determined_tpu.train.state import TrainState

# loss_fn(params, batch, rng) -> scalar loss OR (loss, aux_metrics)
LossFn = Callable[..., Any]


def _call_loss(loss_fn: LossFn, params, batch, rng) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    out = loss_fn(params, batch, rng)
    if isinstance(out, tuple):
        loss, aux = out
    else:
        loss, aux = out, {}
    return loss, aux


def make_train_step(
    loss_fn: LossFn,
    tx: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    rules: Optional[LogicalRules] = None,
    donate_state: bool = True,
    stateful: bool = False,
):
    """Build `step(state, batch, rng) -> (state, metrics)`, jitted.

    Stateless (default): loss_fn(params, batch, rng) -> loss | (loss, metrics).
    Stateful (BatchNorm etc.): loss_fn(params, extra, batch, rng) ->
    (loss, metrics, new_extra); new_extra lands in state.extra.

    metrics always include `loss` and `grad_norm` (fp32 scalars, replicated).
    """
    rules = rules or LogicalRules()

    def step(state: TrainState, batch: Any, rng: jax.Array):
        batch = _constrain_batch(batch, mesh, rules)

        def lfn(params):
            if stateful:
                loss, aux, new_extra = loss_fn(params, state.extra, batch, rng)
            else:
                loss, aux = _call_loss(loss_fn, params, batch, rng)
                new_extra = None
            return loss.astype(jnp.float32), (aux, new_extra)

        (loss, (aux, new_extra)), grads = jax.value_and_grad(lfn, has_aux=True)(
            state.params
        )
        gnorm = optax.global_norm(grads)
        new_state = state.apply_gradients(grads, tx, new_extra)
        metrics = {"loss": loss, "grad_norm": gnorm, **aux}
        return new_state, metrics

    return jax.jit(step, donate_argnums=(0,) if donate_state else ())


def _constrain_batch(batch: Any, mesh: Optional[Mesh], rules: LogicalRules) -> Any:
    """Pin batch leaves to the (data, fsdp) layout along dim 0."""
    if mesh is None:
        return batch
    spec = PartitionSpec(rules.mesh_axes("batch"))

    def constrain(x):
        if getattr(x, "ndim", 0) == 0:
            return x
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(constrain, batch)


def batch_sharding(mesh: Mesh, rules: Optional[LogicalRules] = None) -> NamedSharding:
    """The sharding data loaders should device_put batches with."""
    rules = rules or LogicalRules()
    return NamedSharding(mesh, PartitionSpec(rules.mesh_axes("batch")))


def make_eval_step(
    eval_fn: Callable[..., Dict[str, jax.Array]],
    mesh: Optional[Mesh] = None,
    rules: Optional[LogicalRules] = None,
    stateful: bool = False,
):
    """Build `eval_step(state, batch) -> metrics` (per-batch sums/means).

    Stateless: eval_fn(params, batch); stateful: eval_fn(params, extra, batch).
    """
    rules = rules or LogicalRules()

    def step(state: TrainState, batch: Any):
        batch = _constrain_batch(batch, mesh, rules)
        if stateful:
            return eval_fn(state.params, state.extra, batch)
        return eval_fn(state.params, batch)

    return jax.jit(step)
