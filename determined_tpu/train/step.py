"""Jitted train/eval step factories.

The hot loop. One `jit` per trial covering forward+backward+optimizer update;
batch sharded over (data, fsdp) on entry; all cross-device communication is
GSPMD-inserted XLA collectives (psum for grads over data axes,
reduce-scatter/all-gather for fsdp params) riding ICI — the TPU-native
replacement for DDP allreduce / ZeRO (reference:
harness/determined/pytorch/_pytorch_context.py:297 wrap_model → DDP).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from determined_tpu.parallel.sharding import LogicalRules
from determined_tpu.train.state import TrainState

# loss_fn(params, batch, rng) -> scalar loss OR (loss, aux_metrics)
LossFn = Callable[..., Any]


def _call_loss(loss_fn: LossFn, params, batch, rng) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    out = loss_fn(params, batch, rng)
    if isinstance(out, tuple):
        loss, aux = out
    else:
        loss, aux = out, {}
    return loss, aux


def make_train_step(
    loss_fn: LossFn,
    tx: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    rules: Optional[LogicalRules] = None,
    donate_state: bool = True,
    stateful: bool = False,
    input_sharding: Any = None,
):
    """Build `step(state, batch, rng) -> (state, metrics)`, jitted.

    Stateless (default): loss_fn(params, batch, rng) -> loss | (loss, metrics).
    Stateful (BatchNorm etc.): loss_fn(params, extra, batch, rng) ->
    (loss, metrics, new_extra); new_extra lands in state.extra.

    `input_sharding` (a `NamedSharding` pytree prefix or per-leaf tree —
    `step_input_shardings`) is declared as the batch argument's
    in_shardings: with the DevicePrefetcher placing batches with the same
    shardings, XLA's compiled argument layout equals the arrival layout
    and no resharding copy precedes the first layer (the pre-partitioned
    input contract; asserted on compiled HLO in tests). State and rng
    shardings stay inferred from the arguments.

    metrics always include `loss` and `grad_norm` (fp32 scalars, replicated).
    """
    rules = rules or LogicalRules()

    def step(state: TrainState, batch: Any, rng: jax.Array):
        batch = _constrain_batch(batch, mesh, rules)

        def lfn(params):
            if stateful:
                loss, aux, new_extra = loss_fn(params, state.extra, batch, rng)
            else:
                loss, aux = _call_loss(loss_fn, params, batch, rng)
                new_extra = None
            return loss.astype(jnp.float32), (aux, new_extra)

        (loss, (aux, new_extra)), grads = jax.value_and_grad(lfn, has_aux=True)(
            state.params
        )
        gnorm = optax.global_norm(grads)
        new_state = state.apply_gradients(grads, tx, new_extra)
        # Divergence sentinel (train/health.py): grad_norm is already a
        # reduction over every gradient leaf (NaN/Inf anywhere propagates
        # into it), so one fused logical-and over (loss, grad_norm) covers
        # the whole step. Rides the regular metrics fetch — no extra host
        # sync, no extra collective.
        all_finite = jnp.logical_and(jnp.isfinite(loss), jnp.isfinite(gnorm))
        metrics = {"loss": loss, "grad_norm": gnorm,
                   "all_finite": all_finite.astype(jnp.float32), **aux}
        return new_state, metrics

    kwargs: Dict[str, Any] = {}
    if input_sharding is not None:
        kwargs["in_shardings"] = (None, input_sharding, None)
    return jax.jit(step, donate_argnums=(0,) if donate_state else (),
                   **kwargs)


def make_multi_step(
    loss_fn: LossFn,
    tx: optax.GradientTransformation,
    steps_per_call: int,
    mesh: Optional[Mesh] = None,
    rules: Optional[LogicalRules] = None,
    donate_state: bool = True,
    input_sharding: Any = None,
):
    """Build `multi_step(state, batches, rng) -> (state, metrics)` running
    `steps_per_call` optimizer steps inside ONE jitted call via `lax.scan`.

    TPU-first rationale: a per-step host→device dispatch costs real latency
    (hundreds of µs on a TPU-VM, far more through remote tunnels) and forces
    a host sync point. Scanning N steps per dispatch amortizes that to ~0
    and lets XLA overlap the next step's grads with the optimizer update —
    the same structure production LLM trainers use. Batches: every leaf has
    a leading [steps_per_call, ...] axis (stack loader batches). Returned
    metrics are the per-window mean of each scalar.
    """
    rules = rules or LogicalRules()

    def one_step(state: TrainState, batch: Any, rng: jax.Array):
        def lfn(params):
            loss, aux = _call_loss(loss_fn, params, batch, rng)
            return loss.astype(jnp.float32), aux

        (loss, aux), grads = jax.value_and_grad(lfn, has_aux=True)(state.params)
        gnorm = optax.global_norm(grads)
        new_state = state.apply_gradients(grads, tx, None)
        all_finite = jnp.logical_and(jnp.isfinite(loss), jnp.isfinite(gnorm))
        return new_state, {"loss": loss, "grad_norm": gnorm,
                           "all_finite": all_finite.astype(jnp.float32),
                           **aux}

    def multi_step(state: TrainState, batches: Any, rng: jax.Array):
        batches = _constrain_batch(batches, mesh, rules, leading_dims=2)

        def body(carry, inp):
            state, rng = carry
            rng, step_rng = jax.random.split(rng)
            state, metrics = one_step(state, inp, step_rng)
            return (state, rng), metrics

        (state, _), metrics = jax.lax.scan(
            body, (state, rng), batches, length=steps_per_call
        )
        return state, jax.tree_util.tree_map(lambda m: m.mean(axis=0), metrics)

    kwargs: Dict[str, Any] = {}
    if input_sharding is not None:
        kwargs["in_shardings"] = (None, input_sharding, None)
    return jax.jit(multi_step, donate_argnums=(0,) if donate_state else (),
                   **kwargs)


def _constrain_batch(batch: Any, mesh: Optional[Mesh], rules: LogicalRules,
                     leading_dims: int = 1) -> Any:
    """Pin batch leaves to the (data, fsdp) layout along the batch dim.

    leading_dims=2 means leaves carry a [steps, batch, ...] stack (multi-step
    window): the steps axis stays unsharded, batch sharding applies to dim 1.
    """
    if mesh is None:
        return batch
    batch_axes = rules.mesh_axes("batch")
    spec = (PartitionSpec(None, batch_axes) if leading_dims == 2
            else PartitionSpec(batch_axes))

    def constrain(x):
        # Branches on pytree STRUCTURE (rank), fixed per trial — not a
        # per-shape recompile hazard.
        if getattr(x, "ndim", 0) < leading_dims:  # det: noqa[DTL104]
            return x
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(constrain, batch)


def batch_sharding(mesh: Mesh, rules: Optional[LogicalRules] = None) -> NamedSharding:
    """The sharding data loaders should device_put batches with."""
    rules = rules or LogicalRules()
    return NamedSharding(mesh, PartitionSpec(rules.mesh_axes("batch")))


def step_input_shardings(
    mesh: Mesh,
    rules: Optional[LogicalRules] = None,
    batch: Any = None,
    leading_dims: int = 1,
) -> Any:
    """The jitted step's exact batch-argument `NamedSharding`s.

    One source of truth for both sides of the pre-partitioned input
    contract (`optimizations.prepartition_inputs`): the DevicePrefetcher
    device_puts batches with these shardings and make_train_step /
    make_multi_step declare the same value as `input_sharding`, so the
    compiled step finds its inputs already laid out and inserts no
    resharding copy before the first layer.

    Without `batch` the single batch-dim sharding is returned — jit and
    device_put both accept it as a pytree prefix covering every leaf.
    With an example `batch`, a matching per-leaf tree is returned
    (sub-`leading_dims`-rank leaves replicate — same rank guard as
    `_constrain_batch`). leading_dims=2 is the multi-step window layout
    ([steps, batch, ...]: steps axis unsharded).
    """
    rules = rules or LogicalRules()
    batch_axes = rules.mesh_axes("batch")
    spec = (PartitionSpec(None, batch_axes) if leading_dims == 2
            else PartitionSpec(batch_axes))
    sharded = NamedSharding(mesh, spec)
    if batch is None:
        return sharded

    def leaf(x):
        if getattr(x, "ndim", 0) < leading_dims:  # det: noqa[DTL104]
            return NamedSharding(mesh, PartitionSpec())
        return sharded

    return jax.tree_util.tree_map(leaf, batch)


def make_eval_step(
    eval_fn: Callable[..., Dict[str, jax.Array]],
    mesh: Optional[Mesh] = None,
    rules: Optional[LogicalRules] = None,
    stateful: bool = False,
    input_sharding: Any = None,
):
    """Build `eval_step(state, batch) -> metrics` (per-batch sums/means).

    Stateless: eval_fn(params, batch); stateful: eval_fn(params, extra, batch).
    """
    rules = rules or LogicalRules()

    def step(state: TrainState, batch: Any):
        batch = _constrain_batch(batch, mesh, rules)
        if stateful:
            return eval_fn(state.params, state.extra, batch)
        return eval_fn(state.params, batch)

    kwargs: Dict[str, Any] = {}
    if input_sharding is not None:
        kwargs["in_shardings"] = (None, input_sharding)
    return jax.jit(step, **kwargs)
