"""Trial/Trainer APIs — the JAX-native analogue of the reference's
PyTorchTrial + Trainer (harness/determined/pytorch/_pytorch_trial.py:1391,
_trainer.py:70), re-shaped for functional JAX: a Trial is a bundle of pure
functions (init/loss/eval + an optax optimizer); the Trainer owns the mesh,
sharded train state, jitted step, checkpointing, metric reporting, searcher
ops and preemption.
"""

from determined_tpu.train.state import TrainState, create_train_state  # noqa: F401
from determined_tpu.train.step import (  # noqa: F401
    make_eval_step,
    make_multi_step,
    make_train_step,
)
from determined_tpu.train.health import (  # noqa: F401
    DivergenceError,
    HealthConfig,
    PreemptionConfig,
)
from determined_tpu.train.trial import JaxTrial  # noqa: F401
from determined_tpu.train.trainer import Trainer  # noqa: F401
from determined_tpu.train.watchdog import StepWatchdog  # noqa: F401
