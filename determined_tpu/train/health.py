"""Trial health — divergence sentinel + watchdog configuration.

The self-healing loop's policy knobs (docs/checkpointing.md). The jitted
train step folds an all-finite reduction over (loss, grad-norm) into its
metrics — one fused logical-and on device, fetched with the regular
per-flush metrics batch, so detection costs no extra host sync. What
happens when it trips is configured here:

    health:
      on_nan: warn | rollback | fail   # default warn
      rollback_window: 8               # batches skipped past the NaN
      max_rollbacks: 3                 # rollback->fail escalation
      step_timeout_sec: 0              # step watchdog; 0 = disabled

A trial can override the experiment config with a `health` attribute
(same precedence contract as `JaxTrial.prefetch`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

ON_NAN_POLICIES = ("warn", "rollback", "fail")


class DivergenceError(RuntimeError):
    """Raised when training diverges (non-finite loss/grads) under
    `on_nan: fail`, or when `on_nan: rollback` exhausts `max_rollbacks`."""

    def __init__(self, step: int, detail: str = ""):
        msg = f"training diverged at step {step} (non-finite loss/gradients)"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)
        self.step = step


@dataclasses.dataclass
class HealthConfig:
    """Resolved `health:` knobs (trial attribute over expconf block)."""

    on_nan: str = "warn"
    rollback_window: int = 8
    max_rollbacks: int = 3
    step_timeout_sec: float = 0.0  # 0 = watchdog disabled

    @classmethod
    def from_block(cls, block: Any) -> "HealthConfig":
        if block is None:
            return cls()
        if not isinstance(block, dict):
            raise TypeError(
                f"health config must be a mapping, got {type(block).__name__}")
        on_nan = str(block.get("on_nan", "warn"))
        if on_nan not in ON_NAN_POLICIES:
            raise ValueError(
                f"health.on_nan must be one of {ON_NAN_POLICIES}, "
                f"got {on_nan!r}")
        return cls(
            on_nan=on_nan,
            rollback_window=max(0, int(block.get("rollback_window", 8))),
            max_rollbacks=max(1, int(block.get("max_rollbacks", 3))),
            step_timeout_sec=float(block.get("step_timeout_sec", 0.0)),
        )

    @classmethod
    def resolve(cls, trial: Any = None,
                expconf: Optional[Dict[str, Any]] = None) -> "HealthConfig":
        trial_attr = getattr(trial, "health", None)
        if trial_attr is not None:
            return cls.from_block(trial_attr)
        if isinstance(expconf, dict) and expconf.get("health") is not None:
            return cls.from_block(expconf.get("health"))
        return cls()


@dataclasses.dataclass
class PreemptionConfig:
    """Resolved `preemption:` knobs — the spot-survival emergency
    checkpoint and its deadline budget (docs/checkpointing.md):

        preemption:
          emergency_checkpoint: true   # save out-of-band on a deadline
          budget_safety_factor: 1.5    # estimate multiplier before skipping
          budget_margin_sec: 2.0       # reserved for clean exit + reports

    Trial attribute `preemption` overrides the expconf block (same
    precedence contract as `JaxTrial.health` / `JaxTrial.prefetch`).
    """

    emergency_checkpoint: bool = True
    budget_safety_factor: float = 1.5
    budget_margin_sec: float = 2.0

    @classmethod
    def from_block(cls, block: Any) -> "PreemptionConfig":
        if block is None:
            return cls()
        if isinstance(block, bool):
            return cls(emergency_checkpoint=block)
        if not isinstance(block, dict):
            raise TypeError(
                "preemption config must be a mapping or bool, got "
                f"{type(block).__name__}")
        return cls(
            emergency_checkpoint=bool(block.get("emergency_checkpoint", True)),
            budget_safety_factor=max(
                1.0, float(block.get("budget_safety_factor", 1.5))),
            budget_margin_sec=max(
                0.0, float(block.get("budget_margin_sec", 2.0))),
        )

    @classmethod
    def resolve(cls, trial: Any = None,
                expconf: Optional[Dict[str, Any]] = None) -> "PreemptionConfig":
        trial_attr = getattr(trial, "preemption", None)
        if trial_attr is not None:
            return cls.from_block(trial_attr)
        if isinstance(expconf, dict) and expconf.get("preemption") is not None:
            return cls.from_block(expconf.get("preemption"))
        return cls()

    def should_attempt_save(self, remaining_sec: Optional[float],
                            last_save_ms: Optional[float]) -> bool:
        """The deadline-budget decision: is an emergency checkpoint worth
        starting, or would it produce an uncommitted torso?

        `remaining_sec` is the grace left (None = unbounded — always
        save); `last_save_ms` the observed durable-save cost (None = no
        estimate yet — attempt optimistically: a blown budget still can't
        corrupt restore, the two-phase commit just leaves a PARTIAL that
        lineage fallback skips)."""
        if not self.emergency_checkpoint:
            return False
        if remaining_sec is None:
            return True
        budget_ms = (remaining_sec - self.budget_margin_sec) * 1000.0
        if budget_ms <= 0:
            return False
        if last_save_ms is None:
            return True
        return last_save_ms * self.budget_safety_factor <= budget_ms
