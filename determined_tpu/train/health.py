"""Trial health — divergence sentinel + watchdog configuration.

The self-healing loop's policy knobs (docs/checkpointing.md). The jitted
train step folds an all-finite reduction over (loss, grad-norm) into its
metrics — one fused logical-and on device, fetched with the regular
per-flush metrics batch, so detection costs no extra host sync. What
happens when it trips is configured here:

    health:
      on_nan: warn | rollback | fail   # default warn
      rollback_window: 8               # batches skipped past the NaN
      max_rollbacks: 3                 # rollback->fail escalation
      step_timeout_sec: 0              # step watchdog; 0 = disabled

A trial can override the experiment config with a `health` attribute
(same precedence contract as `JaxTrial.prefetch`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

ON_NAN_POLICIES = ("warn", "rollback", "fail")


class DivergenceError(RuntimeError):
    """Raised when training diverges (non-finite loss/grads) under
    `on_nan: fail`, or when `on_nan: rollback` exhausts `max_rollbacks`."""

    def __init__(self, step: int, detail: str = ""):
        msg = f"training diverged at step {step} (non-finite loss/gradients)"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)
        self.step = step


@dataclasses.dataclass
class HealthConfig:
    """Resolved `health:` knobs (trial attribute over expconf block)."""

    on_nan: str = "warn"
    rollback_window: int = 8
    max_rollbacks: int = 3
    step_timeout_sec: float = 0.0  # 0 = watchdog disabled

    @classmethod
    def from_block(cls, block: Any) -> "HealthConfig":
        if block is None:
            return cls()
        if not isinstance(block, dict):
            raise TypeError(
                f"health config must be a mapping, got {type(block).__name__}")
        on_nan = str(block.get("on_nan", "warn"))
        if on_nan not in ON_NAN_POLICIES:
            raise ValueError(
                f"health.on_nan must be one of {ON_NAN_POLICIES}, "
                f"got {on_nan!r}")
        return cls(
            on_nan=on_nan,
            rollback_window=max(0, int(block.get("rollback_window", 8))),
            max_rollbacks=max(1, int(block.get("max_rollbacks", 3))),
            step_timeout_sec=float(block.get("step_timeout_sec", 0.0)),
        )

    @classmethod
    def resolve(cls, trial: Any = None,
                expconf: Optional[Dict[str, Any]] = None) -> "HealthConfig":
        trial_attr = getattr(trial, "health", None)
        if trial_attr is not None:
            return cls.from_block(trial_attr)
        if isinstance(expconf, dict) and expconf.get("health") is not None:
            return cls.from_block(expconf.get("health"))
        return cls()
