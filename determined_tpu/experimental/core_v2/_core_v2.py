"""Core API v2 implementation (reference experimental/core_v2/_core_v2.py:
module-level singleton + unmanaged experiment creation _unmanaged.py)."""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from determined_tpu.common.api import Session
from determined_tpu.core._checkpoint import CheckpointContext
from determined_tpu.core._distributed import DistributedContext
from determined_tpu.core._searcher import SearcherContext
from determined_tpu.core._train import TrainContext
from determined_tpu.storage import from_config as storage_from_config


class Context:
    """An unmanaged run bound to a master-tracked experiment + trial."""

    def __init__(
        self,
        session: Session,
        experiment_id: int,
        trial_id: int,
        storage,
        distributed: Optional[DistributedContext] = None,
        max_length: Optional[int] = None,
    ):
        self.experiment_id = experiment_id
        self.trial_id = trial_id
        self._session = session
        dist = distributed or DistributedContext.local()
        self.distributed = dist
        self.train = TrainContext(session, trial_id=trial_id, distributed=dist)
        # Unmanaged runs own their training loop — the searcher context is
        # local (one op of max_length), like reference unmanaged mode.
        self.searcher = SearcherContext(
            None, trial_id=trial_id, distributed=dist,
            local_max_length=max_length,
        )
        self.checkpoint = CheckpointContext(
            session, storage, trial_id=trial_id, distributed=dist,
        )

    def close(self, state: str = "COMPLETED") -> None:
        self.checkpoint.close()
        try:
            self._session.post(
                f"/api/v1/experiments/{self.experiment_id}/complete",
                body={"state": state},
            )
        except Exception as e:
            # Swallowing this silently would leave the run COMPLETED locally
            # but RUNNING in the master forever — close() must not raise
            # (it runs in finally blocks), but the operator has to know.
            import warnings

            warnings.warn(
                f"core_v2.close: failed to report final state {state!r} for "
                f"experiment {self.experiment_id} to the master: {e}; the "
                f"run will appear RUNNING until completed manually",
                RuntimeWarning,
            )


_ctx: Optional[Context] = None


def init(
    *,
    config: Optional[Dict[str, Any]] = None,
    master: Optional[str] = None,
    user: str = "determined",
    password: str = "",
    hparams: Optional[Dict[str, Any]] = None,
    checkpoint_storage: Optional[Dict[str, Any]] = None,
    max_length: Optional[int] = None,
    distributed: Optional[DistributedContext] = None,
) -> Context:
    """Register an unmanaged experiment + trial with the master and bind the
    module-level train/checkpoint/searcher handles to it."""
    global _ctx
    config = dict(config or {})
    config.setdefault("name", "unmanaged-run")
    config.setdefault(
        "searcher",
        {"name": "single", "metric": config.get("metric", "loss"),
         "max_length": {"batches": max_length or 0}},
    )
    if hparams:
        config.setdefault("hyperparameters", hparams)
    master = master or os.environ.get("DET_MASTER", "http://127.0.0.1:8080")
    session = Session.login(master, user, password)
    exp = session.post(
        "/api/v1/experiments", body={"config": config, "unmanaged": True}
    )
    eid = exp["id"]
    trial = session.post(
        f"/api/v1/experiments/{eid}/trials", body={"hparams": hparams or {}}
    )
    storage = storage_from_config(
        checkpoint_storage or config.get("checkpoint_storage"))
    _ctx = Context(
        session, eid, trial["id"], storage,
        distributed=distributed, max_length=max_length,
    )
    return _ctx


def close(state: str = "COMPLETED") -> None:
    global _ctx
    if _ctx is not None:
        _ctx.close(state)
        _ctx = None


class _Proxy:
    """Module-level handles resolving to the active context (reference
    core_v2 module globals train/checkpoint/searcher)."""

    def __init__(self, attr: str):
        self._attr = attr

    def __getattr__(self, name: str) -> Any:
        if _ctx is None:
            raise RuntimeError("core_v2.init() has not been called")
        return getattr(getattr(_ctx, self._attr), name)


train = _Proxy("train")
checkpoint = _Proxy("checkpoint")
searcher = _Proxy("searcher")
