"""Core API v2 — "det as a library" (unmanaged experiments).

Reference: harness/determined/experimental/core_v2/_core_v2.py (singleton
init/train.report_metrics) + _unmanaged.py (creates unmanaged experiments
via the API). The training process runs ANYWHERE (laptop, bare TPU-VM, a
different scheduler); the master only tracks it: experiment + trial rows,
metrics, checkpoints. No scheduling, no entrypoint, no agent involved.

    from determined_tpu.experimental import core_v2

    core_v2.init(config={"name": "my-run"}, master="http://master:8080")
    for step in range(100):
        ...
        core_v2.train.report_training_metrics(step, {"loss": loss})
    core_v2.close()
"""

from determined_tpu.experimental.core_v2._core_v2 import (  # noqa: F401
    Context,
    close,
    checkpoint,
    init,
    searcher,
    train,
)
