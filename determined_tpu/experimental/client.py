"""`Determined` SDK client.

Reference: harness/determined/experimental/client.py (module-level singleton
+ `Determined` class) and the resource objects under
harness/determined/common/experimental/ (experiment.py, trial.py,
checkpoint.py, model.py). Thin typed wrappers over the REST API.
"""

from __future__ import annotations

import base64
import io
import os
import tarfile
import time
from typing import Any, Dict, Iterator, List, Optional

from determined_tpu import expconf
from determined_tpu.common.api import Session
from determined_tpu.common.bindings import Bindings

TERMINAL_STATES = {"COMPLETED", "CANCELED", "ERROR", "DELETED"}


class Checkpoint:
    def __init__(self, session: Session, data: Dict[str, Any]):
        self._session = session
        self._api = Bindings(session)
        self.uuid = data["uuid"]
        self.trial_id = data.get("trial_id")
        self.steps_completed = data.get("steps_completed", 0)
        self.state = data.get("state")
        self.metadata = data.get("metadata") or {}
        self.resources = data.get("resources") or {}
        self.experiment_config = data.get("experiment_config") or {}

    def download(self, path: Optional[str] = None) -> str:
        """Fetch checkpoint files locally via the storage backend recorded in
        the experiment config (reference checkpoint.py download)."""
        from determined_tpu.storage import from_config as storage_from_config

        path = path or os.path.join("checkpoints", self.uuid)
        storage = storage_from_config(self.experiment_config.get("checkpoint_storage"))
        storage.download(self.uuid, path)
        return path

    def delete(self) -> None:
        self._api.patch_checkpoints(
            body={"checkpoints": [{"uuid": self.uuid, "state": "DELETED"}]},
        )

    @classmethod
    def _get(cls, session: Session, uuid: str) -> "Checkpoint":
        return cls(session,
                   Bindings(session).get_checkpoints_uuid(uuid)["checkpoint"])


class Trial:
    def __init__(self, session: Session, data: Dict[str, Any]):
        self._session = session
        self._api = Bindings(session)
        self.id = data["id"]
        self.experiment_id = data.get("experiment_id")
        self._refresh(data)

    def _refresh(self, data: Dict[str, Any]) -> None:
        self.state = data.get("state")
        self.hparams = data.get("hparams") or {}
        self.total_batches = data.get("total_batches", 0)
        self.restarts = data.get("restarts", 0)
        self.latest_checkpoint = data.get("latest_checkpoint")
        self.searcher_metric_value = data.get("searcher_metric_value")

    def reload(self) -> "Trial":
        self._refresh(self._api.get_trials_id(self.id)["trial"])
        return self

    def iter_metrics(self, group: str = "training") -> Iterator[Dict[str, Any]]:
        for m in self._api.get_trials_id_metrics(
            self.id, params={"group": group}
        )["metrics"]:
            yield m

    def top_checkpoint(self) -> Optional[Checkpoint]:
        self.reload()
        if not self.latest_checkpoint:
            return None
        return Checkpoint._get(self._session, self.latest_checkpoint)

    def logs(self, follow: bool = False) -> Iterator[str]:
        offset = 0
        while True:
            resp = self._api.get_tasks_id_logs(
                f"trial-{self.id}",
                params={"offset": offset, "follow": "true" if follow else "false"},
                timeout=60.0,
            )
            lines = resp["logs"]
            for line in lines:
                offset = max(offset, line["id"])
                yield line["log"]
            if not lines:
                if not follow:
                    return
                self.reload()
                if self.state in TERMINAL_STATES:
                    return
                time.sleep(0.5)


class Experiment:
    def __init__(self, session: Session, data: Dict[str, Any]):
        self._session = session
        self._api = Bindings(session)
        self.id = data["id"]
        self._refresh(data)

    def _refresh(self, data: Dict[str, Any]) -> None:
        self.state = data.get("state")
        self.config = data.get("config") or {}
        self.progress = data.get("progress", 0.0)
        self.archived = bool(data.get("archived"))

    def reload(self) -> "Experiment":
        self._refresh(self._api.get_experiments_id(self.id)["experiment"])
        return self

    def activate(self) -> None:
        self._api.post_experiments_id_activate(self.id)

    def pause(self) -> None:
        self._api.post_experiments_id_pause(self.id)

    def cancel(self) -> None:
        self._api.post_experiments_id_cancel(self.id)

    def kill(self) -> None:
        self._api.post_experiments_id_kill(self.id)

    def archive(self) -> None:
        self._api.post_experiments_id_archive(self.id)

    def delete(self) -> None:
        self._api.delete_experiments_id(self.id)

    def get_trials(self) -> List[Trial]:
        return [
            Trial(self._session, t)
            for t in self._api.get_experiments_id_trials(self.id)["trials"]
        ]

    def await_first_trial(self, timeout: float = 120.0) -> Trial:
        deadline = time.time() + timeout
        while time.time() < deadline:
            trials = self.get_trials()
            if trials:
                return trials[0]
            time.sleep(0.5)
        raise TimeoutError(f"no trial appeared for experiment {self.id}")

    def wait(self, timeout: float = 3600.0, interval: float = 1.0) -> str:
        """Block until terminal; returns the final state."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            self.reload()
            if self.state in TERMINAL_STATES:
                return self.state
            time.sleep(interval)
        raise TimeoutError(f"experiment {self.id} still {self.state}")

    def top_checkpoint(self, smaller_is_better: Optional[bool] = None) -> Checkpoint:
        """Best trial's checkpoint by searcher metric (reference
        experiment.py top_checkpoint)."""
        self.reload()
        if smaller_is_better is None:
            smaller_is_better = self.config.get("searcher", {}).get(
                "smaller_is_better", True
            )
        trials = [t for t in self.get_trials() if t.searcher_metric_value is not None]
        if not trials:
            raise RuntimeError("no trials with a searcher metric")
        best = (min if smaller_is_better else max)(
            trials, key=lambda t: t.searcher_metric_value
        )
        ckpt = best.top_checkpoint()
        if ckpt is None:
            raise RuntimeError(f"best trial {best.id} has no checkpoint")
        return ckpt


class ModelVersion:
    def __init__(self, session: Session, model_name: str, data: Dict[str, Any]):
        self._session = session
        self._api = Bindings(session)
        self.model_name = model_name
        self.version = data["version"]
        self.checkpoint_uuid = data.get("checkpoint_uuid")

    def get_checkpoint(self) -> Checkpoint:
        return Checkpoint._get(self._session, self.checkpoint_uuid)


class Model:
    def __init__(self, session: Session, data: Dict[str, Any]):
        self._session = session
        self._api = Bindings(session)
        self.name = data["name"]
        self.id = data.get("id")
        self.description = data.get("description", "")
        self.metadata = data.get("metadata") or {}

    def register_version(self, checkpoint_uuid: str) -> ModelVersion:
        resp = self._api.post_models_name_versions(
            self.name,
            body={"checkpoint_uuid": checkpoint_uuid, "metadata": {}},
        )
        return ModelVersion(self._session, self.name, resp["model_version"])

    def get_versions(self) -> List[ModelVersion]:
        return [
            ModelVersion(self._session, self.name, v)
            for v in self._api.get_models_name_versions(self.name)[
                "model_versions"
            ]
        ]


class Determined:
    """Entry point (reference client.py Determined)."""

    def __init__(
        self,
        master: Optional[str] = None,
        user: str = "determined",
        password: str = "",
    ):
        self.master = (master or os.environ.get("DET_MASTER",
                                                "http://127.0.0.1:8080")).rstrip("/")
        resp = Bindings(Session(self.master)).post_auth_login(
            body={"username": user, "password": password}
        )
        self._session = Session(self.master, resp["token"])
        self._api = Bindings(self._session)

    # -- experiments ---------------------------------------------------
    def create_experiment(
        self,
        config: Dict[str, Any],
        model_dir: Optional[str] = None,
        activate: bool = True,
        project_id: int = 1,
    ) -> Experiment:
        config = expconf.check(config)
        model_def = ""
        if model_dir:
            buf = io.BytesIO()
            with tarfile.open(fileobj=buf, mode="w:gz") as tar:
                for root, dirs, files in os.walk(model_dir):
                    dirs[:] = [d for d in dirs
                               if not d.startswith(".") and d != "__pycache__"]
                    for name in files:
                        full = os.path.join(root, name)
                        tar.add(full, arcname=os.path.relpath(full, model_dir))
            model_def = base64.b64encode(buf.getvalue()).decode()
        resp = self._api.post_experiments(
            body={
                "config": config,
                "model_definition": model_def,
                "activate": activate,
                "project_id": project_id,
            },
        )
        return Experiment(self._session, {"id": resp["id"], **resp.get("experiment", {})})

    def get_experiment(self, experiment_id: int) -> Experiment:
        return Experiment(
            self._session,
            self._api.get_experiments_id(experiment_id)["experiment"],
        )

    def list_experiments(self) -> List[Experiment]:
        return [
            Experiment(self._session, e)
            for e in self._api.get_experiments()["experiments"]
        ]

    def get_trial(self, trial_id: int) -> Trial:
        return Trial(self._session,
                     self._api.get_trials_id(trial_id)["trial"])

    def get_checkpoint(self, uuid: str) -> Checkpoint:
        return Checkpoint._get(self._session, uuid)

    # -- model registry ------------------------------------------------
    def create_model(self, name: str, description: str = "") -> Model:
        self._api.post_models(
            body={"name": name, "description": description, "metadata": {},
                  "labels": []},
        )
        return self.get_model(name)

    def get_model(self, name: str) -> Model:
        return Model(self._session,
                     self._api.get_models_name(name)["model"])

    def get_models(self) -> List[Model]:
        return [Model(self._session, m)
                for m in self._api.get_models()["models"]]

    # -- cluster -------------------------------------------------------
    def get_agents(self) -> List[Dict[str, Any]]:
        return self._api.get_agents()["agents"]

    def get_master_info(self) -> Dict[str, Any]:
        return self._api.get_master()


# Module-level convenience singleton (reference client.py login/create_experiment).
_default_client: Optional[Determined] = None


def login(master: Optional[str] = None, user: str = "determined",
          password: str = "") -> Determined:
    global _default_client
    _default_client = Determined(master, user, password)
    return _default_client


def _client() -> Determined:
    global _default_client
    if _default_client is None:
        _default_client = Determined()
    return _default_client


def create_experiment(config: Dict[str, Any], model_dir: Optional[str] = None,
                      **kwargs: Any) -> Experiment:
    return _client().create_experiment(config, model_dir, **kwargs)
