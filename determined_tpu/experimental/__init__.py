"""Python SDK (reference: determined.experimental.client,
harness/determined/experimental/client.py + common/experimental/*)."""

from determined_tpu.experimental.client import (  # noqa: F401
    Checkpoint,
    Determined,
    Experiment,
    Model,
    ModelVersion,
    Trial,
    create_experiment,
    login,
)
