"""Parallelism primitives: device meshes, logical sharding rules, collectives.

TPU-native replacement for the reference's NCCL/Gloo/Horovod/DeepSpeed launch
matrix (SURVEY.md §2.4): all gradient/tensor communication is expressed as
GSPMD shardings over a `jax.sharding.Mesh` and lowered by XLA to ICI/DCN
collectives — there is no external comm library.
"""

from determined_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    create_mesh,
    mesh_shape_for_devices,
)
from determined_tpu.parallel.sharding import (  # noqa: F401
    LogicalRules,
    DEFAULT_RULES,
    logical_to_mesh_spec,
    shard_logical,
    named_sharding,
)
from determined_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_apply,
    pipeline_microbatches_default,
    pipeline_stage_count,
)
