"""Logical-axis sharding rules (GSPMD layout policy).

Models annotate arrays with *logical* axis names ("batch", "embed", "heads",
...).  A `LogicalRules` table maps logical names to mesh axes; changing the
parallelism strategy (DP vs FSDP vs TP vs combinations) is purely a rules
swap — model code never mentions mesh axes.  This is the standard t5x/maxtext
style layout system, re-derived for this framework.
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence, Tuple, Union

from jax.sharding import Mesh, NamedSharding, PartitionSpec

MeshAxes = Union[None, str, Tuple[str, ...]]

# Canonical rules: batch over (data, fsdp); params sharded over fsdp on their
# largest dim and over tensor on the "parallel" dim (Megatron layout); sequence
# over context for ring attention.
DEFAULT_RULES: Tuple[Tuple[str, MeshAxes], ...] = (
    ("batch", ("data", "fsdp")),
    ("seq", "context"),
    ("layers", "pipeline"),  # stacked-layer dim → pipeline stages
    ("embed", "fsdp"),
    ("heads", "tensor"),
    ("kv", None),
    ("mlp", "tensor"),
    ("vocab", "tensor"),
    ("expert", "expert"),
    ("unmodeled", None),
)


class LogicalRules:
    def __init__(self, rules: Sequence[Tuple[str, MeshAxes]] = DEFAULT_RULES):
        self._table: dict = {}
        for name, axes in rules:
            self._table[name] = axes

    def mesh_axes(self, logical: Optional[str]) -> MeshAxes:
        if logical is None:
            return None
        if logical not in self._table:
            raise KeyError(f"no sharding rule for logical axis {logical!r}")
        return self._table[logical]

    def spec(self, logical_axes: Sequence[Optional[str]]) -> PartitionSpec:
        """PartitionSpec for an array whose dims carry these logical names.

        A mesh axis may be consumed at most once per array; later dims that
        would reuse an already-consumed mesh axis fall back to replication.
        """
        used: set = set()
        out = []
        for logical in logical_axes:
            axes = self.mesh_axes(logical)
            if axes is None:
                out.append(None)
                continue
            if isinstance(axes, str):
                axes = (axes,)
            free = tuple(a for a in axes if a not in used)
            used.update(free)
            if not free:
                out.append(None)
            elif len(free) == 1:
                out.append(free[0])
            else:
                out.append(free)
        return PartitionSpec(*out)

    def override(self, **kwargs: MeshAxes) -> "LogicalRules":
        table = dict(self._table)
        table.update(kwargs)
        return LogicalRules(tuple(table.items()))


def logical_to_mesh_spec(
    logical_axes: Sequence[Optional[str]], rules: Optional[LogicalRules] = None
) -> PartitionSpec:
    return (rules or LogicalRules()).spec(logical_axes)


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


def shard_logical(x, logical_axes: Sequence[Optional[str]], rules: Optional[LogicalRules] = None):
    """`with_sharding_constraint` by logical axis names (no-op outside jit/mesh)."""
    import jax

    from determined_tpu import _jax_compat

    if _jax_compat.in_manual_shard_map():
        # Fully-manual shard_map body (old-jax pipeline fallback): every
        # mesh axis is already bound, so a constraint naming one fails at
        # lowering (past any try here) — and the hint is meaningless on a
        # local block anyway.
        return x
    spec = logical_to_mesh_spec(logical_axes, rules)
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, RuntimeError):
        # No mesh context (e.g. pure eager single-device use) — constraint is
        # advisory, skip it.
        return x
