"""Device-mesh construction for TPU slices.

An allocation in determined-tpu is "a set of chips with a fixed ICI mesh"
(SURVEY.md §7).  This module turns a flat device list into a named
`jax.sharding.Mesh` with the canonical axis names used across the framework:

  data     — pure data parallelism (replicated params); rides DCN across slices
  pipeline — pipeline (layer-stage) parallelism; stage boundaries exchange
             activations once per microbatch, so it sits next to `data` on
             the slower axes
  fsdp     — fully-sharded data parallelism (ZeRO-3 analogue); intra-slice ICI
  tensor   — Megatron-style tensor parallelism; innermost, fastest ICI axis
  context  — sequence/context parallelism (ring attention)
  expert   — MoE expert parallelism

Axes of size 1 are always present so PartitionSpecs can reference any axis
unconditionally — XLA treats size-1 mesh axes as free.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Mapping, Optional, Sequence

import numpy as np

AXIS_ORDER = ("data", "pipeline", "fsdp", "expert", "context", "tensor")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh request, part of an experiment's resources config.

    Sizes of -1 mean "absorb all remaining devices" (at most one axis may be
    -1, like a numpy reshape).  Unspecified axes default to 1.
    """

    data: int = -1
    pipeline: int = 1
    fsdp: int = 1
    expert: int = 1
    context: int = 1
    tensor: int = 1

    def sizes(self) -> tuple:
        return tuple(getattr(self, a) for a in AXIS_ORDER)

    def resolve(self, n_devices: int) -> "MeshConfig":
        """Fill in any -1 axis from the device count and validate the product."""
        sizes = list(self.sizes())
        unknown = [i for i, s in enumerate(sizes) if s == -1]
        if len(unknown) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {self}")
        known = math.prod(s for s in sizes if s != -1)
        if unknown:
            if n_devices % known != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {known}"
                )
            sizes[unknown[0]] = n_devices // known
        if math.prod(sizes) != n_devices:
            raise ValueError(
                f"mesh {dict(zip(AXIS_ORDER, sizes))} needs {math.prod(sizes)} "
                f"devices, allocation has {n_devices}"
            )
        return MeshConfig(**dict(zip(AXIS_ORDER, sizes)))

    def resolvable(self, n_devices: int) -> bool:
        """True when `resolve(n_devices)` would succeed — the elastic
        feasibility check (preflight DTL204, Trainer resize) without the
        exception control flow."""
        try:
            self.resolve(n_devices)
            return True
        except ValueError:
            return False

    @staticmethod
    def from_dict(d: Mapping[str, int]) -> "MeshConfig":
        unknown = set(d) - set(AXIS_ORDER)
        if unknown:
            raise ValueError(f"unknown mesh axes {sorted(unknown)}; valid: {AXIS_ORDER}")
        return MeshConfig(**dict(d))


def mesh_shape_for_devices(n_devices: int, config: Optional[MeshConfig] = None) -> tuple:
    cfg = (config or MeshConfig()).resolve(n_devices)
    return cfg.sizes()


def create_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[Any]] = None,
):
    """Build a named Mesh over `devices` (default: all visible devices).

    Uses `mesh_utils.create_device_mesh` so that on real TPU slices the
    logical axes are laid out along physical ICI rings (innermost axis =
    tightest ring, which is why `tensor` is last in AXIS_ORDER).
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    shape = mesh_shape_for_devices(len(devices), config)
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, AssertionError, NotImplementedError):
        # Virtual/CPU devices or odd shapes: plain reshape is fine — there is
        # no physical topology to optimise for.
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def single_device_mesh(device: Optional[Any] = None):
    """A 1-chip mesh (all axes size 1) — used by single-slot trials."""
    import jax

    if device is None:
        device = jax.devices()[0]
    return create_mesh(MeshConfig(data=1), [device])
