"""Pipeline parallelism: GPipe-style microbatched execution over a
`pipeline` mesh axis.

TPU-first design (SURVEY.md §7 step 3 "PP via pipelined shard_map"): layer
stacks are sharded over the `pipeline` axis, and a `jax.shard_map` that is
*manual only over the pipeline axis* (``axis_names={'pipeline'}``) moves
activations between stages with `ppermute` while GSPMD keeps inserting the
data/fsdp/tensor collectives automatically inside each stage. The reference
platform has no native PP — it delegates to DeepSpeed topologies
(reference: harness/determined/pytorch/deepspeed/_mpu.py:9-46); here it is a
first-class framework primitive.

Schedule: plain GPipe. M microbatches flow through S stages in M+S-1 ticks;
each tick every stage applies its layer slice to its current microbatch and
ppermutes the result to the next stage. Bubble fraction = (S-1)/(M+S-1) —
callers should use M >= 4*S for decent efficiency (warned below).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from determined_tpu.parallel.sharding import LogicalRules


def pipeline_stage_count(mesh: Mesh) -> int:
    return mesh.shape.get("pipeline", 1)


def _batch_shards(mesh: Mesh, rules: Optional[LogicalRules]) -> int:
    """How many ways the batch dim is sharded under the rules table."""
    axes = (rules or LogicalRules()).mesh_axes("batch")
    if axes is None:
        return 1
    if isinstance(axes, str):
        axes = (axes,)
    n = 1
    for a in axes:
        n *= mesh.shape.get(a, 1)
    return n


def pipeline_apply(
    block_fn: Callable[[jax.Array, Any], jax.Array],
    stacked_params: Any,  # pytree, leaves [L, ...] (layer-stacked)
    x: jax.Array,  # [B, ...] activations entering layer 0
    *,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "pipeline",
    rules: Optional[LogicalRules] = None,
    compute_dtype: Any = None,
) -> jax.Array:
    """Run L stacked layers as a pipeline over mesh axis `axis`.

    block_fn(x, layer_params) -> x applies ONE layer (layer_params = one
    [L, ...] slice). L must divide evenly into stages; the batch B must
    divide num_microbatches. Returns activations after the last layer,
    replicated over the pipeline axis (other axes keep their GSPMD layout).

    compute_dtype: when set (e.g. bf16 for an f32 input), activations are
    cast to it INSIDE the shard_map body and cast back before returning, so
    the boundary dtype matches x. Keep the boundary in the param dtype —
    low-precision gradient chains crossing a partial-manual shard_map
    boundary trip an XLA partitioner crash ("Invalid binary instruction
    opcode copy") on the CPU backend used for mesh tests.
    """
    n_stages = mesh.shape.get(axis, 1)
    if n_stages == 1:
        # No pipeline axis in this mesh: plain scan.
        def body(carry, lp):
            return block_fn(carry, lp), None

        y, _ = jax.lax.scan(body, x, stacked_params)
        return y

    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_layers % n_stages:
        raise ValueError(f"{n_layers} layers not divisible by {n_stages} stages")
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} not divisible by {num_microbatches} microbatches")
    m = num_microbatches
    mb = b // m
    shards = _batch_shards(mesh, rules)
    if mb % shards:
        raise ValueError(
            f"microbatch size {mb} (batch {b} / {m} microbatches) must stay "
            f"divisible by the {shards}-way batch sharding — use "
            f"pipeline_microbatches_default() to pick a valid count"
        )

    # [B, ...] -> [M, mb, ...]; keep the batch sharding on the mb dim (the
    # microbatch dim is a time axis — replicated) so the partitioner never
    # has to invent a layout for the split.
    micro = x.reshape((m, mb) + x.shape[1:])
    batch_axes = (rules or LogicalRules()).mesh_axes("batch")
    micro = jax.lax.with_sharding_constraint(
        micro,
        PartitionSpec(None, batch_axes, *([None] * (micro.ndim - 2))),
    )

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def stage_apply(params_shard, xin):
        # params_shard leaves: [L/S, ...] — this stage's layers, run in order.
        def body(carry, lp):
            return block_fn(carry, lp), None

        y, _ = jax.lax.scan(body, xin, params_shard)
        return y

    def pipelined(params_shard, micro_local):
        out_dtype = micro_local.dtype
        if compute_dtype is not None:
            micro_local = micro_local.astype(compute_dtype)
        stage = jax.lax.axis_index(axis)
        total = m + n_stages - 1

        def tick(carry, t):
            x_cur, outputs = carry
            # Stage 0 injects microbatch t (clamped once the stream is dry —
            # those ticks' results are masked out downstream).
            inject = jax.lax.dynamic_index_in_dim(
                micro_local, jnp.minimum(t, m - 1), axis=0, keepdims=False)
            x_in = jnp.where(stage == 0, inject, x_cur)
            y = stage_apply(params_shard, x_in)
            # Last stage commits finished microbatch t-S+1 (valid when >= 0).
            out_idx = t - (n_stages - 1)
            committed = jax.lax.dynamic_update_index_in_dim(
                outputs, y.astype(outputs.dtype), jnp.maximum(out_idx, 0), 0)
            outputs = jnp.where(out_idx >= 0, committed, outputs)
            x_next = jax.lax.ppermute(y, axis, perm)
            return (x_next, outputs), None

        # pcast-to-varying: the carries are device-varying over the
        # pipeline axis from tick 1 on; mark the zero-init the same way so
        # the scan carry type is stable under varying-manual-axes checking.
        outputs = jax.lax.pcast(
            jnp.zeros_like(micro_local), (axis,), to="varying")
        x0 = jax.lax.pcast(
            jnp.zeros_like(micro_local[0]), (axis,), to="varying")
        (x_cur, outputs), _ = jax.lax.scan(
            tick, (x0, outputs), jnp.arange(total))
        # Only the last stage holds real outputs; replicate over the axis so
        # the embedding/head (outside the pipeline) see them everywhere.
        is_last = (stage == n_stages - 1).astype(outputs.dtype)
        return jax.lax.psum(outputs * is_last, axis).astype(out_dtype)

    n_axes = set(mesh.axis_names) - {axis}
    y = jax.shard_map(
        pipelined,
        mesh=mesh,
        in_specs=(PartitionSpec(axis), PartitionSpec()),
        out_specs=PartitionSpec(),
        axis_names=frozenset({axis}),
        check_vma=True,
    )(stacked_params, micro)
    del n_axes
    return y.reshape((b,) + y.shape[2:])


def pipeline_microbatches_default(
    mesh: Mesh, batch: int, rules: Optional[LogicalRules] = None
) -> int:
    """Pick a microbatch count: toward 4*stages for a small bubble, while
    each microbatch stays divisible by the batch sharding."""
    s = pipeline_stage_count(mesh)
    if s == 1:
        return 1
    local = max(batch // _batch_shards(mesh, rules), 1)
    want = min(local, 4 * s)
    while local % want:
        want -= 1
    return max(want, 1)
