"""TensorBoard subsystem.

Reference: harness/determined/tensorboard/ — per-trial tfevents written
locally and synced to checkpoint storage by an async upload thread
(tensorboard/base.py:147); the tensorboard NTSC task fetches those synced
directories and serves them (tensorboard/fetchers/). Metric writers mirror
tensorboard/metric_writers/.

Storage layout: ``<storage base>/tensorboard/<experiment_id>/<trial_id>/``.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, Optional

logger = logging.getLogger("determined_tpu.tensorboard")


def storage_prefix(experiment_id: int, trial_id: int) -> str:
    return os.path.join("tensorboard", str(experiment_id), str(trial_id))


class MetricWriter:
    """Scalar tfevents writer (metric_writers/pytorch.py analogue; uses
    torch.utils.tensorboard which is in the baked image)."""

    def __init__(self, log_dir: str):
        os.makedirs(log_dir, exist_ok=True)
        self.log_dir = log_dir
        self._writer = None
        try:
            from torch.utils.tensorboard import SummaryWriter

            self._writer = SummaryWriter(log_dir=log_dir)
        except Exception:
            logger.warning("tensorboard writer unavailable", exc_info=True)

    def add_scalars(self, metrics: Dict[str, Any], step: int,
                    prefix: str = "") -> None:
        if self._writer is None:
            return
        for key, value in metrics.items():
            try:
                self._writer.add_scalar(
                    f"{prefix}{key}" if prefix else key, float(value), step
                )
            except (TypeError, ValueError):
                continue

    def flush(self) -> None:
        if self._writer is not None:
            self._writer.flush()

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()


class TensorboardManager:
    """Writes tfevents locally + async-syncs them into checkpoint storage
    (reference base.py sync thread)."""

    def __init__(self, storage, experiment_id: int, trial_id: int,
                 base_dir: Optional[str] = None, sync_period: float = 10.0):
        self._storage = storage
        self._prefix = storage_prefix(experiment_id, trial_id)
        self.log_dir = base_dir or os.path.join(
            "/tmp/determined_tpu/tensorboard", str(experiment_id), str(trial_id)
        )
        self.writer = MetricWriter(self.log_dir)
        self._sync_period = sync_period
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if storage is not None:
            self._thread = threading.Thread(target=self._sync_loop, daemon=True)
            self._thread.start()

    def on_metrics(self, group: str, steps_completed: int,
                   metrics: Dict[str, Any]) -> None:
        self.writer.add_scalars(metrics, steps_completed, prefix=f"{group}/")

    def sync(self) -> None:
        if self._storage is None:
            return
        self.writer.flush()
        try:
            self._storage.upload(self.log_dir, self._prefix)
        except Exception:
            logger.debug("tensorboard sync failed", exc_info=True)

    def _sync_loop(self) -> None:
        while not self._stop.wait(self._sync_period):
            self.sync()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self.sync()
        self.writer.close()


def fetch_experiment_logs(storage, experiment_id: int, dest: str) -> None:
    """Download every trial's synced tfevents for one experiment
    (fetchers/ analogue — storage-agnostic via the StorageManager API)."""
    base = os.path.join("tensorboard", str(experiment_id))
    try:
        storage.download(base, os.path.join(dest, str(experiment_id)))
    except FileNotFoundError:
        logger.info("no tensorboard logs yet for experiment %s", experiment_id)
