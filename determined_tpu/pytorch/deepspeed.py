"""DeepSpeedTrial-shaped compat surface.

Reference: harness/determined/pytorch/deepspeed/_deepspeed_trial.py:729
(user train_batch receives the data iterator and drives the engine's
microbatch loop; save/load :908,924 are engine-sharded checkpoints) and
_mpu.py:9-46 (ModelParallelUnit — which ranks report metrics / build data
loaders under model parallelism).

On TPU the native capability lives in the JAX stack (FSDP/ZeRO-equivalent
GSPMD sharding) and torch runs through torch-xla FSDP — but users arriving
from the reference bring DeepSpeedTrial subclasses, so the platform ships
the same API shape over any deepspeed-compatible engine object
(duck-typed: train_micro_batch_size_per_gpu / gradient_accumulation_steps /
backward / step / save_checkpoint / load_checkpoint). No deepspeed import
happens here; tests pin the contract with a fake engine the same way the
torch-xla contract is pinned.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Iterator, Optional

from determined_tpu import core
from determined_tpu.pytorch._trial import (
    DataLoader,
    PyTorchTrialContext,
    TorchData,
)

logger = logging.getLogger("determined_tpu.pytorch.deepspeed")


class ModelParallelUnit:
    """Which ranks own data loading / metric reporting (reference
    _mpu.py:9-46). Pure-data-parallel engines use make_data_parallel_mpu;
    pipeline/tensor-parallel engines pass their topology's answers."""

    def __init__(
        self,
        data_parallel_rank: int,
        data_parallel_world_size: int,
        should_report_metrics: bool,
        should_build_data_loader: bool,
    ):
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_world_size = data_parallel_world_size
        self.should_report_metrics = should_report_metrics
        self.should_build_data_loader = should_build_data_loader


def make_data_parallel_mpu(dist) -> ModelParallelUnit:
    rank = dist.rank if dist is not None else 0
    size = dist.size if dist is not None else 1
    return ModelParallelUnit(
        data_parallel_rank=rank,
        data_parallel_world_size=size,
        should_report_metrics=True,
        should_build_data_loader=True,
    )


class DeepSpeedTrialContext(PyTorchTrialContext):
    """Reference _deepspeed_context.py:45: engine registration + MPU."""

    def __init__(self, *args: Any, **kwargs: Any):
        super().__init__(*args, **kwargs)
        self.engines: list = []
        self._mpu: Optional[ModelParallelUnit] = None
        self._auto_grad_accum = True

    def wrap_model_engine(self, engine: Any) -> Any:
        """Register a deepspeed engine. The engine owns device placement
        and gradient comms — no DDP wrap, no .to(device)."""
        self.engines.append(engine)
        if self._mpu is None:
            self._mpu = make_data_parallel_mpu(self.dist)
        return engine

    def wrap_mpu(self, mpu: ModelParallelUnit) -> ModelParallelUnit:
        """Install a topology-aware MPU (pipeline/tensor-parallel engines:
        only data-parallel-rank-0 of each replica group reports/loads)."""
        self._mpu = mpu
        return mpu

    def disable_auto_grad_accumulation(self) -> None:
        """User train_batch consumes exactly one microbatch per call
        instead of a full gradient-accumulation window."""
        self._auto_grad_accum = False

    @property
    def mpu(self) -> ModelParallelUnit:
        if self._mpu is None:
            self._mpu = make_data_parallel_mpu(self.dist)
        return self._mpu

    def get_train_micro_batch_size_per_gpu(self) -> int:
        if not self.engines:
            raise RuntimeError("wrap_model_engine() has not been called")
        return int(self.engines[0].train_micro_batch_size_per_gpu())

    def num_micro_batches_per_slot(self) -> int:
        if not self.engines:
            raise RuntimeError("wrap_model_engine() has not been called")
        if not self._auto_grad_accum:
            return 1
        return int(self.engines[0].gradient_accumulation_steps())


class DeepSpeedTrial:
    """User subclass surface (reference _deepspeed_trial.py:729).

    train_batch/evaluate_batch receive the DATA ITERATOR, not a batch —
    the user pulls `num_micro_batches_per_slot()` microbatches and drives
    engine.backward()/engine.step() per microbatch (the engine internally
    steps the optimizer at accumulation boundaries)."""

    trial_context_class = DeepSpeedTrialContext

    def __init__(self, context: DeepSpeedTrialContext):
        self.context = context

    def train_batch(self, dataloader_iter: Optional[Iterator[TorchData]],
                    epoch_idx: int, batch_idx: int) -> Dict[str, Any]:
        raise NotImplementedError

    def evaluate_batch(self, dataloader_iter: Optional[Iterator[TorchData]],
                       batch_idx: int) -> Dict[str, Any]:
        raise NotImplementedError

    def build_training_data_loader(self) -> Optional[DataLoader]:
        raise NotImplementedError

    def build_validation_data_loader(self) -> Optional[DataLoader]:
        raise NotImplementedError

    def save(self, context: DeepSpeedTrialContext, path: str) -> None:
        """Engine-sharded save (reference :908): every rank participates —
        deepspeed writes per-rank shards under `path`."""
        for i, engine in enumerate(context.engines):
            engine.save_checkpoint(path, tag=f"engine{i}")

    def load(self, context: DeepSpeedTrialContext, path: str) -> None:
        """Engine-sharded load (reference :924)."""
        for i, engine in enumerate(context.engines):
            engine.load_checkpoint(path, tag=f"engine{i}")


class DeepSpeedTrainer:
    """Searcher-driven loop for DeepSpeedTrial (reference
    _deepspeed_trial.py controller :37). One step = one train_batch call =
    one full gradient-accumulation window through the engine."""

    def __init__(self, trial: DeepSpeedTrial,
                 core_context: Optional[core.Context] = None):
        self.trial = trial
        self.context = trial.context
        self.dist = self.context.dist
        self.core = core_context or self.context._core or core.init(
            max_length=100, distributed=self.dist)
        if not self.context.engines:
            raise ValueError(
                "trial must wrap_model_engine() in __init__ before fit()")

    @property
    def _mpu(self) -> ModelParallelUnit:
        return self.context.mpu

    def _data_iter(self, build) -> Optional[Iterator]:
        """Ranks whose MPU says they don't own a data loader hand None to
        train_batch/evaluate_batch (reference: model-parallel peers receive
        activations, not data)."""
        if not self._mpu.should_build_data_loader:
            return None
        loader = build()
        if loader is None:
            return None
        dl = loader.get_data_loader(
            num_replicas=self._mpu.data_parallel_world_size,
            rank=self._mpu.data_parallel_rank)

        def forever():
            while True:
                for batch in dl:
                    yield self.context.to_device(batch)

        return forever()

    def _save(self, steps_completed: int) -> None:
        import json
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            self.trial.save(self.context, td)
            if self.dist is None or self.dist.is_chief:
                # Trainer state rides inside the checkpoint (metadata in
                # the registry is for humans; restore must not depend on
                # a registry round-trip).
                with open(os.path.join(td, "ds_trainer.json"), "w") as f:
                    json.dump({"steps_completed": steps_completed}, f)
            self.core.checkpoint.upload(
                td,
                metadata={"steps_completed": steps_completed,
                          "framework": "deepspeed", "sharded": True},
                shard=True,
            )

    def _restore(self) -> int:
        import json

        latest = self.core.latest_checkpoint
        if not latest:
            return 0
        with self.core.checkpoint.restore_path(latest) as path:
            self.trial.load(self.context, str(path))
            state_file = os.path.join(str(path), "ds_trainer.json")
            if os.path.exists(state_file):
                with open(state_file) as f:
                    return int(json.load(f).get("steps_completed", 0))
        return 0

    def _validate(self, steps: int) -> Dict[str, Any]:
        it = self._data_iter(self.trial.build_validation_data_loader)
        metrics = self.trial.evaluate_batch(it, 0)
        reduced = {k: float(v) for k, v in metrics.items()}
        if self.dist is not None and self.dist.size > 1:
            parts = self.dist.allgather(reduced)
            reduced = {
                k: sum(p[k] for p in parts) / len(parts) for k in reduced
            }
        if self._mpu.should_report_metrics and (
                self.dist is None or self.dist.is_chief):
            self.core.train.report_validation_metrics(steps, reduced)
        return reduced

    def fit(self, searcher_metric: Optional[str] = None,
            report_period: int = 10,
            checkpoint_period: int = 0) -> int:
        steps = self._restore()
        data_iter = self._data_iter(self.trial.build_training_data_loader)
        window: Dict[str, float] = {}
        window_n = 0
        for op in self.core.searcher.operations():
            while steps < op.length:
                metrics = self.trial.train_batch(data_iter, 0, steps)
                steps += 1
                for k, v in metrics.items():
                    try:
                        window[k] = window.get(k, 0.0) + float(v)
                    except (TypeError, ValueError):
                        continue
                window_n += 1
                if (steps % report_period == 0 or steps == op.length) and \
                        self._mpu.should_report_metrics and (
                            self.dist is None or self.dist.is_chief):
                    self.core.train.report_training_metrics(
                        steps, {k: v / window_n for k, v in window.items()})
                    window, window_n = {}, 0
                if checkpoint_period and steps % checkpoint_period == 0:
                    self._save(steps)
                if self.core.preempt.should_preempt():
                    self._save(steps)
                    logger.info("preempted at step %d", steps)
                    return steps
            val = self._validate(steps)
            metric = (val.get(searcher_metric)
                      if searcher_metric else
                      next(iter(val.values()), 0.0))
            if searcher_metric and metric is None:
                raise KeyError(
                    f"searcher metric {searcher_metric!r} not in validation "
                    f"metrics {sorted(val)}")
            op.report_completed(float(metric))
            self._save(steps)
        return steps
